#!/usr/bin/env python
"""Headline benchmark: EC encode GB/s per chip (RS 10+4, GF(2^8) on TPU).

Prints ONE JSON line:
  {"metric": "ec_encode_GBps", "value": <TPU pallas-kernel encode rate>,
   "unit": "GB/s", "vs_baseline": <ratio vs the CPU SIMD codec on this host>,
   ...details}

Methodology notes (this platform needs care):
  * `block_until_ready` does not reliably fence on the axon tunnel, and
    repeated dispatch of the same computation invites CSE.  So the timed
    workload is ONE device-side pallas_call with a (K, G) grid whose input
    index_map shifts by the sweep index k — K full encode sweeps over
    distinct HBM windows in a single dispatch, ended by a host readback.
  * Rate convention matches the reference workload accounting (BASELINE.md):
    encode throughput = volume bytes consumed per second.
  * The CPU baseline is our C++ SSSE3 nibble-table codec — the same
    algorithm class as the reference's SIMD assembly — on this host.
"""

from __future__ import annotations

import functools
import json
import time

import numpy as np


def _tpu_pallas_rate(sweep_mb_per_shard: int = 64, k: int = 16,
                     tile: int = 256) -> dict:
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    from seaweedfs_tpu.ops import gf256
    from seaweedfs_tpu.ops.rs_pallas import LANES, _kernel_body

    rows = tuple(tuple(int(c) for c in r) for r in gf256.rs_parity_matrix(10, 4))
    kernel = functools.partial(_kernel_body, rows)
    g = (sweep_mb_per_shard << 20) // (tile * LANES * 4)
    words_per_sweep = g * tile * LANES
    rng = np.random.default_rng(0)
    buf = jax.device_put(
        rng.integers(0, 2**32, (10, (g + k) * tile * LANES), dtype=np.uint32)
        .reshape(10, (g + k) * tile, LANES)
    )
    fn = jax.jit(
        pl.pallas_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct((4, g * tile, LANES), jnp.uint32),
            grid=(k, g),
            in_specs=[
                pl.BlockSpec(
                    (10, tile, LANES), lambda kk, gg: (0, gg + kk, 0),
                    memory_space=pltpu.VMEM,
                )
            ],
            out_specs=pl.BlockSpec(
                (4, tile, LANES), lambda kk, gg: (0, gg, 0),
                memory_space=pltpu.VMEM,
            ),
        )
    )
    out = fn(buf)
    np.asarray(out[0, 0, :2])  # compile + warm
    times = []
    for _ in range(3):
        t0 = time.perf_counter()
        out = fn(buf)
        np.asarray(out[0, 0, :2])  # fence via readback
        times.append(time.perf_counter() - t0)
    dt = min(times)
    bytes_encoded = 10 * words_per_sweep * 4 * k
    return {
        "rate": bytes_encoded / dt / 1e9,
        "sweeps": k,
        "bytes": bytes_encoded,
        "seconds": dt,
    }


def _cpu_rate(shard_bytes: int = 16 << 20, iters: int = 3) -> float:
    from seaweedfs_tpu.ops.rs_cpu import ReedSolomon

    rs = ReedSolomon()
    rng = np.random.default_rng(1)
    data = rng.integers(0, 256, (10, shard_bytes), dtype=np.uint8)
    rs.parity_of(data)  # warm
    start = time.perf_counter()
    for _ in range(iters):
        rs.parity_of(data)
    dt = time.perf_counter() - start
    return (10 * shard_bytes * iters) / dt / 1e9


def main() -> None:
    tpu = _tpu_pallas_rate()
    cpu = _cpu_rate()
    print(
        json.dumps(
            {
                "metric": "ec_encode_GBps",
                "value": round(tpu["rate"], 2),
                "unit": "GB/s",
                "vs_baseline": round(tpu["rate"] / cpu, 1) if cpu else None,
                "impl": "pallas_swar_u32",
                "cpu_simd_GBps": round(cpu, 3),
                "sweep_bytes": tpu["bytes"],
                "seconds": round(tpu["seconds"], 4),
            }
        )
    )


if __name__ == "__main__":
    main()
