#!/usr/bin/env python
"""Headline benchmark: EC encode GB/s per chip (RS 10+4, GF(2^8) on TPU).

Prints ONE JSON line:
  {"metric": "ec_encode_GBps", "value": <TPU pallas-kernel encode rate>,
   "unit": "GB/s", "vs_baseline": <ratio vs the CPU SIMD codec on this host>,
   ...details}

Methodology notes (this platform needs care):
  * `block_until_ready` does not reliably fence on the axon tunnel, and
    repeated dispatch of the same computation invites CSE.  So the timed
    workload is ONE device-side pallas_call with a (K, G) grid whose input
    index_map shifts by the sweep index k — K full encode sweeps over
    distinct HBM windows in a single dispatch, ended by a host readback.
  * Rate convention matches the reference workload accounting (BASELINE.md):
    encode throughput = volume bytes consumed per second.
  * The CPU baseline is our C++ SSSE3 nibble-table codec — the same
    algorithm class as the reference's SIMD assembly — on this host.
"""

from __future__ import annotations

import functools
import json
import time

import numpy as np


def _tpu_pallas_rate(sweep_mb_per_shard: int = 64, k: int = 16,
                     tile: int = 256) -> dict:
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    from seaweedfs_tpu.ops import gf256
    from seaweedfs_tpu.ops.rs_pallas import LANES, _kernel_body

    rows = tuple(tuple(int(c) for c in r) for r in gf256.rs_parity_matrix(10, 4))
    kernel = functools.partial(_kernel_body, rows)
    g = (sweep_mb_per_shard << 20) // (tile * LANES * 4)
    words_per_sweep = g * tile * LANES
    rng = np.random.default_rng(0)
    buf = jax.device_put(
        rng.integers(0, 2**32, (10, (g + k) * tile * LANES), dtype=np.uint32)
        .reshape(10, (g + k) * tile, LANES)
    )
    fn = jax.jit(
        pl.pallas_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct((4, g * tile, LANES), jnp.uint32),
            grid=(k, g),
            in_specs=[
                pl.BlockSpec(
                    (10, tile, LANES), lambda kk, gg: (0, gg + kk, 0),
                    memory_space=pltpu.VMEM,
                )
            ],
            out_specs=pl.BlockSpec(
                (4, tile, LANES), lambda kk, gg: (0, gg, 0),
                memory_space=pltpu.VMEM,
            ),
        )
    )
    out = fn(buf)
    np.asarray(out[0, 0, :2])  # compile + warm
    times = []
    for _ in range(3):
        t0 = time.perf_counter()
        out = fn(buf)
        np.asarray(out[0, 0, :2])  # fence via readback
        times.append(time.perf_counter() - t0)
    dt = min(times)
    bytes_encoded = 10 * words_per_sweep * 4 * k
    return {
        "rate": bytes_encoded / dt / 1e9,
        "sweeps": k,
        "bytes": bytes_encoded,
        "seconds": dt,
    }


def _e2e_rates(volume_gb: float | None = None, slice_mb: int = 16,
               budget_s: float = 90.0) -> dict:
    """End-to-end file pipeline on the TPU codec (BASELINE configs 2+3).

    Writes a synthetic .dat, times the full disk->HBM->shards encode
    (storage.ec.encoder pipelined path), then deletes the 4 FIRST data
    shards (worst case: full decode-matrix inversion) and times the rebuild.
    Rates follow the reference accounting: volume/input bytes per second.

    The host<->device link here is a tunnel of unknown (possibly very low)
    bandwidth, so the volume size adapts: a pilot slice round-trip sets the
    rate estimate and the volume is sized to ~budget_s of encode time,
    clamped to [128MB, volume_gb].
    """
    import os
    import shutil
    import tempfile

    import jax.numpy as jnp

    from seaweedfs_tpu.ops.codec import get_codec
    from seaweedfs_tpu.storage.ec.constants import DATA_SHARDS, to_ext
    from seaweedfs_tpu.storage.ec.encoder import (
        generate_ec_files,
        rebuild_ec_files,
    )

    if volume_gb is None:
        volume_gb = float(os.environ.get("SEAWEEDFS_TPU_BENCH_E2E_GB", "8"))

    # pilot: one warm slice round-trip to size the volume for the budget
    codec = get_codec("tpu")
    slice_bytes = slice_mb << 20
    rng = np.random.default_rng(7)
    pilot = rng.integers(0, 256, (10, slice_bytes), dtype=np.uint8)
    d3 = pilot.view(np.uint32).reshape(10, -1, 128)

    def _pilot_once() -> None:
        out = codec.encode_device_u32_3d(jnp.asarray(d3))
        if out is None:  # impl without a packed entry — measure the u8 path
            out = codec.encode_device(jnp.asarray(pilot))
        np.asarray(out)

    _pilot_once()  # compile+warm
    t0 = time.perf_counter()
    _pilot_once()
    pilot_dt = time.perf_counter() - t0
    pilot_rate = 10 * slice_bytes / pilot_dt  # volume bytes/s through codec

    dat_size = int(min(volume_gb * (1 << 30), pilot_rate * budget_s))
    dat_size = max(dat_size, 128 << 20)
    dat_size = (dat_size // (64 << 20)) * (64 << 20)

    tmp = tempfile.mkdtemp(prefix="swfs-bench-")
    base = os.path.join(tmp, "1")
    try:
        chunk = 256 << 20
        with open(base + ".dat", "wb") as f:
            left = dat_size
            while left > 0:
                n = min(chunk, left)
                f.write(rng.integers(0, 256, n, dtype=np.uint8).tobytes())
                left -= n

        t0 = time.perf_counter()
        generate_ec_files(base, codec_name="tpu", slice_size=slice_bytes)
        encode_dt = time.perf_counter() - t0

        shard_size = os.path.getsize(base + to_ext(0))
        for i in range(4):  # lose 4 data shards — worst case
            os.remove(base + to_ext(i))
        t0 = time.perf_counter()
        rebuilt = rebuild_ec_files(base, codec_name="tpu", slice_size=slice_bytes)
        rebuild_dt = time.perf_counter() - t0
        assert rebuilt == [0, 1, 2, 3]
        return {
            "e2e_rate": dat_size / encode_dt / 1e9,
            "e2e_bytes": dat_size,
            "e2e_seconds": encode_dt,
            "rebuild_rate": shard_size * DATA_SHARDS / rebuild_dt / 1e9,
            "rebuild_seconds": rebuild_dt,
        }
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def _cpu_rate(shard_bytes: int = 16 << 20, iters: int = 3) -> float:
    from seaweedfs_tpu.ops.rs_cpu import ReedSolomon

    rs = ReedSolomon()
    rng = np.random.default_rng(1)
    data = rng.integers(0, 256, (10, shard_bytes), dtype=np.uint8)
    rs.parity_of(data)  # warm
    start = time.perf_counter()
    for _ in range(iters):
        rs.parity_of(data)
    dt = time.perf_counter() - start
    return (10 * shard_bytes * iters) / dt / 1e9


def _stage_in_subprocess(
    flag: str, timeout_s: float, attempts: int = 3, backoff_s: float = 15.0
) -> dict:
    """Run one TPU-touching bench stage in a worker process, retried.

    The tunnel transport has been observed to (a) refuse backend init
    transiently ("Unable to initialize backend 'axon'") and (b) wedge on
    large transfers.  A thread can't be killed, a subprocess can — and a
    refused init one minute is often fine the next.  The headline metric
    must never hang or rc!=0 the driver's bench run, so every TPU stage
    lives behind this bounded retry loop.
    """
    import os
    import subprocess
    import sys

    last = "no attempt ran"
    for attempt in range(attempts):
        if attempt:
            time.sleep(backoff_s)
        try:
            proc = subprocess.run(
                [sys.executable, os.path.abspath(__file__), flag],
                capture_output=True,
                text=True,
                timeout=timeout_s,
            )
        except subprocess.TimeoutExpired:
            last = f"{flag} timed out after {timeout_s:.0f}s"
            continue
        for line in reversed(proc.stdout.strip().splitlines()):
            try:
                parsed = json.loads(line)
            except (json.JSONDecodeError, ValueError):
                continue
            if isinstance(parsed, dict) and "error" not in parsed:
                return parsed
            if isinstance(parsed, dict):
                last = parsed["error"]
                break
        else:
            last = f"{flag} rc={proc.returncode}: {proc.stderr[-300:]}"
    return {"error": last}


def main() -> None:
    import sys

    if "--e2e-only" in sys.argv:
        try:
            print(json.dumps(_e2e_rates()))
        except Exception as exc:  # noqa: BLE001 — must emit parseable JSON
            print(json.dumps({"error": f"{type(exc).__name__}: {exc}"[:500]}))
        return
    if "--kernel-only" in sys.argv:
        try:
            print(json.dumps(_tpu_pallas_rate()))
        except Exception as exc:  # noqa: BLE001
            print(json.dumps({"error": f"{type(exc).__name__}: {exc}"[:500]}))
        return

    cpu = _cpu_rate()
    tpu = _stage_in_subprocess("--kernel-only", timeout_s=300.0)
    e2e = _stage_in_subprocess("--e2e-only", timeout_s=420.0, attempts=2)
    if "rate" in tpu:
        out = {
            "metric": "ec_encode_GBps",
            "value": round(tpu["rate"], 2),
            "unit": "GB/s",
            "vs_baseline": round(tpu["rate"] / cpu, 1) if cpu else None,
            "impl": "pallas_swar_u32",
            "cpu_simd_GBps": round(cpu, 3),
            "sweep_bytes": tpu["bytes"],
            "seconds": round(tpu["seconds"], 4),
        }
    else:
        # TPU unreachable after bounded retries: degrade to the host CPU
        # SIMD codec so the driver still records a real measured number,
        # with the failure visible in `error`.
        out = {
            "metric": "ec_encode_GBps",
            "value": round(cpu, 3),
            "unit": "GB/s",
            "vs_baseline": 1.0,
            "impl": "cpu_simd_fallback",
            "cpu_simd_GBps": round(cpu, 3),
            "error": (tpu.get("error") or "unknown")[:500],
        }
    if "e2e_rate" in e2e:
        out["ec_encode_e2e_GBps"] = round(e2e["e2e_rate"], 2)
        out["ec_rebuild_GBps"] = round(e2e["rebuild_rate"], 2)
        out["e2e_bytes"] = e2e["e2e_bytes"]
        out["e2e_seconds"] = round(e2e["e2e_seconds"], 2)
        out["rebuild_seconds"] = round(e2e["rebuild_seconds"], 2)
    else:
        out["e2e_error"] = (e2e.get("error") or "unknown")[:300]
    print(json.dumps(out))


if __name__ == "__main__":
    main()
