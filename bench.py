#!/usr/bin/env python
"""Headline benchmark: EC encode GB/s per chip (RS 10+4, GF(2^8) on TPU).

Prints ONE JSON line:
  {"metric": "ec_encode_GBps", "value": <TPU pallas-kernel encode rate>,
   "unit": "GB/s", "vs_baseline": <ratio vs the CPU SIMD codec on this host>,
   ...details}

Methodology notes (this platform needs care):
  * `block_until_ready` does not reliably fence on the axon tunnel, and
    repeated dispatch of the same computation invites CSE.  So the timed
    workload is ONE device-side pallas_call with a (K, G) grid whose input
    index_map shifts by the sweep index k — K full encode sweeps over
    distinct HBM windows in a single dispatch, ended by a host readback.
  * Rate convention matches the reference workload accounting (BASELINE.md):
    encode throughput = volume bytes consumed per second.
  * The CPU baseline is our C++ SSSE3 nibble-table codec — the same
    algorithm class as the reference's SIMD assembly — on this host.
"""

from __future__ import annotations

import functools
import json
import time

import numpy as np


def _tpu_pallas_rate(tile: int = 256) -> dict:
    """Escalating-sweep kernel benchmark with a salvage contract.

    r04 lesson: the old single-shot version device_put a ~660MB buffer and
    printed NOTHING until the final readback — a wedged axon tunnel burned
    the whole 300s budget three times and the round recorded no TPU number
    at all.  Contract now:
      * stage 0 is a small probe (4MB/shard, ~46MB upload) that emits a
        measured partial JSON rate as soon as it completes;
      * each later stage (16 -> 64 -> 256 MB/shard) re-emits the best rate
        so far after the upload, after compile, and after EVERY timing rep,
        so a killed process always leaves the latest measurement on stdout;
      * a stage only starts if the previous stage's observed device_put
        rate projects it to fit in the remaining time budget;
      * SEAWEEDFS_TPU_BENCH_KERNEL_MB caps the largest stage — the retry
        loop halves it on timeout instead of re-running the same shape.
    """
    import os

    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    from seaweedfs_tpu.ops import gf256
    from seaweedfs_tpu.ops.rs_pallas import LANES, _kernel_body

    rows = tuple(tuple(int(c) for c in r) for r in gf256.rs_parity_matrix(10, 4))
    kernel = functools.partial(_kernel_body, rows)
    max_mb = int(os.environ.get("SEAWEEDFS_TPU_BENCH_KERNEL_MB", "256"))
    budget = float(os.environ.get("SEAWEEDFS_TPU_BENCH_KERNEL_BUDGET_S", "250"))
    # logic-testing escape hatch: run the pallas kernel in interpreter mode
    # on a CPU backend (orders of magnitude slower — never for real numbers)
    interpret = os.environ.get("SEAWEEDFS_TPU_BENCH_INTERPRET") == "1"
    if interpret:
        from seaweedfs_tpu.util.jaxenv import force_cpu_backend

        force_cpu_backend()
    t_start = time.perf_counter()
    result: dict = {}

    def emit(**kv) -> None:
        result.update(kv)
        print(json.dumps({"partial": True, **result}), flush=True)

    # (mb_per_shard, sweeps): upload is 10*(g+k) blocks, compute is k full
    # sweeps over g blocks — later stages amortise upload over more compute
    stages = [(4, 8), (16, 32), (64, 16), (256, 8)]
    put_rate = None  # bytes/s observed for device_put, drives stage gating
    compile_dt = 20.0  # refined from each stage's observed compile time
    for mb, k in stages:
        if mb > max_mb and mb != stages[0][0]:
            continue
        g = (mb << 20) // (tile * LANES * 4)
        upload_bytes = 10 * (g + k) * tile * LANES * 4
        remaining = budget - (time.perf_counter() - t_start)
        # each stage is a fresh XLA/Mosaic program (the grid changes), so
        # the projection budgets a compile alongside the upload
        if put_rate and (upload_bytes / put_rate * 1.3
                         + compile_dt * 1.5 + 10 > remaining):
            emit(skipped_stage_mb=mb, skip_reason="projected over budget")
            break
        rng = np.random.default_rng(0)
        host = rng.integers(
            0, 2**32, (10, (g + k) * tile * LANES), dtype=np.uint32
        ).reshape(10, (g + k) * tile, LANES)
        t0 = time.perf_counter()
        buf = jax.device_put(host)
        np.asarray(buf[0, 0, :2])  # fence: block_until_ready is unreliable here
        put_dt = time.perf_counter() - t0
        put_rate = upload_bytes / max(put_dt, 1e-6)
        emit(stage_mb=mb, put_seconds=round(put_dt, 2),
             put_GBps=round(put_rate / 1e9, 3))
        fn = jax.jit(
            pl.pallas_call(
                kernel,
                out_shape=jax.ShapeDtypeStruct((4, g * tile, LANES), jnp.uint32),
                grid=(k, g),
                in_specs=[
                    pl.BlockSpec(
                        (10, tile, LANES), lambda kk, gg: (0, gg + kk, 0),
                        memory_space=pltpu.VMEM,
                    )
                ],
                out_specs=pl.BlockSpec(
                    (4, tile, LANES), lambda kk, gg: (0, gg, 0),
                    memory_space=pltpu.VMEM,
                ),
                interpret=interpret,
            )
        )
        t0 = time.perf_counter()
        out = fn(buf)
        np.asarray(out[0, 0, :2])  # compile + warm
        compile_dt = time.perf_counter() - t0
        emit(compile_seconds=round(compile_dt, 2))
        bytes_encoded = 10 * g * tile * LANES * 4 * k
        for rep in range(3):
            t0 = time.perf_counter()
            out = fn(buf)
            np.asarray(out[0, 0, :2])  # fence via readback
            dt = time.perf_counter() - t0
            rate = bytes_encoded / dt / 1e9
            if rate > result.get("rate", 0.0):
                result.update(rate=rate, sweeps=k, bytes=bytes_encoded,
                              seconds=dt, sweep_mb_per_shard=mb)
            emit(rep=rep)
        del buf, out
    if "rate" not in result:
        return {"error": "no kernel stage completed"}
    return result


def _e2e_rates(volume_mb: int | None = None, slice_mb: int = 8,
               codec_name: str = "tpu") -> dict:
    """End-to-end file pipeline (BASELINE configs 2+3).

    Writes a synthetic .dat, times the full disk->HBM->shards encode
    (storage.ec.encoder pipelined path), then deletes the 4 FIRST data
    shards (worst case: full decode-matrix inversion) and times the rebuild.
    Rates follow the reference accounting: volume/input bytes per second.

    Robustness contract (this stage produced nothing for 3 rounds): it
    EMITS PARTIAL JSON LINES as it goes — after warmup, every ~2s of
    encode/rebuild progress, and after the encode stage — so if the axon
    tunnel wedges mid-transfer and the parent has to kill us, the captured
    stdout still carries a measured rate for every stage that ran.  The
    volume is deliberately small (default 256MB, SEAWEEDFS_TPU_BENCH_E2E_MB
    to override) so a healthy run finishes in well under a minute and the
    parent timeout is never the thing that ends it.
    """
    import os
    import shutil
    import sys
    import tempfile

    from seaweedfs_tpu.storage.ec.constants import DATA_SHARDS, to_ext
    from seaweedfs_tpu.storage.ec.encoder import (
        generate_ec_files,
        rebuild_ec_files,
    )

    if volume_mb is None:
        # host codecs sustain ~0.35 GB/s through this pipeline, so a 1GB
        # volume keeps the stage under ~15s while exercising 100 small-row
        # stripes; the device codec stays at 256MB because the tunnel
        # transport (~10 MB/s) makes anything larger a timeout risk
        default = "256" if codec_name != "cpu" else "1024"
        volume_mb = int(os.environ.get("SEAWEEDFS_TPU_BENCH_E2E_MB", default))
    slice_bytes = slice_mb << 20
    dat_size = max(64, volume_mb) << 20
    result = {"impl": codec_name, "e2e_bytes": dat_size}

    def emit(**kv) -> None:
        result.update(kv)
        print(json.dumps({"partial": True, **result}), flush=True)

    if codec_name != "cpu":
        # prove the tunnel is alive with a TINY buffer before investing in
        # anything.  r04 lesson: the old 80MB full-slice warm produced its
        # first partial only AFTER a full device round trip, so a wedged
        # transport yielded zero salvageable lines — now the first partial
        # prints before any device call, and the warm buffer is ~1.3MB
        # (the real slice shape compiles inside the timed region instead;
        # its one-time cost shows up in the first progress line, which is
        # an acceptable trade for never losing the whole stage).
        import jax.numpy as jnp

        from seaweedfs_tpu.ops.codec import get_codec

        codec = get_codec(codec_name)
        emit(warm_stage="starting")  # before the first device round trip
        t0 = time.perf_counter()
        warm = np.zeros((10, 256 * 512), dtype=np.uint8)  # 1.3MB total
        d3 = warm.view(np.uint32).reshape(10, -1, 128)
        out = codec.encode_device_u32_3d(jnp.asarray(d3))
        if out is None:
            out = codec.encode_device(jnp.asarray(warm))
        np.asarray(out)
        emit(warm_seconds=round(time.perf_counter() - t0, 2))

    tmp = tempfile.mkdtemp(prefix="swfs-bench-")
    base = os.path.join(tmp, "1")
    try:
        # content doesn't affect GF timing: tile one random block
        rng = np.random.default_rng(7)
        block = rng.integers(0, 256, 32 << 20, dtype=np.uint8).tobytes()
        # the timed write+sync of the .dat doubles as the raw-disk write
        # baseline: encode writes 1.4x the volume, so an e2e rate near
        # disk_write_GBps/1.4 means the pipeline runs at the disk's write
        # bandwidth and the codec is fully hidden behind I/O.  Syncing
        # here also keeps the timed encode from competing with its own
        # input's writeback (the read side stays page-cache warm — the
        # "warm volume" of BASELINE config 2).
        t0 = time.perf_counter()
        with open(base + ".dat", "wb") as f:
            left = dat_size
            while left > 0:
                n = min(len(block), left)
                f.write(block[:n])
                left -= n
            f.flush()
            os.fsync(f.fileno())  # time THIS file's writeback only
        result["disk_write_GBps"] = round(
            dat_size / (time.perf_counter() - t0) / 1e9, 3)
        os.sync()  # untimed: clear any other dirty pages before the encode

        last_emit = time.perf_counter()

        def progress(tag: str, start: float, total: int, scale: int = 1):
            # `scale` keeps partial rates on the same accounting as the
            # completed-stage rate (rebuild counts DATA_SHARDS x shard
            # bytes, but the callback reports single-shard column offsets).
            # The emitted {tag}_rate never regresses: a throttled trial's
            # in-flight rate must not overwrite an earlier COMPLETED
            # trial's best-of in the salvage stream (the last partial line
            # is what a timeout kill records).
            def cb(done: int) -> None:
                nonlocal last_emit
                now = time.perf_counter()
                rate = done * scale / (now - start) / 1e9
                print(f"{tag}: {done >> 20}/{total >> 20} MB "
                      f"{rate:.3f} GB/s", file=sys.stderr, flush=True)
                if now - last_emit > 2.0:
                    last_emit = now
                    emit(**{f"{tag}_rate": max(
                            rate, result.get(f"{tag}_rate", 0.0)),
                            f"{tag}_partial_bytes": done})
            return cb

        # three timed trials for host codecs, best-of (mirrors the kernel
        # stage's min-of-3).  Why best-of and not mean: r05 profiling
        # showed the e2e wall time is 96% kernel buffered-write path
        # whose throughput swings 0.2-4.5 GB/s with dirty-page/writeback
        # state on this 1-core VM (codec compute is 0.2s/GB; the
        # user-space gather/syscall costs were eliminated by the mmap+
        # writev encode path) — best-of measures the pipeline, not the
        # writeback lottery.  Trial 1 additionally pays first-allocation
        # of the 1.4x shard extents.  Device codecs run once: the tunnel
        # transport is the bound and a second 100s pass buys nothing.
        trials = 1 if codec_name != "cpu" else 3
        encode_dt = None
        for trial in range(trials):
            t0 = time.perf_counter()
            generate_ec_files(base, codec_name=codec_name,
                              slice_size=slice_bytes,
                              progress=progress("e2e", time.perf_counter(),
                                                dat_size))
            dt = time.perf_counter() - t0
            encode_dt = dt if encode_dt is None else min(encode_dt, dt)
            emit(e2e_rate=dat_size / encode_dt / 1e9,
                 e2e_seconds=round(encode_dt, 2), e2e_trials=trial + 1)
        if codec_name == "cpu":
            # durability-matched variant: shard files fsync'd inside the
            # timed region, so this rate shares semantics with
            # disk_write_GBps (which times an fsync'd raw write) — the
            # warm-cache e2e_rate above deliberately excludes writeback,
            # mirroring the reference encode which never syncs shards
            # (ec_encoder.go:194-231)
            t0 = time.perf_counter()
            generate_ec_files(base, codec_name=codec_name,
                              slice_size=slice_bytes, sync=True)
            emit(e2e_fsync_rate=round(
                dat_size / (time.perf_counter() - t0) / 1e9, 3))

        shard_size = os.path.getsize(base + to_ext(0))
        for i in range(4):  # lose 4 data shards — worst case
            os.remove(base + to_ext(i))
        t0 = time.perf_counter()
        rebuilt = rebuild_ec_files(
            base, codec_name=codec_name, slice_size=slice_bytes,
            progress=progress("rebuild", time.perf_counter(), shard_size,
                              scale=DATA_SHARDS))
        rebuild_dt = time.perf_counter() - t0
        assert rebuilt == [0, 1, 2, 3]
        result.update(
            rebuild_rate=shard_size * DATA_SHARDS / rebuild_dt / 1e9,
            rebuild_seconds=round(rebuild_dt, 2),
        )
        for k in list(result):
            if k.endswith("_partial_bytes"):
                del result[k]
        return result
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def _parse_lose_env(var: str, default: str) -> list[int]:
    """Loss-pattern knob: a csv of shard ids to delete (e.g. "0,1,2,3"
    for the worst-case first-4-data pattern, "10,11,12,13" for parity,
    "2,7,11,13" for mixed)."""
    import os

    raw = os.environ.get(var, default)
    ids = sorted({int(x) for x in raw.split(",") if x.strip() != ""})
    if any(i < 0 or i > 13 for i in ids) or len(ids) > 4:
        raise ValueError(f"{var}={raw!r}: want <=4 shard ids in 0..13")
    return ids


def _rebuild_only_rates(codec_name: str | None = None) -> dict:
    """BASELINE config 3 in isolation: encode a synthetic volume
    (untimed), delete the configured loss pattern
    (SEAWEEDFS_TPU_BENCH_LOSE, default the worst-case first 4 data
    shards), and time rebuild_ec_files alone — the repair-plane headline
    without the encode stage's accounting in the way.  Asserts the
    rebuilt shards byte-identical to the originals.  Volume size via
    SEAWEEDFS_TPU_BENCH_E2E_MB (default 1024), codec via
    SEAWEEDFS_TPU_BENCH_REBUILD_CODEC (default cpu)."""
    import hashlib
    import os
    import shutil
    import tempfile

    from seaweedfs_tpu.storage.ec.constants import DATA_SHARDS, to_ext
    from seaweedfs_tpu.storage.ec.encoder import (
        generate_ec_files,
        rebuild_ec_files,
    )

    if codec_name is None:
        codec_name = os.environ.get("SEAWEEDFS_TPU_BENCH_REBUILD_CODEC", "cpu")
    lose = _parse_lose_env("SEAWEEDFS_TPU_BENCH_LOSE", "0,1,2,3")
    volume_mb = int(os.environ.get("SEAWEEDFS_TPU_BENCH_E2E_MB", "1024"))
    dat_size = max(64, volume_mb) << 20
    slice_bytes = 8 << 20
    result = {"impl": codec_name, "rebuild_lost_shards": lose,
              "rebuild_bytes": dat_size}

    def emit(**kv) -> None:
        result.update(kv)
        print(json.dumps({"partial": True, **result}), flush=True)

    tmp = tempfile.mkdtemp(prefix="swfs-rebuild-")
    base = os.path.join(tmp, "1")
    try:
        rng = np.random.default_rng(7)
        block = rng.integers(0, 256, 32 << 20, dtype=np.uint8).tobytes()
        with open(base + ".dat", "wb") as f:
            left = dat_size
            while left > 0:
                n = min(len(block), left)
                f.write(block[:n])
                left -= n
        generate_ec_files(base, codec_name=codec_name,
                          slice_size=slice_bytes)
        os.sync()  # the timed rebuild must not compete with encode writeback
        digests = {}
        for sid in lose:
            h = hashlib.sha256()
            with open(base + to_ext(sid), "rb") as f:
                for chunk in iter(lambda: f.read(8 << 20), b""):
                    h.update(chunk)
            digests[sid] = h.hexdigest()
            os.remove(base + to_ext(sid))
        shard_size = os.path.getsize(
            base + to_ext(next(i for i in range(14) if i not in lose)))
        emit(encode_done=True)

        # best-of-2: same writeback-lottery reasoning as the e2e stage
        rebuild_dt = None
        for trial in range(2):
            if trial:
                for sid in lose:
                    os.remove(base + to_ext(sid))
            t0 = time.perf_counter()
            rebuilt = rebuild_ec_files(base, codec_name=codec_name,
                                       slice_size=slice_bytes)
            dt = time.perf_counter() - t0
            assert sorted(rebuilt) == lose
            rebuild_dt = dt if rebuild_dt is None else min(rebuild_dt, dt)
            emit(rebuild_rate=shard_size * DATA_SHARDS / rebuild_dt / 1e9,
                 rebuild_seconds=round(rebuild_dt, 2),
                 rebuild_trials=trial + 1)
        for sid in lose:
            h = hashlib.sha256()
            with open(base + to_ext(sid), "rb") as f:
                for chunk in iter(lambda: f.read(8 << 20), b""):
                    h.update(chunk)
            if h.hexdigest() != digests[sid]:
                return {"error": f"rebuilt shard {sid} not byte-identical"}
        result["rebuild_byte_identical"] = True

        # ISSUE 10: partial-sum vs full-fetch A/B on ONE lost shard with
        # all 10 sources remote — the wire-reduction headline, measured
        # by the locality-labeled rebuild-ingress counters
        ab = _rebuild_ab_rates(base, tmp, codec_name, slice_bytes,
                               lose[0], digests[lose[0]])
        result["rebuild_ab"] = ab
        emit()
        if not ab.get("byte_identical"):
            result["error"] = "partial-sum A/B not byte-identical"
        return result
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def _rebuild_ab_rates(src_base: str, tmp: str, codec_name: str,
                      slice_bytes: int, lost: int, want_digest: str) -> dict:
    """Rebuild one lost shard twice with ALL 10 sources remote — once
    streaming full shard intervals, once through the partial-sum
    protocol (10 sources on 10 fake nodes across 2 racks; one rack is
    the rebuilder's, so exactly one combined partial crosses each rack
    boundary and one arrives rack-locally).  Network-in per leg comes
    from the seaweedfs_ec_rebuild_bytes_total{source=rack|dc} deltas;
    byte-identity against the original shard digest gates the result."""
    import hashlib
    import os

    from seaweedfs_tpu.stats.metrics import REGISTRY
    from seaweedfs_tpu.storage.ec import partial as P
    from seaweedfs_tpu.storage.ec.constants import TOTAL_SHARDS, to_ext
    from seaweedfs_tpu.storage.ec.encoder import rebuild_ec_files

    shard_size = os.path.getsize(src_base + to_ext(lost))

    def counters() -> dict:
        return {k: v for k, v in REGISTRY.snapshot_samples(max_samples=1 << 20)
                if "ec_rebuild_bytes" in k or "ec_partial" in k}

    def delta(before: dict, after: dict, name: str) -> float:
        return sum(after.get(k, 0.0) - before.get(k, 0.0)
                   for k in after if k.startswith(name))

    nodes, holders = {}, {}
    for sid in range(TOTAL_SHARDS):
        if sid == lost:
            continue
        addr = f"bench-src-{sid}:0"
        nodes[addr] = (src_base, [sid])
        # rack0 == the rebuilder's rack, rack1 crosses the boundary
        holders[sid] = [(addr, f"rack{sid % 2}", "dc1")]

    def remote_fetch(sid, off, length):
        if sid == lost:
            return None
        with open(src_base + to_ext(sid), "rb") as f:
            f.seek(off)
            return f.read(length)

    remote_fetch.locality_of = (
        lambda sid: "rack" if sid % 2 == 0 else "dc")

    out: dict = {"lost_shard": lost, "shard_size": shard_size}
    legs = {
        "full": dict(remote_fetch=remote_fetch, shard_size=shard_size),
        "partial": dict(
            remote_fetch=remote_fetch,
            partial=P.PartialRepairClient(
                1, "", lambda: holders, P.local_source_network(nodes),
                my_rack="rack0", my_dc="dc1")),
    }
    for leg, kw in legs.items():
        rdir = os.path.join(tmp, f"ab-{leg}")
        os.makedirs(rdir, exist_ok=True)
        rbase = os.path.join(rdir, "1")
        before = counters()
        t0 = time.perf_counter()
        rebuilt = rebuild_ec_files(rbase, codec_name=codec_name,
                                   slice_size=slice_bytes, **kw)
        dt = time.perf_counter() - t0
        after = counters()
        if rebuilt != [lost]:
            return {"error": f"{leg} leg rebuilt {rebuilt}, want [{lost}]"}
        h = hashlib.sha256()
        with open(rbase + to_ext(lost), "rb") as f:
            for chunk in iter(lambda: f.read(8 << 20), b""):
                h.update(chunk)
        if h.hexdigest() != want_digest:
            return {"error": f"{leg} leg not byte-identical"}
        rack_in = delta(before, after,
                        'seaweedfs_ec_rebuild_bytes_total{source="rack"}')
        dc_in = delta(before, after,
                      'seaweedfs_ec_rebuild_bytes_total{source="dc"}')
        out[leg] = {
            "seconds": round(dt, 3),
            "bytes_in": int(rack_in + dc_in),
            "bytes_in_rack": int(rack_in),
            "bytes_in_dc": int(dc_in),
            "fallbacks": int(delta(
                before, after, "seaweedfs_ec_partial_fallback_total")),
        }
    full_in = out["full"]["bytes_in"]
    part_in = out["partial"]["bytes_in"]
    out["wire_reduction"] = round(full_in / part_in, 2) if part_in else 0.0
    out["bytes_in_per_rebuilt_shard"] = {
        "full": full_in, "partial": part_in}
    out["byte_identical"] = True
    return out


def _mass_repair_rates() -> dict:
    """ISSUE 11 A/B over a LIVE loopback cluster (real gRPC sockets,
    the shipped code path end to end): one dead node's worth of EC
    volumes (default 32, SEAWEEDFS_TPU_BENCH_MASS_VOLUMES) each missing
    one shard, rebuilt twice on the same planned targets —

      * per_volume: the PR 10 status quo — one VolumeEcShardsRebuild +
        Mount rpc pair per volume IN SEQUENCE, each rebuild doing its
        own holder lookup, liveness probes and per-rack partial rpcs;
      * batched: the mass-repair transport — one
        VolumeEcShardsBatchRebuild rpc per target node (fired
        concurrently), every volume sourcing remote columns through one
        cross-volume MassPartialSession with plan-supplied size hints.

    Reported per leg: wall seconds, gRPC rpcs served (request_total
    deltas over the EC repair surface), and rebuilder-boundary wire
    bytes (partial request + received-partial counters).  Byte-identity
    against the staged shard digests gates the result; interleaved
    best-of-2 per the noisy-host discipline.  Per-volume .dat MB via
    SEAWEEDFS_TPU_BENCH_MASS_MB (default 2); EC block sizes are scaled
    down (SMALL=64KB) so shards carry real data instead of 1MB padding.
    """
    import hashlib
    import os
    import shutil
    import tempfile
    from concurrent.futures import ThreadPoolExecutor

    from seaweedfs_tpu.master.server import MasterServer
    from seaweedfs_tpu.pb import rpc as rpclib
    from seaweedfs_tpu.pb import volume_server_pb2 as vs
    from seaweedfs_tpu.stats.metrics import REGISTRY
    from seaweedfs_tpu.storage.ec.constants import (
        DATA_SHARDS,
        TOTAL_SHARDS,
        to_ext,
    )
    from seaweedfs_tpu.storage.ec.encoder import generate_ec_files
    from seaweedfs_tpu.volume.server import VolumeServer

    n_vols = int(os.environ.get("SEAWEEDFS_TPU_BENCH_MASS_VOLUMES", "32"))
    vol_mb = float(os.environ.get("SEAWEEDFS_TPU_BENCH_MASS_MB", "2"))
    n_srv = 5
    large, small = 1 << 20, 64 << 10
    dat_size = max(small * DATA_SHARDS, int(vol_mb * (1 << 20)))
    result: dict = {"mass_volumes": n_vols, "volume_bytes": dat_size}

    def emit(**kv) -> None:
        result.update(kv)
        print(json.dumps({"partial": True, **result}), flush=True)

    def free_port() -> int:
        import socket

        with socket.socket() as sk:
            sk.bind(("127.0.0.1", 0))
            return sk.getsockname()[1]

    tmp = tempfile.mkdtemp(prefix="swfs-mass-")
    master = None
    servers: list = []
    try:
        master = MasterServer(ip="127.0.0.1", port=free_port(),
                              volume_size_limit_mb=64, pulse_seconds=1.0)
        # the A/B drives the repair transport by hand; the autonomous
        # orchestrator would race it and heal the staged volumes first
        master.mass_repair.enabled = False
        master.start()
        for i in range(n_srv):
            d = os.path.join(tmp, f"vol{i}")
            os.makedirs(d)
            srv = VolumeServer(
                directories=[d],
                master_addresses=[f"127.0.0.1:{master.grpc_port}"],
                ip="127.0.0.1", port=free_port(), pulse_seconds=1.0,
                rack=f"rack{i % 2}", data_center="dc1",
                max_volume_count=max(64, n_vols))
            srv.start()
            servers.append(srv)
        deadline = time.time() + 30
        while time.time() < deadline and len(master.topo.nodes) < n_srv:
            time.sleep(0.1)
        if len(master.topo.nodes) < n_srv:
            return {**result, "error": "cluster never formed"}

        # stage: every volume misses shard (vid % 14) cluster-wide (the
        # dead node is already gone); survivors spread over all servers
        rng = np.random.default_rng(23)
        block = rng.integers(0, 256, min(dat_size, 8 << 20),
                             dtype=np.uint8).tobytes()
        digests: dict = {}
        lost_of: dict = {}
        stage = os.path.join(tmp, "stage")
        for v in range(1, n_vols + 1):
            d = os.path.join(stage, str(v))
            os.makedirs(d)
            base = os.path.join(d, str(v))
            with open(base + ".dat", "wb") as f:
                left = dat_size
                while left > 0:
                    n = min(len(block), left)
                    f.write(block[:n])
                    left -= n
            generate_ec_files(base, codec_name="cpu",
                              large_block_size=large,
                              small_block_size=small,
                              slice_size=4 << 20)
            lost = v % TOTAL_SHARDS
            lost_of[v] = lost
            h = hashlib.sha256()
            with open(base + to_ext(lost), "rb") as f:
                for chunk in iter(lambda: f.read(8 << 20), b""):
                    h.update(chunk)
            digests[v] = h.hexdigest()
            assign: dict = {j: [] for j in range(n_srv)}
            for k, sid in enumerate(
                    s for s in range(TOTAL_SHARDS) if s != lost):
                assign[k % n_srv].append(sid)
            for j, sids in assign.items():
                tbase = servers[j].store.locations[0].base_name(v, "")
                # synthetic volume: no needle index exists (the bench
                # never reads needles) — mount only requires the file
                open(tbase + ".ecx", "ab").close()
                for sid in sids:
                    shutil.copy(base + to_ext(sid), tbase + to_ext(sid))
                servers[j].store.mount_ec_shards(v, "", sids)
        deadline = time.time() + 60
        while time.time() < deadline and any(
                len(master.topo.lookup_ec_shards(v)) < 13
                for v in range(1, n_vols + 1)):
            time.sleep(0.3)
        shard_size = os.path.getsize(
            os.path.join(stage, "1", "1" + to_ext(lost_of[1] or 1)))
        result["shard_bytes"] = shard_size
        emit(setup_done=True)

        # one plan, both legs: identical targets (the orchestrator's
        # exposure-ranked, cap-spread assignment)
        plans = master.mass_repair.plan()
        if len(plans) != n_vols:
            return {**result,
                    "error": f"planned {len(plans)} of {n_vols}"}
        by_node = {s.store.public_url: s for s in servers}

        def stub_of(node_id):
            host, port = node_id.rsplit(":", 1)
            return rpclib.volume_server_stub(
                f"{host}:{int(port) + 10000}", timeout=600)

        RPC_OPS = ("VolumeEcShardPartialApply", "VolumeEcShardRead",
                   "VolumeEcShardsRebuild", "VolumeEcShardsBatchRebuild",
                   "VolumeEcShardsMount", "LookupEcVolume")
        # background chatter present in both legs but not repair traffic
        BG_OPS = ("SendHeartbeat", "KeepConnected")

        def counters() -> dict:
            """Total wire = every serialized gRPC byte the cluster moved
            (seaweedfs_grpc_bytes_total, counted at the codec boundary),
            heartbeat/keepalive chatter excluded; rpcs = repair-surface
            request counts."""
            out: dict = {"wire": 0.0, "rpcs": 0.0}
            for k, val in REGISTRY.snapshot_samples(max_samples=1 << 20):
                if (k.startswith("seaweedfs_grpc_bytes_total")
                        and not any(f'op="{op}"' in k for op in BG_OPS)):
                    out["wire"] += val
                if k.startswith("seaweedfs_request_total") and any(
                        f'op="{op}"' in k for op in RPC_OPS):
                    out["rpcs"] += val
            return out

        def verify() -> bool:
            for p in plans:
                v = p["volume_id"]
                srv = by_node[p["node"]]
                path = srv.store._ec_base(v, "") + to_ext(lost_of[v])
                h = hashlib.sha256()
                with open(path, "rb") as f:
                    for chunk in iter(lambda: f.read(8 << 20), b""):
                        h.update(chunk)
                if h.hexdigest() != digests[v]:
                    return False
            return True

        def reset() -> None:
            """Drop the rebuilt shards so the next leg starts degraded,
            and wait for the deletion deltas to reach the master — the
            next leg's holder lookups must not see the dead shard as
            alive (rebuild would no-op)."""
            for p in plans:
                v = p["volume_id"]
                stub_of(p["node"]).VolumeEcShardsDelete(
                    vs.VolumeEcShardsDeleteRequest(
                        volume_id=v, collection="",
                        shard_ids=[lost_of[v]]))
            deadline = time.time() + 30
            while time.time() < deadline and any(
                    lost_of[p["volume_id"]] in master.topo.lookup_ec_shards(
                        p["volume_id"])
                    for p in plans):
                time.sleep(0.2)

        def leg_per_volume() -> dict:
            before = counters()
            t0 = time.perf_counter()
            for p in plans:
                v = p["volume_id"]
                stub = stub_of(p["node"])
                resp = stub.VolumeEcShardsRebuild(
                    vs.VolumeEcShardsRebuildRequest(
                        volume_id=v, collection=""))
                rebuilt = list(resp.rebuilt_shard_ids)
                assert rebuilt == [lost_of[v]], (v, rebuilt)
                stub.VolumeEcShardsMount(
                    vs.VolumeEcShardsMountRequest(
                        volume_id=v, collection="", shard_ids=rebuilt))
            dt = time.perf_counter() - t0
            after = counters()
            return {"seconds": round(dt, 3),
                    "rpcs": int(after.get("rpcs", 0)
                                - before.get("rpcs", 0)),
                    "wire_bytes": int(after.get("wire", 0)
                                      - before.get("wire", 0))}

        def leg_batched() -> dict:
            groups: dict = {}
            for p in plans:
                groups.setdefault(p["node"], []).append(p)
            before = counters()
            t0 = time.perf_counter()

            def run_target(item):
                node, tjobs = item
                resp = stub_of(node).VolumeEcShardsBatchRebuild(
                    vs.VolumeEcShardsBatchRebuildRequest(
                        jobs=[vs.BatchRebuildJob(
                            volume_id=p["volume_id"], collection="",
                            shard_size=p["shard_size"]) for p in tjobs]))
                for r in resp.results:
                    assert not r.error, (r.volume_id, r.error)

            with ThreadPoolExecutor(max_workers=len(groups)) as pool:
                list(pool.map(run_target, groups.items()))
            dt = time.perf_counter() - t0
            after = counters()
            return {"seconds": round(dt, 3),
                    "rpcs": int(after.get("rpcs", 0)
                                - before.get("rpcs", 0)),
                    "wire_bytes": int(after.get("wire", 0)
                                      - before.get("wire", 0))}

        # interleaved best-of-2: each leg's best trial faces the same
        # background-interference lottery on a noisy host
        legs: dict = {}
        order = (("per_volume", leg_per_volume),
                 ("batched", leg_batched))
        for trial in range(2):
            for name, fn in order:
                r = fn()
                if not verify():
                    return {**result,
                            "error": f"{name} leg not byte-identical"}
                reset()
                if (name not in legs
                        or r["seconds"] < legs[name]["seconds"]):
                    legs[name] = r
                emit(**{name: legs[name], "trials": trial + 1})
        pv, bt = legs["per_volume"], legs["batched"]
        rebuilt_bytes = n_vols * shard_size
        result.update(
            per_volume=pv, batched=bt, byte_identical=True,
            speedup=round(pv["seconds"] / bt["seconds"], 2)
            if bt["seconds"] else 0.0,
            rpc_reduction=round(pv["rpcs"] / bt["rpcs"], 2)
            if bt["rpcs"] else 0.0,
            wire_bytes_saved=pv["wire_bytes"] - bt["wire_bytes"],
            # reconstructed shard bytes / wall time: the same quantity
            # seaweedfs_repair_batch_bytes_total over _seconds measures,
            # so bench and Prometheus rates compare 1:1
            aggregate_repair_GBps=round(
                rebuilt_bytes / bt["seconds"] / 1e9, 3)
            if bt["seconds"] else 0.0,
            batch_faster=bt["seconds"] < pv["seconds"],
            batch_fewer_wire_bytes=bt["wire_bytes"] < pv["wire_bytes"],
        )
        emit()
        return result
    finally:
        for srv in servers:
            srv.stop()
        if master is not None:
            master.stop()
        shutil.rmtree(tmp, ignore_errors=True)


def _degraded_read_rate(n_needles: int = 600, needle_kb: int = 64,
                        concurrency: int = 16,
                        lose_shards: "list[int] | None" = None,
                        duration_s: float = 4.0) -> dict:
    """BASELINE config 5: streaming EC reads reconstructing needles from
    10-of-14 shards under concurrent load (the reference drives this with
    `weed benchmark` against a degraded volume; here the same read path —
    EcVolume.read_needle -> interval reconstruct on the CPU codec, as
    per-needle reads must never pay device dispatch — runs in-process
    with the reference benchmark's c=16).

    Loses the 4 FIRST data shards, so every needle whose intervals land in
    shards 0-3 pays a full decode-matrix reconstruction from the 10
    survivors; needles on surviving shards measure the undegraded path.
    Reports needles/s and payload GB/s over a fixed wall budget.

    Floor analysis (r05): this host exposes ONE vCPU, so c=16 cannot
    exceed a single core's throughput.  After the r05 optimisation pass
    (single-row decode instead of all-lost reconstruct, .ecx key-column
    searchsorted replacing the pread binary search, and void*-address
    ctypes marshalling) the per-read CPU cost is ~150us — needle parse +
    64KB native CRC32C, the 10-way survivor pread gather, and one GF row
    decode — bounding this host at ~6.5-8k reads/s (r04: 5.0k).  Shard
    reads stay pread, NOT mmap: a truncating racer turns mapped reads
    into process-killing SIGBUS (observed; see EcVolumeShard.read_at).
    The reference's ~47k figure (README.md:545) is an UNdegraded
    1KB-needle run on a multi-core laptop; its shape needs cores.
    """
    import os
    import shutil
    import tempfile
    from concurrent.futures import ThreadPoolExecutor

    from seaweedfs_tpu.storage.ec.constants import to_ext
    from seaweedfs_tpu.storage.ec.encoder import (
        generate_ec_files,
        write_sorted_file_from_idx,
    )
    from seaweedfs_tpu.storage.ec.volume import EcVolume
    from seaweedfs_tpu.storage.needle import FLAG_HAS_NAME, Needle
    from seaweedfs_tpu.storage.super_block import SuperBlock
    from seaweedfs_tpu.storage.volume import Volume

    if lose_shards is None:
        lose_shards = _parse_lose_env(
            "SEAWEEDFS_TPU_BENCH_DEGRADED_LOSE", "0,1,2,3")
    rng = np.random.default_rng(11)
    tmp = tempfile.mkdtemp(prefix="swfs-degraded-")
    try:
        vol = Volume(tmp, "", 1, super_block=SuperBlock())
        payload = needle_kb << 10
        for i in range(1, n_needles + 1):
            n = Needle(cookie=int(rng.integers(0, 2**32)), id=i,
                       data=rng.integers(0, 256, payload)
                       .astype(np.uint8).tobytes())
            n.set(FLAG_HAS_NAME)
            n.name = f"bench-{i}.bin".encode()
            vol.append_needle(n)
        base = vol.file_name()
        vol.close()
        generate_ec_files(base, codec_name="cpu")
        write_sorted_file_from_idx(base)
        for sid in lose_shards:
            os.remove(base + to_ext(sid))

        ev = EcVolume(base, volume_id=1)
        stop_at = time.perf_counter() + duration_s
        t0 = time.perf_counter()

        def worker(seed: int) -> tuple[int, int]:
            r = np.random.default_rng(seed)
            reads = bytes_read = 0
            while time.perf_counter() < stop_at:
                nid = int(r.integers(1, n_needles + 1))
                needle = ev.read_needle(nid)
                assert needle.id == nid
                reads += 1
                bytes_read += len(needle.data)
            return reads, bytes_read

        with ThreadPoolExecutor(max_workers=concurrency) as pool:
            results = list(pool.map(worker, range(concurrency)))
        dt = time.perf_counter() - t0
        ev.close()
        reads = sum(r for r, _ in results)
        payload_bytes = sum(b for _, b in results)
        return {
            "degraded_reads_per_s": round(reads / dt, 1),
            "degraded_read_GBps": round(payload_bytes / dt / 1e9, 4),
            "degraded_concurrency": concurrency,
            "degraded_lost_shards": lose_shards,
        }
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def _scrape_metrics(url: str) -> dict:
    """-> {sample_name_with_labels: float} for counter/gauge samples."""
    import urllib.request

    from seaweedfs_tpu.telemetry.federation import parse_exposition

    with urllib.request.urlopen(url, timeout=10) as r:
        families, samples = parse_exposition(r.read().decode())
    out = {}
    for family, sample_name, value in samples:
        if families.get(family, ("",))[0] in ("counter", "gauge"):
            try:
                out[sample_name] = float(value)
            except ValueError:
                continue
    return out


# counter families worth folding into bench JSON: cache effectiveness,
# connection reuse, and retry pressure explain a rate delta between runs
_SNAPSHOT_PREFIXES = (
    "seaweedfs_needle_cache_", "seaweedfs_chunk_cache_total",
    "seaweedfs_connpool_reuse_total", "seaweedfs_connpool_dial_total",
    "seaweedfs_connpool_evict_total", "seaweedfs_retry_total",
    "seaweedfs_replication_error_total", "seaweedfs_request_total",
    "seaweedfs_ec_service_jobs_total", "seaweedfs_ec_service_flush_total",
    "seaweedfs_fsync_batch_", "seaweedfs_sendfile_",
    "seaweedfs_ec_preadv_batches_total",
)


def _metrics_delta(before: dict, after: dict) -> dict:
    """Counter deltas over a bench run, filtered to the families above,
    zero deltas dropped; plus derived hit/reuse rates."""
    delta = {}
    for name, v in after.items():
        if not name.startswith(_SNAPSHOT_PREFIXES):
            continue
        d = v - before.get(name, 0.0)
        if d:
            delta[name] = round(d, 3)

    def d(name: str) -> float:
        return delta.get(name, 0.0)

    out = {"metrics_delta": delta}
    hits, misses = d("seaweedfs_needle_cache_hit_total"), d(
        "seaweedfs_needle_cache_miss_total")
    if hits + misses > 0:
        out["needle_cache_hit_rate"] = round(hits / (hits + misses), 4)
    reuse, dial = d("seaweedfs_connpool_reuse_total"), d(
        "seaweedfs_connpool_dial_total")
    if reuse + dial > 0:
        out["connpool_reuse_rate"] = round(reuse / (reuse + dial), 4)
    retries = sum(v for k, v in delta.items()
                  if k.startswith("seaweedfs_retry_total"))
    if retries:
        out["retries_during_run"] = round(retries, 1)
    return out


def _smallfile_rates(n: int = 20000, concurrency: int = 16,
                     payload_bytes: int = 1024,
                     metrics_snapshot: bool = False,
                     verify_bytes: bool = False) -> dict:
    """The reference's ONLY published benchmark: random write then read
    of 1KB files at c=16 through the full HTTP data path (README.md:
    514-567, `weed benchmark` defaults benchmark.go:57-59).  Runs an
    in-process master + volume server and drives keep-alive HTTP
    connections exactly like the reference harness.  n is scaled down
    from the reference's 1,048,576 to keep the stage bounded; rates are
    per-second so the comparison holds."""
    import http.client
    import os
    import shutil
    import tempfile
    import threading
    import urllib.request
    from concurrent.futures import ThreadPoolExecutor

    from seaweedfs_tpu.master.server import MasterServer
    from seaweedfs_tpu.volume.server import VolumeServer

    reserved_ports: set[int] = set()

    def _port() -> int:
        # mirrors tests/helpers.free_port: servers derive grpc_port as
        # port+10000, so anything above 55535 would overflow the port
        # space, and BOTH the http port and its derived grpc sibling
        # must stay clear of every previously reserved pair
        import socket

        while True:
            with socket.socket() as s:
                s.bind(("127.0.0.1", 0))
                p = s.getsockname()[1]
            if (p <= 55000 and p not in reserved_ports
                    and p + 10000 not in reserved_ports):
                reserved_ports.update((p, p + 10000))
                return p

    tmp = tempfile.mkdtemp(prefix="swfs-smallfile-")
    # --metrics-snapshot also runs the judgment plane during the bench:
    # canary probes every second + the SLO engine on its burn-rate
    # rules, so the emitted JSON carries its own SLO verdict (probe
    # p50/p99 + any alerts that fired during the run)
    master = MasterServer(ip="127.0.0.1", port=_port(),
                          volume_size_limit_mb=1024,
                          canary_interval=1.0 if metrics_snapshot else 0.0,
                          slo_interval=1.0 if metrics_snapshot else 0.0)
    master.start()
    vs_ = VolumeServer(directories=[tmp], ip="127.0.0.1", port=_port(),
                       master_addresses=[f"127.0.0.1:{master.grpc_port}"],
                       pulse_seconds=0.5, max_volume_count=16)
    vs_.start()
    try:
        deadline = time.time() + 15
        while time.time() < deadline and len(master.topo.nodes) < 1:
            time.sleep(0.1)
        # --metrics-snapshot: counter state before the run; the delta at
        # the end explains the measured rates (cache hit rates, connpool
        # reuse vs dial, retry pressure) in the emitted JSON
        m_before = (_scrape_metrics(f"http://127.0.0.1:{vs_.port}/metrics")
                    if metrics_snapshot else None)
        # pre-assign fids in bulk through the master (the reference
        # assigns per write; bulk keeps the master out of the hot loop
        # measurement the same way its writeBenchmark reuses assigns)
        fids: list[tuple[str, str]] = []
        with urllib.request.urlopen(
            f"http://127.0.0.1:{master.port}/dir/assign?count={n}",
            timeout=20,
        ) as r:
            first = json.loads(r.read())
        base_fid, url = first["fid"], first["url"]
        vid, _, rest = base_fid.partition(",")
        key_hex, cookie = rest[:-8], rest[-8:]
        base_key = int(key_hex, 16)
        fids = [(f"{vid},{base_key + i:x}{cookie}", url)
                for i in range(n)]
        payload = os.urandom(payload_bytes)
        local = threading.local()

        def conn() -> http.client.HTTPConnection:
            c = getattr(local, "c", None)
            if c is None:
                c = http.client.HTTPConnection("127.0.0.1", vs_.port,
                                               timeout=20)
                c.connect()
                import socket as _socket

                c.sock.setsockopt(_socket.IPPROTO_TCP,
                                  _socket.TCP_NODELAY, 1)
                local.c = c
            return c

        lat: list[float] = []
        lat_lock = threading.Lock()

        def write_one(i: int) -> None:
            fid, _ = fids[i]
            body = (b"--bb\r\nContent-Disposition: form-data; "
                    b'name="file"; filename="b.bin"\r\n\r\n'
                    + payload + b"\r\n--bb--\r\n")
            t0 = time.perf_counter()
            c = conn()
            try:
                c.request("POST", f"/{fid}", body, {
                    "Content-Type": "multipart/form-data; boundary=bb"})
                resp = c.getresponse()
                resp.read()
                if resp.status >= 300:
                    return  # counted as failed, not timed as a success
            except (http.client.HTTPException, OSError):
                c.close()
                local.c = None
                return
            with lat_lock:
                lat.append(time.perf_counter() - t0)

        t0 = time.perf_counter()
        with ThreadPoolExecutor(concurrency) as pool:
            list(pool.map(write_one, range(n)))
        write_dt = time.perf_counter() - t0
        lat.sort()
        out = {
            "smallfile_write_reqs_per_s": round(len(lat) / write_dt, 1),
            "smallfile_write_avg_ms": round(
                sum(lat) / max(len(lat), 1) * 1000, 2),
            "smallfile_write_p99_ms": round(
                lat[int(len(lat) * 0.99) - 1] * 1000, 2) if lat else None,
            "smallfile_n": n,
            "smallfile_concurrency": concurrency,
            "smallfile_failed": n - len(lat),
        }

        lat.clear()
        mismatches = [0]

        def read_one(i: int) -> None:
            # Weyl-sequence index scramble: "random" reads without
            # sharing a numpy Generator across threads (not thread-safe)
            fid, _ = fids[(i * 2654435761) % n]
            t0 = time.perf_counter()
            c = conn()
            try:
                c.request("GET", f"/{fid}")
                resp = c.getresponse()
                body = resp.read()
                if resp.status >= 300:
                    return
                if verify_bytes and body != payload:
                    with lat_lock:
                        mismatches[0] += 1
                    return
            except (http.client.HTTPException, OSError):
                c.close()
                local.c = None
                return
            with lat_lock:
                lat.append(time.perf_counter() - t0)

        t0 = time.perf_counter()
        with ThreadPoolExecutor(concurrency) as pool:
            list(pool.map(read_one, range(n)))
        read_dt = time.perf_counter() - t0
        lat.sort()
        out.update({
            "smallfile_read_reqs_per_s": round(len(lat) / read_dt, 1),
            "smallfile_read_avg_ms": round(
                sum(lat) / max(len(lat), 1) * 1000, 2),
            "smallfile_read_p99_ms": round(
                lat[int(len(lat) * 0.99) - 1] * 1000, 2) if lat else None,
            "smallfile_read_failed": n - len(lat),
        })
        if verify_bytes:
            out["smallfile_byte_mismatches"] = mismatches[0]
        if m_before is not None:
            out.update(_metrics_delta(
                m_before,
                _scrape_metrics(f"http://127.0.0.1:{vs_.port}/metrics")))
            out.update(_slo_verdict(master))
        return out
    finally:
        vs_.stop()
        master.stop()
        shutil.rmtree(tmp, ignore_errors=True)


def _slo_verdict(master) -> dict:
    """Canary probe p50/p99 + alerts that fired during a bench run —
    the run's own SLO verdict, folded into the smallfile JSON so a
    bench regression carries its judgment with it."""
    from seaweedfs_tpu.stats.metrics import CANARY_PROBE_SECONDS

    out: dict = {}
    canary = master.canary.status()
    out["canary_probe_ticks"] = canary["tick"]
    out["canary_byte_mismatches"] = canary["byteMismatches"]
    counts, count, _total = _hist_child_snapshot(
        CANARY_PROBE_SECONDS, "volume_rt")
    if count:
        buckets = CANARY_PROBE_SECONDS.buckets
        out["canary_probe_p50_ms"] = round(
            _hist_quantile(buckets, counts, count, 0.5) * 1e3, 3)
        out["canary_probe_p99_ms"] = round(
            _hist_quantile(buckets, counts, count, 0.99) * 1e3, 3)
    fired = [h for h in master.slo.status(evaluate_if_idle=False)["history"]
             if h["state"] == "firing"]
    out["slo_alerts_fired"] = [
        {"slo": h["slo"], "severity": h["severity"],
         "burnShort": h.get("burnShort")} for h in fired]
    out["slo_clean"] = not any(
        h["severity"] == "page" for h in fired)
    return out


def _flight_overhead(n: int = 8000, concurrency: int = 16) -> dict:
    """ISSUE 20 acceptance gate: the always-on flight-recorder planes
    (continuous profiler + hot-key sketch) must cost under
    SEAWEEDFS_TPU_BENCH_FLIGHT_MAX_PCT (default 3%) of smallfile req/s.

    Same-host A/B: one smallfile leg with both planes disabled, one
    with production defaults.  A throwaway warmup leg runs first so the
    OFF leg does not pocket the process's import/allocator warmup and
    overstate the ON leg's cost."""
    import os

    from seaweedfs_tpu.telemetry import hotkeys
    from seaweedfs_tpu.util import profiler

    def leg(on: bool, leg_n: int) -> dict:
        override = ({} if on else
                    {profiler.DISABLE_VAR: "1", hotkeys.DISABLE_VAR: "0"})
        saved = {k: os.environ.get(k)
                 for k in (profiler.DISABLE_VAR, hotkeys.DISABLE_VAR)}
        for k in saved:
            os.environ.pop(k, None)
        os.environ.update(override)
        profiler.stop_continuous()
        hotkeys.reset()
        try:
            return _smallfile_rates(n=leg_n, concurrency=concurrency)
        finally:
            for k, v in saved.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v
            profiler.stop_continuous()
            hotkeys.reset()

    leg(True, max(n // 8, 500))  # warmup, discarded
    # interleaved off/on pairs, judged by the MEDIAN per-pair ratio:
    # adjacent legs share the host's load drift, so their ratio cancels
    # it — a global off-vs-on comparison on a shared box confuses
    # minutes-scale drift (observed at 20%+) with the planes' real cost
    offs, ons = [], []
    for _ in range(3):
        offs.append(leg(False, n))
        ons.append(leg(True, n))

    def med(vals: list[float]) -> float:
        vals = sorted(vals)
        return vals[len(vals) // 2]

    out: dict = {"flight_overhead_n": n}
    worst = 0.0
    for op in ("write", "read"):
        key = f"smallfile_{op}_reqs_per_s"
        out[f"flight_off_{op}_reqs_per_s"] = med([r[key] for r in offs])
        out[f"flight_on_{op}_reqs_per_s"] = med([r[key] for r in ons])
        ratios = [on[key] / off[key]
                  for off, on in zip(offs, ons) if off[key]]
        if ratios:
            worst = max(worst, (1.0 - med(ratios)) * 100.0)
    out["flight_overhead_pct"] = round(worst, 2)
    max_pct = float(os.environ.get(
        "SEAWEEDFS_TPU_BENCH_FLIGHT_MAX_PCT", "3.0"))
    out["flight_overhead_max_pct"] = max_pct
    out["flight_overhead_ok"] = worst <= max_pct
    return out


def _hist_child_snapshot(hist, *labels):
    """(counts[], count, total) for one histogram child — bench-side
    delta arithmetic over the in-process registry."""
    child = hist.labels(*labels)
    with child._lock:
        return list(child.counts), child.count, child.total


def _hist_quantile(buckets, counts, count, q: float) -> float:
    """Linear-interpolated quantile from cumulative bucket counts (the
    usual Prometheus histogram_quantile estimate)."""
    if count <= 0:
        return 0.0
    rank = q * count
    prev_cum, prev_bound = 0, 0.0
    for bound, cum in zip(buckets, counts):
        if cum >= rank:
            if cum == prev_cum:
                return bound
            return prev_bound + (bound - prev_bound) * (
                (rank - prev_cum) / (cum - prev_cum))
        prev_cum, prev_bound = cum, bound
    return buckets[-1] if buckets else 0.0


def _serving_rates() -> dict:
    """ISSUE 18 serving-plane stage, leg by leg:

    * **fsync A/B** (`serving_fsync_write_speedup`): direct concurrent
      Volume appends with SEAWEEDFS_TPU_DURABILITY=sync (one fsync pair
      per mutation — the per-write strawman) vs =batch (one fsync pair
      per group-commit barrier).  Same threads, same payloads; the
      speedup is pure fsync batching, and the batch run's commit/write
      counter deltas report the achieved mean batch size.
    * **sendfile A/B** (`serving_sendfile_read_speedup`): whole-needle
      GETs through the volume HTTP path with SEAWEEDFS_TPU_SENDFILE
      toggled per phase (the env is read per request), needle cache off
      so every GET takes the disk path.  Every response is sha256'd
      against the written payload in BOTH phases —
      `serving_byte_identity` gates the speedup.
    * **keep-alive leg** (ISSUE 18f): parks >=2000 idle keep-alive
      sockets on the event-loop front end, then drives M active
      clients — req/s, p99, the server's own open-socket gauge,
      per-socket RSS delta (client+server share this process, so the
      delta is an upper bound on the server's share), and a post-run
      probe of every idle socket proving zero resets.
    """
    import hashlib
    import http.client
    import os
    import resource
    import shutil
    import tempfile
    import threading
    import urllib.request
    from concurrent.futures import ThreadPoolExecutor

    from seaweedfs_tpu.stats.metrics import (
        FSYNC_BATCH_COMMITS,
        FSYNC_BATCH_WRITES,
        HTTPD_OPEN_SOCKETS,
        SENDFILE_BYTES,
        SENDFILE_FALLBACK,
    )
    from seaweedfs_tpu.storage import Needle, SuperBlock
    from seaweedfs_tpu.storage.volume import Volume

    out: dict = {}

    def emit(**kv) -> None:
        print(json.dumps({"partial": True, **kv}), flush=True)

    def _with_env(key: str, val: str | None):
        """Set/unset one env var, returning an undo callable."""
        old = os.environ.get(key)
        if val is None:
            os.environ.pop(key, None)
        else:
            os.environ[key] = val

        def undo() -> None:
            if old is None:
                os.environ.pop(key, None)
            else:
                os.environ[key] = old
        return undo

    # ---- leg 1: fsync A/B (direct volume appends, no HTTP) ---------------
    n_threads = int(os.environ.get("SEAWEEDFS_TPU_BENCH_FSYNC_THREADS", "16"))
    per_thread = int(os.environ.get("SEAWEEDFS_TPU_BENCH_FSYNC_WRITES", "64"))
    payload_1k = os.urandom(1024)

    def _fsync_writes_per_s(mode: str) -> float:
        tmp = tempfile.mkdtemp(prefix=f"swfs-fsync-{mode}-")
        undo = _with_env("SEAWEEDFS_TPU_DURABILITY", mode)
        # a parked writer can't queue a second mutation, so the barrier
        # can only ever hold n_threads pendings — cap the batch there or
        # the leader burns the full max-delay waiting for writers that
        # cannot arrive
        undo_batch = _with_env("SEAWEEDFS_TPU_FSYNC_MAX_BATCH",
                               str(n_threads))
        try:
            v = Volume(tmp, "", 1, super_block=SuperBlock())
            start = threading.Barrier(n_threads)

            def writer(tid: int) -> None:
                start.wait()
                for k in range(per_thread):
                    v.append_needle(Needle(
                        cookie=0x5EAF00D,
                        id=1 + tid * per_thread + k,
                        data=payload_1k))

            threads = [threading.Thread(target=writer, args=(t,))
                       for t in range(n_threads)]
            t0 = time.perf_counter()
            for th in threads:
                th.start()
            for th in threads:
                th.join()
            dt = time.perf_counter() - t0
            v.close()
            return n_threads * per_thread / dt
        finally:
            undo()
            undo_batch()
            shutil.rmtree(tmp, ignore_errors=True)

    sync_rate = _fsync_writes_per_s("sync")
    commits0 = FSYNC_BATCH_COMMITS.labels().value
    writes0 = FSYNC_BATCH_WRITES.labels().value
    batch_rate = _fsync_writes_per_s("batch")
    commits = FSYNC_BATCH_COMMITS.labels().value - commits0
    writes = FSYNC_BATCH_WRITES.labels().value - writes0
    out.update({
        "serving_fsync_sync_writes_per_s": round(sync_rate, 1),
        "serving_fsync_batch_writes_per_s": round(batch_rate, 1),
        "serving_fsync_write_speedup": round(batch_rate / sync_rate, 2)
        if sync_rate else None,
        "serving_fsync_batch_commits": int(commits),
        "serving_fsync_mean_batch_size": round(writes / commits, 1)
        if commits else None,
        "serving_fsync_concurrency": n_threads,
    })
    emit(**{k: out[k] for k in (
        "serving_fsync_write_speedup", "serving_fsync_mean_batch_size")})

    # ---- legs 2+3 share one in-process master + volume server ------------
    from seaweedfs_tpu.master.server import MasterServer
    from seaweedfs_tpu.volume.server import VolumeServer

    reserved: set[int] = set()

    def _port() -> int:
        import socket

        while True:
            with socket.socket() as s:
                s.bind(("127.0.0.1", 0))
                p = s.getsockname()[1]
            if (p <= 55000 and p not in reserved
                    and p + 10000 not in reserved):
                reserved.update((p, p + 10000))
                return p

    tmp = tempfile.mkdtemp(prefix="swfs-serving-")
    # cache off: a needle-cache hit declines sendfile by design, so the
    # A/B must keep every GET on the disk path to measure the copy
    undo_cache = _with_env("SEAWEEDFS_TPU_NEEDLE_CACHE_MB", "0")
    master = MasterServer(ip="127.0.0.1", port=_port(),
                          volume_size_limit_mb=1024)
    master.start()
    vs_ = VolumeServer(directories=[tmp], ip="127.0.0.1", port=_port(),
                       master_addresses=[f"127.0.0.1:{master.grpc_port}"],
                       pulse_seconds=0.5, max_volume_count=16)
    vs_.start()
    local = threading.local()

    def conn() -> http.client.HTTPConnection:
        c = getattr(local, "c", None)
        if c is None:
            c = http.client.HTTPConnection("127.0.0.1", vs_.port,
                                           timeout=30)
            local.c = c
        return c

    def _post(fid: str, payload: bytes) -> int:
        body = (b"--bb\r\nContent-Disposition: form-data; "
                b'name="file"; filename="b.bin"\r\n\r\n'
                + payload + b"\r\n--bb--\r\n")
        c = conn()
        try:
            c.request("POST", f"/{fid}", body, {
                "Content-Type": "multipart/form-data; boundary=bb"})
            resp = c.getresponse()
            resp.read()
            return resp.status
        except (http.client.HTTPException, OSError):
            c.close()
            local.c = None
            return 599

    try:
        deadline = time.time() + 15
        while time.time() < deadline and len(master.topo.nodes) < 1:
            time.sleep(0.1)

        # ---- leg 2: sendfile A/B -----------------------------------------
        big_n = int(os.environ.get("SEAWEEDFS_TPU_BENCH_SENDFILE_N", "48"))
        big_bytes = int(os.environ.get(
            "SEAWEEDFS_TPU_BENCH_SENDFILE_KB", "512")) * 1024
        rounds = int(os.environ.get(
            "SEAWEEDFS_TPU_BENCH_SENDFILE_ROUNDS", "8"))
        with urllib.request.urlopen(
            f"http://127.0.0.1:{master.port}/dir/assign?count={big_n + 4096}",
            timeout=20,
        ) as r:
            first = json.loads(r.read())
        vid, _, rest = first["fid"].partition(",")
        key_hex, cookie = rest[:-8], rest[-8:]
        base_key = int(key_hex, 16)

        def fid(i: int) -> str:
            return f"{vid},{base_key + i:x}{cookie}"

        digests: dict[str, str] = {}
        for i in range(big_n):
            payload = os.urandom(big_bytes)
            digests[fid(i)] = hashlib.sha256(payload).hexdigest()
            assert _post(fid(i), payload) < 300, "sendfile-leg write failed"

        identity_ok = True

        def _read_phase(read_c: int = 8) -> float:
            nonlocal identity_ok
            lat_bytes = [0]
            lock = threading.Lock()

            def read_one(j: int) -> None:
                nonlocal identity_ok
                f = fid(j % big_n)
                c = conn()
                try:
                    c.request("GET", f"/{f}")
                    resp = c.getresponse()
                    body = resp.read()
                    if resp.status != 200:
                        identity_ok = False
                        return
                except (http.client.HTTPException, OSError):
                    c.close()
                    local.c = None
                    identity_ok = False
                    return
                if hashlib.sha256(body).hexdigest() != digests[f]:
                    identity_ok = False
                with lock:
                    lat_bytes[0] += len(body)

            t0 = time.perf_counter()
            with ThreadPoolExecutor(read_c) as pool:
                list(pool.map(read_one, range(big_n * rounds)))
            dt = time.perf_counter() - t0
            return lat_bytes[0] / dt / 1e9

        undo_sf = _with_env("SEAWEEDFS_TPU_SENDFILE", "0")
        _read_phase()  # warm the page cache so both phases read warm
        off_gbps = _read_phase()
        undo_sf()
        undo_sf = _with_env("SEAWEEDFS_TPU_SENDFILE", "1")
        sf0 = SENDFILE_BYTES.labels().value
        on_gbps = _read_phase()
        sf_bytes = SENDFILE_BYTES.labels().value - sf0
        undo_sf()
        out.update({
            "serving_sendfile_off_GBps": round(off_gbps, 3),
            "serving_sendfile_on_GBps": round(on_gbps, 3),
            "serving_sendfile_read_speedup": round(on_gbps / off_gbps, 2)
            if off_gbps else None,
            "serving_sendfile_bytes": int(sf_bytes),
            "serving_byte_identity": identity_ok,
            "serving_sendfile_payload_kb": big_bytes // 1024,
        })
        emit(serving_sendfile_read_speedup=out[
            "serving_sendfile_read_speedup"],
            serving_byte_identity=identity_ok)

        # ---- leg 3: thousands-of-sockets keep-alive ----------------------
        idle_target = int(os.environ.get(
            "SEAWEEDFS_TPU_BENCH_IDLE_SOCKETS", "2000"))
        active_c = int(os.environ.get(
            "SEAWEEDFS_TPU_BENCH_ACTIVE_CLIENTS", "16"))
        active_n = int(os.environ.get(
            "SEAWEEDFS_TPU_BENCH_ACTIVE_REQS", "4000"))
        soft, hard = resource.getrlimit(resource.RLIMIT_NOFILE)
        need = idle_target * 2 + 1024
        if soft < need:
            lifted = min(need, hard)
            resource.setrlimit(resource.RLIMIT_NOFILE, (lifted, hard))
            if lifted < need:  # hard cap too low: shrink, don't fail
                idle_target = max(64, (lifted - 1024) // 2)

        # a small-file population for the active clients (1KB GETs)
        small_n = 256
        for i in range(small_n):
            p = os.urandom(1024)
            digests[fid(big_n + i)] = hashlib.sha256(p).hexdigest()
            assert _post(fid(big_n + i), p) < 300

        def _rss_kb() -> int:
            with open("/proc/self/status") as f:
                for line in f:
                    if line.startswith("VmRSS:"):
                        return int(line.split()[1])
            return 0

        rss_before = _rss_kb()
        idles: list[http.client.HTTPConnection] = []
        idles_lock = threading.Lock()

        def _park_one(_i: int) -> None:
            c = http.client.HTTPConnection("127.0.0.1", vs_.port,
                                           timeout=30)
            c.request("GET", f"/{fid(big_n)}")
            c.getresponse().read()  # keep-alive: socket parks on the loop
            with idles_lock:
                idles.append(c)

        with ThreadPoolExecutor(64) as pool:
            list(pool.map(_park_one, range(idle_target)))
        time.sleep(0.5)  # let the loop account every parked socket
        gauge_sockets = HTTPD_OPEN_SOCKETS.labels("volume").value
        rss_after_park = _rss_kb()

        lat: list[float] = []
        lat_lock = threading.Lock()
        failures = [0]

        def _active_one(j: int) -> None:
            f = fid(big_n + (j * 2654435761) % small_n)
            t0 = time.perf_counter()
            c = conn()
            try:
                c.request("GET", f"/{f}")
                resp = c.getresponse()
                resp.read()
                if resp.status != 200:
                    with lat_lock:
                        failures[0] += 1
                    return
            except (http.client.HTTPException, OSError):
                c.close()
                local.c = None
                with lat_lock:
                    failures[0] += 1
                return
            with lat_lock:
                lat.append(time.perf_counter() - t0)

        t0 = time.perf_counter()
        with ThreadPoolExecutor(active_c) as pool:
            list(pool.map(_active_one, range(active_n)))
        active_dt = time.perf_counter() - t0
        lat.sort()

        # every idle socket must still be usable: one GET each, any
        # reset/close counts against the zero-resets gate
        resets = [0]

        def _probe_idle(c: http.client.HTTPConnection) -> None:
            try:
                c.request("GET", f"/{fid(big_n)}")
                resp = c.getresponse()
                resp.read()
                if resp.status != 200:
                    raise OSError("bad status")
            except (http.client.HTTPException, OSError):
                with idles_lock:
                    resets[0] += 1

        with ThreadPoolExecutor(64) as pool:
            list(pool.map(_probe_idle, idles))
        for c in idles:
            c.close()

        out.update({
            "keepalive_idle_sockets": len(idles),
            "keepalive_open_sockets_gauge": int(gauge_sockets),
            "keepalive_active_reqs_per_s": round(len(lat) / active_dt, 1)
            if active_dt else None,
            "keepalive_active_p99_ms": round(
                lat[int(len(lat) * 0.99) - 1] * 1000, 2) if lat else None,
            "keepalive_active_failed": failures[0],
            "keepalive_resets": resets[0],
            "keepalive_rss_per_socket_kb": round(
                max(0, rss_after_park - rss_before) / max(len(idles), 1), 2),
            "keepalive_active_clients": active_c,
        })
        return out
    finally:
        undo_cache()
        vs_.stop()
        master.stop()
        shutil.rmtree(tmp, ignore_errors=True)


def _serving_smoke(concurrency: int = 64, n: int = 1500) -> dict:
    """ISSUE 18e CI smoke: the smallfile path at c>=64 keep-alive with
    the event-loop front end OFF then ON — every byte read back must
    match what was written and not one response may be a 5xx (or fail
    outright).  Bounded: two in-process clusters, ~2*n tiny requests
    each."""
    import os

    out: dict = {"serving_smoke_concurrency": concurrency}
    ok = True
    for mode in ("off", "volume"):
        old = os.environ.get("SEAWEEDFS_TPU_EVENTLOOP")
        os.environ["SEAWEEDFS_TPU_EVENTLOOP"] = mode
        try:
            res = _smallfile_rates(n=n, concurrency=concurrency,
                                   verify_bytes=True)
        finally:
            if old is None:
                os.environ.pop("SEAWEEDFS_TPU_EVENTLOOP", None)
            else:
                os.environ["SEAWEEDFS_TPU_EVENTLOOP"] = old
        tag = "eventloop_on" if mode == "volume" else "eventloop_off"
        # _smallfile_rates counts any >=300 status or socket error as
        # failed, so failed==0 across both phases IS the zero-5xx gate;
        # verify_bytes makes every read compare against the written
        # payload, so mismatches==0 is the byte-identity gate
        failed = res["smallfile_failed"] + res["smallfile_read_failed"]
        out[f"{tag}_write_reqs_per_s"] = res["smallfile_write_reqs_per_s"]
        out[f"{tag}_read_reqs_per_s"] = res["smallfile_read_reqs_per_s"]
        out[f"{tag}_failed"] = failed
        out[f"{tag}_byte_mismatches"] = res["smallfile_byte_mismatches"]
        ok = ok and failed == 0 and res["smallfile_byte_mismatches"] == 0
    out["serving_smoke_ok"] = ok
    return out


def _service_rates() -> dict:
    """ISSUE 6 service stage: N volumes' concurrent encode+rebuild GF
    jobs through the shared codec service vs per-volume direct dispatch.

    Two profiles, both on the host codec (the device path is verified
    byte-identical on the virtual mesh in tests/test_codec_service.py):

    * **interval** (the headline `service_speedup`): needle-interval-
      sized jobs (SEAWEEDFS_TPU_BENCH_SERVICE_KB, default 2KB — the
      reference's canonical 1KB-file benchmark decodes ~1.1KB intervals)
      mixing encode parity with rebuild decode-plan applies.  This is
      the regime the service exists for: per-job dispatch overhead
      dominates the GF kernel, and the scheduler's coalescing turns N
      producers' per-call Python into one kernel call per batch.
    * **bulk**: 1MB pipeline slices with reused output buffers — shows
      bulk encode loses nothing by routing through the service
      (`service_bulk_ratio`, expect ~0.9-1.0: kernel-bound either way).

    Occupancy and p50/p99 job latency come from the
    seaweedfs_ec_service_* registry deltas, so the numbers folded into
    the JSON are exactly what /metrics would report.
    """
    import os
    from concurrent.futures import ThreadPoolExecutor

    from seaweedfs_tpu.ops import gf256
    from seaweedfs_tpu.ops.codec_service import CodecService
    from seaweedfs_tpu.ops.rs_cpu import ReedSolomon
    from seaweedfs_tpu.stats.metrics import (
        EC_SERVICE_BATCH_JOBS,
        EC_SERVICE_JOB_SECONDS,
    )

    n_vol = int(os.environ.get("SEAWEEDFS_TPU_BENCH_SERVICE_VOLUMES", "8"))
    kb = int(os.environ.get("SEAWEEDFS_TPU_BENCH_SERVICE_KB", "2"))
    n_jobs = int(os.environ.get("SEAWEEDFS_TPU_BENCH_SERVICE_JOBS", "6000"))
    group = 16
    width = kb << 10
    rng = np.random.default_rng(7)
    rs = ReedSolomon()
    blocks = [rng.integers(0, 256, (10, width), dtype=np.uint8)
              for _ in range(n_vol)]
    # rebuild decode plan for the worst-case loss (first 4 data shards)
    plan = gf256.decode_plan_for(
        rs.matrix, 10, list(range(4, 14)), (0, 1, 2, 3))

    result: dict = {"service_volumes": n_vol, "service_job_kb": kb,
                    "service_jobs_per_volume": n_jobs,
                    "service_mode": "host"}

    def emit(**kv) -> None:
        result.update(kv)
        print(json.dumps({"partial": True, **result}), flush=True)

    def baseline_worker(v: int) -> None:
        codec = ReedSolomon()  # per-volume dispatch: own codec, own calls
        if v % 2 == 0:
            for _ in range(n_jobs):
                codec.parity_of(blocks[v])
        else:
            for _ in range(n_jobs):
                codec.apply_rows(plan, list(blocks[v]))

    def service_worker(svc: CodecService, v: int) -> None:
        pend: list = []
        done = 0
        while done < n_jobs:
            g = min(group, n_jobs - done)
            if v % 2 == 0:
                pend.extend(svc.submit_parity_many([blocks[v]] * g))
            else:
                pend.extend(svc.submit_apply_many(plan, [blocks[v]] * g))
            done += g
            while len(pend) > 2 * group:
                pend.pop(0).result()
        for f in pend:
            f.result()

    total_bytes = n_vol * n_jobs * 10 * width
    rs.parity_of(blocks[0])  # warm the native lib before any timing

    # byte identity through the service before any rates are quoted
    svc = CodecService(mode="host")
    got = np.stack([np.asarray(r) for r in
                    svc.submit_parity(blocks[0]).result(30)])
    if not np.array_equal(got, rs.parity_of(blocks[0])):
        svc.close()
        return {"error": "service parity not byte-identical to cpu_simd"}
    got = np.stack([np.asarray(r) for r in
                    svc.submit_apply(plan, blocks[1]).result(30)])
    if not np.array_equal(got, np.stack(rs.apply_rows(plan, list(blocks[1])))):
        svc.close()
        return {"error": "service decode not byte-identical to cpu_simd"}
    result["service_byte_identical"] = True

    # best-of-2 (same reasoning as every other stage on this noisy host)
    base_dt = svc_dt = None
    occ_before = _hist_child_snapshot(EC_SERVICE_BATCH_JOBS)
    lat_before = {k: _hist_child_snapshot(EC_SERVICE_JOB_SECONDS, k)
                  for k in ("parity", "apply")}
    for trial in range(2):
        t0 = time.perf_counter()
        with ThreadPoolExecutor(n_vol) as pool:
            list(pool.map(baseline_worker, range(n_vol)))
        dt = time.perf_counter() - t0
        base_dt = dt if base_dt is None else min(base_dt, dt)
        t0 = time.perf_counter()
        with ThreadPoolExecutor(n_vol) as pool:
            list(pool.map(lambda v: service_worker(svc, v), range(n_vol)))
        dt = time.perf_counter() - t0
        svc_dt = dt if svc_dt is None else min(svc_dt, dt)
        emit(per_volume_GBps=round(total_bytes / base_dt / 1e9, 3),
             service_GBps=round(total_bytes / svc_dt / 1e9, 3),
             service_speedup=round(base_dt / svc_dt, 3),
             service_trials=trial + 1)
    occ_after = _hist_child_snapshot(EC_SERVICE_BATCH_JOBS)
    jobs_delta = occ_after[1] - occ_before[1]
    if jobs_delta > 0:
        result["service_batch_occupancy_mean"] = round(
            (occ_after[2] - occ_before[2]) / jobs_delta, 2)
    # p50/p99 job latency over the service runs, from the histogram delta
    lat_counts = None
    for k in ("parity", "apply"):
        before, after = lat_before[k], _hist_child_snapshot(
            EC_SERVICE_JOB_SECONDS, k)
        d = [a - b for a, b in zip(after[0], before[0])]
        if lat_counts is None:
            lat_counts, lat_n = d, after[1] - before[1]
        else:
            lat_counts = [x + y for x, y in zip(lat_counts, d)]
            lat_n += after[1] - before[1]
    if lat_counts and lat_n:
        # _HistogramChild.counts are already cumulative (observe bumps
        # every bucket whose bound >= v), and deltas of cumulative
        # counts stay cumulative — no further cumsum
        buckets = EC_SERVICE_JOB_SECONDS.buckets
        result["service_job_p50_ms"] = round(
            _hist_quantile(buckets, lat_counts, lat_n, 0.50) * 1000, 3)
        result["service_job_p99_ms"] = round(
            _hist_quantile(buckets, lat_counts, lat_n, 0.99) * 1000, 3)
    svc.close()

    # bulk profile: 1MB pipeline slices, reused outputs on both sides
    bulk_w = 1 << 20
    bulk_jobs = int(os.environ.get("SEAWEEDFS_TPU_BENCH_SERVICE_BULK_JOBS",
                                   "30"))
    bulk_blocks = [rng.integers(0, 256, (10, bulk_w), dtype=np.uint8)
                   for _ in range(n_vol)]

    def bulk_base(v: int) -> None:
        codec = ReedSolomon()
        outs = [np.empty((4, bulk_w), np.uint8) for _ in range(4)]
        for k in range(bulk_jobs):
            codec.parity_into(list(bulk_blocks[v]), list(outs[k % 4]))

    def bulk_service(svc2: CodecService, v: int) -> None:
        outs = [np.empty((4, bulk_w), np.uint8) for _ in range(4)]
        pend: list = []
        for k in range(bulk_jobs):
            pend.append(svc2.submit_parity(bulk_blocks[v], out=outs[k % 4]))
            if len(pend) > 2:
                pend.pop(0).result()
        for f in pend:
            f.result()

    svc2 = CodecService(mode="host")
    bulk_bytes = n_vol * bulk_jobs * 10 * bulk_w
    bb = bs = None  # best-of-2: same noisy-host reasoning as every stage
    for _ in range(2):
        t0 = time.perf_counter()
        with ThreadPoolExecutor(n_vol) as pool:
            list(pool.map(bulk_base, range(n_vol)))
        bb = min(bb or 1e9, time.perf_counter() - t0)
        t0 = time.perf_counter()
        with ThreadPoolExecutor(n_vol) as pool:
            list(pool.map(lambda v: bulk_service(svc2, v), range(n_vol)))
        bs = min(bs or 1e9, time.perf_counter() - t0)
    svc2.close()
    result.update(
        per_volume_bulk_GBps=round(bulk_bytes / bb / 1e9, 3),
        service_bulk_GBps=round(bulk_bytes / bs / 1e9, 3),
        service_bulk_ratio=round(bb / bs, 3),
    )
    return result


def _soak_rates() -> dict:
    """ISSUE 9 / ROADMAP 5d soak smoke: production-shaped mixed traffic.

    Bounded (~SEAWEEDFS_TPU_SOAK_SECONDS, default 30s of load + setup):
    an in-process master + 2 volume servers run concurrent reads AND
    writes while the lifecycle controller executes one forced
    seal -> EC-encode transition on a filled volume and a vacuum on a
    garbage-heavy sibling.  Asserts:

      * every read during every stage returns the exact original bytes
        (byte-identity through seal, encode, volume delete, EC serving);
      * zero client-visible 5xx;
      * read p99 from the registry request histogram stays under the
        SLO (SEAWEEDFS_TPU_SOAK_P99_S, default 2.0s — generous for
        noisy 1-vCPU CI hosts; the point is catching order-of-magnitude
        regressions under mixed load, not microbenchmarking).

    Emits soak_ok plus the measured numbers; the CI step gates on
    soak_ok so every future PR is judged under production-shaped
    traffic, not single-op microbenches.
    """
    import os
    import shutil
    import socket
    import tempfile
    import threading
    import urllib.error
    import urllib.request
    from concurrent.futures import ThreadPoolExecutor

    from seaweedfs_tpu.master.server import MasterServer
    from seaweedfs_tpu.stats.metrics import REQUEST_HISTOGRAM
    from seaweedfs_tpu.volume.server import VolumeServer

    soak_s = float(os.environ.get("SEAWEEDFS_TPU_SOAK_SECONDS", "30"))
    slo_p99_s = float(os.environ.get("SEAWEEDFS_TPU_SOAK_P99_S", "2.0"))
    reserved: set[int] = set()

    def _port() -> int:
        while True:
            with socket.socket() as s:
                s.bind(("127.0.0.1", 0))
                p = s.getsockname()[1]
            if (p <= 55000 and p not in reserved
                    and p + 10000 not in reserved):
                reserved.update((p, p + 10000))
                return p

    tmp = tempfile.mkdtemp(prefix="swfs-soak-")
    journal_dir = tempfile.mkdtemp(prefix="swfs-soak-journal-")
    master = MasterServer(
        ip="127.0.0.1", port=_port(), volume_size_limit_mb=4,
        lifecycle_dir=journal_dir,
        lifecycle_policy={"*": {
            # force the pipeline inside the bounded window: seal at 10%
            # fullness, encode as soon as sealed+quiet 1s, vacuum at 25%
            "seal_full_percent": 10.0, "ec_cooldown_seconds": 1.0,
            "vacuum_garbage_ratio": 0.25,
        }})
    master.start()
    vols = []
    for i in range(2):
        d = os.path.join(tmp, f"v{i}")
        os.makedirs(d)
        v = VolumeServer(directories=[d], ip="127.0.0.1", port=_port(),
                         master_addresses=[f"127.0.0.1:{master.grpc_port}"],
                         pulse_seconds=0.5, max_volume_count=16)
        v.start()
        vols.append(v)
    errors: list[str] = []
    try:
        deadline = time.time() + 15
        while time.time() < deadline and len(master.topo.nodes) < 2:
            time.sleep(0.1)

        def put(fid: str, url: str, payload: bytes) -> bool:
            body = (b"--bb\r\nContent-Disposition: form-data; "
                    b'name="file"; filename="s.bin"\r\n\r\n'
                    + payload + b"\r\n--bb--\r\n")
            req = urllib.request.Request(
                f"http://{url}/{fid}", data=body, method="POST",
                headers={"Content-Type":
                         "multipart/form-data; boundary=bb"})
            with urllib.request.urlopen(req, timeout=20) as r:
                return r.status < 300

        def assign() -> tuple[str, str]:
            with urllib.request.urlopen(
                f"http://127.0.0.1:{master.port}/dir/assign", timeout=20
            ) as r:
                a = json.loads(r.read())
            return a["fid"], a["url"]

        def derived_fids(base_fid: str, n: int) -> list[str]:
            # consecutive keys on the SAME volume (the smallfile-bench
            # trick): lets the seeding fill one specific volume instead
            # of scattering across the whole writable set
            vid_s, _, rest = base_fid.partition(",")
            base_key = int(rest[:-8], 16)
            cookie = rest[-8:]
            return [f"{vid_s},{base_key + i:x}{cookie}" for i in range(n)]

        # seed the lifecycle target: fill one volume past the seal
        # threshold (4MB limit * 10% = ~420KB) with known payloads
        rng = np.random.default_rng(7)
        known: dict[tuple[str, str], bytes] = {}
        first_fid, first_url = assign()
        target_vid = int(first_fid.split(",")[0])
        for fid in derived_fids(first_fid, 10):
            payload = rng.integers(0, 256, 64 << 10).astype(
                np.uint8).tobytes()
            if put(fid, first_url, payload):
                known[(fid, first_url)] = payload
        # garbage-heavy sibling for the vacuum leg: write then delete
        # most of a second volume's needles
        g_base = None
        for _ in range(20):
            fid, url = assign()
            if int(fid.split(",")[0]) != target_vid:
                g_base = (fid, url)
                break
        if g_base is not None:
            g_fids = derived_fids(g_base[0], 10)
            for fid in g_fids:
                put(fid, g_base[1], os.urandom(32 << 10))
            for fid in g_fids[:-2]:
                req = urllib.request.Request(
                    f"http://{g_base[1]}/{fid}", method="DELETE")
                with urllib.request.urlopen(req, timeout=20):
                    pass

        stop = threading.Event()
        counts = {"reads": 0, "writes": 0}
        lock = threading.Lock()
        items = list(known.items())

        def reader(i: int) -> None:
            while not stop.is_set():
                (fid, url), want = items[counts["reads"] % len(items)]
                try:
                    with urllib.request.urlopen(
                            f"http://{url}/{fid}", timeout=20) as r:
                        got = r.read()
                        if r.status >= 500:
                            errors.append(f"read {fid}: {r.status}")
                        elif got != want:
                            errors.append(f"read {fid}: wrong bytes "
                                          f"({len(got)} vs {len(want)})")
                except urllib.error.HTTPError as e:
                    if e.code >= 500:
                        errors.append(f"read {fid}: {e.code}")
                except OSError as e:
                    errors.append(f"read {fid}: {e}")
                with lock:
                    counts["reads"] += 1

        def writer() -> None:
            # write failures do NOT gate the soak: the assign->write
            # window races the seal (a just-sealed volume bounces a
            # write until the next heartbeat updates the writable set),
            # and the production client re-assigns on that — modeled
            # here by simply retrying with a fresh assign
            while not stop.is_set():
                try:
                    fid, url = assign()
                    if put(fid, url, os.urandom(8 << 10)):
                        with lock:
                            counts["writes"] += 1
                except (urllib.error.HTTPError, OSError):
                    pass
                time.sleep(0.02)

        c0, n0, _t0 = _hist_child_snapshot(
            REQUEST_HISTOGRAM, "volumeServer", "get")
        pool = ThreadPoolExecutor(5)
        futs = [pool.submit(reader, i) for i in range(4)]
        futs.append(pool.submit(writer))
        t_start = time.perf_counter()
        # the forced lifecycle transition runs CONCURRENTLY with the
        # load: cycles until seal + ec_encode + vacuum all land
        transitions_done: dict = {}
        cycle_deadline = time.time() + max(soak_s - 2, 5)
        while time.time() < cycle_deadline:
            master.lifecycle.run_once()
            states = master.lifecycle.journal.counts()
            transitions_done = {
                j["key"]: j["state"]
                for j in master.lifecycle.journal.jobs(("done",))}
            if (f"{target_vid}:ec_encode" in transitions_done
                    and any(k.endswith(":vacuum")
                            for k in transitions_done)):
                break
            time.sleep(1.0)
        remaining = soak_s - (time.perf_counter() - t_start)
        if remaining > 0:
            time.sleep(min(remaining, soak_s))
        stop.set()
        pool.shutdown(wait=True)
        elapsed = time.perf_counter() - t_start
        c1, n1, _t1 = _hist_child_snapshot(
            REQUEST_HISTOGRAM, "volumeServer", "get")
        delta_counts = [b - a for a, b in zip(c0, c1)]
        p99 = _hist_quantile(
            list(REQUEST_HISTOGRAM.buckets), delta_counts, n1 - n0, 0.99)
        sealed = f"{target_vid}:seal" in transitions_done
        encoded = f"{target_vid}:ec_encode" in transitions_done
        vacuumed = any(k.endswith(":vacuum") for k in transitions_done)
        ok = (not errors and sealed and encoded and vacuumed
              and p99 <= slo_p99_s and counts["reads"] > 0)
        return {
            "soak_ok": bool(ok),
            "soak_seconds": round(elapsed, 1),
            "soak_reads": counts["reads"],
            "soak_writes": counts["writes"],
            "soak_read_p99_s": round(p99, 4),
            "soak_p99_slo_s": slo_p99_s,
            "soak_transitions": sorted(transitions_done),
            "soak_error_count": len(errors),
            "soak_errors": errors[:10],
            "soak_journal_states": master.lifecycle.journal.counts(),
        }
    finally:
        for v in vols:
            v.stop()
        master.stop()
        shutil.rmtree(tmp, ignore_errors=True)
        shutil.rmtree(journal_dir, ignore_errors=True)


def _cpu_rate(shard_bytes: int = 16 << 20, iters: int = 5) -> float:
    """Best single-pass rate: this shared vCPU sees multi-second steal
    spikes (observed swinging a mean-of-3 between 3.7 and 5.9 GB/s), so
    the min-latency pass is the codec's actual capability."""
    from seaweedfs_tpu.ops.rs_cpu import ReedSolomon

    rs = ReedSolomon()
    rng = np.random.default_rng(1)
    data = rng.integers(0, 256, (10, shard_bytes), dtype=np.uint8)
    rs.parity_of(data)  # warm
    best = float("inf")
    for _ in range(iters):
        start = time.perf_counter()
        rs.parity_of(data)
        best = min(best, time.perf_counter() - start)
    return (10 * shard_bytes) / best / 1e9


def _geo_rates() -> dict:
    """ISSUE 12: steady-state geo replication lag + throttled link
    throughput, two LIVE in-process clusters (master + volume + filer
    each) cross-linked active-active.

    Two phases:
      * steady state — paced small writes on A, per-object replication
        lag measured as time-to-visible on B (p50/p99 seconds behind);
      * burst — a batch of larger objects written at once, the link's
        measured MB/s compared against its token-bucket budget
        (SEAWEEDFS_TPU_BENCH_GEO_RATE_MBPS) while concurrent foreground
        reads on A must hold the soak read-p99 SLO.
    Byte-identity over the full key set gates the whole leg.
    """
    import os
    import shutil
    import socket
    import tempfile
    import threading
    import urllib.request

    from seaweedfs_tpu.filer.server import FilerServer
    from seaweedfs_tpu.master.server import MasterServer
    from seaweedfs_tpu.volume.server import VolumeServer

    rate_mbps = float(os.environ.get(
        "SEAWEEDFS_TPU_BENCH_GEO_RATE_MBPS", "2"))
    n_steady = int(os.environ.get("SEAWEEDFS_TPU_BENCH_GEO_OBJECTS", "80"))
    # burst sized to several times the bucket's 1s burst capacity, so
    # the measured link rate reflects the THROTTLE, not the free burst
    n_burst = int(os.environ.get("SEAWEEDFS_TPU_BENCH_GEO_BURST", "40"))
    burst_kb = int(os.environ.get("SEAWEEDFS_TPU_BENCH_GEO_BURST_KB",
                                  "128"))
    slo_p99_s = float(os.environ.get("SEAWEEDFS_TPU_SOAK_P99_S", "2.0"))

    reserved: set[int] = set()

    def _port() -> int:
        while True:
            with socket.socket() as s:
                s.bind(("127.0.0.1", 0))
                p = s.getsockname()[1]
            if (p <= 55000 and p not in reserved
                    and p + 10000 not in reserved):
                reserved.update((p, p + 10000))
                return p

    tmp = tempfile.mkdtemp(prefix="swfs-geo-")

    def _cluster(tag: str, cid: int):
        root = os.path.join(tmp, tag)
        os.makedirs(os.path.join(root, "vol"), exist_ok=True)
        m = MasterServer(ip="127.0.0.1", port=_port(),
                         volume_size_limit_mb=256)
        m.start()
        v = VolumeServer(directories=[os.path.join(root, "vol")],
                         ip="127.0.0.1", port=_port(),
                         master_addresses=[f"127.0.0.1:{m.grpc_port}"],
                         pulse_seconds=0.5, max_volume_count=16)
        v.start()
        f = FilerServer(masters=[f"127.0.0.1:{m.grpc_port}"],
                        ip="127.0.0.1", port=_port(), store="sqlite",
                        store_path=os.path.join(root, "filer.db"),
                        cluster_id=cid, geo_rate_mbps=rate_mbps)
        f.start()
        deadline = time.time() + 15
        while time.time() < deadline and len(m.topo.nodes) < 1:
            time.sleep(0.1)
        return m, v, f

    ma, va, fa = _cluster("a", 1)
    mb, vb, fb = _cluster("b", 2)
    from seaweedfs_tpu.replication.geo import GeoReplicator

    # cross-link AFTER both are up (same wiring -geoPeers does)
    ra = GeoReplicator(fa, f"127.0.0.1:{fb.port}",
                       journal_dir=os.path.join(tmp, "a", "geo"),
                       rate_mbps=rate_mbps)
    rb = GeoReplicator(fb, f"127.0.0.1:{fa.port}",
                       journal_dir=os.path.join(tmp, "b", "geo"),
                       rate_mbps=rate_mbps)
    fa.geo_replicators.append(ra)
    fb.geo_replicators.append(rb)
    ra.start()
    rb.start()

    def _put(f, path, data):
        req = urllib.request.Request(
            f"http://127.0.0.1:{f.port}{path}", data=data, method="PUT")
        with urllib.request.urlopen(req, timeout=30) as r:
            r.read()

    def _get(f, path):
        with urllib.request.urlopen(
                f"http://127.0.0.1:{f.port}{path}", timeout=30) as r:
            return r.read()

    def _visible(f, path, want, timeout_s=60.0) -> float:
        t0 = time.perf_counter()
        while time.perf_counter() - t0 < timeout_s:
            try:
                if _get(f, path) == want:
                    return time.perf_counter() - t0
            except Exception:
                pass
            time.sleep(0.004)
        raise TimeoutError(path)

    from seaweedfs_tpu.stats.metrics import REGISTRY

    def _geo_bytes() -> float:
        fam = REGISTRY.family("seaweedfs_geo_bytes_total")
        if fam is None:
            return 0.0
        return sum(float(line.rsplit(" ", 1)[1])
                   for line in fam.render() if not line.startswith("#"))

    objects: dict[str, bytes] = {}
    try:
        # -- steady state: per-object replication lag ----------------------
        lags = []
        for i in range(n_steady):
            key = f"/buckets/geo/s-{i}.bin"
            blob = os.urandom(2048)
            _put(fa, key, blob)
            lags.append(_visible(fb, key, blob))
            objects[key] = blob
        lags.sort()
        lag_p50 = lags[len(lags) // 2]
        lag_p99 = lags[min(len(lags) - 1, int(len(lags) * 0.99))]

        # -- burst under the token bucket + foreground reads ---------------
        bytes_before = _geo_bytes()
        read_lat: list[float] = []
        stop_reads = threading.Event()

        def _reader():
            keys = list(objects)
            i = 0
            while not stop_reads.is_set():
                t0 = time.perf_counter()
                try:
                    _get(fa, keys[i % len(keys)])
                    read_lat.append(time.perf_counter() - t0)
                except Exception:
                    read_lat.append(float("inf"))
                i += 1
                time.sleep(0.01)

        rt = threading.Thread(target=_reader, daemon=True)
        rt.start()
        t0 = time.perf_counter()
        burst: list[tuple[str, bytes]] = []
        for i in range(n_burst):
            key = f"/buckets/geo/burst-{i}.bin"
            blob = os.urandom(burst_kb << 10)
            _put(fa, key, blob)
            objects[key] = blob
            burst.append((key, blob))
        for key, blob in burst:
            _visible(fb, key, blob, timeout_s=300.0)
        burst_s = time.perf_counter() - t0
        stop_reads.set()
        rt.join(timeout=5)
        link_bytes = _geo_bytes() - bytes_before
        link_mbps = link_bytes / burst_s / (1 << 20)
        read_lat.sort()
        read_p99 = (read_lat[int(len(read_lat) * 0.99)]
                    if read_lat else 0.0)

        # -- full-scan byte identity ---------------------------------------
        identical = all(_get(fb, k) == v for k, v in objects.items())
        # the A->B link must not beat ~2x its budget (the 1s bucket
        # burst capacity makes 2x the honest bound, same as scrub); the
        # B->A link ships nothing here (it only sees origin-1-signed
        # applies, which it skips), so the shared-registry sum is A->B
        bounded = link_mbps <= 2.0 * rate_mbps
        return {
            "geo_objects": len(objects),
            "geo_lag_p50_s": round(lag_p50, 4),
            "geo_lag_p99_s": round(lag_p99, 4),
            "geo_burst_MB": round(link_bytes / (1 << 20), 3),
            "geo_burst_seconds": round(burst_s, 2),
            "geo_link_MBps": round(link_mbps, 3),
            "geo_rate_MBps": rate_mbps,
            "geo_bounded": bool(bounded),
            "geo_read_p99_s": round(read_p99, 4),
            "geo_read_p99_ok": bool(read_p99 <= slo_p99_s),
            "geo_byte_identical": bool(identical),
            "geo_ok": bool(identical and bounded
                           and read_p99 <= slo_p99_s),
        }
    finally:
        for srv in (ra, rb):
            srv.stop()
        for srv in (fa, fb, va, vb, ma, mb):
            try:
                srv.stop()
            except Exception:
                pass
        shutil.rmtree(tmp, ignore_errors=True)


def _stage_in_subprocess(
    flag: str, timeout_s: float, attempts: int = 3, backoff_s: float = 15.0,
    env_per_attempt: list[dict] | None = None,
) -> dict:
    """Run one TPU-touching bench stage in a worker process, retried.

    The tunnel transport has been observed to (a) refuse backend init
    transiently ("Unable to initialize backend 'axon'") and (b) wedge on
    large transfers.  A thread can't be killed, a subprocess can — and a
    refused init one minute is often fine the next.  The headline metric
    must never hang or rc!=0 the driver's bench run, so every TPU stage
    lives behind this bounded retry loop.  `env_per_attempt[i]` overlays
    the environment of attempt i (e.g. halving the kernel buffer after a
    timeout instead of re-running the identical shape).
    """
    import os
    import subprocess
    import sys

    def _scan_lines(
        stdout: str | bytes | None,
    ) -> tuple[dict | None, dict | None]:
        """-> (latest rate-bearing JSON line, latest parseable JSON line).
        Partial lines count — that is the whole salvage contract.  The
        final line decides success (a stage that catches an exception
        prints {"error":...} LAST, with rc 0 — earlier measured partials
        must not mask that)."""
        if not stdout:
            return None, None
        if isinstance(stdout, bytes):
            stdout = stdout.decode("utf-8", errors="replace")
        best = final = None
        for line in reversed(stdout.strip().splitlines()):
            try:
                parsed = json.loads(line)
            except (json.JSONDecodeError, ValueError):
                continue
            if not isinstance(parsed, dict):
                continue
            if final is None:
                final = parsed
            if "error" not in parsed and any(
                k in parsed for k in ("rate", "e2e_rate", "devices")
            ):
                best = parsed
                break
        return best, final

    def _has_rate(parsed: dict | None) -> bool:
        return bool(parsed) and "error" not in parsed and any(
            k in parsed for k in ("rate", "e2e_rate", "devices"))

    last = "no attempt ran"
    crash_salvage: dict | None = None  # best partial from a crashed attempt
    for attempt in range(attempts):
        if attempt:
            time.sleep(backoff_s)
        env = dict(os.environ)
        if env_per_attempt and attempt < len(env_per_attempt):
            env.update(env_per_attempt[attempt])
        try:
            proc = subprocess.run(
                [sys.executable, os.path.abspath(__file__), flag],
                capture_output=True,
                text=True,
                timeout=timeout_s,
                env=env,
            )
        except subprocess.TimeoutExpired as exc:
            # the stage wedged (axon tunnel) — salvage whatever partial
            # measurements it printed before we killed it; killing a
            # transfer mid-flight can wedge the tunnel for the rest of the
            # session, so a salvaged partial beats a blind retry
            best, _ = _scan_lines(exc.stdout)
            if _has_rate(best):
                best["timeout_salvaged"] = True
                return best
            last = f"{flag} timed out after {timeout_s:.0f}s"
            continue
        best, final = _scan_lines(proc.stdout)
        if (proc.returncode == 0 and final is not None
                and "error" not in final):
            return best if best is not None else final
        # crashed or error'd attempt: keep the best rate-bearing partial as
        # a last resort, but DO retry — unlike a timeout kill, a dead
        # subprocess can't wedge the tunnel, and the retry overlays
        # (smaller buffers) exist for exactly this case
        if _has_rate(best):
            if crash_salvage is None or best.get(
                    "rate", best.get("e2e_rate", 0)) >= crash_salvage.get(
                    "rate", crash_salvage.get("e2e_rate", 0)):
                crash_salvage = best
        if final is not None and "error" in final:
            last = final["error"]
        else:
            last = f"{flag} rc={proc.returncode}: {proc.stderr[-300:]}"
    if crash_salvage is not None:
        crash_salvage["crash_salvaged"] = True
        crash_salvage["crash_error"] = last[:300]
        return crash_salvage
    return {"error": last}


def main() -> None:
    import sys

    if "--e2e-only" in sys.argv:
        try:
            print(json.dumps(_e2e_rates()))
        except Exception as exc:  # noqa: BLE001 — must emit parseable JSON
            print(json.dumps({"error": f"{type(exc).__name__}: {exc}"[:500]}))
        return
    if "--e2e-cpu-only" in sys.argv:
        try:
            print(json.dumps(_e2e_rates(codec_name="cpu")))
        except Exception as exc:  # noqa: BLE001
            print(json.dumps({"error": f"{type(exc).__name__}: {exc}"[:500]}))
        return
    if "--probe-only" in sys.argv:
        # the shared fast probe (ops.device_probe): subprocess + hard
        # deadline (SEAWEEDFS_TPU_PROBE_TIMEOUT_S, default 10s), the same
        # verdict codec selection uses — a wedged transport answers in
        # seconds here instead of wedging this process
        try:
            from seaweedfs_tpu.ops import device_probe

            print(json.dumps(device_probe.probe(refresh=True).to_json()))
        except Exception as exc:  # noqa: BLE001
            print(json.dumps({"error": f"{type(exc).__name__}: {exc}"[:300]}))
        return
    if "--soak-only" in sys.argv or "--soak" in sys.argv:
        try:
            print(json.dumps(_soak_rates()))
        except Exception as exc:  # noqa: BLE001
            print(json.dumps(
                {"soak_ok": False,
                 "error": f"{type(exc).__name__}: {exc}"[:500]}))
        return
    if "--service-only" in sys.argv or "--service" in sys.argv:
        try:
            print(json.dumps(_service_rates()))
        except Exception as exc:  # noqa: BLE001
            print(json.dumps({"error": f"{type(exc).__name__}: {exc}"[:500]}))
        return
    if "--degraded-only" in sys.argv:
        try:
            print(json.dumps(_degraded_read_rate()))
        except Exception as exc:  # noqa: BLE001
            print(json.dumps({"error": f"{type(exc).__name__}: {exc}"[:500]}))
        return
    if "--rebuild-only" in sys.argv:
        try:
            print(json.dumps(_rebuild_only_rates()))
        except Exception as exc:  # noqa: BLE001
            print(json.dumps({"error": f"{type(exc).__name__}: {exc}"[:500]}))
        return
    if "--geo-only" in sys.argv:
        try:
            print(json.dumps(_geo_rates()))
        except Exception as exc:  # noqa: BLE001
            print(json.dumps(
                {"geo_ok": False,
                 "error": f"{type(exc).__name__}: {exc}"[:500]}))
        return
    if "--mass-repair-only" in sys.argv or "--mass-repair" in sys.argv:
        try:
            print(json.dumps(_mass_repair_rates()))
        except Exception as exc:  # noqa: BLE001
            print(json.dumps({"error": f"{type(exc).__name__}: {exc}"[:500]}))
        return
    if "--serving-only" in sys.argv:
        try:
            print(json.dumps(_serving_rates()))
        except Exception as exc:  # noqa: BLE001
            print(json.dumps({"error": f"{type(exc).__name__}: {exc}"[:500]}))
        return
    if "--serving-smoke-only" in sys.argv:
        try:
            print(json.dumps(_serving_smoke()))
        except Exception as exc:  # noqa: BLE001
            print(json.dumps(
                {"serving_smoke_ok": False,
                 "error": f"{type(exc).__name__}: {exc}"[:500]}))
        return
    if "--smallfile-only" in sys.argv:
        try:
            print(json.dumps(_smallfile_rates(
                metrics_snapshot="--metrics-snapshot" in sys.argv)))
        except Exception as exc:  # noqa: BLE001
            print(json.dumps({"error": f"{type(exc).__name__}: {exc}"[:500]}))
        return
    if "--flight-overhead-only" in sys.argv:
        try:
            print(json.dumps(_flight_overhead()))
        except Exception as exc:  # noqa: BLE001
            print(json.dumps(
                {"flight_overhead_ok": False,
                 "error": f"{type(exc).__name__}: {exc}"[:500]}))
        return
    if "--kernel-only" in sys.argv:
        try:
            print(json.dumps(_tpu_pallas_rate()))
        except Exception as exc:  # noqa: BLE001
            print(json.dumps({"error": f"{type(exc).__name__}: {exc}"[:500]}))
        return

    import os

    cpu = _cpu_rate()
    # stage subprocess timeout, env-configurable: slow hosts recorded
    # `--kernel-only timed out after 300s` as an error with a healthy
    # tunnel (BENCH_r05) — raise SEAWEEDFS_TPU_BENCH_STAGE_TIMEOUT_S
    # there instead of editing this file; the cpu e2e keeps its 1.8x
    # margin (it runs a 4x larger volume)
    stage_timeout = float(os.environ.get(
        "SEAWEEDFS_TPU_BENCH_STAGE_TIMEOUT_S", "300"))
    # fast reachability gate (ops.device_probe, ≤10s hard deadline,
    # in-process, cached): when no non-CPU device answers a round trip,
    # the TPU stages are SKIPPED outright — acceptance is "unreachable
    # devices degrade to cpu_simd in seconds", not one 300s attempt each.
    # (BENCH_r04/r05 burned their entire budget learning the tunnel was
    # dead, three stages at a time.)
    from seaweedfs_tpu.ops import device_probe

    pr = device_probe.probe()
    tunnel_ok = pr.accelerator
    probe_err = (f"skipped: {pr.error or 'no accelerator'} "
                 f"(probe {pr.seconds:.1f}s, platform "
                 f"{pr.platform or 'none'})")
    if tunnel_ok:
        tpu = _stage_in_subprocess(
            "--kernel-only", timeout_s=stage_timeout, attempts=3,
            env_per_attempt=[  # shrink the stage set on each retry: the
                # caps map to DISTINCT subsets of the fixed 4/16/64/256
                # stages ({4,16,64,256} -> {4,16} -> {4}); re-running an
                # identical shape after a timeout just re-wedges the
                # tunnel
                {},
                {"SEAWEEDFS_TPU_BENCH_KERNEL_MB": "16"},
                {"SEAWEEDFS_TPU_BENCH_KERNEL_MB": "4"},
            ])
    else:
        tpu = {"error": probe_err}
    # e2e runs BOTH codecs and reports the faster one — the framework's
    # `-ec.codec=auto` makes the same call at runtime.  On hosts where the
    # TPU sits behind a slow tunnel the C++ SIMD codec wins the
    # disk->shards pipeline outright; on a real PCIe/pod host the device
    # path wins.  The loser's rate is preserved alongside.
    tpu_e2e = (_stage_in_subprocess(
        "--e2e-only", timeout_s=stage_timeout, attempts=2)
        if tunnel_ok else {"error": probe_err})
    cpu_e2e = _stage_in_subprocess("--e2e-cpu-only",
                                   timeout_s=stage_timeout * 1.8,
                                   attempts=1)
    candidates = [c for c in (tpu_e2e, cpu_e2e) if "e2e_rate" in c]
    if candidates:
        e2e = max(candidates, key=lambda c: c["e2e_rate"])
        other = cpu_e2e if e2e is tpu_e2e else tpu_e2e
        if "e2e_rate" in other:
            e2e[f"{other.get('impl', 'other')}_e2e_GBps"] = round(
                other["e2e_rate"], 4)
            if "rebuild_rate" in other:
                e2e[f"{other.get('impl', 'other')}_rebuild_GBps"] = round(
                    other["rebuild_rate"], 4)
        else:
            loser = "tpu" if other is tpu_e2e else "cpu"
            e2e[f"{loser}_e2e_error"] = (
                other.get("error") or "stage yielded no measured rate"
            )[:300]
    else:
        e2e = tpu_e2e
    if "rate" in tpu:
        out = {
            "metric": "ec_encode_GBps",
            "value": round(tpu["rate"], 2),
            "unit": "GB/s",
            "vs_baseline": round(tpu["rate"] / cpu, 1) if cpu else None,
            "impl": "pallas_swar_u32",
            "cpu_simd_GBps": round(cpu, 3),
            "sweep_bytes": tpu["bytes"],
            "seconds": round(tpu["seconds"], 4),
        }
        for k in ("sweep_mb_per_shard", "put_GBps", "timeout_salvaged"):
            if k in tpu:
                out[f"kernel_{k}" if k == "timeout_salvaged" else k] = tpu[k]
    else:
        # TPU unreachable after bounded retries: degrade to the host CPU
        # SIMD codec so the driver still records a real measured number,
        # with the failure visible in `error`.
        out = {
            "metric": "ec_encode_GBps",
            "value": round(cpu, 3),
            "unit": "GB/s",
            "vs_baseline": 1.0,
            "impl": "cpu_simd_fallback",
            "cpu_simd_GBps": round(cpu, 3),
            "error": (tpu.get("error") or "unknown")[:500],
        }
    if "e2e_rate" in e2e:
        out["ec_encode_e2e_GBps"] = round(e2e["e2e_rate"], 2)
        out["e2e_impl"] = e2e.get("impl", "tpu")
        out["e2e_bytes"] = e2e.get("e2e_bytes")
        if "e2e_seconds" in e2e:
            out["e2e_seconds"] = round(e2e["e2e_seconds"], 2)
        if "rebuild_rate" in e2e:
            out["ec_rebuild_GBps"] = round(e2e["rebuild_rate"], 2)
            if "rebuild_seconds" in e2e:
                out["rebuild_seconds"] = round(e2e["rebuild_seconds"], 2)
        for k in ("timeout_salvaged", "tpu_e2e_error", "cpu_e2e_error",
                  "warm_seconds", "e2e_fsync_rate",
                  "e2e_trials"):
            if k in e2e:
                out[k] = e2e[k]
        for k, v in e2e.items():  # the losing codec's rates
            if k.endswith("_GBps"):
                out[k] = v
    else:
        out["e2e_error"] = (e2e.get("error") or "unknown")[:300]
    # BASELINE config 5: concurrent degraded reads (pure host path, no
    # device dispatch — cheap and deterministic, so no subprocess guard)
    try:
        out.update(_degraded_read_rate())
    except Exception as exc:  # noqa: BLE001
        out["degraded_error"] = f"{type(exc).__name__}: {exc}"[:300]
    # the reference's ONLY published numbers: 1KB files at c=16 through
    # the full HTTP path (README.md:514-567) — measured on the same host
    try:
        import sys as _sys

        out.update(_smallfile_rates(
            metrics_snapshot="--metrics-snapshot" in _sys.argv))
    except Exception as exc:  # noqa: BLE001
        out["smallfile_error"] = f"{type(exc).__name__}: {exc}"[:300]
    # ISSUE 20: flight-recorder overhead A/B (continuous profiler +
    # hot-key sketch on vs off) — subprocess-guarded because the legs
    # flip process-global kill switches
    if "--metrics-snapshot" in _sys.argv:
        fo_res = _stage_in_subprocess("--flight-overhead-only",
                                      timeout_s=stage_timeout, attempts=1)
        if "error" in fo_res:
            out["flight_overhead_error"] = fo_res.pop("error")[:300]
        out.update(fo_res)
    # ISSUE 18: serving-plane legs (fsync batching A/B, sendfile A/B,
    # thousands-of-sockets keep-alive) — subprocess-guarded: the
    # keep-alive leg lifts RLIMIT_NOFILE and parks ~2000 sockets
    srv_res = _stage_in_subprocess("--serving-only",
                                   timeout_s=stage_timeout, attempts=1)
    if "error" in srv_res:
        out["serving_error"] = srv_res.pop("error")[:300]
    out.update(srv_res)
    # ISSUE 6: codec-service batching vs per-volume dispatch (host SIMD,
    # in-process, deterministic — no subprocess guard needed)
    try:
        svc_res = _service_rates()
        if "error" in svc_res:  # namespace like every other stage: a
            # service failure must not read as a failed bench run
            out["service_error"] = svc_res.pop("error")
        out.update(svc_res)
    except Exception as exc:  # noqa: BLE001
        out["service_error"] = f"{type(exc).__name__}: {exc}"[:300]
    # ISSUE 12: cross-cluster replication lag + throttled link throughput
    # (opt-in with --geo: spins two full clusters in-process)
    if "--geo" in _sys.argv:
        try:
            out.update(_geo_rates())
        except Exception as exc:  # noqa: BLE001
            out["geo_error"] = f"{type(exc).__name__}: {exc}"[:300]
    print(json.dumps(out))


if __name__ == "__main__":
    main()
