"""Load-time torn-tail healing coverage (ISSUE 14 satellite): direct
unit tests for `Volume.check_and_fix_integrity` — mid-blob tear,
mid-idx-entry tear, tear at the padding boundary, and a tombstone as
the last record — independent of the crash-torture harness."""

from __future__ import annotations

import os

import pytest

from helpers import make_volume

from seaweedfs_tpu.storage import types as t
from seaweedfs_tpu.storage.needle import Needle, actual_size, padding_length
from seaweedfs_tpu.storage.volume import Volume


def _reload(tmp_path) -> Volume:
    return Volume(str(tmp_path), "", 1)


def _last_entry(vol):
    last = None
    for v in vol.needle_map.items_ascending():
        if last is None or v.offset > last.offset:
            last = v
    return last


def test_mid_blob_tear_truncates_to_previous_record(tmp_path):
    vol = make_volume(str(tmp_path), n_needles=6)
    base = vol.file_name()
    last = _last_entry(vol)
    vol.close()
    # chop into the middle of the last blob's DATA bytes
    with open(base + ".dat", "r+b") as f:
        f.truncate(last.offset + t.NEEDLE_HEADER_SIZE
                   + max(last.size // 2, 1))
    vol2 = _reload(tmp_path)
    with pytest.raises(KeyError):
        vol2.read_needle(6)
    assert vol2.read_needle(5).id == 5
    # the torn bytes are gone: the file ends at the previous record
    assert os.path.getsize(base + ".dat") == last.offset
    # and the volume accepts (and persists) new appends
    vol2.append_needle(Needle(cookie=7, id=100, data=b"after-heal"))
    vol2.close()
    vol3 = _reload(tmp_path)
    assert vol3.read_needle(100).data == b"after-heal"
    vol3.close()


def test_mid_idx_entry_tear_drops_partial_entry(tmp_path):
    vol = make_volume(str(tmp_path), n_needles=6)
    base = vol.file_name()
    vol.close()
    idx_size = os.path.getsize(base + ".idx")
    assert idx_size == 6 * t.NEEDLE_MAP_ENTRY_SIZE
    # tear mid-entry: the last entry loses its final 9 bytes
    with open(base + ".idx", "r+b") as f:
        f.truncate(idx_size - 9)
    vol2 = _reload(tmp_path)
    # the partial entry is dropped; its needle is unindexed (the .dat
    # bytes remain as unreferenced garbage until vacuum)
    with pytest.raises(KeyError):
        vol2.read_needle(6)
    assert vol2.read_needle(5).id == 5
    # appends still work and re-index cleanly
    vol2.append_needle(Needle(cookie=7, id=101, data=b"idx-heal"))
    vol2.close()
    vol3 = _reload(tmp_path)
    assert vol3.read_needle(101).data == b"idx-heal"
    assert os.path.getsize(base + ".idx") % t.NEEDLE_MAP_ENTRY_SIZE == 0
    vol3.close()


def test_tear_at_padding_boundary_repads(tmp_path):
    vol = make_volume(str(tmp_path), n_needles=4)
    base = vol.file_name()
    last = _last_entry(vol)
    version = vol.version
    vol.close()
    end = last.offset + actual_size(last.size, version)
    pad = padding_length(last.size, version)
    # truncate EXACTLY at the padding boundary: every real byte of the
    # record (header+body+crc+ts) is present, only padding is missing
    with open(base + ".dat", "r+b") as f:
        f.truncate(end - pad)
    vol2 = _reload(tmp_path)
    # the acked needle survives — dropping it here would be data loss
    n = vol2.read_needle(4)
    assert n.id == 4
    # the file was re-padded back to alignment and appends continue
    assert os.path.getsize(base + ".dat") == end
    vol2.append_needle(Needle(cookie=7, id=102, data=b"padded"))
    assert vol2.read_needle(102).data == b"padded"
    vol2.close()


def test_tombstone_as_last_record(tmp_path):
    vol = make_volume(str(tmp_path), n_needles=5)
    base = vol.file_name()
    assert vol.delete_needle(5) > 0
    vol.close()
    # clean reload: the delete persists, the tombstone tail is benign
    vol2 = _reload(tmp_path)
    with pytest.raises(KeyError):
        vol2.read_needle(5)
    assert vol2.read_needle(4).id == 4
    vol2.append_needle(Needle(cookie=7, id=103, data=b"post-delete"))
    assert vol2.read_needle(103).data == b"post-delete"
    vol2.close()


def test_torn_tombstone_keeps_delete_durable(tmp_path):
    vol = make_volume(str(tmp_path), n_needles=5)
    base = vol.file_name()
    pre = os.path.getsize(base + ".dat")
    assert vol.delete_needle(5) > 0
    vol.close()
    # tear INTO the tombstone marker record: the .idx tombstone entry
    # (written before close) is what makes the delete durable
    with open(base + ".dat", "r+b") as f:
        f.truncate(pre + 4)
    vol2 = _reload(tmp_path)
    with pytest.raises(KeyError):
        vol2.read_needle(5)  # still deleted
    assert vol2.read_needle(4).id == 4
    vol2.close()
