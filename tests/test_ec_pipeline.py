"""EC pipeline conformance — the tier-2 harness from SURVEY.md §4.

Mirrors the reference's ec_test.go: encode a real volume with scaled-down
block sizes (10000/100), then for every live needle assert the `.dat` bytes
equal the striped shard bytes via the interval math, do random 10-of-14
reconstruction per interval, rebuild missing shard files, and round-trip
decode back to a byte-identical `.dat`.  Runs against both the reference's
checked-in fixture (when present) and a synthetic volume, with CPU and TPU
codecs producing identical shards.
"""

import os
import shutil

import numpy as np
import pytest

from seaweedfs_tpu.storage import NeedleMap
from seaweedfs_tpu.storage.ec import constants as ecc
from seaweedfs_tpu.storage.ec.decoder import (
    find_dat_file_size,
    write_dat_file,
    write_idx_file_from_ec_index,
)
from seaweedfs_tpu.storage.ec.encoder import (
    generate_ec_files,
    rebuild_ec_files,
    write_sorted_file_from_idx,
)
from seaweedfs_tpu.storage.ec.locate import locate_data, shard_file_size
from seaweedfs_tpu.storage.ec.volume import EcVolume, NotFoundError
from seaweedfs_tpu.storage.needle import actual_size
from seaweedfs_tpu.storage.super_block import VERSION3
from seaweedfs_tpu.ops.codec import get_codec

from helpers import make_volume

LARGE = 10000  # scaled-down block sizes, as in the reference ec_test.go:16-19
SMALL = 100
REF_EC_DIR = "/root/reference/weed/storage/erasure_coding"


def _encode_dir(base, codec="cpu"):
    generate_ec_files(base, large_block_size=LARGE, small_block_size=SMALL,
                      codec_name=codec, slice_size=50)
    write_sorted_file_from_idx(base)


def _read_ec_interval(base, dat_size, offset, size):
    out = b""
    for iv in locate_data(LARGE, SMALL, dat_size, offset, size):
        sid, soff = iv.to_shard_id_and_offset(LARGE, SMALL)
        with open(base + ecc.to_ext(sid), "rb") as f:
            f.seek(soff)
            out += f.read(iv.size)
    return out


def _validate_all_needles(base):
    """dat bytes == striped shard bytes for every live needle."""
    nm = NeedleMap.load_from_idx(base + ".idx")
    dat_size = os.path.getsize(base + ".dat")
    with open(base + ".dat", "rb") as dat:
        for v in nm.items_ascending():
            if v.size <= 0:
                continue
            dat.seek(v.offset)
            direct = dat.read(v.size)
            striped = _read_ec_interval(base, dat_size, v.offset, v.size)
            assert striped == direct, f"needle {v.key} mismatch"


@pytest.fixture()
def synthetic_base(tmp_path):
    vol = make_volume(str(tmp_path), n_needles=80, seed=3, max_size=3000)
    base = vol.file_name()
    vol.close()
    return base


def test_encode_validate_synthetic(synthetic_base):
    _encode_dir(synthetic_base)
    _validate_all_needles(synthetic_base)
    # shard sizes match the predicted geometry
    dat_size = os.path.getsize(synthetic_base + ".dat")
    expect = shard_file_size(dat_size, LARGE, SMALL)
    for i in range(ecc.TOTAL_SHARDS):
        assert os.path.getsize(synthetic_base + ecc.to_ext(i)) == expect


def test_batched_slices_byte_identical(synthetic_base):
    """Multi-row codec batches (slice >> small block) must produce the
    exact bytes of the one-segment-at-a-time path — parity is columnwise,
    so batching is pure data layout."""
    _encode_dir(synthetic_base)  # slice_size=50: every call one segment
    small_slices = {}
    for i in range(ecc.TOTAL_SHARDS):
        p = synthetic_base + ecc.to_ext(i)
        small_slices[i] = open(p, "rb").read()
        os.remove(p)
    generate_ec_files(synthetic_base, large_block_size=LARGE,
                      small_block_size=SMALL, codec_name="cpu",
                      slice_size=1 << 20)  # whole volume in one batch
    for i in range(ecc.TOTAL_SHARDS):
        batched = open(synthetic_base + ecc.to_ext(i), "rb").read()
        assert batched == small_slices[i], f"shard {i} differs when batched"


def test_auto_codec_resolves():
    codec = get_codec("auto")
    data = np.arange(10 * 64, dtype=np.uint8).reshape(10, 64)
    ref = get_codec("cpu").parity_of(data)
    assert np.array_equal(np.asarray(codec.parity_of(data)), np.asarray(ref))


def test_tpu_and_cpu_shards_identical(synthetic_base):
    _encode_dir(synthetic_base, codec="cpu")
    cpu_shards = {}
    for i in range(ecc.TOTAL_SHARDS):
        p = synthetic_base + ecc.to_ext(i)
        cpu_shards[i] = open(p, "rb").read()
        os.remove(p)
    generate_ec_files(synthetic_base, large_block_size=LARGE,
                      small_block_size=SMALL, codec_name="tpu",
                      slice_size=4096)
    for i in range(ecc.TOTAL_SHARDS):
        tpu = open(synthetic_base + ecc.to_ext(i), "rb").read()
        assert tpu == cpu_shards[i], f"shard {i} differs between codecs"


def test_random_10_of_14_reconstruction(synthetic_base):
    _encode_dir(synthetic_base)
    rng = np.random.default_rng(4)
    nm = NeedleMap.load_from_idx(synthetic_base + ".idx")
    dat_size = os.path.getsize(synthetic_base + ".dat")
    codec = get_codec("cpu")
    for v in list(nm.items_ascending())[:20]:
        for iv in locate_data(LARGE, SMALL, dat_size, v.offset, max(v.size, 1)):
            sid, soff = iv.to_shard_id_and_offset(LARGE, SMALL)
            with open(synthetic_base + ecc.to_ext(sid), "rb") as f:
                f.seek(soff)
                want = f.read(iv.size)
            # pick 10 random other shards, reconstruct this interval
            others = [i for i in range(ecc.TOTAL_SHARDS) if i != sid]
            chosen = rng.choice(others, 10, replace=False)
            shards = [None] * ecc.TOTAL_SHARDS
            for i in chosen:
                with open(synthetic_base + ecc.to_ext(int(i)), "rb") as f:
                    f.seek(soff)
                    shards[int(i)] = np.frombuffer(f.read(iv.size), dtype=np.uint8)
            rebuilt = codec.reconstruct_data(shards)
            got = np.asarray(rebuilt[sid]).tobytes() if sid < 10 else None
            if sid < 10:
                assert got == want
            break  # one interval per needle keeps runtime sane


def test_rebuild_missing_shards(synthetic_base, tmp_path):
    _encode_dir(synthetic_base)
    originals = {}
    for i in (0, 4, 11, 13):  # kill 2 data + 2 parity shards
        p = synthetic_base + ecc.to_ext(i)
        originals[i] = open(p, "rb").read()
        os.remove(p)
    rebuilt = rebuild_ec_files(synthetic_base, slice_size=1000)
    assert sorted(rebuilt) == [0, 4, 11, 13]
    for i, want in originals.items():
        got = open(synthetic_base + ecc.to_ext(i), "rb").read()
        assert got == want, f"rebuilt shard {i} not byte-identical"


def test_decode_roundtrip(synthetic_base, tmp_path):
    _encode_dir(synthetic_base)
    orig_dat = open(synthetic_base + ".dat", "rb").read()
    orig_idx = open(synthetic_base + ".idx", "rb").read()
    # move shards to a fresh dir, decode there
    dec_base = str(tmp_path / "decoded" / "1")
    os.makedirs(os.path.dirname(dec_base))
    for i in range(ecc.TOTAL_SHARDS):
        shutil.copy(synthetic_base + ecc.to_ext(i), dec_base + ecc.to_ext(i))
    shutil.copy(synthetic_base + ".ecx", dec_base + ".ecx")

    # find_dat_file_size recovers the logical size from the index (the tail
    # padding beyond the last needle is not recoverable, nor needed)
    import seaweedfs_tpu.storage.ec.decoder as dec

    orig_large = dec.LARGE_BLOCK_SIZE, dec.SMALL_BLOCK_SIZE
    dec.LARGE_BLOCK_SIZE, dec.SMALL_BLOCK_SIZE = LARGE, SMALL
    try:
        dat_size = find_dat_file_size(dec_base, dec_base)
        write_dat_file(dec_base, dat_size)
        write_idx_file_from_ec_index(dec_base)
    finally:
        dec.LARGE_BLOCK_SIZE, dec.SMALL_BLOCK_SIZE = orig_large

    got = open(dec_base + ".dat", "rb").read()
    assert got == orig_dat[: len(got)]
    assert len(got) >= dat_size
    assert open(dec_base + ".idx", "rb").read() == orig_idx


def test_ec_volume_runtime(synthetic_base):
    _encode_dir(synthetic_base)
    ev = EcVolume(synthetic_base, volume_id=1, version=VERSION3,
                  large_block_size=LARGE, small_block_size=SMALL)
    n = ev.read_needle(5)
    assert n.id == 5
    # degraded read: drop 4 shard files from the volume's view
    for sid in (0, 1, 2, 3):
        ev.delete_shard(sid)
    n2 = ev.read_needle(5)
    assert n2.data == n.data
    # delete: tombstone + journal, then read fails
    ev.delete_needle(5)
    with pytest.raises((NotFoundError, KeyError)):
        ev.read_needle(5)
    assert os.path.exists(synthetic_base + ".ecj")
    ev.close()


def test_ec_volume_remote_only_reads(synthetic_base):
    """A server holding only the .ecx (every shard remote) must still locate
    and read needles: shard size is derived from the index, intervals are
    served through the remote-fetch hook."""
    _encode_dir(synthetic_base)
    ref = EcVolume(synthetic_base, volume_id=1, version=VERSION3,
                   large_block_size=LARGE, small_block_size=SMALL)
    want = ref.read_needle(5)
    real_shard_size = ref.shard_size
    ref.close()

    ev = EcVolume(synthetic_base, volume_id=1, version=VERSION3,
                  large_block_size=LARGE, small_block_size=SMALL)
    for sid in list(ev.shards):
        ev.delete_shard(sid)

    def fetch(shard_id, offset, length):
        with open(synthetic_base + ecc.to_ext(shard_id), "rb") as f:
            f.seek(offset)
            return f.read(length)

    ev.remote_fetch = fetch
    assert ev.shard_size == real_shard_size
    got = ev.read_needle(5)
    assert got.data == want.data
    ev.close()


@pytest.mark.skipif(not os.path.isdir(REF_EC_DIR), reason="reference fixture absent")
def test_reference_fixture_conformance(tmp_path):
    """Encode the reference's real 1.dat volume (written by the original
    implementation) with the scaled block sizes from its own test harness and
    validate every needle through the stripe — our equivalent of the
    reference's TestEncodingDecoding over the same bytes."""
    base = str(tmp_path / "1")
    shutil.copy(os.path.join(REF_EC_DIR, "1.dat"), base + ".dat")
    shutil.copy(os.path.join(REF_EC_DIR, "1.idx"), base + ".idx")
    _encode_dir(base)
    _validate_all_needles(base)


def test_locate_data_reference_vectors():
    """The exact interval pinned by the reference's TestLocateData."""
    ivs = locate_data(LARGE, SMALL, 10 * LARGE + 1, 10 * LARGE, 1)
    assert len(ivs) == 1
    iv = ivs[0]
    assert (iv.block_index, iv.inner_block_offset, iv.size, iv.is_large_block) == (
        0, 0, 1, False,
    )
    assert iv.large_block_rows_count == 1
    # spanning interval: from mid-large-area to the end of the volume
    total = 10 * LARGE + 1
    start = 10 * LARGE // 2 + 100
    ivs = locate_data(LARGE, SMALL, total, start, total - start)
    assert sum(i.size for i in ivs) == total - start
    # contiguity: intervals chain across block boundaries
    pos = start
    dat = np.arange(total) % 251
    for iv in ivs:
        pos += iv.size
    assert pos == total


def test_shard_file_size_edges():
    ten = ecc.DATA_SHARDS
    assert shard_file_size(0, LARGE, SMALL) == 0
    assert shard_file_size(1, LARGE, SMALL) == SMALL
    assert shard_file_size(ten * SMALL, LARGE, SMALL) == SMALL
    assert shard_file_size(ten * SMALL + 1, LARGE, SMALL) == 2 * SMALL
    assert shard_file_size(ten * LARGE, LARGE, SMALL) == LARGE  # all small rows
    assert shard_file_size(ten * LARGE + 1, LARGE, SMALL) == LARGE + SMALL
