"""Codec conformance: CPU (numpy), TPU-xor, TPU-mxu, and native C++ paths all
agree with each other and with independently computed GF math; reconstruction
from any 10-of-14 shards is exact (the property pinned by the reference's
ec_test.go random 10-of-14 ReconstructData check)."""

import itertools

import numpy as np
import pytest

from seaweedfs_tpu.ops import codec, gf256
from seaweedfs_tpu.ops.rs_cpu import ReedSolomon
from seaweedfs_tpu.ops.rs_jax import ReedSolomonTPU


def _rand_shards(rng, n=10, size=257):
    data = [rng.integers(0, 256, size).astype(np.uint8) for _ in range(n)]
    parity = [np.zeros(size, dtype=np.uint8) for _ in range(4)]
    return data + parity


def _slow_parity(data):
    """Independent reference: elementwise log/exp GF multiply-accumulate."""
    m = gf256.rs_parity_matrix(10, 4)
    out = []
    for i in range(4):
        acc = np.zeros_like(data[0])
        for j in range(10):
            c = int(m[i, j])
            acc ^= np.array(
                [gf256.gf_mul(c, int(b)) for b in data[j]], dtype=np.uint8
            )
        out.append(acc)
    return out


def test_cpu_encode_matches_slow_reference():
    rng = np.random.default_rng(7)
    shards = _rand_shards(rng, size=31)
    rs = ReedSolomon()
    rs.encode(shards)
    expect = _slow_parity(shards[:10])
    for i in range(4):
        assert np.array_equal(shards[10 + i], expect[i])
    assert rs.verify(shards)


@pytest.mark.parametrize("impl", ["tpu", "tpu_mxu"])
def test_jax_encode_matches_cpu(impl):
    rng = np.random.default_rng(8)
    shards_cpu = _rand_shards(rng, size=1000)
    shards_tpu = [s.copy() for s in shards_cpu]
    ReedSolomon().encode(shards_cpu)
    codec.get_codec(impl).encode(shards_tpu)
    for i in range(14):
        assert np.array_equal(shards_cpu[i], shards_tpu[i]), f"shard {i}"


def test_reconstruct_all_four_missing_patterns():
    rng = np.random.default_rng(9)
    rs = ReedSolomon()
    shards = _rand_shards(rng, size=129)
    rs.encode(shards)
    # every 4-subset of missing shards (worst case allowed by RS(10,4))
    for missing in itertools.combinations(range(14), 4):
        damaged = [
            None if i in missing else shards[i].copy() for i in range(14)
        ]
        rebuilt = rs.reconstruct(damaged)
        for i in range(14):
            assert np.array_equal(rebuilt[i], shards[i]), (missing, i)


def test_reconstruct_data_only():
    rng = np.random.default_rng(10)
    rs = ReedSolomon()
    shards = _rand_shards(rng, size=64)
    rs.encode(shards)
    damaged = [None if i in (0, 5, 13) else shards[i].copy() for i in range(14)]
    rebuilt = rs.reconstruct_data(damaged)
    for i in range(10):
        assert np.array_equal(rebuilt[i], shards[i])
    assert rebuilt[13] is None  # parity not rebuilt on the data-only path


def test_too_few_shards_raises():
    rs = ReedSolomon()
    shards = [np.zeros(8, dtype=np.uint8)] * 9 + [None] * 5
    with pytest.raises(ValueError):
        rs.reconstruct(shards)


@pytest.mark.parametrize("impl", ["xor", "mxu"])
def test_jax_reconstruct(impl):
    rng = np.random.default_rng(11)
    rs = ReedSolomon()
    shards = _rand_shards(rng, size=640)
    rs.encode(shards)
    tpu = ReedSolomonTPU(impl=impl)
    damaged = [None if i in (1, 2, 3, 4) else shards[i].copy() for i in range(14)]
    rebuilt = tpu.reconstruct(damaged)
    for i in range(14):
        assert np.array_equal(rebuilt[i], shards[i])


def test_native_cpp_agrees_if_available():
    from seaweedfs_tpu.native import lib

    if not lib.available():
        pytest.skip("native library unavailable")
    rng = np.random.default_rng(12)
    shards = _rand_shards(rng, size=1000)
    ReedSolomon().encode(shards)
    m = gf256.rs_parity_matrix(10, 4)
    outs = lib.gf_apply(m, [s.tobytes() for s in shards[:10]], 4)
    for i in range(4):
        assert bytes(outs[i]) == shards[10 + i].tobytes()


def test_crc32c_masked():
    from seaweedfs_tpu.ops import crc32c

    # crc32c("123456789") = 0xE3069283 (Castagnoli check value)
    assert crc32c.checksum(b"123456789") == 0xE3069283
    # incremental == one-shot
    c = crc32c.update(crc32c.update(0, b"1234"), b"56789")
    assert c == 0xE3069283
    # masked value formula from the reference crc.go:25
    assert crc32c.mask(0xE3069283) == (
        (((0xE3069283 >> 15) | (0xE3069283 << 17)) & 0xFFFFFFFF) + 0xA282EAD8
    ) & 0xFFFFFFFF
    assert crc32c.checksum(b"") == 0


def test_native_codec_uses_simd_on_this_host():
    """The native GF codec must engage a SIMD path (GFNI or SSSE3) on
    x86 hosts — a silently-scalar build costs ~4x throughput (this
    exact staleness shipped for three rounds before being caught)."""
    from seaweedfs_tpu.native import lib as native

    if not native.available():
        import pytest

        pytest.skip("native lib unavailable")
    with open("/proc/cpuinfo") as f:
        flags = f.read()
    impl = native._lib.sw_gf_impl()
    if "gfni" in flags and "avx512bw" in flags:
        assert impl == 3, ("GFNI host must use the column-interleaved "
                           "gf2p8affine kernel")
    elif "ssse3" in flags:
        assert impl >= 1, "SSE host must not run the scalar codec"
