"""Security (JWT write tokens, whitelist) + metrics/logging tests.

Reference analogues: weed/security/jwt.go:21-58, guard.go:43,
weed/stats/metrics.go:25-123, weed/glog.
"""

import io
import json
import socket
import time
import urllib.error
import urllib.request

import pytest

from seaweedfs_tpu.security.guard import Guard
from seaweedfs_tpu.security.jwt import (
    decode_jwt,
    encode_jwt,
    gen_write_jwt,
    verify_write_jwt,
)
from seaweedfs_tpu.stats.metrics import Registry
from seaweedfs_tpu.util import glog


# -- jwt --------------------------------------------------------------------


def test_jwt_roundtrip_and_tamper():
    key = b"secret-key"
    token = encode_jwt(key, {"sub": "3,abc", "exp": int(time.time()) + 60})
    claims = decode_jwt(key, token)
    assert claims["sub"] == "3,abc"
    # wrong key
    assert decode_jwt(b"other", token) is None
    # tampered payload
    h, p, s = token.split(".")
    assert decode_jwt(key, f"{h}.{p}x.{s}") is None
    # expired
    old = encode_jwt(key, {"exp": int(time.time()) - 1})
    assert decode_jwt(key, old) is None


def test_write_jwt_fid_binding():
    key = b"k"
    token = gen_write_jwt(key, "7,deadbeef01")
    assert verify_write_jwt(key, token, "7,deadbeef01")
    assert not verify_write_jwt(key, token, "7,other")
    assert not verify_write_jwt(key, "", "7,deadbeef01")
    assert gen_write_jwt(b"", "x") == ""  # keyless cluster: no tokens


def test_guard_whitelist():
    g = Guard(["127.0.0.1", "10.0.0.0/8"])
    assert g.allows("127.0.0.1")
    assert g.allows("10.1.2.3")
    assert not g.allows("192.168.1.1")
    assert Guard([]).allows("8.8.8.8")  # empty whitelist admits all


# -- metrics registry -------------------------------------------------------


def test_metrics_render():
    r = Registry()
    c = r.counter("test_requests_total", "requests", labels=("op",))
    c.labels("read").inc()
    c.labels("read").inc(2)
    c.labels("write").inc()
    g = r.gauge("test_volumes", "volumes")
    g.set(42)
    h = r.histogram("test_latency_seconds", "latency", buckets=(0.1, 1.0))
    h.observe(0.05)
    h.observe(0.5)
    h.observe(5.0)
    text = r.render()
    assert 'test_requests_total{op="read"} 3.0' in text
    assert 'test_requests_total{op="write"} 1.0' in text
    assert "test_volumes 42.0" in text
    assert 'test_latency_seconds_bucket{le="0.1"} 1' in text
    assert 'test_latency_seconds_bucket{le="1.0"} 2' in text
    assert 'test_latency_seconds_bucket{le="+Inf"} 3' in text
    assert "test_latency_seconds_count 3" in text


def test_histogram_timer():
    r = Registry()
    h = r.histogram("t_seconds", "t")
    with h.labels().time():
        time.sleep(0.01)
    child = h.labels()
    assert child.count == 1 and child.total >= 0.01


# -- glog -------------------------------------------------------------------


def test_glog_levels_and_format():
    buf = io.StringIO()
    glog.set_output(buf)
    try:
        glog.info("hello %d", 42)
        glog.warning("watch out")
        glog.error("boom")
        glog.set_verbosity(2)
        assert glog.V(2) and not glog.V(3)
    finally:
        glog.set_verbosity(0)
        import sys

        glog.set_output(sys.stderr)
    out = buf.getvalue()
    lines = out.strip().split("\n")
    assert lines[0].startswith("I") and "hello 42" in lines[0]
    assert lines[1].startswith("W")
    assert lines[2].startswith("E")
    assert "test_security_metrics.py" in lines[0]


# -- cluster: jwt enforcement + /metrics scrape -----------------------------


def _free_port():
    from helpers import free_port

    return free_port()


def _http(method, url, data=None, headers=None):
    req = urllib.request.Request(url, data=data, method=method,
                                 headers=headers or {})
    try:
        with urllib.request.urlopen(req, timeout=10) as r:
            return r.status, r.read()
    except urllib.error.HTTPError as e:
        return e.code, e.read()


@pytest.fixture(scope="module")
def secured_cluster(tmp_path_factory):
    from seaweedfs_tpu.master.server import MasterServer
    from seaweedfs_tpu.volume.server import VolumeServer

    key = "cluster-signing-key"
    m = MasterServer(ip="127.0.0.1", port=_free_port(),
                     jwt_signing_key=key, metrics_port=_free_port())
    m.start()
    v = VolumeServer(
        directories=[str(tmp_path_factory.mktemp("svol"))],
        master_addresses=[f"127.0.0.1:{m.grpc_port}"],
        ip="127.0.0.1", port=_free_port(), pulse_seconds=0.5,
        jwt_signing_key=key, metrics_port=_free_port(),
    )
    v.start()
    deadline = time.time() + 10
    while time.time() < deadline and not m.topo.nodes:
        time.sleep(0.1)
    yield m, v
    v.stop()
    m.stop()


def test_jwt_write_enforcement(secured_cluster):
    m, v = secured_cluster
    code, body = _http("GET", f"http://127.0.0.1:{m.port}/dir/assign")
    assert code == 200
    a = json.loads(body)
    assert a.get("auth"), "keyed master must hand out a write token"
    # unsigned write rejected
    code, _ = _http("POST", f"http://{a['url']}/{a['fid']}", b"data")
    assert code == 401
    # signed write accepted
    code, _ = _http(
        "POST", f"http://{a['url']}/{a['fid']}", b"data",
        headers={"Authorization": f"BEARER {a['auth']}"},
    )
    assert code == 201
    # reads stay open (read tokens are a separate opt-in in the reference)
    code, got = _http("GET", f"http://{a['url']}/{a['fid']}")
    assert code == 200 and got == b"data"
    # unsigned delete rejected; signed delete passes
    code, _ = _http("DELETE", f"http://{a['url']}/{a['fid']}")
    assert code == 401
    code, _ = _http(
        "DELETE", f"http://{a['url']}/{a['fid']}",
        headers={"Authorization": f"BEARER {a['auth']}"},
    )
    assert code == 202


def test_metrics_scrape(secured_cluster):
    m, v = secured_cluster
    time.sleep(1.2)  # let a full heartbeat refresh gauges
    code, body = _http("GET", f"http://127.0.0.1:{v.metrics_port}/metrics")
    assert code == 200
    text = body.decode()
    assert "seaweedfs_request_total" in text
    code, body = _http("GET", f"http://127.0.0.1:{m.metrics_port}/metrics")
    assert code == 200
    assert "seaweedfs_request_total" in body.decode()


def test_whitelist_guard_rejects(tmp_path_factory):
    from seaweedfs_tpu.master.server import MasterServer
    from seaweedfs_tpu.volume.server import VolumeServer

    m = MasterServer(ip="127.0.0.1", port=_free_port())
    m.start()
    v = VolumeServer(
        directories=[str(tmp_path_factory.mktemp("wvol"))],
        master_addresses=[f"127.0.0.1:{m.grpc_port}"],
        ip="127.0.0.1", port=_free_port(), pulse_seconds=0.5,
        whitelist=["10.9.9.9"],  # excludes 127.0.0.1
    )
    v.start()
    code, body = _http("GET", f"http://127.0.0.1:{v.port}/status")
    assert code == 403
    v.stop()
    m.stop()
