"""Direct coverage for telemetry/stitch.py clock-skew edge cases and
federation.py's snapshot-staleness fallback (ISSUE 13 satellite) —
previously exercised only incidentally by the cluster acceptance test.
"""

from __future__ import annotations

import time

from seaweedfs_tpu.telemetry.federation import (
    FederatedExposition,
    inject_labels,
    parse_exposition,
)
from seaweedfs_tpu.telemetry.stitch import estimate_skew, stitch_trace

TID = "ab" * 16


def _span(span_id, parent, start, dur_ms, name="op"):
    return {"traceId": TID, "spanId": span_id, "parentId": parent,
            "name": name, "start": start, "durationMs": dur_ms,
            "attrs": {}, "status": "ok"}


# -- stitch: clock skew ------------------------------------------------------


def test_estimate_skew_symmetric_path():
    import pytest

    # node clock 0.4s ahead: sent at 100, rtt 0.2 -> midpoint 100.1
    assert estimate_skew(100.5, 100.0, 0.2) == pytest.approx(0.4)
    # NEGATIVE skew: node clock behind the master's
    assert estimate_skew(99.0, 100.0, 0.2) == pytest.approx(-1.1)


def test_stitch_negative_skew_reorders_spans():
    """A node whose clock runs BEHIND stamps its spans too early; the
    skew adjustment must shift them forward so the merged timeline
    orders by true wall time."""
    # true order: master span at t=100.0, then volume span at t=100.5,
    # but the volume node's clock is 2s behind (stamps 98.5)
    results = [
        {"instance": "m:1", "type": "master",
         "spans": [_span("aa" * 8, "", 100.0, 10.0)],
         "skew_s": 0.0, "rtt_s": 0.0},
        {"instance": "v:1", "type": "volume",
         "spans": [_span("bb" * 8, "aa" * 8, 98.5, 5.0)],
         "skew_s": -2.0, "rtt_s": 0.01},
    ]
    doc = stitch_trace(TID, results)
    assert [s["spanId"] for s in doc["spans"]] == ["aa" * 8, "bb" * 8]
    vol = doc["spans"][1]
    assert vol["startAdjusted"] == 100.5
    assert doc["nodes"]["v:1"]["clockSkewMs"] == -2000.0
    # duration spans the ADJUSTED envelope, not the raw stamps
    assert doc["durationMs"] == 505.0


def test_stitch_missing_skew_field_defaults_to_zero():
    """A node result without skew/rtt (e.g. a /debug/traces response
    missing `now`) merges with no adjustment rather than crashing."""
    results = [
        {"instance": "v:1", "type": "volume",
         "spans": [_span("aa" * 8, "", 50.0, 1.0)]},  # no skew_s/rtt_s
    ]
    doc = stitch_trace(TID, results)
    assert doc["spans"][0]["startAdjusted"] == 50.0
    assert doc["nodes"]["v:1"]["clockSkewMs"] == 0.0


def test_stitch_marks_orphans_and_empty_input():
    results = [
        {"instance": "a:1", "type": "filer",
         "spans": [_span("aa" * 8, "", 10.0, 1.0),
                   _span("bb" * 8, "aa" * 8, 10.1, 1.0),
                   _span("cc" * 8, "99" * 8, 10.2, 1.0)],  # dead parent
         "skew_s": 0.0, "rtt_s": 0.0},
    ]
    doc = stitch_trace(TID, results)
    by_id = {s["spanId"]: s for s in doc["spans"]}
    assert not by_id["aa" * 8]["orphan"]  # root: empty parent, no orphan
    assert not by_id["bb" * 8]["orphan"]  # parent present
    assert by_id["cc" * 8]["orphan"]      # parent ring-evicted/process gone
    empty = stitch_trace(TID, [])
    assert empty["spans"] == [] and "durationMs" not in empty


# -- federation: parse + snapshot fallback -----------------------------------


def test_parse_exposition_groups_histograms_and_drops_malformed():
    text = "\n".join([
        "# HELP x_seconds latency",
        "# TYPE x_seconds histogram",
        'x_seconds_bucket{le="0.5"} 3',
        "x_seconds_sum 1.5",
        "x_seconds_count 3",
        "# TYPE y_total counter",
        "y_total 7 1700000000",  # timestamped sample: value still parsed
        'broken{no_close 9',     # malformed: dropped, not corrupting
        "bare_untyped 1",
    ])
    families, samples = parse_exposition(text)
    assert families["x_seconds"][0] == "histogram"
    by_family: dict = {}
    for family, name, value in samples:
        by_family.setdefault(family, []).append((name, value))
    # histogram pieces all file under the base family (contiguity)
    assert {n for n, _v in by_family["x_seconds"]} == {
        'x_seconds_bucket{le="0.5"}', "x_seconds_sum", "x_seconds_count"}
    assert ("y_total", "7") in by_family["y_total"]
    assert "bare_untyped" in by_family
    assert not any("broken" in f for f in by_family)


def test_snapshot_fallback_renders_with_registry_kinds():
    """An unreachable node served from its heartbeat snapshot: known
    families pick up their TYPE from the local registry, unknown ones
    render untyped, and stale/age meta-samples mark the node."""
    fed = FederatedExposition()
    node = {"instance": "10.0.0.9:8080", "type": "volume"}
    fed.add_snapshot(node, [
        ('seaweedfs_request_total{type="volumeServer",op="get"}', 42.0),
        ("totally_unknown_total", 7.0),
    ], age_seconds=12.5)
    out = fed.render()
    assert "# TYPE seaweedfs_request_total counter" in out
    assert "# TYPE totally_unknown_total untyped" in out
    assert ('seaweedfs_federation_stale{instance="10.0.0.9:8080"'
            in out)
    assert "seaweedfs_federation_snapshot_age_seconds" in out
    assert 'seaweedfs_request_total{instance="10.0.0.9:8080"' in out


def test_down_node_still_visible():
    fed = FederatedExposition()
    fed.add_down({"instance": "10.0.0.9:8080", "type": "volume"})
    out = fed.render()
    assert 'seaweedfs_federation_up{instance="10.0.0.9:8080"' in out


def test_inject_labels_orders_extras_first():
    line = inject_labels('x_total{op="get"}', {"instance": "a:1"})
    assert line == 'x_total{instance="a:1",op="get"}'
    assert inject_labels("x_total", {"instance": "a:1"}) == (
        'x_total{instance="a:1"}')


def test_federation_targets_staleness_cutoff(tmp_path):
    """Snapshots for departed nodes are served only within the retention
    window: a node gone 15+ minutes is an outage, not a scrape blip."""
    from helpers import free_port
    from seaweedfs_tpu.master import observability
    from seaweedfs_tpu.master.server import MasterServer

    master = MasterServer(ip="127.0.0.1", port=free_port())
    # no start(): federation_targets only reads in-memory state
    now = time.monotonic()
    master.stats_snapshots["1.1.1.1:80"] = {
        "type": "volume", "samples": [("x_total", 1.0)],
        "captured_at_ms": 0, "received": now - 10.0}           # fresh
    master.stats_snapshots["2.2.2.2:80"] = {
        "type": "volume", "samples": [("x_total", 1.0)],
        "captured_at_ms": 0,
        "received": now - observability.SNAPSHOT_RETENTION_S - 5}  # stale
    instances = {t["instance"] for t in
                 observability.federation_targets(master)}
    assert "1.1.1.1:80" in instances
    assert "2.2.2.2:80" not in instances
