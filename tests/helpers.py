"""Shared test fixtures: synthetic needle-log volumes."""

from __future__ import annotations

import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np

from seaweedfs_tpu.storage import Needle, SuperBlock
from seaweedfs_tpu.storage.needle import FLAG_HAS_MIME, FLAG_HAS_NAME
from seaweedfs_tpu.storage.volume import Volume


def make_volume(
    directory: str,
    volume_id: int = 1,
    n_needles: int = 50,
    seed: int = 0,
    max_size: int = 2000,
    collection: str = "",
) -> Volume:
    """Create a volume with random needles; returns the open Volume."""
    rng = np.random.default_rng(seed)
    vol = Volume(directory, collection, volume_id, super_block=SuperBlock())
    for i in range(1, n_needles + 1):
        size = int(rng.integers(1, max_size))
        n = Needle(
            cookie=int(rng.integers(0, 2**32)),
            id=i,
            data=rng.integers(0, 256, size).astype(np.uint8).tobytes(),
        )
        if i % 3 == 0:
            n.set(FLAG_HAS_NAME)
            n.name = f"file-{i}.bin".encode()
        if i % 5 == 0:
            n.set(FLAG_HAS_MIME)
            n.mime = b"application/octet-stream"
        vol.append_needle(n)
    return vol


class S3StubHandler(BaseHTTPRequestHandler):
    """Minimal unsigned S3 endpoint: PUT/GET(Range)/DELETE over an
    in-memory dict — enough surface for the remote-tier backend without
    spinning a whole gateway cluster.  Use `start_s3_stub()`."""

    protocol_version = "HTTP/1.1"
    objects: dict[str, bytes] = {}
    range_reads = 0

    def log_message(self, fmt, *args):
        pass

    def _reply(self, code, body=b"", headers=()):
        self.send_response(code)
        for k, v in headers:
            self.send_header(k, v)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        if body:
            self.wfile.write(body)

    def do_PUT(self):
        length = int(self.headers.get("Content-Length") or 0)
        self.objects[self.path] = self.rfile.read(length)
        self._reply(200, headers=[("ETag", '"stub"')])

    def do_GET(self):
        blob = self.objects.get(self.path)
        if blob is None:
            return self._reply(404)
        rng = self.headers.get("Range")
        if rng and rng.startswith("bytes="):
            type(self).range_reads += 1
            lo, _, hi = rng[len("bytes="):].partition("-")
            lo = int(lo)
            hi = int(hi) if hi else len(blob) - 1
            part = blob[lo:hi + 1]
            return self._reply(206, part, headers=[(
                "Content-Range", f"bytes {lo}-{hi}/{len(blob)}")])
        self._reply(200, blob)

    def do_DELETE(self):
        self.objects.pop(self.path, None)
        self._reply(204)


def start_s3_stub():
    """-> (httpd, handler_class).  handler_class.objects is the live
    object dict ('/bucket/key' -> bytes); handler_class.range_reads
    counts ranged GETs.  Caller shuts down via httpd.shutdown()."""
    handler = type("BoundS3Stub", (S3StubHandler,),
                   {"objects": {}, "range_reads": 0})
    httpd = ThreadingHTTPServer(("127.0.0.1", 0), handler)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    return httpd, handler


_used_ports: set[int] = set()


def free_port() -> int:
    """A free TCP port, never handed out twice in one test session.

    Reuse matters because pb/rpc.py caches one channel per address
    process-wide: a port recycled from an earlier module's dead server
    would serve its stale, backed-off channel to the new one.

    Ports come from 20000-22767: DISJOINT from the kernel's ephemeral
    range (32768-60999) — and so are the derived grpc_port = port+10000
    siblings (30000-32767, ending just below the ephemeral floor; the
    band also keeps them under 65536).  A port-0 server (fake stores,
    FTP PASV sockets) can therefore never squat on a port this function
    later hands a module fixture — a race that made whole modules error
    with 'Failed to bind' roughly once per several full-suite runs."""
    import random
    import socket

    rng = random.Random()
    for _ in range(20000):  # fail loud, never hang, if the band drains
        port = rng.randrange(20000, 22768)
        if port in _used_ports:
            continue
        try:
            with socket.socket() as s:
                s.bind(("127.0.0.1", port))
        except OSError:
            continue
        _used_ports.add(port)
        return port
    raise RuntimeError(
        "free_port: test port band 20000-22767 exhausted or blocked")


def start_master_cluster(base_dir: str, **kw):
    """Start SEAWEEDFS_TPU_TEST_MASTERS in-process masters (default 1)
    and return ``(leader, all_masters)``.

    n=1 reproduces the classic single-master setup exactly (no peers,
    no raft).  n>=3 starts a raft quorum — each master gets its own
    ``lifecycle_dir`` subdirectory under the caller's (the maintenance
    journal is raft-replicated, so the elected leader's view is the
    cluster's) — letting CI re-run the chaos suites against a 3-master
    quorum without a second copy of every test."""
    import os
    import time

    from seaweedfs_tpu.master.server import MasterServer

    n = int(os.environ.get("SEAWEEDFS_TPU_TEST_MASTERS", "1"))
    if n <= 1:
        m = MasterServer(ip="127.0.0.1", port=free_port(), **kw)
        m.start()
        return m, [m]
    ports = [free_port() for _ in range(n)]
    peers = [f"127.0.0.1:{p}" for p in ports]
    raft_dir = os.path.join(base_dir, "raft-state")
    os.makedirs(raft_dir, exist_ok=True)
    masters = []
    for i, p in enumerate(ports):
        mkw = dict(kw)
        if "lifecycle_dir" in mkw:
            d = os.path.join(mkw["lifecycle_dir"], f"m{i}")
            os.makedirs(d, exist_ok=True)
            mkw["lifecycle_dir"] = d
        m = MasterServer(ip="127.0.0.1", port=p, peers=peers,
                         raft_state_dir=raft_dir, **mkw)
        m.start()
        masters.append(m)
    deadline = time.time() + 30
    while time.time() < deadline:
        leaders = [m for m in masters if m.is_leader()]
        if len(leaders) == 1 and masters[0].leader():
            return leaders[0], masters
        time.sleep(0.05)
    raise AssertionError("master quorum elected no leader")
