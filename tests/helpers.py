"""Shared test fixtures: synthetic needle-log volumes."""

from __future__ import annotations

import numpy as np

from seaweedfs_tpu.storage import Needle, SuperBlock
from seaweedfs_tpu.storage.needle import FLAG_HAS_MIME, FLAG_HAS_NAME
from seaweedfs_tpu.storage.volume import Volume


def make_volume(
    directory: str,
    volume_id: int = 1,
    n_needles: int = 50,
    seed: int = 0,
    max_size: int = 2000,
    collection: str = "",
) -> Volume:
    """Create a volume with random needles; returns the open Volume."""
    rng = np.random.default_rng(seed)
    vol = Volume(directory, collection, volume_id, super_block=SuperBlock())
    for i in range(1, n_needles + 1):
        size = int(rng.integers(1, max_size))
        n = Needle(
            cookie=int(rng.integers(0, 2**32)),
            id=i,
            data=rng.integers(0, 256, size).astype(np.uint8).tobytes(),
        )
        if i % 3 == 0:
            n.set(FLAG_HAS_NAME)
            n.name = f"file-{i}.bin".encode()
        if i % 5 == 0:
            n.set(FLAG_HAS_MIME)
            n.mime = b"application/octet-stream"
        vol.append_needle(n)
    return vol


_used_ports: set[int] = set()


def free_port(limit: int = 55000) -> int:
    """A free TCP port whose +10000 gRPC sibling stays below 65536,
    never handed out twice in one test session.

    Every server derives grpc_port = port + 10000; an ephemeral port
    above 55535 silently wraps modulo 65536 inside grpc and dials the
    wrong place.  Reuse matters because pb/rpc.py caches one channel per
    address process-wide: a port recycled from an earlier module's dead
    server would serve its stale, backed-off channel to the new one."""
    import socket

    while True:
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]
        if port <= limit and port not in _used_ports \
                and port + 10000 not in _used_ports:
            _used_ports.add(port)
            _used_ports.add(port + 10000)
            return port
