"""Disk-type (hdd/ssd) topology modeling and volume.tier.move.

Reference: weed/storage/types/volume_disk_type.go ("" == hdd),
master.proto disk_type fields, shell/command_volume_tier_move.go.
Tier-3 pure-placement tests over pb snapshots plus a live ssd->hdd
scenario across two volume servers (VERDICT r4 item 5).
"""

import time
import urllib.error
import urllib.request

import pytest

from seaweedfs_tpu.master.server import MasterServer
from seaweedfs_tpu.pb import master_pb2
from seaweedfs_tpu.pb import rpc as rpclib
from seaweedfs_tpu.pb import volume_server_pb2 as vs_pb
from seaweedfs_tpu.shell.commands import CommandEnv, run_command
from seaweedfs_tpu.shell.volume_commands import (
    collect_volume_ids_for_tier_change,
    pick_tier_move_target,
)
from seaweedfs_tpu.volume.server import VolumeServer


def _free_port() -> int:
    from helpers import free_port

    return free_port()


def _http(method, url, data=None):
    req = urllib.request.Request(url, data=data, method=method)
    try:
        with urllib.request.urlopen(req, timeout=10) as r:
            return r.status, r.read()
    except urllib.error.HTTPError as e:
        return e.code, e.read()


# -- tier-3: pure placement over pb snapshots -------------------------------


def _topo(nodes):
    """nodes: {id: {disk_type: (max, [(vid, size, mtime, dt)...])}}"""
    info = master_pb2.TopologyInfo(id="topo")
    dc = info.data_center_infos.add(id="dc1")
    rack = dc.rack_infos.add(id="r1")
    for node_id, disks in nodes.items():
        dn = rack.data_node_infos.add(id=node_id)
        for dt, (maxv, vols) in disks.items():
            disk = dn.disk_infos[dt]
            disk.max_volume_count = maxv
            disk.volume_count = len(vols)
            for vid, size, mtime in vols:
                disk.volume_infos.add(
                    id=vid, size=size, modified_at_second=mtime,
                    disk_type=dt)
    return info


def test_collect_tier_change_selects_full_quiet_source_tier():
    now = 1_000_000
    limit = 100
    topo = _topo({
        "n1:8080": {"ssd": (5, [
            (1, 96, now - 7200),   # full + quiet on ssd -> selected
            (2, 50, now - 7200),   # not full
            (3, 96, now - 10),     # not quiet
        ])},
        "n2:8080": {"": (5, [
            (4, 96, now - 7200),   # hdd, wrong source tier
        ])},
    })
    got = collect_volume_ids_for_tier_change(
        topo, limit, "ssd", full_percent=95, quiet_for_seconds=3600,
        now=now)
    assert got == [1]
    # hdd source: both spellings select the default tier
    assert collect_volume_ids_for_tier_change(
        topo, limit, "hdd", full_percent=95, quiet_for_seconds=3600,
        now=now) == [4]


def test_pick_tier_move_target_prefers_free_capacity():
    topo = _topo({
        "src:8080": {"ssd": (5, [(7, 96, 0)])},
        "small:8080": {"": (2, [(9, 10, 0)])},
        "big:8080": {"": (10, [])},
        "ssdonly:8080": {"ssd": (10, [])},
    })
    picked = pick_tier_move_target(topo, 7, "hdd")
    assert picked == ("src:8080", "big:8080")
    # no capacity on the target tier -> None
    topo2 = _topo({
        "src:8080": {"ssd": (5, [(7, 96, 0)])},
        "ssdonly:8080": {"ssd": (10, [])},
    })
    assert pick_tier_move_target(topo2, 7, "hdd") is None
    # a node already holding the volume is never the target
    topo3 = _topo({
        "src:8080": {"ssd": (5, [(7, 96, 0)]), "": (10, [])},
    })
    assert pick_tier_move_target(topo3, 7, "hdd") is None


# -- tier-4: live ssd -> hdd move across nodes ------------------------------


@pytest.fixture(scope="module")
def tier_cluster(tmp_path_factory):
    master = MasterServer(ip="127.0.0.1", port=_free_port(),
                          volume_size_limit_mb=64)
    master.start()
    vs_ssd = VolumeServer(
        directories=[str(tmp_path_factory.mktemp("ssdvol"))],
        disk_types=["ssd"],
        master_addresses=[f"127.0.0.1:{master.grpc_port}"],
        ip="127.0.0.1", port=_free_port(), pulse_seconds=0.5,
    )
    vs_ssd.start()
    vs_hdd = VolumeServer(
        directories=[str(tmp_path_factory.mktemp("hddvol"))],
        master_addresses=[f"127.0.0.1:{master.grpc_port}"],
        ip="127.0.0.1", port=_free_port(), pulse_seconds=0.5,
    )
    vs_hdd.start()
    deadline = time.time() + 15
    while time.time() < deadline and len(master.topo.nodes) < 2:
        time.sleep(0.1)
    assert len(master.topo.nodes) == 2
    yield master, vs_ssd, vs_hdd
    vs_hdd.stop()
    vs_ssd.stop()
    master.stop()


def test_volume_tier_move_ssd_to_hdd(tier_cluster):
    master, vs_ssd, vs_hdd = tier_cluster
    # allocate a volume on the ssd tier and write a blob into it
    stub = rpclib.volume_server_stub(f"127.0.0.1:{vs_ssd.grpc_port}")
    stub.AllocateVolume(vs_pb.AllocateVolumeRequest(
        volume_id=77, collection="", replication="000", disk_type="ssd"))
    fid = "77,1deadbeef"
    code, _ = _http("POST", f"http://127.0.0.1:{vs_ssd.port}/{fid}",
                    b"tiered!")
    assert code == 201
    # the heartbeat must carry the ssd disk type into the topology
    deadline = time.time() + 10
    node_ssd = f"127.0.0.1:{vs_ssd.port}"
    while time.time() < deadline:
        with master.topo.lock:
            n = master.topo.nodes.get(node_ssd)
            v = n.volumes.get(77) if n else None
        if v is not None and v.disk_type == "ssd":
            break
        time.sleep(0.2)
    assert v is not None and v.disk_type == "ssd"
    assert n.max_volume_counts.get("ssd")
    snapshot = master.topo.to_topology_info()
    dn = [d for dc in snapshot.data_center_infos for r in dc.rack_infos
          for d in r.data_node_infos if d.id == node_ssd][0]
    assert 77 in [v.id for v in dn.disk_infos["ssd"].volume_infos]

    env = CommandEnv(master_grpc=f"127.0.0.1:{master.grpc_port}")
    out = run_command(
        env,
        "volume.tier.move -volumeId=77 -fromDiskType=ssd "
        "-toDiskType=hdd -force")
    assert "moved volume 77" in out, out

    assert vs_ssd.store.find_volume(77) is None
    moved = vs_hdd.store.find_volume(77)
    assert moved is not None and moved.disk_type == ""
    code, body = _http("GET", f"http://127.0.0.1:{vs_hdd.port}/{fid}")
    assert (code, body) == (200, b"tiered!")

    # same-tier move refuses loudly
    try:
        run_command(env, "volume.tier.move -fromDiskType=hdd "
                         "-toDiskType=hdd")
        raise AssertionError("expected same-tier refusal")
    except RuntimeError as e:
        assert "same as target" in str(e)
