"""Degraded-read plumbing: concurrent recovery fetches, the tiered
shard-location cache, and the chaos suite (fault-injected volume servers
proving replica failover, EC degraded-read fallback, retry metrics and
circuit-breaker state — run with `pytest -m chaos`).

Reference analogues: store_ec.go:324-378 (parallel goroutine fan-out per
source shard) and store_ec.go:223-264 (TTL-tiered location cache with
error/empty distinction).
"""

import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from seaweedfs_tpu.ops.rs_cpu import ReedSolomon
from seaweedfs_tpu.wdclient.location_cache import TieredLocationCache


def test_reconstruct_interval_fetches_concurrently(tmp_path):
    """10 remote interval fetches, each 50ms, must overlap: the degraded
    read completes in ~1 RTT, not 10 sequential RTTs."""
    from seaweedfs_tpu.storage import types as t
    from seaweedfs_tpu.storage.ec.volume import EcVolume

    rs = ReedSolomon()
    rng = np.random.default_rng(3)
    length = 4096
    shards = [rng.integers(0, 256, length, dtype=np.uint8) for _ in range(10)]
    shards += [np.zeros(length, dtype=np.uint8) for _ in range(4)]
    rs.encode(shards)

    base = str(tmp_path / "1")
    # a minimal .ecx with one (never-read) entry so EcVolume can open
    with open(base + ".ecx", "wb") as f:
        f.write(t.pack_index_entry(1, 0, 8))
    ev = EcVolume(base, volume_id=1)

    in_flight = {"now": 0, "max": 0}
    lock = threading.Lock()

    def fetch(shard_id, offset, size):
        if shard_id == 0:
            return None  # the lost shard: force on-the-fly reconstruction
        with lock:
            in_flight["now"] += 1
            in_flight["max"] = max(in_flight["max"], in_flight["now"])
        time.sleep(0.05)
        with lock:
            in_flight["now"] -= 1
        return shards[shard_id][offset : offset + size].tobytes()

    ev.remote_fetch = fetch
    t0 = time.perf_counter()
    got = ev.read_shard_interval(0, 0, length)
    dt = time.perf_counter() - t0
    ev.close()
    assert got == shards[0].tobytes()
    assert in_flight["max"] >= 8, "fetches did not overlap"
    assert dt < 0.4, f"degraded read took {dt:.2f}s — looks sequential"


def test_location_cache_tiers():
    clock = {"t": 0.0}
    upstream = {"value": {0: ["a:1"]}, "fail": False}

    def lookup():
        if upstream["fail"]:
            raise RuntimeError("master down")
        return dict(upstream["value"])

    c = TieredLocationCache(
        lookup, found_ttl=300.0, empty_ttl=11.0, error_retry=2.0,
        clock=lambda: clock["t"],
    )
    # found: trusted for found_ttl without re-lookup
    assert c.get() == {0: ["a:1"]}
    clock["t"] = 299.0
    assert c.get() == {0: ["a:1"]}
    assert c.lookups == 1
    clock["t"] = 301.0
    assert c.get() == {0: ["a:1"]}
    assert c.lookups == 2

    # error: serves stale, backs off error_retry before retrying
    upstream["fail"] = True
    clock["t"] = 700.0
    assert c.get() == {0: ["a:1"]}  # stale, not empty
    assert c.errors == 1
    clock["t"] = 701.0
    c.get()
    assert c.errors == 1  # within error_retry: no new upstream call
    clock["t"] = 703.0
    c.get()
    assert c.errors == 2

    # empty: negative-cached only empty_ttl
    upstream["fail"] = False
    upstream["value"] = {}
    clock["t"] = 710.0
    assert c.get() == {}
    n = c.lookups
    clock["t"] = 715.0
    assert c.get() == {}
    assert c.lookups == n  # within empty_ttl
    upstream["value"] = {1: ["b:2"]}
    clock["t"] = 722.0
    assert c.get() == {1: ["b:2"]}

    # invalidate forces a refresh
    upstream["value"] = {2: ["c:3"]}
    c.invalidate()
    assert c.get() == {2: ["c:3"]}


def test_location_cache_initial_error_returns_empty():
    def lookup():
        raise RuntimeError("never up")

    c = TieredLocationCache(lookup)
    assert c.get() == {}
    assert c.errors == 1


def test_concurrent_degraded_reads_share_file_handles(tmp_path):
    """Shard and .ecx reads use positioned I/O: concurrent needle reads on
    one EcVolume must not corrupt each other (a seek+read pair on the
    shared handle interleaves under load; reference uses ReadAt,
    ec_shard.go:93).  Regression: found by bench --degraded-only."""
    from concurrent.futures import ThreadPoolExecutor

    from seaweedfs_tpu.storage.ec import constants as ecc
    from seaweedfs_tpu.storage.ec.encoder import (
        generate_ec_files,
        write_sorted_file_from_idx,
    )
    from seaweedfs_tpu.storage.ec.volume import EcVolume

    import os
    from helpers import make_volume

    vol = make_volume(str(tmp_path), n_needles=120, seed=9, max_size=60000)
    base = vol.file_name()
    vol.close()
    generate_ec_files(base, codec_name="cpu")
    write_sorted_file_from_idx(base)
    for sid in range(4):
        os.remove(base + ecc.to_ext(sid))

    ev = EcVolume(base, volume_id=1)

    def reader(seed: int) -> int:
        rng = np.random.default_rng(seed)
        ok = 0
        for _ in range(60):
            nid = int(rng.integers(1, 121))
            n = ev.read_needle(nid)
            assert n.id == nid
            ok += 1
        return ok

    with ThreadPoolExecutor(max_workers=8) as pool:
        counts = list(pool.map(reader, range(8)))
    ev.close()
    assert sum(counts) == 8 * 60


# ===========================================================================
# Chaos suite: a real in-process cluster (master + 2 volume servers +
# filer) driven through the public HTTP surface with fault points armed
# via /debug/faults — proving reads fail over to a replica, then to EC
# rebuild, writes survive a dying volume server via retry + re-assign,
# and the circuit breaker for the dead peer opens and recovers, all
# observable in /metrics.
# ===========================================================================


def _http(method: str, url: str, data: bytes | None = None,
          timeout: float = 30.0) -> tuple[int, bytes]:
    req = urllib.request.Request(url, data=data, method=method)
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, r.read()
    except urllib.error.HTTPError as e:
        return e.code, e.read()


def _scrape_metrics(port: int) -> str:
    with urllib.request.urlopen(f"http://127.0.0.1:{port}/metrics",
                                timeout=10) as r:
        return r.read().decode()


def _arm_fault(port: int, name: str, mode: str = "error", count: int = -1,
               delay: float = 0.0, match: str = "") -> dict:
    url = (f"http://127.0.0.1:{port}/debug/faults?set={name}&mode={mode}"
           f"&count={count}&delay={delay}&match={match}")
    with urllib.request.urlopen(url, timeout=10) as r:
        return json.loads(r.read())


def _clear_faults(port: int) -> None:
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/debug/faults?clear=all", timeout=10):
        pass


@pytest.fixture(scope="module")
def chaos_cluster(tmp_path_factory):
    import os

    from helpers import free_port

    from seaweedfs_tpu.filer.server import FilerServer
    from seaweedfs_tpu.master.server import MasterServer
    from seaweedfs_tpu.volume.server import VolumeServer

    # runtime fault arming over HTTP is opt-in (production safety)
    os.environ["SEAWEEDFS_TPU_FAULTS_ENABLED"] = "1"

    master = MasterServer(ip="127.0.0.1", port=free_port(),
                          volume_size_limit_mb=64)
    master.start()
    vols = []
    for i in range(2):
        vs = VolumeServer(
            directories=[str(tmp_path_factory.mktemp(f"cvol{i}"))],
            master_addresses=[f"127.0.0.1:{master.grpc_port}"],
            ip="127.0.0.1", port=free_port(), pulse_seconds=0.5,
            max_volume_count=30,
        )
        vs.start()
        vols.append(vs)
    deadline = time.time() + 15
    while time.time() < deadline and len(master.topo.nodes) < 2:
        time.sleep(0.1)
    assert len(master.topo.nodes) == 2, "volume servers did not register"
    filer = FilerServer(
        masters=[f"127.0.0.1:{master.grpc_port}"],
        ip="127.0.0.1", port=free_port(),
        store="memory",
        max_mb=1,
        default_replication="001",  # two copies: replica failover exists
        chunk_cache_mem_mb=0,  # every read hits volume servers (no cache)
    )
    filer.start()
    yield master, vols, filer
    _clear_faults(filer.port)
    filer.stop()
    for v in vols:
        v.stop()
    master.stop()
    os.environ.pop("SEAWEEDFS_TPU_FAULTS_ENABLED", None)


@pytest.fixture(autouse=True)
def _chaos_hygiene(request):
    """Chaos tests must not leak armed faults or tripped breakers into
    each other (the registries are process-global)."""
    if "chaos" not in request.keywords:
        yield
        return
    from seaweedfs_tpu.util import failsafe, faultpoint

    faultpoint.clear_fault("all")
    failsafe.reset_breakers()
    yield
    faultpoint.clear_fault("all")
    failsafe.reset_breakers()


def _retry_total(rtype: str, op: str, reason: str) -> float:
    from seaweedfs_tpu.util import failsafe

    return failsafe.RETRY_COUNTER.labels(rtype, op, reason).value


@pytest.mark.chaos
def test_chaos_get_fails_over_to_replica(chaos_cluster):
    """One volume server erroring every GET: filer reads must fail over
    to the replica with byte-identical content and visible retry/fault
    metrics."""
    from seaweedfs_tpu.util import faultpoint

    _, vols, filer = chaos_cluster
    base = f"http://127.0.0.1:{filer.port}"
    payload = bytes(np.random.default_rng(11).integers(
        0, 256, 300_000, dtype=np.uint8))
    code, body = _http("PUT", f"{base}/chaos/replica.bin", payload)
    assert code == 201, body

    sick = f"127.0.0.1:{vols[0].port}"
    fired_before = faultpoint.FAULT_COUNTER.labels("volume.http.get").value
    state = _arm_fault(filer.port, "volume.http.get", mode="error",
                       match=sick)
    assert state["armed"]["volume.http.get"]["match"] == sick

    code, got = _http("GET", f"{base}/chaos/replica.bin")
    assert code == 200
    assert got == payload, "failover read must be byte-identical"

    fired_after = faultpoint.FAULT_COUNTER.labels("volume.http.get").value
    metrics = _scrape_metrics(filer.port)
    if fired_after > fired_before:
        # the sick server was actually tried: the retry counter must show
        # the failover and /metrics must expose both families
        assert 'seaweedfs_fault_injected_total{point="volume.http.get"}' \
            in metrics
        assert 'seaweedfs_retry_total{type="filer",op="chunk_read"' \
            in metrics
    else:
        # every chunk location list happened to lead with the healthy
        # replica; force the sick server into the path directly
        from seaweedfs_tpu.operation.upload import download

        entry = filer.filer.find_entry("/chaos/replica.bin")
        fid = entry.chunks[0].file_id
        with pytest.raises(Exception):
            download(f"http://{sick}/{fid}", retries=2)
        assert faultpoint.FAULT_COUNTER.labels("volume.http.get").value \
            > fired_before


@pytest.mark.chaos
def test_chaos_get_survives_slow_replica(chaos_cluster):
    """Latency injection (not death): the read completes correctly even
    when one replica answers slowly."""
    _, vols, filer = chaos_cluster
    base = f"http://127.0.0.1:{filer.port}"
    payload = b"slow-replica-payload " * 4096
    code, _ = _http("PUT", f"{base}/chaos/slow.bin", payload)
    assert code == 201

    _arm_fault(filer.port, "volume.http.get", mode="delay", delay=0.3,
               match=f"127.0.0.1:{vols[0].port}")
    t0 = time.perf_counter()
    code, got = _http("GET", f"{base}/chaos/slow.bin")
    dt = time.perf_counter() - t0
    assert code == 200 and got == payload
    assert dt < 10.0, f"slow-replica read took {dt:.1f}s"


@pytest.mark.chaos
def test_chaos_put_retries_transient_5xx(chaos_cluster):
    """A volume server NACKing a few POSTs: the client PUT must succeed
    through jittered retries (and re-assign if attempts exhaust)."""
    _, _, filer = chaos_cluster
    base = f"http://127.0.0.1:{filer.port}"
    # no match: the first two POST attempts — wherever assigned — 500
    _arm_fault(filer.port, "volume.http.post", mode="error", count=2)
    payload = b"retry-put-payload " * 2048
    before = _retry_total("operation", "upload", "http_500")
    code, body = _http("PUT", f"{base}/chaos/put-retry.bin", payload)
    assert code == 201, body
    assert _retry_total("operation", "upload", "http_500") >= before + 1
    _clear_faults(filer.port)
    code, got = _http("GET", f"{base}/chaos/put-retry.bin")
    assert code == 200 and got == payload


@pytest.mark.chaos
def test_chaos_put_reassigns_when_server_dead(chaos_cluster):
    """A volume server hard-failing every POST for a while: upload_data's
    attempts exhaust and the filer re-assigns until the write lands —
    the acceptance 'PUT succeeds via retry + re-assign' path."""
    from seaweedfs_tpu.util import failsafe

    _, vols, filer = chaos_cluster
    base = f"http://127.0.0.1:{filer.port}"
    sick = f"127.0.0.1:{vols[0].port}"
    # every failed upload attempt consumes exactly one fault count
    # (either a direct POST to the sick server or the healthy primary's
    # replication fan-out to it), so 6 counts = two exhausted upload
    # rounds and a deterministic recovery on the third re-assign.  The
    # breaker is kept out of the way (threshold above the fault count) —
    # its dynamics get their own test below.
    old_thresh = failsafe.BREAKER_FAILURE_THRESHOLD
    failsafe.BREAKER_FAILURE_THRESHOLD = 1000
    failsafe.reset_breakers()
    try:
        _arm_fault(filer.port, "volume.http.post", mode="error", count=6,
                   match=sick)
        payload = bytes(np.random.default_rng(13).integers(
            0, 256, 100_000, dtype=np.uint8))
        before = _retry_total("filer", "upload_chunk", "reassign")
        code, body = _http("PUT", f"{base}/chaos/reassign.bin", payload,
                           timeout=60.0)
        assert code == 201, body
        assert _retry_total("filer", "upload_chunk", "reassign") > before, \
            "the write must have gone through at least one re-assign"
        _clear_faults(filer.port)
        code, got = _http("GET", f"{base}/chaos/reassign.bin")
        assert code == 200 and got == payload
    finally:
        failsafe.BREAKER_FAILURE_THRESHOLD = old_thresh


@pytest.mark.chaos
def test_chaos_breaker_opens_and_recovers(chaos_cluster):
    """Consecutive failures against one peer open its breaker (visible
    as seaweedfs_circuit_state{peer}=1 in /metrics); after the fault
    clears and the reset timeout passes, a probe closes it again."""
    from seaweedfs_tpu.operation.upload import download
    from seaweedfs_tpu.util import failsafe

    _, vols, filer = chaos_cluster
    base = f"http://127.0.0.1:{filer.port}"
    payload = b"breaker-payload " * 1024
    code, _ = _http("PUT", f"{base}/chaos/breaker.bin", payload)
    assert code == 201
    entry = filer.filer.find_entry("/chaos/breaker.bin")
    fid = entry.chunks[0].file_id

    sick = f"127.0.0.1:{vols[0].port}"
    old_thresh = failsafe.BREAKER_FAILURE_THRESHOLD
    old_reset = failsafe.BREAKER_RESET_TIMEOUT
    failsafe.BREAKER_FAILURE_THRESHOLD = 3
    failsafe.BREAKER_RESET_TIMEOUT = 1.0
    # drop breakers created during the PUT above: instances capture the
    # thresholds at creation, and this test needs the shrunk ones
    failsafe.reset_breakers()
    try:
        _arm_fault(filer.port, "volume.http.get", mode="error", match=sick)
        # hammer the sick server directly until its breaker trips
        for _ in range(2):
            with pytest.raises(Exception):
                download(f"http://{sick}/{fid}", retries=3)
        br = failsafe.breaker_for(sick)
        assert br.state == failsafe.OPEN
        assert f'seaweedfs_circuit_state{{peer="{sick}"}} 1.0' \
            in _scrape_metrics(filer.port)
        # open breaker fast-fails without touching the network
        with pytest.raises(failsafe.CircuitOpenError):
            failsafe.call(lambda: b"never reached", op="x", retry_type="t",
                          peer=sick)

        # while the peer is down+open, filer reads still succeed (replica)
        code, got = _http("GET", f"{base}/chaos/breaker.bin")
        assert code == 200 and got == payload

        # recovery: clear the fault, wait out the reset timeout, probe
        _clear_faults(filer.port)
        time.sleep(1.1)
        assert download(f"http://{sick}/{fid}") == payload
        assert failsafe.breaker_for(sick).state == failsafe.CLOSED
        assert f'seaweedfs_circuit_state{{peer="{sick}"}} 0.0' \
            in _scrape_metrics(filer.port)
    finally:
        failsafe.BREAKER_FAILURE_THRESHOLD = old_thresh
        failsafe.BREAKER_RESET_TIMEOUT = old_reset


@pytest.mark.chaos
def test_chaos_rebuild_source_dies_midstream(tmp_path):
    """Rebuild smoke for the pipelined repair plane: a source failing
    mid-rebuild (the `ec.rebuild.read` faultpoint, armed to fire once a
    few slices in) must surface a clean error with every partial .ecNN
    output removed — and the retry, with the fault exhausted, must
    rebuild byte-identical shards.  Exercises the pipeline's
    error/drain paths (prefetch bail-out, writer drain, output cleanup)."""
    import os

    from helpers import make_volume

    from seaweedfs_tpu.storage.ec import constants as ecc
    from seaweedfs_tpu.storage.ec.encoder import generate_ec_files, \
        rebuild_ec_files
    from seaweedfs_tpu.util import faultpoint

    vol = make_volume(str(tmp_path), n_needles=80, seed=23, max_size=4000)
    base = vol.file_name()
    vol.close()
    generate_ec_files(base, large_block_size=10000, small_block_size=100,
                      codec_name="cpu", slice_size=1 << 20)
    originals = {}
    for sid in (0, 1, 12, 13):
        p = base + ecc.to_ext(sid)
        originals[sid] = open(p, "rb").read()
        os.remove(p)

    threads_before = threading.active_count()
    fired_before = faultpoint.FAULT_COUNTER.labels("ec.rebuild.read").value
    # error once: fires while the output files are already open, so the
    # cleanup contract is exercised (test_ec_repair.py additionally
    # kills a remote source several slices in)
    faultpoint.set_fault("ec.rebuild.read", "error", count=1)
    try:
        with pytest.raises(IOError):
            rebuild_ec_files(base, codec_name="cpu", slice_size=1000)
    finally:
        faultpoint.clear_fault("ec.rebuild.read")
    assert faultpoint.FAULT_COUNTER.labels("ec.rebuild.read").value \
        > fired_before
    for sid in originals:
        assert not os.path.exists(base + ecc.to_ext(sid)), \
            f"partial shard {sid} must not survive a failed rebuild"

    # retry with the fault cleared: clean success, byte-identical
    rebuilt = rebuild_ec_files(base, codec_name="cpu", slice_size=1000)
    assert sorted(rebuilt) == sorted(originals)
    for sid, want in originals.items():
        assert open(base + ecc.to_ext(sid), "rb").read() == want
    # the pipeline's prefetch/writer threads drained on BOTH paths
    time.sleep(0.2)
    assert threading.active_count() <= threads_before + 1


@pytest.mark.chaos
def test_chaos_read_falls_back_to_ec_rebuild(chaos_cluster):
    """After the chunk volume is erasure-coded away (original replicas
    deleted), a filer read must still produce byte-identical content by
    reaching an EC shard holder, which rebuilds the needle on the fly."""
    from seaweedfs_tpu.shell.commands import CommandEnv, run_command

    master, vols, filer = chaos_cluster
    base = f"http://127.0.0.1:{filer.port}"
    payload = bytes(np.random.default_rng(17).integers(
        0, 256, 200_000, dtype=np.uint8))
    code, body = _http(
        "PUT",
        f"{base}/chaos/ecfile.bin?collection=chaosec&replication=000",
        payload)
    assert code == 201, body
    entry = filer.filer.find_entry("/chaos/ecfile.bin")
    vid = int(entry.chunks[0].file_id.split(",")[0])

    env = CommandEnv(f"127.0.0.1:{master.grpc_port}")
    out = run_command(env, f"ec.encode -volumeId={vid} -collection=chaosec")
    assert f"ec.encode {vid}" in out
    deadline = time.time() + 30
    while time.time() < deadline:
        if (len(master.topo.lookup_ec_shards(vid)) == 14
                and all(v.store.find_volume(vid) is None for v in vols)):
            break
        time.sleep(0.2)
    assert all(v.store.find_volume(vid) is None for v in vols), \
        "original volume should be gone after ec.encode"

    code, got = _http("GET", f"{base}/chaos/ecfile.bin", timeout=60.0)
    assert code == 200
    assert got == payload, "EC degraded-read fallback must be byte-identical"
