"""Degraded-read plumbing: concurrent recovery fetches and the tiered
shard-location cache.

Reference analogues: store_ec.go:324-378 (parallel goroutine fan-out per
source shard) and store_ec.go:223-264 (TTL-tiered location cache with
error/empty distinction).
"""

import threading
import time

import numpy as np
import pytest

from seaweedfs_tpu.ops.rs_cpu import ReedSolomon
from seaweedfs_tpu.wdclient.location_cache import TieredLocationCache


def test_reconstruct_interval_fetches_concurrently(tmp_path):
    """10 remote interval fetches, each 50ms, must overlap: the degraded
    read completes in ~1 RTT, not 10 sequential RTTs."""
    from seaweedfs_tpu.storage import types as t
    from seaweedfs_tpu.storage.ec.volume import EcVolume

    rs = ReedSolomon()
    rng = np.random.default_rng(3)
    length = 4096
    shards = [rng.integers(0, 256, length, dtype=np.uint8) for _ in range(10)]
    shards += [np.zeros(length, dtype=np.uint8) for _ in range(4)]
    rs.encode(shards)

    base = str(tmp_path / "1")
    # a minimal .ecx with one (never-read) entry so EcVolume can open
    with open(base + ".ecx", "wb") as f:
        f.write(t.pack_index_entry(1, 0, 8))
    ev = EcVolume(base, volume_id=1)

    in_flight = {"now": 0, "max": 0}
    lock = threading.Lock()

    def fetch(shard_id, offset, size):
        if shard_id == 0:
            return None  # the lost shard: force on-the-fly reconstruction
        with lock:
            in_flight["now"] += 1
            in_flight["max"] = max(in_flight["max"], in_flight["now"])
        time.sleep(0.05)
        with lock:
            in_flight["now"] -= 1
        return shards[shard_id][offset : offset + size].tobytes()

    ev.remote_fetch = fetch
    t0 = time.perf_counter()
    got = ev.read_shard_interval(0, 0, length)
    dt = time.perf_counter() - t0
    ev.close()
    assert got == shards[0].tobytes()
    assert in_flight["max"] >= 8, "fetches did not overlap"
    assert dt < 0.4, f"degraded read took {dt:.2f}s — looks sequential"


def test_location_cache_tiers():
    clock = {"t": 0.0}
    upstream = {"value": {0: ["a:1"]}, "fail": False}

    def lookup():
        if upstream["fail"]:
            raise RuntimeError("master down")
        return dict(upstream["value"])

    c = TieredLocationCache(
        lookup, found_ttl=300.0, empty_ttl=11.0, error_retry=2.0,
        clock=lambda: clock["t"],
    )
    # found: trusted for found_ttl without re-lookup
    assert c.get() == {0: ["a:1"]}
    clock["t"] = 299.0
    assert c.get() == {0: ["a:1"]}
    assert c.lookups == 1
    clock["t"] = 301.0
    assert c.get() == {0: ["a:1"]}
    assert c.lookups == 2

    # error: serves stale, backs off error_retry before retrying
    upstream["fail"] = True
    clock["t"] = 700.0
    assert c.get() == {0: ["a:1"]}  # stale, not empty
    assert c.errors == 1
    clock["t"] = 701.0
    c.get()
    assert c.errors == 1  # within error_retry: no new upstream call
    clock["t"] = 703.0
    c.get()
    assert c.errors == 2

    # empty: negative-cached only empty_ttl
    upstream["fail"] = False
    upstream["value"] = {}
    clock["t"] = 710.0
    assert c.get() == {}
    n = c.lookups
    clock["t"] = 715.0
    assert c.get() == {}
    assert c.lookups == n  # within empty_ttl
    upstream["value"] = {1: ["b:2"]}
    clock["t"] = 722.0
    assert c.get() == {1: ["b:2"]}

    # invalidate forces a refresh
    upstream["value"] = {2: ["c:3"]}
    c.invalidate()
    assert c.get() == {2: ["c:3"]}


def test_location_cache_initial_error_returns_empty():
    def lookup():
        raise RuntimeError("never up")

    c = TieredLocationCache(lookup)
    assert c.get() == {}
    assert c.errors == 1


def test_concurrent_degraded_reads_share_file_handles(tmp_path):
    """Shard and .ecx reads use positioned I/O: concurrent needle reads on
    one EcVolume must not corrupt each other (a seek+read pair on the
    shared handle interleaves under load; reference uses ReadAt,
    ec_shard.go:93).  Regression: found by bench --degraded-only."""
    from concurrent.futures import ThreadPoolExecutor

    from seaweedfs_tpu.storage.ec import constants as ecc
    from seaweedfs_tpu.storage.ec.encoder import (
        generate_ec_files,
        write_sorted_file_from_idx,
    )
    from seaweedfs_tpu.storage.ec.volume import EcVolume

    import os
    from helpers import make_volume

    vol = make_volume(str(tmp_path), n_needles=120, seed=9, max_size=60000)
    base = vol.file_name()
    vol.close()
    generate_ec_files(base, codec_name="cpu")
    write_sorted_file_from_idx(base)
    for sid in range(4):
        os.remove(base + ecc.to_ext(sid))

    ev = EcVolume(base, volume_id=1)

    def reader(seed: int) -> int:
        rng = np.random.default_rng(seed)
        ok = 0
        for _ in range(60):
            nid = int(rng.integers(1, 121))
            n = ev.read_needle(nid)
            assert n.id == nid
            ok += 1
        return ok

    with ThreadPoolExecutor(max_workers=8) as pool:
        counts = list(pool.map(reader, range(8)))
    ev.close()
    assert sum(counts) == 8 * 60
