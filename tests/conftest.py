"""Test harness config: run JAX on a virtual 8-device CPU mesh.

Multi-chip hardware is not available in CI; sharding/collective paths are
validated on virtual CPU devices exactly as the driver's dryrun does.
Must run before the first `import jax` anywhere in the test process.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ.setdefault("JAX_ENABLE_X64", "1")
