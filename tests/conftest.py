"""Test harness config: run JAX on a virtual 8-device CPU mesh.

Multi-chip hardware is not available in CI; sharding/collective paths are
validated on virtual CPU devices exactly as the driver's dryrun does.

The environment preloads the jax *module* at interpreter startup, but the
backend is only created on first use — so pinning the platform via
jax.config here (before any test touches a device) still takes effect.

Set JAX_PLATFORMS explicitly (e.g. =tpu) to run the suite against real
hardware instead; the pin below only applies when the var is unset.
"""

import os

_explicit = "JAX_PLATFORMS" in os.environ
if not _explicit:
    os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

if not _explicit:
    try:
        import jax
    except ImportError:
        pass
    else:
        jax.config.update("jax_platforms", "cpu")
