"""Test harness config: run JAX on a virtual 8-device CPU mesh.

Multi-chip hardware is not available in CI; sharding/collective paths are
validated on virtual CPU devices exactly as the driver's dryrun does.

The environment preloads the jax *module* at interpreter startup (and sets
JAX_PLATFORMS=axon ambiently), but the backend is only created on first use —
so pinning the platform via jax.config here (before any test touches a
device) still takes effect.

To run the suite against real hardware instead, set SEAWEEDFS_TPU_TEST_REAL=1
(a dedicated opt-out: the ambient JAX_PLATFORMS can't express user intent).
"""

import os

import pytest


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: long-running tests excluded from the tier-1 run")
    config.addinivalue_line(
        "markers",
        "chaos: fault-injection chaos suite; run separately with -m chaos")


def pytest_collection_modifyitems(config, items):
    # chaos tests imply slow: tier-1 (-m 'not slow') stays fast and
    # deterministic, while `-m chaos` selects exactly the chaos suite
    for item in items:
        if "chaos" in item.keywords:
            item.add_marker(pytest.mark.slow)


if not os.environ.get("SEAWEEDFS_TPU_TEST_REAL"):
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()
    try:
        import jax  # noqa: F401
    except ImportError:
        pass
    else:
        # pins the platform AND drops the axon auto-init hook, which would
        # otherwise hang the whole suite on a wedged tunnel (see docstring
        # of util.jaxenv)
        import sys

        sys.path.insert(0, os.path.dirname(os.path.dirname(__file__)))
        from seaweedfs_tpu.util.jaxenv import force_cpu_backend

        force_cpu_backend()
