"""Manifest chunks + filer chunk cache.

Reference analogues: weed/filer/filechunk_manifest_test.go and the
tiered chunk cache behavior of reader_at.go:88-104.
"""

import socket
import time

import pytest

from seaweedfs_tpu.filer import filechunk_manifest as fcm
from seaweedfs_tpu.filer import filechunks
from seaweedfs_tpu.pb import filer_pb2


def _free_port() -> int:
    from helpers import free_port

    return free_port()


def chunk(fid, offset, size, mtime=1):
    return filer_pb2.FileChunk(file_id=fid, offset=offset, size=size,
                               mtime=mtime)


class BlobStore:
    """In-memory save/fetch pair for unit tests."""

    def __init__(self):
        self.blobs = {}
        self.n = 0

    def save(self, data: bytes) -> filer_pb2.FileChunk:
        self.n += 1
        fid = f"m,{self.n:x}"
        self.blobs[fid] = data
        return filer_pb2.FileChunk(file_id=fid, size=len(data))

    def fetch(self, fid: str) -> bytes:
        return self.blobs[fid]


def test_manifestize_and_resolve_roundtrip():
    store = BlobStore()
    chunks = [chunk(f"1,{i:x}", i * 100, 100, mtime=i) for i in range(10)]
    folded = fcm.maybe_manifestize(store.save, chunks, manifest_batch=4)
    # 10 plain -> 2 manifests of 4 + 2 plain tail
    manifests = [c for c in folded if c.is_chunk_manifest]
    plain = [c for c in folded if not c.is_chunk_manifest]
    assert len(manifests) == 2 and len(plain) == 2
    # manifest chunk spans its batch's byte range
    assert manifests[0].offset == 0 and manifests[0].size == 400
    # resolution returns the full original list
    resolved = fcm.resolve_chunk_manifest(store.fetch, folded)
    assert sorted(c.file_id for c in resolved) == sorted(
        c.file_id for c in chunks
    )
    assert filechunks.total_size(resolved) == 1000
    # short lists pass through untouched
    short = fcm.maybe_manifestize(store.save, chunks[:3], manifest_batch=4)
    assert [c.file_id for c in short] == [c.file_id for c in chunks[:3]]


def test_manifest_of_manifests_resolves():
    store = BlobStore()
    chunks = [chunk(f"1,{i:x}", i * 10, 10) for i in range(16)]
    folded = fcm.maybe_manifestize(store.save, chunks, manifest_batch=4)
    refolded = fcm.maybe_manifestize(store.save, folded, manifest_batch=4)
    resolved = fcm.resolve_chunk_manifest(store.fetch, refolded)
    assert sorted(c.file_id for c in resolved) == sorted(
        c.file_id for c in chunks
    )


def test_manifest_cycle_detected():
    m = filer_pb2.FileChunkManifest()
    mc = chunk("loop,1", 0, 10)
    mc.is_chunk_manifest = True
    m.chunks.append(mc)
    import gzip

    blob = gzip.compress(m.SerializeToString())
    with pytest.raises(IOError):
        fcm.resolve_chunk_manifest(lambda fid: blob, [mc])


# -- live filer with a tiny manifest batch ----------------------------------


@pytest.fixture(scope="module")
def manifest_cluster(tmp_path_factory):
    from seaweedfs_tpu.filer.server import FilerServer
    from seaweedfs_tpu.master.server import MasterServer
    from seaweedfs_tpu.volume.server import VolumeServer

    master = MasterServer(ip="127.0.0.1", port=_free_port(),
                          volume_size_limit_mb=64)
    master.start()
    vs = VolumeServer(
        directories=[str(tmp_path_factory.mktemp("manvol"))],
        master_addresses=[f"127.0.0.1:{master.grpc_port}"],
        ip="127.0.0.1", port=_free_port(), pulse_seconds=0.5,
    )
    vs.start()
    deadline = time.time() + 15
    while time.time() < deadline and len(master.topo.nodes) < 1:
        time.sleep(0.1)
    filer = FilerServer(
        masters=[f"127.0.0.1:{master.grpc_port}"],
        ip="127.0.0.1", port=_free_port(), store="memory",
        max_mb=1,
        manifest_batch=4,  # tiny: a 6MB file manifestizes
        chunk_cache_dir=str(tmp_path_factory.mktemp("fcache")),
    )
    filer.start()
    yield master, vs, filer
    filer.stop()
    vs.stop()
    master.stop()


def _http(method, url, data=None):
    import urllib.error
    import urllib.request

    req = urllib.request.Request(url, data=data, method=method)
    try:
        with urllib.request.urlopen(req, timeout=30) as r:
            return r.status, r.read()
    except urllib.error.HTTPError as e:
        return e.code, e.read()


def test_filer_manifestizes_large_files(manifest_cluster):
    _, _, filer = manifest_cluster
    base = f"http://127.0.0.1:{filer.port}"
    payload = bytes(range(256)) * 24576  # 6MB -> 6 chunks > batch of 4
    code, _ = _http("PUT", f"{base}/big/manifested.bin", payload)
    assert code == 201
    entry = filer.filer.find_entry("/big/manifested.bin")
    manifests = [c for c in entry.chunks if c.is_chunk_manifest]
    assert manifests, "expected the chunk list to be manifestized"
    assert len(entry.chunks) < 6
    # reads resolve through the manifest (and populate the chunk cache)
    code, got = _http("GET", f"{base}/big/manifested.bin")
    assert code == 200 and got == payload
    # ranged read across a manifest boundary
    import urllib.request

    req = urllib.request.Request(
        f"{base}/big/manifested.bin",
        headers={"Range": "bytes=4194204-4194404"},
    )
    with urllib.request.urlopen(req, timeout=30) as r:
        assert r.read() == payload[4194204:4194405]


def test_chunk_cache_hits_counted(manifest_cluster):
    from seaweedfs_tpu.stats.metrics import CHUNK_CACHE_COUNTER

    _, _, filer = manifest_cluster
    base = f"http://127.0.0.1:{filer.port}"
    payload = b"cachable" * 1000
    _http("PUT", f"{base}/c/cached.bin", payload)

    def hits():
        return CHUNK_CACHE_COUNTER.labels("hit").value

    _http("GET", f"{base}/c/cached.bin")
    h0 = hits()
    _http("GET", f"{base}/c/cached.bin")
    assert hits() > h0


def test_mount_reads_manifested_file(manifest_cluster):
    """The mount layer resolves manifest chunks on read too."""
    from seaweedfs_tpu.mount.wfs import WFS

    _, _, filer = manifest_cluster
    base = f"http://127.0.0.1:{filer.port}"
    payload = bytes(range(256)) * 24576  # 6MB, manifestized
    _http("PUT", f"{base}/mnt/m.bin", payload)
    w = WFS(filer_grpc=f"127.0.0.1:{filer.grpc_port}",
            filer_http=f"127.0.0.1:{filer.port}", chunk_size_mb=1)
    h = w.open("/mnt/m.bin")
    assert h.read(0, len(payload)) == payload
    assert h.read((3 << 20) - 10, 20) == payload[(3 << 20) - 10 : (3 << 20) + 10]
    w.release(h)
    w.close()


def test_manifest_gc_preserves_inner_chunks(manifest_cluster):
    """Overwriting a manifestized file must delete the manifest AND its
    inner chunks, while a rewrite folding chunks into a manifest must NOT
    delete the live inner chunks."""
    _, _, filer = manifest_cluster
    base = f"http://127.0.0.1:{filer.port}"
    payload = bytes(range(256)) * 24576  # 6MB, manifestized
    _http("PUT", f"{base}/gc/f.bin", payload)
    code, got = _http("GET", f"{base}/gc/f.bin")
    assert code == 200 and got == payload

    # overwrite with new content: the old manifest + inner chunks become
    # garbage; data must still read back correctly afterwards
    payload2 = bytes(reversed(range(256))) * 24576
    _http("PUT", f"{base}/gc/f.bin", payload2)
    code, got = _http("GET", f"{base}/gc/f.bin")
    assert code == 200 and got == payload2

    # delete: queue drains without failing and the entry is gone
    code, _ = _http("DELETE", f"{base}/gc/f.bin")
    assert code in (200, 202, 204)
    assert filer.filer.find_entry("/gc/f.bin") is None
