"""Multi-filer metadata federation: two filers with separate stores
converge via SubscribeLocalMetadata + MetaAggregator replay.

Reference: weed/filer/meta_aggregator.go, filer.proto SubscribeLocalMetadata.
"""

from __future__ import annotations

import socket
import time

import pytest

from seaweedfs_tpu.pb import filer_pb2
from seaweedfs_tpu.pb import rpc as rpclib
from seaweedfs_tpu.s3api.filer_client import FilerClient


def _free_port() -> int:
    from helpers import free_port

    return free_port()


@pytest.fixture(scope="module")
def federation(tmp_path_factory):
    from seaweedfs_tpu.filer.server import FilerServer
    from seaweedfs_tpu.master.server import MasterServer
    from seaweedfs_tpu.volume.server import VolumeServer

    master = MasterServer(ip="127.0.0.1", port=_free_port(),
                          volume_size_limit_mb=64)
    master.start()
    vs = VolumeServer(
        directories=[str(tmp_path_factory.mktemp("fedvol"))],
        master_addresses=[f"127.0.0.1:{master.grpc_port}"],
        ip="127.0.0.1", port=_free_port(), pulse_seconds=0.5,
        max_volume_count=100,
    )
    vs.start()
    deadline = time.time() + 15
    while time.time() < deadline and len(master.topo.nodes) < 1:
        time.sleep(0.1)

    pa, pb = _free_port(), _free_port()
    fa = FilerServer(
        masters=[f"127.0.0.1:{master.grpc_port}"],
        ip="127.0.0.1", port=pa, store="memory", max_mb=1,
        peers=[f"127.0.0.1:{pb}"],
    )
    fb = FilerServer(
        masters=[f"127.0.0.1:{master.grpc_port}"],
        ip="127.0.0.1", port=pb, store="memory", max_mb=1,
        peers=[f"127.0.0.1:{pa}"],
    )
    fa.start()
    fb.start()
    yield fa, fb
    fb.stop()
    fa.stop()
    vs.stop()
    master.stop()


def _wait_entry(client: FilerClient, directory: str, name: str,
                timeout: float = 15.0):
    from seaweedfs_tpu.s3api.filer_client import FilerUnavailable

    deadline = time.time() + timeout
    while time.time() < deadline:
        try:
            e = client.find_entry(directory, name)
        except FilerUnavailable:
            # each filer's aggregator dialed its peer before that peer
            # was listening; the shared channel cache is in reconnect
            # backoff for a moment
            e = None
        if e is not None:
            return e
        time.sleep(0.2)
    return None


def test_namespaces_converge_both_ways(federation):
    fa, fb = federation
    ca = FilerClient(f"127.0.0.1:{fa.port}")
    cb = FilerClient(f"127.0.0.1:{fb.port}")

    # distinct stores: different signatures drive replication
    assert fa.signature != fb.signature

    ca.put_object("/fed/a-born.txt", b"written on A")
    cb.put_object("/fed/b-born.txt", b"written on B")

    # each side sees the other's write (metadata replayed via aggregator)
    ea = _wait_entry(cb, "/fed", "a-born.txt")
    eb = _wait_entry(ca, "/fed", "b-born.txt")
    assert ea is not None, "B never saw A's entry"
    assert eb is not None, "A never saw B's entry"

    # the chunks reference the same blobs, so bytes read through EITHER
    # filer are identical
    code, _, body = cb.get_object("/fed/a-born.txt")
    assert code == 200 and body == b"written on A"
    code, _, body = ca.get_object("/fed/b-born.txt")
    assert code == 200 and body == b"written on B"


def test_deletes_propagate(federation):
    fa, fb = federation
    ca = FilerClient(f"127.0.0.1:{fa.port}")
    cb = FilerClient(f"127.0.0.1:{fb.port}")
    ca.put_object("/fed/del-me.txt", b"x")
    assert _wait_entry(cb, "/fed", "del-me.txt") is not None
    # delete on B (metadata only: blobs are shared, A's replay must not
    # double-free) and verify A converges
    cb.delete_entry("/fed", "del-me.txt", is_delete_data=False)
    deadline = time.time() + 15
    while time.time() < deadline:
        if ca.find_entry("/fed", "del-me.txt") is None:
            break
        time.sleep(0.2)
    assert ca.find_entry("/fed", "del-me.txt") is None


def test_subscribe_metadata_is_merged_stream(federation):
    fa, fb = federation
    ca = FilerClient(f"127.0.0.1:{fa.port}")
    cb = FilerClient(f"127.0.0.1:{fb.port}")
    since = time.time_ns()
    ca.put_object("/merge/on-a.txt", b"1")
    cb.put_object("/merge/on-b.txt", b"2")
    # A's public SubscribeMetadata must carry BOTH events
    stub = rpclib.filer_stub(f"127.0.0.1:{fa.grpc_port}")
    stream = stub.SubscribeMetadata(
        filer_pb2.SubscribeMetadataRequest(
            client_name="test", path_prefix="/merge", since_ns=since),
        timeout=20,
    )
    seen = set()
    for resp in stream:
        seen.add(resp.event_notification.new_entry.name)
        if {"on-a.txt", "on-b.txt"} <= seen:
            break
    assert {"on-a.txt", "on-b.txt"} <= seen


def test_directory_delete_and_rename_propagate(federation):
    """Recursive deletes and directory renames emit ONE event for the
    directory; the replica must mirror the whole subtree."""
    fa, fb = federation
    ca = FilerClient(f"127.0.0.1:{fa.port}")
    cb = FilerClient(f"127.0.0.1:{fb.port}")

    ca.put_object("/tree/sub/deep.txt", b"deep")
    assert _wait_entry(cb, "/tree/sub", "deep.txt") is not None

    # rename the whole directory on A
    ca.stub().AtomicRenameEntry(filer_pb2.AtomicRenameEntryRequest(
        old_directory="/", old_name="tree",
        new_directory="/", new_name="forest"))
    e = _wait_entry(cb, "/forest/sub", "deep.txt")
    assert e is not None, "renamed subtree child missing on replica"
    deadline = time.time() + 10
    while time.time() < deadline and cb.find_entry("/tree", "sub"):
        time.sleep(0.2)
    assert cb.find_entry("/tree", "sub") is None

    # recursive delete on A drops the subtree on B too
    ca.delete_entry("/", "forest", is_delete_data=False, is_recursive=True)
    deadline = time.time() + 10
    while time.time() < deadline and cb.find_entry("/forest/sub", "deep.txt"):
        time.sleep(0.2)
    assert cb.find_entry("/forest/sub", "deep.txt") is None


def test_new_peer_bootstraps_preexisting_namespace(federation, tmp_path_factory):
    """A filer joining AFTER entries already exist must converge on them:
    SubscribeLocalMetadata snapshots the store when the requested history
    predates the in-memory log."""
    from seaweedfs_tpu.filer.server import FilerServer

    fa, fb = federation
    ca = FilerClient(f"127.0.0.1:{fa.port}")
    ca.put_object("/boot/old.txt", b"pre-existing")

    pc = _free_port()
    fc = FilerServer(
        masters=fa.masters, ip="127.0.0.1", port=pc,
        store="memory", max_mb=1,
        peers=[f"127.0.0.1:{fa.port}"],
    )
    fc.start()
    try:
        cc = FilerClient(f"127.0.0.1:{pc}")
        e = _wait_entry(cc, "/boot", "old.txt")
        assert e is not None, "late joiner never bootstrapped the namespace"
        code, _, body = cc.get_object("/boot/old.txt")
        assert code == 200 and body == b"pre-existing"
    finally:
        fc.stop()
