"""Sharded-mesh codec tests on the virtual 8-device CPU mesh."""

import numpy as np

import jax

from seaweedfs_tpu.ops import gf256
from seaweedfs_tpu.ops.rs_cpu import ReedSolomon
from seaweedfs_tpu.parallel.mesh import (
    batch_encode_sharded,
    distributed_reconstruct,
    make_mesh,
)


def test_eight_virtual_devices():
    assert len(jax.devices()) == 8


def test_make_mesh_shapes():
    mesh = make_mesh()
    assert mesh.shape["dp"] * mesh.shape["sp"] == 8
    assert mesh.shape["dp"] == 2


def test_batch_encode_sharded_matches_cpu():
    mesh = make_mesh()
    rng = np.random.default_rng(0)
    v, b = 4, 512  # divisible by dp=2, sp=4
    volumes = rng.integers(0, 256, (v, 10, b)).astype(np.uint8)
    parity = np.asarray(batch_encode_sharded(mesh, volumes))
    rs = ReedSolomon()
    for vi in range(v):
        shards = [volumes[vi, i] for i in range(10)] + [
            np.zeros(b, dtype=np.uint8) for _ in range(4)
        ]
        rs.encode(shards)
        for i in range(4):
            assert np.array_equal(parity[vi, i], shards[10 + i])


def test_distributed_reconstruct_psum():
    mesh = make_mesh()
    rng = np.random.default_rng(1)
    b = 256
    rs = ReedSolomon()
    shards = [rng.integers(0, 256, b).astype(np.uint8) for _ in range(10)] + [
        np.zeros(b, dtype=np.uint8) for _ in range(4)
    ]
    rs.encode(shards)
    # lose shards 0,2,11,13 -> decode data from 10 survivors
    present = [1, 3, 4, 5, 6, 7, 8, 9, 10, 12]
    dec = gf256.decode_matrix_for(gf256.rs_matrix(10, 14), 10, present)
    survivors = np.stack([shards[i] for i in present])
    rebuilt = np.asarray(distributed_reconstruct(mesh, dec, survivors))
    for i in range(10):
        assert np.array_equal(rebuilt[i], shards[i]), f"data shard {i}"


def test_graft_entry_contract():
    import __graft_entry__ as g

    fn, args = g.entry()
    out = jax.jit(fn)(*args)
    assert out.shape == (4, args[0].shape[1])
    assert out.dtype == np.uint8
    g.dryrun_multichip(8)


def test_ec_encode_selection_full_and_quiet():
    """collect_volume_ids_for_ec_encode: full-percent threshold, collection
    filter, and the -quietFor window over modified_at_second (pure tier-3
    logic, command_ec_encode.go collectVolumeIdsForEcEncode)."""
    from seaweedfs_tpu.pb import master_pb2
    from seaweedfs_tpu.shell.ec_commands import (
        collect_volume_ids_for_ec_encode,
    )

    now = 10_000
    topo = master_pb2.TopologyInfo(id="topo")
    dn = (topo.data_center_infos.add(id="dc")
          .rack_infos.add(id="r").data_node_infos.add(id="n1"))
    disk = dn.disk_infos[""]
    # (vid, size, collection, modified_at)
    for vid, size, coll, mod in (
        (1, 95, "a", now - 7200),   # full + quiet -> selected
        (2, 95, "a", now - 60),     # full but ACTIVE -> skipped by quietFor
        (3, 10, "a", now - 7200),   # quiet but not full -> skipped
        (4, 95, "b", now - 7200),   # wrong collection when filtered
    ):
        disk.volume_infos.add(id=vid, size=size, collection=coll,
                              modified_at_second=mod)

    got = collect_volume_ids_for_ec_encode(
        topo, volume_size_limit=100, full_percent=90, collection="a",
        quiet_for_seconds=3600, now=now)
    assert got == [1]
    # without the quiet window the active volume is selected too
    got = collect_volume_ids_for_ec_encode(
        topo, volume_size_limit=100, full_percent=90, collection="a",
        quiet_for_seconds=0, now=now)
    assert got == [1, 2]
    # no collection filter picks up 'b' as well
    got = collect_volume_ids_for_ec_encode(
        topo, volume_size_limit=100, full_percent=90,
        quiet_for_seconds=3600, now=now)
    assert got == [1, 4]


def test_batch_generate_ec_files_byte_identical(tmp_path):
    """BASELINE config 4 as a file flow: three volumes of different sizes
    batch-encode through one mesh-sharded dispatch per step, and every
    shard file is byte-identical to the serial per-volume encoder."""
    import os

    import numpy as np

    from seaweedfs_tpu.parallel.batch import batch_generate_ec_files
    from seaweedfs_tpu.parallel.mesh import make_mesh
    from seaweedfs_tpu.storage.ec import constants as ecc
    from seaweedfs_tpu.storage.ec.encoder import generate_ec_files

    LARGE, SMALL = 10000, 100
    rng = np.random.default_rng(5)
    bases = []
    for i, size in enumerate((25_000, 7_333, 41_017)):  # deliberately odd
        base = str(tmp_path / f"v{i}")
        with open(base + ".dat", "wb") as f:
            f.write(rng.integers(0, 256, size, dtype=np.uint8).tobytes())
        bases.append(base)

    # serial reference shards
    expect = {}
    for base in bases:
        generate_ec_files(base, large_block_size=LARGE,
                          small_block_size=SMALL, slice_size=512)
        for i in range(ecc.TOTAL_SHARDS):
            p = base + ecc.to_ext(i)
            expect[p] = open(p, "rb").read()
            os.remove(p)

    seen = []
    batch_generate_ec_files(
        bases, mesh=make_mesh(), large_block_size=LARGE,
        small_block_size=SMALL, slice_size=512,
        progress=seen.append)
    assert seen and seen[-1] == sum(
        os.path.getsize(b + ".dat") for b in bases), seen[-3:]
    for base in bases:
        for i in range(ecc.TOTAL_SHARDS):
            p = base + ecc.to_ext(i)
            assert open(p, "rb").read() == expect[p], f"{p} differs"


def test_balanced_ec_distribution_scenarios():
    """The reference's shell/command_ec_test.go scenarios as pure tier-3
    checks: fresh capacity spreads evenly, an uneven cluster leans on the
    freest nodes, and insufficient capacity refuses loudly."""
    import pytest as _pytest

    from seaweedfs_tpu.topology.placement import balanced_ec_distribution

    # even capacity: 14 shards over 3 equal nodes -> 5/5/4 split
    plan = balanced_ec_distribution({"a": 50, "b": 50, "c": 50})
    sizes = sorted(len(s) for s in plan.values())
    assert sizes == [4, 5, 5]
    assert sorted(sid for s in plan.values() for sid in s) == list(range(14))

    # uneven capacity: the constrained node takes no more than its slots
    plan = balanced_ec_distribution({"small": 2, "big1": 50, "big2": 50})
    assert len(plan.get("small", [])) <= 2
    assert sum(len(s) for s in plan.values()) == 14

    # a node with zero slots is never used
    plan = balanced_ec_distribution({"full": 0, "ok": 20})
    assert "full" not in plan
    assert len(plan["ok"]) == 14

    # insufficient total capacity refuses instead of over-packing
    with _pytest.raises(ValueError):
        balanced_ec_distribution({"a": 5, "b": 5})


def test_mesh_rebuild_ec_files_byte_identical(tmp_path):
    """The file-level distributed rebuild (BASELINE config 3 at scale):
    lose the 4 FIRST data shards (worst case, full decode-matrix inversion)
    plus a parity shard, rebuild through the dp-psum decode matmul, and
    every regenerated shard file is byte-identical to the originals."""
    import os

    import numpy as np

    from seaweedfs_tpu.parallel.batch import mesh_rebuild_ec_files
    from seaweedfs_tpu.parallel.mesh import make_mesh
    from seaweedfs_tpu.storage.ec import constants as ecc
    from seaweedfs_tpu.storage.ec.encoder import generate_ec_files

    rng = np.random.default_rng(9)
    base = str(tmp_path / "v")
    with open(base + ".dat", "wb") as f:
        f.write(rng.integers(0, 256, 33_077, dtype=np.uint8).tobytes())
    generate_ec_files(base, large_block_size=10000, small_block_size=100,
                      slice_size=512)
    mesh = make_mesh()
    for lost in ([0, 1, 2, 3],   # worst case: full decode-matrix inversion
                 [7, 11, 13]):   # data + parity mix (composed parity rows)
        expect = {}
        for i in lost:
            p = base + ecc.to_ext(i)
            expect[p] = open(p, "rb").read()
            os.remove(p)
        seen = []
        rebuilt = mesh_rebuild_ec_files(base, mesh=mesh, slice_size=511,
                                        progress=seen.append)
        assert rebuilt == lost
        shard_size = os.path.getsize(base + ecc.to_ext(4))
        assert seen and seen[-1] == shard_size
        for p, want in expect.items():
            assert open(p, "rb").read() == want, f"{p} differs"
