"""Dead-node mass repair (ISSUE 11): exposure ranking, target
spreading bounds, the cross-volume batched partial transport (byte
identity, coalescing, per-volume fallback on source death), orchestrator
planning over a live topology snapshot, crash-safe journal resume, and
the scrub-pass / mass-repair mutual exclusion."""

import hashlib
import os
import threading
import time
from concurrent.futures import ThreadPoolExecutor

import pytest

from seaweedfs_tpu.maintenance.mass_repair import (
    exposure_class,
    rank_by_exposure,
)
from seaweedfs_tpu.stats.metrics import (
    EC_PARTIAL_FALLBACK,
    EC_PARTIAL_JOBS,
    REPAIR_BATCH_JOBS,
)
from seaweedfs_tpu.storage.ec import constants as ecc
from seaweedfs_tpu.storage.ec import partial as P
from seaweedfs_tpu.storage.ec.encoder import (
    generate_ec_files,
    rebuild_ec_files,
    write_sorted_file_from_idx,
)
from seaweedfs_tpu.storage.ec.shard_bits import ShardBits
from seaweedfs_tpu.topology.placement import spread_rebuild_targets
from seaweedfs_tpu.topology.topology import DataNode
from seaweedfs_tpu.util import faultpoint

from helpers import free_port, make_volume

LARGE = 10000
SMALL = 100


# -- pure planning --------------------------------------------------------


def test_rank_by_exposure_floor_first():
    """Volumes one shard from data loss (10 surviving) schedule strictly
    before every healthier volume, regardless of size."""
    vols = [
        {"volume_id": 1, "surviving": 13, "shard_size": 999999},
        {"volume_id": 2, "surviving": 10, "shard_size": 1},
        {"volume_id": 3, "surviving": 12, "shard_size": 5},
        {"volume_id": 4, "surviving": 10, "shard_size": 777},
        {"volume_id": 5, "surviving": 11, "shard_size": 123456},
    ]
    ranked = rank_by_exposure(vols)
    assert [v["volume_id"] for v in ranked][:2] == [4, 2]  # floor first,
    # bigger shard (more bytes at risk) breaks the tie
    assert [v["surviving"] for v in ranked] == [10, 10, 11, 12, 13]


def test_exposure_class_labels():
    assert exposure_class(9) == "lost"
    assert exposure_class(10) == "0"
    assert exposure_class(11) == "1"
    assert exposure_class(13) == "3"
    assert exposure_class(14) == "3"  # clamped: healthy never planned


def test_spread_targets_respects_cap():
    """N volumes over alive nodes: no node gets more than
    ceil(N/alive)+1 assignments, even when every volume prefers the
    same holder."""
    import math

    n_vols, nodes = 20, {f"n{i}:80": 100 for i in range(4)}
    vols = [{"volume_id": v, "surviving": 10,
             # every volume's shards live mostly on n0 — without the cap
             # n0 would take the whole batch
             "holders": {"n0:80": 9, "n1:80": 1}}
            for v in range(n_vols)]
    targets = spread_rebuild_targets(vols, nodes)
    assert len(targets) == n_vols
    cap = math.ceil(n_vols / len(nodes)) + 1
    per_node: dict = {}
    for t in targets.values():
        per_node[t] = per_node.get(t, 0) + 1
    assert max(per_node.values()) <= cap, per_node


def test_spread_targets_prefers_surviving_holders():
    """Within the cap, the node already holding the most surviving
    shards wins (its plan columns are local, off the wire)."""
    nodes = {"a:80": 10, "b:80": 10}
    vols = [{"volume_id": 1, "holders": {"b:80": 7, "a:80": 3}}]
    assert spread_rebuild_targets(vols, nodes) == {1: "b:80"}


def test_spread_targets_skips_full_nodes():
    """A node with zero free EC slots never gets a rebuild it cannot
    store, even when it holds the most surviving shards — unless every
    node is full (then the rebuild itself surfaces the no-space)."""
    vols = [{"volume_id": 1, "holders": {"full:80": 9, "ok:80": 1}}]
    assert spread_rebuild_targets(
        vols, {"full:80": 0, "ok:80": 5}) == {1: "ok:80"}
    assert spread_rebuild_targets(
        vols, {"full:80": 0, "alsofull:80": 0}) in (
        {1: "full:80"}, {1: "alsofull:80"})


# -- cross-volume batched transport ---------------------------------------


@pytest.fixture()
def multi_volume_fleet(tmp_path):
    """4 encoded volumes spread over 5 fake source nodes on 2 racks;
    each volume is missing shard (vid % 14) cluster-wide."""
    n_src = 5
    nodes: dict = {}
    holders_of: dict = {}
    bases: dict = {}
    digests: dict = {}
    for v in range(1, 5):
        d = tmp_path / f"v{v}"
        d.mkdir()
        vol = make_volume(str(d), volume_id=v, n_needles=30, seed=v,
                          max_size=2500)
        base = vol.file_name()
        vol.close()
        generate_ec_files(base, large_block_size=LARGE,
                          small_block_size=SMALL, codec_name="cpu",
                          slice_size=1 << 20)
        write_sorted_file_from_idx(base)
        lost = v % ecc.TOTAL_SHARDS
        digests[v] = hashlib.sha256(
            open(base + ecc.to_ext(lost), "rb").read()).hexdigest()
        bases[v] = base
        holders: dict = {}
        for sid in range(ecc.TOTAL_SHARDS):
            if sid == lost:
                continue
            addr = f"mass-src-{sid % n_src}:0"
            nodes.setdefault(addr, {}).setdefault(v, (base, []))[1].append(
                sid)
            holders.setdefault(sid, []).append(
                (addr, f"rack{(sid % n_src) % 2}", "dc1"))
        holders_of[v] = holders
    stub_for = P.local_source_network(nodes)
    return stub_for, holders_of, bases, digests


def _batched_rebuild(tmp_path, stub_for, holders_of, bases, digests,
                     session, vids, slice_size=1000, with_fallback=False):
    results = {}

    def one(v):
        rdir = tmp_path / f"r{v}"
        rdir.mkdir(exist_ok=True)
        rbase = str(rdir / str(v))
        holders = holders_of[v]
        client = P.BatchedPartialClient(
            session, v, "", lambda h=holders: h, stub_for,
            my_rack="rack0", my_dc="dc1",
            shard_size_hint=os.path.getsize(
                bases[v] + ecc.to_ext((v + 1) % ecc.TOTAL_SHARDS)))
        kw = {}
        if with_fallback:
            lost = v % ecc.TOTAL_SHARDS

            def fetch(sid, off, length, v=v, lost=lost):
                if sid == lost:
                    return None
                with open(bases[v] + ecc.to_ext(sid), "rb") as f:
                    f.seek(off)
                    return f.read(length)

            kw["remote_fetch"] = fetch
        rebuilt = rebuild_ec_files(rbase, codec_name="cpu",
                                   slice_size=slice_size, partial=client,
                                   **kw)
        got = hashlib.sha256(
            open(rbase + ecc.to_ext(v % ecc.TOTAL_SHARDS),
                 "rb").read()).hexdigest()
        results[v] = (rebuilt, got)

    with ThreadPoolExecutor(max_workers=len(vids)) as pool:
        list(pool.map(one, vids))
    for v in vids:
        rebuilt, got = results[v]
        assert rebuilt == [v % ecc.TOTAL_SHARDS], (v, rebuilt)
        assert got == digests[v], f"volume {v} not byte-identical"


def test_batched_rebuild_byte_identity(tmp_path, multi_volume_fleet):
    """4 volumes rebuilt concurrently through one MassPartialSession:
    byte-identical outputs, and the rack-group jobs coalesce into fewer
    rpcs than the per-volume path would issue."""
    stub_for, holders_of, bases, digests = multi_volume_fleet
    session = P.MassPartialSession(stub_for)
    try:
        before = EC_PARTIAL_JOBS.labels("fetch", "ok").value
        _batched_rebuild(tmp_path, stub_for, holders_of, bases, digests,
                         session, [1, 2, 3, 4])
        assert EC_PARTIAL_JOBS.labels("fetch", "ok").value >= before + 4
        # every per-volume fetch succeeded through the session
        assert session.batched_jobs >= session.rpcs
        assert session.rpcs >= 1
    finally:
        session.close()


def test_batched_rebuild_multi_slice(tmp_path, multi_volume_fleet):
    """Shards larger than the slice: successive slices of one volume
    must not merge into one rpc (frames are keyed by volume id), and
    output stays byte-identical."""
    stub_for, holders_of, bases, digests = multi_volume_fleet
    session = P.MassPartialSession(stub_for)
    try:
        _batched_rebuild(tmp_path, stub_for, holders_of, bases, digests,
                         session, [1, 2], slice_size=257)
    finally:
        session.close()


def test_batch_source_death_falls_back_per_volume(tmp_path,
                                                  multi_volume_fleet):
    """faultpoint repair.batch.source scoped to ONE volume's batch job:
    exactly that volume degrades to the full-fetch path (fallback
    counter +1), the rest of the batch rides the aggregated protocol,
    and every output is byte-identical."""
    stub_for, holders_of, bases, digests = multi_volume_fleet
    session = P.MassPartialSession(stub_for)
    faultpoint.set_fault("repair.batch.source", "error", match="vol=3")
    try:
        before_fb = EC_PARTIAL_FALLBACK.labels("rebuild").value
        _batched_rebuild(tmp_path, stub_for, holders_of, bases, digests,
                         session, [1, 2, 3, 4], with_fallback=True)
        assert EC_PARTIAL_FALLBACK.labels("rebuild").value == before_fb + 1
    finally:
        faultpoint.clear_fault("repair.batch.source")
        session.close()


def test_session_coalesces_waves():
    """While one rpc is in flight, queued jobs pile into the NEXT wave:
    a blocking first rpc forces jobs 2-4 into one batch rpc."""
    import numpy as np

    gate = threading.Event()
    first_started = threading.Event()
    batch_sizes = []

    class _Stub:
        def VolumeEcShardPartialApply(self, request):
            batch_sizes.append(len(request.batch))
            if len(batch_sizes) == 1:
                first_started.set()
                gate.wait(timeout=10)
            for job in request.batch:
                blob = bytes(job.row_count * job.size)
                yield type("R", (), {
                    "volume_id": job.volume_id, "data": blob,
                    "eof": False, "error": ""})()
                yield type("R", (), {
                    "volume_id": job.volume_id, "data": b"",
                    "eof": True, "error": ""})()

    session = P.MassPartialSession(lambda addr: _Stub())

    def job(vid):
        return {"volume_id": vid, "collection": "", "offset": 0,
                "size": 8, "row_count": 1, "shard_ids": [1],
                "coefficients": b"\x01", "delegates": []}

    try:
        f1 = session.submit("a:0", job(1))
        assert first_started.wait(timeout=10)
        fs = [session.submit("a:0", job(v)) for v in (2, 3, 4)]
        gate.set()
        assert isinstance(f1.result(timeout=10), np.ndarray)
        for f in fs:
            f.result(timeout=10)
        assert batch_sizes[0] == 1
        assert 3 in batch_sizes, batch_sizes  # jobs 2-4 rode one rpc
    finally:
        session.close()


# -- orchestrator over a topology snapshot --------------------------------


def _fake_master(tmp_path, journal=True):
    from seaweedfs_tpu.master.server import MasterServer

    jd = ""
    if journal:
        jd = str(tmp_path / "journal")
        os.makedirs(jd, exist_ok=True)
    return MasterServer(ip="127.0.0.1", port=free_port(),
                        volume_size_limit_mb=64, lifecycle_dir=jd)


def _bits(*sids):
    b = ShardBits(0)
    for s in sids:
        b = b.add(s)
    return b


def _register(master, node_id, rack, ec):
    """ec: {vid: (shard_ids, shard_size)}"""
    n = DataNode(id=node_id, public_url=node_id,
                 grpc_address=node_id, rack=rack, data_center="dc1",
                 max_volumes=100)
    n.ec_shards = {vid: _bits(*sids) for vid, (sids, _sz) in ec.items()}
    n.ec_collections = {vid: "" for vid in ec}
    n.ec_shard_sizes = {vid: sz for vid, (_sids, sz) in ec.items()}
    master.topo.register_node(n)
    return n


def test_orchestrator_plan_ranks_and_spreads(tmp_path):
    """Live-topology planning: the volume at the decode floor plans
    first, targets never exceed the cap, unrepairable volumes are
    reported not planned."""
    master = _fake_master(tmp_path, journal=False)
    # volume 1: 13 surviving (lost 1 shard), volume 2: 10 surviving,
    # volume 3: 9 surviving (below floor -> unrepairable)
    _register(master, "a:80", "r0", {
        1: (list(range(0, 7)), 100),
        2: (list(range(0, 5)), 999),
        3: (list(range(0, 5)), 5),
    })
    _register(master, "b:80", "r1", {
        1: (list(range(7, 13)), 100),
        2: (list(range(5, 10)), 999),
        3: (list(range(5, 9)), 5),
    })
    plans = master.mass_repair.plan(dead_node="dead:80")
    assert [p["volume_id"] for p in plans] == [2, 1]  # floor first
    assert plans[0]["surviving"] == 10
    assert plans[0]["shard_size"] == 999
    assert plans[0]["bytes"] == 4 * 999
    assert all(p["node"] in ("a:80", "b:80") for p in plans)
    assert master.mass_repair._counts["unrepairable"] == 1


def test_orchestrator_journal_resume_exactly_once(tmp_path):
    """Jobs journaled by a first master run (killed before execution)
    replay as pending in a second run and execute exactly once."""
    master1 = _fake_master(tmp_path)
    _register(master1, "a:80", "r0", {1: (list(range(0, 7)), 64)})
    _register(master1, "b:80", "r1", {1: (list(range(7, 13)), 64)})
    accepted = master1.mass_repair.submit(master1.mass_repair.plan())
    assert len(accepted) == 1
    assert master1.mass_repair.pending()

    # "crash": a fresh master over the same journal dir
    master2 = _fake_master(tmp_path)
    _register(master2, "a:80", "r0", {1: (list(range(0, 7)), 64)})
    _register(master2, "b:80", "r1", {1: (list(range(7, 13)), 64)})
    pending = master2.mass_repair.pending()
    assert [j["volume_id"] for j in pending] == [1]

    executed = []

    class _Stub:
        def VolumeEcShardsBatchRebuild(self, req):
            executed.extend(j.volume_id for j in req.jobs)
            resp = type("R", (), {})()
            resp.results = [type("J", (), {
                "volume_id": j.volume_id, "rebuilt_shard_ids": [13],
                "error": "", "used_partial": True})() for j in req.jobs]
            return resp

    master2.mass_repair._target_stub = lambda node: _Stub()
    before_ok = REPAIR_BATCH_JOBS.labels("ok").value
    master2.mass_repair.run_wave(master2.mass_repair.pending())
    assert executed == [1]
    assert not master2.mass_repair.pending()
    job = master2.mass_repair.journal.get("1:mass_repair")
    assert job["state"] == "done"
    assert REPAIR_BATCH_JOBS.labels("ok").value == before_ok + 1
    # a second wave over the drained queue re-runs nothing
    master2.mass_repair.run_wave(master2.mass_repair.pending())
    assert executed == [1]


def test_orchestrator_failed_target_parks_after_attempts(tmp_path):
    """An unreachable target fails the job (attempts preserved across
    resubmits) until MAX_ATTEMPTS parks it for an operator."""
    import grpc

    master = _fake_master(tmp_path, journal=False)
    _register(master, "a:80", "r0", {1: (list(range(0, 7)), 64)})
    _register(master, "b:80", "r1", {1: (list(range(7, 13)), 64)})

    class _DeadStub:
        def VolumeEcShardsBatchRebuild(self, req):
            raise grpc.RpcError("unreachable")

    master.mass_repair._target_stub = lambda node: _DeadStub()
    for attempt in range(1, 4):
        accepted = master.mass_repair.submit(master.mass_repair.plan())
        assert accepted, f"attempt {attempt} not resubmitted"
        master.mass_repair.run_wave(master.mass_repair.pending())
        job = master.mass_repair.journal.get("1:mass_repair")
        assert job["attempts"] == attempt
    assert job["state"] == "parked"
    # parked: no more resubmission until an operator clears it
    assert master.mass_repair.submit(master.mass_repair.plan()) == []


def test_scrub_pass_skips_volume_under_mass_repair(tmp_path):
    """Mutual exclusion, both directions, on the (volume, transition)
    journal key: a scrub finding on a volume with an active mass_repair
    job is skipped (stays queued), and the orchestrator skips a volume
    the scrub pass is currently healing."""
    master = _fake_master(tmp_path, journal=False)
    _register(master, "a:80", "r0", {7: (list(range(0, 7)), 64)})
    _register(master, "b:80", "r1", {7: (list(range(7, 13)), 64)})

    # active mass_repair job on volume 7
    accepted = master.mass_repair.submit(master.mass_repair.plan())
    assert [j["volume_id"] for j in accepted] == [7]

    finding = type("F", (), {
        "volume_id": 7, "kind": "needle", "shard_id": 0,
        "needle_id": 1, "detail": "crc", "detected_at_ms": 1})()
    master.record_scrub_findings("a:80", [finding])
    summary = master.repair_pass()
    key = ("a:80", 7, "needle", 0, 1)
    assert key in summary["skipped"]
    assert master.scrub_findings[key]["status"] == "pending"  # requeued

    # reverse: scrub pass mid-heal on volume 7 -> orchestrator defers
    master.lifecycle.journal.update("7:mass_repair", state="done")
    master._scrub_repairing.add(7)
    assert master.mass_repair.submit(master.mass_repair.plan()) == []
    master._scrub_repairing.clear()


def test_lifecycle_skips_volume_under_mass_repair(tmp_path):
    """The shared journal's one-transition-per-volume rule keeps every
    lifecycle planner off a volume that mass repair holds, and the
    controller's executor never claims mass_repair jobs."""
    master = _fake_master(tmp_path, journal=False)
    _register(master, "a:80", "r0", {9: (list(range(0, 7)), 64)})
    _register(master, "b:80", "r1", {9: (list(range(7, 13)), 64)})
    accepted = master.mass_repair.submit(master.mass_repair.plan())
    assert [j["volume_id"] for j in accepted] == [9]
    # a lifecycle plan for the same volume is suppressed
    assert master.lifecycle.submit([{
        "key": "9:vacuum", "volume_id": 9, "transition": "vacuum",
        "collection": "", "node": "a:80", "holders": ["a:80"],
        "bytes": 0}]) == []
    # and the controller's executor leaves the mass_repair job alone
    assert master.lifecycle.run_pending(wait=True) == []
    assert master.mass_repair.pending()


def test_lifecycle_rpc_mass_repair_actions(tmp_path):
    """The shell's surface: mass_repair_status reports orchestrator
    state, mass_repair_plan dry-runs the exposure-ranked plan."""
    import json

    from seaweedfs_tpu.master.grpc_handlers import MasterGrpcService
    from seaweedfs_tpu.pb import master_pb2

    master = _fake_master(tmp_path, journal=False)
    _register(master, "a:80", "r0", {4: (list(range(0, 7)), 64)})
    _register(master, "b:80", "r1", {4: (list(range(7, 13)), 64)})
    svc = MasterGrpcService(master)
    st = json.loads(svc.Lifecycle(master_pb2.LifecycleRequest(
        action="mass_repair_status"), None).report)
    assert st["enabled"] and st["pending"] == 0
    plan = json.loads(svc.Lifecycle(master_pb2.LifecycleRequest(
        action="mass_repair_plan", node="dead:80"), None).report)
    assert [p["volume_id"] for p in plan["planned"]] == [4]
    assert plan["planned"][0]["dead_node"] == "dead:80"
    # a dry run journals nothing
    assert master.mass_repair.pending() == []


def test_eager_cache_invalidation_registry(tmp_path):
    """Dead-node notice plumbing: every partial client / fetcher cache a
    volume server hands out is registered, and one call drops them all
    to force a fresh master lookup."""
    from seaweedfs_tpu.volume.server import VolumeServer

    d = tmp_path / "v"
    d.mkdir()
    s = VolumeServer(directories=[str(d)], master_addresses=["127.0.0.1:1"],
                     ip="127.0.0.1", port=free_port())
    client = s._make_partial_client(1)
    fetch = s._make_ec_fetcher(2)
    assert fetch is not None and client is not None
    now = time.monotonic()
    for c in s._loc_caches:
        c._fetched_at = now  # simulate a fresh, trusted holder map
    assert len(list(s._loc_caches)) == 2
    assert s.invalidate_location_caches() == 2
    for c in s._loc_caches:
        assert c._fetched_at == float("-inf")


# -- proactive evacuation (ISSUE 14: failing-disk trigger) ----------------


def _set_disk_state(node, state):
    node.disk_health = {"/d": {"state": state, "free_bytes": 1,
                               "total_bytes": 2}}


def test_plan_evacuation_spreads_and_skips(tmp_path):
    """EC shards on a failing node spread across healthy nodes by free
    EC slots; full/failing nodes are never targets; replicated volumes
    (a healthy copy exists) are not copied; sole-copy volumes are."""
    from seaweedfs_tpu.topology.topology import VolumeInfo

    master = _fake_master(tmp_path, journal=False)
    sick = _register(master, "sick:80", "r0", {
        1: ([0, 1, 2], 64), 2: ([5], 64)})
    sick.volumes = {7: VolumeInfo(volume_id=7),   # sole copy
                    8: VolumeInfo(volume_id=8)}   # replicated on b
    _set_disk_state(sick, "failing")
    a = _register(master, "a:80", "r0", {1: ([3, 4], 64)})
    b = _register(master, "b:80", "r1", {})
    b.volumes = {8: VolumeInfo(volume_id=8)}
    full = _register(master, "full:80", "r1", {})
    _set_disk_state(full, "full")

    moves = master.mass_repair.plan_evacuation("sick:80")
    ec = [m for m in moves if m["kind"] == "ec_shard"]
    vols = [m for m in moves if m["kind"] == "volume"]
    # every shard the sick node holds is planned off it
    assert sorted((m["volume_id"], m["shard_id"]) for m in ec) == [
        (1, 0), (1, 1), (1, 2), (2, 5)]
    assert all(m["target"] in ("a:80", "b:80") for m in moves), moves
    # volume 7 (sole copy) moves; volume 8 already has a healthy holder
    assert [m["volume_id"] for m in vols] == [7]


def test_on_disk_failing_rate_limited_and_executes(tmp_path, monkeypatch):
    """The heartbeat-ingest trigger runs one evacuation per cooldown
    window and drives the per-move rpc helpers."""
    from seaweedfs_tpu.topology.topology import VolumeInfo

    master = _fake_master(tmp_path, journal=False)
    sick = _register(master, "sick:80", "r0", {3: ([0, 1], 64)})
    sick.volumes = {9: VolumeInfo(volume_id=9)}
    _set_disk_state(sick, "failing")
    _register(master, "a:80", "r0", {})

    done = []
    monkeypatch.setattr(
        master.mass_repair, "_evacuate_ec_shard",
        lambda mv: done.append(("ec", mv["volume_id"], mv["shard_id"])))
    monkeypatch.setattr(
        master.mass_repair, "_evacuate_volume",
        lambda mv: done.append(("vol", mv["volume_id"])))
    master.note_disk_health(sick)
    deadline = time.time() + 5
    while time.time() < deadline and len(done) < 3:
        time.sleep(0.05)
    assert sorted(done) == [("ec", 3, 0), ("ec", 3, 1), ("vol", 9)]
    assert master.mass_repair._counts["evacuated"] == 3
    # cooldown: an immediate re-trigger is a no-op
    done.clear()
    master.note_disk_health(sick)
    time.sleep(0.3)
    assert done == []
