"""Codec service + device probe: byte identity (host/device/batched vs
single), fairness under a saturating producer, clean shutdown with jobs
in flight, and probe-driven fallback when devices are unreachable."""

from __future__ import annotations

import os
import threading
import time

import numpy as np
import pytest

from seaweedfs_tpu.ops import codec_service, device_probe, gf256
from seaweedfs_tpu.ops.codec import get_codec
from seaweedfs_tpu.ops.codec_service import CodecService
from seaweedfs_tpu.ops.rs_cpu import ReedSolomon


@pytest.fixture(autouse=True)
def _clean_service_state():
    yield
    codec_service.shutdown_all(timeout=10)
    device_probe.reset_cache()


def _rand_block(rng, width):
    return rng.integers(0, 256, (10, width), dtype=np.uint8)


def _as2d(result):
    return np.stack([np.asarray(r) for r in result])


# -- device probe -----------------------------------------------------------


def test_probe_ok_on_this_host_and_cached(monkeypatch):
    device_probe.reset_cache()
    pr = device_probe.probe()
    assert pr.ok and pr.devices >= 1
    assert pr.platform == "cpu"  # conftest pins the cpu backend
    assert not pr.accelerator

    # second call must come from the cache — a subprocess here would fail
    import subprocess

    def boom(*a, **k):
        raise AssertionError("probe re-ran despite cache")

    monkeypatch.setattr(subprocess, "run", boom)
    assert device_probe.probe() is pr


def test_probe_hard_deadline_reports_unreachable():
    device_probe.reset_cache()
    pr = device_probe.probe(timeout_s=0.001, refresh=True)
    assert not pr.ok
    assert "timed out" in pr.error
    assert pr.seconds < 5.0


def test_get_codec_degrades_to_cpu_when_probe_fails():
    device_probe.reset_cache()
    device_probe.probe(timeout_s=0.001, refresh=True)  # poison the cache
    codec = get_codec("tpu")
    assert codec._impl == "cpu"  # InstrumentedCodec label


def test_effective_codec_passthrough_when_probe_ok():
    from seaweedfs_tpu.ops.codec import effective_codec

    device_probe.reset_cache()
    assert effective_codec("cpu") == ("cpu", "")
    name, reason = effective_codec("tpu_xor")
    assert name == "tpu_xor" and reason == ""  # cpu-jax answers the probe


# -- host-mode byte identity ------------------------------------------------


def test_host_parity_identity_mixed_widths():
    rs = ReedSolomon()
    svc = CodecService(mode="host")
    rng = np.random.default_rng(1)
    widths = (0, 1, 7, 100, 4096, 17 << 10, 300_000)  # spans the slab cutoff
    futs, expect = [], []
    for w in widths:
        block = _rand_block(rng, w)
        futs.append(svc.submit_parity(block))
        expect.append(rs.parity_of(block))
    for fut, exp in zip(futs, expect):
        assert np.array_equal(_as2d(fut.result(30)), exp)
    svc.close()


def test_host_apply_identity_decode_plan():
    rs = ReedSolomon()
    svc = CodecService(mode="host")
    rng = np.random.default_rng(2)
    plan = gf256.decode_plan_for(
        rs.matrix, 10, list(range(4, 14)), (0, 1, 2, 3))
    block = _rand_block(rng, 5000)
    got = _as2d(svc.submit_apply(plan, block).result(30))
    assert np.array_equal(got, np.stack(rs.apply_rows(plan, list(block))))
    svc.close()


def test_vectored_submit_preserves_order_and_identity():
    rs = ReedSolomon()
    svc = CodecService(mode="host")
    rng = np.random.default_rng(3)
    datas = [_rand_block(rng, w) for w in (64, 0, 2048, 9000, 3)]
    futs = svc.submit_parity_many(datas)
    for fut, data in zip(futs, datas):
        assert np.array_equal(_as2d(fut.result(30)), rs.parity_of(data))
    svc.close()


def test_out_buffers_filled_in_place():
    rs = ReedSolomon()
    svc = CodecService(mode="host")
    rng = np.random.default_rng(4)
    block = _rand_block(rng, 12345)
    out = np.zeros((4, 12345), dtype=np.uint8)
    svc.parity_into(block, out)
    assert np.array_equal(out, rs.parity_of(block))
    svc.close()


def test_list_of_rows_input():
    """mmap-view-style input: a list of 1-D rows, not a 2-D array."""
    rs = ReedSolomon()
    svc = CodecService(mode="host")
    rng = np.random.default_rng(5)
    rows = [rng.integers(0, 256, 777, dtype=np.uint8) for _ in range(10)]
    got = _as2d(svc.submit_parity(rows).result(30))
    assert np.array_equal(got, rs.parity_of(np.stack(rows)))
    svc.close()


def test_strided_row_views_are_decoded_correctly():
    """Non-contiguous row views must be copied before the raw-pointer
    kernel path, or it would silently read stride-1 garbage — for widths
    on BOTH sides of the slab-coalescing cutoff."""
    rs = ReedSolomon()
    svc = CodecService(mode="host", coalesce_kb=16)
    rng = np.random.default_rng(15)
    for w in (1024, 64 << 10):  # slab path and per-job native path
        rows = [rng.integers(0, 256, 2 * w, dtype=np.uint8)[::2]
                for _ in range(10)]
        got = _as2d(svc.submit_parity(rows).result(30))
        exp = rs.parity_of(np.stack([np.ascontiguousarray(r_)
                                     for r_ in rows]))
        assert np.array_equal(got, exp)
    svc.close()


# -- device-mode (mesh dry-run on the virtual 8-device CPU mesh) ------------


def test_device_mode_identity_parity_and_apply():
    rs = ReedSolomon()
    svc = CodecService(mode="device", codec_name="tpu_xor")
    rng = np.random.default_rng(6)
    futs, expect = [], []
    for w in (64, 200, 256, 1000):  # spans two width buckets
        block = _rand_block(rng, w)
        futs.append(svc.submit_parity(block))
        expect.append(rs.parity_of(block))
    plan = gf256.decode_plan_for(
        rs.matrix, 10, list(range(4, 14)), (2,))
    block = _rand_block(rng, 513)
    afut = svc.submit_apply(plan, block)
    for fut, exp in zip(futs, expect):
        assert np.array_equal(_as2d(fut.result(120)), exp)
    assert np.array_equal(
        _as2d(afut.result(120)),
        np.stack(rs.apply_rows(plan, list(block))))
    svc.close()


def test_auto_mode_falls_back_to_host_without_accelerator():
    # cpu-jax answers the probe but is no accelerator -> host mode
    device_probe.reset_cache()
    svc = CodecService(mode="auto", codec_name="tpu")
    assert svc.mode == "host"
    assert svc.fallback_reason  # names why the device path was refused
    rng = np.random.default_rng(7)
    block = _rand_block(rng, 1024)
    assert np.array_equal(
        _as2d(svc.submit_parity(block).result(30)),
        ReedSolomon().parity_of(block))
    svc.close()


# -- scheduler behavior -----------------------------------------------------


def test_batches_coalesce_under_load():
    from seaweedfs_tpu.stats.metrics import EC_SERVICE_BATCH_JOBS

    child = EC_SERVICE_BATCH_JOBS.labels()
    before_total, before_count = child.total, child.count
    svc = CodecService(mode="host", max_batch=16, coalesce_kb=16)
    rng = np.random.default_rng(8)
    big = _rand_block(rng, 32 << 20)  # occupies the worker for a while
    small = [_rand_block(rng, 2048) for _ in range(12)]
    first = svc.submit_parity(big)
    futs = svc.submit_parity_many(small)
    first.result(60)
    for f in futs:
        f.result(60)
    svc.close()
    jobs = child.total - before_total
    batches = child.count - before_count
    assert jobs == 13
    # the 12 small jobs queued while the big one computed must have
    # coalesced into (far) fewer than 12 batches
    assert batches < 13


def test_fairness_saturating_producer_does_not_starve():
    svc = CodecService(mode="host", max_batch=8)
    rng = np.random.default_rng(9)
    flood_block = _rand_block(rng, 64 << 10)
    stop = threading.Event()

    def flood():
        pend = []
        while not stop.is_set():
            pend.append(svc.submit_parity(flood_block))
            if len(pend) > 8:
                pend.pop(0).result()
        for f in pend:
            f.result()

    threads = [threading.Thread(target=flood) for _ in range(3)]
    for t in threads:
        t.start()
    try:
        time.sleep(0.1)  # let the flood saturate the queue
        plan = gf256.decode_plan_for(
            ReedSolomon().matrix, 10, list(range(4, 14)), (1,))
        block = _rand_block(rng, 2048)
        t0 = time.perf_counter()
        got = svc.submit_apply(plan, block).result(10)
        latency = time.perf_counter() - t0
        assert np.array_equal(
            _as2d(got),
            np.stack(ReedSolomon().apply_rows(plan, list(block))))
        # head-of-queue batching bounds the odd job's wait to a couple of
        # batch service times, not the flood's duration
        assert latency < 2.0
    finally:
        stop.set()
        for t in threads:
            t.join()
        svc.close()


def test_clean_shutdown_delivers_inflight_jobs():
    rs = ReedSolomon()
    svc = CodecService(mode="host")
    rng = np.random.default_rng(10)
    datas = [_rand_block(rng, 100_000) for _ in range(24)]
    futs = svc.submit_parity_many(datas)
    svc.close()  # drain: every already-accepted job still completes
    for fut, data in zip(futs, datas):
        assert np.array_equal(_as2d(fut.result(30)), rs.parity_of(data))
    with pytest.raises(RuntimeError):
        svc.submit_parity(datas[0])


def test_compute_failure_fails_jobs_not_hangs(monkeypatch):
    svc = CodecService(mode="host")

    def boom(batch):
        raise RuntimeError("injected compute failure")

    monkeypatch.setattr(svc, "_compute_host", boom)
    fut = svc.submit_parity(_rand_block(np.random.default_rng(11), 1024))
    with pytest.raises(RuntimeError, match="injected"):
        fut.result(30)
    svc.close()


def test_validation_errors_raise_in_caller():
    svc = CodecService(mode="host")
    rng = np.random.default_rng(12)
    with pytest.raises(ValueError):
        svc.submit_parity(rng.integers(0, 256, (9, 64), dtype=np.uint8))
    with pytest.raises(ValueError):
        svc.submit_parity(
            [rng.integers(0, 256, w, dtype=np.uint8)
             for w in (64,) * 9 + (65,)])
    with pytest.raises(ValueError):
        svc.submit_parity(_rand_block(rng, 64),
                          out=np.zeros((4, 63), np.uint8))
    svc.close()


# -- singletons + env gating ------------------------------------------------


def test_get_service_disabled_by_env(monkeypatch):
    monkeypatch.setenv("SEAWEEDFS_TPU_EC_SERVICE", "0")
    assert codec_service.get_service("cpu") is None
    assert codec_service.service_for_codec("tpu") is None
    assert codec_service.service_for_degraded() is None


def test_get_service_shared_and_recreated_after_shutdown(monkeypatch):
    monkeypatch.delenv("SEAWEEDFS_TPU_EC_SERVICE", raising=False)
    a = codec_service.get_service("cpu")
    assert a is codec_service.get_service("cpu")
    codec_service.shutdown_all()
    b = codec_service.get_service("cpu")
    assert b is not a and not b.closed


def test_service_for_codec_requires_accelerator(monkeypatch):
    # cpu-jax probe: ok but not an accelerator -> bulk pipelines keep
    # their direct (tested) dispatch paths
    monkeypatch.delenv("SEAWEEDFS_TPU_EC_SERVICE", raising=False)
    device_probe.reset_cache()
    assert codec_service.service_for_codec("tpu") is None
    assert codec_service.service_for_codec("cpu") is None


# -- pipeline integration ---------------------------------------------------


def _write_dat(path, nbytes, seed=13):
    rng = np.random.default_rng(seed)
    with open(path, "wb") as f:
        f.write(rng.integers(0, 256, nbytes, dtype=np.uint8).tobytes())


def test_generate_and_rebuild_via_service_byte_identical(tmp_path):
    from seaweedfs_tpu.storage.ec.constants import to_ext
    from seaweedfs_tpu.storage.ec.encoder import (
        generate_ec_files,
        rebuild_ec_files,
    )

    base = str(tmp_path / "v")
    large, small = 1 << 20, 64 << 10
    _write_dat(base + ".dat", 11 * (1 << 20) + 4321)
    generate_ec_files(base, large_block_size=large, small_block_size=small,
                      codec_name="cpu", slice_size=256 << 10)
    ref = {i: open(base + to_ext(i), "rb").read() for i in range(14)}

    svc = CodecService(mode="host")
    # mixed slice sizes through the service: batched segments coalesce
    for slice_size in (64 << 10, 192 << 10):
        generate_ec_files(base, large_block_size=large,
                          small_block_size=small, codec_name="cpu",
                          slice_size=slice_size, service=svc)
        for i in range(14):
            assert open(base + to_ext(i), "rb").read() == ref[i], \
                f"shard {i} differs at slice_size={slice_size}"
    # rebuild through the service: worst-case data loss + one parity
    for sid in (0, 1, 2, 13):
        os.remove(base + to_ext(sid))
    rebuilt = rebuild_ec_files(base, codec_name="cpu",
                               slice_size=128 << 10, service=svc)
    assert sorted(rebuilt) == [0, 1, 2, 13]
    for i in range(14):
        assert open(base + to_ext(i), "rb").read() == ref[i]
    svc.close()


def test_generate_device_codec_via_device_service(tmp_path):
    """The pipelined encode path with an explicit device-mode service —
    the mesh dry-run for the serving path's batched dispatch."""
    from seaweedfs_tpu.storage.ec.constants import to_ext
    from seaweedfs_tpu.storage.ec.encoder import generate_ec_files

    base = str(tmp_path / "v")
    large, small = 1 << 20, 64 << 10
    _write_dat(base + ".dat", 3 * (1 << 20) + 999)
    generate_ec_files(base, large_block_size=large, small_block_size=small,
                      codec_name="cpu", slice_size=256 << 10)
    ref = {i: open(base + to_ext(i), "rb").read() for i in range(14)}
    svc = CodecService(mode="device", codec_name="tpu_xor")
    generate_ec_files(base, large_block_size=large, small_block_size=small,
                      codec_name="tpu_xor", slice_size=256 << 10,
                      service=svc)
    for i in range(14):
        assert open(base + to_ext(i), "rb").read() == ref[i]
    svc.close()


def test_degraded_read_via_service(tmp_path, monkeypatch):
    from seaweedfs_tpu.storage.ec.constants import to_ext
    from seaweedfs_tpu.storage.ec.encoder import (
        generate_ec_files,
        write_sorted_file_from_idx,
    )
    from seaweedfs_tpu.storage.ec.volume import EcVolume
    from seaweedfs_tpu.storage.needle import FLAG_HAS_NAME, Needle
    from seaweedfs_tpu.storage.super_block import SuperBlock
    from seaweedfs_tpu.storage.volume import Volume

    rng = np.random.default_rng(14)
    vol = Volume(str(tmp_path), "", 1, super_block=SuperBlock())
    payloads = {}
    for i in range(1, 21):
        n = Needle(cookie=int(rng.integers(0, 2**32)), id=i,
                   data=rng.integers(0, 256, 4096, dtype=np.uint8).tobytes())
        n.set(FLAG_HAS_NAME)
        n.name = f"svc-{i}.bin".encode()
        payloads[i] = n.data
        vol.append_needle(n)
    base = vol.file_name()
    vol.close()
    generate_ec_files(base, codec_name="cpu")
    write_sorted_file_from_idx(base)
    for sid in (0, 1, 2, 3):
        os.remove(base + to_ext(sid))

    monkeypatch.setenv("SEAWEEDFS_TPU_EC_SERVICE_DEGRADED", "1")
    monkeypatch.setenv("SEAWEEDFS_TPU_EC_INTERVAL_CACHE_MB", "0")
    codec_service.shutdown_all()
    ev = EcVolume(base, volume_id=1)
    try:
        for i in (1, 5, 9, 20):
            needle = ev.read_needle(i)
            assert needle.data == payloads[i]
    finally:
        ev.close()
