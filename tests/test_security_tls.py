"""mTLS over the gRPC substrate + the TOML config tier + scaffold.

Reference: weed/security/tls.go (cert-based gRPC identity for every
component), weed/util/config.go:20-48 (TOML discovery),
weed/command/scaffold.go (default config emission).
"""

from __future__ import annotations

import importlib.util
import socket
import time

import grpc
import pytest

from seaweedfs_tpu.pb import master_pb2
from seaweedfs_tpu.pb import rpc as rpclib
from seaweedfs_tpu.security.tls import (
    generate_dev_certs,
    load_client_credentials,
    load_server_credentials,
)
from seaweedfs_tpu.util.config import Configuration, load_configuration
from seaweedfs_tpu.util.scaffold import scaffold


def _free_port() -> int:
    from helpers import free_port

    return free_port()


@pytest.fixture()
def plaintext_rpc():
    """Restore the substrate to plaintext after each mTLS test."""
    yield
    rpclib.configure_security(None, None)


def _tls_config(certs: dict, component: str) -> Configuration:
    return Configuration({
        "grpc": {
            "ca": certs["ca"][0],
            component: {"cert": certs[component][0],
                        "key": certs[component][1]},
        },
    }, path="<test>")


def test_toml_discovery_and_dotted_access(tmp_path):
    (tmp_path / "master.toml").write_text(
        '[master.maintenance]\nscripts = ["ec.rebuild -force"]\n'
        'periodic_seconds = 60\n[codec]\ntype = "tpu"\n')
    conf = load_configuration("master", search_paths=(str(tmp_path),))
    assert conf.loaded
    assert conf.get_list("master.maintenance.scripts") == \
        ["ec.rebuild -force"]
    assert conf.get_int("master.maintenance.periodic_seconds") == 60
    assert conf.get_string("codec.type") == "tpu"
    missing = load_configuration("nope", search_paths=(str(tmp_path),))
    assert not missing.loaded
    with pytest.raises(FileNotFoundError):
        load_configuration("nope", required=True,
                           search_paths=(str(tmp_path),))


def test_scaffold_emits_parseable_toml(tmp_path):
    tomllib = pytest.importorskip(
        "tomllib", reason="no TOML parser on python < 3.11")

    for name in ("security", "master", "filer"):
        data = tomllib.loads(scaffold(name))
        assert data, name
    m = tomllib.loads(scaffold("master"))
    assert m["master"]["maintenance"]["scripts"]
    s = tomllib.loads(scaffold("security"))
    assert "grpc" in s


@pytest.mark.skipif(
    importlib.util.find_spec("cryptography") is None,
    reason="cert generation needs the cryptography package")
def test_mtls_cluster_roundtrip(tmp_path, plaintext_rpc):
    """A master+volume cluster where every gRPC hop is mutually
    authenticated: heartbeats, lookups, admin rpcs."""
    from seaweedfs_tpu.master.server import MasterServer
    from seaweedfs_tpu.volume.server import VolumeServer

    certs = generate_dev_certs(str(tmp_path / "certs"),
                               components=("master", "client"))
    server_creds = load_server_credentials(
        _tls_config(certs, "master"), "master")
    channel_creds = load_client_credentials(
        _tls_config(certs, "client"), "client")
    assert server_creds is not None and channel_creds is not None
    rpclib.configure_security(server_creds, channel_creds)

    master = MasterServer(ip="127.0.0.1", port=_free_port(),
                          volume_size_limit_mb=64)
    master.start()
    vs = VolumeServer(
        directories=[str(tmp_path / "v1")],
        master_addresses=[f"127.0.0.1:{master.grpc_port}"],
        ip="127.0.0.1", port=_free_port(), pulse_seconds=0.5,
    )
    vs.start()
    try:
        deadline = time.time() + 15
        while time.time() < deadline and len(master.topo.nodes) < 1:
            time.sleep(0.1)
        assert len(master.topo.nodes) == 1, \
            "volume server failed to heartbeat over mTLS"
        # client rpc over the secured channel
        stub = rpclib.master_stub(f"127.0.0.1:{master.grpc_port}",
                                  timeout=10)
        resp = stub.Assign(master_pb2.AssignRequest(count=1))
        assert resp.fid and not resp.error
    finally:
        vs.stop()
        master.stop()


@pytest.mark.skipif(
    importlib.util.find_spec("cryptography") is None,
    reason="cert generation needs the cryptography package")
def test_mtls_rejects_unauthenticated_client(tmp_path, plaintext_rpc):
    """A client without a certificate cannot complete the handshake."""
    certs = generate_dev_certs(str(tmp_path / "certs"),
                               components=("master", "client"))
    server_creds = load_server_credentials(
        _tls_config(certs, "master"), "master")
    port = _free_port()
    server = grpc.server(
        __import__("concurrent.futures", fromlist=["futures"])
        .ThreadPoolExecutor(max_workers=2))
    server.add_secure_port(f"127.0.0.1:{port}", server_creds)
    server.start()
    try:
        # plaintext dial: must fail
        ch = grpc.insecure_channel(f"127.0.0.1:{port}")
        call = ch.unary_unary(
            "/master_pb.Seaweed/VolumeList",
            request_serializer=master_pb2.VolumeListRequest.SerializeToString,
            response_deserializer=master_pb2.VolumeListResponse.FromString,
        )
        with pytest.raises(grpc.RpcError):
            call(master_pb2.VolumeListRequest(), timeout=5)
        ch.close()
        # TLS without a client cert: handshake refused (server requires it)
        with open(certs["ca"][0], "rb") as f:
            anon = grpc.ssl_channel_credentials(root_certificates=f.read())
        ch = grpc.secure_channel(f"127.0.0.1:{port}", anon)
        call = ch.unary_unary(
            "/master_pb.Seaweed/VolumeList",
            request_serializer=master_pb2.VolumeListRequest.SerializeToString,
            response_deserializer=master_pb2.VolumeListResponse.FromString,
        )
        with pytest.raises(grpc.RpcError):
            call(master_pb2.VolumeListRequest(), timeout=5)
        ch.close()
    finally:
        server.stop(0)


def test_scaffold_notification_replication_shell(tmp_path, monkeypatch):
    """scaffold emits notification/replication/shell TOMLs and the
    factories build the enabled backend from them
    (command/scaffold.go parity)."""
    from seaweedfs_tpu.notification import publisher_from_config
    from seaweedfs_tpu.notification.publishers import FilePublisher
    from seaweedfs_tpu.replication.sink import LocalSink, sink_from_config
    from seaweedfs_tpu.util.config import load_configuration
    from seaweedfs_tpu.util.scaffold import scaffold

    for kind in ("notification", "replication", "shell"):
        text = scaffold(kind)
        (tmp_path / f"{kind}.toml").write_text(text)

    # enable the file publisher (into tmp_path, not the CWD) + local sink
    n = (tmp_path / "notification.toml").read_text().replace(
        "[notification.file]\n# Append JSON events to a local file.\n"
        "enabled = false\npath = \"./filer_events.jsonl\"",
        "[notification.file]\nenabled = true\n"
        f"path = \"{tmp_path}/filer_events.jsonl\"")
    (tmp_path / "notification.toml").write_text(n)
    r = (tmp_path / "replication.toml").read_text().replace(
        "[sink.local]\nenabled = false",
        "[sink.local]\nenabled = true")
    (tmp_path / "replication.toml").write_text(r)

    paths = [str(tmp_path)]
    nconf = load_configuration("notification", search_paths=paths)
    pub = publisher_from_config(nconf)
    assert isinstance(pub, FilePublisher)
    assert str(tmp_path) in pub.path
    pub.close()

    rconf = load_configuration("replication", search_paths=paths)
    sink, label = sink_from_config(rconf)
    assert isinstance(sink, LocalSink) and label.startswith("local:")

    sconf = load_configuration("shell", search_paths=paths)
    assert sconf.get_string("cluster.default.master") == "localhost:9333"
