"""Strict validation of the Prometheus text exposition format.

A mini-parser walks the full rendered output and checks the invariants a
real Prometheus scraper relies on: HELP-then-TYPE ordering per family,
cumulative (non-decreasing) histogram buckets ending in an +Inf bucket
equal to _count, _sum/_count presence per labelset, float-rendered `le`
bounds, and backslash/quote escaping in label values.
"""

import re

from seaweedfs_tpu.stats.metrics import (
    Registry,
    escape_label_value,
    format_le,
)

_SAMPLE_RE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?:\{(?P<labels>.*)\})? (?P<value>[^ ]+)$"
)
_LABEL_RE = re.compile(r'(\w+)="((?:[^"\\]|\\.)*)"')


def _parse(text: str):
    """-> (families, samples): families[name] = (help, type);
    samples = [(name, {label: raw_value}, float)]."""
    families: dict[str, list] = {}
    samples = []
    pending_help: dict[str, str] = {}
    for line in text.splitlines():
        if not line:
            continue
        if line.startswith("# HELP "):
            _, _, rest = line.partition("# HELP ")
            name, _, help_ = rest.partition(" ")
            assert name not in families, f"duplicate HELP for {name}"
            pending_help[name] = help_
            continue
        if line.startswith("# TYPE "):
            _, _, rest = line.partition("# TYPE ")
            name, _, kind = rest.partition(" ")
            # HELP must have directly preceded TYPE for the same family
            assert name in pending_help, f"TYPE before HELP for {name}"
            families[name] = (pending_help.pop(name), kind.strip())
            continue
        assert not line.startswith("#"), f"unknown comment {line!r}"
        m = _SAMPLE_RE.match(line)
        assert m, f"unparseable sample line {line!r}"
        labels = dict(_LABEL_RE.findall(m.group("labels") or ""))
        samples.append((m.group("name"), labels, float(m.group("value"))))
    return families, samples


def _family_of(sample_name: str, families) -> str:
    for suffix in ("_bucket", "_sum", "_count"):
        base = sample_name[: -len(suffix)] if sample_name.endswith(suffix) else None
        if base in families and families[base][1] == "histogram":
            return base
    return sample_name


def _build_registry() -> Registry:
    r = Registry()
    c = r.counter("t_requests_total", "requests", labels=("type", "op"))
    c.labels("volume", "get").inc(3)
    c.labels("filer", "post").inc()
    g = r.gauge("t_volumes", "volumes", labels=("collection",))
    g.labels("pics").set(7)
    # label values exercising the escaping rules
    c.labels('he said "hi"', "back\\slash").inc()
    h = r.histogram("t_latency_seconds", "latency",
                    labels=("op",), buckets=(0.25, 1, 10))
    for v in (0.1, 0.5, 3.0, 50.0):
        h.labels("read").observe(v)
    h.labels("write").observe(0.2)
    return r


def test_full_output_parses_and_is_consistent():
    text = _build_registry().render()
    families, samples = _parse(text)

    assert families["t_requests_total"][1] == "counter"
    assert families["t_volumes"][1] == "gauge"
    assert families["t_latency_seconds"][1] == "histogram"

    # every sample belongs to a declared family
    for name, labels, _ in samples:
        assert _family_of(name, families) in families, name

    # histogram invariants per labelset
    by_op: dict[str, list] = {}
    sums = {}
    counts = {}
    for name, labels, value in samples:
        if name == "t_latency_seconds_bucket":
            by_op.setdefault(labels["op"], []).append((labels["le"], value))
        elif name == "t_latency_seconds_sum":
            sums[labels["op"]] = value
        elif name == "t_latency_seconds_count":
            counts[labels["op"]] = value
    assert set(by_op) == {"read", "write"}
    for op, buckets in by_op.items():
        les = [le for le, _ in buckets]
        assert les[-1] == "+Inf"
        # finite bounds render as floats, in ascending order
        finite = [float(le) for le in les[:-1]]
        assert finite == sorted(finite)
        values = [v for _, v in buckets]
        assert values == sorted(values), f"{op}: non-cumulative buckets"
        assert values[-1] == counts[op], f"{op}: +Inf != _count"
        assert op in sums and op in counts
    # the int bucket bound 1 and 10 must render as 1.0 / 10.0
    read_les = [le for le, _ in by_op["read"]]
    assert read_les == ["0.25", "1.0", "10.0", "+Inf"]
    # cumulative counts: 0.1<=0.25; 0.5<=1; 3<=10; 50 only in +Inf
    assert [v for _, v in by_op["read"]] == [1, 2, 3, 4]
    assert sums["read"] == 0.1 + 0.5 + 3.0 + 50.0


def test_label_value_escaping_round_trips():
    text = _build_registry().render()
    # raw escaped forms present in the exposition
    assert r'type="he said \"hi\""' in text
    assert r'op="back\\slash"' in text
    # and the parser (which unescapes per the spec regex) sees the family
    _, samples = _parse(text)
    escaped = [
        labels for name, labels, _ in samples
        if name == "t_requests_total" and "hi" in labels.get("type", "")
    ]
    assert escaped, "escaped labelset missing from exposition"


def test_escape_helpers():
    assert escape_label_value('a"b') == 'a\\"b'
    assert escape_label_value("a\\b") == "a\\\\b"
    assert escape_label_value("a\nb") == "a\\nb"
    assert format_le(10) == "10.0"
    assert format_le(10.0) == "10.0"
    assert format_le(0.25) == "0.25"
    assert format_le(0.0001) == "0.0001"


def test_saturation_gauge_families_render():
    """The PR-5 saturation families (executor pools, per-peer connpool,
    EC pipeline stages) must expose through the standard renderer."""
    from seaweedfs_tpu.stats.metrics import (
        CONNPOOL_IDLE,
        CONNPOOL_IN_USE,
        EC_PIPELINE_STAGE,
        EXECUTOR_ACTIVE,
        EXECUTOR_MAX,
        EXECUTOR_QUEUE_DEPTH,
        REGISTRY,
    )

    EXECUTOR_QUEUE_DEPTH.labels("t_exposition").set(3)
    EXECUTOR_ACTIVE.labels("t_exposition").set(2)
    EXECUTOR_MAX.labels("t_exposition").set(8)
    CONNPOOL_IN_USE.labels("10.0.0.1:8080").set(1)
    CONNPOOL_IDLE.labels("10.0.0.1:8080").set(4)
    EC_PIPELINE_STAGE.labels("prefetch").observe(0.01)
    text = REGISTRY.render()
    families, samples = _parse(text)
    assert families["seaweedfs_executor_queue_depth"][1] == "gauge"
    assert families["seaweedfs_connpool_in_use"][1] == "gauge"
    assert families["seaweedfs_ec_pipeline_stage_seconds"][1] == "histogram"
    assert ('seaweedfs_executor_queue_depth{executor="t_exposition"} 3.0'
            in text)
    assert 'seaweedfs_connpool_idle{peer="10.0.0.1:8080"} 4.0' in text
    assert ('seaweedfs_ec_pipeline_stage_seconds_count{stage="prefetch"}'
            in text)
    # the full registry still passes the strict parser with them present
    for name, labels, _v in samples:
        assert _family_of(name, families) in families, name


def test_federated_exposition_parses_and_groups():
    """The master's /cluster/metrics merge: per-node expositions regroup
    by family (text-format requirement), instance/type labels injected,
    and the result passes the same strict parser as a single node."""
    from seaweedfs_tpu.telemetry.federation import FederatedExposition

    node_a = _build_registry().render()
    node_b = _build_registry().render()
    fed = FederatedExposition()
    fed.add_live({"instance": "10.0.0.1:8080", "type": "volume"}, node_a,
                 0.01)
    fed.add_live({"instance": "10.0.0.2:8080", "type": "volume"}, node_b,
                 0.02)
    fed.add_snapshot({"instance": "10.0.0.3:8888", "type": "filer"},
                     [('t_volumes{collection="pics"}', 7.0)], 12.5)
    fed.add_down({"instance": "10.0.0.4:8080", "type": "volume"})
    text = fed.render()
    families, samples = _parse(text)

    # every sample belongs to a declared family, all families grouped once
    for name, labels, _v in samples:
        assert _family_of(name, families) in families, name
    # both live nodes present with distinct instance labels, extra labels
    # injected ahead of the node's own
    assert ('t_requests_total{instance="10.0.0.1:8080",type_="volume"'
            not in text)  # guard against label-name mangling
    per_instance = {
        labels.get("instance")
        for name, labels, _v in samples if name == "t_requests_total"
    }
    assert {"10.0.0.1:8080", "10.0.0.2:8080"} <= per_instance
    # histogram samples stayed contiguous under their base family
    bucket_lines = [i for i, line in enumerate(text.splitlines())
                    if line.startswith("t_latency_seconds")]
    assert bucket_lines == list(
        range(bucket_lines[0], bucket_lines[0] + len(bucket_lines)))
    # federation meta-families: up/stale/age
    by_name = {}
    for name, labels, v in samples:
        by_name.setdefault(name, {})[labels.get("instance")] = v
    assert by_name["seaweedfs_federation_up"]["10.0.0.1:8080"] == 1
    assert by_name["seaweedfs_federation_up"]["10.0.0.3:8888"] == 0
    assert by_name["seaweedfs_federation_stale"]["10.0.0.3:8888"] == 1
    assert by_name["seaweedfs_federation_stale"]["10.0.0.4:8080"] == 0
    assert by_name["seaweedfs_federation_snapshot_age_seconds"][
        "10.0.0.3:8888"] == 12.5
    # snapshot sample re-served with the node's own labels preserved
    assert ('t_volumes{instance="10.0.0.3:8888",type="filer",'
            'collection="pics"} 7.0' in text)


def test_federated_instance_label_value_escaping():
    """A hostile/odd instance string must escape per the exposition spec
    both in injected labels and in the meta-families."""
    from seaweedfs_tpu.telemetry.federation import (
        FederatedExposition,
        inject_labels,
    )

    weird = 'host"with\\quirks\n:80'
    out = inject_labels("t_total", {"instance": weird})
    assert out == (
        't_total{instance="host\\"with\\\\quirks\\n:80"}')
    # and through the full merge path
    fed = FederatedExposition()
    fed.add_live({"instance": weird, "type": "volume"},
                 "# HELP t_total t\n# TYPE t_total counter\nt_total 1\n",
                 0.0)
    text = fed.render()
    families, samples = _parse(text)
    values = [labels["instance"] for name, labels, _v in samples
              if name == "t_total"]
    assert values, text
    # the strict parser's regex unescapes nothing; the raw escaped form
    # must round-trip the spec escapes
    assert values[0] == 'host\\"with\\\\quirks\\n:80'


def test_preexisting_request_label_pairs_render():
    """The label pairs the seed emitted must still appear after the
    middleware refactor (ISSUE satellite: no silent metric loss)."""
    from seaweedfs_tpu.stats.metrics import (
        REGISTRY,
        REQUEST_COUNTER,
        REQUEST_HISTOGRAM,
    )

    legacy = [
        ("master", "assign"),
        ("filer", "get"), ("filer", "post"),
        ("volumeServer", "get"), ("volumeServer", "post"),
        ("volumeServer", "delete"),
        ("s3", "get"), ("s3", "put"),
    ]
    for t, op in legacy:
        REQUEST_COUNTER.labels(t, op).inc(0)
        REQUEST_HISTOGRAM.labels(t, op)
    text = REGISTRY.render()
    for t, op in legacy:
        assert f'seaweedfs_request_total{{type="{t}",op="{op}"}}' in text
        assert (f'seaweedfs_request_seconds_count{{type="{t}",op="{op}"}}'
                in text)
