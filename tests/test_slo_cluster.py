"""Chaos: the end-to-end judgment loop (ISSUE 13 acceptance).

A live cluster (in-process master + 4 subprocess volume servers via the
CLI) runs the canary prober and the SLO engine with second-scale burn
windows.  The test proves:

* a clean soak produces ZERO false-positive page-tier firings while
  every canary probe passes byte-identity;
* SIGKILL of a volume server under canary load fires the page-tier
  `availability` alert within the fast burn window, carrying an
  exemplar trace id that resolves through `/cluster/traces`;
* the `ec-exposure` alert fires while dead-node mass repair has volumes
  queued below full redundancy;
* both alerts transition to resolved after mass repair completes and
  the dead node leaves the probe set.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import time
import urllib.request

import pytest

from helpers import free_port, make_volume

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# master pulse: subprocess volume servers full-beat every 3s (the CLI
# default), so the dead-node window must be 3 pulses of >= that
PULSE_S = 3.0
# burn windows at 1/200 scale: page tier evaluates 1.5s/18s
WINDOW_SCALE = 0.005
CANARY_TICK_S = 0.3
SLO_TICK_S = 0.4


def _spawn_volume(tmp_path, i, master_port):
    d = tmp_path / f"vol{i}"
    d.mkdir()
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    port = free_port()
    proc = subprocess.Popen(
        [sys.executable, "-m", "seaweedfs_tpu", "volume",
         "-dir", str(d), "-mserver", f"127.0.0.1:{master_port}",
         "-ip", "127.0.0.1", "-port", str(port),
         "-rack", f"rack{i % 2}", "-max", "30"],
        cwd=str(tmp_path), env=env,
        stdout=subprocess.DEVNULL, stderr=subprocess.STDOUT)
    return proc, f"127.0.0.1:{port}"


def _get_json(url, timeout=10):
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return json.loads(r.read())


def _wait(cond, deadline_s, what):
    deadline = time.time() + deadline_s
    while time.time() < deadline:
        if cond():
            return
        time.sleep(0.2)
    raise TimeoutError(what)


def _page_firings(master, since_idx=0):
    hist = list(master.slo.alert_history)[since_idx:]
    return [h for h in hist
            if h["severity"] == "page" and h["state"] == "firing"]


@pytest.mark.chaos
def test_chaos_kill_volume_server_fires_and_resolves(tmp_path):
    from seaweedfs_tpu.master.server import MasterServer
    from seaweedfs_tpu.shell.commands import CommandEnv, run_command

    jd = tmp_path / "journal"
    jd.mkdir()
    master = MasterServer(
        ip="127.0.0.1", port=free_port(), pulse_seconds=PULSE_S,
        lifecycle_dir=str(jd),
        slo_interval=SLO_TICK_S, canary_interval=0.0,
        slo_window_scale=WINDOW_SCALE)
    # CI boxes run this alongside heavy suites: a loaded host can push a
    # round trip past the 2s production default without being an outage
    master.canary.timeout_s = 5.0
    master.start()
    procs = []
    try:
        nodes = []
        for i in range(4):
            proc, addr = _spawn_volume(tmp_path, i, master.port)
            procs.append(proc)
            nodes.append(addr)
        _wait(lambda: len(master.topo.nodes) == 4, 30,
              "4 volume servers registered")

        # writable volumes on every node + payload objects to EC-encode
        # (placement is random: keep growing until all 4 nodes hold one)
        def covered():
            with master.topo.lock:
                return sum(1 for n in master.topo.nodes.values()
                           if n.volumes) == 4

        _get_json(f"http://127.0.0.1:{master.port}/vol/grow?count=10")
        for _ in range(8):
            deadline = time.time() + 6
            while time.time() < deadline and not covered():
                time.sleep(0.3)
            if covered():
                break
            _get_json(f"http://127.0.0.1:{master.port}/vol/grow?count=4")
        _wait(covered, 10, "every node holds a volume")
        fids = []
        for i in range(24):
            a = _get_json(
                f"http://127.0.0.1:{master.port}/dir/assign?count=1")
            body = os.urandom(1500)
            req = urllib.request.Request(
                f"http://{a['url']}/{a['fid']}", data=body,
                headers={"Content-Type": "application/octet-stream"},
                method="POST")
            urllib.request.urlopen(req, timeout=10).read()
            fids.append((a["fid"], a["url"]))

        # EC-encode three volumes that actually HOLD data (an empty
        # volume has no live needle for the degraded-read canary) so a
        # node death creates real exposure
        env = CommandEnv(f"127.0.0.1:{master.grpc_port}")
        vids = sorted({int(fid.split(",")[0]) for fid, _u in fids})[:3]
        for vid in vids:
            out = run_command(env, f"ec.encode -volumeId={vid}")
            assert "error" not in out.lower(), out
        # spread the shards EXPLICITLY so every node holds <= 4 of each
        # volume's 14: losing any one node must create EXPOSURE (>= 10
        # survivors, mass-repairable), never data loss
        from seaweedfs_tpu.shell.ec_commands import apply_ec_move

        def per_node_shards(v):
            per: dict = {}
            for sid, ns in master.topo.lookup_ec_shards(v).items():
                for n in ns:
                    per.setdefault(n.id, []).append(sid)
            return per

        for v in vids:
            _wait(lambda v=v: len(master.topo.lookup_ec_shards(v)) == 14,
                  30, f"vid {v}: all 14 shards registered")
            per = per_node_shards(v)
            spare = [nid for nid in nodes for _ in range(4)]
            for nid in per:
                for _sid in per[nid][:4]:
                    spare.remove(nid)
            for nid, sids in sorted(per.items()):
                for sid in sids[4:]:
                    target = spare.pop(0)
                    apply_ec_move(env, {
                        "volumeId": v, "shardId": sid,
                        "source": nid, "target": target})

        def spread():
            for v in vids:
                per = per_node_shards(v)
                held = sum(len(s) for s in per.values())
                if held != 14 or 14 - max(
                        len(s) for s in per.values()) < 10:
                    return False
            return True

        _wait(spread, 30, "EC shards spread <= 4 per node")

        # -- clean soak: zero page-tier false positives ------------------
        # The canary starts AFTER setup, like an operator's would: the
        # encode/move churn above leaves gRPC channels between the
        # subprocess servers in reconnect backoff for tens of seconds,
        # and probes against that are honest degraded-capability errors,
        # not the false positives this soak measures.
        master.canary.interval_s = CANARY_TICK_S
        master.canary.start()
        def error_count():
            total = 0.0
            from seaweedfs_tpu.stats.metrics import REGISTRY
            for name, v in REGISTRY.snapshot_samples(max_samples=1 << 20):
                if (name.startswith("seaweedfs_canary_probe_total")
                        and 'result="error"' in name):
                    total += v
            return total

        # quiet for a full LONG window: an error still inside it keeps
        # burnLong hot, and one fresh soak blip would then co-fire both
        long_window_s = 3600.0 * WINDOW_SCALE
        last_count, last_change = error_count(), time.time()
        deadline = time.time() + 90
        while time.time() - last_change < long_window_s + 1.0:
            if time.time() > deadline:
                raise TimeoutError("canary error-free baseline")
            time.sleep(0.5)
            cur = error_count()
            if cur != last_count:
                last_count, last_change = cur, time.time()
        soak_s = 8.0
        hist_before = len(master.slo.alert_history)
        time.sleep(soak_s)
        assert _page_firings(master, hist_before) == [], (
            f"false-positive page alerts during clean soak: "
            f"{_page_firings(master, hist_before)}")
        st = master.canary.status()
        assert st["byteMismatches"] == 0
        vt = st["probes"]["volume_rt"]["targets"]
        # a node whose only volumes were EC-encoded away has nothing
        # writable and is legitimately not write-probed — but at least
        # 3 of 4 nodes are, and NONE of the probes errored
        assert len(vt) >= 3 and all(
            t["result"] == "ok" for t in vt.values()), vt
        ec_probe = st["probes"]["ec_degraded"]["targets"]
        assert ec_probe and all(
            t["result"] == "ok" for t in ec_probe.values()), ec_probe

        # -- SIGKILL a shard-holding, volume-holding node ----------------
        with master.topo.lock:
            victim_id = next(
                n.id for n in master.topo.nodes.values()
                if n.ec_shards and any(
                    not v.read_only for v in n.volumes.values()))
        victim = procs[nodes.index(victim_id)]
        hist_idx = len(master.slo.alert_history)
        t_kill = time.time()
        victim.send_signal(signal.SIGKILL)
        victim.wait(timeout=10)

        # availability page alert within the fast window (+ detection
        # lag: the canary stops probing the node 3 missed pulses after
        # the kill, so errors accumulate for ~3*PULSE_S first)
        fast_window_s = 300.0 * WINDOW_SCALE
        bound_s = 3 * PULSE_S + fast_window_s + 10.0
        _wait(lambda: any(h["slo"] == "availability"
                          for h in _page_firings(master, hist_idx)),
              bound_s, "availability page alert")
        fired_at = time.time() - t_kill
        avail = next(h for h in _page_firings(master, hist_idx)
                     if h["slo"] == "availability")
        assert fired_at <= bound_s

        # the alert carries an exemplar trace id that resolves through
        # the stitched-trace endpoint
        assert avail.get("exemplars"), avail
        tid = avail["exemplars"][0]["traceId"]
        doc = _get_json(f"http://127.0.0.1:{master.port}"
                        f"/cluster/traces?trace={tid}")
        assert doc["traceId"] == tid and doc["spans"], doc

        # exposure alert while mass repair has volumes queued
        _wait(lambda: any(
            h["slo"] == "ec-exposure" and h["state"] == "firing"
            for h in list(master.slo.alert_history)[hist_idx:]),
            bound_s + 30, "ec-exposure alert fired")

        # -- repair completes: everything resolves -----------------------
        def repaired():
            return (not master.mass_repair.pending()
                    and all(len(master.topo.lookup_ec_shards(v)) > 0
                            for v in vids))

        _wait(repaired, 90, "mass repair drained")

        def all_resolved():
            s = master.slo.status(evaluate_if_idle=False)["states"]
            return (s["availability"]["state"] == "ok"
                    and s["ec-exposure"]["state"] == "ok")

        _wait(all_resolved, 60, "alerts resolved after repair")
        hist = list(master.slo.alert_history)[hist_idx:]
        assert any(h["slo"] == "availability" and h["state"] == "ok"
                   for h in hist), hist
        assert any(h["slo"] == "ec-exposure" and h["state"] == "ok"
                   for h in hist), hist

        # canary byte identity held across the whole incident, and the
        # /cluster/alerts surface serves the full document over HTTP
        assert master.canary.status()["byteMismatches"] == 0
        doc = _get_json(
            f"http://127.0.0.1:{master.port}/cluster/alerts")
        assert doc["states"]["availability"]["state"] == "ok"
        assert any(h["state"] == "firing" for h in doc["history"])
        assert hist_before <= len(doc["history"])
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        for p in procs:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                pass
        master.stop()


@pytest.mark.chaos
def test_chaos_ec_canary_pages_on_decode_rot(tmp_path):
    """A volume server whose EC decode path serves garbage (flipped
    shard byte) fails the drop-shard canary loudly — 'process up but
    serving garbage' is exactly what black-box probing exists to page
    on."""
    import shutil

    from seaweedfs_tpu.master.server import MasterServer
    from seaweedfs_tpu.storage.ec import constants as ecc
    from seaweedfs_tpu.storage.ec.encoder import (
        generate_ec_files,
        write_sorted_file_from_idx,
    )
    from seaweedfs_tpu.volume.server import VolumeServer

    master = MasterServer(ip="127.0.0.1", port=free_port(),
                          pulse_seconds=0.5)
    master.start()
    vol_dir = tmp_path / "vol"
    vol_dir.mkdir()
    vs = VolumeServer(
        directories=[str(vol_dir)],
        master_addresses=[f"127.0.0.1:{master.grpc_port}"],
        ip="127.0.0.1", port=free_port(), pulse_seconds=0.5,
        max_volume_count=8)
    vs.start()
    try:
        _wait(lambda: master.topo.nodes, 15, "node registered")
        stage = tmp_path / "stage"
        stage.mkdir()
        svol = make_volume(str(stage), volume_id=7, n_needles=6, seed=3)
        base = svol.file_name()
        svol.close()
        generate_ec_files(base, large_block_size=10000,
                          small_block_size=100, codec_name="cpu",
                          slice_size=1 << 20)
        write_sorted_file_from_idx(base)
        tbase = vs.store.locations[0].base_name(7, "")
        shutil.copy(base + ".ecx", tbase + ".ecx")
        for sid in range(ecc.TOTAL_SHARDS):
            shutil.copy(base + ecc.to_ext(sid), tbase + ecc.to_ext(sid))
        vs.store.mount_ec_shards(7, "", list(range(ecc.TOTAL_SHARDS)))
        ev = vs.store.find_ec_volume(7)
        ev.large_block_size = 10000
        ev.small_block_size = 100
        _wait(lambda: any(n.ec_shards
                          for n in master.topo.nodes.values()),
              15, "ec shards in topology")
        st = master.canary.run_once()
        assert all(t["result"] == "ok" for t in
                   st["probes"]["ec_degraded"]["targets"].values())
        # rot a byte in a DATA shard the reconstruct path reads from
        ev._interval_cache and ev._interval_cache.clear()
        with open(tbase + ecc.to_ext(1), "r+b") as f:
            f.seek(10)
            b = f.read(1)
            f.seek(10)
            f.write(bytes([b[0] ^ 0xFF]))
        st = master.canary.run_once()
        results = [t["result"] for t in
                   st["probes"]["ec_degraded"]["targets"].values()]
        assert "error" in results, st["probes"]["ec_degraded"]
    finally:
        vs.stop()
        master.stop()
