"""replication/meta_backup.py coverage (ISSUE 12 satellite).

The continuous metadata-backup command was untested: these cover the
event->store apply decision tree (create/update/rename/delete), the
round-trip of a full traverse + incremental stream against a live filer,
resume-from-offset across backup restarts, and the torn-stream window
(an interrupted stream re-applies its overlap idempotently — the
documented ≤3s crash contract).
"""

from __future__ import annotations

import threading
import time

from helpers import free_port

from seaweedfs_tpu.filer.filerstore import make_store
from seaweedfs_tpu.pb import filer_pb2
from seaweedfs_tpu.replication.meta_backup import MetaBackup


def _entry(name: str, content: bytes = b"", directory: bool = False):
    e = filer_pb2.Entry(name=name, content=content,
                        is_directory=directory)
    e.attributes.mtime = int(time.time())
    e.attributes.file_mode = 0o40755 if directory else 0o644
    return e


def _event(directory, old=None, new=None, new_parent=""):
    resp = filer_pb2.SubscribeMetadataResponse(
        directory=directory, ts_ns=time.time_ns())
    if old is not None:
        resp.event_notification.old_entry.CopyFrom(old)
    if new is not None:
        resp.event_notification.new_entry.CopyFrom(new)
    resp.event_notification.new_parent_path = new_parent
    return resp


def _names(store, directory):
    return sorted(e.name for e in store.list_entries(directory,
                                                     limit=1000))


# ---------------------------------------------------------------------------
# apply_event decision tree (no cluster needed)
# ---------------------------------------------------------------------------


def test_apply_event_create_update_rename_delete():
    mb = MetaBackup("127.0.0.1:1", make_store("memory"))
    mb.apply_event(_event("/d", new=_entry("a", b"v1")))
    mb.apply_event(_event("/d", new=_entry("b", b"b1")))
    assert _names(mb.store, "/d") == ["a", "b"]
    # in-place update
    mb.apply_event(_event("/d", old=_entry("a", b"v1"),
                          new=_entry("a", b"v2")))
    assert bytes(mb.store.find_entry("/d", "a").content) == b"v2"
    # cross-directory rename = delete + insert
    mb.apply_event(_event("/d", old=_entry("b", b"b1"),
                          new=_entry("c", b"b1"), new_parent="/d2"))
    assert _names(mb.store, "/d") == ["a"]
    assert _names(mb.store, "/d2") == ["c"]
    # delete
    mb.apply_event(_event("/d", old=_entry("a", b"v2")))
    assert _names(mb.store, "/d") == []
    # no-op event (neither side named) is ignored
    mb.apply_event(_event("/d"))


def test_apply_event_replay_is_idempotent():
    """The ≤3s offset-save window replays events on restart: applying
    the same sequence twice must land in the same state."""
    mb = MetaBackup("127.0.0.1:1", make_store("memory"))
    events = [
        _event("/d", new=_entry("a", b"v1")),
        _event("/d", old=_entry("a", b"v1"), new=_entry("a", b"v2")),
        _event("/d", new=_entry("b", b"b1")),
        _event("/d", old=_entry("b", b"b1")),
    ]
    for ev in events:
        mb.apply_event(ev)
    first = {n: bytes(mb.store.find_entry("/d", n).content)
             for n in _names(mb.store, "/d")}
    for ev in events:  # torn-stream overlap: full replay
        mb.apply_event(ev)
    second = {n: bytes(mb.store.find_entry("/d", n).content)
              for n in _names(mb.store, "/d")}
    assert first == second == {"a": b"v2"}


def test_offset_roundtrip_survives_restart():
    store = make_store("memory")
    mb = MetaBackup("127.0.0.1:1", store)
    assert mb.get_offset() is None
    mb.set_offset(123_456_789_000)
    # a NEW MetaBackup over the same store resumes where this one stopped
    mb2 = MetaBackup("127.0.0.1:1", store)
    assert mb2.get_offset() == 123_456_789_000


# ---------------------------------------------------------------------------
# live round trip: traverse + stream + resume-from-offset
# ---------------------------------------------------------------------------


def _start_cluster():
    from seaweedfs_tpu.filer.server import FilerServer
    from seaweedfs_tpu.master.server import MasterServer

    master = MasterServer(ip="127.0.0.1", port=free_port())
    master.start()
    filer = FilerServer(
        masters=[f"127.0.0.1:{master.grpc_port}"],
        ip="127.0.0.1", port=free_port(), store="memory",
    )
    filer.start()
    return master, filer


def test_backup_traverse_stream_and_resume():
    master, filer = _start_cluster()
    try:
        for i in range(5):
            filer.filer.create_entry(
                "/d", _entry(f"seed-{i}", f"s{i}".encode()))
        mb = MetaBackup(f"127.0.0.1:{filer.port}", make_store("memory"))
        copied = mb.traverse()
        assert copied >= 5
        assert _names(mb.store, "/d") == [f"seed-{i}" for i in range(5)]
        mb.set_offset(time.time_ns())
        # incremental: stream in a thread, mutate, watch the backup follow
        t = threading.Thread(target=mb.stream,
                             kwargs={"offset_every_s": 0.1}, daemon=True)
        t.start()
        time.sleep(0.3)
        filer.filer.create_entry("/d", _entry("live-1", b"l1"))
        filer.filer.delete_entry("/d", "seed-0")
        deadline = time.time() + 10
        while time.time() < deadline:
            if (mb.store.find_entry("/d", "live-1") is not None
                    and mb.store.find_entry("/d", "seed-0") is None):
                break
            time.sleep(0.05)
        assert mb.store.find_entry("/d", "live-1") is not None
        assert mb.store.find_entry("/d", "seed-0") is None
        mb.cancel()  # torn stream: offset persisted in finally
        t.join(timeout=10)
        assert not t.is_alive()
        saved = mb.get_offset()
        assert saved is not None and saved > 0
        # write WHILE the backup is down, then resume from the offset
        filer.filer.create_entry("/d", _entry("while-down", b"wd"))
        t2 = threading.Thread(target=mb.stream,
                              kwargs={"offset_every_s": 0.1}, daemon=True)
        t2.start()
        deadline = time.time() + 10
        while time.time() < deadline:
            if mb.store.find_entry("/d", "while-down") is not None:
                break
            time.sleep(0.05)
        assert mb.store.find_entry("/d", "while-down") is not None
        # and live-1 was not corrupted by the overlap replay
        assert bytes(mb.store.find_entry("/d", "live-1").content) == b"l1"
        mb.cancel()
        t2.join(timeout=10)
    finally:
        filer.stop()
        master.stop()
