"""Pallas kernel conformance (interpret mode on the CPU test mesh)."""

import numpy as np
import jax.numpy as jnp

from seaweedfs_tpu.ops import gf256
from seaweedfs_tpu.ops.rs_cpu import ReedSolomon
from seaweedfs_tpu.ops.rs_pallas import apply_matrix_pallas, parity_fn


def test_pallas_parity_matches_cpu():
    fn = parity_fn()  # interpret=None -> auto interpret on CPU
    rng = np.random.default_rng(0)
    data = rng.integers(0, 256, (10, 4096), dtype=np.uint8)
    got = np.asarray(fn(jnp.asarray(data)))
    shards = list(data) + [np.zeros(4096, np.uint8) for _ in range(4)]
    ReedSolomon().encode(shards)
    for i in range(4):
        assert np.array_equal(got[i], shards[10 + i])


def test_pallas_unaligned_width():
    fn = parity_fn()
    rng = np.random.default_rng(1)
    for b in (1, 100, 511, 513, 1000):
        data = rng.integers(0, 256, (10, b), dtype=np.uint8)
        got = np.asarray(fn(jnp.asarray(data)))
        shards = list(data) + [np.zeros(b, np.uint8) for _ in range(4)]
        ReedSolomon().encode(shards)
        for i in range(4):
            assert np.array_equal(got[i], shards[10 + i]), (b, i)


def test_pallas_u32_entry():
    fn = parity_fn()
    rng = np.random.default_rng(2)
    data = rng.integers(0, 256, (10, 2048), dtype=np.uint8)
    got = np.asarray(fn.as_u32(jnp.asarray(data.view(np.uint32))))
    shards = list(data) + [np.zeros(2048, np.uint8) for _ in range(4)]
    ReedSolomon().encode(shards)
    got8 = got.view(np.uint8).reshape(4, -1) if got.dtype != np.uint8 else got
    for i in range(4):
        assert np.array_equal(np.ascontiguousarray(got8[i]), shards[10 + i])


def test_pallas_decode_matrix():
    rng = np.random.default_rng(3)
    rs = ReedSolomon()
    shards = [rng.integers(0, 256, 1024).astype(np.uint8) for _ in range(10)]
    shards += [np.zeros(1024, np.uint8) for _ in range(4)]
    rs.encode(shards)
    present = [0, 1, 4, 5, 6, 7, 8, 9, 10, 13]  # lost 2,3,11,12
    dec = gf256.decode_matrix_for(gf256.rs_matrix(10, 14), 10, present)
    inputs = jnp.asarray(np.stack([shards[i] for i in present]))
    rebuilt = np.asarray(apply_matrix_pallas(dec, inputs))
    for i in range(10):
        assert np.array_equal(rebuilt[i], shards[i])


def test_codec_registry_pallas():
    from seaweedfs_tpu.ops.codec import get_codec

    c = get_codec("tpu")
    assert c.impl == "pallas"
    rng = np.random.default_rng(4)
    shards = [rng.integers(0, 256, 512).astype(np.uint8) for _ in range(10)]
    shards += [np.zeros(512, np.uint8) for _ in range(4)]
    ref = [s.copy() for s in shards]
    ReedSolomon().encode(ref)
    c.encode(shards)
    for i in range(14):
        assert np.array_equal(shards[i], ref[i])
