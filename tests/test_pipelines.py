"""Async pipeline tests: notification publishers, replication replay into
local/filer sinks, and the message broker's publish/subscribe/persistence.

Reference analogues: weed/replication/replicator.go event mapping and the
broker rpcs of weed/messaging (SURVEY.md §2.6).
"""

import socket
import threading
import time
import urllib.request

import pytest

from seaweedfs_tpu.notification import FilePublisher, MemoryPublisher, make_publisher
from seaweedfs_tpu.notification.publishers import ConfigurationError
from seaweedfs_tpu.pb import filer_pb2
from seaweedfs_tpu.pb import messaging_pb2 as mq
from seaweedfs_tpu.pb import rpc as rpclib
from seaweedfs_tpu.replication import FilerSource, LocalSink, Replicator
from seaweedfs_tpu.messaging.broker import hash_ring_owner


def _free_port() -> int:
    from helpers import free_port

    return free_port()


# -- notification ------------------------------------------------------------


def _event(old=None, new=None):
    ev = filer_pb2.EventNotification()
    if old:
        ev.old_entry.name = old
    if new:
        ev.new_entry.name = new
    return ev


def test_notification_file_roundtrip(tmp_path):
    path = str(tmp_path / "events.jsonl")
    pub = FilePublisher(path)
    pub.publish("/a/b", _event(new="b"))
    pub.publish("/a/b", _event(old="b"))
    pub.close()
    events = FilePublisher.read_events(path)
    assert len(events) == 2
    assert events[0][0] == "/a/b"
    assert events[0][1].new_entry.name == "b"
    assert events[1][1].old_entry.name == "b"


def test_notification_gated_backends():
    with pytest.raises(ConfigurationError):
        make_publisher("kafka")
    assert isinstance(make_publisher("memory"), MemoryPublisher)


# -- replicator event mapping (pure) -----------------------------------------


class RecordingSink:
    def __init__(self):
        self.ops = []

    def create_entry(self, d, e, data):
        self.ops.append(("create", d, e.name, data))

    def update_entry(self, d, e, data):
        self.ops.append(("update", d, e.name, data))

    def delete_entry(self, d, name, is_dir):
        self.ops.append(("delete", d, name, is_dir))


class FakeSource:
    filer_http = "unused"

    def read_entry_data(self, directory, entry):
        return b"<" + entry.name.encode() + b">"


def test_replicator_event_mapping():
    sink = RecordingSink()
    rep = Replicator(FakeSource(), sink)
    rep.process_event("/d", _event(new="a"))           # create
    rep.process_event("/d", _event(old="a"))           # delete
    rep.process_event("/d", _event(old="a", new="a"))  # update in place
    ev = _event(old="a", new="b")                      # rename
    ev.new_parent_path = "/d2"
    rep.process_event("/d", ev)
    assert sink.ops == [
        ("create", "/d", "a", b"<a>"),
        ("delete", "/d", "a", False),
        ("create", "/d", "a", b"<a>"),  # in-place update = overwrite
        ("delete", "/d", "a", False),   # rename = delete old + create new
        ("create", "/d2", "b", b"<b>"),
    ]


# -- live replication + broker over a mini-cluster ---------------------------


@pytest.fixture(scope="module")
def pipeline_cluster(tmp_path_factory):
    from seaweedfs_tpu.filer.server import FilerServer
    from seaweedfs_tpu.master.server import MasterServer
    from seaweedfs_tpu.messaging.broker import MessageBrokerServer
    from seaweedfs_tpu.notification import MemoryPublisher
    from seaweedfs_tpu.volume.server import VolumeServer

    master = MasterServer(ip="127.0.0.1", port=_free_port(),
                          volume_size_limit_mb=64)
    master.start()
    vs = VolumeServer(
        directories=[str(tmp_path_factory.mktemp("pvol"))],
        master_addresses=[f"127.0.0.1:{master.grpc_port}"],
        ip="127.0.0.1", port=_free_port(), pulse_seconds=0.5,
        max_volume_count=100,
    )
    vs.start()
    deadline = time.time() + 15
    while time.time() < deadline and len(master.topo.nodes) < 1:
        time.sleep(0.1)
    notify = MemoryPublisher()
    filer = FilerServer(
        masters=[f"127.0.0.1:{master.grpc_port}"],
        ip="127.0.0.1", port=_free_port(), store="memory",
        notification=notify,
    )
    filer.start()
    broker = MessageBrokerServer(
        filer=f"127.0.0.1:{filer.port}", ip="127.0.0.1", port=_free_port()
    )
    broker.start()
    yield master, vs, filer, broker, notify
    broker.stop()
    filer.stop()
    vs.stop()
    master.stop()


def _put(filer_port, path, data):
    req = urllib.request.Request(
        f"http://127.0.0.1:{filer_port}{path}", data=data, method="PUT"
    )
    with urllib.request.urlopen(req, timeout=15) as r:
        return r.status


def test_filer_notification_published(pipeline_cluster):
    _master, _vs, filer, _broker, notify = pipeline_cluster
    _put(filer.port, "/notif/x.txt", b"hello")
    deadline = time.time() + 5
    while time.time() < deadline:
        if any(k.endswith("/x.txt") for k, _ in notify.events):
            break
        time.sleep(0.05)
    keys = [k for k, _ in notify.events]
    assert any(k == "/notif/x.txt" for k in keys), keys


def test_replication_to_local_sink(pipeline_cluster, tmp_path):
    _master, _vs, filer, _broker, _notify = pipeline_cluster
    sink_dir = tmp_path / "mirror"
    rep = Replicator(
        FilerSource(f"127.0.0.1:{filer.port}"), LocalSink(str(sink_dir)),
        path_prefix="/repl",
    )
    stop = threading.Event()
    t = threading.Thread(target=rep.run, args=(stop,), daemon=True)
    t.start()
    time.sleep(0.3)
    _put(filer.port, "/repl/docs/a.txt", b"replicated!")
    deadline = time.time() + 10
    target = sink_dir / "repl" / "docs" / "a.txt"
    while time.time() < deadline and not target.exists():
        time.sleep(0.1)
    assert target.exists(), "file did not replicate"
    assert target.read_bytes() == b"replicated!"
    # deletes propagate too
    req = urllib.request.Request(
        f"http://127.0.0.1:{filer.port}/repl/docs/a.txt", method="DELETE"
    )
    urllib.request.urlopen(req, timeout=10)
    deadline = time.time() + 10
    while time.time() < deadline and target.exists():
        time.sleep(0.1)
    assert not target.exists(), "delete did not replicate"
    stop.set()


def test_broker_publish_subscribe(pipeline_cluster):
    _master, _vs, _filer, broker, _notify = pipeline_cluster
    stub = rpclib.Stub(rpclib.MESSAGING, broker.grpc_address)

    def publish_msgs():
        yield mq.PublishRequest(
            init=mq.PublishRequest.InitMessage(
                namespace="ns", topic="chat", partition=0
            )
        )
        for i in range(3):
            yield mq.PublishRequest(
                data=mq.Message(
                    event_time_ns=time.time_ns(),
                    key=f"k{i}".encode(),
                    value=f"payload-{i}".encode(),
                )
            )

    responses = list(stub.Publish(publish_msgs()))
    assert responses[0].config.partition_count == 1
    assert responses[-1].is_closed

    def subscribe_msgs():
        yield mq.SubscriberMessage(
            init=mq.SubscriberMessage.InitMessage(
                namespace="ns", topic="chat", partition=0,
                startPosition=mq.SubscriberMessage.InitMessage.EARLIEST,
                subscriber_id="t1",
            )
        )
        time.sleep(3)

    got = []
    for msg in stub.Subscribe(subscribe_msgs()):
        got.append(msg.data.value.decode())
        if len(got) == 3:
            break
    assert got == ["payload-0", "payload-1", "payload-2"]


def test_broker_persistence_across_restart(pipeline_cluster):
    """Messages survive a broker restart via the filer log file
    (topic_manager.go + filer segment files)."""
    from seaweedfs_tpu.messaging.broker import MessageBrokerServer

    _master, _vs, filer, broker, _notify = pipeline_cluster
    stub = rpclib.Stub(rpclib.MESSAGING, broker.grpc_address)

    def publish_msgs():
        yield mq.PublishRequest(
            init=mq.PublishRequest.InitMessage(
                namespace="ns", topic="durable", partition=0
            )
        )
        yield mq.PublishRequest(
            data=mq.Message(event_time_ns=1, key=b"k", value=b"still-here")
        )

    list(stub.Publish(publish_msgs()))
    broker.flush()  # force the batched log append to the filer
    # a brand-new broker process (same filer) replays the log
    b2 = MessageBrokerServer(filer=f"127.0.0.1:{filer.port}",
                             ip="127.0.0.1", port=_free_port())
    b2.start()
    try:
        stub2 = rpclib.Stub(rpclib.MESSAGING, b2.grpc_address)

        def subscribe_msgs():
            yield mq.SubscriberMessage(
                init=mq.SubscriberMessage.InitMessage(
                    namespace="ns", topic="durable", partition=0,
                    startPosition=mq.SubscriberMessage.InitMessage.EARLIEST,
                )
            )
            time.sleep(2)

        got = []
        for msg in stub2.Subscribe(subscribe_msgs()):
            got.append(msg.data.value)
            break
        assert got == [b"still-here"]
    finally:
        b2.stop()


def test_hash_ring_owner_stable():
    brokers = ["b1:1", "b2:2", "b3:3"]
    owners = {f"ns/t/{p}": hash_ring_owner(brokers, f"ns/t/{p}")
              for p in range(20)}
    # deterministic
    assert owners == {k: hash_ring_owner(brokers, k) for k in owners}
    # uses more than one broker across partitions
    assert len(set(owners.values())) > 1
    # removing a broker only moves its own keys
    survivors = brokers[:2]
    for k, owner in owners.items():
        if owner in survivors:
            assert hash_ring_owner(survivors, k) == owner


def test_filer_sync_no_loop(pipeline_cluster, tmp_path_factory):
    """Bidirectional sync with a shared signature: a write on A lands on B
    exactly once and does NOT ping-pong back (command/filer_sync.go)."""
    from seaweedfs_tpu.filer.server import FilerServer
    from seaweedfs_tpu.replication.sink import FilerSink

    master, _vs, filer_a, _broker, _notify = pipeline_cluster
    filer_b = FilerServer(
        masters=[f"127.0.0.1:{master.grpc_port}"],
        ip="127.0.0.1", port=_free_port(), store="memory",
    )
    filer_b.start()
    try:
        sig = 424242
        a_addr = f"127.0.0.1:{filer_a.port}"
        b_addr = f"127.0.0.1:{filer_b.port}"
        ra = Replicator(FilerSource(a_addr), FilerSink(b_addr, signature=sig),
                        "/sync", signature=sig)
        rb = Replicator(FilerSource(b_addr), FilerSink(a_addr, signature=sig),
                        "/sync", signature=sig)
        stop = threading.Event()
        threading.Thread(target=ra.run, args=(stop,), daemon=True).start()
        threading.Thread(target=rb.run, args=(stop,), daemon=True).start()
        time.sleep(0.3)
        _put(filer_a.port, "/sync/f.txt", b"one way")
        deadline = time.time() + 10
        while time.time() < deadline:
            if filer_b.filer.find_entry("/sync/f.txt") is not None:
                break
            time.sleep(0.1)
        assert filer_b.filer.find_entry("/sync/f.txt") is not None
        # let any (wrong) ping-pong develop, then check it didn't
        before = ra.replicated + rb.replicated
        time.sleep(1.5)
        after = ra.replicated + rb.replicated
        assert after == before, (
            f"replication kept firing ({before} -> {after}): sync loop"
        )
        # ra saw the parent-dir creation + the file; rb must see NOTHING
        # (B's events carry the sync signature and are filtered out)
        assert ra.replicated >= 1 and rb.replicated == 0
        stop.set()
    finally:
        filer_b.stop()


def test_meta_backup_traverse_and_stream(pipeline_cluster, tmp_path):
    """filer.meta.backup: full BFS copy, then live events applied to the
    backup store, resume offset persisted (command/filer_meta_backup.go)."""
    from seaweedfs_tpu.replication.meta_backup import MetaBackup

    _master, _vs, filer, _broker, _notify = pipeline_cluster
    _put(filer.port, "/mb/a.txt", b"alpha")
    _put(filer.port, "/mb/sub/b.txt", b"beta")

    mb = MetaBackup.with_store(
        f"127.0.0.1:{filer.port}", "sqlite",
        str(tmp_path / "backup.db"), filer_dir="/mb")
    assert mb.get_offset() is None
    copied = mb.traverse()
    assert copied >= 3  # a.txt, sub, sub/b.txt
    assert mb.store.find_entry("/mb", "a.txt") is not None
    assert mb.store.find_entry("/mb/sub", "b.txt") is not None
    mb.set_offset(time.time_ns())

    stop = threading.Event()
    t = threading.Thread(target=lambda: mb.stream(stop), daemon=True)
    t.start()
    time.sleep(0.3)
    _put(filer.port, "/mb/c.txt", b"gamma")
    deadline = time.time() + 10
    while time.time() < deadline:
        if mb.store.find_entry("/mb", "c.txt") is not None:
            break
        time.sleep(0.05)
    assert mb.store.find_entry("/mb", "c.txt") is not None
    assert mb.get_offset() is not None and mb.get_offset() > 0
    stop.set()
    mb.cancel()  # interrupt the idle subscription; thread exits cleanly
    t.join(timeout=10)
    assert not t.is_alive()
