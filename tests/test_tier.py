"""Tiered storage backend tests: the BackendStorageFile seam, remote-tier
volume round-trip, and the S3 tier dogfooding the framework's own gateway.

Reference analogues: weed/storage/backend/backend.go:15-48,
volume_tier.go, shell/command_volume_tier_upload.go / _download.go.
"""

import os
import shutil
import socket
import time

import numpy as np
import pytest

from seaweedfs_tpu.storage.backend import (
    BackendStorage,
    DiskFile,
    RemoteBackendFile,
    get_backend,
    register_backend,
)
from seaweedfs_tpu.shell.volume_commands import _locate_volume
from seaweedfs_tpu.storage.volume import Volume

from helpers import make_volume


def _free_port() -> int:
    from helpers import free_port

    return free_port()


class DirBackend(BackendStorage):
    """Test tier: objects are files under a directory."""

    def __init__(self, backend_id, directory):
        super().__init__("dir", backend_id)
        self.directory = directory
        os.makedirs(directory, exist_ok=True)
        self.range_reads = 0

    def _p(self, key):
        return os.path.join(self.directory, key.replace("/", "_"))

    def upload_file(self, local_path, key, progress=None):
        shutil.copyfile(local_path, self._p(key))
        size = os.path.getsize(local_path)
        if progress:
            progress(size)
        return size

    def download_file(self, key, local_path, progress=None):
        shutil.copyfile(self._p(key), local_path)
        return os.path.getsize(local_path)

    def delete_file(self, key):
        if os.path.exists(self._p(key)):
            os.remove(self._p(key))

    def read_range(self, key, offset, size):
        self.range_reads += 1
        with open(self._p(key), "rb") as f:
            f.seek(offset)
            return f.read(size)


# -- seam unit tests --------------------------------------------------------


def test_disk_file(tmp_path):
    f = DiskFile(str(tmp_path / "x.dat"))
    assert f.file_size() == 0
    off = f.append(b"hello")
    assert off == 0
    f.write_at(5, b" world")
    assert f.read_at(0, 11) == b"hello world"
    f.truncate(5)
    assert f.file_size() == 5
    f.sync()
    f.close()


def test_remote_backend_file_block_cache(tmp_path):
    b = DirBackend("t", str(tmp_path / "store"))
    blob = os.urandom((2 << 20) + 777)
    src = tmp_path / "src.bin"
    src.write_bytes(blob)
    b.upload_file(str(src), "obj")
    rf = RemoteBackendFile(b, "obj", len(blob))
    # cross-block read
    lo = (1 << 20) - 100
    assert rf.read_at(lo, 300) == blob[lo : lo + 300]
    n = b.range_reads
    # same blocks again: served from cache
    assert rf.read_at(lo, 300) == blob[lo : lo + 300]
    assert b.range_reads == n
    # tail clamp + write rejection
    assert rf.read_at(len(blob) - 10, 100) == blob[-10:]
    with pytest.raises(PermissionError):
        rf.write_at(0, b"x")


# -- volume tier round-trip -------------------------------------------------


def test_volume_tier_roundtrip(tmp_path):
    backend = DirBackend("default", str(tmp_path / "tier"))
    register_backend(backend)
    vol = make_volume(str(tmp_path), volume_id=7, n_needles=30)
    want = {i: vol.read_needle(i).data for i in range(1, 31)}
    size = vol.tier_to_remote("dir.default")
    assert size > 0
    assert vol.is_remote and vol.read_only
    assert not os.path.exists(vol.file_name() + ".dat")
    # reads flow through ranged requests on the remote object
    for i in (1, 15, 30):
        assert vol.read_needle(i).data == want[i]
    from seaweedfs_tpu.storage.needle import Needle

    with pytest.raises(PermissionError):
        vol.append_needle(Needle(id=99, cookie=1, data=b"net new"))
    vol.close()

    # restart: a fresh Volume object finds the tier placement in the .vif
    vol2 = Volume(str(tmp_path), "", 7)
    assert vol2.is_remote
    for i in (2, 29):
        assert vol2.read_needle(i).data == want[i]
    # download back: writable again, remote object gone
    got = vol2.tier_to_local()
    assert got == size
    assert not vol2.is_remote and not vol2.read_only
    vol2.append_needle(Needle(id=99, cookie=1, data=b"net new"))
    assert vol2.read_needle(99).data == b"net new"
    assert not os.listdir(str(tmp_path / "tier"))
    vol2.close()


def test_volume_tier_keep_local(tmp_path):
    backend = DirBackend("keep", str(tmp_path / "tier"))
    register_backend(backend)
    vol = make_volume(str(tmp_path), volume_id=8, n_needles=5)
    vol.tier_to_remote("dir.keep", keep_local=True)
    assert os.path.exists(vol.file_name() + ".dat")
    assert vol.read_needle(3).id == 3
    vol.close()


def test_volume_tier_roundtrip_s3_stub(tmp_path):
    """Volume.tier_to_remote/tier_to_local against the S3 backend stub:
    signed-path-shaped HTTP all the way (PUT, ranged GET, DELETE)
    without a whole gateway cluster — the lifecycle controller's tier
    jobs drive exactly this surface (ISSUE 9 satellite: this round-trip
    was previously only reachable through shell commands)."""
    from helpers import start_s3_stub

    from seaweedfs_tpu.storage.backend_s3 import make_s3_backend

    stub, handler = start_s3_stub()
    try:
        endpoint = f"http://127.0.0.1:{stub.server_address[1]}"
        make_s3_backend("stubrt", {"endpoint": endpoint,
                                   "bucket": "tier-rt"})
        vol = make_volume(str(tmp_path), volume_id=17, n_needles=30)
        want = {i: vol.read_needle(i).data for i in range(1, 31)}
        size = vol.tier_to_remote("s3.stubrt")
        # keep_local defaults False: the local .dat is gone, the bytes
        # live in the bucket
        assert not os.path.exists(vol.file_name() + ".dat")
        assert len(handler.objects["/tier-rt/17.dat"]) == size
        # reads are served from the remote tier through ranged GETs
        before = handler.range_reads
        for i in (1, 15, 30):
            assert vol.read_needle(i).data == want[i]
        assert handler.range_reads > before
        vol.close()

        # a fresh load finds the remote placement via the .vif and the
        # download brings it back local + deletes the remote object
        vol2 = Volume(str(tmp_path), "", 17)
        assert vol2.is_remote
        got = vol2.tier_to_local()
        assert got == size
        assert "/tier-rt/17.dat" not in handler.objects
        assert not vol2.is_remote and not vol2.read_only
        for i in (2, 29):
            assert vol2.read_needle(i).data == want[i]
        vol2.close()
    finally:
        stub.shutdown()
        stub.server_close()


def test_volume_tier_s3_stub_keep_local(tmp_path):
    from helpers import start_s3_stub

    from seaweedfs_tpu.storage.backend_s3 import make_s3_backend

    stub, handler = start_s3_stub()
    try:
        endpoint = f"http://127.0.0.1:{stub.server_address[1]}"
        make_s3_backend("stubkeep", {"endpoint": endpoint,
                                     "bucket": "tier-keep"})
        vol = make_volume(str(tmp_path), volume_id=18, n_needles=5)
        want = vol.read_needle(3).data
        vol.tier_to_remote("s3.stubkeep", keep_local=True)
        assert os.path.exists(vol.file_name() + ".dat")
        assert "/tier-keep/18.dat" in handler.objects
        assert vol.read_needle(3).data == want
        vol.close()
    finally:
        stub.shutdown()
        stub.server_close()


def test_unconfigured_backend_fails_loud(tmp_path):
    backend = DirBackend("gone", str(tmp_path / "tier"))
    register_backend(backend)
    vol = make_volume(str(tmp_path), volume_id=9, n_needles=3)
    vol.tier_to_remote("dir.gone")
    vol.close()
    from seaweedfs_tpu.storage import backend as backend_mod

    del backend_mod._BACKENDS["dir.gone"]
    with pytest.raises(IOError):
        Volume(str(tmp_path), "", 9)
    register_backend(backend)  # restore for other tests


# -- S3 tier against the framework's own gateway ----------------------------


@pytest.fixture(scope="module")
def tier_cluster(tmp_path_factory):
    from seaweedfs_tpu.filer.server import FilerServer
    from seaweedfs_tpu.master.server import MasterServer
    from seaweedfs_tpu.s3api.server import S3ApiServer
    from seaweedfs_tpu.volume.server import VolumeServer

    master = MasterServer(ip="127.0.0.1", port=_free_port(),
                          volume_size_limit_mb=64)
    master.start()
    vols = []
    for i in range(2):
        vs = VolumeServer(
            directories=[str(tmp_path_factory.mktemp(f"tvol{i}"))],
            master_addresses=[f"127.0.0.1:{master.grpc_port}"],
            ip="127.0.0.1", port=_free_port(), pulse_seconds=0.5,
        )
        vs.start()
        vols.append(vs)
    deadline = time.time() + 15
    while time.time() < deadline and len(master.topo.nodes) < 2:
        time.sleep(0.1)
    filer = FilerServer(
        masters=[f"127.0.0.1:{master.grpc_port}"],
        ip="127.0.0.1", port=_free_port(), store="memory",
    )
    filer.start()
    s3 = S3ApiServer(filer=f"127.0.0.1:{filer.port}", port=_free_port())
    s3.start()
    yield master, vols, filer, s3
    s3.stop()
    filer.stop()
    for v in vols:
        v.stop()
    master.stop()


def test_s3_backend_tier_dogfood(tier_cluster, tmp_path):
    """A volume's .dat tiers into a bucket served by the same cluster;
    needle reads keep working through signed ranged GETs."""
    import urllib.request

    from seaweedfs_tpu.storage.backend_s3 import make_s3_backend

    _, vols, _, s3 = tier_cluster
    endpoint = f"http://127.0.0.1:{s3.port}"
    req = urllib.request.Request(f"{endpoint}/tier-bucket", method="PUT")
    with urllib.request.urlopen(req, timeout=10):
        pass
    make_s3_backend("dogfood", {"endpoint": endpoint, "bucket": "tier-bucket"})

    vol = make_volume(str(tmp_path), volume_id=42, n_needles=20, seed=5)
    want = {i: vol.read_needle(i).data for i in range(1, 21)}
    size = vol.tier_to_remote("s3.dogfood")
    assert size > 0 and vol.is_remote
    for i in (1, 10, 20):
        assert vol.read_needle(i).data == want[i]
    # bytes really live in the bucket (behind the gateway -> filer -> chunks)
    with urllib.request.urlopen(f"{endpoint}/tier-bucket/42.dat",
                                timeout=10) as r:
        assert len(r.read()) == size
    got = vol.tier_to_local()
    assert got == size and not vol.is_remote
    assert vol.read_needle(7).data == want[7]
    vol.close()


def test_s3_backend_multipart_upload(tier_cluster, tmp_path):
    """Files over the part size stream through the gateway's multipart API."""
    from seaweedfs_tpu.storage.backend_s3 import S3Backend

    _, _, _, s3 = tier_cluster
    endpoint = f"http://127.0.0.1:{s3.port}"
    import urllib.request

    req = urllib.request.Request(f"{endpoint}/mp-bucket", method="PUT")
    with urllib.request.urlopen(req, timeout=10):
        pass
    b = S3Backend("mp", endpoint, "mp-bucket")
    blob = os.urandom(5 << 20)
    src = tmp_path / "big.bin"
    src.write_bytes(blob)
    assert b.upload_file(str(src), "big", part_size=2 << 20) == len(blob)
    assert b.read_range("big", (3 << 20) - 50, 100) == blob[
        (3 << 20) - 50 : (3 << 20) + 50
    ]
    dst = tmp_path / "back.bin"
    assert b.download_file("big", str(dst)) == len(blob)
    assert dst.read_bytes() == blob
    b.delete_file("big")


def test_tier_grpc_and_shell(tier_cluster, tmp_path):
    """volume.tier.upload / volume.tier.download through the shell against
    a live volume server, dogfooding the gateway as the tier."""
    import urllib.request

    from seaweedfs_tpu.shell.commands import CommandEnv, run_command
    from seaweedfs_tpu.storage.backend_s3 import make_s3_backend

    master, vols, filer, s3 = tier_cluster
    endpoint = f"http://127.0.0.1:{s3.port}"
    req = urllib.request.Request(f"{endpoint}/shell-tier", method="PUT")
    with urllib.request.urlopen(req, timeout=10):
        pass
    make_s3_backend("shell", {"endpoint": endpoint, "bucket": "shell-tier"})

    # write one object through the cluster so a volume exists + has data
    data = b"tiered needle payload " * 100
    with urllib.request.urlopen(urllib.request.Request(
        f"http://127.0.0.1:{master.port}/dir/assign"
    ), timeout=10) as r:
        import json

        a = json.loads(r.read())
    fid, url = a["fid"], a["url"]
    boundary = "x123"
    body = (
        f"--{boundary}\r\nContent-Disposition: form-data; name=\"file\"; "
        f"filename=\"t.bin\"\r\n\r\n"
    ).encode() + data + f"\r\n--{boundary}--\r\n".encode()
    req = urllib.request.Request(
        f"http://{url}/{fid}", data=body, method="POST",
        headers={"Content-Type": f"multipart/form-data; boundary={boundary}"},
    )
    with urllib.request.urlopen(req, timeout=10):
        pass
    vid = int(fid.split(",")[0])

    env = CommandEnv(f"127.0.0.1:{master.grpc_port}")
    # the new volume reaches the topology via the next heartbeat delta
    deadline = time.time() + 10
    while time.time() < deadline:
        try:
            _locate_volume(env, vid)
            break
        except RuntimeError:
            time.sleep(0.2)
    out = run_command(
        env, f"volume.tier.upload -volumeId={vid} -dest=s3.shell"
    )
    assert "s3.shell" in out
    # the needle still reads through the cluster HTTP path (remote tier)
    with urllib.request.urlopen(f"http://{url}/{fid}", timeout=10) as r:
        assert r.read() == data
    out = run_command(env, f"volume.tier.download -volumeId={vid}")
    assert "downloaded" in out
    with urllib.request.urlopen(f"http://{url}/{fid}", timeout=10) as r:
        assert r.read() == data
