"""Filer plane tests: chunk interval model, stores, core namespace ops,
and the full cluster integration (master + volume servers + filer HTTP).

Reference test analogue: weed/filer/filechunks_test.go and the compose
harness (SURVEY.md §4 tiers 1 and 4).
"""

import importlib.util
import json
import socket
import time
import urllib.error
import urllib.request

import pytest

from seaweedfs_tpu.filer import filechunks
from seaweedfs_tpu.filer.filer import Filer, split_path
from seaweedfs_tpu.filer.filerstore import make_store
from seaweedfs_tpu.pb import filer_pb2


def chunk(fid, offset, size, mtime):
    return filer_pb2.FileChunk(file_id=fid, offset=offset, size=size, mtime=mtime)


# -- interval model (filechunks_test.go analogues) --------------------------


def test_visible_intervals_append():
    chunks = [chunk("1,a", 0, 100, 1), chunk("2,b", 100, 50, 2)]
    vis = filechunks.non_overlapping_visible_intervals(chunks)
    assert [(v.start, v.stop, v.file_id) for v in vis] == [
        (0, 100, "1,a"), (100, 150, "2,b"),
    ]
    assert filechunks.total_size(chunks) == 150


def test_visible_intervals_full_overwrite():
    chunks = [chunk("1,a", 0, 100, 1), chunk("2,b", 0, 100, 2)]
    vis = filechunks.non_overlapping_visible_intervals(chunks)
    assert [(v.start, v.stop, v.file_id) for v in vis] == [(0, 100, "2,b")]
    compacted, garbage = filechunks.compact_chunks(chunks)
    assert [c.file_id for c in compacted] == ["2,b"]
    assert [c.file_id for c in garbage] == ["1,a"]


def test_visible_intervals_partial_overwrite():
    # newer chunk punches a hole in the middle
    chunks = [chunk("1,a", 0, 100, 1), chunk("2,b", 30, 40, 2)]
    vis = filechunks.non_overlapping_visible_intervals(chunks)
    assert [(v.start, v.stop, v.file_id) for v in vis] == [
        (0, 30, "1,a"), (30, 70, "2,b"), (70, 100, "1,a"),
    ]
    # right remainder reads from within the old chunk at the right offset
    assert vis[2].chunk_offset == 70


def test_view_from_chunks_range():
    chunks = [chunk("1,a", 0, 100, 1), chunk("2,b", 100, 100, 2)]
    views = filechunks.view_from_chunks(chunks, 50, 100)
    assert [(v.file_id, v.offset, v.size, v.logical_offset) for v in views] == [
        ("1,a", 50, 50, 50), ("2,b", 0, 50, 100),
    ]


def test_minus_chunks():
    old = [chunk("1,a", 0, 10, 1), chunk("2,b", 10, 10, 1)]
    new = [chunk("2,b", 10, 10, 1), chunk("3,c", 0, 10, 2)]
    assert [c.file_id for c in filechunks.minus_chunks(old, new)] == ["1,a"]


# -- stores -----------------------------------------------------------------


@pytest.fixture(params=["memory", "sqlite", "leveldb", "leveldb2",
                        "leveldb3", "redis", "abstract_sql", "etcd",
                        "elastic7", "mongodb", "cassandra"])
def store(request, tmp_path):
    fake = None
    if request.param == "cassandra":
        from seaweedfs_tpu.util.cql import FakeCassandraServer

        fake = FakeCassandraServer()
        fake.start()
        s = make_store("cassandra", host="127.0.0.1", port=fake.port)
    elif request.param == "mongodb":
        from seaweedfs_tpu.util.mongo import FakeMongoServer

        fake = FakeMongoServer()
        fake.start()
        s = make_store("mongodb", host="127.0.0.1", port=fake.port)
    elif request.param == "etcd":
        from seaweedfs_tpu.util.etcd import FakeEtcdServer

        fake = FakeEtcdServer()
        fake.start()
        s = make_store("etcd", servers=f"127.0.0.1:{fake.port}")
    elif request.param == "elastic7":
        from seaweedfs_tpu.util.fake_elastic import FakeElasticServer

        fake = FakeElasticServer()
        fake.start()
        s = make_store("elastic7",
                       servers=f"http://127.0.0.1:{fake.port}")
    elif request.param == "sqlite":
        s = make_store("sqlite", path=str(tmp_path / "filer.db"))
    elif request.param == "leveldb":
        s = make_store("leveldb", path=str(tmp_path / "filerldb"))
    elif request.param == "leveldb2":
        s = make_store("leveldb2", path=str(tmp_path / "filerldb2"))
    elif request.param == "leveldb3":
        s = make_store("leveldb3", path=str(tmp_path / "filerldb3"))
    elif request.param == "abstract_sql":
        # the shared mysql/postgres SQL layer, driven by the stdlib
        # DB-API driver so its dialect plumbing is exercised offline
        import sqlite3

        from seaweedfs_tpu.filer.stores.sql_store import (
            AbstractSqlStore,
            SqliteDialect,
        )

        s = AbstractSqlStore(
            sqlite3.connect(str(tmp_path / "absql.db"),
                            check_same_thread=False),
            SqliteDialect(),
        )
    elif request.param == "redis":
        from seaweedfs_tpu.util.resp import FakeRedisServer

        fake = FakeRedisServer()
        fake.start()
        s = make_store("redis", host="127.0.0.1", port=fake.port)
    else:
        s = make_store("memory")
    yield s
    s.close()
    if fake is not None:
        fake.stop()


def entry(name, is_dir=False, content=b""):
    e = filer_pb2.Entry(name=name, is_directory=is_dir, content=content)
    return e


def test_store_crud(store):
    store.insert_entry("/d", entry("f1"))
    store.insert_entry("/d", entry("f2"))
    assert store.find_entry("/d", "f1").name == "f1"
    assert store.find_entry("/d", "zz") is None
    names = [e.name for e in store.list_entries("/d")]
    assert names == ["f1", "f2"]
    store.delete_entry("/d", "f1")
    assert store.find_entry("/d", "f1") is None


def test_store_listing_pagination_and_prefix(store):
    for n in ["a1", "a2", "b1", "b2", "c1"]:
        store.insert_entry("/p", entry(n))
    assert [e.name for e in store.list_entries("/p", limit=2)] == ["a1", "a2"]
    assert [e.name for e in store.list_entries("/p", start_from="a2")] == [
        "b1", "b2", "c1",
    ]
    assert [
        e.name for e in store.list_entries("/p", start_from="a2", inclusive=True)
    ] == ["a2", "b1", "b2", "c1"]
    assert [e.name for e in store.list_entries("/p", prefix="b")] == ["b1", "b2"]


def test_store_delete_folder_children(store):
    store.insert_entry("/x", entry("sub", is_dir=True))
    store.insert_entry("/x/sub", entry("f"))
    store.insert_entry("/x/sub/deep", entry("g"))
    store.insert_entry("/xother", entry("keep"))
    store.delete_folder_children("/x/sub")
    assert store.find_entry("/x/sub", "f") is None
    assert store.find_entry("/x/sub/deep", "g") is None
    assert store.find_entry("/xother", "keep").name == "keep"


def test_store_kv(store):
    store.kv_put(b"k", b"v")
    assert store.kv_get(b"k") == b"v"
    store.kv_put(b"k", b"")
    assert store.kv_get(b"k") is None


# -- filer core -------------------------------------------------------------


def test_filer_parent_dirs_and_listing():
    f = Filer(make_store("memory"))
    e = entry("file.txt", content=b"hello")
    f.create_entry("/a/b/c", e)
    # ancestors materialised
    assert f.find_entry("/a").is_directory
    assert f.find_entry("/a/b/c").is_directory
    assert f.find_entry("/a/b/c/file.txt").content == b"hello"
    assert [x.name for x in f.list_directory("/a/b")] == ["c"]
    f.close()


def test_filer_delete_recursive_collects_chunks():
    deleted = []
    f = Filer(make_store("memory"), delete_chunks_fn=deleted.extend)
    e = filer_pb2.Entry(name="data.bin")
    e.chunks.append(chunk("7,abc", 0, 10, 1))
    f.create_entry("/dir/sub", e)
    with pytest.raises(IsADirectoryError):
        f.delete_entry("/dir", "sub")  # non-recursive on non-empty dir
    f.delete_entry("/", "dir", is_recursive=True)
    f.drain_deletions()
    assert deleted == ["7,abc"]
    assert f.find_entry("/dir") is None
    f.close()


def test_filer_update_queues_shadowed_chunks():
    deleted = []
    f = Filer(make_store("memory"), delete_chunks_fn=deleted.extend)
    e = filer_pb2.Entry(name="f")
    e.chunks.append(chunk("1,old", 0, 10, 1))
    f.create_entry("/d", e)
    e2 = filer_pb2.Entry(name="f")
    e2.chunks.append(chunk("2,new", 0, 10, 2))
    f.update_entry("/d", e2)
    f.drain_deletions()
    assert deleted == ["1,old"]
    f.close()


def test_filer_rename_moves_subtree():
    f = Filer(make_store("memory"))
    f.create_entry("/old/sub", entry("f1", content=b"x"))
    f.rename_entry("/", "old", "/", "new")
    assert f.find_entry("/old") is None
    assert f.find_entry("/new/sub/f1").content == b"x"
    f.close()


def test_filer_metadata_log_subscription():
    import threading

    f = Filer(make_store("memory"))
    f.create_entry("/logs", entry("before", content=b"1"))
    stop = threading.Event()
    seen = []
    sub = f.meta_log.subscribe(0, "/logs", stop_event=stop)
    f.create_entry("/logs", entry("after", content=b"2"))
    for ev in sub:
        seen.append(ev)
        if ev.event_notification.new_entry.name == "after":
            stop.set()
            break
    names = [e.event_notification.new_entry.name for e in seen]
    assert "before" in names and "after" in names
    assert all(a.ts_ns < b.ts_ns for a, b in zip(seen, seen[1:]))
    f.close()


def test_bucket_collection_mapping():
    f = Filer(make_store("memory"))
    assert f.bucket_collection("/buckets/photos/2024/x.jpg") == "photos"
    assert f.bucket_collection("/notbuckets/x") == ""
    f.close()


def test_split_path():
    assert split_path("/") == ("/", "")
    assert split_path("/a") == ("/", "a")
    assert split_path("/a/b/c") == ("/a/b", "c")


# -- cluster integration ----------------------------------------------------


def _free_port() -> int:
    from helpers import free_port

    return free_port()


def _http(method, url, data=None, headers=None):
    req = urllib.request.Request(url, data=data, method=method,
                                 headers=headers or {})
    try:
        with urllib.request.urlopen(req, timeout=15) as r:
            return r.status, r.read()
    except urllib.error.HTTPError as e:
        return e.code, e.read()


@pytest.fixture(scope="module")
def filer_cluster(tmp_path_factory):
    from seaweedfs_tpu.filer.server import FilerServer
    from seaweedfs_tpu.master.server import MasterServer
    from seaweedfs_tpu.volume.server import VolumeServer

    master = MasterServer(ip="127.0.0.1", port=_free_port(),
                          volume_size_limit_mb=64)
    master.start()
    vols = []
    for i in range(2):
        vs = VolumeServer(
            directories=[str(tmp_path_factory.mktemp(f"fvol{i}"))],
            master_addresses=[f"127.0.0.1:{master.grpc_port}"],
            ip="127.0.0.1", port=_free_port(), pulse_seconds=0.5,
        )
        vs.start()
        vols.append(vs)
    deadline = time.time() + 15
    while time.time() < deadline and len(master.topo.nodes) < 2:
        time.sleep(0.1)
    filer = FilerServer(
        masters=[f"127.0.0.1:{master.grpc_port}"],
        ip="127.0.0.1", port=_free_port(),
        store="sqlite",
        store_path=str(tmp_path_factory.mktemp("filerdb") / "filer.db"),
        max_mb=1,  # force multi-chunk files with small uploads
    )
    filer.start()
    yield master, vols, filer
    filer.stop()
    for v in vols:
        v.stop()
    master.stop()


def test_filer_write_read_multichunk(filer_cluster):
    _, _, filer = filer_cluster
    base = f"http://127.0.0.1:{filer.port}"
    # 2.5 MB > max_mb=1 → 3 chunks
    payload = bytes(range(256)) * 10240
    code, body = _http("PUT", f"{base}/docs/big.bin", payload)
    assert code == 201, body
    entry = filer.filer.find_entry("/docs/big.bin")
    assert len(entry.chunks) == 3
    code, got = _http("GET", f"{base}/docs/big.bin")
    assert code == 200 and got == payload
    # range read spanning a chunk boundary
    req = urllib.request.Request(
        f"{base}/docs/big.bin", headers={"Range": "bytes=1048000-1049000"}
    )
    with urllib.request.urlopen(req, timeout=15) as r:
        assert r.status == 206
        assert r.read() == payload[1048000:1049001]


def test_filer_list_directory(filer_cluster):
    _, _, filer = filer_cluster
    base = f"http://127.0.0.1:{filer.port}"
    for name in ["a.txt", "b.txt", "c.txt"]:
        code, _ = _http("PUT", f"{base}/listdir/{name}", b"x")
        assert code == 201
    code, body = _http("GET", f"{base}/listdir/?limit=2")
    out = json.loads(body)
    assert [e["FullPath"] for e in out["Entries"]] == [
        "/listdir/a.txt", "/listdir/b.txt",
    ]
    assert out["ShouldDisplayLoadMore"]
    code, body = _http("GET", f"{base}/listdir/?lastFileName=b.txt")
    assert [e["FullPath"] for e in json.loads(body)["Entries"]] == [
        "/listdir/c.txt",
    ]


def test_filer_delete_removes_blobs(filer_cluster):
    _, vols, filer = filer_cluster
    base = f"http://127.0.0.1:{filer.port}"
    payload = b"deletable" * 1000
    code, _ = _http("PUT", f"{base}/del/zap.bin", payload)
    assert code == 201
    entry = filer.filer.find_entry("/del/zap.bin")
    fid = entry.chunks[0].file_id
    urls = filer.master_client.lookup_file_id(fid)
    assert urls
    code, _ = _http("DELETE", f"{base}/del/zap.bin")
    assert code == 204
    filer.filer.drain_deletions()
    assert filer.filer.find_entry("/del/zap.bin") is None
    # the chunk blob is gone from the volume server too
    deadline = time.time() + 10
    while time.time() < deadline:
        code, _ = _http("GET", urls[0])
        if code == 404:
            break
        time.sleep(0.2)
    assert code == 404


def test_filer_grpc_surface(filer_cluster):
    from seaweedfs_tpu.pb import rpc as rpclib

    _, _, filer = filer_cluster
    stub = rpclib.filer_stub(f"127.0.0.1:{filer.grpc_port}", timeout=15)
    # CreateEntry + LookupDirectoryEntry
    req = filer_pb2.CreateEntryRequest(directory="/grpc")
    req.entry.name = "hello.txt"
    req.entry.content = b"inline content"
    resp = stub.CreateEntry(req)
    assert not resp.error
    found = stub.LookupDirectoryEntry(
        filer_pb2.LookupDirectoryEntryRequest(directory="/grpc", name="hello.txt")
    )
    assert found.entry.content == b"inline content"
    # ListEntries stream
    names = [
        r.entry.name
        for r in stub.ListEntries(filer_pb2.ListEntriesRequest(directory="/grpc"))
    ]
    assert names == ["hello.txt"]
    # AtomicRenameEntry
    stub.AtomicRenameEntry(filer_pb2.AtomicRenameEntryRequest(
        old_directory="/grpc", old_name="hello.txt",
        new_directory="/grpc2", new_name="renamed.txt",
    ))
    assert filer.filer.find_entry("/grpc2/renamed.txt") is not None
    assert filer.filer.find_entry("/grpc/hello.txt") is None
    # KV
    stub.KvPut(filer_pb2.KvPutRequest(key=b"k1", value=b"v1"))
    assert stub.KvGet(filer_pb2.KvGetRequest(key=b"k1")).value == b"v1"
    # AssignVolume proxies the master
    a = stub.AssignVolume(filer_pb2.AssignVolumeRequest(count=1))
    assert not a.error and a.file_id
    # configuration
    conf = stub.GetFilerConfiguration(filer_pb2.GetFilerConfigurationRequest())
    assert conf.dir_buckets == "/buckets"


def test_filer_subscribe_metadata_grpc(filer_cluster):
    import threading

    from seaweedfs_tpu.pb import rpc as rpclib

    _, _, filer = filer_cluster
    stub = rpclib.filer_stub(f"127.0.0.1:{filer.grpc_port}")
    seen = []
    done = threading.Event()

    def consume():
        call = stub.SubscribeMetadata(
            filer_pb2.SubscribeMetadataRequest(
                client_name="test", path_prefix="/subtest", since_ns=0
            )
        )
        for ev in call:
            seen.append(ev)
            done.set()
            call.cancel()
            return

    t = threading.Thread(target=consume, daemon=True)
    t.start()
    time.sleep(0.3)
    base = f"http://127.0.0.1:{filer.port}"
    _http("PUT", f"{base}/subtest/notify.txt", b"event!")
    assert done.wait(10), "no metadata event received"
    assert seen[0].event_notification.new_entry.name in ("notify.txt", "subtest")


def test_leveldb_store_persistence_and_compaction(tmp_path):
    """Bitcask-style store: entries survive reopen; WAL compaction keeps
    live records and drops deleted ones."""
    import os

    from seaweedfs_tpu.filer.filerstore import make_store

    path = str(tmp_path / "ldb")
    s = make_store("leveldb", path=path, compact_bytes=2048)
    for i in range(30):
        s.insert_entry("/d", entry(f"f{i:03d}", content=b"x" * 100))
    for i in range(0, 30, 2):
        s.delete_entry("/d", f"f{i:03d}")
    s.kv_put(b"k1", b"v1")
    s.close()

    # reopen: replay snapshot + wal
    s2 = make_store("leveldb", path=path)
    names = [e.name for e in s2.list_entries("/d", limit=100)]
    assert names == [f"f{i:03d}" for i in range(1, 30, 2)]
    assert s2.find_entry("/d", "f001").content == b"x" * 100
    assert s2.find_entry("/d", "f000") is None
    assert s2.kv_get(b"k1") == b"v1"
    # the small compact_bytes forced at least one compaction: the wal
    # must be smaller than the data ever written
    assert os.path.getsize(os.path.join(path, "wal.log")) < 30 * 130
    s2.close()


def test_leveldb_store_torn_tail_heals(tmp_path):
    """A crash mid-append leaves a partial WAL record; the store must
    truncate it at load instead of refusing to start."""
    import os

    from seaweedfs_tpu.filer.filerstore import make_store

    path = str(tmp_path / "torn")
    s = make_store("leveldb", path=path)
    s.insert_entry("/d", entry("keep.txt", content=b"kept"))
    s.close()
    with open(os.path.join(path, "wal.log"), "ab") as f:
        f.write(b"\x01\x10\x00\x00\x00/partial")  # torn record
    s2 = make_store("leveldb", path=path)
    assert s2.find_entry("/d", "keep.txt").content == b"kept"
    # the torn bytes are gone and appends work again
    s2.insert_entry("/d", entry("after.txt", content=b"ok"))
    s2.close()
    s3 = make_store("leveldb", path=path)
    assert s3.find_entry("/d", "after.txt").content == b"ok"
    s3.close()


@pytest.mark.skipif(
    importlib.util.find_spec("cryptography") is None,
    reason="chunk encryption needs the cryptography package")
def test_cipher_round_trip_and_opaque_volume_bytes(tmp_path_factory):
    """-encryptVolumeData: chunks are AES-GCM sealed on upload, decrypted
    transparently on read; the bytes on the volume server reveal nothing
    (util/cipher.go)."""
    from seaweedfs_tpu.filer.server import FilerServer
    from seaweedfs_tpu.master.server import MasterServer
    from seaweedfs_tpu.volume.server import VolumeServer
    from helpers import free_port

    master = MasterServer(ip="127.0.0.1", port=free_port(),
                          volume_size_limit_mb=64)
    master.start()
    vdir = tmp_path_factory.mktemp("ciphervol")
    vs = VolumeServer(
        directories=[str(vdir)],
        master_addresses=[f"127.0.0.1:{master.grpc_port}"],
        ip="127.0.0.1", port=free_port(), pulse_seconds=0.5,
        max_volume_count=100,
    )
    vs.start()
    deadline = time.time() + 15
    while time.time() < deadline and len(master.topo.nodes) < 1:
        time.sleep(0.1)
    filer = FilerServer(
        masters=[f"127.0.0.1:{master.grpc_port}"],
        ip="127.0.0.1", port=free_port(), store="memory", max_mb=1,
        cipher=True,
    )
    filer.start()
    try:
        from seaweedfs_tpu.s3api.filer_client import FilerClient

        client = FilerClient(f"127.0.0.1:{filer.port}")
        secret = b"TOP-SECRET-" * 400  # spans the 1MB chunk? no, one chunk
        client.put_object("/sec/plan.txt", secret)
        # read back through the filer: plaintext
        code, _, body = client.get_object("/sec/plan.txt")
        assert code == 200 and body == secret
        # ranged read decrypts too
        code, _, body = client.get_object("/sec/plan.txt",
                                          range_header="bytes=4-13")
        assert code == 206 and body == secret[4:14]
        # chunk metadata carries the key; stored blob is opaque
        e = client.find_entry("/sec", "plan.txt")
        assert e.chunks and e.chunks[0].cipher_key
        dats = list(vdir.glob("*.dat"))
        assert dats
        raw = b"".join(p.read_bytes() for p in dats)
        assert b"TOP-SECRET-" not in raw
    finally:
        filer.stop()
        vs.stop()
        master.stop()


def test_redis_store_glob_metachar_paths():
    """Paths containing KEYS glob metacharacters delete exactly their own
    subtree — no orphans, no collateral deletion of glob-sibling paths."""
    from seaweedfs_tpu.filer.filerstore import make_store
    from seaweedfs_tpu.util.resp import FakeRedisServer

    fake = FakeRedisServer()
    fake.start()
    try:
        s = make_store("redis", host="127.0.0.1", port=fake.port)
        s.insert_entry("/docs[ab]", entry("child.txt"))
        s.insert_entry("/docs[ab]/deep", entry("g.txt"))
        s.insert_entry("/docsa", entry("keep.txt"))
        s.delete_folder_children("/docs[ab]")
        assert s.find_entry("/docs[ab]", "child.txt") is None
        assert s.find_entry("/docs[ab]/deep", "g.txt") is None
        assert s.find_entry("/docsa", "keep.txt") is not None
        s.close()
    finally:
        fake.stop()


def test_resp_client_reconnects():
    """One dropped connection must not wedge the store forever."""
    from seaweedfs_tpu.util.resp import FakeRedisServer, RespClient

    fake = FakeRedisServer()
    fake.start()
    try:
        c = RespClient("127.0.0.1", fake.port)
        assert c.command("SET", "k", "v") == "OK"
        # sever the transport behind the client's back
        c._sock.close()
        assert c.command("GET", "k") == b"v"  # reconnected transparently
        c.close()
    finally:
        fake.stop()


def test_sql_store_gated_kinds_and_dialects():
    """mysql/postgres kinds fail loud without their drivers; the dialect
    SQL text carries each backend's upsert form."""
    from seaweedfs_tpu.filer.stores.sql_store import (
        ConfigurationError,
        MysqlDialect,
        PostgresDialect,
        hash_string_to_long,
    )

    for kind in ("mysql", "postgres"):
        with pytest.raises(ConfigurationError):
            make_store(kind)

    assert "ON DUPLICATE KEY UPDATE" in MysqlDialect().upsert_suffix
    assert "ON CONFLICT" in PostgresDialect().upsert_suffix

    # md5-prefix signed int64 (util.HashStringToLong, weed/util/bytes.go:73)
    import hashlib

    h = hash_string_to_long("/some/dir")
    expect = int.from_bytes(hashlib.md5(b"/some/dir").digest()[:8],
                            "big", signed=True)
    assert h == expect


def test_leveldb2_partitions_span_directories(tmp_path):
    """Entries land in the md5-chosen partition; subtree delete reaches
    descendants that hash to OTHER partitions."""
    import os

    s = make_store("leveldb2", path=str(tmp_path / "ldb2"))
    dirs = [f"/d{i}" for i in range(32)]
    for d in dirs:
        s.insert_entry(d, filer_pb2.Entry(name="f.txt"))
    # with 32 directories the md5 routing should touch >1 partition dir
    used = [p for p in sorted(os.listdir(tmp_path / "ldb2"))
            if (tmp_path / "ldb2" / p / "wal.log").exists()]
    assert len(used) > 1, used
    for d in dirs:
        assert s.find_entry(d, "f.txt") is not None
    # subtree delete crosses partitions
    s.insert_entry("/t", filer_pb2.Entry(name="sub", is_directory=True))
    s.insert_entry("/t/sub", filer_pb2.Entry(name="leaf.txt"))
    s.delete_folder_children("/t")
    assert s.find_entry("/t/sub", "leaf.txt") is None
    s.close()


def test_leveldb3_bucket_partitioning(tmp_path):
    """leveldb3 routes /buckets/<b>/... into a per-bucket DB directory,
    drops the whole DB on bucket subtree delete (leveldb3_store.go:248-261),
    and survives a close/reopen with bucket DBs adopted from disk."""
    import os

    path = str(tmp_path / "ldb3")
    s = make_store("leveldb3", path=path)
    e = filer_pb2.Entry(name="o1")
    e.attributes.file_size = 7
    s.insert_entry("/buckets/b1/dirx", e)
    # objects at bucket TOP LEVEL (the common S3 shape) route to the
    # bucket DB too — the entry's FULL path decides, not its parent dir
    s.insert_entry("/buckets/b1", filer_pb2.Entry(name="top.txt"))
    s.insert_entry("/plain/dir", filer_pb2.Entry(name="p1"))
    # the bucket entry itself is a child of /buckets in _main
    s.insert_entry("/buckets", filer_pb2.Entry(name="b1", is_directory=True))
    # the bucket got its own partition on disk; plain paths go to _main
    assert os.path.isdir(os.path.join(path, "b1"))
    assert os.path.isdir(os.path.join(path, "_main"))
    assert s.find_entry("/buckets/b1/dirx", "o1").attributes.file_size == 7
    assert s.find_entry("/buckets/b1", "top.txt") is not None
    assert s.find_entry("/plain/dir", "p1") is not None
    assert [x.name for x in s.list_entries("/buckets/b1/dirx")] == ["o1"]
    assert [x.name for x in s.list_entries("/buckets/b1")] == ["top.txt"]
    assert [x.name for x in s.list_entries("/buckets")] == ["b1"]

    # reopen: bucket DBs adopted from disk
    s.close()
    s = make_store("leveldb3", path=path)
    assert s.find_entry("/buckets/b1/dirx", "o1").attributes.file_size == 7

    # whole-bucket delete drops the DB directory in O(1)
    s.delete_folder_children("/buckets/b1")
    assert not os.path.isdir(os.path.join(path, "b1"))
    assert s.find_entry("/buckets/b1/dirx", "o1") is None
    assert s.find_entry("/buckets/b1", "top.txt") is None
    assert s.find_entry("/plain/dir", "p1") is not None

    # wiping /buckets itself must drop EVERY bucket DB — a recreated
    # bucket must not resurrect old objects from a lazily-reopened DB
    s.insert_entry("/buckets/b2/d", filer_pb2.Entry(name="ghost.txt"))
    assert os.path.isdir(os.path.join(path, "b2"))
    s.delete_folder_children("/buckets")
    assert not os.path.isdir(os.path.join(path, "b2"))
    assert s.find_entry("/buckets/b2/d", "ghost.txt") is None
    s.close()


def test_filer_hardlink_rewrite_reclaims_shadowed_chunks():
    """Rewriting a hardlinked file in place must garbage-collect the
    shadowed shared chunks (every link sees the new content through the
    KV meta), and unlinking the last name reclaims the rest."""
    deleted = []
    f = Filer(make_store("memory"), delete_chunks_fn=deleted.extend)
    e = filer_pb2.Entry(name="a", hard_link_id=b"x" * 17,
                        hard_link_counter=2)
    e.chunks.append(chunk("1,old", 0, 10, 1))
    f.create_entry("/hl", e)
    b = filer_pb2.Entry(name="b", hard_link_id=b"x" * 17,
                        hard_link_counter=2)
    b.chunks.append(chunk("1,old", 0, 10, 1))
    f.create_entry("/hl", b)
    # rewrite a in place with a new chunk: the old shared chunk is
    # shadowed for EVERY link and must be queued
    e2 = filer_pb2.Entry(name="a", hard_link_id=b"x" * 17,
                         hard_link_counter=2)
    e2.chunks.append(chunk("2,new", 0, 10, 2))
    f.update_entry("/hl", e2)
    f.drain_deletions()
    assert deleted == ["1,old"]
    # both names read the new chunk through the KV meta
    assert [c.file_id for c in f.find_entry("/hl/b").chunks] == ["2,new"]
    # unlink both: data reclaimed exactly once, at the last unlink
    f.delete_entry("/hl", "a")
    f.drain_deletions()
    assert deleted == ["1,old"]
    f.delete_entry("/hl", "b")
    f.drain_deletions()
    assert deleted == ["1,old", "2,new"]
    f.close()


def test_sql_store_dirhash_collision_fails_loudly(monkeypatch):
    """A 64-bit dirhash collision between two directories must never
    silently replace the other directory's row (the reference's scoped
    update + loud failure, abstract_sql_store.go InsertEntry)."""
    import sqlite3

    import seaweedfs_tpu.filer.stores.sql_store as ss

    monkeypatch.setattr(ss, "hash_string_to_long", lambda s: 42)
    s = ss.AbstractSqlStore(
        sqlite3.connect(":memory:", check_same_thread=False),
        ss.SqliteDialect())
    e = filer_pb2.Entry(name="x")
    s.insert_entry("/dirA", e)
    with pytest.raises(ValueError, match="collision"):
        s.insert_entry("/dirB", filer_pb2.Entry(name="x"))
    # dirA's row survived and rewrites of it still work
    assert s.find_entry("/dirA", "x") is not None
    assert s.find_entry("/dirB", "x") is None
    s.insert_entry("/dirA", filer_pb2.Entry(name="x", content=b"v2"))
    assert s.find_entry("/dirA", "x").content == b"v2"
    s.close()
