"""Event-loop front end unit suite (ISSUE 18): keep-alive parking,
pipelining, chunked-body drain, idle-socket scale, oversized headers,
single-syscall response writes, and the backlog/front-end knobs."""

from __future__ import annotations

import json
import socket
import threading
import time
import weakref
from http.server import BaseHTTPRequestHandler

import pytest

from seaweedfs_tpu.util.httpd import (
    _BufferedSocketWriter,
    EventLoopHTTPServer,
    drain_request_body,
    eventloop_enabled,
    listen_backlog,
    make_http_server,
)


class EchoHandler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):
        pass

    def _reply(self, code: int, body: bytes):
        self.send_response(code)
        self.send_header("Content-Type", "application/octet-stream")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        if self.command != "HEAD":
            self.wfile.write(body)

    def do_GET(self):
        if self.path == "/slow":
            time.sleep(0.2)
        self._reply(200, b"path=%s" % self.path.encode())

    def do_HEAD(self):
        self._reply(200, b"path=%s" % self.path.encode())

    def do_POST(self):
        if self.path == "/drain":
            # early reply without reading the body: hygiene helper must
            # keep the connection usable for small chunked bodies
            drain_request_body(self, cap=1 << 16)
            self._reply(200, b"drained")
            return
        length = int(self.headers.get("Content-Length") or 0)
        body = self.rfile.read(length)
        self._reply(200, b"len=%d" % len(body))


@pytest.fixture
def loop_server():
    srv = EventLoopHTTPServer(("127.0.0.1", 0), EchoHandler)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    yield srv
    srv.shutdown()
    srv.server_close()


def _connect(srv) -> socket.socket:
    s = socket.create_connection(srv.server_address, timeout=10)
    s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    return s


# bytes received past a response body (the start of the next pipelined
# response) stashed between _read_response calls, keyed per socket
_RESP_LEFTOVER: "weakref.WeakKeyDictionary[socket.socket, bytes]" = (
    weakref.WeakKeyDictionary())


def _read_response(sock) -> tuple[int, bytes]:
    """One HTTP/1.1 response off the socket (Content-Length framing).

    Pipelined responses can arrive coalesced in a single recv; bytes
    past this response's body belong to the NEXT one, so they are
    stashed per-socket and consumed by the next call instead of being
    dropped."""
    buf = _RESP_LEFTOVER.pop(sock, b"")
    while b"\r\n\r\n" not in buf:
        chunk = sock.recv(65536)
        assert chunk, f"connection closed mid-headers: {buf!r}"
        buf += chunk
    head, rest = buf.split(b"\r\n\r\n", 1)
    status = int(head.split(b" ", 2)[1])
    length = 0
    for line in head.split(b"\r\n")[1:]:
        k, _, v = line.partition(b":")
        if k.strip().lower() == b"content-length":
            length = int(v.strip())
    while len(rest) < length:
        chunk = sock.recv(65536)
        assert chunk, "connection closed mid-body"
        rest += chunk
    if len(rest) > length:
        _RESP_LEFTOVER[sock] = rest[length:]
    return status, rest[:length]


def test_keepalive_sequential_requests(loop_server):
    s = _connect(loop_server)
    try:
        for i in range(5):
            s.sendall(b"GET /r%d HTTP/1.1\r\nHost: x\r\n\r\n" % i)
            code, body = _read_response(s)
            assert code == 200 and body == b"path=/r%d" % i
    finally:
        s.close()


def test_pipelined_requests(loop_server):
    s = _connect(loop_server)
    try:
        s.sendall(
            b"GET /a HTTP/1.1\r\nHost: x\r\n\r\n"
            b"GET /b HTTP/1.1\r\nHost: x\r\n\r\n"
            b"GET /c HTTP/1.1\r\nHost: x\r\n\r\n")
        for path in (b"/a", b"/b", b"/c"):
            code, body = _read_response(s)
            assert code == 200 and body == b"path=" + path
    finally:
        s.close()


def test_post_body_and_keepalive(loop_server):
    s = _connect(loop_server)
    try:
        payload = b"z" * 5000
        s.sendall(
            b"POST /p HTTP/1.1\r\nHost: x\r\n"
            b"Content-Length: %d\r\n\r\n" % len(payload) + payload)
        code, body = _read_response(s)
        assert code == 200 and body == b"len=5000"
        s.sendall(b"GET /after HTTP/1.1\r\nHost: x\r\n\r\n")
        code, body = _read_response(s)
        assert code == 200 and body == b"path=/after"
    finally:
        s.close()


def test_chunked_drain_keeps_connection(loop_server):
    s = _connect(loop_server)
    try:
        chunked = (b"POST /drain HTTP/1.1\r\nHost: x\r\n"
                   b"Transfer-Encoding: chunked\r\n\r\n"
                   b"5\r\nhello\r\n3\r\nxyz\r\n0\r\n\r\n")
        s.sendall(chunked)
        code, body = _read_response(s)
        assert code == 200 and body == b"drained"
        # the framing was fully consumed: the next request parses clean
        s.sendall(b"GET /next HTTP/1.1\r\nHost: x\r\n\r\n")
        code, body = _read_response(s)
        assert code == 200 and body == b"path=/next"
    finally:
        s.close()


def test_chunked_drain_with_trailers(loop_server):
    s = _connect(loop_server)
    try:
        s.sendall(b"POST /drain HTTP/1.1\r\nHost: x\r\n"
                  b"Transfer-Encoding: chunked\r\n\r\n"
                  b"4\r\nabcd\r\n0\r\nX-Trailer: 1\r\n\r\n")
        code, body = _read_response(s)
        assert code == 200 and body == b"drained"
        s.sendall(b"GET /t HTTP/1.1\r\nHost: x\r\n\r\n")
        code, body = _read_response(s)
        assert code == 200 and body == b"path=/t"
    finally:
        s.close()


def test_oversized_header_431(loop_server):
    s = _connect(loop_server)
    try:
        s.sendall(b"GET / HTTP/1.1\r\nHost: x\r\nX-Big: ")
        s.sendall(b"a" * (70 << 10))  # past MAX_HEADER_BYTES, no blank line
        code, _body = _read_response(s)
        assert code == 431
        # and the loop closed the connection
        s.settimeout(5)
        assert s.recv(1024) == b""
    finally:
        s.close()


def test_many_idle_sockets_stay_off_threads(loop_server):
    """Hundreds of idle keep-alive connections cost loop buffers, not
    worker threads — an active request still answers immediately."""
    idle = []
    try:
        for _ in range(200):
            idle.append(_connect(loop_server))
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            if len(loop_server._conns) >= 200:
                break
            time.sleep(0.01)
        assert len(loop_server._conns) >= 200
        # pool is far smaller than the socket count, yet requests flow
        assert loop_server._workers < 200
        s = _connect(loop_server)
        try:
            s.sendall(b"GET /live HTTP/1.1\r\nHost: x\r\n\r\n")
            code, body = _read_response(s)
            assert code == 200 and body == b"path=/live"
        finally:
            s.close()
        # the open-socket gauge tracks the parked population
        assert loop_server._open_gauge.value >= 200
    finally:
        for s in idle:
            s.close()


def test_concurrent_clients(loop_server):
    errs = []

    def worker(i):
        try:
            s = _connect(loop_server)
            try:
                for k in range(3):
                    s.sendall(b"GET /c%d-%d HTTP/1.1\r\nHost: x\r\n\r\n"
                              % (i, k))
                    code, body = _read_response(s)
                    assert code == 200
                    assert body == b"path=/c%d-%d" % (i, k)
            finally:
                s.close()
        except Exception as e:  # noqa: BLE001 — collected for the assert
            errs.append(e)

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(16)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(30)
    assert not errs, errs


def test_connection_close_honored(loop_server):
    s = _connect(loop_server)
    try:
        s.sendall(b"GET /bye HTTP/1.1\r\nHost: x\r\nConnection: close\r\n\r\n")
        code, body = _read_response(s)
        assert code == 200 and body == b"path=/bye"
        s.settimeout(5)
        assert s.recv(1024) == b""  # server closed after the response
    finally:
        s.close()


def test_idle_sweep_closes_stale_conns(monkeypatch):
    monkeypatch.setenv("SEAWEEDFS_TPU_LOOP_IDLE_TIMEOUT_S", "1")
    srv = EventLoopHTTPServer(("127.0.0.1", 0), EchoHandler)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    try:
        s = _connect(srv)
        deadline = time.monotonic() + 3
        while time.monotonic() < deadline and not srv._conns:
            time.sleep(0.01)
        assert srv._conns
        # force an immediate sweep rather than waiting the 5s cadence
        srv._sweep_idle(time.monotonic() + 10)
        s.settimeout(5)
        assert s.recv(1024) == b""
        s.close()
    finally:
        srv.shutdown()
        srv.server_close()


def test_shutdown_unblocks_and_closes(loop_server):
    s = _connect(loop_server)
    s.sendall(b"GET /x HTTP/1.1\r\nHost: x\r\n\r\n")
    code, _ = _read_response(s)
    assert code == 200
    loop_server.shutdown()
    assert loop_server._stopped.is_set()
    s.close()


# -- knobs and seam ----------------------------------------------------------


def test_listen_backlog_env_clamp(monkeypatch):
    monkeypatch.setenv("SEAWEEDFS_TPU_LISTEN_BACKLOG", "64")
    assert listen_backlog() == 64
    monkeypatch.setenv("SEAWEEDFS_TPU_LISTEN_BACKLOG", "0")
    assert listen_backlog() == 1  # floor
    monkeypatch.setenv("SEAWEEDFS_TPU_LISTEN_BACKLOG", "10000000")
    from seaweedfs_tpu.util.httpd import _somaxconn

    assert listen_backlog() == _somaxconn()  # somaxconn ceiling
    monkeypatch.setenv("SEAWEEDFS_TPU_LISTEN_BACKLOG", "garbage")
    assert listen_backlog() == 128  # default on parse failure


def test_eventloop_enabled_modes(monkeypatch):
    monkeypatch.delenv("SEAWEEDFS_TPU_EVENTLOOP", raising=False)
    assert eventloop_enabled("volume") is True  # default: volume only
    assert eventloop_enabled("filer") is False
    monkeypatch.setenv("SEAWEEDFS_TPU_EVENTLOOP", "all")
    assert eventloop_enabled("filer") is True
    monkeypatch.setenv("SEAWEEDFS_TPU_EVENTLOOP", "off")
    assert eventloop_enabled("volume") is False


def test_make_http_server_seam(monkeypatch):
    monkeypatch.setenv("SEAWEEDFS_TPU_EVENTLOOP", "off")
    srv = make_http_server(("127.0.0.1", 0), EchoHandler, surface="volume")
    assert not isinstance(srv, EventLoopHTTPServer)
    srv.server_close()
    monkeypatch.setenv("SEAWEEDFS_TPU_EVENTLOOP", "volume")
    srv = make_http_server(("127.0.0.1", 0), EchoHandler, surface="volume")
    assert isinstance(srv, EventLoopHTTPServer)
    srv.server_close()
    srv = make_http_server(("127.0.0.1", 0), EchoHandler, surface="filer")
    assert not isinstance(srv, EventLoopHTTPServer)
    srv.server_close()


class _CountingSock:
    """sendmsg-counting socket stand-in for the coalescing writer."""

    def __init__(self):
        self.calls = 0
        self.data = b""

    def sendmsg(self, parts):
        self.calls += 1
        blob = b"".join(bytes(p) for p in parts)
        self.data += blob
        return len(blob)


def test_buffered_writer_single_syscall():
    sock = _CountingSock()
    w = _BufferedSocketWriter(sock)
    w.write(b"HTTP/1.1 200 OK\r\n")
    w.write(b"Content-Length: 5\r\n")
    w.write(b"\r\n")
    w.write(b"hello")
    assert sock.calls == 0  # nothing hits the kernel before flush
    w.flush()
    assert sock.calls == 1  # header block + body in ONE sendmsg
    assert sock.data == (
        b"HTTP/1.1 200 OK\r\nContent-Length: 5\r\n\r\nhello")


def test_buffered_writer_interim_response_flushes_now():
    sock = _CountingSock()
    w = _BufferedSocketWriter(sock)
    w.write(b"HTTP/1.1 100 Continue\r\n\r\n")
    # a 100-continue cannot sit in the buffer: the client is waiting
    assert sock.calls == 1 and b"100 Continue" in sock.data


def test_buffered_writer_partial_sends():
    class Dribble(_CountingSock):
        def sendmsg(self, parts):
            self.calls += 1
            blob = b"".join(bytes(p) for p in parts)
            take = min(3, len(blob))
            self.data += blob[:take]
            return take

    sock = Dribble()
    w = _BufferedSocketWriter(sock)
    w.write(b"abcdefghij")
    w.flush()
    assert sock.data == b"abcdefghij"


def test_volume_server_runs_on_event_loop(monkeypatch):
    """The default wiring: serve_http on the volume surface hands back
    an EventLoopHTTPServer, and /status answers over it."""
    monkeypatch.delenv("SEAWEEDFS_TPU_EVENTLOOP", raising=False)

    class StatusHandler(EchoHandler):
        def do_GET(self):
            body = json.dumps({"ok": True}).encode()
            self._reply(200, body)

    srv = make_http_server(("127.0.0.1", 0), StatusHandler, surface="volume")
    assert isinstance(srv, EventLoopHTTPServer)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    try:
        import urllib.request

        with urllib.request.urlopen(
                "http://127.0.0.1:%d/status" % srv.server_address[1],
                timeout=10) as r:
            assert r.status == 200 and json.loads(r.read())["ok"] is True
    finally:
        srv.shutdown()
        srv.server_close()


# -- /debug/profile behind the event-loop front end --------------------------
#
# The sampler blocks its worker thread for the whole run, so the front
# end's job is to keep the *other* lanes honest while one is sampling:
# single-flight 409, kill-switch 403, bad-params 400 must all answer
# from fresh connections without queueing behind the in-flight run.


class DebugSurfaceHandler(EchoHandler):
    """The real debug surface mounted the way every server mounts it."""

    def do_GET(self):
        from seaweedfs_tpu.telemetry import serve_debug_http

        if serve_debug_http(self, self.path.partition("?")[0]):
            return
        self._reply(200, b"path=%s" % self.path.encode())


@pytest.fixture
def debug_loop_server():
    srv = EventLoopHTTPServer(("127.0.0.1", 0), DebugSurfaceHandler)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    yield srv
    srv.shutdown()
    srv.server_close()


def _get(srv, path: str) -> tuple[int, bytes]:
    s = _connect(srv)
    try:
        s.sendall(b"GET %s HTTP/1.1\r\nHost: x\r\n\r\n" % path.encode())
        return _read_response(s)
    finally:
        s.close()


def test_debug_profile_single_flight_409_on_event_loop(debug_loop_server):
    results = {}

    def long_run():
        results["first"] = _get(
            debug_loop_server, "/debug/profile?seconds=1.5&hz=20")

    t = threading.Thread(target=long_run)
    t.start()
    # wait until the run actually holds the single-flight lock
    from seaweedfs_tpu.util import profiler

    deadline = time.time() + 5
    while not profiler._RUN_LOCK.locked():
        assert time.time() < deadline, "profile run never started"
        time.sleep(0.01)
    code, body = _get(debug_loop_server, "/debug/profile?seconds=1&hz=20")
    assert code == 409 and b"already in progress" in body
    t.join(timeout=10)
    code, body = results["first"]
    assert code == 200  # the in-flight run is unharmed by the rejection


def test_debug_profile_bad_params_400_on_event_loop(debug_loop_server):
    for q in ("seconds=0", "seconds=999", "hz=0", "hz=100000",
              "seconds=nan&hz=banana"):
        code, _ = _get(debug_loop_server, "/debug/profile?" + q)
        assert code == 400, q


def test_debug_profile_kill_switch_403_on_event_loop(
        debug_loop_server, monkeypatch):
    monkeypatch.setenv("SEAWEEDFS_TPU_PROFILER_DISABLED", "1")
    code, body = _get(debug_loop_server, "/debug/profile?seconds=1")
    assert code == 403 and b"disabled" in body
    code, _ = _get(debug_loop_server, "/debug/profile/history")
    assert code == 403
    # the cheap status stub stays open even with the sampler closed
    code, body = _get(debug_loop_server, "/debug/profile?status=1")
    assert code == 200 and "max_rss_kb" in json.loads(body)


def test_debug_profile_history_ring_rotation(debug_loop_server, monkeypatch):
    """The continuous sampler's ring rotates windows and the history
    endpoint serves them — oldest evicted once `retain` is exceeded."""
    from seaweedfs_tpu.util import profiler

    monkeypatch.setenv(profiler.CONTINUOUS_HZ_VAR, "40")
    monkeypatch.setenv(profiler.CONTINUOUS_WINDOW_VAR, "0.1")
    monkeypatch.setenv(profiler.CONTINUOUS_RETAIN_VAR, "3")
    cp = profiler.ContinuousProfiler()
    cp.start()
    try:
        deadline = time.time() + 10
        while len(cp.history()["windows"]) < 3:
            assert time.time() < deadline, "ring never filled"
            time.sleep(0.05)
        first_seen = cp.history()["windows"][0]["start"]
        while cp.history()["windows"][0]["start"] == first_seen:
            assert time.time() < deadline, "ring never rotated"
            time.sleep(0.05)
        doc = cp.history()
        complete = [w for w in doc["windows"] if not w.get("partial")]
        assert len(complete) <= 3  # bounded by retain
        assert doc["running"] is True
        # windows carry collapsed-stack text with sample counts
        sampled = [w for w in complete if w["samples"]]
        assert sampled and "collapsed" in sampled[0]
    finally:
        cp.stop()
    assert cp.history()["running"] is False
