"""FTP gateway: stdlib ftplib client against a live filer-backed server.

The reference's weed/ftpd/ is an 81-LoC stub that serves nothing; this
gateway actually speaks RFC 959, so the test drives the full verb set a
real client uses: login, mkdir, cwd, store, list, size, retrieve,
append, rename, delete, rmdir.
"""

import ftplib
import io
import socket
import time

import pytest


@pytest.fixture(scope="module")
def ftp_cluster(tmp_path_factory):
    from seaweedfs_tpu.filer.server import FilerServer
    from seaweedfs_tpu.ftpd.server import FtpServer
    from seaweedfs_tpu.master.server import MasterServer
    from seaweedfs_tpu.volume.server import VolumeServer

    from helpers import free_port

    master = MasterServer(ip="127.0.0.1", port=free_port(),
                          volume_size_limit_mb=64)
    master.start()
    vs = VolumeServer(
        directories=[str(tmp_path_factory.mktemp("fvol"))],
        master_addresses=[f"127.0.0.1:{master.grpc_port}"],
        ip="127.0.0.1", port=free_port(), pulse_seconds=0.5,
        max_volume_count=50,
    )
    vs.start()
    deadline = time.time() + 15
    while time.time() < deadline and len(master.topo.nodes) < 1:
        time.sleep(0.1)
    filer = FilerServer(
        masters=[f"127.0.0.1:{master.grpc_port}"],
        ip="127.0.0.1", port=free_port(), store="memory",
    )
    filer.start()
    ftp = FtpServer(filer=f"127.0.0.1:{filer.port}", ip="127.0.0.1",
                    port=0, users={"weed": "secret"})
    ftp.start()
    yield ftp
    ftp.stop()
    filer.stop()
    vs.stop()
    master.stop()


def _client(ftp) -> ftplib.FTP:
    c = ftplib.FTP()
    c.connect("127.0.0.1", ftp.port, timeout=15)
    c.login("weed", "secret")
    return c


def test_ftp_full_session(ftp_cluster):
    c = _client(ftp_cluster)
    assert c.pwd() == "/"
    c.mkd("/ftp-test")
    c.cwd("/ftp-test")
    assert c.pwd() == "/ftp-test"

    payload = b"ftp gateway payload " * 100
    c.storbinary("STOR hello.bin", io.BytesIO(payload))
    assert c.size("hello.bin") == len(payload)
    assert "hello.bin" in c.nlst()

    got = bytearray()
    c.retrbinary("RETR hello.bin", got.extend)
    assert bytes(got) == payload

    # append doubles the content
    c.storbinary("APPE hello.bin", io.BytesIO(payload))
    got = bytearray()
    c.retrbinary("RETR hello.bin", got.extend)
    assert bytes(got) == payload * 2

    # LIST format parses as a unix-ish listing
    lines = []
    c.retrlines("LIST", lines.append)
    assert any("hello.bin" in ln for ln in lines)

    c.rename("hello.bin", "renamed.bin")
    assert "renamed.bin" in c.nlst() and "hello.bin" not in c.nlst()

    c.delete("renamed.bin")
    assert "renamed.bin" not in c.nlst()
    c.cwd("/")
    c.rmd("/ftp-test")
    c.quit()


def test_ftp_auth_required(ftp_cluster):
    c = ftplib.FTP()
    c.connect("127.0.0.1", ftp_cluster.port, timeout=15)
    with pytest.raises(ftplib.error_perm):
        c.login("weed", "wrong-password")
    # unauthenticated commands are refused
    with pytest.raises(ftplib.error_perm):
        c.mkd("/nope")
    c.close()


def test_ftp_missing_file_and_cwd_errors(ftp_cluster):
    c = _client(ftp_cluster)
    with pytest.raises(ftplib.error_perm):
        c.size("/does-not-exist.bin")
    with pytest.raises(ftplib.error_perm):
        c.cwd("/does-not-exist-dir")
    got = bytearray()
    with pytest.raises(ftplib.error_perm):
        c.retrbinary("RETR /does-not-exist.bin", got.extend)
    c.quit()


def test_ftp_large_transfer_spools(ftp_cluster):
    """STOR/RETR stream through a spooled temp file (>8MB spills to disk)
    rather than buffering whole objects in gateway memory."""
    c = _client(ftp_cluster)
    c.mkd("/ftp-big")
    c.cwd("/ftp-big")
    blob = bytes(range(256)) * (48 * 1024)  # 12MB, over the spool limit
    c.storbinary("STOR big.bin", io.BytesIO(blob), blocksize=1 << 16)
    assert c.size("big.bin") == len(blob)
    got = bytearray()
    c.retrbinary("RETR big.bin", got.extend, blocksize=1 << 16)
    assert bytes(got) == blob
    c.delete("big.bin")
    c.cwd("/")
    c.rmd("/ftp-big")
    c.quit()


def test_ftp_pasv_hijack_rejected(ftp_cluster):
    """A stranger racing to the advertised PASV port must not receive the
    data (classic PASV hijack): only the control-connection peer's IP may
    claim the data socket.  The hijacker connects from 127.0.0.2 while
    the control session runs on 127.0.0.1."""
    import socket

    c = _client(ftp_cluster)
    c.storbinary("STOR hijack.bin", io.BytesIO(b"secret-payload"))

    # open a passive data port, then race a foreign-IP connection to it
    c.putcmd("PASV")
    resp = c.getresp()
    assert resp.startswith("227")
    nums = resp[resp.index("(") + 1:resp.index(")")].split(",")
    data_port = int(nums[4]) * 256 + int(nums[5])

    hijacker = socket.socket()
    try:
        hijacker.bind(("127.0.0.2", 0))  # different loopback source IP
        hijacker.connect(("127.0.0.1", data_port))
    except OSError:
        hijacker = None  # host without 127/8 loopback range: skip race
    c.putcmd("RETR hijack.bin")

    if hijacker is not None:
        # the server must close the foreign connection without payload
        hijacker.settimeout(10)
        leaked = b""
        try:
            while True:
                chunk = hijacker.recv(4096)
                if not chunk:
                    break
                leaked += chunk
        except OSError:
            pass
        assert leaked == b"", "PASV hijacker received data"
        hijacker.close()

    # the legitimate client still completes the transfer on its own
    # connection from 127.0.0.1
    legit = socket.create_connection(("127.0.0.1", data_port), timeout=10)
    resp = c.getresp()
    assert resp.startswith("150")
    got = b""
    while True:
        chunk = legit.recv(4096)
        if not chunk:
            break
        got += chunk
    legit.close()
    assert got == b"secret-payload"
    assert c.getresp().startswith("226")
    c.delete("hijack.bin")
    c.quit()
