"""Acceptance test for the cluster observability plane (ISSUE 5).

Real subprocesses through the CLI (master + TWO volume servers + filer):

* one client PUT appears as a single stitched trace from the master's
  /cluster/traces?trace=<id> — spans from the filer AND a volume server,
  parent-linked across processes, with per-node skew annotation;
* /cluster/metrics federates both volume servers' gauges under distinct
  `instance` labels, and keeps serving — with the dead node marked
  stale and its last heartbeat snapshot re-served — after one volume
  process is SIGKILLed;
* /debug/profile returns non-empty collapsed stacks from a live server
  while requests keep flowing.
"""

import json
import os
import signal
import subprocess
import sys
import time
import urllib.error
import urllib.request

from helpers import free_port

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

CLIENT_TRACE_ID = "0b5e" + "ab" * 14  # 32 hex chars
TRACEPARENT = f"00-{CLIENT_TRACE_ID}-{'22' * 8}-01"


def _env():
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    return env


def _spawn(args, cwd):
    return subprocess.Popen(
        [sys.executable, "-m", "seaweedfs_tpu", *args],
        cwd=cwd, env=_env(),
        stdout=subprocess.DEVNULL, stderr=subprocess.STDOUT,
    )


def _wait_http(url, deadline_s=25):
    deadline = time.time() + deadline_s
    while time.time() < deadline:
        try:
            with urllib.request.urlopen(url, timeout=2) as r:
                return r.status
        except (urllib.error.URLError, ConnectionError, OSError):
            time.sleep(0.3)
    raise TimeoutError(url)


def _get_bytes(url, timeout=10):
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return r.read()


def _get(url, timeout=10):
    return _get_bytes(url, timeout).decode()


def test_cluster_observability_plane(tmp_path):
    mport, v1port, v2port, fport = (free_port(), free_port(), free_port(),
                                    free_port())
    for d in ("v1", "v2"):
        (tmp_path / d).mkdir()
    procs = {}
    try:
        procs["master"] = _spawn(["master", "-port", str(mport)],
                                 str(tmp_path))
        _wait_http(f"http://127.0.0.1:{mport}/cluster/healthz")
        for name, port in (("v1", v1port), ("v2", v2port)):
            procs[name] = _spawn(
                ["volume", "-dir", str(tmp_path / name), "-port", str(port),
                 "-mserver", f"127.0.0.1:{mport}", "-ec.codec", "cpu"],
                str(tmp_path))
        procs["filer"] = _spawn(
            ["filer", "-master", f"127.0.0.1:{mport}", "-port", str(fport),
             "-store", str(tmp_path / "filer.db")], str(tmp_path))
        _wait_http(f"http://127.0.0.1:{fport}/")

        # both volume servers registered and assignable
        deadline = time.time() + 25
        while time.time() < deadline:
            try:
                status = json.loads(
                    _get(f"http://127.0.0.1:{mport}/cluster/status"))
                if len(status.get("DataNodes", {})) >= 2 and status.get(
                        "Filers"):
                    break
            except (urllib.error.URLError, OSError):
                pass
            time.sleep(0.3)
        else:
            raise AssertionError("cluster never fully registered")
        assert all("secondsSinceLastBeat" in n
                   for n in status["DataNodes"].values())

        # -- one PUT -> one stitched trace --------------------------------
        req = urllib.request.Request(
            f"http://127.0.0.1:{fport}/obs/file.bin",
            data=os.urandom(4096), method="PUT",
            headers={"traceparent": TRACEPARENT},
        )
        with urllib.request.urlopen(req, timeout=15) as r:
            assert r.status == 201

        # the edge span records just AFTER the 201 is written — poll
        # briefly so a fast client can't outrun the ring append
        deadline = time.time() + 5
        while time.time() < deadline:
            doc = json.loads(_get(
                f"http://127.0.0.1:{mport}/cluster/traces"
                f"?trace={CLIENT_TRACE_ID}"))
            if {"filer.post", "volumeServer.post"} <= {
                    s["name"] for s in doc["spans"]}:
                break
            time.sleep(0.2)
        assert doc["traceId"] == CLIENT_TRACE_ID
        spans = doc["spans"]
        instances = {s["instance"] for s in spans}
        names = {s["name"] for s in spans}
        assert f"127.0.0.1:{fport}" in instances, instances
        assert instances & {f"127.0.0.1:{v1port}", f"127.0.0.1:{v2port}"}, (
            instances)
        assert "filer.post" in names and "volumeServer.post" in names
        # cross-process parent link survives the stitch: the volume POST
        # span's parent lives in the filer process's span set
        filer_ids = {s["spanId"] for s in spans
                     if s["instance"] == f"127.0.0.1:{fport}"}
        vol_posts = [s for s in spans if s["name"] == "volumeServer.post"]
        assert vol_posts and any(s["parentId"] in filer_ids
                                 for s in vol_posts)
        assert not vol_posts[0]["orphan"]
        for node in doc["nodes"].values():
            assert "clockSkewMs" in node
        # bad input validation mirrors /debug/traces
        try:
            _get(f"http://127.0.0.1:{mport}/cluster/traces?trace=nope")
            raise AssertionError("invalid trace id accepted")
        except urllib.error.HTTPError as e:
            assert e.code == 400

        # -- sampling profiler under load (before any node dies, so the
        # load path has all its chunks) ------------------------------------
        stop = time.time() + 2.0

        def load():
            while time.time() < stop:
                try:
                    _get_bytes(f"http://127.0.0.1:{fport}/obs/file.bin",
                               timeout=5)
                except Exception:
                    pass

        import threading

        lt = threading.Thread(target=load, daemon=True)
        lt.start()
        prof = _get(
            f"http://127.0.0.1:{fport}/debug/profile?seconds=1&hz=97",
            timeout=15)
        lt.join()
        assert prof.strip(), "profiler returned no stacks"
        line = prof.splitlines()[0]
        stack, _, count = line.rpartition(" ")
        assert int(count) >= 1 and stack
        # requests kept flowing during and after the profile
        assert len(_get_bytes(
            f"http://127.0.0.1:{fport}/obs/file.bin")) == 4096
        # parameter validation
        try:
            _get(f"http://127.0.0.1:{fport}/debug/profile?seconds=999")
            raise AssertionError("overlong profile accepted")
        except urllib.error.HTTPError as e:
            assert e.code == 400

        # -- federation: both instances, then stale fallback --------------
        text = _get(f"http://127.0.0.1:{mport}/cluster/metrics")
        for port in (v1port, v2port):
            assert f'instance="127.0.0.1:{port}"' in text, port
        assert f'instance="127.0.0.1:{fport}"' in text
        assert (f'seaweedfs_federation_up{{instance="127.0.0.1:{v2port}"'
                f',type="volume"}} 1') in text

        procs.pop("v2").kill()  # SIGKILL: sockets die, no clean leave
        deadline = time.time() + 20
        while time.time() < deadline:
            text = _get(f"http://127.0.0.1:{mport}/cluster/metrics")
            if (f'seaweedfs_federation_stale{{instance='
                    f'"127.0.0.1:{v2port}",type="volume"}} 1') in text:
                break
            time.sleep(0.5)
        else:
            raise AssertionError("dead node never marked stale")
        # the dead node's last-heartbeat snapshot is still served, with
        # its age, under its instance label; the live node stays live
        assert (f'seaweedfs_federation_snapshot_age_seconds'
                f'{{instance="127.0.0.1:{v2port}"') in text
        assert f'instance="127.0.0.1:{v2port}"' in text
        assert (f'seaweedfs_federation_up{{instance="127.0.0.1:{v1port}"'
                f',type="volume"}} 1') in text
    finally:
        for p in procs.values():
            p.send_signal(signal.SIGTERM)
        for p in procs.values():
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()
