"""Filer fleet units: consistent-hash ring properties, tenant quotas,
WFQ admission isolation, ring-routed client failover, and the SQLite
read/write lock split (ISSUE 7).

The ring property tests pin the contracts the sharded metadata plane
stands on: determinism across processes (two gateways must agree on
every key's owner), bounded remap under membership churn (~K/N keys
move when one of N nodes joins/leaves), and logarithmic lookup.
"""

from __future__ import annotations

import json
import subprocess
import sys
import threading
import time

import pytest

from seaweedfs_tpu.filer.fleet.ring import HashRing, shard_key
from seaweedfs_tpu.filer.fleet.tenant import (
    AdmissionController,
    QuotaExceededError,
    SlowDownError,
    TenantManager,
)

KEYS = [f"b/bucket-{i}" for i in range(4000)]
NODES = [f"10.0.0.{i}:8888" for i in range(1, 6)]  # N=5


# -- ring properties ---------------------------------------------------------


def test_ring_lookup_matches_linear_reference():
    """bisect lookup == the brute-force 'first vnode clockwise' scan."""
    from seaweedfs_tpu.filer.fleet.ring import _hash64

    ring = HashRing(NODES, vnodes=16)
    points = sorted(
        (_hash64(f"{n}#{i}"), n) for n in NODES for i in range(16))
    for key in KEYS[:500]:
        h = _hash64(key)
        expect = next((n for ph, n in points if ph > h), points[0][1])
        assert ring.lookup(key) == expect


def test_ring_deterministic_across_processes():
    """A gateway restarted (or a second gateway) derives the identical
    mapping from the same membership — no process-seeded hashing."""
    script = (
        "from seaweedfs_tpu.filer.fleet.ring import HashRing\n"
        f"ring = HashRing({NODES!r})\n"
        f"print('|'.join(ring.lookup(f'b/bucket-{{i}}') "
        "for i in range(200)))\n"
    )
    out = subprocess.run(
        [sys.executable, "-c", script], capture_output=True, text=True,
        check=True).stdout.strip()
    ring = HashRing(NODES)
    assert out == "|".join(ring.lookup(f"b/bucket-{i}")
                           for i in range(200))


def test_ring_add_node_remaps_bounded_fraction():
    before = HashRing(NODES)
    after = HashRing(NODES + ["10.0.0.6:8888"])
    moved = sum(1 for k in KEYS if before.lookup(k) != after.lookup(k))
    expected = len(KEYS) / (len(NODES) + 1)
    # every moved key must move TO the new node, and the count sits near
    # K/(N+1) (generous 2x bound for vnode variance)
    assert moved <= 2.0 * expected, (moved, expected)
    for k in KEYS:
        if before.lookup(k) != after.lookup(k):
            assert after.lookup(k) == "10.0.0.6:8888"


def test_ring_remove_node_remaps_only_its_keys():
    before = HashRing(NODES)
    dead = NODES[2]
    after = HashRing([n for n in NODES if n != dead])
    for k in KEYS:
        owner = before.lookup(k)
        if owner != dead:
            # survivors keep every key they already owned
            assert after.lookup(k) == owner, k
        else:
            assert after.lookup(k) != dead


def test_ring_lookup_is_logarithmic():
    """Doubling node count from 64 to 4096 vnodes total must not scale
    lookup cost linearly.  Measured generously: 64x the ring points may
    cost at most ~8x the time (true O(log) costs ~2x; a linear scan
    would cost ~64x even on a noisy host)."""
    small = HashRing([f"n{i}" for i in range(4)], vnodes=16)     # 64 pts
    big = HashRing([f"n{i}" for i in range(64)], vnodes=64)      # 4096 pts
    keys = [f"b/k{i}" for i in range(3000)]

    def measure(ring):
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            for k in keys:
                ring.lookup(k)
            best = min(best, time.perf_counter() - t0)
        return best

    t_small, t_big = measure(small), measure(big)
    assert t_big < 8 * t_small + 0.02, (t_small, t_big)


def test_ring_lookup_order_covers_all_nodes():
    ring = HashRing(NODES)
    for k in KEYS[:50]:
        order = ring.lookup_order(k)
        assert order[0] == ring.lookup(k)
        assert sorted(order) == sorted(NODES)  # distinct, complete


def test_shard_key_mapping():
    assert shard_key("/buckets/photos/a/b.jpg") == "b/photos"
    assert shard_key("/buckets/photos") == "b/photos"
    assert shard_key("buckets/photos/") == "b/photos"
    assert shard_key("/etc/iam/identity.json") == "t/etc"
    assert shard_key("/topics/ns/t/messages.log") == "t/topics"
    assert shard_key("/buckets") == "/"
    assert shard_key("/") == "/"


def test_empty_ring_raises():
    with pytest.raises(LookupError):
        HashRing([]).lookup("b/x")


# -- tenant quotas -----------------------------------------------------------


def test_tenant_quota_objects_and_bytes():
    tm = TenantManager()
    tm.set_config("t1", quota_objects=2, quota_bytes=100)
    tm.check_quota("t1", 1, 40)
    tm.record("t1", 1, 40)
    tm.check_quota("t1", 1, 40)
    tm.record("t1", 1, 40)
    with pytest.raises(QuotaExceededError):
        tm.check_quota("t1", 1, 10)  # third object
    with pytest.raises(QuotaExceededError):
        tm.check_quota("t1", 0, 30)  # 80 + 30 > 100
    # deletes always pass and free space
    tm.record("t1", -1, -40)
    tm.check_quota("t1", 1, 40)
    # an unconfigured tenant is unlimited
    tm.check_quota("t2", 1000, 1 << 40)


def test_tenant_usage_persists_in_store_kv():
    from seaweedfs_tpu.filer.filerstore import make_store

    store = make_store("memory")
    tm = TenantManager(store)
    tm.set_config("t1", quota_bytes=1000)
    tm.record("t1", 3, 300)
    tm.close()
    tm2 = TenantManager(store)
    assert tm2.usage("t1") == {"objects": 3, "bytes": 300}
    assert tm2.config("t1")["quota_bytes"] == 1000


def test_filer_mutations_enforce_quota():
    """End-to-end through Filer.create/update/delete: accounting follows
    the entry lifecycle and over-quota writes raise before the store."""
    from seaweedfs_tpu.filer.filer import Filer
    from seaweedfs_tpu.filer.filerstore import make_store
    from seaweedfs_tpu.pb import filer_pb2

    filer = Filer(make_store("memory"))
    tm = TenantManager(filer.store)
    filer.tenants = tm
    tm.set_config("b1", quota_objects=2)

    def entry(name, size=10):
        e = filer_pb2.Entry(name=name)
        e.attributes.file_size = size
        e.content = b"x" * size
        return e

    filer.create_entry("/buckets/b1", entry("a"))
    filer.create_entry("/buckets/b1", entry("b"))
    assert tm.usage("b1") == {"objects": 2, "bytes": 20}
    with pytest.raises(QuotaExceededError):
        filer.create_entry("/buckets/b1", entry("c"))
    # overwrite is not a new object
    filer.create_entry("/buckets/b1", entry("a", size=30))
    assert tm.usage("b1") == {"objects": 2, "bytes": 40}
    # a second tenant proceeds untouched
    filer.create_entry("/buckets/b2", entry("x"))
    assert tm.usage("b2") == {"objects": 1, "bytes": 10}
    # delete frees the slot
    filer.delete_entry("/buckets/b1", "b")
    assert tm.usage("b1") == {"objects": 1, "bytes": 30}
    filer.create_entry("/buckets/b1", entry("c"))
    # recursive dir delete releases the whole subtree
    filer.delete_entry("/buckets", "b1", is_recursive=True)
    assert tm.usage("b1") == {"objects": 0, "bytes": 0}
    filer.close()


def test_untenanted_paths_skip_accounting():
    from seaweedfs_tpu.filer.filer import Filer
    from seaweedfs_tpu.filer.filerstore import make_store
    from seaweedfs_tpu.pb import filer_pb2

    filer = Filer(make_store("memory"))
    tm = TenantManager(filer.store)
    filer.tenants = tm
    e = filer_pb2.Entry(name="identity.json")
    e.content = b"{}"
    filer.create_entry("/etc/iam", e)
    assert tm.snapshot() == {}
    filer.close()


# -- WFQ admission -----------------------------------------------------------


def _controller(capacity=4, queue_depth=0):
    tm = TenantManager()
    return tm, AdmissionController(
        tm, capacity=capacity, queue_threshold=64,
        queue_depth_fn=lambda: queue_depth)


def test_admission_below_capacity_admits_everyone():
    _, ac = _controller(capacity=4)
    slots = [ac.admit("a"), ac.admit("a"), ac.admit("b")]
    for s in slots:
        s.__enter__()
    for s in slots:
        s.__exit__(None, None, None)
    assert ac.snapshot()["total"] == 0


def test_admission_saturated_clamps_heavy_tenant_not_light():
    _, ac = _controller(capacity=4)
    held = [ac.admit("hog") for _ in range(4)]
    for s in held:
        s.__enter__()
    # saturated: the hog is far past its share -> SlowDown
    with pytest.raises(SlowDownError):
        ac.try_enter("hog")
    # a light tenant still has a reserved share
    with ac.admit("light"):
        pass
    for s in held:
        s.__exit__(None, None, None)


def test_admission_weights_shift_fair_share():
    tm, ac = _controller(capacity=8)
    tm.set_config("gold", weight=3.0)
    tm.set_config("bronze", weight=1.0)
    held = [ac.admit("bronze") for _ in range(8)]
    for s in held:
        s.__enter__()
    # saturated; gold's share = 8 * 3/4 = 6 -> admit several
    admitted = []
    for _ in range(3):
        s = ac.admit("gold")
        s.__enter__()
        admitted.append(s)
    for s in admitted + held:
        s.__exit__(None, None, None)


def test_admission_queue_depth_gauge_triggers_saturation():
    depth = [0]
    tm = TenantManager()
    ac = AdmissionController(tm, capacity=100, queue_threshold=5,
                             queue_depth_fn=lambda: depth[0])
    held = [ac.admit("a") for _ in range(50)]
    for s in held:
        s.__enter__()  # far below capacity: all admitted
    depth[0] = 10  # the PR 5 saturation signal fires
    with pytest.raises(SlowDownError):
        ac.try_enter("a")  # growth frozen at current inflight
    # a light tenant still gets its share of what's in flight
    with ac.admit("b"):
        pass
    for s in held:
        s.__exit__(None, None, None)
    depth[0] = 0
    with ac.admit("a"):  # saturation cleared -> admitted again
        pass


def test_admission_untenanted_exempt():
    _, ac = _controller(capacity=1)
    with ac.admit("t"):
        # capacity gone; untenanted config reads still pass
        with ac.admit(""):
            pass


def test_wfq_saturating_tenant_cannot_move_victim_p99():
    """The SLO-isolation property: tenant A floods a capacity-8 filer
    from 16 threads; tenant B sends sequential requests.  B must see
    ZERO rejections and a p99 admission latency within the SLO bound
    (admission is rejection-based — nothing queues, so B pays lock +
    GIL scheduling cost only), while A is actively being rejected."""
    tm, ac = _controller(capacity=8)
    stop = threading.Event()
    a_rejects = [0]

    def flood():
        while not stop.is_set():
            try:
                with ac.admit("A"):
                    time.sleep(0.002)
            except SlowDownError:
                a_rejects[0] += 1

    threads = [threading.Thread(target=flood, daemon=True)
               for _ in range(16)]
    for t in threads:
        t.start()
    time.sleep(0.05)  # let A saturate
    latencies = []
    b_rejects = 0
    for _ in range(200):
        t0 = time.perf_counter()
        try:
            with ac.admit("B"):
                pass
        except SlowDownError:
            b_rejects += 1
        latencies.append(time.perf_counter() - t0)
    stop.set()
    for t in threads:
        t.join(timeout=5)
    latencies.sort()
    p99 = latencies[int(len(latencies) * 0.99)]
    assert b_rejects == 0, f"victim tenant rejected {b_rejects}x"
    # generous for a noisy shared host: the property is "bounded by
    # scheduling noise, not by the flood" — an unfair controller would
    # reject B outright or queue it behind A's 2ms holds
    assert p99 < 0.050, f"victim p99 {p99 * 1e3:.2f}ms past the SLO bound"
    assert a_rejects[0] > 0, "the flood was never actually clamped"


# -- fleet client routing ----------------------------------------------------


class _FakeFilerClient:
    """Stands in for s3api FilerClient: records calls, optionally dead."""

    def __init__(self, addr, registry, dead=False):
        self.addr = addr
        self.registry = registry
        self.dead = dead
        self.entries: dict[str, list] = {}

    def _touch(self, op):
        self.registry.append((self.addr, op))
        if self.dead:
            from seaweedfs_tpu.s3api.filer_client import FilerUnavailable

            raise FilerUnavailable(f"{self.addr} is down")

    def find_entry(self, directory, name):
        self._touch("find")
        return None

    def list_entries(self, directory, prefix="", start_from="",
                     inclusive=False, limit=1024):
        self._touch("list")
        return list(self.entries.get(directory, []))

    def create_entry(self, directory, entry):
        self._touch("create")


def _fleet(nodes, dead=()):
    from seaweedfs_tpu.filer.fleet import FleetFilerClient, FleetRouter

    router = FleetRouter(filers=nodes)
    client = FleetFilerClient(router)
    registry: list = []
    for n in nodes:
        client._clients[n] = _FakeFilerClient(n, registry, dead=n in dead)
    return client, registry


def test_fleet_client_routes_to_ring_owner():
    nodes = [f"127.0.0.1:{p}" for p in (7001, 7002, 7003)]
    client, registry = _fleet(nodes)
    ring = client.router.ring()
    client.find_entry("/buckets/photos", "x.jpg")
    assert registry == [(ring.lookup("b/photos"), "find")]


def test_fleet_client_fails_over_in_ring_order():
    nodes = [f"127.0.0.1:{p}" for p in (7001, 7002, 7003)]
    ring_owner = None
    from seaweedfs_tpu.filer.fleet.ring import HashRing

    ring_owner = HashRing(nodes).lookup("b/photos")
    client, registry = _fleet(nodes, dead={ring_owner})
    client.find_entry("/buckets/photos", "x.jpg")
    order = HashRing(nodes).lookup_order("b/photos")
    assert [a for a, _ in registry] == order[:2]  # owner tried, then next


def test_fleet_client_all_dead_raises_unavailable():
    from seaweedfs_tpu.s3api.filer_client import FilerUnavailable

    nodes = [f"127.0.0.1:{p}" for p in (7001, 7002, 7003)]
    client, _ = _fleet(nodes, dead=set(nodes))
    with pytest.raises(FilerUnavailable):
        client.find_entry("/buckets/photos", "x.jpg")


def test_fleet_client_bucket_listing_fans_out_and_merges():
    from seaweedfs_tpu.pb import filer_pb2

    nodes = [f"127.0.0.1:{p}" for p in (7001, 7002, 7003)]
    client, registry = _fleet(nodes)
    # one bucket visible on one shard only (replication lag), another on
    # all three (converged): the merged view holds both, deduped
    lagged = filer_pb2.Entry(name="fresh-bucket", is_directory=True)
    common = filer_pb2.Entry(name="old-bucket", is_directory=True)
    for n in nodes:
        client._clients[n].entries["/buckets"] = [common]
    client._clients[nodes[1]].entries["/buckets"].append(lagged)
    names = [e.name for e in client.list_entries("/buckets")]
    assert names == ["fresh-bucket", "old-bucket"]
    assert {a for a, _ in registry} == set(nodes)  # true fan-out


def test_fleet_client_non_transport_errors_do_not_fail_over():
    nodes = [f"127.0.0.1:{p}" for p in (7001, 7002)]
    client, registry = _fleet(nodes)

    class Boom(_FakeFilerClient):
        def find_entry(self, directory, name):
            self._touch("find")
            raise IOError("quota exceeded for tenant 'photos': full")

    owner = client.router.ring().lookup("b/photos")
    client._clients[owner] = Boom(owner, registry)
    with pytest.raises(IOError, match="quota exceeded"):
        client.find_entry("/buckets/photos", "x")
    assert len(registry) == 1  # no second shard saw the request


# -- sqlite store: reads do not stall behind the write lock ------------------


def test_sqlite_reads_bypass_write_lock(tmp_path):
    from seaweedfs_tpu.filer.filerstore import make_store
    from seaweedfs_tpu.pb import filer_pb2

    store = make_store("sqlite", path=str(tmp_path / "filer.db"))
    for i in range(20):
        e = filer_pb2.Entry(name=f"f{i}")
        store.insert_entry("/d", e)
    assert store.count_entries() == 20

    results: dict = {}
    with store._lock:  # a writer mid-commit holds this
        def read():
            results["find"] = store.find_entry("/d", "f3")
            results["list"] = [e.name for e in store.list_entries("/d")]
            results["kv"] = store.kv_get(b"nope")

        t = threading.Thread(target=read, daemon=True)
        t.start()
        t.join(timeout=5)
        assert not t.is_alive(), "read stalled behind the write lock"
    assert results["find"].name == "f3"
    assert len(results["list"]) == 20
    store.close()


def test_sqlite_read_conn_sees_committed_writes(tmp_path):
    from seaweedfs_tpu.filer.filerstore import make_store
    from seaweedfs_tpu.pb import filer_pb2

    store = make_store("sqlite", path=str(tmp_path / "filer.db"))
    store.insert_entry("/d", filer_pb2.Entry(name="a"))
    assert store.find_entry("/d", "a") is not None  # read conn, write conn
    store.kv_put(b"k", b"v")
    assert store.kv_get(b"k") == b"v"
    store.delete_entry("/d", "a")
    assert store.find_entry("/d", "a") is None
    store.close()


# -- router discovery parsing ------------------------------------------------


def test_router_static_mode_is_stable():
    from seaweedfs_tpu.filer.fleet import FleetRouter

    r = FleetRouter(filers=["b:2", "a:1"])
    assert r.ring().nodes == ["a:1", "b:2"]
    assert r.candidates("/buckets/x/k")[0] == r.owner("/buckets/x/k")


def test_router_discovery_parses_cluster_status(monkeypatch):
    from seaweedfs_tpu.filer.fleet import router as router_mod

    doc = {"Filers": {
        "filer@127.0.0.1:8881": {"httpAddress": "127.0.0.1:8881",
                                 "secondsSinceLastSeen": 1.0},
        "filer@127.0.0.1:8882": {"httpAddress": "127.0.0.1:8882",
                                 "secondsSinceLastSeen": 2.0},
        "filer@127.0.0.1:8883": {"httpAddress": "127.0.0.1:8883",
                                 "secondsSinceLastSeen": 999.0},  # stale
    }}

    class _Resp:
        status = 200

        def read(self):
            return json.dumps(doc).encode()

        def __enter__(self):
            return self

        def __exit__(self, *a):
            return False

    monkeypatch.setattr(router_mod.connpool, "request",
                        lambda *a, **k: _Resp())
    r = router_mod.FleetRouter(masters=["127.0.0.1:9333"])
    assert r.ring().nodes == ["127.0.0.1:8881", "127.0.0.1:8882"]


# -- fault points ------------------------------------------------------------


def test_filer_store_insert_faultpoint_fires():
    """Arming `filer.store.insert` models a shard store dying mid-write:
    the mutation fails BEFORE the store insert, nothing is recorded, and
    tenant usage stays untouched (FAULTS.md shard-death fault point)."""
    from seaweedfs_tpu.filer.filer import Filer
    from seaweedfs_tpu.filer.filerstore import make_store
    from seaweedfs_tpu.pb import filer_pb2
    from seaweedfs_tpu.util import faultpoint

    filer = Filer(make_store("memory"))
    tm = TenantManager(filer.store)
    filer.tenants = tm
    faultpoint.set_fault("filer.store.insert", "error", count=1,
                         match="/buckets/fp-b/")
    try:
        e = filer_pb2.Entry(name="x")
        e.content = b"data"
        with pytest.raises(faultpoint.FaultInjected):
            filer.create_entry("/buckets/fp-b", e)
        assert filer.store.find_entry("/buckets/fp-b", "x") is None
        assert tm.usage("fp-b") == {"objects": 0, "bytes": 0}
        # the armed count is spent: the retry lands
        filer.create_entry("/buckets/fp-b", e)
        assert tm.usage("fp-b") == {"objects": 1, "bytes": 4}
    finally:
        faultpoint.clear_fault("all")
        filer.close()
