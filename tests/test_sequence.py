"""Sequencer semantics (reference: weed/sequence/memory_sequencer.go,
snowflake via weed/sequence/).

Pins the advisor-flagged edge cases: set_max must advance past an *equal*
seen value, and the snowflake generator must stay monotonic and return the
first id of a reserved range.
"""

from seaweedfs_tpu.master.sequence import MemorySequencer, SnowflakeSequencer


def test_memory_sequencer_basic():
    s = MemorySequencer()
    a = s.next_file_id()
    b = s.next_file_id(5)
    c = s.next_file_id()
    assert b == a + 1
    assert c == b + 5


def test_memory_set_max_equal_value_advances():
    # a heartbeat reporting max_file_key == counter must still bump, or the
    # next assign reuses a live needle id (reference: counter <= seenValue)
    s = MemorySequencer(start=5)
    assert s.peek() == 5
    s.set_max(5)
    assert s.next_file_id() == 6
    s.set_max(3)  # lower values never move the counter back
    assert s.next_file_id() == 7


def test_snowflake_monotonic_and_range_start():
    s = SnowflakeSequencer(node_id=7)
    ids = [s.next_file_id() for _ in range(100)]
    assert ids == sorted(ids)
    assert len(set(ids)) == 100
    # count>1 reserves [first, first+count) and returns the first id
    first = s.next_file_id(10)
    nxt = s.next_file_id()
    assert nxt > first
    # node id occupies bits 12..21
    assert (first >> 12) & 0x3FF == 7


def test_snowflake_overflow_advances_monotonically():
    s = SnowflakeSequencer(node_id=1)
    # exhaust a millisecond's 4096-id space; the generator advances to the
    # next logical millisecond and clamps _last_ms monotonically, so the
    # bumped millisecond can never be re-issued even if the wall clock lags
    ids = [s.next_file_id(512) for _ in range(20)]
    assert ids == sorted(ids)
    assert len(set(ids)) == 20
