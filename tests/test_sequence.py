"""Sequencer semantics (reference: weed/sequence/memory_sequencer.go,
snowflake via weed/sequence/).

Pins the advisor-flagged edge cases: set_max must advance past an *equal*
seen value, and the snowflake generator must stay monotonic and return the
first id of a reserved range.
"""

from seaweedfs_tpu.master.sequence import MemorySequencer, SnowflakeSequencer


def test_memory_sequencer_basic():
    s = MemorySequencer()
    a = s.next_file_id()
    b = s.next_file_id(5)
    c = s.next_file_id()
    assert b == a + 1
    assert c == b + 5


def test_memory_set_max_equal_value_advances():
    # a heartbeat reporting max_file_key == counter must still bump, or the
    # next assign reuses a live needle id (reference: counter <= seenValue)
    s = MemorySequencer(start=5)
    assert s.peek() == 5
    s.set_max(5)
    assert s.next_file_id() == 6
    s.set_max(3)  # lower values never move the counter back
    assert s.next_file_id() == 7


def test_snowflake_monotonic_and_range_start():
    s = SnowflakeSequencer(node_id=7)
    ids = [s.next_file_id() for _ in range(100)]
    assert ids == sorted(ids)
    assert len(set(ids)) == 100
    # count>1 reserves [first, first+count) and returns the first id
    first = s.next_file_id(10)
    nxt = s.next_file_id()
    assert nxt > first
    # node id occupies bits 12..21
    assert (first >> 12) & 0x3FF == 7


def test_snowflake_overflow_advances_monotonically():
    s = SnowflakeSequencer(node_id=1)
    # exhaust a millisecond's 4096-id space; the generator advances to the
    # next logical millisecond and clamps _last_ms monotonically, so the
    # bumped millisecond can never be re-issued even if the wall clock lags
    ids = [s.next_file_id(512) for _ in range(20)]
    assert ids == sorted(ids)
    assert len(set(ids)) == 20


def test_etcd_sequencer_leases_disjoint_ranges():
    """Two masters leasing from one etcd never hand out overlapping ids,
    and set_max pushes the shared counter past volume-reported keys
    (etcd_sequencer.go:26-110 semantics via the native v3 client)."""
    from seaweedfs_tpu.master.sequence import make_sequencer
    from seaweedfs_tpu.util.etcd import FakeEtcdServer

    fake = FakeEtcdServer()
    fake.start()
    try:
        ep = f"127.0.0.1:{fake.port}"
        a = make_sequencer("etcd", etcd_endpoint=ep)
        b = make_sequencer("etcd", etcd_endpoint=ep)
        seen = set()
        for _ in range(40):
            for s, count in ((a, 3), (b, 5)):
                start = s.next_file_id(count)
                ids = set(range(start, start + count))
                assert not (ids & seen), "overlapping id ranges"
                seen |= ids
        # a volume server reports a higher max key: EVERY id handed out
        # by the informed master afterwards must clear it (ids below are
        # live needles), and the other master clears it once its current
        # lease drains
        a.set_max(1_000_000)
        assert a.next_file_id(1) > 1_000_000
        for _ in range(600):  # drain b's already-leased range
            b.next_file_id(1)
        assert b.next_file_id(1) > 1_000_000
    finally:
        fake.stop()


def test_etcd_sequencer_cas_contention():
    """Concurrent leases under contention stay disjoint (the CAS loop)."""
    import threading

    from seaweedfs_tpu.master.sequence import make_sequencer
    from seaweedfs_tpu.util.etcd import FakeEtcdServer

    fake = FakeEtcdServer()
    fake.start()
    try:
        ep = f"127.0.0.1:{fake.port}"
        seqs = [make_sequencer("etcd", etcd_endpoint=ep) for _ in range(4)]
        out: list[int] = []
        lock = threading.Lock()

        def worker(s):
            got = [s.next_file_id(7) for _ in range(50)]
            with lock:
                out.extend(got)

        ts = [threading.Thread(target=worker, args=(s,)) for s in seqs]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        starts = sorted(out)
        for i in range(1, len(starts)):
            assert starts[i] - starts[i - 1] >= 7, "ranges overlap"
    finally:
        fake.stop()
