"""Disk-fault survival plane cluster acceptance (ISSUE 14): ENOSPC on
one volume server's disk under concurrent writers — zero acked-write
loss, typed-409 re-assign with zero steady-state client 5xx, master
stops assigning to the full disk within one heartbeat — plus the
failing-disk proactive-evacuation trigger and the low-space emergency
vacuum reaction."""

from __future__ import annotations

import errno
import threading
import time
import urllib.error
import urllib.request

import pytest

from helpers import free_port

from seaweedfs_tpu.master.server import MasterServer
from seaweedfs_tpu.operation.assign import assign_any
from seaweedfs_tpu.operation.upload import VolumeFullError, upload_data
from seaweedfs_tpu.util import faultpoint
from seaweedfs_tpu.volume.server import VolumeServer

GB = 1 << 30
MB = 1 << 20

pytestmark = pytest.mark.chaos


@pytest.fixture()
def cluster(tmp_path_factory):
    mport = free_port()
    master = MasterServer(ip="127.0.0.1", port=mport,
                          volume_size_limit_mb=64)
    master.start()
    servers, dirs = [], []
    for i in range(3):
        d = tmp_path_factory.mktemp(f"dfvol{i}")
        vs_ = VolumeServer(
            directories=[str(d)],
            master_addresses=[f"127.0.0.1:{master.grpc_port}"],
            ip="127.0.0.1",
            port=free_port(),
            pulse_seconds=0.5,
            max_volume_count=50,
        )
        vs_.start()
        servers.append(vs_)
        dirs.append(str(d))
    deadline = time.time() + 15
    while time.time() < deadline and len(master.topo.nodes) < 3:
        time.sleep(0.1)
    assert len(master.topo.nodes) == 3
    try:
        yield master, servers, dirs
    finally:
        faultpoint.clear_fault("all")
        for s in servers:
            s.stop()
        master.stop()


def _get(url: str) -> tuple[int, bytes]:
    try:
        with urllib.request.urlopen(f"http://{url}", timeout=10) as r:
            return r.status, r.read()
    except urllib.error.HTTPError as e:
        return e.code, e.read()


def _put_with_reassign(master_grpc: str, payload: bytes,
                       stats: dict, lock: threading.Lock):
    """The filer's discipline: assign -> upload -> on failure re-assign.
    -> the final fid/url, or raises after bounded rounds."""
    last = None
    for _ in range(6):
        res = assign_any([master_grpc], count=1)
        try:
            upload_data(res.fid_url(), payload, filename="t.bin",
                        retries=1)
            return res
        except VolumeFullError as e:
            last = e
            with lock:
                stats["full_409"] += 1
            continue  # typed 409: immediate re-assign, no backoff
        except Exception as e:  # noqa: BLE001
            last = e
            with lock:
                stats["other_fail"] += 1
            time.sleep(0.1)
            continue
    raise AssertionError(f"write failed after re-assigns: {last}")


def test_enospc_chaos_survival(cluster):
    master, servers, dirs = cluster
    master_grpc = f"127.0.0.1:{master.grpc_port}"
    victim, victim_dir = servers[0], dirs[0]

    # baseline traffic so every server grows volumes
    stats = {"full_409": 0, "other_fail": 0}
    lock = threading.Lock()
    baseline = {}
    for i in range(8):
        payload = b"base-%d" % i * 50
        res = _put_with_reassign(master_grpc, payload, stats, lock)
        baseline[res.fid] = (res.url, payload)

    # the victim's disk "fills": statvfs reports almost nothing free
    # AND the backend throws torn mid-blob ENOSPC writes (the
    # disk.write.enospc faultpoint, scoped to the victim's data dir)
    victim.store.locations[0].health._statvfs = (
        lambda _d: (100 * GB, 10 * MB))
    faultpoint.set_fault("disk.write.enospc", "error", count=-1,
                         match=victim_dir)

    # concurrent writers keep hammering through the fault window
    written: dict = {}
    wlock = threading.Lock()
    errors: list = []

    def writer(w: int) -> None:
        for i in range(12):
            payload = (b"w%d-%d-" % (w, i)) * 60
            try:
                res = _put_with_reassign(master_grpc, payload, stats,
                                         lock)
            except AssertionError as e:
                errors.append(str(e))
                return
            with wlock:
                written[res.fid] = (res.url, payload)

    threads = [threading.Thread(target=writer, args=(w,))
               for w in range(4)]
    for th in threads:
        th.start()
    for th in threads:
        th.join(timeout=60)
    # zero steady-state failures: every logical write eventually landed
    assert not errors, errors
    assert len(written) == 4 * 12

    # every acked write reads back byte-identical
    for fid, (url, payload) in {**baseline, **written}.items():
        code, body = _get(f"{url}/{fid}")
        assert code == 200, (fid, code, body[:200])
        assert body == payload, f"acked write {fid} not byte-identical"

    # the master stopped assigning to the full node (one heartbeat was
    # forced by the first classified write fault): a fresh burst of
    # assigns must all avoid it
    victim_url = f"{victim.ip}:{victim.port}"
    time.sleep(1.5)  # > one pulse: the full beat definitely landed
    for _ in range(20):
        res = assign_any([master_grpc], count=1)
        assert res.url != victim_url, (
            "master still assigns to the full disk")

    # the typed 409 actually drove re-assigns (the fault fired) and the
    # cluster sees the disk as full
    node = master.topo.nodes.get(victim_url)
    assert node is not None and node.worst_disk_state() == "full"

    # recovery: space returns -> volumes unfreeze -> assignment resumes
    faultpoint.clear_fault("disk.write.enospc")
    victim.store.locations[0].health._statvfs = (
        lambda _d: (100 * GB, 50 * GB))
    deadline = time.time() + 10
    recovered = False
    while time.time() < deadline and not recovered:
        recovered = node.worst_disk_state() == "healthy"
        time.sleep(0.2)
    assert recovered, "full state did not clear after space returned"
    res = _put_with_reassign(master_grpc, b"post-recovery" * 20, stats,
                             lock)
    code, body = _get(f"{res.url}/{res.fid}")
    assert code == 200 and body == b"post-recovery" * 20


def test_failing_disk_triggers_evacuation(cluster):
    """K EIOs flip the disk to `failing`; the heartbeat carries it and
    the master proactively copies the node's sole-copy volumes to a
    healthy peer BEFORE the disk dies."""
    master, servers, dirs = cluster
    master_grpc = f"127.0.0.1:{master.grpc_port}"
    stats = {"full_409": 0, "other_fail": 0}
    lock = threading.Lock()

    # land at least one volume on every node, then find a volume whose
    # ONLY copy lives on the victim
    fids = [
        _put_with_reassign(master_grpc, b"evac-%d" % i * 40, stats, lock)
        for i in range(10)
    ]
    # victim: any server that actually holds a volume (growth placement
    # is free to leave some node empty)
    victim = next(
        s for s in servers
        if any(loc.volumes for loc in s.store.locations))
    victim_url = f"{victim.ip}:{victim.port}"
    victim_vids = sorted(
        vid for loc in victim.store.locations for vid in loc.volumes)

    # the victim's disk starts throwing EIOs: cross the K threshold
    health = victim.store.locations[0].health
    for _ in range(3):
        health.record_write_error(OSError(errno.EIO, "injected"))
    assert health.state == "failing"

    # within a couple beats the master must have copied the victim's
    # sole-copy volumes to a healthy node (the victim keeps its copy)
    others = [s for s in servers if s is not victim]
    deadline = time.time() + 20
    evacuated = False
    while time.time() < deadline and not evacuated:
        for vid in victim_vids:
            holders = [
                s for s in others
                if any(vid in loc.volumes for loc in s.store.locations)
            ]
            if holders:
                evacuated = True
                break
        time.sleep(0.3)
    assert evacuated, "no volume was evacuated off the failing disk"
    assert master.mass_repair.status()["counts"]["evacuated"] >= 1

    # reads still served throughout (victim alive, data intact)
    for res in fids:
        code, body = _get(f"{res.url}/{res.fid}")
        assert code == 200

    # the failing node is not a growth target: every fresh assign
    # avoids it once the failing beat landed
    time.sleep(1.0)
    node = master.topo.nodes.get(victim_url)
    assert node is not None and node.worst_disk_state() == "failing"
    assert node.free_slots() == 0 and node.free_ec_slots() == 0


def test_low_space_triggers_emergency_vacuum(cluster):
    """A low_space heartbeat makes the lifecycle plane vacuum the
    node's garbage immediately (quiet windows and policy ratios
    bypassed)."""
    master, servers, dirs = cluster
    master_grpc = f"127.0.0.1:{master.grpc_port}"
    stats = {"full_409": 0, "other_fail": 0}
    lock = threading.Lock()

    # build garbage: write then delete most of a volume's needles
    results = [
        _put_with_reassign(master_grpc, b"g%03d" % i * 500, stats, lock)
        for i in range(12)
    ]
    victim = None
    for s in servers:
        if any(s.store.find_volume(int(r.fid.split(",")[0]))
               for r in results):
            victim = s
            break
    assert victim is not None
    for r in results[:10]:
        urllib.request.urlopen(urllib.request.Request(
            f"http://{r.url}/{r.fid}", method="DELETE"), timeout=10
        ).read()

    before = {
        vid: loc.volumes[vid].content_size
        for loc in victim.store.locations
        for vid in loc.volumes
    }
    # the victim reports low_space from now on
    victim.store.locations[0].health._statvfs = (
        lambda _d: (100 * GB, 2 * GB))

    deadline = time.time() + 20
    compacted = False
    while time.time() < deadline and not compacted:
        if master.lifecycle._counts.get("emergency", 0) > 0:
            for loc in victim.store.locations:
                for vid, v in loc.volumes.items():
                    if v.content_size < before.get(vid, 0):
                        compacted = True
        time.sleep(0.3)
    assert compacted, "emergency vacuum did not reclaim space"

    # surviving needles byte-identical after the compaction
    for i, r in enumerate(results[10:], start=10):
        code, body = _get(f"{r.url}/{r.fid}")
        assert code == 200 and body == b"g%03d" % i * 500
