"""Client utilities: backup / upload / download / filer.cat / filer.copy.

Reference: weed/command/backup.go, upload.go:51, download.go:32,
filer_cat.go:54, filer_copy.go:65.
"""

from __future__ import annotations

import os
import time

import pytest

from helpers import free_port
from seaweedfs_tpu.tools.backup import (
    backup_volume,
    download_files,
    filer_cat,
    filer_copy,
    upload_files,
)


@pytest.fixture(scope="module")
def stack(tmp_path_factory):
    from seaweedfs_tpu.filer.server import FilerServer
    from seaweedfs_tpu.master.server import MasterServer
    from seaweedfs_tpu.volume.server import VolumeServer

    master = MasterServer(ip="127.0.0.1", port=free_port(),
                          volume_size_limit_mb=64)
    master.start()
    vsrv = VolumeServer(
        directories=[str(tmp_path_factory.mktemp("ctvol"))],
        master_addresses=[f"127.0.0.1:{master.grpc_port}"],
        ip="127.0.0.1", port=free_port(), pulse_seconds=0.5,
        max_volume_count=100,
    )
    vsrv.start()
    deadline = time.time() + 15
    while time.time() < deadline and len(master.topo.nodes) < 1:
        time.sleep(0.1)
    filer = FilerServer(
        masters=[f"127.0.0.1:{master.grpc_port}"],
        ip="127.0.0.1", port=free_port(), store="memory", max_mb=1,
    )
    filer.start()
    yield master, vsrv, filer
    filer.stop()
    vsrv.stop()
    master.stop()


def test_upload_download_roundtrip(stack, tmp_path):
    master, _, _ = stack
    src = tmp_path / "up.bin"
    src.write_bytes(b"upload-download-payload" * 100)
    results = upload_files(f"127.0.0.1:{master.port}", [str(src)])
    assert len(results) == 1 and results[0]["fid"]
    outdir = tmp_path / "dl"
    outdir.mkdir()
    paths = download_files(f"127.0.0.1:{master.port}",
                           [results[0]["fid"]], str(outdir))
    assert len(paths) == 1
    assert open(paths[0], "rb").read() == src.read_bytes()


def test_filer_copy_and_cat(stack, tmp_path):
    _, _, filer = stack
    d = tmp_path / "tree"
    (d / "sub").mkdir(parents=True)
    (d / "top.txt").write_bytes(b"top-level")
    (d / "sub" / "deep.txt").write_bytes(b"deep-file")
    created = filer_copy(f"127.0.0.1:{filer.port}", [str(d)], "/copied")
    assert sorted(created) == [
        "/copied/tree/sub/deep.txt", "/copied/tree/top.txt"]
    assert filer_cat(f"127.0.0.1:{filer.port}",
                     "/copied/tree/top.txt") == b"top-level"
    assert filer_cat(f"127.0.0.1:{filer.port}",
                     "/copied/tree/sub/deep.txt") == b"deep-file"
    with pytest.raises(FileNotFoundError):
        filer_cat(f"127.0.0.1:{filer.port}", "/copied/absent")


def test_backup_incremental(stack, tmp_path):
    master, _, _ = stack
    maddr = f"127.0.0.1:{master.port}"
    f1 = tmp_path / "b1.bin"
    f1.write_bytes(b"backup-one" * 50)
    r1 = upload_files(maddr, [str(f1)])
    vid = int(r1[0]["fid"].partition(",")[0])

    bdir = str(tmp_path / "mirror")
    res = backup_volume(maddr, vid, bdir)
    assert res["appended"] >= 1
    assert os.path.exists(os.path.join(bdir, f"{vid}.dat"))

    # incremental: a second backup after another write to the SAME volume
    # appends only the delta
    f2 = tmp_path / "b2.bin"
    f2.write_bytes(b"backup-two" * 50)
    # force same volume by writing directly via assign loop until vid matches
    for _ in range(20):
        r2 = upload_files(maddr, [str(f2)])
        if int(r2[0]["fid"].partition(",")[0]) == vid:
            break
    else:
        pytest.skip("assigner never placed the second blob on the volume")
    res2 = backup_volume(maddr, vid, bdir)
    assert res2["appended"] >= 1 and not res2["full_resync"]
    # an immediate third run has nothing new
    res3 = backup_volume(maddr, vid, bdir)
    assert res3["appended"] == 0

    # the mirrored volume is readable offline and contains both payloads
    from seaweedfs_tpu.storage.volume import Volume

    vol = Volume(bdir, "", vid)
    payloads = [bytes(vol.read_needle(nv.key).data)
                for nv in vol.needle_map.items_ascending()]
    vol.close()
    assert any(b"backup-one" in p for p in payloads)
    assert any(b"backup-two" in p for p in payloads)
