"""Raft consensus tests: election, replication, leader failover, log
conflict repair, and the master-quorum integration.

Reference analogue: the raft behavior of weed/server/raft_server.go (leader
election + MaxVolumeId state machine) exercised without real processes,
like SURVEY.md §4 tier 3.
"""

import threading
import time

import pytest

from seaweedfs_tpu.master.raft import LEADER, RaftNode


class Net:
    """In-memory lossy transport between named nodes."""

    def __init__(self):
        self.nodes: dict[str, RaftNode] = {}
        self.cut: set[tuple[str, str]] = set()
        self.lock = threading.Lock()

    def send(self, src: str):
        def _send(dst: str, msg: dict):
            with self.lock:
                if (src, dst) in self.cut or (dst, src) in self.cut:
                    return None
                node = self.nodes.get(dst)
            if node is None:
                return None
            return node.handle(msg)

        return _send

    def partition(self, a: str, b: str):
        with self.lock:
            self.cut.add((a, b))

    def heal(self):
        with self.lock:
            self.cut.clear()


def make_cluster(n=3, tmp_path=None):
    net = Net()
    ids = [f"n{i}" for i in range(n)]
    applied = {i: [] for i in ids}
    nodes = []
    for i in ids:
        node = RaftNode(
            i, ids, net.send(i),
            apply_fn=lambda cmd, i=i: applied[i].append(cmd),
            state_path=str(tmp_path / f"{i}.raft") if tmp_path else "",
            election_timeout=(0.15, 0.3),
            heartbeat_interval=0.05,
        )
        net.nodes[i] = node
        nodes.append(node)
    return net, nodes, applied


def wait_leader(nodes, timeout=5.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        leaders = [n for n in nodes if n.is_leader() and not n._stop.is_set()]
        if len(leaders) == 1:
            return leaders[0]
        time.sleep(0.02)
    raise AssertionError("no single leader elected")


def test_raft_elects_single_leader(tmp_path):
    net, nodes, _ = make_cluster(3, tmp_path)
    for n in nodes:
        n.start()
    leader = wait_leader(nodes)
    time.sleep(0.3)
    assert sum(1 for n in nodes if n.is_leader()) == 1
    assert all(n.leader_id == leader.id for n in nodes)
    for n in nodes:
        n.stop()


def test_raft_replicates_and_applies(tmp_path):
    net, nodes, applied = make_cluster(3, tmp_path)
    for n in nodes:
        n.start()
    leader = wait_leader(nodes)
    for v in (5, 9, 12):
        assert leader.propose({"op": "max_vid", "value": v}, timeout=3)
    deadline = time.time() + 3
    want = [{"op": "max_vid", "value": v} for v in (5, 9, 12)]
    while time.time() < deadline:
        if all(applied[n.id] == want for n in nodes):
            break
        time.sleep(0.02)
    for n in nodes:
        assert applied[n.id] == want, f"{n.id} applied {applied[n.id]}"
        n.stop()


def test_raft_leader_failover_preserves_log(tmp_path):
    net, nodes, applied = make_cluster(3, tmp_path)
    for n in nodes:
        n.start()
    leader = wait_leader(nodes)
    assert leader.propose({"op": "max_vid", "value": 7}, timeout=3)
    leader.stop()
    net.nodes.pop(leader.id)
    rest = [n for n in nodes if n is not leader]
    new_leader = wait_leader(rest)
    assert new_leader is not leader
    # the committed entry survives the failover
    assert any(
        e.command == {"op": "max_vid", "value": 7} for e in new_leader.log
    )
    assert new_leader.propose({"op": "max_vid", "value": 8}, timeout=3)
    deadline = time.time() + 3
    while time.time() < deadline:
        if all(
            {"op": "max_vid", "value": 8} in applied[n.id] for n in rest
        ):
            break
        time.sleep(0.02)
    for n in rest:
        assert {"op": "max_vid", "value": 7} in applied[n.id]
        assert {"op": "max_vid", "value": 8} in applied[n.id]
        n.stop()


def test_raft_minority_partition_cannot_commit(tmp_path):
    net, nodes, applied = make_cluster(3, tmp_path)
    for n in nodes:
        n.start()
    leader = wait_leader(nodes)
    others = [n for n in nodes if n is not leader]
    # isolate the leader from both followers
    for o in others:
        net.partition(leader.id, o.id)
    assert not leader.propose({"op": "max_vid", "value": 99}, timeout=1.0)
    new_leader = wait_leader(others)
    assert new_leader.propose({"op": "max_vid", "value": 100}, timeout=3)
    net.heal()
    # old leader rejoins as follower and repairs its log
    deadline = time.time() + 5
    while time.time() < deadline:
        if (
            not leader.is_leader()
            and {"op": "max_vid", "value": 100} in applied[leader.id]
        ):
            break
        time.sleep(0.02)
    assert not leader.is_leader()
    assert {"op": "max_vid", "value": 100} in applied[leader.id]
    assert {"op": "max_vid", "value": 99} not in applied[new_leader.id]
    for n in nodes:
        n.stop()


def test_raft_persistence_restart(tmp_path):
    net, nodes, applied = make_cluster(3, tmp_path)
    for n in nodes:
        n.start()
    leader = wait_leader(nodes)
    assert leader.propose({"op": "max_vid", "value": 42}, timeout=3)
    for n in nodes:
        n.stop()
    # restart from persisted state: the log must survive
    reborn = RaftNode("n0", ["n0", "n1", "n2"], lambda d, m: None,
                      state_path=str(tmp_path / "n0.raft"))
    assert any(
        e.command == {"op": "max_vid", "value": 42} for e in reborn.log
    )
    assert reborn.term >= 1


def test_raft_apply_time_increment_unique_across_failover(tmp_path):
    """Ids computed at APPLY time cannot be re-issued after failover even
    when the new leader's commit index lags the old leader's (the
    stale-read hazard of proposing a precomputed value)."""
    net, nodes, _ = make_cluster(3, tmp_path)
    counters = {n.id: [0] for n in nodes}
    for n in nodes:
        counter = counters[n.id]

        def apply(cmd, counter=counter):
            if cmd.get("op") == "inc":
                counter[0] += 1
                return counter[0]
            return None

        n.apply_fn = apply
        n.start()
    leader = wait_leader(nodes)
    issued = []
    for _ in range(3):
        ok, v = leader.propose_and_get({"op": "inc"}, timeout=3)
        assert ok
        issued.append(v)
    assert issued == [1, 2, 3]
    leader.stop()
    net.nodes.pop(leader.id)
    rest = [n for n in nodes if n is not leader]
    new_leader = wait_leader(rest)
    ok, v = new_leader.propose_and_get({"op": "inc"}, timeout=3)
    assert ok and v == 4, f"expected fresh id 4, got {v}"
    for n in rest:
        n.stop()


def test_raft_restart_mid_election_cannot_double_vote(tmp_path):
    """A node that voted, crashed, and restarted in the SAME term must
    honor its persisted voted_for — re-granting the vote to a second
    candidate would allow two leaders in one term."""
    path = str(tmp_path / "n0.raft")
    node = RaftNode("n0", ["n0", "n1", "n2"], lambda d, m: None,
                    state_path=path)
    vote_a = {"type": "vote", "term": 5, "candidate": "n1",
              "last_log_index": 0, "last_log_term": 0}
    assert node.handle(vote_a)["granted"] is True
    # crash + restart: only what _persist() wrote survives
    reborn = RaftNode("n0", ["n0", "n1", "n2"], lambda d, m: None,
                      state_path=path)
    assert reborn.term == 5
    assert reborn.voted_for == "n1"
    vote_b = {"type": "vote", "term": 5, "candidate": "n2",
              "last_log_index": 0, "last_log_term": 0}
    assert reborn.handle(vote_b)["granted"] is False
    # the original candidate may retry (vote is idempotent per term)
    assert reborn.handle(vote_a)["granted"] is True


def test_raft_same_term_stepdown_keeps_vote(tmp_path):
    """_become_follower at an UNCHANGED term (candidate losing, leader
    check-quorum step-down) must not clear voted_for: votedFor is per
    term (Raft fig. 2)."""
    node = RaftNode("n0", ["n0", "n1", "n2"], lambda d, m: None,
                    state_path=str(tmp_path / "n0.raft"))
    vote = {"type": "vote", "term": 3, "candidate": "n1",
            "last_log_index": 0, "last_log_term": 0}
    assert node.handle(vote)["granted"] is True
    with node.lock:
        node._become_follower(node.term)  # same-term step-down
    assert node.voted_for == "n1"
    rival = {"type": "vote", "term": 3, "candidate": "n2",
             "last_log_index": 0, "last_log_term": 0}
    assert node.handle(rival)["granted"] is False
    # a HIGHER term does reset the vote
    later = {"type": "vote", "term": 4, "candidate": "n2",
             "last_log_index": 0, "last_log_term": 0}
    assert node.handle(later)["granted"] is True


def test_raft_conflicting_entries_truncated_to_converge(tmp_path):
    """A follower holding uncommitted entries from a deposed leader
    truncates them when the new leader's AppendEntries conflicts, and
    converges on the new leader's log."""
    applied = []
    node = RaftNode("n0", ["n0", "n1", "n2"], lambda d, m: None,
                    apply_fn=applied.append,
                    state_path=str(tmp_path / "n0.raft"))
    # deposed leader at term 1 replicated two entries, never committed
    stale = {"type": "append", "term": 1, "leader": "n1",
             "prev_log_index": 0, "prev_log_term": 0,
             "entries": [{"term": 1, "command": {"op": "max_vid",
                                                 "value": 7}},
                         {"term": 1, "command": {"op": "max_vid",
                                                 "value": 8}}],
             "leader_commit": 0}
    assert node.handle(stale)["success"] is True
    assert len(node.log) == 2
    # new leader at term 2 won without those entries; its first append
    # conflicts at index 1 — both stale entries must go
    fresh = {"type": "append", "term": 2, "leader": "n2",
             "prev_log_index": 0, "prev_log_term": 0,
             "entries": [{"term": 2, "command": {"op": "noop"}},
                         {"term": 2, "command": {"op": "max_vid",
                                                 "value": 9}}],
             "leader_commit": 2}
    assert node.handle(fresh)["success"] is True
    assert [e.term for e in node.log] == [2, 2]
    assert {"op": "max_vid", "value": 9} in applied
    assert {"op": "max_vid", "value": 7} not in applied
    assert {"op": "max_vid", "value": 8} not in applied
    # restart: the truncation was persisted, not just in memory
    reborn = RaftNode("n0", ["n0", "n1", "n2"], lambda d, m: None,
                      state_path=str(tmp_path / "n0.raft"))
    assert [e.term for e in reborn.log] == [2, 2]


def test_raft_partitioned_leader_steps_down(tmp_path):
    """Check-quorum: a leader cut off from every follower deposes ITSELF
    within an election timeout instead of reigning over a phantom
    cluster — the hook that lets the control plane fence its executors
    on the minority side of an asymmetric partition."""
    net, nodes, applied = make_cluster(3, tmp_path)
    for n in nodes:
        n.start()
    leader = wait_leader(nodes)
    deposed = threading.Event()
    leader.on_role_change = lambda role, term: (
        deposed.set() if role != LEADER else None)
    for o in nodes:
        if o is not leader:
            net.partition(leader.id, o.id)
    assert not leader.propose({"op": "max_vid", "value": 50}, timeout=1.0)
    assert deposed.wait(5.0), "partitioned leader never stepped down"
    assert not leader.is_leader()
    # the unreplicated entry must not have been applied anywhere
    for n in nodes:
        assert {"op": "max_vid", "value": 50} not in applied[n.id]
    net.heal()
    new_leader = wait_leader(nodes)
    assert new_leader.propose({"op": "max_vid", "value": 51}, timeout=3)
    for n in nodes:
        n.stop()


def test_master_peers_mismatch_rejected(tmp_path):
    from seaweedfs_tpu.master.server import MasterServer

    with pytest.raises(ValueError):
        MasterServer(ip="127.0.0.1", port=19999,
                     peers=["10.0.0.1:9333", "10.0.0.2:9333"])


# -- master quorum integration ----------------------------------------------


def _free_port():
    from helpers import free_port

    return free_port()


def test_master_quorum_failover(tmp_path):
    import urllib.request

    from seaweedfs_tpu.master.server import MasterServer

    ports = [_free_port() for _ in range(3)]
    peers = [f"127.0.0.1:{p}" for p in ports]
    masters = []
    for p in ports:
        m = MasterServer(ip="127.0.0.1", port=p, peers=peers,
                         raft_state_dir=str(tmp_path))
        m.start()
        masters.append(m)
    deadline = time.time() + 10
    leader = None
    while time.time() < deadline:
        leaders = [m for m in masters if m.is_leader()]
        if len(leaders) == 1:
            leader = leaders[0]
            break
        time.sleep(0.05)
    assert leader is not None, "master quorum elected no leader"
    # every master converges on the leader address (followers learn it from
    # the next AppendEntries heartbeat, not instantly)
    deadline = time.time() + 10
    while time.time() < deadline:
        if all(m.leader() == f"127.0.0.1:{leader.port}" for m in masters):
            break
        if len([m for m in masters if m.is_leader()]) != 1:
            leader = next((m for m in masters if m.is_leader()), leader)
        time.sleep(0.05)
    for m in masters:
        assert m.leader() == f"127.0.0.1:{leader.port}"
    # cluster status endpoint reports raft state
    follower = next(m for m in masters if m is not leader)
    with urllib.request.urlopen(
        f"http://127.0.0.1:{follower.port}/cluster/status", timeout=5
    ) as r:
        import json

        status = json.loads(r.read())
    assert status["Leader"] == f"127.0.0.1:{leader.port}"
    assert status["IsLeader"] is False
    # leader replicates max volume id through the quorum
    vid = leader.next_volume_id()
    deadline = time.time() + 5
    while time.time() < deadline:
        if all(m.topo.max_volume_id >= vid for m in masters):
            break
        time.sleep(0.05)
    for m in masters:
        assert m.topo.max_volume_id >= vid
    # failover: stop the leader, a new one takes over with the state
    leader.stop()
    rest = [m for m in masters if m is not leader]
    deadline = time.time() + 20  # loaded 1-vCPU host: elections are slow
    new_leader = None
    while time.time() < deadline:
        leaders = [m for m in rest if m.is_leader()]
        if len(leaders) == 1:
            new_leader = leaders[0]
            break
        time.sleep(0.05)
    assert new_leader is not None, "no failover leader"
    assert new_leader.topo.max_volume_id >= vid
    vid2 = new_leader.next_volume_id()
    assert vid2 > vid
    for m in rest:
        m.stop()


def test_raft_transport_rejects_forged_messages(tmp_path):
    """With a cluster secret set, unsigned /cluster/raft POSTs are refused
    — forged append/vote messages must not corrupt the quorum."""
    import json
    import urllib.error
    import urllib.request

    from seaweedfs_tpu.master.server import MasterServer

    ports = [_free_port() for _ in range(2)]
    peers = [f"127.0.0.1:{p}" for p in ports]
    masters = [
        MasterServer(ip="127.0.0.1", port=p, peers=peers,
                     raft_state_dir=str(tmp_path), jwt_signing_key=b"sekrit")
        for p in ports
    ]
    for m in masters:
        m.start()
    try:
        deadline = time.time() + 10
        while time.time() < deadline:
            if any(m.is_leader() for m in masters):
                break
            time.sleep(0.05)
        assert any(m.is_leader() for m in masters), \
            "signed quorum failed to elect"
        forged = json.dumps({
            "type": "append", "term": 999, "leader": "evil",
            "prev_log_index": 0, "prev_log_term": 0,
            "entries": [{"term": 999, "command": {"op": "max_vid",
                                                  "value": 4_000_000_000}}],
            "leader_commit": 1,
        }).encode()
        req = urllib.request.Request(
            f"http://127.0.0.1:{ports[0]}/cluster/raft", data=forged,
            headers={"Content-Type": "application/json"},
        )
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(req, timeout=5)
        assert ei.value.code == 403
        assert masters[0].raft.term < 999
        assert masters[0].topo.max_volume_id < 4_000_000_000
    finally:
        for m in masters:
            m.stop()


def test_follower_redirects_admin_endpoints(tmp_path):
    """Followers 307 state-bearing HTTP endpoints to the leader, draining
    posted bodies first so keep-alive connections stay in sync."""
    import json
    import urllib.request

    from seaweedfs_tpu.master.server import MasterServer

    ports = [_free_port() for _ in range(3)]
    peers = [f"127.0.0.1:{p}" for p in ports]
    masters = []
    for p in ports:
        m = MasterServer(ip="127.0.0.1", port=p, peers=peers,
                         raft_state_dir=str(tmp_path))
        m.start()
        masters.append(m)
    try:
        deadline = time.time() + 15
        leader = None
        while time.time() < deadline:
            leaders = [m for m in masters if m.is_leader()]
            if len(leaders) == 1 and all(
                    m.leader() == f"127.0.0.1:{leaders[0].port}"
                    for m in masters):
                leader = leaders[0]
                break
            time.sleep(0.05)
        assert leader is not None
        follower = next(m for m in masters if m is not leader)
        expect = f"http://127.0.0.1:{leader.port}"

        class NoRedirect(urllib.request.HTTPRedirectHandler):
            def redirect_request(self, *a, **k):
                return None

        opener = urllib.request.build_opener(NoRedirect)
        for path in ("/dir/assign", "/vol/grow?collection=x",
                     "/vol/status"):
            try:
                with opener.open(
                        f"http://127.0.0.1:{follower.port}{path}",
                        timeout=5) as r:
                    code, loc = r.status, r.headers.get("Location", "")
            except urllib.error.HTTPError as e:
                code, loc = e.code, e.headers.get("Location", "")
                e.close()
            assert code == 307, (path, code)
            assert loc.startswith(expect), (path, loc)
        # POST /submit with a body: redirect + the body must be drained
        req = urllib.request.Request(
            f"http://127.0.0.1:{follower.port}/submit",
            data=b"x" * 100000, method="POST")
        try:
            with opener.open(req, timeout=5) as r:
                code = r.status
        except urllib.error.HTTPError as e:
            code = e.code
            e.close()
        assert code == 307
        # healthz: follower knowing a leader is healthy
        with urllib.request.urlopen(
                f"http://127.0.0.1:{follower.port}/cluster/healthz",
                timeout=5) as r:
            assert json.loads(r.read())["ok"] is True
    finally:
        for m in masters:
            m.stop()


def test_volume_server_chases_leader_across_failover(tmp_path):
    """The full membership story (SURVEY §3.4): a volume server
    heartbeating a 3-master quorum re-registers with the NEW leader
    after the old one dies, and assigns keep working."""
    import urllib.request

    from seaweedfs_tpu.master.server import MasterServer
    from seaweedfs_tpu.volume.server import VolumeServer

    ports = [_free_port() for _ in range(3)]
    peers = [f"127.0.0.1:{p}" for p in ports]
    masters = [MasterServer(ip="127.0.0.1", port=p, peers=peers,
                            raft_state_dir=str(tmp_path))
               for p in ports]
    for m in masters:
        m.start()
    vs = None
    try:
        deadline = time.time() + 15
        leader = None
        while time.time() < deadline:
            leaders = [m for m in masters if m.is_leader()]
            if len(leaders) == 1:
                leader = leaders[0]
                break
            time.sleep(0.05)
        assert leader is not None

        vs = VolumeServer(
            directories=[str(tmp_path / "v")],
            master_addresses=[f"127.0.0.1:{p + 10000}" for p in ports],
            ip="127.0.0.1", port=_free_port(), pulse_seconds=0.5,
            max_volume_count=20,
        )
        vs.start()
        deadline = time.time() + 20
        while time.time() < deadline and not leader.topo.nodes:
            time.sleep(0.1)
        assert leader.topo.nodes, "VS never registered with the leader"

        def assign_ok(m) -> bool:
            try:
                with urllib.request.urlopen(
                        f"http://127.0.0.1:{m.port}/dir/assign",
                        timeout=5) as r:
                    import json as _json

                    return "fid" in _json.loads(r.read())
            except Exception:
                return False

        assert assign_ok(leader)

        leader.stop()
        rest = [m for m in masters if m is not leader]
        deadline = time.time() + 30  # loaded host: elections are slow
        new_leader = None
        while time.time() < deadline:
            leaders = [m for m in rest if m.is_leader()]
            if len(leaders) == 1:
                new_leader = leaders[0]
                break
            time.sleep(0.1)
        assert new_leader is not None, "no failover leader"
        # the VS must chase the new leader and re-register there
        deadline = time.time() + 30
        while time.time() < deadline and not new_leader.topo.nodes:
            time.sleep(0.2)
        assert new_leader.topo.nodes, "VS did not re-register after failover"
        deadline = time.time() + 20
        ok = False
        while time.time() < deadline:
            if assign_ok(new_leader):
                ok = True
                break
            time.sleep(0.5)
        assert ok, "assign does not work on the failover leader"
    finally:
        if vs is not None:
            vs.stop()
        for m in masters:
            try:
                m.stop()
            except Exception:
                pass
