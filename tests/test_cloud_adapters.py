"""Cloud notification publishers + replication sinks: config parsing and
wire-format construction (the parts that run in ANY deployment; the
network sends need egress/credentials).

Reference: weed/notification/{kafka,aws_sqs,google_pub_sub},
weed/replication/sink/{s3sink,gcssink,azuresink,b2sink}.
"""

from __future__ import annotations

import base64
import urllib.parse

import pytest

from seaweedfs_tpu.notification.publishers import (
    ConfigurationError,
    GcpPubSubPublisher,
    KafkaPublisher,
    SqsPublisher,
    make_publisher,
)
from seaweedfs_tpu.pb import filer_pb2
from seaweedfs_tpu.replication.sink import (
    AzureSink,
    B2Sink,
    GcsSink,
    SignedS3Sink,
)


def _event(name: str = "f.txt") -> filer_pb2.EventNotification:
    ev = filer_pb2.EventNotification()
    ev.new_entry.name = name
    return ev


def test_kafka_config_and_mapping(monkeypatch):
    # without the client library, construction fails LOUDLY at startup
    # (a publish-time error would vanish in the meta-log listener)
    with pytest.raises(ConfigurationError):
        KafkaPublisher("broker1:9092", "fs-events")
    import sys
    import types

    monkeypatch.setitem(sys.modules, "kafka", types.ModuleType("kafka"))
    p = KafkaPublisher("broker1:9092, broker2:9092", "fs-events")
    assert p.hosts == ["broker1:9092", "broker2:9092"]
    k, v = p.map_event("/d/f.txt", _event())
    assert k == b"/d/f.txt"
    parsed = filer_pb2.EventNotification()
    parsed.ParseFromString(v)
    assert parsed.new_entry.name == "f.txt"
    with pytest.raises(ConfigurationError):
        KafkaPublisher("", "topic")


def test_sqs_signed_request_shape():
    p = SqsPublisher(
        "https://sqs.us-west-2.amazonaws.com/123456789/fs-events",
        "us-west-2", access_key="AKIDEXAMPLE", secret_key="SECRET")
    url, headers, body = p.build_request("/d/f.txt", _event())
    assert url.startswith("https://sqs.us-west-2")
    auth = headers["Authorization"]
    assert auth.startswith("AWS4-HMAC-SHA256 Credential=AKIDEXAMPLE/")
    assert "/us-west-2/sqs/aws4_request" in auth
    assert "Signature=" in auth
    form = urllib.parse.parse_qs(body.decode())
    assert form["Action"] == ["SendMessage"]
    ev = filer_pb2.EventNotification()
    ev.ParseFromString(base64.b64decode(form["MessageBody"][0]))
    assert ev.new_entry.name == "f.txt"
    assert form["MessageAttribute.1.Value.StringValue"] == ["/d/f.txt"]
    with pytest.raises(ConfigurationError):
        SqsPublisher("", "us-west-2")


def test_gcp_pubsub_payload():
    p = GcpPubSubPublisher("my-proj", "fs-events",
                           token_source=lambda: "tok")
    assert "projects/my-proj/topics/fs-events:publish" in p.endpoint
    import json

    payload = json.loads(p.build_payload("/d/f.txt", _event()))
    msg = payload["messages"][0]
    assert msg["attributes"]["key"] == "/d/f.txt"
    ev = filer_pb2.EventNotification()
    ev.ParseFromString(base64.b64decode(msg["data"]))
    assert ev.new_entry.name == "f.txt"
    with pytest.raises(ConfigurationError):
        GcpPubSubPublisher("", "t", token_source=lambda: "x")
    with pytest.raises(ConfigurationError):
        GcpPubSubPublisher("p", "t")  # token source required at startup


def test_make_publisher_dispatch(monkeypatch):
    import sys
    import types

    monkeypatch.setitem(sys.modules, "kafka", types.ModuleType("kafka"))
    p = make_publisher("kafka", hosts="h:9092", topic="t")
    assert isinstance(p, KafkaPublisher)
    p = make_publisher("aws_sqs", queue_url="https://sqs.x/y",
                       region="r", aws_access_key_id="a",
                       aws_secret_access_key="s")
    assert isinstance(p, SqsPublisher)
    with pytest.raises(ConfigurationError):
        make_publisher("nope")
    with pytest.raises(ConfigurationError, match="Go-CDK"):
        make_publisher("gocdk_pub_sub")


def test_signed_s3_sink_headers():
    s = SignedS3Sink("s3.amazonaws.com", "bkt", "AK", "SK",
                     region="eu-central-1", prefix="mirror")
    assert s._key("/dir", "f.bin") == "mirror/dir/f.bin"
    h = s.signed_headers("PUT", "mirror/dir/f.bin", b"data")
    assert "/eu-central-1/s3/aws4_request" in h["Authorization"]
    assert h["x-amz-content-sha256"] != ""


def test_gcs_b2_sink_endpoints():
    g = GcsSink("bkt", "AK", "SK")
    assert g.endpoint == "storage.googleapis.com"
    b = B2Sink("us-west-004", "bkt", "KID", "APPKEY")
    assert b.endpoint == "s3.us-west-004.backblazeb2.com"
    assert "/us-west-004/s3/aws4_request" in \
        b.signed_headers("PUT", "k", b"x")["Authorization"]


def test_azure_shared_key_headers():
    key = base64.b64encode(b"0" * 32).decode()
    a = AzureSink("myacct", key, "container", prefix="mirror")
    h = a.signed_headers("PUT", "mirror/d/f.txt", b"data",
                         "text/plain")
    assert h["Authorization"].startswith("SharedKey myacct:")
    assert h["x-ms-blob-type"] == "BlockBlob"
    assert a._url("k") == \
        "https://myacct.blob.core.windows.net/container/k"
