"""Tier-4 compose-style harness: REAL subprocesses through the CLI.

Every other integration test runs servers in-process; this one spawns
`python -m seaweedfs_tpu master|volume|filer` exactly as an operator
would (SURVEY §4 tier 4, the reference's local-cluster-compose.yml), so
CLI flag wiring, module entry points, and cross-process gRPC/HTTP all
get exercised end to end.
"""

import json
import os
import signal
import subprocess
import sys
import time
import urllib.error
import urllib.request

from helpers import free_port

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _env():
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    return env


def _spawn(args, cwd):
    env = _env()
    # subprocesses must not touch the (possibly wedged) device tunnel:
    # the volume server's -ec.codec default probes in a subprocess, but
    # cpu pins it outright
    # DEVNULL: the output is never asserted on, and an unread PIPE would
    # block a chatty server once the 64KB buffer fills
    return subprocess.Popen(
        [sys.executable, "-m", "seaweedfs_tpu", *args],
        cwd=cwd, env=env,
        stdout=subprocess.DEVNULL, stderr=subprocess.STDOUT,
    )


def _wait_http(url, deadline_s=25):
    deadline = time.time() + deadline_s
    while time.time() < deadline:
        try:
            with urllib.request.urlopen(url, timeout=2) as r:
                return r.status
        except (urllib.error.URLError, ConnectionError, OSError):
            time.sleep(0.3)
    raise TimeoutError(url)


def test_cli_three_process_cluster(tmp_path):
    mport, vport, fport = free_port(), free_port(), free_port()
    vol_dir = tmp_path / "v1"
    vol_dir.mkdir()
    procs = []
    try:
        procs.append(_spawn(["master", "-port", str(mport)],
                            str(tmp_path)))
        _wait_http(f"http://127.0.0.1:{mport}/cluster/healthz")
        procs.append(_spawn(
            ["volume", "-dir", str(vol_dir), "-port", str(vport),
             "-mserver", f"127.0.0.1:{mport}", "-ec.codec", "cpu"],
            str(tmp_path)))
        procs.append(_spawn(
            ["filer", "-master", f"127.0.0.1:{mport}",
             "-port", str(fport),
             "-store", str(tmp_path / "filer.db")],
            str(tmp_path)))
        _wait_http(f"http://127.0.0.1:{fport}/")

        # wait for the volume server to register with the master
        deadline = time.time() + 20
        while time.time() < deadline:
            try:
                with urllib.request.urlopen(
                    f"http://127.0.0.1:{mport}/dir/assign", timeout=2
                ) as r:
                    assign = json.loads(r.read())
                if assign.get("fid"):
                    break
            except (urllib.error.URLError, OSError):
                pass
            time.sleep(0.3)
        else:
            raise AssertionError("master never produced an assignment")

        # filer write + read across three real processes
        payload = b"three-process-cluster!"
        req = urllib.request.Request(
            f"http://127.0.0.1:{fport}/dir/hello.txt", data=payload,
            method="PUT")
        with urllib.request.urlopen(req, timeout=10) as r:
            assert r.status in (200, 201)
        with urllib.request.urlopen(
            f"http://127.0.0.1:{fport}/dir/hello.txt", timeout=10
        ) as r:
            assert r.read() == payload

        # the shell subcommand drives the live cluster as a 4th process
        out = subprocess.run(
            [sys.executable, "-m", "seaweedfs_tpu", "shell",
             "-m", f"127.0.0.1:{mport}", "-c", "volume.list"],
            cwd=str(tmp_path), capture_output=True, text=True,
            timeout=30, env=_env(),
        )
        assert out.returncode == 0
        assert f"127.0.0.1:{vport}" in out.stdout
    finally:
        for p in procs:
            p.send_signal(signal.SIGTERM)
        for p in procs:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()


def test_cli_three_master_raft_quorum(tmp_path):
    """A 3-master raft quorum as real CLI subprocesses: exactly one
    leader, followers redirect admin writes, and /cluster/status agrees
    (reference: local-cluster-compose.yml's 3-master raft tier)."""
    ports = [free_port() for _ in range(3)]
    peers = ",".join(f"127.0.0.1:{p}" for p in ports)
    procs = []
    try:
        for i, p in enumerate(ports):
            d = tmp_path / f"m{i}"
            d.mkdir()
            procs.append(_spawn(
                ["master", "-port", str(p), "-peers", peers,
                 "-raftDir", str(d)], str(tmp_path)))
        for p in ports:
            _wait_http(f"http://127.0.0.1:{p}/cluster/healthz")

        # a leader emerges and every node names the same one
        deadline = time.time() + 30
        leaders = set()
        while time.time() < deadline:
            leaders = set()
            ok = True
            for p in ports:
                try:
                    with urllib.request.urlopen(
                        f"http://127.0.0.1:{p}/cluster/status",
                        timeout=2,
                    ) as r:
                        st = json.loads(r.read())
                    leaders.add(st.get("Leader") or st.get("leader"))
                except (urllib.error.URLError, OSError, ValueError):
                    ok = False
            if ok and len(leaders) == 1 and None not in leaders:
                break
            time.sleep(0.5)
        assert len(leaders) == 1 and None not in leaders, leaders
        leader = leaders.pop()

        # followers answer admin writes with a redirect to that leader
        follower = next(f"127.0.0.1:{p}" for p in ports
                        if f"127.0.0.1:{p}" != leader)
        req = urllib.request.Request(
            f"http://{follower}/vol/grow?count=1", method="GET")

        class NoRedirect(urllib.request.HTTPRedirectHandler):
            def redirect_request(self, *a, **k):
                return None

        opener = urllib.request.build_opener(NoRedirect)
        try:
            resp = opener.open(req, timeout=5)
            code, location = resp.status, resp.headers.get("Location", "")
        except urllib.error.HTTPError as e:
            code, location = e.code, e.headers.get("Location", "")
        assert code in (307, 503), code
        if code == 307:
            assert leader in location
    finally:
        for p in procs:
            p.send_signal(signal.SIGTERM)
        for p in procs:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()
