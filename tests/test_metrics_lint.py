"""Metric-family lint: the registration rules CI enforces.

Every `seaweedfs_*` family must be registered exactly once, in
stats/metrics.py, with a snake_case name — scattered registration is how
two call sites end up disagreeing about a family's labels and silently
corrupting one of them (the pre-PR-5 state: failsafe.py and faultpoint.py
registered their own).  The Registry itself now raises on a conflicting
re-registration, and this test walks the source so a regression fails in
the lint job, not in production.
"""

from __future__ import annotations

import ast
import os
import re

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = os.path.join(REPO, "seaweedfs_tpu")
METRICS_PY = os.path.join(PKG, "stats", "metrics.py")

_SNAKE = re.compile(r"^[a-z][a-z0-9_]*$")
_REGISTER_METHODS = {"counter", "gauge", "histogram"}


def _registration_calls(path: str):
    """Yield (family_name_node, lineno) for REGISTRY.<kind>(...) calls."""
    tree = ast.parse(open(path).read(), filename=path)
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        if (isinstance(fn, ast.Attribute)
                and fn.attr in _REGISTER_METHODS
                and isinstance(fn.value, ast.Name)
                and fn.value.id == "REGISTRY"):
            yield node, node.lineno


def test_every_family_registered_once_in_metrics_py():
    seen: dict[str, int] = {}
    for call, lineno in _registration_calls(METRICS_PY):
        assert call.args and isinstance(call.args[0], ast.Constant), (
            f"metrics.py:{lineno}: family name must be a string literal")
        name = call.args[0].value
        assert isinstance(name, str)
        assert name.startswith("seaweedfs_"), (
            f"metrics.py:{lineno}: {name!r} must carry the seaweedfs_ "
            "namespace")
        assert _SNAKE.match(name), (
            f"metrics.py:{lineno}: {name!r} is not snake_case")
        assert name not in seen, (
            f"metrics.py:{lineno}: {name!r} already registered at "
            f"line {seen[name]}")
        seen[name] = lineno
    assert len(seen) >= 25, "registry looks implausibly small"


def test_no_registration_outside_metrics_py():
    offenders = []
    for dirpath, dirnames, filenames in os.walk(PKG):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for fn in filenames:
            if not fn.endswith(".py") or fn.endswith("_pb2.py"):
                continue
            path = os.path.join(dirpath, fn)
            if os.path.samefile(path, METRICS_PY):
                continue
            for _call, lineno in _registration_calls(path):
                offenders.append(f"{os.path.relpath(path, REPO)}:{lineno}")
    assert not offenders, (
        "metric families must be registered in stats/metrics.py only; "
        f"found REGISTRY registrations at: {offenders}")


def test_runtime_registry_matches_source_families():
    """Importing the package registers exactly the families the source
    declares — no duplicates, no import-order surprises."""
    from seaweedfs_tpu.stats.metrics import REGISTRY

    # importing the consumers must not add or conflict with anything
    import seaweedfs_tpu.util.failsafe  # noqa: F401
    import seaweedfs_tpu.util.faultpoint  # noqa: F401

    declared = set()
    for call, _ in _registration_calls(METRICS_PY):
        declared.add(call.args[0].value)
    registered = {n for n in REGISTRY._metrics if n.startswith("seaweedfs_")}
    assert declared == registered, (
        declared.symmetric_difference(registered))


def test_metrics_md_documents_every_registered_family():
    """Cross-check stats/metrics.py against METRICS.md: every registered
    family must be mentioned (table row or prose), and every family TABLE
    ROW must name a family that still exists — stale doc rows mislead the
    operator mid-incident, which is worse than no docs at all.

    The seaweedfs_federation_* meta-families are synthesized by the
    federation merger rather than registered, so the table-row check
    allow-lists them from the merger's own source of truth."""
    from seaweedfs_tpu.telemetry.federation import _META_FAMILIES

    metrics_md = os.path.join(REPO, "METRICS.md")
    text = open(metrics_md).read()

    registered = {call.args[0].value
                  for call, _ in _registration_calls(METRICS_PY)}

    # "documented" = named in backticks anywhere (tables or prose);
    # label-suffix mentions like `seaweedfs_x{le=...}` still match
    documented = set(re.findall(r"`(seaweedfs_[a-z0-9_]+)", text))
    undocumented = sorted(registered - documented)
    assert not undocumented, (
        f"families registered in stats/metrics.py but absent from "
        f"METRICS.md: {undocumented}")

    # table rows must reference live families only
    rows = re.findall(r"^\|\s*`(seaweedfs_[a-z0-9_]+)`",
                      text, flags=re.MULTILINE)
    known = registered | set(_META_FAMILIES)
    stale = sorted(set(rows) - known)
    assert not stale, (
        f"METRICS.md table rows for families that no longer exist: "
        f"{stale}")


def test_conflicting_reregistration_raises():
    from seaweedfs_tpu.stats.metrics import Registry

    r = Registry()
    r.counter("t_total", "x", labels=("a",))
    r.counter("t_total", "x", labels=("a",))  # identical: fine
    with pytest.raises(ValueError, match="already registered"):
        r.counter("t_total", "x", labels=("a", "b"))  # labels differ
    with pytest.raises(ValueError, match="already registered"):
        r.gauge("t_total", "x", labels=("a",))  # kind differs
    r.histogram("t_seconds", "x", labels=("op",))
    with pytest.raises(ValueError, match="already registered"):
        r.histogram("t_seconds", "x", labels=("other",))
    with pytest.raises(ValueError, match="already registered"):
        r.counter("t_seconds", "x")
