"""Serving-plane units (ISSUE 18): group-commit fsync barrier semantics
and the zero-copy sendfile GET path (needle extents + HTTP byte
identity)."""

from __future__ import annotations

import hashlib
import json
import os
import threading
import time
import urllib.error
import urllib.request

import pytest

from helpers import make_volume
from seaweedfs_tpu.ops import crc32c
from seaweedfs_tpu.storage.disk_health import DiskFullError
from seaweedfs_tpu.storage.needle import Needle
from seaweedfs_tpu.storage.vacuum import vacuum_volume
from seaweedfs_tpu.storage.volume import Volume
from seaweedfs_tpu.stats.metrics import (
    FSYNC_BATCH_COMMITS,
    FSYNC_BATCH_WRITES,
    SENDFILE_BYTES,
    SENDFILE_FALLBACK,
)


def _payload(i: int) -> bytes:
    seedb = hashlib.sha256(b"sp-%d" % i).digest()
    return (seedb * (1 + i % 30))[: 64 + (i * 97) % 900]


# -- group commit ------------------------------------------------------------


def test_batch_mode_concurrent_appends_durable(tmp_path, monkeypatch):
    monkeypatch.setenv("SEAWEEDFS_TPU_DURABILITY", "batch")
    v = Volume(str(tmp_path), "", 1)
    assert v.durability == "batch" and v._group is not None
    commits0 = FSYNC_BATCH_COMMITS.labels().value
    writes0 = FSYNC_BATCH_WRITES.labels().value
    n_writers, per = 8, 6
    errs: list[Exception] = []

    def writer(tid):
        for k in range(per):
            i = 1 + tid * per + k
            try:
                v.append_needle(Needle(cookie=9, id=i, data=_payload(i)))
            except Exception as e:  # noqa: BLE001 — surfaced in the assert
                errs.append(e)

    threads = [threading.Thread(target=writer, args=(t,))
               for t in range(n_writers)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(30)
    assert not errs, errs
    total = n_writers * per
    for i in range(1, total + 1):
        assert bytes(v.read_needle(i).data) == _payload(i)
    commits = FSYNC_BATCH_COMMITS.labels().value - commits0
    writes = FSYNC_BATCH_WRITES.labels().value - writes0
    assert writes >= total          # every append rode a barrier
    assert 1 <= commits <= writes   # ... and barriers batched (or not)
    v.close()


def test_batch_ack_only_after_fsync(tmp_path, monkeypatch):
    """No needle-map publish (and so no ack) may precede the barrier's
    fsync — the PR 14 contract with N writers sharing one fsync."""
    monkeypatch.setenv("SEAWEEDFS_TPU_DURABILITY", "batch")
    v = Volume(str(tmp_path), "", 1)
    events: list[str] = []
    real_sync = v._dat.sync
    real_publish = v._publish_append

    def spy_sync():
        events.append("fsync")
        real_sync()

    def spy_publish(nid, offset, size):
        events.append("publish")
        real_publish(nid, offset, size)

    monkeypatch.setattr(v._dat, "sync", spy_sync)
    monkeypatch.setattr(v, "_publish_append", spy_publish)
    for i in range(1, 6):
        v.append_needle(Needle(cookie=1, id=i, data=_payload(i)))
    assert "fsync" in events and "publish" in events
    assert events.index("fsync") < events.index("publish")
    # every publish is preceded by at least one fsync
    fsyncs = 0
    for ev in events:
        if ev == "fsync":
            fsyncs += 1
        else:
            assert fsyncs > 0
    v.close()


def test_batch_mode_deletes_ride_barrier(tmp_path, monkeypatch):
    monkeypatch.setenv("SEAWEEDFS_TPU_DURABILITY", "batch")
    v = Volume(str(tmp_path), "", 1)
    v.append_needle(Needle(cookie=3, id=7, data=_payload(7)))
    assert v.delete_needle(7) > 0
    with pytest.raises(KeyError):
        v.read_needle(7)
    v.close()
    # remount: the tombstone was fsync-durable before the delete acked
    v2 = Volume(str(tmp_path), "", 1)
    with pytest.raises(KeyError):
        v2.read_needle(7)
    v2.close()


def test_batch_fsync_failure_rolls_back_whole_batch(tmp_path, monkeypatch):
    monkeypatch.setenv("SEAWEEDFS_TPU_DURABILITY", "batch")
    v = Volume(str(tmp_path), "", 1)
    v.append_needle(Needle(cookie=2, id=1, data=_payload(1)))
    dat_size = v._dat.file_size()
    idx_size = os.path.getsize(v.file_name() + ".idx")

    def broken_sync():
        raise OSError(28, "No space left on device")

    monkeypatch.setattr(v._dat, "sync", broken_sync)
    with pytest.raises(DiskFullError):
        v.append_needle(Needle(cookie=2, id=2, data=_payload(2)))
    # nothing published, bytes rolled back, volume flipped read-only-full
    with pytest.raises(KeyError):
        v.read_needle(2)
    assert v._dat.file_size() == dat_size
    assert os.path.getsize(v.file_name() + ".idx") == idx_size
    assert v.read_only and v.read_only_reason == "full"
    # the previously-acked needle is untouched
    assert bytes(v.read_needle(1).data) == _payload(1)
    # space recovers: volume taken writable again serves new appends
    monkeypatch.undo()
    monkeypatch.setenv("SEAWEEDFS_TPU_DURABILITY", "batch")
    v.read_only = False
    v.read_only_reason = ""
    v.append_needle(Needle(cookie=2, id=3, data=_payload(3)))
    assert bytes(v.read_needle(3).data) == _payload(3)
    v.close()


def test_sync_mode_fsyncs_every_append(tmp_path, monkeypatch):
    monkeypatch.setenv("SEAWEEDFS_TPU_DURABILITY", "sync")
    v = Volume(str(tmp_path), "", 1)
    assert v.durability == "sync" and v._group is None
    calls = [0]
    real_sync = v._dat.sync

    def spy():
        calls[0] += 1
        real_sync()

    monkeypatch.setattr(v._dat, "sync", spy)
    for i in range(1, 5):
        v.append_needle(Needle(cookie=1, id=i, data=_payload(i)))
    assert calls[0] == 4  # one fsync pair per mutation: the A/B baseline
    v.close()


def test_default_mode_unchanged(tmp_path, monkeypatch):
    monkeypatch.delenv("SEAWEEDFS_TPU_DURABILITY", raising=False)
    v = Volume(str(tmp_path), "", 1)
    assert v.durability == "none" and v._group is None
    v.append_needle(Needle(cookie=1, id=1, data=b"x"))
    assert bytes(v.read_needle(1).data) == b"x"
    v.close()


# -- needle extents (zero-copy read descriptors) -----------------------------


def test_needle_extent_byte_identity(tmp_path):
    v = make_volume(str(tmp_path), n_needles=30, seed=11)
    try:
        for i in range(1, 31):
            ref = v.read_needle(i)
            ext = v.needle_extent(i)
            assert ext is not None
            with ext:
                got = os.pread(ext.fd, ext.data_len, ext.data_offset)
                assert got == bytes(ref.data), f"needle {i} bytes differ"
                assert ext.data_len == len(bytes(ref.data))
                n = ext.needle
                # metadata parsed WITHOUT reading the payload matches the
                # full parse: checksum (Etag), cookie, name, mime
                assert n.checksum == ref.checksum
                assert crc32c.checksum(got) == n.checksum
                assert n.cookie == ref.cookie
                assert bytes(n.name or b"") == bytes(ref.name or b"")
                assert bytes(n.mime or b"") == bytes(ref.mime or b"")
    finally:
        v.close()


def test_needle_extent_declines_and_misses(tmp_path):
    v = Volume(str(tmp_path), "", 1)
    try:
        with pytest.raises(KeyError):
            v.needle_extent(99)
        v.append_needle(Needle(cookie=1, id=1, data=b""))  # empty payload
        assert v.needle_extent(1) is None  # nothing to sendfile
        v.append_needle(Needle(cookie=1, id=2, data=b"live"))
        v.delete_needle(2)
        with pytest.raises(KeyError):
            v.needle_extent(2)
    finally:
        v.close()


def test_needle_extent_refuses_corrupt_payload(tmp_path):
    """Zero-copy must not out-race the CRC check: a needle whose on-disk
    payload rotted is DECLINED by the extent path (first serve verifies
    the payload crc32c), so the GET falls back to the ordinary read path
    and raises CorruptNeedleError into quarantine/rotation exactly as it
    did before sendfile existed — never a 200 of rotten bytes."""
    from seaweedfs_tpu.storage import types as t
    from seaweedfs_tpu.storage.needle import CorruptNeedleError

    v = make_volume(str(tmp_path), n_needles=6, seed=7)
    try:
        # a clean needle verifies once, then serves from the verified set
        ext = v.needle_extent(2)
        assert ext is not None
        ext.close()
        assert (2, v.needle_map.get(2).offset) in v._extent_verified
        ext = v.needle_extent(2)
        assert ext is not None
        ext.close()

        # flip one payload byte of needle 3 on disk
        nv = v.needle_map.get(3)
        data_off = nv.offset + t.NEEDLE_HEADER_SIZE + 4
        path = v.file_name() + ".dat"
        with open(path, "r+b") as f:
            f.seek(data_off + 1)
            b = f.read(1)
            f.seek(data_off + 1)
            f.write(bytes([b[0] ^ 0xFF]))
        assert v.needle_extent(3) is None  # declined, not served
        with pytest.raises(CorruptNeedleError):
            v.read_needle(3)
        # the healthy neighbours keep serving extents
        ext = v.needle_extent(4)
        assert ext is not None
        ext.close()
    finally:
        v.close()


def test_needle_extent_survives_vacuum_handle_swap(tmp_path):
    """The dup'd fd pins the OLD .dat's open file description: a vacuum
    committed mid-send cannot close it or recycle the fd number, and the
    old append-only bytes stay readable to the end of the stream."""
    v = make_volume(str(tmp_path), n_needles=10, seed=5)
    try:
        ref = bytes(v.read_needle(4).data)
        ext = v.needle_extent(4)
        assert ext is not None
        v.delete_needle(9)  # give the vacuum something to drop
        vacuum_volume(v)
        got = os.pread(ext.fd, ext.data_len, ext.data_offset)
        assert got == ref
        ext.close()
        # the vacuumed volume still serves (fresh handle, fresh extents)
        assert bytes(v.read_needle(4).data) == ref
        ext2 = v.needle_extent(4)
        assert ext2 is not None
        with ext2:
            assert os.pread(
                ext2.fd, ext2.data_len, ext2.data_offset) == ref
    finally:
        v.close()


# -- HTTP sendfile path ------------------------------------------------------


def _free_port() -> int:
    from helpers import free_port

    return free_port()


def _http(method, url, data=None, headers=None):
    req = urllib.request.Request(
        url, data=data, method=method, headers=headers or {})
    try:
        with urllib.request.urlopen(req, timeout=10) as r:
            return r.status, r.read(), dict(r.headers)
    except urllib.error.HTTPError as e:
        return e.code, e.read(), dict(e.headers)


@pytest.fixture(scope="module")
def mini_cluster(tmp_path_factory):
    from seaweedfs_tpu.master.server import MasterServer
    from seaweedfs_tpu.volume.server import VolumeServer

    master = MasterServer(ip="127.0.0.1", port=_free_port(),
                          volume_size_limit_mb=64)
    master.start()
    vs = VolumeServer(
        directories=[str(tmp_path_factory.mktemp("vol"))],
        master_addresses=[f"127.0.0.1:{master.grpc_port}"],
        ip="127.0.0.1", port=_free_port(), pulse_seconds=0.5)
    vs.start()
    deadline = time.time() + 15
    while time.time() < deadline and not master.topo.nodes:
        time.sleep(0.1)
    assert master.topo.nodes, "volume server did not register"
    yield master, vs
    vs.stop()
    master.stop()


def _assign(master) -> dict:
    code, body, _ = _http(
        "GET", f"http://127.0.0.1:{master.port}/dir/assign")
    assert code == 200, body
    return json.loads(body)


def _await(cond, timeout: float = 5.0) -> bool:
    """Counters tick on the server thread AFTER the last payload byte is
    on the wire — the client can observe the response first."""
    deadline = time.time() + timeout
    while time.time() < deadline:
        if cond():
            return True
        time.sleep(0.01)
    return cond()


def test_http_get_is_sendfile_and_byte_identical(mini_cluster, monkeypatch):
    master, _vs = mini_cluster
    a = _assign(master)
    payload = hashlib.sha256(b"sendfile").digest() * 3000  # ~96KB, not image
    code, _, _ = _http("POST", f"http://{a['url']}/{a['fid']}", payload)
    assert code == 201
    sf0 = SENDFILE_BYTES.labels().value
    code, got, hdrs = _http("GET", f"http://{a['url']}/{a['fid']}")
    assert code == 200 and got == payload
    assert _await(
        lambda: SENDFILE_BYTES.labels().value - sf0 >= len(payload))
    etag_sendfile = hdrs.get("Etag")
    assert etag_sendfile
    # A/B: the userspace path serves the same bytes and the same Etag
    monkeypatch.setenv("SEAWEEDFS_TPU_SENDFILE", "0")
    code, got2, hdrs2 = _http("GET", f"http://{a['url']}/{a['fid']}")
    assert code == 200 and got2 == payload
    assert hdrs2.get("Etag") == etag_sendfile


def test_http_range_falls_back_from_sendfile(mini_cluster, monkeypatch):
    monkeypatch.setenv("SEAWEEDFS_TPU_SENDFILE", "1")
    master, _vs = mini_cluster
    a = _assign(master)
    payload = bytes(range(256)) * 64
    code, _, _ = _http("POST", f"http://{a['url']}/{a['fid']}", payload)
    assert code == 201
    fb0 = SENDFILE_FALLBACK.labels("range").value
    code, got, hdrs = _http(
        "GET", f"http://{a['url']}/{a['fid']}",
        headers={"Range": "bytes=100-299"})
    assert code == 206 and got == payload[100:300]
    assert SENDFILE_FALLBACK.labels("range").value == fb0 + 1
    # the fallback read cached the needle, so the next whole-object GET
    # declines the extent by DESIGN: bytes already in RAM beat sendfile
    cache0 = SENDFILE_FALLBACK.labels("cache").value
    code, got, _ = _http("GET", f"http://{a['url']}/{a['fid']}")
    assert code == 200 and got == payload
    assert _await(
        lambda: SENDFILE_FALLBACK.labels("cache").value == cache0 + 1)


def test_http_sendfile_cookie_and_404_paths(mini_cluster):
    master, _vs = mini_cluster
    a = _assign(master)
    payload = b"guarded" * 100
    code, _, _ = _http("POST", f"http://{a['url']}/{a['fid']}", payload)
    assert code == 201
    vid, rest = a["fid"].split(",", 1)
    # wrong cookie: same volume/needle, mangled cookie digits
    bad = rest[:-4] + ("0000" if rest[-4:] != "0000" else "1111")
    code, _, _ = _http("GET", f"http://{a['url']}/{vid},{bad}")
    assert code == 404
    code, _, _ = _http("DELETE", f"http://{a['url']}/{a['fid']}")
    assert code == 202
    code, _, _ = _http("GET", f"http://{a['url']}/{a['fid']}")
    assert code == 404
