"""Unit tests for the unified fault-tolerance layer (util/failsafe.py):
backoff math, deadlines, failure classification, circuit breakers and
the retry/failover loops — all with fake clocks/rngs, no sockets.
"""

import random
import urllib.error

import grpc
import pytest

from seaweedfs_tpu.util import failsafe


@pytest.fixture(autouse=True)
def _clean_breakers():
    failsafe.reset_breakers()
    yield
    failsafe.reset_breakers()


def _retry_count(rtype, op, reason) -> float:
    return failsafe.RETRY_COUNTER.labels(rtype, op, reason).value


# -- backoff ----------------------------------------------------------------


def test_full_jitter_bounds():
    p = failsafe.RetryPolicy(base_delay=0.1, max_delay=2.0)
    rng = random.Random(7)
    for attempt in range(12):
        cap = min(2.0, 0.1 * 2 ** attempt)
        for _ in range(50):
            d = p.delay(attempt, rng)
            assert 0.0 <= d <= cap


def test_backoff_survives_very_long_outages():
    """Open-ended reconnect loops call next() forever; 2.0**attempt must
    not overflow a float after ~17 minutes-to-hours of retrying."""
    b = failsafe.Backoff(failsafe.RetryPolicy(base_delay=0.5, max_delay=15.0))
    b.attempt = 5000
    d = b.next()
    assert 0.0 <= d <= 15.0


def test_backoff_grows_and_resets():
    rng = random.Random(1)
    b = failsafe.Backoff(
        failsafe.RetryPolicy(base_delay=1.0, max_delay=64.0), rng=rng)
    # caps grow 1,2,4,...; a draw can be small, but the CAP must grow:
    for i in range(5):
        assert b.policy.delay(i, random.Random(0)) <= 1.0 * 2 ** i
        b.next()
    assert b.attempt == 5
    b.reset()
    assert b.attempt == 0


# -- deadlines --------------------------------------------------------------


def test_deadline_scope_clamps_attempt_timeout():
    assert failsafe.current_deadline() is None
    assert failsafe.attempt_timeout(30.0) == 30.0
    with failsafe.deadline_scope(0.5):
        t = failsafe.attempt_timeout(30.0)
        assert t is not None and t <= 0.5
        # no default: the remaining budget is the timeout
        assert failsafe.attempt_timeout(None) <= 0.5
    assert failsafe.current_deadline() is None


def test_nested_deadline_takes_tighter():
    with failsafe.deadline_scope(0.2) as outer:
        with failsafe.deadline_scope(60.0) as inner:
            assert inner is outer  # the outer, tighter budget wins


def test_expired_deadline_raises():
    clock = {"t": 0.0}
    dl = failsafe.Deadline(1.0, clock=lambda: clock["t"])
    assert dl.remaining() == 1.0
    clock["t"] = 2.0
    assert dl.expired
    tok = failsafe._deadline_var.set(dl)
    try:
        with pytest.raises(failsafe.DeadlineExceeded):
            failsafe.attempt_timeout(5.0)
    finally:
        failsafe._deadline_var.reset(tok)


# -- classification ---------------------------------------------------------


@pytest.mark.parametrize("exc,idem,reason,retryable", [
    (ConnectionRefusedError(), False, "refused", True),
    (ConnectionResetError(), False, "reset", False),
    (ConnectionResetError(), True, "reset", True),
    (TimeoutError(), False, "timeout", False),
    (TimeoutError(), True, "timeout", True),
    (urllib.error.HTTPError("u", 500, "boom", {}, None), False, "http_500", True),
    (urllib.error.HTTPError("u", 503, "boom", {}, None), False, "http_503", True),
    (urllib.error.HTTPError("u", 404, "nf", {}, None), True, "http_404", False),
    (urllib.error.URLError(ConnectionRefusedError()), False, "refused", True),
    (ValueError("nope"), True, "error", False),
])
def test_classify_table(exc, idem, reason, retryable):
    assert failsafe.classify(exc, idem) == (reason, retryable)


def test_classify_grpc_unavailable():
    class Err(grpc.RpcError):
        def code(self):
            return grpc.StatusCode.UNAVAILABLE

    assert failsafe.classify(Err(), False) == ("unavailable", True)


def test_classify_grpc_not_leader_rotates():
    class Err(grpc.RpcError):
        def code(self):
            return grpc.StatusCode.FAILED_PRECONDITION

    reason, retryable = failsafe.classify(Err(), True)
    assert retryable, "not-leader must rotate to the next master"


def test_is_connection_refused_unwraps_urlerror():
    assert failsafe.is_connection_refused(ConnectionRefusedError())
    assert failsafe.is_connection_refused(
        urllib.error.URLError(ConnectionRefusedError()))
    assert not failsafe.is_connection_refused(TimeoutError())
    assert not failsafe.is_connection_refused(
        urllib.error.HTTPError("u", 500, "b", {}, None))


# -- call(): single-peer retry loop -----------------------------------------


def test_call_retries_transient_then_succeeds():
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] < 3:
            raise ConnectionRefusedError()
        return "ok"

    before = _retry_count("t", "op1", "refused")
    got = failsafe.call(
        flaky, op="op1", retry_type="t",
        policy=failsafe.RetryPolicy(max_attempts=5, base_delay=0.0,
                                    max_delay=0.0))
    assert got == "ok" and calls["n"] == 3
    assert _retry_count("t", "op1", "refused") == before + 2


def test_call_nonretryable_raises_immediately():
    calls = {"n": 0}

    def fatal():
        calls["n"] += 1
        raise ValueError("bad request")

    with pytest.raises(ValueError):
        failsafe.call(fatal, op="op2", retry_type="t",
                      policy=failsafe.RetryPolicy(max_attempts=5,
                                                  base_delay=0.0))
    assert calls["n"] == 1


def test_call_exhausts_attempts():
    calls = {"n": 0}

    def always():
        calls["n"] += 1
        raise ConnectionRefusedError()

    with pytest.raises(ConnectionRefusedError):
        failsafe.call(always, op="op3", retry_type="t",
                      policy=failsafe.RetryPolicy(max_attempts=3,
                                                  base_delay=0.0,
                                                  max_delay=0.0))
    assert calls["n"] == 3


def test_call_nonidempotent_does_not_retry_timeout():
    calls = {"n": 0}

    def times_out():
        calls["n"] += 1
        raise TimeoutError()

    with pytest.raises(TimeoutError):
        failsafe.call(times_out, op="op4", retry_type="t", idempotent=False,
                      policy=failsafe.RetryPolicy(max_attempts=5,
                                                  base_delay=0.0))
    assert calls["n"] == 1


# -- circuit breaker --------------------------------------------------------


def test_breaker_full_state_machine():
    clock = {"t": 0.0}
    br = failsafe.CircuitBreaker("peer:1", failure_threshold=3,
                                 reset_timeout=10.0,
                                 clock=lambda: clock["t"])
    assert br.state == failsafe.CLOSED and br.allow()
    for _ in range(2):
        br.record_failure()
    assert br.state == failsafe.CLOSED, "below threshold stays closed"
    br.record_failure()
    assert br.state == failsafe.OPEN
    assert not br.allow()
    assert failsafe.CIRCUIT_STATE.labels("peer:1").value == 1.0

    # reset_timeout elapses -> half-open admits exactly one probe
    clock["t"] = 11.0
    assert br.allow()
    assert br.state == failsafe.HALF_OPEN
    assert failsafe.CIRCUIT_STATE.labels("peer:1").value == 2.0
    assert not br.allow(), "second concurrent probe must be rejected"

    # failed probe -> back to open for another full reset_timeout
    br.record_failure()
    assert br.state == failsafe.OPEN
    clock["t"] = 15.0
    assert not br.allow()
    clock["t"] = 22.0
    assert br.allow()

    # successful probe -> closed, gauge back to 0
    br.record_success()
    assert br.state == failsafe.CLOSED
    assert failsafe.CIRCUIT_STATE.labels("peer:1").value == 0.0
    assert br.allow()


def test_half_open_probe_released_on_spent_deadline():
    """A DeadlineExceeded after allow() admitted the half-open probe must
    free the probe slot — otherwise the breaker wedges open forever."""
    clock = {"t": 0.0}
    br = failsafe.CircuitBreaker("peer:dl", failure_threshold=1,
                                 reset_timeout=1.0, clock=lambda: clock["t"])
    br.record_failure()
    assert br.state == failsafe.OPEN
    clock["t"] = 2.0

    failsafe._breakers["peer:dl"] = br  # route call() to this instance
    try:
        def spent():
            raise failsafe.DeadlineExceeded("budget gone")

        with pytest.raises(failsafe.DeadlineExceeded):
            failsafe.call(spent, op="dl", retry_type="t", peer="peer:dl",
                          policy=failsafe.RetryPolicy(max_attempts=1))
        # probe slot freed and the peer not blamed: the next caller can
        # probe and a success closes the breaker
        assert br.allow()
        br.record_success()
        assert br.state == failsafe.CLOSED
    finally:
        failsafe._breakers.pop("peer:dl", None)


def test_breaker_success_resets_failure_run():
    br = failsafe.CircuitBreaker("peer:2", failure_threshold=3)
    br.record_failure()
    br.record_failure()
    br.record_success()
    br.record_failure()
    br.record_failure()
    assert br.state == failsafe.CLOSED, "non-consecutive failures don't trip"


def test_breaker_registry_reuses_instances():
    a = failsafe.breaker_for("x:1")
    assert failsafe.breaker_for("x:1") is a
    assert failsafe.breaker_for("x:2") is not a


# -- call_with_failover ------------------------------------------------------


def test_failover_rotates_to_next_peer():
    seen = []

    def fn(peer):
        seen.append(peer)
        if peer == "a":
            raise ConnectionRefusedError()
        return f"ok-{peer}"

    got = failsafe.call_with_failover(
        ["a", "b"], fn, op="fo1", retry_type="t",
        policy=failsafe.RetryPolicy(max_attempts=2, base_delay=0.0,
                                    max_delay=0.0))
    assert got == "ok-b" and seen == ["a", "b"]


def test_failover_nonretryable_still_rotates():
    """One replica answering an authoritative error (404, cookie
    mismatch) says nothing about the others: rotation must continue and
    the healthy copy must win."""
    seen = []

    def fn(peer):
        seen.append(peer)
        if peer == "a":
            raise ValueError("this copy says no")
        return f"ok-{peer}"

    got = failsafe.call_with_failover(
        ["a", "b"], fn, op="fo2", retry_type="t",
        policy=failsafe.RetryPolicy(max_attempts=1, base_delay=0.0))
    assert got == "ok-b" and seen == ["a", "b"]

    # when EVERY peer refuses authoritatively, the last error surfaces
    with pytest.raises(ValueError):
        failsafe.call_with_failover(
            ["a"], lambda p: (_ for _ in ()).throw(ValueError("no")),
            op="fo2", retry_type="t",
            policy=failsafe.RetryPolicy(max_attempts=1, base_delay=0.0))


def test_failover_spent_deadline_aborts_without_blaming_peers():
    clock = {"t": 0.0}
    dl = failsafe.Deadline(1.0, clock=lambda: clock["t"])
    clock["t"] = 2.0  # budget already gone
    tok = failsafe._deadline_var.set(dl)
    try:
        def fn(peer):
            failsafe.attempt_timeout(5.0)  # raises DeadlineExceeded
            return "never"

        with pytest.raises(failsafe.DeadlineExceeded):
            failsafe.call_with_failover(
                ["da", "db"], fn, op="fo-dl", retry_type="t",
                policy=failsafe.RetryPolicy(max_attempts=2, base_delay=0.0))
    finally:
        failsafe._deadline_var.reset(tok)
    # the peers were never actually contacted: breakers stay pristine
    assert failsafe.breaker_for("da").state == failsafe.CLOSED
    assert failsafe.breaker_for("da")._consecutive_failures == 0


def test_failover_refreshes_peers_between_rounds():
    rounds = []

    def peers(round_no):
        rounds.append(round_no)
        return ["a"] if round_no == 0 else ["b"]

    def fn(peer):
        if peer == "a":
            raise ConnectionRefusedError()
        return peer

    got = failsafe.call_with_failover(
        peers, fn, op="fo3", retry_type="t",
        policy=failsafe.RetryPolicy(max_attempts=3, base_delay=0.0,
                                    max_delay=0.0))
    assert got == "b" and rounds == [0, 1]


def test_failover_skips_open_breaker_but_probes_when_all_open():
    # trip both peers' breakers
    for peer in ("p1", "p2"):
        br = failsafe.breaker_for(peer)
        for _ in range(failsafe.BREAKER_FAILURE_THRESHOLD):
            br.record_failure()
        assert br.state == failsafe.OPEN

    calls = []

    def fn(peer):
        calls.append(peer)
        return "revived"

    # every breaker open: the loop must force-probe rather than wedge
    got = failsafe.call_with_failover(
        ["p1", "p2"], fn, op="fo4", retry_type="t",
        policy=failsafe.RetryPolicy(max_attempts=1, base_delay=0.0))
    assert got == "revived" and calls == ["p1"]
    assert failsafe.breaker_for("p1").state == failsafe.CLOSED


def test_failover_peer_key_aggregates_breaker_state():
    urls = ["http://h:1/fid-a", "http://h:1/fid-b"]

    def fn(url):
        raise ConnectionRefusedError()

    with pytest.raises(ConnectionRefusedError):
        failsafe.call_with_failover(
            urls, fn, op="fo5", retry_type="t",
            policy=failsafe.RetryPolicy(max_attempts=3, base_delay=0.0,
                                        max_delay=0.0),
            peer_key=lambda u: u.split("/")[2])
    br = failsafe.breaker_for("h:1")
    assert br.state == failsafe.OPEN, "6 failures on one host must trip it"


def test_failover_empty_peer_list():
    with pytest.raises(failsafe.CircuitOpenError):
        failsafe.call_with_failover(
            [], lambda p: p, op="fo6", retry_type="t",
            policy=failsafe.RetryPolicy(max_attempts=2, base_delay=0.0))
