"""WebDAV class-1 verb round-trips against a live instance.

Reference: weed/server/webdav_server.go:45 (x/net/webdav FS over filer).
"""

from __future__ import annotations

import time
import urllib.error
import urllib.request
import xml.etree.ElementTree as ET

import pytest

from helpers import free_port


def _dav(port, method, path, body=None, headers=None):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}", data=body, method=method,
        headers=headers or {})
    try:
        with urllib.request.urlopen(req, timeout=10) as r:
            return r.status, dict(r.headers), r.read()
    except urllib.error.HTTPError as e:
        return e.code, dict(e.headers), e.read()


@pytest.fixture(scope="module")
def dav(tmp_path_factory):
    from seaweedfs_tpu.filer.server import FilerServer
    from seaweedfs_tpu.master.server import MasterServer
    from seaweedfs_tpu.volume.server import VolumeServer
    from seaweedfs_tpu.webdav.server import WebDavServer

    master = MasterServer(ip="127.0.0.1", port=free_port(),
                          volume_size_limit_mb=64)
    master.start()
    vs = VolumeServer(
        directories=[str(tmp_path_factory.mktemp("davvol"))],
        master_addresses=[f"127.0.0.1:{master.grpc_port}"],
        ip="127.0.0.1", port=free_port(), pulse_seconds=0.5,
        max_volume_count=100,
    )
    vs.start()
    deadline = time.time() + 15
    while time.time() < deadline and len(master.topo.nodes) < 1:
        time.sleep(0.1)
    filer = FilerServer(
        masters=[f"127.0.0.1:{master.grpc_port}"],
        ip="127.0.0.1", port=free_port(), store="memory", max_mb=1,
    )
    filer.start()
    srv = WebDavServer(filer=f"127.0.0.1:{filer.port}", port=free_port())
    srv.start()
    yield srv
    srv.stop()
    filer.stop()
    vs.stop()
    master.stop()


def test_options_advertises_dav(dav):
    code, headers, _ = _dav(dav.port, "OPTIONS", "/")
    assert code == 200
    assert "1" in headers.get("DAV", "")
    assert "PROPFIND" in headers.get("Allow", "")


def test_mkcol_put_get_head(dav):
    code, _, _ = _dav(dav.port, "MKCOL", "/davdir")
    assert code == 201
    code, _, _ = _dav(dav.port, "PUT", "/davdir/file.txt",
                      b"dav payload")
    assert code in (200, 201, 204)
    code, headers, body = _dav(dav.port, "GET", "/davdir/file.txt")
    assert code == 200 and body == b"dav payload"
    code, headers, _ = _dav(dav.port, "HEAD", "/davdir/file.txt")
    assert code == 200 and int(headers["Content-Length"]) == 11


def test_propfind_lists_collection(dav):
    _dav(dav.port, "MKCOL", "/pfdir")
    _dav(dav.port, "PUT", "/pfdir/a.txt", b"aaa")
    _dav(dav.port, "PUT", "/pfdir/b.txt", b"bbbb")
    code, _, body = _dav(dav.port, "PROPFIND", "/pfdir",
                         headers={"Depth": "1"})
    assert code == 207, body
    root = ET.fromstring(body)
    hrefs = [h.text for h in root.iter("{DAV:}href")]
    assert any(h.endswith("/pfdir/a.txt") for h in hrefs)
    assert any(h.endswith("/pfdir/b.txt") for h in hrefs)
    # file sizes reported
    lengths = [int(e.text) for e in root.iter("{DAV:}getcontentlength")
               if e.text and e.text.isdigit()]
    assert 3 in lengths and 4 in lengths


def test_move_and_delete(dav):
    _dav(dav.port, "PUT", "/mvsrc.txt", b"move-me")
    code, _, _ = _dav(dav.port, "MOVE", "/mvsrc.txt",
                      headers={"Destination": f"http://127.0.0.1:{dav.port}/mvdst.txt"})
    assert code in (201, 204)
    code, _, body = _dav(dav.port, "GET", "/mvdst.txt")
    assert code == 200 and body == b"move-me"
    code, _, _ = _dav(dav.port, "GET", "/mvsrc.txt")
    assert code == 404
    code, _, _ = _dav(dav.port, "DELETE", "/mvdst.txt")
    assert code in (200, 204)
    code, _, _ = _dav(dav.port, "GET", "/mvdst.txt")
    assert code == 404


def test_copy(dav):
    _dav(dav.port, "PUT", "/cpsrc.txt", b"copy-me")
    code, _, _ = _dav(dav.port, "COPY", "/cpsrc.txt",
                      headers={"Destination": f"http://127.0.0.1:{dav.port}/cpdst.txt"})
    assert code in (201, 204)
    for p in ("/cpsrc.txt", "/cpdst.txt"):
        code, _, body = _dav(dav.port, "GET", p)
        assert code == 200 and body == b"copy-me"
