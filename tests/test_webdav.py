"""WebDAV class-1 verb round-trips against a live instance.

Reference: weed/server/webdav_server.go:45 (x/net/webdav FS over filer).
"""

from __future__ import annotations

import time
import urllib.error
import urllib.request
import xml.etree.ElementTree as ET

import pytest

from helpers import free_port


def _dav(port, method, path, body=None, headers=None):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}", data=body, method=method,
        headers=headers or {})
    try:
        with urllib.request.urlopen(req, timeout=10) as r:
            return r.status, dict(r.headers), r.read()
    except urllib.error.HTTPError as e:
        return e.code, dict(e.headers), e.read()


@pytest.fixture(scope="module")
def dav(tmp_path_factory):
    from seaweedfs_tpu.filer.server import FilerServer
    from seaweedfs_tpu.master.server import MasterServer
    from seaweedfs_tpu.volume.server import VolumeServer
    from seaweedfs_tpu.webdav.server import WebDavServer

    master = MasterServer(ip="127.0.0.1", port=free_port(),
                          volume_size_limit_mb=64)
    master.start()
    vs = VolumeServer(
        directories=[str(tmp_path_factory.mktemp("davvol"))],
        master_addresses=[f"127.0.0.1:{master.grpc_port}"],
        ip="127.0.0.1", port=free_port(), pulse_seconds=0.5,
        max_volume_count=100,
    )
    vs.start()
    deadline = time.time() + 15
    while time.time() < deadline and len(master.topo.nodes) < 1:
        time.sleep(0.1)
    filer = FilerServer(
        masters=[f"127.0.0.1:{master.grpc_port}"],
        ip="127.0.0.1", port=free_port(), store="memory", max_mb=1,
    )
    filer.start()
    srv = WebDavServer(filer=f"127.0.0.1:{filer.port}", port=free_port())
    srv.start()
    yield srv
    srv.stop()
    filer.stop()
    vs.stop()
    master.stop()


def test_options_advertises_dav(dav):
    code, headers, _ = _dav(dav.port, "OPTIONS", "/")
    assert code == 200
    assert "1" in headers.get("DAV", "")
    assert "PROPFIND" in headers.get("Allow", "")


def test_mkcol_put_get_head(dav):
    code, _, _ = _dav(dav.port, "MKCOL", "/davdir")
    assert code == 201
    code, _, _ = _dav(dav.port, "PUT", "/davdir/file.txt",
                      b"dav payload")
    assert code in (200, 201, 204)
    code, headers, body = _dav(dav.port, "GET", "/davdir/file.txt")
    assert code == 200 and body == b"dav payload"
    code, headers, _ = _dav(dav.port, "HEAD", "/davdir/file.txt")
    assert code == 200 and int(headers["Content-Length"]) == 11


def test_propfind_lists_collection(dav):
    _dav(dav.port, "MKCOL", "/pfdir")
    _dav(dav.port, "PUT", "/pfdir/a.txt", b"aaa")
    _dav(dav.port, "PUT", "/pfdir/b.txt", b"bbbb")
    code, _, body = _dav(dav.port, "PROPFIND", "/pfdir",
                         headers={"Depth": "1"})
    assert code == 207, body
    root = ET.fromstring(body)
    hrefs = [h.text for h in root.iter("{DAV:}href")]
    assert any(h.endswith("/pfdir/a.txt") for h in hrefs)
    assert any(h.endswith("/pfdir/b.txt") for h in hrefs)
    # file sizes reported
    lengths = [int(e.text) for e in root.iter("{DAV:}getcontentlength")
               if e.text and e.text.isdigit()]
    assert 3 in lengths and 4 in lengths


def test_move_and_delete(dav):
    _dav(dav.port, "PUT", "/mvsrc.txt", b"move-me")
    code, _, _ = _dav(dav.port, "MOVE", "/mvsrc.txt",
                      headers={"Destination": f"http://127.0.0.1:{dav.port}/mvdst.txt"})
    assert code in (201, 204)
    code, _, body = _dav(dav.port, "GET", "/mvdst.txt")
    assert code == 200 and body == b"move-me"
    code, _, _ = _dav(dav.port, "GET", "/mvsrc.txt")
    assert code == 404
    code, _, _ = _dav(dav.port, "DELETE", "/mvdst.txt")
    assert code in (200, 204)
    code, _, _ = _dav(dav.port, "GET", "/mvdst.txt")
    assert code == 404


def test_copy(dav):
    _dav(dav.port, "PUT", "/cpsrc.txt", b"copy-me")
    code, _, _ = _dav(dav.port, "COPY", "/cpsrc.txt",
                      headers={"Destination": f"http://127.0.0.1:{dav.port}/cpdst.txt"})
    assert code in (201, 204)
    for p in ("/cpsrc.txt", "/cpdst.txt"):
        code, _, body = _dav(dav.port, "GET", p)
        assert code == 200 and body == b"copy-me"


_LOCKINFO = (
    b'<?xml version="1.0" encoding="utf-8"?>'
    b'<D:lockinfo xmlns:D="DAV:">'
    b'<D:lockscope><D:exclusive/></D:lockscope>'
    b'<D:locktype><D:write/></D:locktype>'
    b'<D:owner>tester</D:owner>'
    b'</D:lockinfo>')


def test_dav_class2_lock_cycle(dav):
    """RFC 4918 class-2: LOCK/UNLOCK with token enforcement — the surface
    Windows/Office write clients require (the reference gets it from
    x/net/webdav's memLS)."""
    port = dav.port
    code, headers, _ = _dav(port, "OPTIONS", "/")
    assert "2" in headers.get("DAV", "")
    assert "LOCK" in headers.get("Allow", "")

    _dav(port, "PUT", "/locked.txt", b"v1")
    code, headers, body = _dav(port, "LOCK", "/locked.txt", _LOCKINFO,
                               {"Timeout": "Second-600"})
    assert code == 200
    token = headers.get("Lock-Token", "").strip("<>")
    assert token.startswith("opaquelocktoken:")
    assert b"lockdiscovery" in body and token.encode() in body

    # without the token, writes answer 423 Locked
    code, _, _ = _dav(port, "PUT", "/locked.txt", b"intruder")
    assert code == 423
    code, _, _ = _dav(port, "DELETE", "/locked.txt")
    assert code == 423
    # with the token in If, the owner writes through
    code, _, _ = _dav(port, "PUT", "/locked.txt", b"v2",
                      {"If": f"(<{token}>)"})
    assert code in (201, 204)
    assert _dav(port, "GET", "/locked.txt")[2] == b"v2"

    # refresh (bodyless LOCK with If), then unlock
    code, _, _ = _dav(port, "LOCK", "/locked.txt", None,
                      {"If": f"(<{token}>)", "Timeout": "Second-60"})
    assert code == 200
    code, _, _ = _dav(port, "UNLOCK", "/locked.txt", None,
                      {"Lock-Token": f"<{token}>"})
    assert code == 204
    code, _, _ = _dav(port, "PUT", "/locked.txt", b"v3")
    assert code in (201, 204)
    _dav(port, "DELETE", "/locked.txt")


def test_dav_lock_unmapped_and_depth(dav):
    """LOCK on an unmapped URL creates an empty resource (201); a
    depth-infinity lock on a collection covers its children."""
    port = dav.port
    code, headers, _ = _dav(port, "LOCK", "/ghost.bin", _LOCKINFO)
    assert code == 201
    token = headers.get("Lock-Token", "").strip("<>")
    assert _dav(port, "GET", "/ghost.bin")[0] == 200
    _dav(port, "UNLOCK", "/ghost.bin", None,
         {"Lock-Token": f"<{token}>"})

    _dav(port, "MKCOL", "/ldir")
    _dav(port, "PUT", "/ldir/kid.txt", b"k")
    code, headers, _ = _dav(port, "LOCK", "/ldir", _LOCKINFO,
                            {"Depth": "infinity"})
    assert code == 200
    token = headers.get("Lock-Token", "").strip("<>")
    code, _, _ = _dav(port, "PUT", "/ldir/kid.txt", b"blocked")
    assert code == 423
    code, _, _ = _dav(port, "PUT", "/ldir/kid.txt", b"ok",
                      {"If": f"(<{token}>)"})
    assert code in (201, 204)
    # second exclusive lock on a covered child is refused
    code, _, _ = _dav(port, "LOCK", "/ldir/kid.txt", _LOCKINFO)
    assert code == 423
    _dav(port, "UNLOCK", "/ldir", None, {"Lock-Token": f"<{token}>"})


def test_dav_proppatch_acknowledged(dav):
    port = dav.port
    _dav(port, "PUT", "/pp.txt", b"x")
    body = (b'<?xml version="1.0"?>'
            b'<D:propertyupdate xmlns:D="DAV:" xmlns:Z="urn:x">'
            b'<D:set><D:prop><Z:Win32LastModifiedTime>x'
            b'</Z:Win32LastModifiedTime></D:prop></D:set>'
            b'</D:propertyupdate>')
    code, _, out = _dav(port, "PROPPATCH", "/pp.txt", body)
    assert code == 207
    assert b"200 OK" in out
    _dav(port, "DELETE", "/pp.txt")


def test_dav_child_lock_blocks_directory_ops(dav):
    """A lock on a child blocks deleting/moving its ancestor directory,
    COPY respects destination locks, and MKCOL inside an exclusively
    locked collection is refused (RFC 4918 §6.1/7 overlap rules)."""
    port = dav.port
    _dav(port, "MKCOL", "/cl")
    _dav(port, "PUT", "/cl/held.txt", b"h")
    code, headers, _ = _dav(port, "LOCK", "/cl/held.txt", _LOCKINFO)
    assert code == 200
    token = headers.get("Lock-Token", "").strip("<>")
    # deleting the parent would destroy the locked child -> 423
    assert _dav(port, "DELETE", "/cl")[0] == 423
    assert _dav(port, "MOVE", "/cl", None,
                {"Destination": f"http://127.0.0.1:{port}/cl2"})[0] == 423
    # COPY onto the locked resource without the token -> 423
    _dav(port, "PUT", "/other.txt", b"o")
    assert _dav(port, "COPY", "/other.txt", None,
                {"Destination":
                 f"http://127.0.0.1:{port}/cl/held.txt"})[0] == 423
    # an exclusive subtree lock over a live child lock is refused
    assert _dav(port, "LOCK", "/cl", _LOCKINFO,
                {"Depth": "infinity"})[0] == 423
    _dav(port, "UNLOCK", "/cl/held.txt", None,
         {"Lock-Token": f"<{token}>"})
    # with the child lock gone, the subtree lock works and gates MKCOL
    code, headers, _ = _dav(port, "LOCK", "/cl", _LOCKINFO,
                            {"Depth": "infinity"})
    assert code == 200
    token = headers.get("Lock-Token", "").strip("<>")
    assert _dav(port, "MKCOL", "/cl/sub")[0] == 423
    assert _dav(port, "MKCOL", "/cl/sub", None,
                {"If": f"(<{token}>)"})[0] == 201
    _dav(port, "UNLOCK", "/cl", None, {"Lock-Token": f"<{token}>"})
    _dav(port, "DELETE", "/cl")
    _dav(port, "DELETE", "/other.txt")
