"""Cross-process tracing: one trace id spans filer -> volume hops.

Real subprocesses through the CLI (the tier-4 harness of
test_cli_processes.py): a client PUT to the filer with an explicit W3C
`traceparent` must surface the SAME trace id in the filer process's
/debug/traces AND the volume process's /debug/traces — proving the
context crossed the process boundary on the chunk upload — with >= 3
spans overall, and every server's /metrics must expose the request
latency histograms the middleware emits.
"""

import json
import os
import signal
import subprocess
import sys
import time
import urllib.error
import urllib.request

from helpers import free_port

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

CLIENT_TRACE_ID = "c0ffee" + "ab" * 13  # 32 hex chars
CLIENT_SPAN_ID = "11" * 8
TRACEPARENT = f"00-{CLIENT_TRACE_ID}-{CLIENT_SPAN_ID}-01"


def _env():
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    return env


def _spawn(args, cwd):
    return subprocess.Popen(
        [sys.executable, "-m", "seaweedfs_tpu", *args],
        cwd=cwd, env=_env(),
        stdout=subprocess.DEVNULL, stderr=subprocess.STDOUT,
    )


def _wait_http(url, deadline_s=25):
    deadline = time.time() + deadline_s
    while time.time() < deadline:
        try:
            with urllib.request.urlopen(url, timeout=2) as r:
                return r.status
        except (urllib.error.URLError, ConnectionError, OSError):
            time.sleep(0.3)
    raise TimeoutError(url)


def _get_json(url):
    with urllib.request.urlopen(url, timeout=5) as r:
        return json.loads(r.read())


def _trace_spans(port, trace_id):
    doc = _get_json(f"http://127.0.0.1:{port}/debug/traces")
    for t in doc["traces"]:
        if t["traceId"] == trace_id:
            return t["spans"]
    return []


def test_one_trace_spans_filer_and_volume_processes(tmp_path):
    mport, vport, fport = free_port(), free_port(), free_port()
    vol_dir = tmp_path / "v1"
    vol_dir.mkdir()
    procs = []
    try:
        procs.append(_spawn(["master", "-port", str(mport)], str(tmp_path)))
        _wait_http(f"http://127.0.0.1:{mport}/cluster/healthz")
        procs.append(_spawn(
            ["volume", "-dir", str(vol_dir), "-port", str(vport),
             "-mserver", f"127.0.0.1:{mport}", "-ec.codec", "cpu"],
            str(tmp_path)))
        procs.append(_spawn(
            ["filer", "-master", f"127.0.0.1:{mport}",
             "-port", str(fport), "-store", str(tmp_path / "filer.db")],
            str(tmp_path)))
        _wait_http(f"http://127.0.0.1:{fport}/")

        # wait for the volume server to register with the master
        deadline = time.time() + 20
        while time.time() < deadline:
            try:
                if _get_json(
                    f"http://127.0.0.1:{mport}/dir/assign"
                ).get("fid"):
                    break
            except (urllib.error.URLError, OSError):
                pass
            time.sleep(0.3)
        else:
            raise AssertionError("master never produced an assignment")

        # one client PUT carrying an explicit traceparent
        req = urllib.request.Request(
            f"http://127.0.0.1:{fport}/traced/file.bin",
            data=os.urandom(4096), method="PUT",
            headers={"traceparent": TRACEPARENT},
        )
        with urllib.request.urlopen(req, timeout=15) as r:
            assert r.status == 201

        # the edge span records just AFTER the 201 is written — poll
        # briefly so a fast client can't outrun the ring append
        deadline = time.time() + 5
        while time.time() < deadline:
            filer_spans = _trace_spans(fport, CLIENT_TRACE_ID)
            volume_spans = _trace_spans(vport, CLIENT_TRACE_ID)
            if {"filer.post", "volumeServer.post"} <= {
                    s["name"] for s in filer_spans + volume_spans}:
                break
            time.sleep(0.2)

        assert filer_spans, "filer did not adopt the client trace id"
        assert volume_spans, (
            "volume server did not join the trace: the traceparent was "
            "not propagated on the chunk upload hop")
        names = {s["name"] for s in filer_spans + volume_spans}
        assert "filer.post" in names
        assert "volumeServer.post" in names
        assert len(filer_spans) + len(volume_spans) >= 3, names
        # the filer's edge span hangs off the client's span id
        edge = [s for s in filer_spans if s["name"] == "filer.post"]
        assert edge and edge[0]["parentId"] == CLIENT_SPAN_ID
        # spans are linked: every volume span's trace matches, and the
        # chunk-upload hop's parent exists in the filer process
        filer_ids = {s["spanId"] for s in filer_spans}
        assert any(s["parentId"] in filer_ids for s in volume_spans)

        # /metrics on every server exposes the middleware histograms
        for port, needle in (
            (fport, 'seaweedfs_request_seconds_count{type="filer",op="post"}'),
            (vport, 'seaweedfs_request_seconds_count{type="volumeServer",op="post"}'),
            (mport, 'seaweedfs_request_seconds_count{type="master",op="assign"}'),
        ):
            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics", timeout=5
            ) as r:
                text = r.read().decode()
            assert needle in text, f"port {port} missing {needle}"
    finally:
        for p in procs:
            p.send_signal(signal.SIGTERM)
        for p in procs:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()


def test_ec_codec_metrics_after_encode_reconstruct_cycle():
    """One encode/reconstruct cycle must surface
    seaweedfs_ec_op_seconds{op,impl} (+ byte histograms) in /metrics."""
    import numpy as np

    from seaweedfs_tpu.ops.codec import get_codec
    from seaweedfs_tpu.stats.metrics import REGISTRY

    codec = get_codec("cpu")
    shards = [
        np.random.randint(0, 256, 512, dtype=np.uint8) if i < 10
        else np.zeros(512, np.uint8)
        for i in range(14)
    ]
    codec.encode(shards)
    broken = list(shards)
    broken[2] = broken[11] = None
    rec = codec.reconstruct(broken)
    assert np.array_equal(rec[2], shards[2])
    text = REGISTRY.render()
    for op in ("encode", "reconstruct"):
        assert (f'seaweedfs_ec_op_seconds_count{{op="{op}",impl="cpu"}}'
                in text)
        assert f'seaweedfs_ec_op_bytes_count{{op="{op}",impl="cpu"}}' in text
