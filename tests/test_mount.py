"""Mount layer tests: dirty-page intervals, meta cache, chunk cache, the
WFS filesystem core against a live mini-cluster, and (when the host allows
it) a REAL kernel FUSE mount exercised with plain os/file calls.

Reference analogues: weed/filesys/dirty_page_interval_test.go, the mount
compose tier (docker/compose/local-mount-compose.yml), meta_cache/.
"""

import importlib.util
import os
import socket
import time

import pytest

from seaweedfs_tpu.mount.dirty_pages import ContinuousIntervals
from seaweedfs_tpu.mount.meta_cache import MetaCache
from seaweedfs_tpu.pb import filer_pb2
from seaweedfs_tpu.util.chunk_cache import TieredChunkCache


def _free_port() -> int:
    from helpers import free_port

    return free_port()


# -- dirty-page intervals (dirty_page_interval_test.go analogues) -----------


def test_intervals_sequential_writes_merge():
    ci = ContinuousIntervals()
    ci.add(0, b"aaaa")
    ci.add(4, b"bbbb")
    ci.add(8, b"cccc")
    assert len(ci.intervals) == 1
    assert bytes(ci.intervals[0].data) == b"aaaabbbbcccc"


def test_intervals_overwrite_newest_wins():
    ci = ContinuousIntervals()
    ci.add(0, b"aaaaaaaaaa")
    ci.add(3, b"BBB")
    assert len(ci.intervals) == 1
    assert bytes(ci.intervals[0].data) == b"aaaBBBaaaa"
    # overlapping tail + gap + separate interval, then a bridging write
    ci2 = ContinuousIntervals()
    ci2.add(0, b"11111")
    ci2.add(10, b"22222")
    assert len(ci2.intervals) == 2
    ci2.add(3, b"xxxxxxxxx")  # 3..12 bridges both
    assert len(ci2.intervals) == 1
    assert bytes(ci2.intervals[0].data) == b"111xxxxxxxxx222"


def test_intervals_read_overlay_and_pop():
    ci = ContinuousIntervals()
    ci.add(5, b"ZZZZ")
    base = bytearray(b"." * 10)
    ci.read(3, 10, base)
    assert bytes(base) == b"..ZZZZ...."
    assert ci.total_bytes() == 4
    assert ci.max_stop() == 9
    iv = ci.pop_largest()
    assert iv.offset == 5 and not ci.intervals


# -- meta cache -------------------------------------------------------------


def _entry(name, is_dir=False):
    e = filer_pb2.Entry(name=name, is_directory=is_dir)
    return e


def test_meta_cache_listing_completeness():
    mc = MetaCache()
    mc.mark_dir_listed("/d", [_entry("a"), _entry("b")])
    assert mc.is_dir_listed("/d")
    assert {e.name for e in mc.children("/d")} == {"a", "b"}
    assert mc.get("/d/a") is not None
    mc.delete("/d/a")
    assert mc.get("/d/a") is None
    # deleting a dir drops its subtree
    mc.put("/x", _entry("x", is_dir=True))
    mc.put("/x/y", _entry("y"))
    mc.delete("/x")
    assert mc.get("/x/y") is None


def test_meta_cache_lru_bound():
    mc = MetaCache(limit_entries=4)
    for i in range(8):
        mc.put(f"/f{i}", _entry(f"f{i}"))
    assert mc.get("/f0") is None and mc.get("/f7") is not None


# -- tiered chunk cache -----------------------------------------------------


def test_chunk_cache_tiers(tmp_path):
    c = TieredChunkCache(
        mem_limit_bytes=1024, mem_max_entry=256,
        disk_dir=str(tmp_path / "cc"), disk_limit_bytes=4096,
    )
    c.set("1,a", b"x" * 100)       # memory
    c.set("2,b", b"y" * 1000)      # too big for mem entry -> disk
    assert c.get("1,a") == b"x" * 100
    assert c.get("2,b") == b"y" * 1000
    assert c.mem.get("2,b") is None  # stayed on disk (1000 > mem max entry)
    # mem eviction under byte pressure
    for i in range(20):
        c.set(f"m,{i}", bytes([i]) * 200)
    assert c.get("m,19") is not None
    # disk eviction under byte pressure
    for i in range(10):
        c.disk.set(f"d,{i}", bytes([i]) * 1000)
    assert c.disk.get("d,9") is not None
    assert c.disk.get("d,0") is None


# -- WFS over a live cluster ------------------------------------------------


@pytest.fixture(scope="module")
def mount_cluster(tmp_path_factory):
    from seaweedfs_tpu.filer.server import FilerServer
    from seaweedfs_tpu.master.server import MasterServer
    from seaweedfs_tpu.volume.server import VolumeServer

    master = MasterServer(ip="127.0.0.1", port=_free_port(),
                          volume_size_limit_mb=64)
    master.start()
    vs = VolumeServer(
        directories=[str(tmp_path_factory.mktemp("mvol"))],
        master_addresses=[f"127.0.0.1:{master.grpc_port}"],
        ip="127.0.0.1", port=_free_port(), pulse_seconds=0.5,
    )
    vs.start()
    deadline = time.time() + 15
    while time.time() < deadline and len(master.topo.nodes) < 1:
        time.sleep(0.1)
    filer = FilerServer(
        masters=[f"127.0.0.1:{master.grpc_port}"],
        ip="127.0.0.1", port=_free_port(),
        store="sqlite",
        store_path=str(tmp_path_factory.mktemp("mountdb") / "filer.db"),
        max_mb=1,
    )
    filer.start()
    yield master, vs, filer
    filer.stop()
    vs.stop()
    master.stop()


@pytest.fixture()
def wfs(mount_cluster, tmp_path):
    from seaweedfs_tpu.mount.wfs import WFS

    _, _, filer = mount_cluster
    w = WFS(
        filer_grpc=f"127.0.0.1:{filer.grpc_port}",
        filer_http=f"127.0.0.1:{filer.port}",
        chunk_size_mb=1,
        cache_dir=str(tmp_path / "cache"),
    )
    yield w
    w.close()


def test_wfs_file_roundtrip_chunked(wfs):
    wfs.mkdir("/data")
    h = wfs.open("/data/big.bin", create=True)
    payload = bytes(range(256)) * 10240  # 2.5MB -> 3 chunks at 1MB
    h.write(0, payload)
    h.flush()
    wfs.release(h)
    entry = wfs.lookup_entry("/data/big.bin")
    assert len(entry.chunks) >= 3
    h2 = wfs.open("/data/big.bin")
    assert h2.read(0, len(payload)) == payload
    assert h2.read(1 << 20, 4096) == payload[1 << 20 : (1 << 20) + 4096]
    wfs.release(h2)
    assert wfs.getattr("/data/big.bin")["st_size"] == len(payload)


def test_wfs_overwrite_and_dirty_read(wfs):
    h = wfs.open("/data/notes.txt", create=True)
    h.write(0, b"hello world")
    # un-flushed dirty bytes must be visible to reads through the handle
    assert h.read(0, 11) == b"hello world"
    h.flush()
    h.write(6, b"WORLD")
    assert h.read(0, 11) == b"hello WORLD"
    h.flush()
    wfs.release(h)
    h2 = wfs.open("/data/notes.txt")
    assert h2.read(0, 100) == b"hello WORLD"
    wfs.release(h2)


def test_wfs_namespace_ops(wfs):
    wfs.mkdir("/ns")
    wfs.mkdir("/ns/sub")
    h = wfs.open("/ns/f1", create=True)
    h.write(0, b"abc")
    wfs.release(h)
    names = {e.name for e in wfs.list_dir("/ns")}
    assert names == {"sub", "f1"}
    wfs.rename("/ns/f1", "/ns/sub/f2")
    assert wfs.lookup_entry("/ns/f1") is None
    h = wfs.open("/ns/sub/f2")
    assert h.read(0, 3) == b"abc"
    wfs.release(h)
    wfs.unlink("/ns/sub/f2")
    with pytest.raises(OSError):
        wfs.getattr("/ns/sub/f2")
    wfs.rmdir("/ns/sub")
    assert wfs.lookup_entry("/ns/sub") is None
    wfs.rmdir("/ns")
    assert wfs.lookup_entry("/ns") is None


def test_wfs_truncate_and_setattr(wfs):
    h = wfs.open("/trunc.bin", create=True)
    h.write(0, b"x" * 1000)
    h.flush()
    wfs.release(h)
    wfs.set_attr("/trunc.bin", size=0)
    assert wfs.getattr("/trunc.bin")["st_size"] == 0
    wfs.set_attr("/trunc.bin", mode=0o600, uid=12, gid=34)
    a = wfs.getattr("/trunc.bin")
    assert a["st_mode"] & 0o7777 == 0o600
    assert (a["st_uid"], a["st_gid"]) == (12, 34)


def test_wfs_xattr_and_symlink(wfs):
    h = wfs.open("/xf", create=True)
    wfs.release(h)
    wfs.setxattr("/xf", "user.color", b"blue")
    assert wfs.getxattr("/xf", "user.color") == b"blue"
    assert wfs.listxattr("/xf") == ["user.color"]
    wfs.removexattr("/xf", "user.color")
    with pytest.raises(OSError):
        wfs.getxattr("/xf", "user.color")
    wfs.symlink("/xf", "/xlink")
    assert wfs.readlink("/xlink") == "/xf"


def test_wfs_spill_large_write(wfs):
    """Writes beyond one chunk window spill early; flush commits the rest."""
    h = wfs.open("/spill.bin", create=True)
    blob = os.urandom(3 << 20)  # 3MB with chunk_size 1MB
    for off in range(0, len(blob), 64 << 10):
        h.write(off, blob[off : off + (64 << 10)])
    assert h._pending_chunks, "expected early spill before flush"
    h.flush()
    wfs.release(h)
    h2 = wfs.open("/spill.bin")
    assert h2.read(0, len(blob)) == blob
    wfs.release(h2)


def test_wfs_sees_external_writes(mount_cluster, wfs):
    """A file written through the filer HTTP API is visible via WFS."""
    import urllib.request

    _, _, filer = mount_cluster
    req = urllib.request.Request(
        f"http://127.0.0.1:{filer.port}/ext/via-http.txt",
        data=b"written by http", method="PUT",
    )
    with urllib.request.urlopen(req, timeout=10):
        pass
    h = wfs.open("/ext/via-http.txt")
    assert h.read(0, 100) == b"written by http"
    wfs.release(h)


# -- real kernel FUSE mount -------------------------------------------------


def _fuse_usable() -> bool:
    from seaweedfs_tpu.mount.fuse import available

    return available() and os.geteuid() == 0


@pytest.mark.skipif(not _fuse_usable(), reason="no FUSE on this host")
def test_kernel_fuse_mount(mount_cluster, tmp_path):
    """cp/cat/rm through a real kernel mountpoint (the reference's
    local-mount-compose tier, but in-process)."""
    from seaweedfs_tpu.mount.fuse import FuseMount
    from seaweedfs_tpu.mount.wfs import WFS

    _, _, filer = mount_cluster
    w = WFS(
        filer_grpc=f"127.0.0.1:{filer.grpc_port}",
        filer_http=f"127.0.0.1:{filer.port}",
        chunk_size_mb=1,
    )
    mp = str(tmp_path / "mnt")
    m = FuseMount(w, mp)
    m.start()
    try:
        os.makedirs(f"{mp}/docs")
        payload = os.urandom(2 << 20) + b"tail"
        with open(f"{mp}/docs/blob.bin", "wb") as f:
            f.write(payload)
        with open(f"{mp}/docs/blob.bin", "rb") as f:
            assert f.read() == payload
        assert os.stat(f"{mp}/docs/blob.bin").st_size == len(payload)
        assert sorted(os.listdir(f"{mp}/docs")) == ["blob.bin"]
        os.rename(f"{mp}/docs/blob.bin", f"{mp}/docs/blob2.bin")
        with open(f"{mp}/docs/blob2.bin", "rb") as f:
            assert f.read(16) == payload[:16]
        # hard links through the KERNEL: os.link -> FUSE link op ->
        # filer hardlink KV (dir_link.go parity)
        os.link(f"{mp}/docs/blob2.bin", f"{mp}/docs/blob3.bin")
        st = os.stat(f"{mp}/docs/blob2.bin")
        assert st.st_nlink == 2
        with open(f"{mp}/docs/blob3.bin", "rb") as f:
            assert f.read(16) == payload[:16]
        os.remove(f"{mp}/docs/blob2.bin")
        with open(f"{mp}/docs/blob3.bin", "rb") as f:  # survives unlink
            assert f.read(16) == payload[:16]
        assert os.stat(f"{mp}/docs/blob3.bin").st_nlink == 1
        os.remove(f"{mp}/docs/blob3.bin")
        assert os.listdir(f"{mp}/docs") == []
        os.rmdir(f"{mp}/docs")
        # the durable state lives in the filer, not the mount
        assert filer.filer.find_entry("/docs") is None
    finally:
        m.stop()


@pytest.mark.skipif(
    importlib.util.find_spec("cryptography") is None,
    reason="chunk encryption needs the cryptography package")
def test_wfs_cipher_write_and_read(mount_cluster, tmp_path):
    """Against a -encryptVolumeData filer, mount WRITES seal chunks with
    per-chunk keys and mount READS decrypt them; volume bytes stay
    opaque (cipher parity across the FUSE plane)."""
    from seaweedfs_tpu.filer.server import FilerServer
    from seaweedfs_tpu.mount.wfs import WFS

    master, vs, _ = mount_cluster
    filer = FilerServer(
        masters=[f"127.0.0.1:{master.grpc_port}"],
        ip="127.0.0.1", port=_free_port(), store="memory", max_mb=1,
        cipher=True,
    )
    filer.start()
    w = WFS(
        filer_grpc=f"127.0.0.1:{filer.grpc_port}",
        filer_http=f"127.0.0.1:{filer.port}",
        chunk_size_mb=1,
        cache_dir=str(tmp_path / "ccache"),
    )
    try:
        secret = b"MOUNT-SECRET-" * 300
        h = w.open("/vault.bin", create=True)
        h.write(0, secret)
        h.flush()
        w.release(h)
        entry = w.lookup_entry("/vault.bin")
        assert entry.chunks and entry.chunks[0].cipher_key
        h2 = w.open("/vault.bin")
        got = h2.read(0, len(secret))
        w.release(h2)
        assert got == secret
        # chunks on disk are ciphertext
        import glob as _glob
        import os as _os

        raw = b""
        for loc in vs.store.locations:
            for p in _glob.glob(_os.path.join(loc.directory, "*.dat")):
                raw += open(p, "rb").read()
        assert b"MOUNT-SECRET-" not in raw
    finally:
        w.close()
        filer.stop()


def test_wfs_hardlink_roundtrip(wfs):
    """Hard links through the WFS surface (dir_link.go semantics): both
    names read the shared bytes, st_nlink reflects the counter, unlinking
    one name keeps the data alive, unlinking the last reclaims it."""
    wfs.mkdir("/hl")
    h = wfs.open("/hl/a.txt", create=True)
    h.write(0, b"shared-bytes" * 100000)  # >1MB so real chunks exist
    h.flush()
    wfs.release(h)

    wfs.link("/hl/a.txt", "/hl/b.txt")
    ea = wfs.lookup_entry("/hl/a.txt")
    eb = wfs.lookup_entry("/hl/b.txt")
    assert ea.hard_link_id and ea.hard_link_id == eb.hard_link_id
    assert wfs.getattr("/hl/a.txt")["st_nlink"] == 2
    assert wfs.getattr("/hl/b.txt")["st_nlink"] == 2
    h = wfs.open("/hl/b.txt")
    assert h.read(0, 12) == b"shared-bytes"
    wfs.release(h)

    # link to a third name, drop the original: data stays readable
    wfs.link("/hl/b.txt", "/hl/c.txt")
    wfs.unlink("/hl/a.txt")
    assert wfs.getattr("/hl/b.txt")["st_nlink"] == 2
    assert wfs.getattr("/hl/c.txt")["st_nlink"] == 2
    h = wfs.open("/hl/c.txt")
    assert h.read(0, 12) == b"shared-bytes"
    wfs.release(h)

    wfs.unlink("/hl/b.txt")
    wfs.unlink("/hl/c.txt")
    assert wfs.lookup_entry("/hl/c.txt") is None
