"""EC repair data plane: the pipelined rebuild (byte identity across
loss patterns, remote-source hook, clean-error contract), the shared
decode-plan cache, and the degraded-read single-flight + interval LRU.

Companion to test_ec_pipeline.py (encode conformance) — this file covers
the REPAIR half of the north star (BASELINE configs 3 and 5).
"""

import os
import threading
import time
from concurrent.futures import ThreadPoolExecutor

import numpy as np
import pytest

from seaweedfs_tpu.ops import gf256
from seaweedfs_tpu.ops.codec import get_codec
from seaweedfs_tpu.stats.metrics import (
    EC_DECODE_PLAN,
    EC_SINGLEFLIGHT,
)
from seaweedfs_tpu.storage.ec import constants as ecc
from seaweedfs_tpu.storage.ec.encoder import (
    generate_ec_files,
    rebuild_ec_files,
    write_sorted_file_from_idx,
)
from seaweedfs_tpu.storage.ec.volume import EcVolume
from seaweedfs_tpu.storage.super_block import VERSION3

from helpers import make_volume

LARGE = 10000  # scaled-down block sizes, as in test_ec_pipeline.py
SMALL = 100


@pytest.fixture()
def encoded_base(tmp_path):
    vol = make_volume(str(tmp_path), n_needles=60, seed=21, max_size=3000)
    base = vol.file_name()
    vol.close()
    generate_ec_files(base, large_block_size=LARGE, small_block_size=SMALL,
                      codec_name="cpu", slice_size=1 << 20)
    write_sorted_file_from_idx(base)
    return base


def _shard_bytes(base):
    return {i: open(base + ecc.to_ext(i), "rb").read()
            for i in range(ecc.TOTAL_SHARDS)}


# -- rebuild byte identity across loss patterns ---------------------------

# 1-4 lost shards: data-only, parity-only, and mixed patterns
LOSS_PATTERNS = [
    (0,),
    (13,),
    (0, 13),
    (0, 1, 2, 3),          # worst case: 4 data shards
    (10, 11, 12, 13),      # all parity
    (2, 7, 11, 13),        # mixed
]


@pytest.mark.parametrize("lost", LOSS_PATTERNS)
def test_rebuild_byte_identity_cpu(encoded_base, lost):
    originals = _shard_bytes(encoded_base)
    for sid in lost:
        os.remove(encoded_base + ecc.to_ext(sid))
    rebuilt = rebuild_ec_files(encoded_base, codec_name="cpu",
                               slice_size=1000)
    assert sorted(rebuilt) == sorted(lost)
    for sid in lost:
        got = open(encoded_base + ecc.to_ext(sid), "rb").read()
        assert got == originals[sid], f"shard {sid} not byte-identical"


@pytest.mark.parametrize("lost", [(0, 1, 2, 3), (3, 9, 12, 13)])
def test_rebuild_byte_identity_device_codec(encoded_base, lost):
    """The async-dispatch device path (apply_rows_device, one slice in
    flight) must produce the same bytes as the host codec."""
    originals = _shard_bytes(encoded_base)
    for sid in lost:
        os.remove(encoded_base + ecc.to_ext(sid))
    rebuilt = rebuild_ec_files(encoded_base, codec_name="tpu",
                               slice_size=4096)
    assert sorted(rebuilt) == sorted(lost)
    for sid in lost:
        got = open(encoded_base + ecc.to_ext(sid), "rb").read()
        assert got == originals[sid], f"shard {sid} differs on device codec"


def test_rebuild_progress_monotonic(encoded_base):
    for sid in (0, 11):
        os.remove(encoded_base + ecc.to_ext(sid))
    seen = []
    rebuild_ec_files(encoded_base, codec_name="cpu", slice_size=1000,
                     progress=seen.append)
    assert seen == sorted(seen) and seen, "progress must be monotonic"
    assert seen[-1] == os.path.getsize(encoded_base + ecc.to_ext(0))


# -- remote-source hook ---------------------------------------------------

def test_rebuild_remote_source_hook(encoded_base):
    """A node with fewer than DATA_SHARDS local shards streams the
    missing source intervals from peers instead of failing — and only
    rebuilds the GLOBALLY missing shards (peer-held ones need a copy
    rpc, not a decode)."""
    originals = _shard_bytes(encoded_base)
    gone = [0, 1, 2, 3, 4, 5]  # 8 local left — not enough to decode
    peer_holds = {4, 5}        # the rest are lost cluster-wide
    for sid in gone:
        os.remove(encoded_base + ecc.to_ext(sid))

    # without the hook: clean refusal, nothing rebuilt
    with pytest.raises(ValueError):
        rebuild_ec_files(encoded_base, codec_name="cpu", slice_size=1000)
    for sid in gone:
        assert not os.path.exists(encoded_base + ecc.to_ext(sid))

    calls = []

    def fetch(sid, off, length):
        if sid not in peer_holds:
            return None
        calls.append(sid)
        return originals[sid][off:off + length]

    rebuilt = rebuild_ec_files(encoded_base, codec_name="cpu",
                               slice_size=1000, remote_fetch=fetch)
    assert sorted(rebuilt) == [0, 1, 2, 3]
    assert calls, "remote sources must have been streamed"
    for sid in (0, 1, 2, 3):
        got = open(encoded_base + ecc.to_ext(sid), "rb").read()
        assert got == originals[sid], f"shard {sid} differs via remote hook"
    for sid in peer_holds:  # healthy on a peer: not regenerated locally
        assert not os.path.exists(encoded_base + ecc.to_ext(sid))


def test_rebuild_remote_source_dies_cleanly(encoded_base):
    """A peer dying mid-rebuild surfaces a clean IOError and leaves NO
    partial .ecNN outputs for a later mount to trust; a retry against a
    healthy peer then succeeds byte-identically."""
    originals = _shard_bytes(encoded_base)
    gone = [0, 1, 2, 3, 4]  # 9 local left; the peer holds only shard 4
    for sid in gone:
        os.remove(encoded_base + ecc.to_ext(sid))
    fail_after = {"n": 4}  # probe + a few slices, then the peer dies

    def dying_fetch(sid, off, length):
        if sid != 4:
            return None
        if fail_after["n"] <= 0:
            return None  # the peer went away mid-stream
        fail_after["n"] -= 1
        return originals[sid][off:off + length]

    with pytest.raises(IOError):
        rebuild_ec_files(encoded_base, codec_name="cpu", slice_size=1000,
                         remote_fetch=dying_fetch)
    for sid in gone:
        assert not os.path.exists(encoded_base + ecc.to_ext(sid)), \
            f"partial shard {sid} must be removed on error"

    def good_fetch(sid, off, length):
        return originals[sid][off:off + length] if sid == 4 else None

    rebuilt = rebuild_ec_files(encoded_base, codec_name="cpu",
                               slice_size=1000, remote_fetch=good_fetch)
    assert sorted(rebuilt) == [0, 1, 2, 3]
    for sid in (0, 1, 2, 3):
        assert open(encoded_base + ecc.to_ext(sid), "rb").read() \
            == originals[sid]


def test_rebuild_writer_error_does_not_deadlock(encoded_base):
    """A writer-stage failure (here: the progress callback raising, the
    same path a full disk takes) must surface promptly — the prefetch
    thread's buffer-pool wait is stop-aware, so the error path cannot
    strand the join — and must remove partial outputs."""
    for sid in (0, 1):
        os.remove(encoded_base + ecc.to_ext(sid))

    def bad_progress(done):
        raise RuntimeError("writer boom")

    result = {}

    def run():
        try:
            rebuild_ec_files(encoded_base, codec_name="cpu", slice_size=500,
                             progress=bad_progress)
            result["r"] = "no error"
        except Exception as e:  # noqa: BLE001
            result["r"] = e

    t = threading.Thread(target=run, daemon=True)
    t.start()
    t.join(20)
    assert not t.is_alive(), "rebuild deadlocked on writer error"
    assert isinstance(result["r"], RuntimeError)
    for sid in (0, 1):
        assert not os.path.exists(encoded_base + ecc.to_ext(sid))


# -- decode-plan cache ----------------------------------------------------

def test_decode_plan_matches_direct_computation():
    m = gf256.rs_matrix(10, 14)
    present = [2, 3, 4, 5, 6, 7, 8, 9, 10, 12]
    wanted = (0, 1, 11, 13)
    plan = gf256.decode_plan_for(m, 10, present, wanted)
    dec = gf256.mat_inv(m[np.asarray(present[:10], dtype=np.int64)])
    for i, w in enumerate(wanted):
        if w < 10:
            assert np.array_equal(plan[i], dec[w])
        else:
            assert np.array_equal(
                plan[i], gf256.mat_mul(m[w:w + 1, :10], dec)[0])


def test_decode_plan_cache_hits():
    m = gf256.rs_matrix(10, 14)
    present = [0, 1, 2, 3, 4, 5, 6, 7, 8, 13]  # a set other tests don't use
    wanted = (9, 10)
    hit = EC_DECODE_PLAN.labels("hit")
    first = gf256.decode_plan_for(m, 10, present, wanted)
    before = hit.value
    again = gf256.decode_plan_for(m, 10, present, wanted)
    assert again is first, "second lookup must come from the cache"
    assert hit.value == before + 1


def test_decode_plan_cached_vs_uncached_decode(encoded_base):
    """Needle bytes decoded through the cached plan equal a from-scratch
    numpy decode with no cache involved."""
    ev = EcVolume(encoded_base, volume_id=1, version=VERSION3,
                  large_block_size=LARGE, small_block_size=SMALL)
    want = ev.read_needle(7)
    for sid in (0, 1, 2, 3):
        ev.delete_shard(sid)
    got = ev.read_needle(7)  # degraded: through decode_plan_for
    assert got.data == want.data
    ev.close()

    # from-scratch check of one reconstructed interval, bypassing every
    # cache: invert with a fresh Gauss-Jordan per call
    shard_size = os.path.getsize(encoded_base + ecc.to_ext(4))
    m = gf256.rs_matrix(10, 14)
    present = list(range(4, 14))
    dec = gf256.mat_inv(m[np.asarray(present, dtype=np.int64)])
    srcs = [np.frombuffer(
        open(encoded_base + ecc.to_ext(i), "rb").read(), dtype=np.uint8)
        for i in present]
    t = gf256.mul_table()
    acc = np.zeros(shard_size, dtype=np.uint8)
    for j, c in enumerate(dec[0]):
        if c:
            acc ^= srcs[j] if c == 1 else t[c][srcs[j]]
    cached = get_codec("cpu").reconstruct_one(
        [None, None, None, None] + srcs, 0)
    assert np.array_equal(np.asarray(cached), acc)


# -- degraded-read single-flight + interval cache -------------------------

def _degraded_volume(base):
    """EcVolume with the first 4 data shards gone."""
    for sid in range(4):
        os.remove(base + ecc.to_ext(sid))
    return EcVolume(base, volume_id=1, version=VERSION3,
                    large_block_size=LARGE, small_block_size=SMALL)


def _count_gathers(ev, delay=0.0):
    """Wrap _gather_and_decode with an invocation counter."""
    counter = {"n": 0}
    inner = ev._gather_and_decode

    def counting(shard_id, offset, length):
        counter["n"] += 1
        if delay:
            time.sleep(delay)
        return inner(shard_id, offset, length)

    ev._gather_and_decode = counting
    return counter


def test_single_flight_coalesces_concurrent_readers(encoded_base):
    ev = _degraded_volume(encoded_base)
    counter = _count_gathers(ev, delay=0.05)
    coalesced = EC_SINGLEFLIGHT.labels("coalesced")
    before = coalesced.value
    length = 256

    results = []
    with ThreadPoolExecutor(max_workers=16) as pool:
        futs = [pool.submit(ev._reconstruct_interval, 0, 0, length)
                for _ in range(16)]
        results = [f.result() for f in futs]
    ev.close()
    assert all(r == results[0] for r in results)
    assert len(results[0]) == length
    # 16 concurrent readers of the same lost interval: one gather+decode
    # (a tiny window exists where a follower arrives after the leader
    # popped the key — allow 2, never 16)
    assert counter["n"] <= 2, f"{counter['n']} gathers for one interval"
    assert coalesced.value >= before + 14


def test_interval_cache_serves_repeat_reads(encoded_base):
    ev = _degraded_volume(encoded_base)
    counter = _count_gathers(ev)
    first = ev._reconstruct_interval(1, 0, 512)
    again = ev._reconstruct_interval(1, 0, 512)
    assert first == again
    assert counter["n"] == 1, "second read must come from the interval LRU"
    ev.close()


def test_interval_cache_invalidated_on_unmount_and_delete(encoded_base):
    # lose only 3 shards so 11 stay mounted: the test can unmount one
    # more and the interval is still decodable from the remaining 10
    for sid in range(3):
        os.remove(encoded_base + ecc.to_ext(sid))
    ev = EcVolume(encoded_base, volume_id=1, version=VERSION3,
                  large_block_size=LARGE, small_block_size=SMALL)
    counter = _count_gathers(ev)
    ev._reconstruct_interval(2, 0, 512)
    assert counter["n"] == 1

    # shard unmount: the layout changed wholesale — re-gather
    ev.delete_shard(13)
    ev._reconstruct_interval(2, 0, 512)
    assert counter["n"] == 2
    ev.add_shard(13)
    ev._reconstruct_interval(2, 0, 512)
    assert counter["n"] == 3

    # needle delete bumps delete_seq: cached intervals become unservable
    nid = 9
    ev.delete_needle(nid)
    ev._reconstruct_interval(2, 0, 512)
    assert counter["n"] == 4
    ev.close()


def test_interval_cache_compare_before_publish(encoded_base):
    """A delete racing the gather must prevent the stale publish: the
    token captured before the reads no longer matches at put time."""
    ev = _degraded_volume(encoded_base)
    inner = ev._gather_and_decode

    def racing(shard_id, offset, length):
        data, token = inner(shard_id, offset, length)
        ev.delete_needle(11)  # bump delete_seq after the capture
        return data, token

    ev._gather_and_decode = racing
    ev._reconstruct_interval(3, 0, 256)
    assert len(ev._interval_cache) == 0, \
        "stale interval must not be published"
    ev.close()


def test_degraded_reads_spawn_no_new_threads(encoded_base):
    """The per-call ThreadPoolExecutor is gone: after warmup, a storm of
    degraded reads (incl. remote fetches through the shared bounded
    executor) must not grow the process thread count."""
    originals = {i: open(encoded_base + ecc.to_ext(i), "rb").read()
                 for i in range(ecc.TOTAL_SHARDS)}
    for sid in range(6):  # force the remote fan-out path (8 local < 10)
        os.remove(encoded_base + ecc.to_ext(sid))
    ev = EcVolume(encoded_base, volume_id=1, version=VERSION3,
                  large_block_size=LARGE, small_block_size=SMALL)
    ev.remote_fetch = lambda sid, off, ln: originals[sid][off:off + ln]

    for i in range(4):  # warm the shared pool + caches
        ev._gather_and_decode(0, i * 7, 64)
    baseline = threading.active_count()
    for i in range(40):
        ev._gather_and_decode(0, i * 11, 64)  # distinct intervals: no LRU
    assert threading.active_count() <= baseline, \
        "degraded reads must not spawn threads per call"
    ev.close()
