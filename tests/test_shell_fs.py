"""fs.* / collection.* / s3.* admin-shell commands against a live stack.

Reference semantics: weed/shell/command_fs_ls.go, command_fs_du.go,
command_fs_tree.go, command_fs_mv.go, command_fs_meta_save.go /
command_fs_meta_load.go (the 4-byte-size + FullEntry .meta stream),
command_collection_list.go, command_s3_bucket_create.go,
command_s3_clean_uploads.go.
"""

from __future__ import annotations

import socket
import time

import pytest

from seaweedfs_tpu.shell.commands import CommandEnv, run_command


def _free_port() -> int:
    from helpers import free_port

    return free_port()


def _poll(fn, ok, timeout=10.0):
    deadline = time.time() + timeout
    out = fn()
    while not ok(out) and time.time() < deadline:
        time.sleep(0.2)
        out = fn()
    return out


@pytest.fixture(scope="module")
def stack(tmp_path_factory):
    from seaweedfs_tpu.filer.server import FilerServer
    from seaweedfs_tpu.master.server import MasterServer
    from seaweedfs_tpu.volume.server import VolumeServer

    master = MasterServer(ip="127.0.0.1", port=_free_port(),
                          volume_size_limit_mb=64)
    master.start()
    vs = VolumeServer(
        directories=[str(tmp_path_factory.mktemp("shellvol"))],
        master_addresses=[f"127.0.0.1:{master.grpc_port}"],
        ip="127.0.0.1", port=_free_port(), pulse_seconds=0.5,
        max_volume_count=100,
    )
    vs.start()
    deadline = time.time() + 15
    while time.time() < deadline and len(master.topo.nodes) < 1:
        time.sleep(0.1)
    filer = FilerServer(
        masters=[f"127.0.0.1:{master.grpc_port}"],
        ip="127.0.0.1", port=_free_port(),
        store="memory",
        max_mb=1,
    )
    filer.start()
    env = CommandEnv(f"127.0.0.1:{master.grpc_port}")
    env.option["filer"] = f"127.0.0.1:{filer.port}"
    yield env, filer
    filer.stop()
    vs.stop()
    master.stop()


@pytest.fixture(scope="module")
def populated(stack):
    """Seed a small namespace through the filer HTTP path."""
    env, filer = stack
    from seaweedfs_tpu.s3api.filer_client import FilerClient

    client = FilerClient(f"127.0.0.1:{filer.port}")
    client.put_object("/data/a.txt", b"alpha\n", mime="text/plain")
    client.put_object("/data/b.txt", b"bravo-bravo\n", mime="text/plain")
    client.put_object("/data/sub/c.bin", b"\x00" * 1024)
    client.put_object("/data/.hidden", b"shh")
    return env, client


def test_fs_ls(populated):
    env, _ = populated
    names = run_command(env, "fs.ls /data").splitlines()
    assert names == ["a.txt", "b.txt", "sub"]
    all_names = run_command(env, "fs.ls -a /data").splitlines()
    assert ".hidden" in all_names
    long = run_command(env, "fs.ls -l /data")
    assert "a.txt" in long and long.strip().endswith("total 3")
    # prefix listing
    assert run_command(env, "fs.ls /data/a").splitlines() == ["a.txt"]


def test_fs_cd_pwd(populated):
    env, _ = populated
    assert run_command(env, "fs.pwd") == "/"
    run_command(env, "fs.cd /data")
    assert run_command(env, "fs.pwd") == "/data"
    # relative listing from cwd
    assert "a.txt" in run_command(env, "fs.ls").splitlines()
    run_command(env, "fs.cd sub")
    assert run_command(env, "fs.pwd") == "/data/sub"
    run_command(env, "fs.cd /")
    with pytest.raises(ValueError):
        run_command(env, "fs.cd /data/a.txt")


def test_fs_cat(populated):
    env, _ = populated
    assert run_command(env, "fs.cat /data/a.txt") == "alpha\n"
    with pytest.raises(ValueError):
        run_command(env, "fs.cat /data")


def test_fs_du(populated):
    env, _ = populated
    out = run_command(env, "fs.du /data")
    # per-file rows plus the directory total on the last line
    assert out.splitlines()[-1].endswith("/data")
    total = int(out.splitlines()[-1].split("byte:")[1].split("\t")[0])
    assert total >= 6 + 12 + 1024


def test_fs_tree(populated):
    env, _ = populated
    out = run_command(env, "fs.tree /data")
    assert "├── a.txt" in out or "└── a.txt" in out
    assert "c.bin" in out
    assert out.splitlines()[-1].startswith("1 directories, 4 files")


def test_fs_mv(populated):
    env, client = populated
    client.put_object("/data/mv-me.txt", b"move")
    run_command(env, "fs.mv /data/mv-me.txt /data/sub")
    assert client.find_entry("/data", "mv-me.txt") is None
    assert client.find_entry("/data/sub", "mv-me.txt") is not None
    run_command(env, "fs.mv /data/sub/mv-me.txt /data/renamed.txt")
    assert client.find_entry("/data", "renamed.txt") is not None
    run_command(env, "fs.rm /data/renamed.txt")
    assert client.find_entry("/data", "renamed.txt") is None


def test_fs_meta_cat(populated):
    env, _ = populated
    out = run_command(env, "fs.meta.cat /data/a.txt")
    assert "a.txt" in out and "chunks" in out


def test_fs_meta_save_load(populated, tmp_path):
    env, client = populated
    meta = tmp_path / "snap.meta"
    out = run_command(env, f"fs.meta.save -o {meta} /data")
    assert "saved to" in out
    # wipe the subtree, then restore the namespace (metadata only)
    client.delete_entry("/data", "sub", is_delete_data=False,
                        is_recursive=True)
    assert client.find_entry("/data", "sub") is None
    out = run_command(env, f"fs.meta.load {meta}")
    assert "is loaded." in out
    assert client.find_entry("/data", "sub") is not None
    assert client.find_entry("/data/sub", "c.bin") is not None


def test_collection_and_buckets(populated):
    env, client = populated
    run_command(env, "s3.bucket.create -name shelltest")
    assert "shelltest" in run_command(env, "s3.bucket.list").splitlines()
    # objects in the bucket land in collection "shelltest"; the master
    # learns collections from volume-server heartbeats, so poll a pulse
    client.put_object("/buckets/shelltest/obj", b"payload" * 100)
    cols = _poll(lambda: run_command(env, "collection.list"),
                 lambda out: 'collection:"shelltest"' in out)
    assert 'collection:"shelltest"' in cols
    run_command(env, "s3.bucket.delete -name shelltest")
    assert "shelltest" not in run_command(env, "s3.bucket.list").splitlines()
    cols = _poll(lambda: run_command(env, "collection.list"),
                 lambda out: 'collection:"shelltest"' not in out)
    assert 'collection:"shelltest"' not in cols


def test_s3_clean_uploads(populated):
    env, client = populated
    run_command(env, "s3.bucket.create -name upbucket")
    client.mkdir("/buckets/upbucket", ".uploads")
    client.put_object("/buckets/upbucket/.uploads/stale1/part1", b"x")
    client.put_object("/buckets/upbucket/.uploads/stale2/part1", b"y")
    # nothing older than 24h yet
    assert run_command(env, "s3.clean.uploads") == ""
    out = run_command(env, "s3.clean.uploads -timeAgo 0s")
    assert "purge" in out
    assert client.find_entry("/buckets/upbucket/.uploads", "stale1") is None
    run_command(env, "s3.bucket.delete -name upbucket")


def test_s3_configure(populated):
    """s3.configure manages the shared identity json (command_s3_configure.go);
    the IAM API and gateway read the same file."""
    env, client = populated
    out = run_command(
        env, "s3.configure -user carol -access_key AKCAROL "
             "-secret_key SKCAROL -actions Read,Write -apply")
    assert "applied." in out
    import json

    code, _, body = client.get_object("/etc/iam/identity.json")
    assert code == 200
    conf = json.loads(body)
    carol = next(i for i in conf["identities"] if i["name"] == "carol")
    assert carol["credentials"][0]["accessKey"] == "AKCAROL"
    assert set(carol["actions"]) == {"Read", "Write"}
    # bucket-scoped grants
    run_command(env, "s3.configure -user carol -actions List "
                     "-buckets photos -apply")
    code, _, body = client.get_object("/etc/iam/identity.json")
    conf = json.loads(body)
    carol = next(i for i in conf["identities"] if i["name"] == "carol")
    assert "List:photos" in carol["actions"]
    # delete a key, then the whole user
    run_command(env, "s3.configure -user carol -access_key AKCAROL "
                     "-delete -apply")
    conf = json.loads(client.get_object("/etc/iam/identity.json")[2])
    carol = next(i for i in conf["identities"] if i["name"] == "carol")
    assert carol["credentials"] == []
    run_command(env, "s3.configure -user carol -delete -apply")
    conf = json.loads(client.get_object("/etc/iam/identity.json")[2])
    assert all(i["name"] != "carol" for i in conf["identities"])


def test_lock_unlock(stack):
    """lock/unlock take and release the exclusive admin lease
    (command_fs_lock_unlock.go); a second holder is refused."""
    env, _ = stack
    from seaweedfs_tpu.shell.commands import CommandEnv

    assert run_command(env, "lock") == "locked"
    other = CommandEnv(env.master_grpc)
    assert run_command(other, "lock") == "lock busy"
    assert run_command(env, "unlock") == "unlocked"
    assert run_command(other, "lock") == "locked"
    run_command(other, "unlock")


def test_fs_configure_path_rules(populated):
    """fs.configure rules steer writes: files under the prefix land in
    the rule's collection (filer_conf.go + command_fs_configure.go)."""
    env, client = populated
    out = run_command(
        env, "fs.configure -locationPrefix /ruled/ -collection ruledcoll "
             "-apply")
    assert "applied." in out and "ruledcoll" in out
    # keep writing until the conf holder refreshes (~2s) and the
    # master's heartbeat reports the grown collection
    def write_and_list():
        client.put_object("/ruled/file.bin", b"steered" * 100)
        return run_command(env, "collection.list")

    cols = _poll(write_and_list,
                 lambda o: 'collection:"ruledcoll"' in o, timeout=20)
    assert 'collection:"ruledcoll"' in cols
    # un-ruled paths stay in the default collection
    run_command(env, "fs.configure -locationPrefix /ruled/ -delete -apply")
    out = run_command(env, "fs.configure")
    assert "ruledcoll" not in out


def test_fs_configure_validation(populated):
    env, client = populated
    with pytest.raises(Exception):
        run_command(env, "fs.configure -locationPrefix /x/ -ttl banana")
    with pytest.raises(Exception):
        run_command(env, "fs.configure -locationPrefix /x/ -ttl 300s")
    with pytest.raises(Exception):
        run_command(env, "fs.configure -locationPrefix /buckets/b/ "
                         "-collection other")
    with pytest.raises(Exception):
        run_command(env, "fs.configure -locationPrefix /x/ "
                         "-replication 9z9")
    # the conf file itself is exempt from path rules: a broad TTL rule
    # must not place /etc/seaweedfs/filer.conf on an expiring volume
    run_command(env, "fs.configure -locationPrefix / -ttl 1h -apply")
    out = run_command(env, "fs.configure -locationPrefix / -delete -apply")
    assert '"locationPrefix": "/"' not in out


def test_fs_meta_notify(populated, tmp_path):
    """fs.meta.notify backfills a notification queue from the namespace
    (command_fs_meta_notify.go); here into the file backend."""
    env, _ = populated
    out_path = tmp_path / "events.jsonl"
    out = run_command(
        env, f"fs.meta.notify -backend file -path {out_path} /data")
    assert "notified" in out and "files" in out
    from seaweedfs_tpu.notification.publishers import FilePublisher

    events = FilePublisher.read_events(str(out_path))
    keys = {k for k, _ in events}
    assert any(k.endswith("/a.txt") for k in keys)
    assert any(k.endswith("/c.bin") for k in keys)


def test_default_maintenance_script_matches_scaffold():
    """Pin the default [master.maintenance] suite to the reference scaffold
    block (command/scaffold.go:503-518): same commands, same order, and
    every line resolvable in the shell registry — so a command rename can't
    silently hollow out the leader's elastic-recovery loop."""
    import shlex

    from seaweedfs_tpu.shell.commands import (
        COMMANDS,
        DEFAULT_MAINTENANCE_SCRIPT,
    )
    from seaweedfs_tpu.util.scaffold import MASTER_TOML

    assert [shlex.split(line)[0] for line in DEFAULT_MAINTENANCE_SCRIPT] == [
        "ec.encode",
        "ec.rebuild",
        "ec.balance",
        "volume.balance",
        "volume.fix.replication",
    ]
    for line in DEFAULT_MAINTENANCE_SCRIPT:
        assert shlex.split(line)[0] in COMMANDS, line
    # the scaffold master.toml must ship the same suite it documents
    for line in DEFAULT_MAINTENANCE_SCRIPT:
        assert f'"{line}"' in MASTER_TOML, line
