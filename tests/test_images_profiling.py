"""Image resize-on-read + profiling hooks.

Reference: weed/images/resizing.go + orientation.go;
weed/command/volume.go:117-120 (-cpuprofile/-memprofile) and the pprof
handlers.
"""

from __future__ import annotations

import io
import json
import time
import urllib.request

import pytest

from helpers import free_port
from seaweedfs_tpu.images import fix_orientation, is_image, resized
from seaweedfs_tpu.util.grace import profile_status, setup_profiling


def _png(w: int, h: int) -> bytes:
    from PIL import Image

    img = Image.new("RGB", (w, h), (200, 30, 30))
    out = io.BytesIO()
    img.save(out, format="PNG")
    return out.getvalue()


def _dims(data: bytes) -> tuple[int, int]:
    from PIL import Image

    return Image.open(io.BytesIO(data)).size


def test_resize_modes():
    src = _png(400, 200)
    out, w, h = resized(src, ".png", width=100, height=100, mode="fit")
    assert (w, h) == _dims(out) and w <= 100 and h <= 100
    out, w, h = resized(src, ".png", width=100, height=100, mode="fill")
    assert _dims(out) == (100, 100)
    # default square thumbnail on non-square input
    out, w, h = resized(src, ".png", width=50, height=50)
    assert _dims(out) == (50, 50)
    # width-only preserves aspect
    out, w, h = resized(src, ".png", width=200)
    assert _dims(out) == (200, 100)
    # no upscale: smaller than requested passes through
    out, w, h = resized(src, ".png", width=4000)
    assert out == src
    # non-image data passes through untouched
    blob = b"not an image"
    assert resized(blob, ".png", width=10)[0] == blob
    assert is_image(".jpg") and is_image("", "image/png")
    assert not is_image(".txt", "text/plain")
    assert fix_orientation(blob) == blob
    # orientation-free JPEGs must pass through BYTE-IDENTICAL (no silent
    # recompression on every read)
    import io as _io

    from PIL import Image as _Image

    j = _io.BytesIO()
    _Image.new("RGB", (20, 20), (1, 2, 3)).save(j, format="JPEG")
    assert fix_orientation(j.getvalue()) == j.getvalue()


def test_volume_server_resizes_on_read(tmp_path_factory):
    from seaweedfs_tpu.master.server import MasterServer
    from seaweedfs_tpu.volume.server import VolumeServer

    master = MasterServer(ip="127.0.0.1", port=free_port(),
                          volume_size_limit_mb=64)
    master.start()
    vs = VolumeServer(
        directories=[str(tmp_path_factory.mktemp("imgvol"))],
        master_addresses=[f"127.0.0.1:{master.grpc_port}"],
        ip="127.0.0.1", port=free_port(), pulse_seconds=0.5,
    )
    vs.start()
    try:
        deadline = time.time() + 15
        while time.time() < deadline and len(master.topo.nodes) < 1:
            time.sleep(0.1)
        with urllib.request.urlopen(
                f"http://127.0.0.1:{master.port}/dir/assign",
                timeout=10) as r:
            a = json.loads(r.read())
        png = _png(300, 300)
        boundary = "imgb"
        body = (f"--{boundary}\r\nContent-Disposition: form-data; "
                f'name="file"; filename="pic.png"\r\n'
                f"Content-Type: image/png\r\n\r\n").encode() + png + \
            f"\r\n--{boundary}--\r\n".encode()
        req = urllib.request.Request(
            f"http://{a['url']}/{a['fid']}", data=body, method="POST",
            headers={"Content-Type":
                     f"multipart/form-data; boundary={boundary}"})
        urllib.request.urlopen(req, timeout=10).read()
        with urllib.request.urlopen(
                f"http://{a['url']}/{a['fid']}?width=64&height=64",
                timeout=10) as r:
            small = r.read()
        assert _dims(small) == (64, 64)
        with urllib.request.urlopen(
                f"http://{a['url']}/{a['fid']}", timeout=10) as r:
            assert r.read() == png  # no params: original bytes
        # /debug/profile works on both servers: ?status=1 keeps the cheap
        # JSON status, the default now runs the stack sampler (ISSUE 5)
        for port in (master.port, vs.port):
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/debug/profile?status=1",
                    timeout=10) as r:
                st = json.loads(r.read())
            assert st["threads"] >= 1 and st["max_rss_kb"] > 0
        with urllib.request.urlopen(
                f"http://127.0.0.1:{vs.port}/debug/profile"
                "?seconds=0.2&hz=50", timeout=10) as r:
            collapsed = r.read().decode()
        assert collapsed.strip(), "sampler returned no stacks"
    finally:
        vs.stop()
        master.stop()


def test_profiling_dumps(tmp_path):
    cpu = tmp_path / "cpu.pprof"
    setup_profiling(cpuprofile=str(cpu))
    st = profile_status()
    assert st["cpu_profiler_armed"] is True
    # the atexit dump is process-global; emulate it here
    import atexit  # noqa: F401 — documented path
    from seaweedfs_tpu.util import grace

    grace._cpu_profiler.disable()
    grace._cpu_profiler.dump_stats(str(cpu))
    import pstats

    stats = pstats.Stats(str(cpu))
    assert stats.total_calls >= 0
    grace._cpu_profiler = None


def test_server_ui_pages(tmp_path_factory):
    """Per-server /ui status pages (server/*_ui analogue)."""
    from seaweedfs_tpu.master.server import MasterServer
    from seaweedfs_tpu.volume.server import VolumeServer

    master = MasterServer(ip="127.0.0.1", port=free_port(),
                          volume_size_limit_mb=64)
    master.start()
    vs = VolumeServer(
        directories=[str(tmp_path_factory.mktemp("uivol"))],
        master_addresses=[f"127.0.0.1:{master.grpc_port}"],
        ip="127.0.0.1", port=free_port(), pulse_seconds=0.5,
    )
    vs.start()
    try:
        deadline = time.time() + 15
        while time.time() < deadline and len(master.topo.nodes) < 1:
            time.sleep(0.1)
        for port, marker in ((master.port, b"master"), (vs.port, b"volume")):
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/ui", timeout=10) as r:
                page = r.read()
            assert r.headers["Content-Type"].startswith("text/html")
            assert marker in page and b"<table>" in page
    finally:
        vs.stop()
        master.stop()
