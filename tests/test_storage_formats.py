"""On-disk format tests: needles, idx entries, superblock, ttl, replica
placement — including parsing the reference's checked-in binary fixture
(1.dat/1.idx) to pin byte compatibility with files written by the original
implementation."""

import os

import numpy as np
import pytest

from seaweedfs_tpu.storage import (
    TTL,
    Needle,
    NeedleMap,
    ReplicaPlacement,
    SuperBlock,
    types as t,
)
from seaweedfs_tpu.storage.needle import (
    FLAG_HAS_LAST_MODIFIED,
    FLAG_HAS_MIME,
    FLAG_HAS_NAME,
    FLAG_HAS_PAIRS,
    FLAG_HAS_TTL,
    actual_size,
    padding_length,
)
from seaweedfs_tpu.storage.super_block import VERSION1, VERSION2, VERSION3
from seaweedfs_tpu.storage.volume import Volume

from helpers import make_volume

REF_EC_DIR = "/root/reference/weed/storage/erasure_coding"


def test_index_entry_roundtrip():
    b = t.pack_index_entry(0xDEADBEEF12345678, 8 * 12345, 6789)
    assert len(b) == 16
    key, off, size = t.unpack_index_entry(b)
    assert (key, off, size) == (0xDEADBEEF12345678, 8 * 12345, 6789)
    # tombstone size round-trips as -1
    b = t.pack_index_entry(1, 0, t.TOMBSTONE_FILE_SIZE)
    assert t.unpack_index_entry(b)[2] == -1


def test_offset_alignment_required():
    with pytest.raises(ValueError):
        t.offset_to_bytes(13)


def test_padding_always_1_to_8():
    for version in (VERSION1, VERSION2, VERSION3):
        for size in range(0, 64):
            p = padding_length(size, version)
            assert 1 <= p <= 8
            assert actual_size(size, version) % 8 == 0


@pytest.mark.parametrize("version", [VERSION1, VERSION2, VERSION3])
def test_needle_roundtrip(version):
    n = Needle(cookie=0x12345678, id=0xABCDEF, data=b"hello world needle")
    blob = n.to_bytes(version)
    assert len(blob) % 8 == 0
    m = Needle.from_bytes(blob, version)
    assert m.id == n.id and m.cookie == n.cookie and m.data == n.data


def test_needle_full_fields_v3():
    n = Needle(cookie=7, id=99, data=b"x" * 100)
    n.set(FLAG_HAS_NAME)
    n.name = b"a.txt"
    n.set(FLAG_HAS_MIME)
    n.mime = b"text/plain"
    n.set(FLAG_HAS_LAST_MODIFIED)
    n.last_modified = 1234567890
    n.set(FLAG_HAS_TTL)
    n.ttl = TTL.parse("3d")
    n.set(FLAG_HAS_PAIRS)
    n.pairs = b'{"k":"v"}'
    n.append_at_ns = 42
    blob = n.to_bytes(VERSION3)
    m = Needle.from_bytes(blob, VERSION3)
    assert m.name == b"a.txt"
    assert m.mime == b"text/plain"
    assert m.last_modified == 1234567890
    assert m.ttl == TTL.parse("3d")
    assert m.pairs == b'{"k":"v"}'
    assert m.append_at_ns == 42


def test_needle_crc_detects_corruption():
    n = Needle(cookie=1, id=2, data=b"payload")
    blob = bytearray(n.to_bytes(VERSION3))
    blob[t.NEEDLE_HEADER_SIZE + 5] ^= 0xFF  # flip a data byte
    with pytest.raises(ValueError, match="CRC"):
        Needle.from_bytes(bytes(blob), VERSION3)


def test_ttl():
    for s in ("3m", "4h", "5d", "6w", "7M", "8y"):
        assert str(TTL.parse(s)) == s
    assert TTL.parse("") == TTL()
    assert TTL.parse("90") == TTL.parse("90m")
    assert TTL.from_uint32(TTL.parse("4h").to_uint32()) == TTL.parse("4h")
    assert TTL.parse("2h").minutes() == 120
    assert TTL.from_bytes(TTL.parse("1d").to_bytes()) == TTL.parse("1d")


def test_replica_placement():
    rp = ReplicaPlacement.parse("012")
    assert (rp.diff_dc, rp.diff_rack, rp.same_rack) == (0, 1, 2)
    assert rp.copy_count() == 4
    assert ReplicaPlacement.from_byte(rp.to_byte()) == rp
    assert str(rp) == "012"
    with pytest.raises(ValueError):
        ReplicaPlacement.parse("091")


def test_super_block_roundtrip():
    sb = SuperBlock(
        version=VERSION3,
        replica_placement=ReplicaPlacement.parse("001"),
        ttl=TTL.parse("3w"),
        compaction_revision=7,
    )
    b = sb.to_bytes()
    assert len(b) == 8
    sb2 = SuperBlock.from_bytes(b)
    assert sb2 == sb


def test_volume_write_read_delete(tmp_path):
    vol = make_volume(str(tmp_path), n_needles=30)
    n = vol.read_needle(7)
    assert n.id == 7
    freed = vol.delete_needle(7)
    assert freed > 0
    with pytest.raises(KeyError):
        vol.read_needle(7)
    vol.close()
    # reload from disk: deletes persist, live needles still readable
    vol2 = Volume(str(tmp_path), "", 1)
    with pytest.raises(KeyError):
        vol2.read_needle(7)
    assert vol2.read_needle(8).id == 8
    vol2.close()


def test_volume_torn_tail_truncated(tmp_path):
    vol = make_volume(str(tmp_path), n_needles=5)
    base = vol.file_name()
    last = vol.needle_map.get(5)
    vol.close()
    size = os.path.getsize(base + ".dat")
    # tear ONLY trailing padding: every real byte of needle 5 is intact
    # and CRC-clean, so the load-time healer re-pads instead of dropping
    # an acked write (padding is 1..8 bytes, so -1 is always pad-only)
    with open(base + ".dat", "r+b") as f:
        f.truncate(size - 1)
    vol2 = Volume(str(tmp_path), "", 1)
    assert vol2.read_needle(5).id == 5  # healed, not dropped
    assert os.path.getsize(base + ".dat") == size  # re-padded to aligned
    vol2.close()
    # tear into the record's REAL bytes: the torn needle is dropped and
    # the .dat truncated back to the previous record
    with open(base + ".dat", "r+b") as f:
        f.truncate(last.offset + 10)
    vol3 = Volume(str(tmp_path), "", 1)
    with pytest.raises(KeyError):
        vol3.read_needle(5)  # torn needle dropped
    assert vol3.read_needle(4).id == 4
    assert os.path.getsize(base + ".dat") == last.offset
    vol3.close()


@pytest.mark.skipif(not os.path.isdir(REF_EC_DIR), reason="reference fixture absent")
def test_parse_reference_fixture():
    """Parse the reference's real 1.dat/1.idx: our reader must accept files
    written by the original implementation (including CRC verification)."""
    nm = NeedleMap.load_from_idx(os.path.join(REF_EC_DIR, "1.idx"))
    assert len(nm) > 0
    with open(os.path.join(REF_EC_DIR, "1.dat"), "rb") as f:
        sb = SuperBlock.from_bytes(f.read(64))
        assert sb.version in (VERSION2, VERSION3)
        checked = 0
        for v in nm.items_ascending():
            if v.size <= 0:
                continue
            f.seek(v.offset)
            blob = f.read(actual_size(v.size, sb.version))
            n = Needle.from_bytes(blob, sb.version)  # verifies CRC
            assert n.id == v.key
            assert n.size == v.size
            checked += 1
        assert checked > 10


def test_five_byte_offsets_lift_32gb_cap(tmp_path):
    """offset_5bytes.go analogue: 17-byte index entries round-trip
    offsets beyond the 4-byte 32GB limit."""
    from seaweedfs_tpu.storage import types as t
    from seaweedfs_tpu.storage.idx import IndexWriter, parse_index_arrays
    from seaweedfs_tpu.storage.needle_map import NeedleMap

    t.set_offset_size(5)
    try:
        assert t.NEEDLE_MAP_ENTRY_SIZE == 17
        big = 40 * (1 << 30)  # 40GB: beyond the 4-byte cap
        b = t.offset_to_bytes(big)
        assert len(b) == 5 and t.bytes_to_offset(b) == big
        # entry pack/unpack round-trip
        entry = t.pack_index_entry(7, big, 1234)
        assert len(entry) == 17
        assert t.unpack_index_entry(entry) == (7, big, 1234)
        # .idx writer + vectorized parser agree
        p = tmp_path / "big.idx"
        w = IndexWriter(str(p))
        w.put(1, 8, 10)
        w.put(2, big, 20)
        w.close()
        keys, offsets, sizes = parse_index_arrays(str(p))
        assert list(keys) == [1, 2]
        assert list(offsets) == [8, big]
        # sorted .ecx write/read round-trip at >32GB offsets
        nm = NeedleMap()
        nm.put(5, big, 99)
        ecx = tmp_path / "big.ecx"
        nm.write_sorted_index(str(ecx))
        raw = ecx.read_bytes()
        assert len(raw) == 17
        assert t.unpack_index_entry(raw) == (5, big, 99)
    finally:
        t.set_offset_size(4)


def test_four_byte_offsets_reject_beyond_cap():
    from seaweedfs_tpu.storage import types as t

    assert t.OFFSET_SIZE == 4
    b = t.offset_to_bytes(32 * (1 << 30) - 8)  # top of the 4-byte range
    assert t.bytes_to_offset(b) == 32 * (1 << 30) - 8
