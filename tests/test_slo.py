"""Unit tests for the SLO engine + canary plane (ISSUE 13).

Covers: burn-rate arithmetic over windowed counter deltas for all three
SLI kinds, the pending/firing/resolved state machine, sinks, exemplar
linking, the tracer's important-span retention ring, the validated
?family= exposition filter, and a live in-process canary round trip
(byte identity + failure detection + the EC drop-shard probe).
"""

from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from seaweedfs_tpu.stats.metrics import Registry, parse_family_prefixes
from seaweedfs_tpu.telemetry.slo import (
    FIRING,
    OK,
    PENDING,
    BurnWindow,
    SloEngine,
    SloSpec,
    WebhookSink,
    sample_labels,
    spec_from_dict,
)


def _scrape_of(state: dict):
    """scrape(families) closure over a mutable {sample_name: value}."""

    def scrape(_families):
        return "\n".join(f"{k} {v}" for k, v in state.items()) + "\n"

    return scrape


def _engine(state, spec, clock, sinks=None, exemplars=None):
    return SloEngine(
        _scrape_of(state), specs=[spec], sinks=sinks or [],
        interval_s=0.0, window_scale=1.0,
        now=lambda: clock["t"], exemplars=exemplars)


RATIO_SPEC = dict(
    name="avail", severity="page", kind="ratio",
    bad_family="probe_total", bad_labels={"result": "error"},
    total_family="probe_total",
    total_labels={"result": ("ok", "error")},
    objective=0.99, window=BurnWindow(10.0, 60.0, 2.0),
)


def test_sample_labels_parses_escapes():
    name, labels = sample_labels(
        'x_total{a="b",path="q\\"uote",n="l\\nf"}')
    assert name == "x_total"
    assert labels == {"a": "b", "path": 'q"uote', "n": "l\nf"}
    assert sample_labels("plain") == ("plain", {})


def test_ratio_spec_fires_and_resolves():
    clock = {"t": 1000.0}
    state = {'probe_total{result="ok"}': 100.0,
             'probe_total{result="error"}': 0.0}
    transitions = []
    eng = _engine(state, SloSpec(**RATIO_SPEC), clock,
                  sinks=[transitions.append])
    eng.evaluate()  # baseline
    clock["t"] += 5
    state['probe_total{result="ok"}'] += 10
    assert eng.evaluate() == []  # clean traffic: ok
    # 50% of traffic failing: burn = 0.5/0.01 = 50 >> 2 in both windows
    for _ in range(3):
        clock["t"] += 3
        state['probe_total{result="ok"}'] += 5
        state['probe_total{result="error"}'] += 5
        eng.evaluate()
    st = eng.status(evaluate_if_idle=False)
    assert st["states"]["avail"]["state"] == FIRING
    assert any(t["state"] == FIRING for t in transitions)
    alert = st["alerts"][0]
    assert alert["burnShort"] > 2 and alert["burnLong"] > 2
    # clean traffic again: once the SHORT window (10s) has rolled past
    # the burst, the alert resolves even though the long window is dirty
    for _ in range(6):
        clock["t"] += 3
        state['probe_total{result="ok"}'] += 10
        eng.evaluate()
    st = eng.status(evaluate_if_idle=False)
    assert st["states"]["avail"]["state"] == OK
    assert any(t["state"] == OK and t.get("from") == FIRING
               for t in transitions)


def test_ratio_pending_when_only_short_window_burns():
    clock = {"t": 0.0}
    state = {'probe_total{result="ok"}': 1000.0,
             'probe_total{result="error"}': 0.0}
    eng = _engine(state, SloSpec(**RATIO_SPEC), clock)
    eng.evaluate()
    # long clean history first, so the long window dilutes the burst
    for _ in range(20):
        clock["t"] += 5
        state['probe_total{result="ok"}'] += 100
        eng.evaluate()
    # short sharp burst: dominates the 10s window, diluted in the 60s
    clock["t"] += 5
    state['probe_total{result="error"}'] += 10
    state['probe_total{result="ok"}'] += 90
    eng.evaluate()
    assert eng.status(evaluate_if_idle=False)["states"]["avail"][
        "state"] == PENDING


def test_counter_reset_does_not_go_negative():
    clock = {"t": 0.0}
    state = {'probe_total{result="ok"}': 500.0,
             'probe_total{result="error"}': 20.0}
    eng = _engine(state, SloSpec(**RATIO_SPEC), clock)
    eng.evaluate()
    # node restart: counters reset below the baseline
    clock["t"] += 5
    state['probe_total{result="ok"}'] = 10.0
    state['probe_total{result="error"}'] = 0.0
    eng.evaluate()
    st = eng.status(evaluate_if_idle=False)
    assert st["states"]["avail"]["state"] == OK


def test_latency_spec_from_bucket_deltas():
    clock = {"t": 0.0}
    state = {
        'req_seconds_bucket{type="volumeServer",op="get",le="0.5"}': 100.0,
        'req_seconds_bucket{type="volumeServer",op="get",le="+Inf"}': 100.0,
        'req_seconds_count{type="volumeServer",op="get"}': 100.0,
    }
    spec = SloSpec(
        name="read-p99", severity="page", kind="latency",
        family="req_seconds",
        labels={"type": "volumeServer", "op": "get"},
        threshold_s=0.5, objective=0.99,
        window=BurnWindow(10.0, 60.0, 2.0))
    eng = _engine(state, spec, clock)
    eng.evaluate()
    # 90 of 100 new requests above the 0.5s bucket: burn = 0.9/0.01
    clock["t"] += 5
    state['req_seconds_bucket{type="volumeServer",op="get",le="0.5"}'] += 10
    state['req_seconds_bucket{type="volumeServer",op="get",le="+Inf"}'] += 100
    state['req_seconds_count{type="volumeServer",op="get"}'] += 100
    eng.evaluate()
    st = eng.status(evaluate_if_idle=False)
    assert st["states"]["read-p99"]["state"] == FIRING
    assert st["alerts"][0]["burnShort"] == pytest.approx(90.0)


def test_gauge_spec_pending_for_then_firing_then_resolved():
    clock = {"t": 0.0}
    state = {"queue_depth": 0.0}
    transitions = []
    spec = SloSpec(
        name="backlog", severity="warn", kind="gauge",
        family="queue_depth", threshold=1.0, for_s=10.0,
        window=BurnWindow(10.0, 60.0, 1.0))
    eng = _engine(state, spec, clock, sinks=[transitions.append])
    eng.evaluate()
    assert eng.status(evaluate_if_idle=False)["states"]["backlog"][
        "state"] == OK
    state["queue_depth"] = 3.0
    clock["t"] += 1
    eng.evaluate()
    assert eng.status(evaluate_if_idle=False)["states"]["backlog"][
        "state"] == PENDING
    clock["t"] += 11  # held above threshold past for_s
    eng.evaluate()
    st = eng.status(evaluate_if_idle=False)
    assert st["states"]["backlog"]["state"] == FIRING
    assert st["alerts"][0]["value"] == 3.0
    state["queue_depth"] = 0.0
    clock["t"] += 1
    eng.evaluate()
    assert eng.status(evaluate_if_idle=False)["states"]["backlog"][
        "state"] == OK
    assert [t["state"] for t in transitions] == [PENDING, FIRING, OK]


def test_event_spec_counts_window_delta_and_rolls_off():
    """An `event` spec fires on a counter increment even when the
    underlying gauge would already have drained, and resolves once the
    short window rolls past the burst."""
    clock = {"t": 0.0}
    state = {'exposed_total{exposure="1"}': 0.0}
    spec = SloSpec(name="exposure", severity="page", kind="event",
                   family="exposed_total", threshold=1.0, for_s=0.0,
                   window=BurnWindow(10.0, 60.0, 1.0))
    eng = _engine(state, spec, clock)
    eng.evaluate()
    # 3 volumes drop below redundancy; the repair drains them instantly
    # (no gauge would ever read non-zero at a tick boundary)
    clock["t"] += 2
    state['exposed_total{exposure="1"}'] += 3
    eng.evaluate()
    st = eng.status(evaluate_if_idle=False)
    assert st["states"]["exposure"]["state"] == FIRING
    assert st["alerts"][0]["value"] == 3.0
    # no new events: resolved once the 10s short window rolls past
    clock["t"] += 11
    eng.evaluate()
    assert eng.status(evaluate_if_idle=False)["states"]["exposure"][
        "state"] == OK


def test_gauge_label_filter_and_max_across_instances():
    clock = {"t": 0.0}
    state = {
        'lag_seconds{instance="a",link="x"}': 5.0,
        'lag_seconds{instance="b",link="y"}': 80.0,
        'other_seconds{instance="a"}': 500.0,
    }
    spec = SloSpec(name="lag", severity="warn", kind="gauge",
                   family="lag_seconds", threshold=60.0, for_s=0.0,
                   window=BurnWindow(10.0, 60.0, 1.0))
    eng = _engine(state, spec, clock)
    eng.evaluate()
    st = eng.status(evaluate_if_idle=False)
    assert st["states"]["lag"]["state"] == FIRING
    assert st["alerts"][0]["value"] == 80.0


def test_firing_alert_embeds_exemplar_trace_ids():
    r = Registry()
    hist = r.histogram("t13_probe_seconds", "x", labels=("probe",))
    hist.labels("volume_rt").observe(0.4, trace_id="ab" * 16)
    hist.labels("volume_rt").observe(0.1, trace_id="cd" * 16)
    clock = {"t": 0.0}
    state = {'probe_total{result="ok"}': 10.0,
             'probe_total{result="error"}': 0.0}
    spec = SloSpec(**{**RATIO_SPEC,
                      "exemplar_family": "t13_probe_seconds"})
    eng = _engine(state, spec, clock, exemplars=r.exemplars)
    eng.evaluate()
    clock["t"] += 5
    state['probe_total{result="error"}'] += 10
    transitions = eng.evaluate()
    assert transitions and transitions[0]["state"] == FIRING
    ex = transitions[0]["exemplars"]
    # slowest sample first, with a ready-made trace query link
    assert ex[0]["traceId"] == "ab" * 16
    assert ex[0]["traceQuery"].endswith("ab" * 16)


def test_histogram_exemplar_keeps_slowest_and_rotates(monkeypatch):
    r = Registry()
    hist = r.histogram("t13_rot_seconds", "x")
    hist.observe(0.3, trace_id="aa" * 16)
    hist.observe(0.26, trace_id="bb" * 16)  # same bucket, smaller: not kept
    ex = r.exemplars("t13_rot_seconds")
    assert [e["traceId"] for e in ex] == ["aa" * 16]
    # age the entry past the window: a smaller sample may replace it
    child = hist.labels()
    for entry in child.exemplars.values():
        entry[2] -= 10_000
    hist.observe(0.25, trace_id="cc" * 16)
    assert "cc" * 16 in {e["traceId"]
                         for e in r.exemplars("t13_rot_seconds")}


def test_webhook_sink_posts_alert_json():
    received = []

    class Hook(BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass

        def do_POST(self):
            body = self.rfile.read(
                int(self.headers.get("Content-Length") or 0))
            received.append(json.loads(body))
            self.send_response(200)
            self.send_header("Content-Length", "0")
            self.end_headers()

    httpd = ThreadingHTTPServer(("127.0.0.1", 0), Hook)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    try:
        sink = WebhookSink(
            f"http://127.0.0.1:{httpd.server_address[1]}/alert")
        sink({"slo": "avail", "state": "firing", "severity": "page"})
        deadline = time.time() + 5
        while time.time() < deadline and not received:
            time.sleep(0.02)
        assert received and received[0]["slo"] == "avail"
        # a dead webhook must not raise into the engine
        WebhookSink("http://127.0.0.1:9/alert", timeout_s=0.2)(
            {"slo": "x", "state": "firing", "severity": "page"})
    finally:
        httpd.shutdown()
        httpd.server_close()


def test_spec_from_dict_with_window_override():
    spec = spec_from_dict({
        "name": "x", "severity": "warn", "kind": "gauge",
        "family": "f", "threshold": 2.0,
        "window": {"shortS": 5, "longS": 25, "factor": 3},
    })
    w = spec.burn_window()
    assert (w.short_s, w.long_s, w.factor) == (5.0, 25.0, 3.0)


def test_alert_history_is_bounded():
    clock = {"t": 0.0}
    state = {"queue_depth": 0.0}
    spec = SloSpec(name="b", severity="warn", kind="gauge",
                   family="queue_depth", threshold=1.0, for_s=0.0,
                   window=BurnWindow(1.0, 2.0, 1.0))
    eng = SloEngine(_scrape_of(state), specs=[spec], sinks=[],
                    interval_s=0.0, window_scale=1.0,
                    now=lambda: clock["t"], max_history=8)
    for i in range(40):
        clock["t"] += 1
        state["queue_depth"] = float(i % 2 * 5)
        eng.evaluate()
    assert len(eng.alert_history) == 8


# -- tracer important-span retention ----------------------------------------


def test_tracer_important_ring_survives_healthy_flood():
    from seaweedfs_tpu.telemetry.trace import Span, Tracer

    tr = Tracer(max_spans=10, max_important=8)
    bad = Span(trace_id="de" * 16, span_id="11" * 8, parent_id="",
               name="volumeServer.get", start=time.time(),
               duration=0.01, status="error: IOError")
    slow = Span(trace_id="fa" * 16, span_id="22" * 8, parent_id="",
                name="filer.post", start=time.time(), duration=99.0)
    tr.record(bad)
    tr.record(slow)
    for i in range(50):  # healthy flood far past the main ring bound
        tr.record(Span(trace_id=f"{i:032x}", span_id=f"{i:016x}",
                       parent_id="", name="ok", start=time.time(),
                       duration=0.001))
    trace_ids = {s.trace_id for s in tr.spans()}
    assert bad.trace_id in trace_ids and slow.trace_id in trace_ids
    # and the per-trace query still finds it
    assert tr.recent_traces(100, trace_id=bad.trace_id)
    # no duplicates when a span is in both rings
    tr2 = Tracer(max_spans=10, max_important=8)
    tr2.record(bad)
    assert len(tr2.spans()) == 1


# -- ?family= filter ---------------------------------------------------------


def test_parse_family_prefixes_validation():
    assert parse_family_prefixes("") is None
    assert parse_family_prefixes("seaweedfs_canary") == [
        "seaweedfs_canary"]
    assert parse_family_prefixes("a_x, b_y") == ["a_x", "b_y"]
    with pytest.raises(ValueError):
        parse_family_prefixes("bad-name")
    with pytest.raises(ValueError):
        parse_family_prefixes("1leading")
    with pytest.raises(ValueError):
        parse_family_prefixes(",".join(f"f{i}" for i in range(17)))


def test_registry_render_family_filter():
    r = Registry()
    r.counter("t13f_a_total", "x").inc()
    r.counter("t13f_b_total", "x").inc()
    text = r.render(["t13f_a"])
    assert "t13f_a_total" in text and "t13f_b_total" not in text
    assert "t13f_b_total" in r.render()


def test_federated_exposition_family_filter_keeps_meta():
    from seaweedfs_tpu.telemetry.federation import FederatedExposition

    fed = FederatedExposition(["keep_me"])
    node = {"instance": "1.2.3.4:80", "type": "volume"}
    fed.add_live(node, "keep_me_total 3\ndrop_me_total 9\n", 0.01)
    out = fed.render()
    assert "keep_me_total" in out and "drop_me_total" not in out
    # scrape-health meta families always survive the filter
    assert 'seaweedfs_federation_up{instance="1.2.3.4:80"' in out


# -- live canary round trip (in-process master + volume server) --------------


@pytest.fixture(scope="module")
def canary_cluster(tmp_path_factory):
    import shutil

    from helpers import free_port, make_volume
    from seaweedfs_tpu.master.server import MasterServer
    from seaweedfs_tpu.storage.ec import constants as ecc
    from seaweedfs_tpu.storage.ec.encoder import (
        generate_ec_files,
        write_sorted_file_from_idx,
    )
    from seaweedfs_tpu.volume.server import VolumeServer

    tmp = tmp_path_factory.mktemp("t13canary")
    master = MasterServer(ip="127.0.0.1", port=free_port(),
                          pulse_seconds=0.5)
    master.start()
    vol_dir = tmp / "vol"
    vol_dir.mkdir()
    vs = VolumeServer(
        directories=[str(vol_dir)],
        master_addresses=[f"127.0.0.1:{master.grpc_port}"],
        ip="127.0.0.1", port=free_port(), pulse_seconds=0.5,
        max_volume_count=16)
    vs.start()
    deadline = time.time() + 15
    while time.time() < deadline and not master.topo.nodes:
        time.sleep(0.1)
    import urllib.request

    urllib.request.urlopen(
        f"http://127.0.0.1:{master.port}/dir/assign", timeout=10).read()
    deadline = time.time() + 15
    while time.time() < deadline:
        with master.topo.lock:
            if any(n.volumes for n in master.topo.nodes.values()):
                break
        time.sleep(0.1)
    # stage one tiny EC volume (vid 99) for the degraded-read probe
    stage = tmp / "stage"
    stage.mkdir()
    svol = make_volume(str(stage), volume_id=99, n_needles=8, seed=7)
    base = svol.file_name()
    svol.close()
    generate_ec_files(base, large_block_size=10000, small_block_size=100,
                      codec_name="cpu", slice_size=1 << 20)
    write_sorted_file_from_idx(base)
    tbase = vs.store.locations[0].base_name(99, "")
    shutil.copy(base + ".ecx", tbase + ".ecx")
    for sid in range(ecc.TOTAL_SHARDS):
        shutil.copy(base + ecc.to_ext(sid), tbase + ecc.to_ext(sid))
    vs.store.mount_ec_shards(99, "", list(range(ecc.TOTAL_SHARDS)))
    ev = vs.store.find_ec_volume(99)
    ev.large_block_size = 10000
    ev.small_block_size = 100
    deadline = time.time() + 15
    while time.time() < deadline:
        with master.topo.lock:
            if any(n.ec_shards for n in master.topo.nodes.values()):
                break
        time.sleep(0.1)
    yield master, vs
    vs.stop()
    master.stop()


def test_canary_round_trip_live(canary_cluster):
    from seaweedfs_tpu.stats.metrics import REGISTRY

    master, _vs = canary_cluster

    def counter(probe, result):
        total = 0.0
        for name, v in REGISTRY.snapshot_samples(max_samples=1 << 20):
            if (name.startswith("seaweedfs_canary_probe_total")
                    and f'probe="{probe}"' in name
                    and f'result="{result}"' in name):
                total += v
        return total

    ok_before = counter("volume_rt", "ok")
    ec_before = counter("ec_degraded", "ok")
    st = master.canary.run_once()
    assert st["byteMismatches"] == 0
    vt = st["probes"]["volume_rt"]["targets"]
    assert vt and all(t["result"] == "ok" for t in vt.values())
    ec = st["probes"]["ec_degraded"]["targets"]
    assert ec and all(t["result"] == "ok" for t in ec.values())
    assert counter("volume_rt", "ok") > ok_before
    assert counter("ec_degraded", "ok") > ec_before
    # probe spans carry exemplar trace ids for the availability alert
    ex = REGISTRY.exemplars("seaweedfs_canary_probe_seconds")
    assert ex and all(len(e["traceId"]) == 32 for e in ex)


def test_canary_ec_probe_reconstructs(canary_cluster):
    _master, vs = canary_cluster
    ev = vs.store.find_ec_volume(99)
    res = ev.canary_read()
    assert res["reconstructed"] and res["droppedShard"] is not None
    assert res["bytes"] > 0


def test_cluster_alerts_endpoint_and_shell(canary_cluster):
    import urllib.error
    import urllib.request

    from seaweedfs_tpu.shell.commands import CommandEnv, run_command

    master, _vs = canary_cluster
    with urllib.request.urlopen(
            f"http://127.0.0.1:{master.port}/cluster/alerts",
            timeout=10) as r:
        doc = json.loads(r.read())
    assert "availability" in doc["states"]
    assert doc["canary"]["tick"] >= 1
    env = CommandEnv(f"127.0.0.1:{master.grpc_port}")
    text = run_command(env, "cluster.alerts")
    assert "SLOs (" in text and "canary:" in text
    status = run_command(env, "cluster.status")
    assert "health:" in status
    # the ?family= filter is validated at the cluster surface too
    bad = urllib.request.Request(
        f"http://127.0.0.1:{master.port}/cluster/metrics?family=no-dash")
    with pytest.raises(urllib.error.HTTPError) as ei:
        urllib.request.urlopen(bad, timeout=10)
    assert ei.value.code == 400


def test_geo_sentinel_probe_measures_remote_payload_age():
    """The geo probe writes a sentinel through the local filer and reads
    it back from a REMOTE cluster's filer; the payload age it observes
    becomes seaweedfs_canary_staleness_seconds{probe="geo_sentinel"}."""
    from seaweedfs_tpu.stats.metrics import CANARY_STALENESS
    from seaweedfs_tpu.telemetry.canary import CanaryProber

    class StubMaster:
        ip, port = "127.0.0.1", 1234
        peer_clusters = ["peer-master:9333"]
        lifecycle = None

        def clients_snapshot(self):
            return {"filer@a": {"type": "filer",
                                "http_address": "local-filer:8888"}}

    prober = CanaryProber(StubMaster())
    calls = []
    lag_s = 7.5

    def fake_http(method, url, body=b"", headers=None):
        calls.append((method, url))
        if "/cluster/status" in url:
            return json.dumps(
                {"Filers": {"x": {"httpAddress": "remote-filer:8888"}}}
            ).encode()
        if url.startswith("http://remote-filer:8888"):
            return json.dumps({"ts": time.time() - lag_s}).encode()
        return b""

    prober._http = fake_http
    prober.probe_geo_sentinel()
    st = prober.status()["probes"]["geo_sentinel"]
    assert st["targets"]["peer-master:9333"]["result"] == "ok"
    assert ("PUT", "http://local-filer:8888/.canary/geo-sentinel") in calls
    staleness = CANARY_STALENESS.labels("geo_sentinel")
    assert lag_s - 1 <= staleness.value <= lag_s + 5

    # an unreachable peer counts as a probe error, never a crash
    def broken_http(method, url, body=b"", headers=None):
        if "/cluster/status" in url:
            raise IOError("peer down")
        return fake_http(method, url, body, headers)

    prober._http = broken_http
    prober.probe_geo_sentinel()
    st = prober.status()["probes"]["geo_sentinel"]
    assert st["targets"]["peer-master:9333"]["result"] == "error"


def test_canary_detects_dead_volume_server(tmp_path):
    from helpers import free_port
    from seaweedfs_tpu.master.server import MasterServer
    from seaweedfs_tpu.volume.server import VolumeServer

    master = MasterServer(ip="127.0.0.1", port=free_port(),
                          pulse_seconds=30.0)  # slow sweep: node stays
    master.start()
    vs = VolumeServer(
        directories=[str(tmp_path)],
        master_addresses=[f"127.0.0.1:{master.grpc_port}"],
        ip="127.0.0.1", port=free_port(), pulse_seconds=0.5,
        max_volume_count=8)
    vs.start()
    try:
        deadline = time.time() + 15
        while time.time() < deadline and not master.topo.nodes:
            time.sleep(0.1)
        import urllib.request

        urllib.request.urlopen(
            f"http://127.0.0.1:{master.port}/dir/assign",
            timeout=10).read()
        deadline = time.time() + 15
        while time.time() < deadline:
            with master.topo.lock:
                if any(n.volumes for n in master.topo.nodes.values()):
                    break
            time.sleep(0.1)
        assert master.canary.run_once()["byteMismatches"] == 0
        vs.stop()  # the process is gone but the topology still lists it
        st = master.canary.run_once()
        vt = st["probes"]["volume_rt"]["targets"]
        assert any(t["result"] == "error" for t in vt.values())
    finally:
        master.stop()
