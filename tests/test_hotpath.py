"""Small-file hot-path suite: keep-alive connection pool, hot-needle
cache invalidation, lock-free concurrent needle reads, and the
metadata-only HEAD path (ISSUE 3)."""

import http.server
import json
import threading
import time
import urllib.error

import pytest

from helpers import free_port, make_volume
from seaweedfs_tpu.storage.needle import Needle
from seaweedfs_tpu.storage.store import Store
from seaweedfs_tpu.util import connpool, faultpoint
from seaweedfs_tpu.util.chunk_cache import NeedleCache


# ---------------------------------------------------------------------------
# connection pool
# ---------------------------------------------------------------------------


class _CountingHandler(http.server.BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"

    def setup(self):
        super().setup()
        self.server.conn_count += 1
        self.server.live_socks.append(self.connection)

    def log_message(self, fmt, *args):
        pass

    def do_GET(self):
        body = json.dumps({"path": self.path}).encode()
        self.send_response(200)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_POST(self):
        length = int(self.headers.get("Content-Length", 0))
        payload = self.rfile.read(length)
        body = json.dumps({"echo_len": len(payload)}).encode()
        self.send_response(200)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)


def _counting_server(port: int, handler=_CountingHandler):
    httpd = http.server.ThreadingHTTPServer(("127.0.0.1", port), handler)
    httpd.conn_count = 0
    httpd.live_socks = []
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    return httpd


def _stop_server(httpd):
    httpd.shutdown()
    httpd.server_close()
    for sock in httpd.live_socks:  # kill keep-alive conns, not just accept
        try:
            sock.shutdown(2)
            sock.close()
        except OSError:
            pass


def test_pool_reuses_one_socket_for_sequential_requests():
    port = free_port()
    httpd = _counting_server(port)
    pool = connpool.ConnectionPool()
    try:
        for i in range(5):
            with pool.request("GET", f"http://127.0.0.1:{port}/r{i}") as r:
                assert r.status == 200
                assert json.loads(r.read())["path"] == f"/r{i}"
        # five sequential requests, ONE accepted TCP connection
        assert httpd.conn_count == 1
        assert pool.idle_count("127.0.0.1", port) == 1
    finally:
        pool.close_all()
        _stop_server(httpd)


def test_pool_interleaves_posts_and_bodies():
    port = free_port()
    httpd = _counting_server(port)
    pool = connpool.ConnectionPool()
    try:
        for size in (0, 1, 4096):
            with pool.request("POST", f"http://127.0.0.1:{port}/w",
                              body=b"x" * size) as r:
                assert json.loads(r.read())["echo_len"] == size
        assert httpd.conn_count == 1
    finally:
        pool.close_all()
        _stop_server(httpd)


def test_pool_retries_stale_socket_once():
    """A pooled keep-alive socket whose peer restarted is replayed once
    on a fresh dial instead of failing the request."""
    port = free_port()
    httpd = _counting_server(port)
    pool = connpool.ConnectionPool()
    try:
        with pool.request("GET", f"http://127.0.0.1:{port}/warm") as r:
            r.read()
        assert pool.idle_count("127.0.0.1", port) == 1
        # the peer goes away and comes back: the pooled socket is now dead
        _stop_server(httpd)
        httpd = _counting_server(port)
        with pool.request("GET", f"http://127.0.0.1:{port}/again") as r:
            assert r.status == 200
            r.read()
        assert httpd.conn_count == 1  # the retry dialed the new server
    finally:
        pool.close_all()
        _stop_server(httpd)


def test_pool_fails_fast_on_fresh_connection_errors():
    """Errors on a never-used connection are NOT retried by the pool —
    retry policy belongs to failsafe at the call sites."""
    pool = connpool.ConnectionPool()
    port = free_port()  # nothing listening
    with pytest.raises(OSError):
        pool.request("GET", f"http://127.0.0.1:{port}/x", timeout=2)


def test_pool_raises_httperror_like_urlopen():
    port = free_port()

    class _NotFound(_CountingHandler):
        def do_GET(self):
            body = b'{"error": "nope"}'
            self.send_response(404)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

    httpd = _counting_server(port, _NotFound)
    pool = connpool.ConnectionPool()
    try:
        with pytest.raises(urllib.error.HTTPError) as ei:
            pool.request("GET", f"http://127.0.0.1:{port}/missing")
        assert ei.value.code == 404
        assert b"nope" in ei.value.read()
        # the error response was drained: the socket is reusable
        assert pool.idle_count("127.0.0.1", port) == 1
    finally:
        pool.close_all()
        _stop_server(httpd)


def test_pool_bounds_idle_connections():
    port = free_port()
    httpd = _counting_server(port)
    pool = connpool.ConnectionPool(max_idle_per_host=2)
    try:
        # three conns held concurrently, all released: only two kept
        rs = [pool.request("GET", f"http://127.0.0.1:{port}/c{i}")
              for i in range(3)]
        for r in rs:
            r.read()
        assert httpd.conn_count == 3
        assert pool.idle_count("127.0.0.1", port) == 2
    finally:
        pool.close_all()
        _stop_server(httpd)


# ---------------------------------------------------------------------------
# hot-needle cache
# ---------------------------------------------------------------------------


def _store(tmp_path, **kw) -> Store:
    s = Store([str(tmp_path)], needle_cache_mb=kw.pop("needle_cache_mb", 8))
    s.add_volume(1, "")
    return s


def _needle(nid: int, data: bytes, cookie: int = 0x1234) -> Needle:
    return Needle(cookie=cookie, id=nid, data=data)


def test_needle_cache_hit_and_write_invalidation(tmp_path):
    s = _store(tmp_path)
    try:
        s.write_needle(1, _needle(7, b"v1"))
        assert s.read_needle(1, 7).data == b"v1"  # miss, fills cache
        assert len(s.needle_cache) == 1
        assert s.read_needle(1, 7).data == b"v1"  # hit
        # overwrite must invalidate: the next read sees v2, never v1
        s.write_needle(1, _needle(7, b"v2"))
        assert len(s.needle_cache) == 0
        assert s.read_needle(1, 7).data == b"v2"
    finally:
        s.close()


def test_needle_cache_delete_invalidation(tmp_path):
    s = _store(tmp_path)
    try:
        s.write_needle(1, _needle(9, b"doomed"))
        assert s.read_needle(1, 9).data == b"doomed"
        s.delete_needle(1, 9)
        with pytest.raises(KeyError):
            s.read_needle(1, 9)
    finally:
        s.close()


def test_needle_cache_vacuum_invalidation(tmp_path):
    s = _store(tmp_path)
    try:
        for nid in (1, 2, 3):
            s.write_needle(1, _needle(nid, f"blob-{nid}".encode() * 50))
        s.delete_needle(1, 2)
        for nid in (1, 3):
            s.read_needle(1, nid)
        assert len(s.needle_cache) == 2
        s.compact_volume(1)
        s.commit_compact_volume(1)
        # vacuum rewrote every offset: the volume's entries are gone...
        assert len(s.needle_cache) == 0
        # ...and post-vacuum reads still serve the right bytes
        assert s.read_needle(1, 1).data == b"blob-1" * 50
        with pytest.raises(KeyError):
            s.read_needle(1, 2)
    finally:
        s.close()


def test_needle_cache_cookie_checked_on_hit(tmp_path):
    s = _store(tmp_path)
    try:
        s.write_needle(1, _needle(5, b"secret", cookie=0xAA))
        s.read_needle(1, 5)  # fill
        with pytest.raises(PermissionError):
            s.read_needle(1, 5, expected_cookie=0xBB)
        assert s.read_needle(1, 5, expected_cookie=0xAA).data == b"secret"
    finally:
        s.close()


def test_needle_cache_byte_bound_evicts():
    cache = NeedleCache(limit_bytes=4096, max_entry_bytes=4096)
    for nid in range(10):
        cache.put(1, nid, _needle(nid, b"z" * 1024))
    # (1024 + 64) per entry under a 4096 bound -> only 3 fit
    assert len(cache) == 3
    assert cache.get(1, 9) is not None  # newest survives
    assert cache.get(1, 0) is None  # oldest evicted


# ---------------------------------------------------------------------------
# lock-free concurrent reads
# ---------------------------------------------------------------------------


def test_concurrent_reads_on_one_volume_overlap(tmp_path):
    """Two GETs on one volume must overlap their disk I/O.  A 0.4s
    faultpoint delay sits INSIDE the (unlocked) disk-read section; two
    threads reading serially would take >= 0.8s, overlapped ~0.4s."""
    vol = make_volume(str(tmp_path), n_needles=4)
    try:
        faultpoint.set_fault("volume.disk.read", "delay", delay=0.4, count=2)
        results = {}

        def read(nid: int) -> None:
            results[nid] = vol.read_needle(nid)

        t0 = time.perf_counter()
        threads = [threading.Thread(target=read, args=(nid,))
                   for nid in (1, 2)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        elapsed = time.perf_counter() - t0
        assert results[1].id == 1 and results[2].id == 2
        assert elapsed < 0.7, (
            f"reads serialized: {elapsed:.2f}s for two 0.4s-delayed reads")
    finally:
        faultpoint.clear_fault("volume.disk.read")
        vol.close()


def test_read_survives_racing_vacuum(tmp_path):
    """A read that snapshots the .dat handle right before a vacuum swap
    retries under the lock and still returns the right bytes."""
    from seaweedfs_tpu.storage.vacuum import vacuum_volume

    vol = make_volume(str(tmp_path), n_needles=30)
    try:
        stop = threading.Event()
        errors = []

        def reader():
            while not stop.is_set():
                try:
                    n = vol.read_needle(7)
                    assert n.id == 7
                except Exception as e:  # noqa: BLE001
                    errors.append(e)

        t = threading.Thread(target=reader)
        t.start()
        for _ in range(3):
            vacuum_volume(vol)
        stop.set()
        t.join()
        assert not errors, errors[:3]
    finally:
        vol.close()


# ---------------------------------------------------------------------------
# HEAD from metadata (no image transforms)
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def mini_cluster(tmp_path_factory):
    from seaweedfs_tpu.master.server import MasterServer
    from seaweedfs_tpu.volume.server import VolumeServer

    master = MasterServer(ip="127.0.0.1", port=free_port(),
                          volume_size_limit_mb=64)
    master.start()
    vs_ = VolumeServer(
        directories=[str(tmp_path_factory.mktemp("vol"))],
        master_addresses=[f"127.0.0.1:{master.grpc_port}"],
        ip="127.0.0.1", port=free_port(), pulse_seconds=0.5,
        max_volume_count=8,
    )
    vs_.start()
    deadline = time.time() + 15
    while time.time() < deadline and len(master.topo.nodes) < 1:
        time.sleep(0.1)
    assert master.topo.nodes
    yield master, vs_
    vs_.stop()
    master.stop()


def test_head_skips_image_pipeline(mini_cluster, monkeypatch):
    from seaweedfs_tpu import images

    master, vs_ = mini_cluster
    with connpool.request(
            "GET",
            f"http://127.0.0.1:{master.port}/dir/assign") as r:
        a = json.loads(r.read())
    payload = b"\xff\xd8\xff\xe0 not really a jpeg \xff\xd9" * 40
    body = (b"--bb\r\nContent-Disposition: form-data; name=\"file\"; "
            b"filename=\"photo.jpg\"\r\n"
            b"Content-Type: image/jpeg\r\n\r\n"
            + payload + b"\r\n--bb--\r\n")
    url = f"http://{a['url']}/{a['fid']}"
    with connpool.request(
            "POST", url, body=body,
            headers={"Content-Type":
                     "multipart/form-data; boundary=bb"}) as r:
        assert r.status == 201

    calls = []
    orig = images.fix_orientation
    monkeypatch.setattr(
        images, "fix_orientation",
        lambda data: calls.append(1) or orig(data))

    with connpool.request("HEAD", url) as r:
        assert r.status == 200
        assert r.read() == b""
        assert int(r.headers["Content-Length"]) == len(payload)
        assert r.headers["Etag"]
        assert r.headers["Content-Type"] == "image/jpeg"
    assert calls == [], "HEAD ran the image transform pipeline"

    with connpool.request("GET", url) as r:
        assert r.status == 200
        assert r.read() == payload
    assert calls, "GET should still run the image pipeline"

    # range semantics survive the metadata-only HEAD path
    with connpool.request("HEAD", url,
                          headers={"Range": "bytes=0-99"}) as r:
        assert r.status == 206
        assert r.read() == b""
        assert r.headers["Content-Range"] == f"bytes 0-99/{len(payload)}"
        assert int(r.headers["Content-Length"]) == 100


# ---------------------------------------------------------------------------
# _writev_all index bookkeeping
# ---------------------------------------------------------------------------


def test_writev_all_chunks_past_iov_max(tmp_path, monkeypatch):
    from seaweedfs_tpu.storage.ec import encoder

    monkeypatch.setattr(encoder, "_IOV_MAX", 4)
    bufs = [bytes([i % 251]) * (i % 7 + 1) for i in range(100)]
    want = b"".join(bufs)
    path = tmp_path / "iov.bin"
    import os

    fd = os.open(str(path), os.O_WRONLY | os.O_CREAT)
    try:
        encoder._writev_all(fd, list(bufs))
    finally:
        os.close(fd)
    assert path.read_bytes() == want
