"""Flight recorder + heavy-hitter attribution suite (ISSUE 20).

Tier-1: the space-saving sketch's guarantees, hot-key window rotation
and cardinality bounds, and the full bundle journey on an in-process
master + volume cluster (manual capture over HTTP, listing, retention,
traversal guard, single-flight 409, /cluster/hot federation).

Chaos (slow): SIGKILL a volume-holding node under zipf-hot canary load
— the availability page fires and the flight recorder auto-captures a
bundle that covers every live node, pins the alert's exemplar trace,
and names the zipf-hot needle in the hot-key tables, while the client
load sees zero 5xx.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import pytest

from helpers import free_port

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _get_json(url, timeout=10):
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return json.loads(r.read())


def _wait(cond, deadline_s, what):
    deadline = time.time() + deadline_s
    while time.time() < deadline:
        if cond():
            return
        time.sleep(0.2)
    raise TimeoutError(what)


# -- space-saving sketch -----------------------------------------------------


def test_space_saving_heavy_hitter_guarantee():
    from seaweedfs_tpu.telemetry.hotkeys import SpaceSaving

    s = SpaceSaving(k=8)
    # 1000 hits on the hot key buried in 500 distinct cold keys: any key
    # with true frequency > N/k must survive, with count exact to within
    # its reported error
    for i in range(500):
        s.record(f"cold-{i}")
        s.record("hot", 2)
    assert len(s) <= 8
    top = s.top(1)[0]
    assert top["key"] == "hot"
    assert top["count"] - top["error"] <= 1000 <= top["count"]


def test_space_saving_eviction_inherits_error():
    from seaweedfs_tpu.telemetry.hotkeys import SpaceSaving

    s = SpaceSaving(k=2)
    s.record("a", 5)
    s.record("b", 3)
    s.record("c")  # evicts b (min=3); c inherits 3 as its error floor
    entries = {e["key"]: e for e in s.top()}
    assert set(entries) == {"a", "c"}
    assert entries["c"]["count"] == 4 and entries["c"]["error"] == 3
    assert entries["a"]["error"] == 0


def test_hotkey_recorder_window_rotation_and_gauge_bound():
    from seaweedfs_tpu.stats.metrics import HOTKEY_TOP
    from seaweedfs_tpu.telemetry.hotkeys import (
        DIMENSIONS,
        TOP_GAUGE_KEYS,
        HotKeyRecorder,
    )

    r = HotKeyRecorder(k=16, window_s=0.1)
    for i in range(40):
        r.record("needle", f"3,{i:08x}")
    r.record("bucket", "photos", 7)
    snap = r.snapshot()
    assert snap["dims"]["bucket"]["current"][0]["key"] == "photos"
    time.sleep(0.15)
    snap = r.snapshot()  # lazy rotation on read
    assert snap["dims"]["bucket"]["previous"][0]["key"] == "photos"
    assert snap["dims"]["bucket"]["current"] == []
    # rotation republished the gauge children wholesale: hard bound
    with HOTKEY_TOP._lock:
        children = len(HOTKEY_TOP._children)
    assert children <= len(DIMENSIONS) * TOP_GAUGE_KEYS


def test_hotkeys_kill_switch(monkeypatch):
    from seaweedfs_tpu.telemetry import hotkeys

    monkeypatch.setenv(hotkeys.DISABLE_VAR, "0")
    hotkeys.reset()
    try:
        hotkeys.record("needle", "3,01010101")
        snap = hotkeys.snapshot()
        assert snap["enabled"] is False
        assert snap["dims"]["needle"]["current"] == []
    finally:
        hotkeys.reset()


# -- bundle journey on an in-process cluster ---------------------------------


def test_flight_recorder_bundle_journey(tmp_path, monkeypatch):
    from seaweedfs_tpu.master.server import MasterServer
    from seaweedfs_tpu.telemetry import hotkeys
    from seaweedfs_tpu.volume.server import VolumeServer

    monkeypatch.setenv("SEAWEEDFS_TPU_DEBUG_BUNDLE_RETAIN", "2")
    hotkeys.reset()
    debug_dir = tmp_path / "debug-bundles"
    master = MasterServer(ip="127.0.0.1", port=free_port(),
                          pulse_seconds=0.5, debug_dir=str(debug_dir))
    master.start()
    vol_dir = tmp_path / "vol"
    vol_dir.mkdir()
    vs = VolumeServer(
        directories=[str(vol_dir)],
        master_addresses=[f"127.0.0.1:{master.grpc_port}"],
        ip="127.0.0.1", port=free_port(), pulse_seconds=0.5,
        max_volume_count=8)
    vs.start()
    base = f"http://127.0.0.1:{master.port}"
    try:
        _wait(lambda: master.topo.nodes, 15, "node registered")
        _get_json(f"{base}/vol/grow?count=2")
        a = _get_json(f"{base}/dir/assign?count=1")
        req = urllib.request.Request(
            f"http://{a['url']}/{a['fid']}", data=b"x" * 256,
            headers={"Content-Type": "application/octet-stream"},
            method="POST")
        urllib.request.urlopen(req, timeout=10).read()
        urllib.request.urlopen(
            f"http://{a['url']}/{a['fid']}", timeout=10).read()

        # the GET fed the needle dimension; visible per node and merged
        hot = _get_json(f"http://{a['url']}/debug/hot")
        needle_keys = {e["key"] for e in hot["dims"]["needle"]["current"]}
        assert a["fid"] in needle_keys
        merged = _get_json(f"{base}/cluster/hot?n=16")
        assert a["fid"] in {e["key"]
                            for e in merged["dims"]["needle"]["current"]}
        assert f"127.0.0.1:{vs.port}" in merged["nodes"]
        assert _get_json(f"{base}/cluster/hot")  # default n
        with pytest.raises(urllib.error.HTTPError) as ei:
            _get_json(f"{base}/cluster/hot?n=0")
        assert ei.value.code == 400

        # manual capture over HTTP
        meta = _get_json(f"{base}/cluster/debug/capture", timeout=30)
        assert meta["trigger"] == "manual" and meta["sizeBytes"] > 0
        assert f"127.0.0.1:{vs.port}" in meta["nodes"]
        assert f"127.0.0.1:{master.port}" in meta["nodes"]

        doc = _get_json(f"{base}/cluster/debug")
        assert doc["debugDir"] == str(debug_dir) and doc["retain"] == 2
        assert [b["name"] for b in doc["bundles"]] == [meta["name"]]

        bundle = _get_json(f"{base}/cluster/debug?bundle={meta['name']}")
        assert bundle["trigger"] == "manual"
        vol_sections = bundle["nodes"][f"127.0.0.1:{vs.port}"]
        assert "seaweedfs_" in vol_sections["metrics"]
        assert "traces" in vol_sections["spans"]
        assert "windows" in vol_sections["profile"]
        assert a["fid"] in json.dumps(vol_sections["hot"])
        assert "states" in bundle["cluster"]["sloStates"]
        assert "lifecycle" in bundle["cluster"]

        # unknown + traversal-shaped names are rejected, not served
        with pytest.raises(urllib.error.HTTPError) as ei:
            _get_json(f"{base}/cluster/debug?bundle=bundle-nope")
        assert ei.value.code == 404
        assert master.flight.bundle("../../etc/passwd") is None
        assert master.flight.bundle("bundle-x/../y") is None

        # retention: captures 2..3 prune down to the newest 2
        for _ in range(2):
            time.sleep(1.1)  # distinct second-resolution bundle stamps
            _get_json(f"{base}/cluster/debug/capture", timeout=30)
        names = [b["name"] for b in master.flight.list_bundles()]
        assert len(names) == 2 and meta["name"] not in names

        # single-flight: 409 while a capture holds the lock
        assert master.flight._capture_lock.acquire(blocking=False)
        try:
            with pytest.raises(urllib.error.HTTPError) as ei:
                _get_json(f"{base}/cluster/debug/capture")
            assert ei.value.code == 409
        finally:
            master.flight._capture_lock.release()

        # /cluster/alerts lists the bundles alongside the history
        alerts = _get_json(f"{base}/cluster/alerts")
        assert [b["name"] for b in alerts["debugBundles"]] == names
    finally:
        vs.stop()
        master.stop()
        hotkeys.reset()


def test_flight_recorder_memory_ring_and_sink_gating(tmp_path):
    """No -debugDir: bundles land in a bounded in-memory ring.  The SLO
    sink only captures on firing transitions and honors the cooldown."""
    from seaweedfs_tpu.master.server import MasterServer

    master = MasterServer(ip="127.0.0.1", port=free_port(),
                          pulse_seconds=0.5)
    master.start()
    try:
        fr = master.flight
        assert fr.debug_dir == "" and fr.list_bundles() == []
        fr.cooldown_s = 3600.0

        fr.sink({"state": "ok", "slo": "availability"})
        fr.sink({"state": "pending", "slo": "availability"})
        time.sleep(0.3)
        assert fr.list_bundles() == []  # non-firing never captures

        fr.sink({"state": "firing", "slo": "availability",
                 "severity": "page", "exemplars": []})
        _wait(lambda: len(fr.list_bundles()) == 1, 20, "sink capture")
        fr.sink({"state": "firing", "slo": "availability",
                 "severity": "page", "exemplars": []})
        time.sleep(0.5)
        assert len(fr.list_bundles()) == 1  # cooldown coalesced

        name = fr.list_bundles()[0]["name"]
        doc = fr.bundle(name)
        assert doc["trigger"] == "alert"
        assert doc["alert"]["slo"] == "availability"

        # ring is bounded at retain even without a directory
        fr.cooldown_s = 0.0
        for _ in range(fr.retain + 2):
            fr.capture(trigger="manual")
        assert len(fr.list_bundles()) == fr.retain
    finally:
        master.stop()


# -- chaos: alert-triggered auto-capture under zipf load ---------------------

PULSE_S = 3.0
WINDOW_SCALE = 0.005
CANARY_TICK_S = 0.3
SLO_TICK_S = 0.4


def _spawn_volume(tmp_path, i, master_port):
    d = tmp_path / f"vol{i}"
    d.mkdir()
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    port = free_port()
    proc = subprocess.Popen(
        [sys.executable, "-m", "seaweedfs_tpu", "volume",
         "-dir", str(d), "-mserver", f"127.0.0.1:{master_port}",
         "-ip", "127.0.0.1", "-port", str(port),
         "-rack", f"rack{i % 2}", "-max", "30"],
        cwd=str(tmp_path), env=env,
        stdout=subprocess.DEVNULL, stderr=subprocess.STDOUT)
    return proc, f"127.0.0.1:{port}"


class _ZipfLoad:
    """Background GET load, zipf-skewed over fids, tallying statuses."""

    def __init__(self, fids):
        self.fids = fids  # [(fid, url)], rank 0 hottest
        self.stop = threading.Event()
        self.codes: list[int] = []
        self.errors: list[str] = []
        self._t = threading.Thread(target=self._run, daemon=True)

    def _run(self):
        i = 0
        while not self.stop.is_set():
            # deterministic zipf-ish schedule: rank 0 gets ~half the hits
            rank = 0
            step = i
            while step % 2 == 1 and rank < len(self.fids) - 1:
                rank += 1
                step //= 2
            fid, url = self.fids[rank]
            try:
                with urllib.request.urlopen(
                        f"http://{url}/{fid}", timeout=10) as r:
                    self.codes.append(r.status)
            except urllib.error.HTTPError as e:
                self.codes.append(e.code)
            except Exception as e:  # noqa: BLE001 — tallied, asserted on
                self.errors.append(str(e))
            i += 1
            time.sleep(0.02)

    def start(self):
        self._t.start()

    def finish(self):
        self.stop.set()
        self._t.join(timeout=10)


@pytest.mark.chaos
def test_chaos_page_auto_captures_bundle(tmp_path, monkeypatch):
    """Kill a volume-holding node under canary + zipf-hot client load:
    the availability page fires, the flight recorder auto-captures a
    bundle covering every live node with the alert's exemplar trace
    pinned and the hot needle named — and the client load (which never
    touched the victim) sees zero 5xx."""
    from seaweedfs_tpu.master.server import MasterServer
    from seaweedfs_tpu.telemetry import hotkeys

    monkeypatch.setenv("SEAWEEDFS_TPU_DEBUG_BUNDLE_COOLDOWN_S", "0")
    hotkeys.reset()
    debug_dir = tmp_path / "debug-bundles"
    master = MasterServer(
        ip="127.0.0.1", port=free_port(), pulse_seconds=PULSE_S,
        slo_interval=SLO_TICK_S, canary_interval=0.0,
        slo_window_scale=WINDOW_SCALE, debug_dir=str(debug_dir))
    master.canary.timeout_s = 5.0
    master.start()
    procs, load = [], None
    try:
        nodes = []
        for i in range(4):
            proc, addr = _spawn_volume(tmp_path, i, master.port)
            procs.append(proc)
            nodes.append(addr)
        _wait(lambda: len(master.topo.nodes) == 4, 30,
              "4 volume servers registered")

        def covered():
            with master.topo.lock:
                return sum(1 for n in master.topo.nodes.values()
                           if n.volumes) == 4

        _get_json(f"http://127.0.0.1:{master.port}/vol/grow?count=10")
        for _ in range(8):
            deadline = time.time() + 6
            while time.time() < deadline and not covered():
                time.sleep(0.3)
            if covered():
                break
            _get_json(f"http://127.0.0.1:{master.port}/vol/grow?count=4")
        _wait(covered, 10, "every node holds a volume")

        fids = []
        for _ in range(24):
            a = _get_json(
                f"http://127.0.0.1:{master.port}/dir/assign?count=1")
            body = os.urandom(1024)
            req = urllib.request.Request(
                f"http://{a['url']}/{a['fid']}", data=body,
                headers={"Content-Type": "application/octet-stream"},
                method="POST")
            urllib.request.urlopen(req, timeout=10).read()
            fids.append((a["fid"], a["url"]))

        # victim: a volume-holding node that serves NONE of the loaded
        # fids — client traffic must survive the kill untouched
        by_url: dict[str, list] = {}
        for fid, url in fids:
            by_url.setdefault(url, []).append(fid)
        victim_addr = next(n for n in nodes
                           if len(by_url.get(n, [])) <= min(
                               len(v) for v in by_url.values()))
        survivor_fids = [(f, u) for f, u in fids if u != victim_addr]
        assert len(survivor_fids) >= 4

        master.canary.interval_s = CANARY_TICK_S
        master.canary.start()

        # clean baseline: error-free for a full long burn window, so the
        # kill below is the FIRST burn source and the firing transition
        # (which triggers the auto-capture) is unambiguous
        def error_count():
            from seaweedfs_tpu.stats.metrics import REGISTRY

            total = 0.0
            for name, v in REGISTRY.snapshot_samples(max_samples=1 << 20):
                if (name.startswith("seaweedfs_canary_probe_total")
                        and 'result="error"' in name):
                    total += v
            return total

        long_window_s = 3600.0 * WINDOW_SCALE
        last_count, last_change = error_count(), time.time()
        deadline = time.time() + 90
        while time.time() - last_change < long_window_s + 1.0:
            if time.time() > deadline:
                raise TimeoutError("canary error-free baseline")
            time.sleep(0.5)
            cur = error_count()
            if cur != last_count:
                last_count, last_change = cur, time.time()

        load = _ZipfLoad(survivor_fids)
        load.start()
        time.sleep(1.0)

        hist_idx = len(master.slo.alert_history)
        pre_bundles = {b["name"] for b in master.flight.list_bundles()}
        victim = procs[nodes.index(victim_addr)]
        victim.send_signal(signal.SIGKILL)
        victim.wait(timeout=10)

        def page_fired():
            return any(h["severity"] == "page" and h["state"] == "firing"
                       and h["slo"] == "availability"
                       for h in list(master.slo.alert_history)[hist_idx:])

        fast_window_s = 300.0 * WINDOW_SCALE
        _wait(page_fired, 3 * PULSE_S + fast_window_s + 15.0,
              "availability page alert")

        # the firing transition auto-captures a bundle in the background
        def alert_bundle():
            for b in master.flight.list_bundles():
                if "-alert-" in b["name"] and b["name"] not in pre_bundles:
                    return master.flight.bundle(b["name"])
            return None

        _wait(lambda: alert_bundle() is not None, 30,
              "alert-triggered bundle capture")
        bundle = alert_bundle()

        # covers every live node (victim may appear with scrape errors)
        survivors = [n for n in nodes if n != victim_addr]
        for addr in survivors + [f"127.0.0.1:{master.port}"]:
            assert addr in bundle["nodes"], sorted(bundle["nodes"])
            assert "seaweedfs_" in bundle["nodes"][addr].get(
                "metrics", ""), addr

        # the alert's exemplar trace id is pinned in the bundle's spans
        alert = bundle["alert"]
        assert alert["slo"] == "availability"
        assert alert.get("exemplars"), alert
        tid = alert["exemplars"][0]["traceId"]
        stitched = bundle.get("exemplarTrace", {})
        assert stitched.get("traceId") == tid
        assert stitched.get("spans"), stitched

        # hot-key tables name the zipf-hot needle on its serving node
        hot_fid, hot_url = survivor_fids[0]
        hot_doc = bundle["nodes"][hot_url]["hot"]
        seen = {e["key"]
                for w in ("current", "previous")
                for e in hot_doc["dims"]["needle"][w]}
        assert hot_fid in seen, (hot_fid, sorted(seen)[:8])
        # ... and the federated live view agrees
        merged = _get_json(
            f"http://127.0.0.1:{master.port}/cluster/hot?n=32")
        merged_keys = {e["key"]
                       for w in ("current", "previous")
                       for e in merged["dims"]["needle"][w]}
        assert hot_fid in merged_keys

        # client traffic never saw a server error across kill + capture
        load.finish()
        assert load.codes and all(c < 500 for c in load.codes), (
            sorted(set(load.codes)))
        assert not load.errors, load.errors[:3]
    finally:
        if load is not None:
            load.finish()
        for p in procs:
            if p.poll() is None:
                p.kill()
        for p in procs:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                pass
        master.stop()
        hotkeys.reset()
