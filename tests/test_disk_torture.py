"""Crash-recovery torture harness (ISSUE 14): SIGKILL a writer process
at randomized write points, remount, and verify the durability
invariant — every acked write readable byte-identical, every acked
delete still deleted, torn tails healed, .idx/.dat consistent — cycle
after cycle.

The child process appends (and deletes) needles through the real
Volume write path; a monkeypatched write hook lands a RANDOM PREFIX of
some Nth raw write (dat blob, pad, or idx entry — all write paths can
tear) and then SIGKILLs itself, which is exactly the state a power-cut
mid-write leaves in the page cache.  Acks are written (fsync'd) only
after `Volume.sync()` returned, so the acked set is the durability
contract.

Tier-1 runs a handful of cycles; the chaos-marked run does
SEAWEEDFS_TPU_TORTURE_CYCLES (default 100, CI caps via env).
"""

from __future__ import annotations

import hashlib
import os
import signal
import subprocess
import sys

import pytest

from seaweedfs_tpu.storage import types as t
from seaweedfs_tpu.storage.needle import Needle, actual_size
from seaweedfs_tpu.storage.volume import Volume

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# the torture writer: argv = repo, dir, seed, kill_after, start_id, writes
CHILD = r"""
import os, random, signal, sys

repo, dirpath, seed, kill_after, start_id, n_writes = sys.argv[1:7]
sys.path.insert(0, repo)
seed, kill_after = int(seed), int(kill_after)
start_id, n_writes = int(start_id), int(n_writes)
rng = random.Random(seed)

from seaweedfs_tpu.storage import backend as B
from seaweedfs_tpu.storage import idx as I
from seaweedfs_tpu.storage.needle import Needle
from seaweedfs_tpu.storage.volume import Volume

count = [0]

def maybe_kill(f, offset, data):
    count[0] += 1
    if count[0] == kill_after:
        j = rng.randrange(0, len(data) + 1)
        if j:
            f.seek(offset)
            f.write(data[:j])
            f.flush()  # the torn prefix reaches the page cache
        os.kill(os.getpid(), signal.SIGKILL)

_orig_write_at = B.DiskFile.write_at
def chaos_write_at(self, offset, data):
    with self._lock:
        maybe_kill(self._f, offset, data)
    return _orig_write_at(self, offset, data)
B.DiskFile.write_at = chaos_write_at

_orig_idx_write = I.IndexWriter._write
def chaos_idx_write(self, entry):
    maybe_kill(self._f, self._f.tell(), entry)
    return _orig_idx_write(self, entry)
I.IndexWriter._write = chaos_idx_write

def payload(i):
    import hashlib
    seedb = hashlib.sha256(b"needle-%d" % i).digest()
    return (seedb * (1 + (i * 37) % 40))[: 32 + (i * 131) % 1200]

v = Volume(dirpath, "", 1)  # remount: the healer runs under fire too
ack = open(os.path.join(dirpath, "acks.log"), "a")
live = []
for k in range(n_writes):
    i = start_id + k
    n = Needle(cookie=1234, id=i, data=payload(i))
    v.append_needle(n)
    v.sync()
    ack.write("put %d\n" % i)
    ack.flush(); os.fsync(ack.fileno())
    live.append(i)
    if k % 5 == 4 and len(live) > 2:
        dead = live.pop(rng.randrange(0, len(live) - 1))
        # intent BEFORE the mutation: a kill mid-delete leaves the
        # needle in either state (the delete was never acked), and the
        # verifier must not demand liveness for it
        ack.write("deli %d\n" % dead)
        ack.flush(); os.fsync(ack.fileno())
        v.delete_needle(dead)
        v.sync()
        ack.write("del %d\n" % dead)
        ack.flush(); os.fsync(ack.fileno())
v.close()
print("FINISHED")
"""


# the group-commit torture writer: N concurrent threads append through
# the batch-fsync barrier and ack ONLY after append_needle returns (no
# explicit sync — the barrier fsync IS the durability edge), then the
# process SIGKILLs itself mid-stream.  The kill is ack-count-triggered
# (a wall-clock timer races host speed: an idle box drains every write
# before the timer fires, a loaded one starves it), plus a 0-2ms jitter
# so the kill also lands INSIDE a barrier flush, not only between them.
# argv = repo, dir, base_id, n_threads, per_thread, kill_at, jitter_us
BATCH_CHILD = r"""
import os, random, signal, sys, threading, time

repo, dirpath, base_id, n_threads, per_thread, kill_at, jitter_us = sys.argv[1:8]
sys.path.insert(0, repo)
base_id, n_threads = int(base_id), int(n_threads)
per_thread, kill_at = int(per_thread), int(kill_at)
jitter_us = int(jitter_us)

from seaweedfs_tpu.storage.needle import Needle
from seaweedfs_tpu.storage.volume import Volume

def payload(i):
    import hashlib
    seedb = hashlib.sha256(b"needle-%d" % i).digest()
    return (seedb * (1 + (i * 37) % 40))[: 32 + (i * 131) % 1200]

v = Volume(dirpath, "", 1)
assert v.durability == "batch", v.durability
ack = open(os.path.join(dirpath, "acks.log"), "a")
ack_lock = threading.Lock()
acked = [0]

def writer(tid):
    for k in range(per_thread):
        i = base_id + tid * per_thread + k
        n = Needle(cookie=1234, id=i, data=payload(i))
        try:
            v.append_needle(n)  # parks on the flush barrier
        except Exception:
            return
        with ack_lock:
            ack.write("put %d\n" % i)
            ack.flush(); os.fsync(ack.fileno())
            acked[0] += 1

def killer():
    while acked[0] < kill_at:
        time.sleep(0.0002)
    time.sleep(jitter_us / 1e6)
    os.kill(os.getpid(), signal.SIGKILL)

threading.Thread(target=killer, daemon=True).start()
threads = [threading.Thread(target=writer, args=(t,), daemon=True)
           for t in range(n_threads)]
for th in threads:
    th.start()
time.sleep(10.0)  # fallback: the killer thread should always win
os.kill(os.getpid(), signal.SIGKILL)
"""


def _payload(i: int) -> bytes:
    seedb = hashlib.sha256(b"needle-%d" % i).digest()
    return (seedb * (1 + (i * 37) % 40))[: 32 + (i * 131) % 1200]


def _parse_acks(path: str) -> tuple[set, set, set]:
    """-> (acked-live, acked-deleted, delete-in-flight) needle ids."""
    live, deleted, in_flight = set(), set(), set()
    if not os.path.exists(path):
        return live, deleted, in_flight
    with open(path) as f:
        for line in f:
            parts = line.split()
            if len(parts) != 2:
                continue  # a torn ack line acks nothing
            op, i = parts[0], int(parts[1])
            if op == "put":
                live.add(i)
                deleted.discard(i)
            elif op == "deli":
                in_flight.add(i)
            elif op == "del":
                deleted.add(i)
                live.discard(i)
                in_flight.discard(i)
    return live, deleted, in_flight


def _verify_cycle(dirpath: str, cycle: int) -> None:
    """Remount and prove the durability invariant."""
    live, deleted, in_flight = _parse_acks(
        os.path.join(dirpath, "acks.log"))
    v = Volume(dirpath, "", 1)  # runs the load-time healer
    try:
        for i in sorted(live):
            if i in in_flight:
                # an unacked delete was issued against this acked put:
                # either state is legal, but a surviving copy must
                # still be byte-identical
                try:
                    n = v.read_needle(i)
                except KeyError:
                    continue
                assert n.data == _payload(i)
                continue
            n = v.read_needle(i)
            assert n.data == _payload(i), (
                f"cycle {cycle}: acked needle {i} not byte-identical")
        for i in sorted(deleted):
            with pytest.raises(KeyError):
                v.read_needle(i)
        # .idx/.dat consistency: every live index entry parses from the
        # .dat at its offset with a matching id and a clean CRC
        dat_size = v.content_size
        for nv in v.needle_map.items_ascending():
            end = nv.offset + actual_size(max(nv.size, 0), v.version)
            assert end <= dat_size, (
                f"cycle {cycle}: entry {nv.key:x} beyond .dat")
            blob = v._dat.read_at(
                nv.offset, actual_size(max(nv.size, 0), v.version))
            n = Needle.from_bytes(blob, v.version)  # CRC-verifies
            assert n.id == nv.key
        # the healed index is aligned
        idx_size = os.path.getsize(v.file_name() + ".idx")
        assert idx_size % t.NEEDLE_MAP_ENTRY_SIZE == 0
        # and the volume still takes (and serves) new writes
        probe_id = 10_000_000 + cycle
        v.append_needle(Needle(cookie=1, id=probe_id, data=b"probe"))
        assert v.read_needle(probe_id).data == b"probe"
        assert v.delete_needle(probe_id) > 0
    finally:
        v.close()


def _run_torture(tmp_path, cycles: int, seed: int = 0) -> int:
    """-> how many cycles actually got killed (vs finished)."""
    import random

    rng = random.Random(seed)
    dirpath = str(tmp_path)
    start_id = 1
    kills = 0
    for cycle in range(cycles):
        kill_after = rng.randrange(1, 40)
        n_writes = rng.randrange(5, 25)
        proc = subprocess.run(
            [sys.executable, "-c", CHILD, REPO, dirpath,
             str(seed * 10007 + cycle), str(kill_after),
             str(start_id), str(n_writes)],
            capture_output=True, text=True, timeout=60,
            env={**os.environ, "JAX_PLATFORMS": "cpu",
                 "SEAWEEDFS_TPU_NEEDLE_CACHE_MB": "0"},
        )
        if proc.returncode == -signal.SIGKILL:
            kills += 1
        else:
            assert proc.returncode == 0, (
                f"cycle {cycle}: child failed\n{proc.stderr[-2000:]}")
            assert "FINISHED" in proc.stdout
        _verify_cycle(dirpath, cycle)
        start_id += n_writes
    return kills


def _run_batch_torture(tmp_path, cycles: int, seed: int = 0) -> tuple[int, int]:
    """SIGKILL mid-group-commit: concurrent writers ack only after the
    flush-barrier fsync, the process dies at a random instant, and the
    remount must serve every acked write byte-identical with the torn
    unacked tail rolled back by the load-time healer.

    -> (cycles killed mid-flight, total acked writes)."""
    import random

    rng = random.Random(seed)
    dirpath = str(tmp_path)
    base_id = 1
    mid_flight = 0
    total_acked = 0
    for cycle in range(cycles):
        n_threads = rng.randrange(3, 7)
        per_thread = rng.randrange(8, 20)
        total = n_threads * per_thread
        # die after a random prefix of the acks (never the whole run),
        # with up to 2ms extra so some kills land inside a barrier
        kill_at = rng.randrange(1, max(2, total * 2 // 3))
        jitter_us = rng.randrange(0, 2000)
        proc = subprocess.run(
            [sys.executable, "-c", BATCH_CHILD, REPO, dirpath,
             str(base_id), str(n_threads), str(per_thread),
             str(kill_at), str(jitter_us)],
            capture_output=True, text=True, timeout=60,
            env={**os.environ, "JAX_PLATFORMS": "cpu",
                 "SEAWEEDFS_TPU_NEEDLE_CACHE_MB": "0",
                 "SEAWEEDFS_TPU_DURABILITY": "batch",
                 # small batches + a real delay window so the kill lands
                 # between barrier flushes, not only inside one
                 "SEAWEEDFS_TPU_FSYNC_MAX_BATCH": "8",
                 "SEAWEEDFS_TPU_FSYNC_MAX_DELAY_MS": "2"},
        )
        assert proc.returncode == -signal.SIGKILL, (
            f"cycle {cycle}: batch child exited {proc.returncode}\n"
            f"{proc.stderr[-2000:]}")
        live, _, _ = _parse_acks(os.path.join(dirpath, "acks.log"))
        acked_now = len(live)
        if acked_now - total_acked < n_threads * per_thread:
            mid_flight += 1
        total_acked = acked_now
        _verify_cycle(dirpath, cycle)
        base_id += n_threads * per_thread
    return mid_flight, total_acked


def test_torture_smoke(tmp_path):
    """Tier-1: a handful of randomized kill-point cycles."""
    kills = _run_torture(tmp_path, cycles=6, seed=1)
    assert kills >= 1  # the harness must actually be killing writers


def test_torture_batch_commit_smoke(tmp_path):
    """Tier-1: SIGKILL mid-group-commit — acked batch writes survive
    remount byte-identical, unacked writes roll back (ISSUE 18)."""
    mid_flight, acked = _run_batch_torture(tmp_path, cycles=5, seed=3)
    assert mid_flight >= 1  # the kill must interrupt in-flight batches
    assert acked >= 1       # and some writes must have been acked first


@pytest.mark.chaos
def test_torture_hundred_cycles(tmp_path):
    """The acceptance run: >= 100 randomized SIGKILL+remount cycles
    with every durability invariant checked per cycle."""
    cycles = int(os.environ.get("SEAWEEDFS_TPU_TORTURE_CYCLES", "100"))
    kills = _run_torture(tmp_path, cycles=cycles, seed=2)
    # the vast majority of cycles must die mid-write, not run to finish
    assert kills >= cycles // 2


@pytest.mark.chaos
def test_torture_batch_commit_cycles(tmp_path):
    """Chaos run of the group-commit kill leg: many randomized
    SIGKILL-mid-batch cycles, durability invariant checked per cycle."""
    cycles = int(os.environ.get("SEAWEEDFS_TPU_TORTURE_BATCH_CYCLES", "30"))
    mid_flight, acked = _run_batch_torture(tmp_path, cycles=cycles, seed=4)
    assert mid_flight >= cycles // 3
    assert acked >= cycles  # every cycle must land some durable writes
