"""Fault-injection framework tests: registry semantics, env parsing, the
injection modes, match scoping, and the /debug/faults HTTP surface.
"""

import json
import time
import urllib.error
import urllib.request

import pytest

from seaweedfs_tpu.util import faultpoint
from seaweedfs_tpu.util.faultpoint import FaultInjected, FaultRegistry


@pytest.fixture(autouse=True)
def _clean_global_faults():
    faultpoint.clear_fault("all")
    yield
    faultpoint.clear_fault("all")


def _fired(point: str) -> float:
    return faultpoint.FAULT_COUNTER.labels(point).value


# -- registry semantics ------------------------------------------------------


def test_unarmed_point_is_passthrough():
    r = FaultRegistry()
    r.register("p.a")
    assert r.inject("p.a", data=b"xyz") == b"xyz"
    assert r.inject("p.a") is None


def test_error_mode_counts_down():
    r = FaultRegistry()
    r.set("p.b", "error", count=2)
    for _ in range(2):
        with pytest.raises(FaultInjected):
            r.inject("p.b")
    # exhausted: back to passthrough
    assert r.inject("p.b", data=b"ok") == b"ok"


def test_delay_mode_sleeps_then_passes_data():
    r = FaultRegistry()
    r.set("p.c", "delay", delay=0.05, count=1)
    t0 = time.perf_counter()
    assert r.inject("p.c", data=b"d") == b"d"
    assert time.perf_counter() - t0 >= 0.04
    # count exhausted: no further delay
    t0 = time.perf_counter()
    r.inject("p.c")
    assert time.perf_counter() - t0 < 0.04


def test_partial_mode_truncates():
    r = FaultRegistry()
    r.set("p.d", "partial")
    assert r.inject("p.d", data=b"12345678") == b"1234"
    # without data to truncate, partial degrades to an error
    with pytest.raises(FaultInjected):
        r.inject("p.d")


def test_match_scopes_to_context():
    r = FaultRegistry()
    r.set("p.e", "error", match="127.0.0.1:8081")
    # other servers pass through
    assert r.inject("p.e", ctx="127.0.0.1:8080", data=b"x") == b"x"
    with pytest.raises(FaultInjected):
        r.inject("p.e", ctx="127.0.0.1:8081")


def test_clear_disarms():
    r = FaultRegistry()
    r.set("p.f", "error")
    r.clear("p.f")
    assert r.inject("p.f", data=b"x") == b"x"
    r.set("p.g", "error")
    r.set("p.h", "error")
    r.clear("all")
    assert r.state()["armed"] == {}


def test_bad_mode_rejected():
    r = FaultRegistry()
    with pytest.raises(ValueError):
        r.set("p.i", "explode")


# -- env parsing -------------------------------------------------------------


def test_load_env_formats():
    r = FaultRegistry()
    r.load_env("a=error:3, b=delay:0.25, c=delay:0.1:2, d=error, ,junk")
    armed = r.state()["armed"]
    assert armed["a"] == {"mode": "error", "delay": 0.0, "remaining": 3,
                          "match": ""}
    assert armed["b"]["mode"] == "delay" and armed["b"]["delay"] == 0.25
    assert armed["b"]["remaining"] == -1
    assert armed["c"]["remaining"] == 2
    assert armed["d"]["remaining"] == -1
    assert "junk" not in armed


def test_load_env_bad_entries_skipped():
    r = FaultRegistry()
    r.load_env("x=delay:abc,y=error:1")
    armed = r.state()["armed"]
    assert "x" not in armed and "y" in armed


# -- metrics -----------------------------------------------------------------


def test_fault_counter_increments():
    before = _fired("p.metric")
    faultpoint.set_fault("p.metric", "error", count=1)
    with pytest.raises(FaultInjected):
        faultpoint.inject("p.metric")
    assert _fired("p.metric") == before + 1
    # passthrough (exhausted) does not count
    faultpoint.inject("p.metric")
    assert _fired("p.metric") == before + 1


# -- /debug/faults HTTP surface ---------------------------------------------


def test_debug_faults_endpoint_roundtrip(monkeypatch):
    import seaweedfs_tpu.operation.upload  # noqa: F401 - registers points
    from seaweedfs_tpu.stats.metrics import serve_metrics

    from helpers import free_port

    port = free_port()
    httpd = serve_metrics(port, host="127.0.0.1")
    base = f"http://127.0.0.1:{port}/debug/faults"
    try:
        # runtime arming is opt-in: without the flag, ?set= answers 403
        # (plain listing stays open, like /metrics)
        monkeypatch.delenv(faultpoint.ENABLE_VAR, raising=False)
        monkeypatch.delenv(faultpoint.ENV_VAR, raising=False)
        with pytest.raises(urllib.error.HTTPError) as exc_info:
            urllib.request.urlopen(f"{base}?set=x&mode=error", timeout=5)
        assert exc_info.value.code == 403
        with urllib.request.urlopen(base, timeout=5) as r:
            assert json.loads(r.read())["armed"] == {}

        monkeypatch.setenv(faultpoint.ENABLE_VAR, "1")
        # arm via query string
        with urllib.request.urlopen(
            f"{base}?set=volume.http.get&mode=error&count=3"
            "&match=127.0.0.1:9999", timeout=5,
        ) as r:
            state = json.loads(r.read())
        assert state["armed"]["volume.http.get"] == {
            "mode": "error", "delay": 0.0, "remaining": 3,
            "match": "127.0.0.1:9999",
        }
        # the registered points from module imports are listed
        assert "operation.upload" in state["registered"]

        # plain GET lists without mutating
        with urllib.request.urlopen(base, timeout=5) as r:
            state = json.loads(r.read())
        assert state["armed"]["volume.http.get"]["remaining"] == 3

        # clear
        with urllib.request.urlopen(f"{base}?clear=volume.http.get",
                                    timeout=5) as r:
            state = json.loads(r.read())
        assert state["armed"] == {}

        # bad numbers answer 400
        with pytest.raises(urllib.error.HTTPError) as exc_info:
            urllib.request.urlopen(f"{base}?set=x&mode=error&count=banana",
                                   timeout=5)
        assert exc_info.value.code == 400
    finally:
        httpd.shutdown()
        httpd.server_close()
