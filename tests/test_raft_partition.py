"""Partition chaos (ISSUE 17): the leader-fenced control plane under an
asymmetric network partition.

Test 1 — a 3-master quorum runs a mass repair (held open by a delay
fault) while clients assign continuously.  The leader is then cut off
from its peers via `raft.send` faults (volume servers still reach it —
the asymmetric case).  Proves: exactly one leader survives, the deposed
leader steps down via check-quorum and fences its executors, zero
duplicate fid across both sides' assign logs, volume servers reject a
stale-epoch batch rpc with the typed FAILED_PRECONDITION, the new
leader resumes the replicated journal's running jobs exactly-once (with
`resumed` markers), and the quorum side serves zero 5xx throughout.

Test 2 — the heartbeat failover regression: after the leader is
partitioned away, its volume servers re-register with the NEW leader
within an election-timeout budget (immediate leader re-resolve, not the
fixed rotation backoff).
"""

import json
import threading
import time
import urllib.error
import urllib.request

import grpc
import pytest

from seaweedfs_tpu.pb import rpc as rpclib
from seaweedfs_tpu.pb import volume_server_pb2 as vs_pb
from seaweedfs_tpu.util import faultpoint
from seaweedfs_tpu.volume.grpc_handlers import STALE_EPOCH_DETAIL

from helpers import free_port

N_SRV = 5
V = 6


def _start_masters(tmp_path, n=3):
    from seaweedfs_tpu.master.server import MasterServer

    ports = [free_port() for _ in range(n)]
    peers = [f"127.0.0.1:{p}" for p in ports]
    (tmp_path / "raft").mkdir(exist_ok=True)
    masters = []
    for i, p in enumerate(ports):
        jd = tmp_path / f"journal{i}"
        jd.mkdir()
        m = MasterServer(
            ip="127.0.0.1", port=p, peers=peers,
            raft_state_dir=str(tmp_path / "raft"),
            lifecycle_dir=str(jd), volume_size_limit_mb=64,
            pulse_seconds=0.5, repair_deadline_s=90.0,
            # collision-free ids across masters: duplicate-fid scanning
            # below asserts the whole pipeline, not sequencer luck
            sequencer="snowflake", sequencer_node_id=i + 1)
        m.start()
        masters.append(m)
    return masters


def _start_volume_servers(tmp_path, master_grpcs, n=N_SRV, pulse=0.5):
    from seaweedfs_tpu.volume.server import VolumeServer

    servers = []
    for i in range(n):
        d = tmp_path / f"vol{i}"
        d.mkdir()
        s = VolumeServer(
            directories=[str(d)], master_addresses=list(master_grpcs),
            ip="127.0.0.1", port=free_port(), pulse_seconds=pulse,
            rack=f"rack{i % 2}", data_center="dc1", max_volume_count=600)
        s.start()
        servers.append(s)
    return servers


def _wait_single_leader(masters, timeout=20.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        leaders = [m for m in masters if m.is_leader()]
        if len(leaders) == 1:
            return leaders[0]
        time.sleep(0.05)
    raise AssertionError("no single leader")


@pytest.mark.chaos
def test_chaos_asymmetric_partition_mid_mass_repair(tmp_path):
    from test_mass_repair_cluster import _stage_volumes

    masters = _start_masters(tmp_path)
    grpcs = [f"127.0.0.1:{m.grpc_port}" for m in masters]
    servers = []
    try:
        leader = _wait_single_leader(masters)
        quorum = [m for m in masters if m is not leader]
        old_epoch = leader.leader_epoch()
        assert old_epoch > 0

        servers = _start_volume_servers(tmp_path, grpcs)
        deadline = time.time() + 30
        while time.time() < deadline and len(leader.topo.nodes) < N_SRV:
            time.sleep(0.1)
        assert len(leader.topo.nodes) == N_SRV

        needles = _stage_volumes(
            tmp_path, servers, V,
            victim_sids=lambda v: [v % 14, (v + 1) % 14])
        deadline = time.time() + 30
        while time.time() < deadline and any(
                len(leader.topo.lookup_ec_shards(v)) < 14
                for v in range(1, V + 1)):
            time.sleep(0.2)
        assert all(len(leader.topo.lookup_ec_shards(v)) == 14
                   for v in range(1, V + 1))

        # -- concurrent assigns, recording every fid and every 5xx -----
        fids: list = []
        errs: list = []  # (t, port, code)
        stop = threading.Event()

        def hammer():
            while not stop.is_set():
                live = [m for m in masters if m.is_leader()]
                if not live:
                    time.sleep(0.05)  # election gap: no leader to ask
                    continue
                m = live[0]
                try:
                    with urllib.request.urlopen(
                            f"http://127.0.0.1:{m.port}/dir/assign",
                            timeout=20) as r:
                        doc = json.loads(r.read())
                        if "fid" in doc:
                            fids.append(doc["fid"])
                except urllib.error.HTTPError as e:
                    if e.code >= 500:
                        errs.append((time.time(), m.port, e.code))
                    e.close()
                except OSError:
                    pass  # connection-level, not a served 5xx
                time.sleep(0.02)

        t = threading.Thread(target=hammer, daemon=True)
        t.start()
        deadline = time.time() + 20
        while time.time() < deadline and len(fids) < 5:
            time.sleep(0.1)
        assert len(fids) >= 5, f"assigns never started: {errs}"

        # -- trigger mass repair, hold it open, partition the leader ---
        faultpoint.set_fault("repair.batch.source", "delay", delay=1.5)
        victim = servers[0]
        victim.stop()
        follower_journal = quorum[0].lifecycle.journal
        deadline = time.time() + 60
        running = []
        while time.time() < deadline and not running:
            # read the RUNNING records off a FOLLOWER's journal mirror:
            # the raft-replicated maintenance state, not the leader's
            # local file
            running = [j for j in follower_journal.jobs(("running",))
                       if j.get("transition") == "mass_repair"]
            time.sleep(0.05)
        assert running, "no mass_repair job replicated as running"

        t_cut = time.time()
        # cut the leader off from BOTH peers, both directions (its
        # address appears in the ctx as src or dst); volume servers
        # still reach it — the asymmetric case
        faultpoint.set_fault("raft.send", "error",
                             match=f"127.0.0.1:{leader.port}")
        faultpoint.clear_fault("repair.batch.source")

        new_leader = _wait_single_leader(quorum, timeout=20)
        new_epoch = new_leader.leader_epoch()
        assert new_epoch > old_epoch

        # check-quorum: the cut-off leader deposes itself
        deadline = time.time() + 10
        while time.time() < deadline and leader.is_leader():
            time.sleep(0.05)
        assert not leader.is_leader(), "partitioned leader never stepped down"
        assert sum(1 for m in masters if m.is_leader()) == 1

        # -- the repair completes exactly-once under the new leader ----
        survivors = servers[1:]

        def all_mounted():
            for v in range(1, V + 1):
                held: dict = {}
                for s in survivors:
                    for sid in s.store.status()["ec_volumes"].get(v, []):
                        held[sid] = held.get(sid, 0) + 1
                if sorted(held) != list(range(14)):
                    return False
                dup = {sid: c for sid, c in held.items() if c != 1}
                assert not dup, f"duplicate shard holders: vol {v} {dup}"
            return True

        deadline = time.time() + 120
        while time.time() < deadline and not all_mounted():
            time.sleep(0.5)
        assert all_mounted()

        mass = {j["key"]: j
                for j in new_leader.lifecycle.journal.jobs()
                if j.get("transition") == "mass_repair"}
        assert len(mass) == V, sorted(mass)
        assert all(j["state"] == "done" for j in mass.values()), mass
        assert any(j.get("resumed") for j in mass.values()), \
            "no resumed marker: the new leader never replayed the journal"

        # -- stale-epoch fencing: the deposed leader's rpc is refused --
        target = survivors[0]
        deadline = time.time() + 20
        while time.time() < deadline and target._leader_epoch < new_epoch:
            time.sleep(0.1)
        assert target._leader_epoch >= new_epoch
        stub = rpclib.volume_server_stub(
            f"127.0.0.1:{target.port + 10000}", timeout=10)
        with pytest.raises(grpc.RpcError) as ei:
            stub.VolumeEcShardsBatchRebuild(
                vs_pb.VolumeEcShardsBatchRebuildRequest(
                    leader_epoch=old_epoch,
                    jobs=[vs_pb.BatchRebuildJob(volume_id=1)]))
        assert ei.value.code() == grpc.StatusCode.FAILED_PRECONDITION
        assert STALE_EPOCH_DETAIL in (ei.value.details() or "")
        # epoch 0 (shell operator) stays unfenced: vacuum-check passes
        stub.VacuumVolumeCheck(vs_pb.VacuumVolumeCheckRequest(volume_id=1))

        # -- heal: the old leader rejoins as a follower and converges --
        faultpoint.clear_fault("raft.send")
        deadline = time.time() + 20
        while time.time() < deadline and (
                leader.is_leader()
                or leader.leader() != f"127.0.0.1:{new_leader.port}"):
            time.sleep(0.1)
        assert not leader.is_leader()
        assert leader.leader() == f"127.0.0.1:{new_leader.port}"

        stop.set()
        t.join(timeout=20)
        # zero duplicate fid across BOTH sides' assign logs
        assert len(fids) == len(set(fids)), "duplicate fid assigned"
        # zero 5xx served by the quorum side (the minority-side leader
        # may legitimately fail a grow mid-partition; quorum must not)
        quorum_ports = {m.port for m in quorum}
        bad = [e for e in errs
               if e[1] in quorum_ports or e[0] < t_cut]
        assert not bad, f"5xx on the quorum side: {bad}"

        # byte identity through the healed cluster
        reader = survivors[0]
        for v in (1, V):
            for fid, want in list(needles[v].items())[:3]:
                with urllib.request.urlopen(
                        f"http://127.0.0.1:{reader.port}/{fid}",
                        timeout=15) as r:
                    assert r.read() == want, f"corrupt read {fid}"
    finally:
        faultpoint.clear_fault("raft.send")
        faultpoint.clear_fault("repair.batch.source")
        for s in servers[1:]:
            s.stop()
        for m in masters:
            try:
                m.stop()
            except Exception:
                pass


@pytest.mark.chaos
def test_chaos_vs_reregisters_with_new_leader_quickly(tmp_path):
    """Satellite regression: a volume server heartbeating a leader that
    gets partitioned away re-registers with the NEW leader within an
    election-timeout budget — the deposed leader ends the stream, the
    server unpins and chases without the old fixed rotation backoff."""
    masters = _start_masters(tmp_path)
    grpcs = [f"127.0.0.1:{m.grpc_port}" for m in masters]
    vs = None
    try:
        leader = _wait_single_leader(masters)
        quorum = [m for m in masters if m is not leader]
        (vs,) = _start_volume_servers(tmp_path, grpcs, n=1, pulse=0.2)
        deadline = time.time() + 20
        while time.time() < deadline and not leader.topo.nodes:
            time.sleep(0.1)
        assert leader.topo.nodes

        faultpoint.set_fault("raft.send", "error",
                             match=f"127.0.0.1:{leader.port}")
        new_leader = _wait_single_leader(quorum, timeout=20)
        t0 = time.time()
        # budget: one election timeout (the deposed leader's check-
        # quorum step-down, <=0.8s) + two 0.2s pulses to detect the
        # ended stream and rebeat, + scheduling slack on a loaded host
        deadline = t0 + 3.0
        while time.time() < deadline and not new_leader.topo.nodes:
            time.sleep(0.02)
        elapsed = time.time() - t0
        assert new_leader.topo.nodes, \
            f"VS did not re-register within {elapsed:.1f}s"
        assert f"127.0.0.1:{vs.port}" in new_leader.topo.nodes
    finally:
        faultpoint.clear_fault("raft.send")
        if vs is not None:
            vs.stop()
        for m in masters:
            try:
                m.stop()
            except Exception:
                pass
