"""Systematic concurrency stress tier (SURVEY §5.2).

The reference leans on `go test -race`; Python has no race detector, so
this tier hammers the lock-based invariants directly: parallel writers/
readers/deleters on one volume, mixed filer namespace mutation, and
vacuum racing live appends.  Each test bounds its runtime (~seconds) and
asserts full consistency afterwards.
"""

from __future__ import annotations

import concurrent.futures
import json
import random
import threading
import time
import urllib.request

import pytest

from helpers import free_port


@pytest.fixture(scope="module")
def stack(tmp_path_factory):
    from seaweedfs_tpu.filer.server import FilerServer
    from seaweedfs_tpu.master.server import MasterServer
    from seaweedfs_tpu.volume.server import VolumeServer

    master = MasterServer(ip="127.0.0.1", port=free_port(),
                          volume_size_limit_mb=64)
    master.start()
    vs = VolumeServer(
        directories=[str(tmp_path_factory.mktemp("stressvol"))],
        master_addresses=[f"127.0.0.1:{master.grpc_port}"],
        ip="127.0.0.1", port=free_port(), pulse_seconds=0.5,
        max_volume_count=100,
    )
    vs.start()
    deadline = time.time() + 15
    while time.time() < deadline and len(master.topo.nodes) < 1:
        time.sleep(0.1)
    filer = FilerServer(
        masters=[f"127.0.0.1:{master.grpc_port}"],
        ip="127.0.0.1", port=free_port(), store="memory", max_mb=1,
    )
    filer.start()
    yield master, vs, filer
    filer.stop()
    vs.stop()
    master.stop()


def _assign(master) -> dict:
    with urllib.request.urlopen(
            f"http://127.0.0.1:{master.port}/dir/assign", timeout=10) as r:
        return json.loads(r.read())


def _post(url: str, data: bytes) -> dict:
    boundary = "stressb"
    body = (f"--{boundary}\r\nContent-Disposition: form-data; "
            f'name="file"; filename="s.bin"\r\n\r\n').encode() + data + \
        f"\r\n--{boundary}--\r\n".encode()
    req = urllib.request.Request(
        f"http://{url}", data=body, method="POST",
        headers={"Content-Type":
                 f"multipart/form-data; boundary={boundary}"})
    with urllib.request.urlopen(req, timeout=20) as r:
        return json.loads(r.read() or b"{}")


def test_parallel_volume_writers_and_readers(stack):
    """32 threads × assign+write, interleaved with reads: every blob must
    come back byte-exact; the needle map never loses an entry."""
    master, vs, _ = stack
    n = 64
    payloads = {}
    lock = threading.Lock()

    def write_one(i: int):
        a = _assign(master)
        data = (f"payload-{i}-".encode()) * 50
        _post(f"{a['url']}/{a['fid']}", data)
        with lock:
            payloads[a["fid"]] = data
        return a["fid"]

    with concurrent.futures.ThreadPoolExecutor(max_workers=16) as ex:
        fids = list(ex.map(write_one, range(n)))
    assert len(set(fids)) == n

    def read_one(fid: str) -> bool:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{vs.port}/{fid}", timeout=20) as r:
            return r.read() == payloads[fid]

    with concurrent.futures.ThreadPoolExecutor(max_workers=16) as ex:
        assert all(ex.map(read_one, fids))


def test_mixed_write_delete_read_storm(stack):
    """Concurrent writers + deleters on the same volumes: reads after the
    storm agree exactly with the surviving set."""
    master, vs, _ = stack
    alive: dict[str, bytes] = {}
    dead: list[str] = []
    lock = threading.Lock()

    def worker(i: int):
        a = _assign(master)
        data = f"storm-{i}".encode() * 20
        _post(f"{a['url']}/{a['fid']}", data)
        if i % 3 == 0:
            req = urllib.request.Request(
                f"http://{a['url']}/{a['fid']}", method="DELETE")
            with urllib.request.urlopen(req, timeout=20):
                pass
            with lock:
                dead.append(a["fid"])
        else:
            with lock:
                alive[a["fid"]] = data

    with concurrent.futures.ThreadPoolExecutor(max_workers=16) as ex:
        list(ex.map(worker, range(48)))

    for fid, data in alive.items():
        with urllib.request.urlopen(
                f"http://127.0.0.1:{vs.port}/{fid}", timeout=20) as r:
            assert r.read() == data
    for fid in dead:
        try:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{vs.port}/{fid}", timeout=20) as r:
                assert r.status == 404
        except urllib.error.HTTPError as e:
            assert e.code == 404


def test_filer_namespace_storm(stack):
    """Parallel creates/overwrites/deletes across shared directories; the
    final listing matches the computed survivor set."""
    _, _, filer = stack
    from seaweedfs_tpu.s3api.filer_client import FilerClient

    client = FilerClient(f"127.0.0.1:{filer.port}")
    survivors: dict[str, bytes] = {}
    lock = threading.Lock()

    def worker(i: int):
        d = f"/storm/d{i % 4}"
        name = f"f{i}.bin"
        data = f"content-{i}-v2".encode()
        client.put_object(f"{d}/{name}", f"content-{i}-v1".encode())
        client.put_object(f"{d}/{name}", data)  # overwrite
        if i % 4 == 0:
            client.delete_entry(d, name)
        else:
            with lock:
                survivors[f"{d}/{name}"] = data

    with concurrent.futures.ThreadPoolExecutor(max_workers=12) as ex:
        list(ex.map(worker, range(48)))

    for path, data in survivors.items():
        code, _, body = client.get_object(path)
        assert code == 200 and body == data, path
    listed = set()
    for i in range(4):
        for e in client.list_entries(f"/storm/d{i}", limit=1000):
            listed.add(f"/storm/d{i}/{e.name}")
    assert listed == set(survivors)


def test_vacuum_races_live_appends(tmp_path):
    """Compaction with concurrent appends must keep every needle written
    before AND during the vacuum (makeupDiff replay,
    volume_vacuum.go:179)."""
    import numpy as np

    from seaweedfs_tpu.storage import SuperBlock
    from seaweedfs_tpu.storage.needle import Needle
    from seaweedfs_tpu.storage.vacuum import commit_compact, compact
    from seaweedfs_tpu.storage.volume import Volume

    vol = Volume(str(tmp_path), "", 1, super_block=SuperBlock())
    rng = np.random.default_rng(3)
    expect: dict[int, bytes] = {}
    for i in range(1, 101):
        data = rng.integers(0, 256, 200).astype(np.uint8).tobytes()
        vol.append_needle(Needle(cookie=7, id=i, data=data))
        expect[i] = data
    for i in range(1, 51):  # delete half -> garbage to reclaim
        vol.delete_needle(i)
        del expect[i]

    stop = threading.Event()
    racer_ids = []

    def racer():
        i = 1000
        while not stop.is_set():
            data = f"racer-{i}".encode() * 3
            vol.append_needle(Needle(cookie=7, id=i, data=data))
            expect[i] = data
            racer_ids.append(i)
            i += 1
            time.sleep(0.001)

    t = threading.Thread(target=racer)
    t.start()
    time.sleep(0.02)
    _base, snapshot = compact(vol)
    time.sleep(0.05)  # let more appends race the shadow copy
    stop.set()
    t.join()
    commit_compact(vol, snapshot)

    assert len(racer_ids) > 0
    for nid, data in expect.items():
        assert bytes(vol.read_needle(nid).data) == data, nid
    for nid in range(1, 51):
        with pytest.raises(KeyError):
            vol.read_needle(nid)
    vol.close()


def test_replicated_write_storm(tmp_path_factory):
    """8 threads of 001-replicated writes with concurrent readers hitting
    BOTH replicas directly: every read returns the written bytes and the
    replica pairs converge to identical file counts (store_replicate.go
    fan-out under contention)."""
    from seaweedfs_tpu.master.server import MasterServer
    from seaweedfs_tpu.volume.server import VolumeServer

    master = MasterServer(ip="127.0.0.1", port=free_port(),
                          volume_size_limit_mb=64)
    master.start()
    servers = []
    for i in range(2):
        vs = VolumeServer(
            directories=[str(tmp_path_factory.mktemp(f"repvol{i}"))],
            master_addresses=[f"127.0.0.1:{master.grpc_port}"],
            ip="127.0.0.1", port=free_port(), pulse_seconds=0.5,
            max_volume_count=100,
        )
        vs.start()
        servers.append(vs)
    try:
        deadline = time.time() + 15
        while time.time() < deadline and len(master.topo.nodes) < 2:
            time.sleep(0.1)
        assert len(master.topo.nodes) == 2

        written: dict[str, bytes] = {}
        wlock = threading.Lock()
        errors: list[str] = []

        def writer(seed: int) -> None:
            rng = random.Random(seed)
            for i in range(25):
                try:
                    with urllib.request.urlopen(
                            f"http://127.0.0.1:{master.port}/dir/assign"
                            "?replication=001", timeout=10) as r:
                        a = json.loads(r.read())
                    payload = bytes(rng.randrange(256) for _ in range(600))
                    _post(f"{a['url']}/{a['fid']}", payload)
                    with wlock:
                        written[a["fid"]] = payload
                except Exception as e:
                    errors.append(f"write: {e!r}")

        def reader() -> None:
            rng = random.Random()
            end = time.time() + 6
            while time.time() < end:
                with wlock:
                    items = list(written.items())
                if not items:
                    time.sleep(0.05)
                    continue
                fid, payload = rng.choice(items)
                vs_ = rng.choice(servers)
                if vs_.store.find_volume(int(fid.split(",")[0])) is None:
                    continue
                try:
                    with urllib.request.urlopen(
                            f"http://127.0.0.1:{vs_.port}/{fid}",
                            timeout=10) as r:
                        got = r.read()
                    if got != payload:
                        errors.append(f"read mismatch on {fid}")
                except urllib.error.HTTPError as e:
                    if e.code != 404:  # replica may trail briefly
                        errors.append(f"read {fid}: HTTP {e.code}")
                except Exception as e:
                    errors.append(f"read: {e!r}")

        threads = [threading.Thread(target=writer, args=(s,))
                   for s in range(8)]
        threads += [threading.Thread(target=reader) for _ in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errors, errors[:5]
        assert len(written) == 200

        # every fid reads back correctly from BOTH holders
        for fid, payload in list(written.items())[:40]:
            vid = int(fid.split(",")[0])
            holders = [s for s in servers
                       if s.store.find_volume(vid) is not None]
            assert len(holders) == 2, f"vid {vid} not on both servers"
            for s in holders:
                with urllib.request.urlopen(
                        f"http://127.0.0.1:{s.port}/{fid}", timeout=10) as r:
                    assert r.read() == payload, f"{fid} differs on a replica"
    finally:
        for s in servers:
            s.stop()
        master.stop()


def test_hardlink_counter_survives_concurrent_unlink_storm():
    """N names hardlinked to one file unlink concurrently from many
    threads: the locked counter RMW must reclaim the shared chunks
    EXACTLY once, with no leak (counter never reaching 0) and no
    double-free (reclaimed while links remain)."""
    import threading

    from seaweedfs_tpu.filer.filer import Filer
    from seaweedfs_tpu.filer.filerstore import make_store
    from seaweedfs_tpu.pb import filer_pb2

    deleted: list[str] = []
    lock = threading.Lock()

    def collect(fids):
        with lock:
            deleted.extend(fids)

    f = Filer(make_store("memory"), delete_chunks_fn=collect)
    n_links = 24
    hid = b"s" * 17
    for i in range(n_links):
        e = filer_pb2.Entry(name=f"l{i}", hard_link_id=hid,
                            hard_link_counter=n_links)
        e.chunks.append(filer_pb2.FileChunk(
            file_id="9,shared", offset=0, size=10, mtime=1))
        f.create_entry("/storm", e)

    errs: list[Exception] = []

    def unlink(i: int) -> None:
        try:
            f.delete_entry("/storm", f"l{i}")
        except Exception as e:  # noqa: BLE001
            errs.append(e)

    threads = [threading.Thread(target=unlink, args=(i,))
               for i in range(n_links)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    f.drain_deletions()
    assert not errs
    # exactly one reclamation of the shared chunk — no leak, no double
    assert deleted == ["9,shared"], deleted
    assert f.store.kv_get(hid) is None  # meta dropped with the last link
    f.close()
