"""Multi-filer fleet acceptance (ISSUE 7): real subprocesses through the
CLI — master + volume server + THREE peered filers + a stateless S3
gateway in master-discovery mode.

Asserts the tentpole contracts:

* the gateway routes every bucket to its ring owner and serves reads
  and writes across all three shards;
* restarting the gateway mid-test changes nothing — it holds no routing
  state beyond the master-discovered ring snapshot;
* SIGKILL one filer: NO namespace is lost (its buckets re-route to the
  ring successor, which holds the replicated metadata), keys owned by
  surviving shards see ZERO 5xx throughout, and writes keep working —
  including new writes into the dead shard's buckets.

Runs as its own bounded CI step (see .github/workflows/ci.yml),
mirroring the PR 5 cluster-observability job; marked slow so tier-1
stays fast.
"""

import json
import os
import signal
import subprocess
import sys
import time
import urllib.error
import urllib.request

import pytest

from helpers import free_port

from seaweedfs_tpu.filer.fleet.ring import HashRing

pytestmark = pytest.mark.slow

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _env():
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    return env


def _spawn(args, cwd):
    return subprocess.Popen(
        [sys.executable, "-m", "seaweedfs_tpu", *args],
        cwd=cwd, env=_env(),
        stdout=subprocess.DEVNULL, stderr=subprocess.STDOUT,
    )


def _req(method, url, data=None, headers=None, timeout=15):
    req = urllib.request.Request(url, data=data, method=method,
                                 headers=headers or {})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, r.read()
    except urllib.error.HTTPError as e:
        return e.code, e.read()


def _wait_http(url, deadline_s=30):
    deadline = time.time() + deadline_s
    while time.time() < deadline:
        try:
            with urllib.request.urlopen(url, timeout=2) as r:
                return r.status
        except (urllib.error.URLError, ConnectionError, OSError):
            time.sleep(0.3)
    raise TimeoutError(url)


def _spawn_gateway(s3port, mport, cwd):
    p = _spawn(["s3", "-port", str(s3port),
                "-master", f"127.0.0.1:{mport}"], cwd)
    _wait_http(f"http://127.0.0.1:{s3port}/")
    return p


def test_filer_fleet_shard_death_and_stateless_gateway(tmp_path):
    mport = free_port()
    vport = free_port()
    fports = [free_port() for _ in range(3)]
    s3port = free_port()
    filer_addrs = [f"127.0.0.1:{p}" for p in fports]
    peers = ",".join(filer_addrs)
    (tmp_path / "vol").mkdir()
    procs = {}
    try:
        procs["master"] = _spawn(["master", "-port", str(mport)],
                                 str(tmp_path))
        _wait_http(f"http://127.0.0.1:{mport}/cluster/healthz")
        # every bucket is its own collection (volume growth per bucket),
        # so the slot budget must cover 6+ buckets x 3 grown volumes
        procs["volume"] = _spawn(
            ["volume", "-dir", str(tmp_path / "vol"), "-port", str(vport),
             "-mserver", f"127.0.0.1:{mport}", "-ec.codec", "cpu",
             "-max", "500"],
            str(tmp_path))
        for i, port in enumerate(fports):
            procs[f"filer{i}"] = _spawn(
                ["filer", "-master", f"127.0.0.1:{mport}",
                 "-port", str(port),
                 "-store", str(tmp_path / f"filer{i}.db"),
                 "-peers", peers],
                str(tmp_path))
        for port in fports:
            _wait_http(f"http://127.0.0.1:{port}/")

        # master sees the volume server + all three filer registrations
        deadline = time.time() + 30
        while time.time() < deadline:
            try:
                status = json.loads(urllib.request.urlopen(
                    f"http://127.0.0.1:{mport}/cluster/status",
                    timeout=5).read())
                if (len(status.get("DataNodes", {})) >= 1
                        and len(status.get("Filers", {})) >= 3):
                    break
            except (urllib.error.URLError, OSError):
                pass
            time.sleep(0.3)
        else:
            raise AssertionError("fleet never fully registered")

        procs["s3"] = _spawn_gateway(s3port, mport, str(tmp_path))

        # the ring is deterministic: compute shard ownership exactly as
        # the gateway does, and pick buckets until every shard owns >= 2
        ring = HashRing(sorted(filer_addrs))
        by_owner = {a: [] for a in filer_addrs}
        buckets = []
        for i in range(200):
            name = f"fleet-b{i}"
            owner = ring.lookup(f"b/{name}")
            if len(by_owner[owner]) < 2:
                by_owner[owner].append(name)
                buckets.append(name)
            if all(len(v) >= 2 for v in by_owner.values()):
                break
        assert all(len(v) >= 2 for v in by_owner.values()), by_owner

        # -- writes + reads across every shard ----------------------------
        payload = {b: f"payload-of-{b}".encode() * 64 for b in buckets}
        for b in buckets:
            code, _ = _req("PUT", f"http://127.0.0.1:{s3port}/{b}")
            assert code == 200, (b, code)
            code, _ = _req("PUT", f"http://127.0.0.1:{s3port}/{b}/obj1",
                           data=payload[b])
            assert code == 200, (b, code)
        for b in buckets:
            code, body = _req("GET", f"http://127.0.0.1:{s3port}/{b}/obj1")
            assert code == 200 and body == payload[b], b

        # list-buckets merges across shards
        code, body = _req("GET", f"http://127.0.0.1:{s3port}/")
        assert code == 200
        for b in buckets:
            assert b.encode() in body

        # -- stateless gateway: restart it mid-test, behavior identical ---
        procs["s3"].terminate()
        procs["s3"].wait(timeout=10)
        procs["s3"] = _spawn_gateway(s3port, mport, str(tmp_path))
        for b in buckets:
            code, body = _req("GET", f"http://127.0.0.1:{s3port}/{b}/obj1")
            assert code == 200 and body == payload[b], (
                f"post-restart read of {b} failed: {code}")

        # -- wait until every filer holds every bucket's metadata ---------
        # (peer replication: each filer replays the others' mutation
        # streams into its own store)
        deadline = time.time() + 30
        replicated = False
        while time.time() < deadline and not replicated:
            replicated = True
            for addr in filer_addrs:
                for b in buckets:
                    code, _ = _req(
                        "GET", f"http://{addr}/buckets/{b}/obj1",
                        timeout=5)
                    if code != 200:
                        replicated = False
                        break
                if not replicated:
                    break
            if not replicated:
                time.sleep(0.5)
        assert replicated, "peer replication never converged"

        # -- shell: filer.ring renders membership + shard entry counts ----
        shell = subprocess.run(
            [sys.executable, "-m", "seaweedfs_tpu", "shell",
             "-master", f"127.0.0.1:{mport}", "-c", "filer.ring"],
            capture_output=True, text=True, env=_env(),
            cwd=str(tmp_path), timeout=30)
        assert "filer ring: 3 shard(s)" in shell.stdout, shell.stdout
        for addr in filer_addrs:
            assert f"{addr} entries=" in shell.stdout, shell.stdout

        # -- SIGKILL one shard --------------------------------------------
        victim_idx = 0
        victim_addr = filer_addrs[victim_idx]
        dead_buckets = by_owner[victim_addr]
        surviving = [b for b in buckets if b not in dead_buckets]
        procs.pop(f"filer{victim_idx}").kill()

        # keys owned by SURVIVING shards: zero 5xx, polled throughout
        # the recovery window
        recover_deadline = time.time() + 25
        dead_ok = False
        while time.time() < recover_deadline:
            for b in surviving:
                code, body = _req(
                    "GET", f"http://127.0.0.1:{s3port}/{b}/obj1")
                assert code < 500, (
                    f"surviving-shard key {b} returned {code} "
                    "during failover")
                assert code == 200 and body == payload[b], (b, code)
            if not dead_ok:
                # the dead shard's namespace must re-route and recover
                codes = [
                    _req("GET",
                         f"http://127.0.0.1:{s3port}/{b}/obj1")[0]
                    for b in dead_buckets]
                dead_ok = all(c == 200 for c in codes)
            if dead_ok:
                break
            time.sleep(0.5)
        assert dead_ok, "dead shard's namespace was lost"
        for b in dead_buckets:
            code, body = _req("GET", f"http://127.0.0.1:{s3port}/{b}/obj1")
            assert code == 200 and body == payload[b], (
                f"no namespace may be lost: {b} -> {code}")

        # -- writes keep working, including INTO the dead shard -----------
        for b in (surviving[0], dead_buckets[0]):
            code, _ = _req("PUT",
                           f"http://127.0.0.1:{s3port}/{b}/post-kill",
                           data=b"written after shard death")
            assert code == 200, (b, code)
            code, body = _req(
                "GET", f"http://127.0.0.1:{s3port}/{b}/post-kill")
            assert code == 200 and body == b"written after shard death", b
    finally:
        for p in procs.values():
            p.send_signal(signal.SIGTERM)
        for p in procs.values():
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()
