"""Self-healing integrity plane (ISSUE 8): scrub daemon, corruption
quarantine, index last-resort rebuild, vacuum verification, and the
end-to-end detect -> quarantine -> repair -> byte-identical chaos proof.
"""

from __future__ import annotations

import json
import os
import threading
import time
import urllib.error
import urllib.request

import pytest

from helpers import free_port, make_volume

from seaweedfs_tpu.storage.ec import constants as ecc
from seaweedfs_tpu.storage.ec.encoder import (
    write_ec_files,
    write_sorted_file_from_idx,
)
from seaweedfs_tpu.storage.needle import CorruptNeedleError
from seaweedfs_tpu.storage.scrub import (
    CURSOR_FILE,
    Quarantine,
    Scrubber,
    TokenBucket,
)
from seaweedfs_tpu.storage.store import Store


def _flip_byte(path: str, offset: int, mask: int = 0xFF) -> None:
    with open(path, "r+b") as f:
        f.seek(offset)
        b = f.read(1)
        f.seek(offset)
        f.write(bytes([b[0] ^ mask]))


def _corrupt_needle(volume, needle_id: int) -> None:
    """Flip one byte inside the stored data region of a live needle."""
    nv = volume.needle_map.get(needle_id)
    assert nv is not None
    # header(16) + data_size(4) + 2 bytes into the payload
    _flip_byte(volume.file_name() + ".dat", nv.offset + 16 + 4 + 2)


def _make_store(tmp_path, **kw):
    kw.setdefault("needle_cache_mb", 0)
    store = Store([str(tmp_path)], **kw)
    scrubber = Scrubber(store, rate_mbps=500, interval_s=9999)
    store.scrubber = scrubber
    return store, scrubber


# ---------------------------------------------------------------------------
# throttle
# ---------------------------------------------------------------------------


def test_token_bucket_paces_consumption():
    tb = TokenBucket(1 << 20)  # 1 MB/s, 1 MB burst capacity
    t0 = time.monotonic()
    tb.consume(1 << 20)        # burst: free
    for _ in range(4):
        tb.consume(256 << 10)  # +1 MB over the burst -> ~1s
    elapsed = time.monotonic() - t0
    assert 0.7 <= elapsed <= 3.0, elapsed


def test_token_bucket_rate_change_applies():
    tb = TokenBucket(1 << 20)
    tb.set_rate(100 << 20)
    t0 = time.monotonic()
    tb.consume(20 << 20)
    assert time.monotonic() - t0 < 2.0


def test_token_bucket_oversized_read_does_not_wedge():
    """A single read larger than the bucket capacity must be granted
    (charged as debt) instead of blocking forever."""
    tb = TokenBucket(1 << 20)  # capacity 1 MB
    t0 = time.monotonic()
    tb.consume(3 << 20)        # 3x capacity
    first = time.monotonic() - t0
    assert first < 2.0, first
    # and the debt is actually paid back by the next consumer
    t0 = time.monotonic()
    tb.consume(1)
    assert time.monotonic() - t0 >= 1.0


def test_quarantine_bounds_and_clear():
    q = Quarantine()
    assert q.mark_needle(1, 7)
    assert not q.mark_needle(1, 7)  # already suspect
    assert q.is_needle_suspect(1, 7)
    q.clear_needle(1, 7)
    assert not q.is_needle_suspect(1, 7)
    for i in range(Quarantine.MAX_PER_VOLUME + 10):
        q.mark_needle(2, i)
    assert len(q.status()["needles"]["2"]) == Quarantine.MAX_PER_VOLUME


# ---------------------------------------------------------------------------
# volume scrub: detection + read-path quarantine
# ---------------------------------------------------------------------------


def test_scrub_clean_volume_finds_nothing(tmp_path):
    make_volume(str(tmp_path), volume_id=1, n_needles=30, seed=1).close()
    store, scrubber = _make_store(tmp_path)
    r = scrubber.scrub_once()
    assert r["corrupt_needles"] == 0
    assert r["volumes"] == 1
    assert r["scanned_bytes"] > 0
    assert scrubber.outstanding_findings() == []
    store.close()


def test_scrub_detects_flipped_byte_in_dat(tmp_path):
    make_volume(str(tmp_path), volume_id=1, n_needles=30, seed=2).close()
    store, scrubber = _make_store(tmp_path)
    _corrupt_needle(store.find_volume(1), 9)
    r = scrubber.scrub_once()
    assert r["corrupt_needles"] == 1
    findings = scrubber.outstanding_findings()
    assert [(f["kind"], f["needle_id"]) for f in findings] == [("replica", 9)]
    assert scrubber.quarantine.is_needle_suspect(1, 9)
    # re-scrub re-confirms but does NOT duplicate the outstanding finding,
    # and the finding is RE-DELIVERED on every beat until the target
    # heals (a heartbeat that dies mid-send loses nothing)
    scrubber.scrub_once()
    assert len(scrubber.outstanding_findings()) == 1
    assert len(scrubber.outstanding_findings()) == 1
    # a repair remounts the volume -> forget clears delivery + quarantine
    scrubber.forget_volume(1)
    assert scrubber.outstanding_findings() == []
    assert not scrubber.quarantine.is_needle_suspect(1, 9)
    store.close()


def test_read_path_corruption_is_retryable_and_quarantined(tmp_path):
    make_volume(str(tmp_path), volume_id=1, n_needles=10, seed=3).close()
    store, scrubber = _make_store(tmp_path)
    _corrupt_needle(store.find_volume(1), 4)
    with pytest.raises(CorruptNeedleError):
        store.read_needle(1, 4)
    assert scrubber.quarantine.is_needle_suspect(1, 4)
    # the queued suspicion confirms into a finding without a full pass
    scrubber._confirm_pending()
    findings = scrubber.outstanding_findings()
    assert findings and findings[0]["needle_id"] == 4
    # healthy needles still read fine
    assert store.read_needle(1, 5).id == 5
    store.close()


def test_read_path_transient_error_is_not_reported(tmp_path):
    """A confirm of a healthy needle clears the quarantine instead of
    reporting — transient I/O noise must not trigger repairs."""
    make_volume(str(tmp_path), volume_id=1, n_needles=10, seed=4).close()
    store, scrubber = _make_store(tmp_path)
    scrubber.suspect_needle(1, 6)
    assert scrubber.quarantine.is_needle_suspect(1, 6)
    scrubber._confirm_pending()
    assert scrubber.outstanding_findings() == []
    assert not scrubber.quarantine.is_needle_suspect(1, 6)
    store.close()


def test_scrub_cursor_persists_and_resumes(tmp_path):
    make_volume(str(tmp_path), volume_id=1, n_needles=20, seed=5).close()
    store, scrubber = _make_store(tmp_path)
    scrubber.scrub_once()
    store.close()
    path = os.path.join(str(tmp_path), CURSOR_FILE)
    assert os.path.exists(path)
    with open(path) as f:
        cur = json.load(f)
    # completed pass wraps the volume cursor to 0 for the next round
    assert cur["volume"]["1"] == 0
    # a fresh scrubber loads the persisted state
    store2, scrubber2 = _make_store(tmp_path)
    assert scrubber2._cursor(str(tmp_path), "volume", 1) == 0
    store2.close()


# ---------------------------------------------------------------------------
# EC scrub: parity verification + localization + read-path failover
# ---------------------------------------------------------------------------


def _make_ec_store(tmp_path, vid=2, n_needles=60, seed=7):
    vol = make_volume(str(tmp_path), volume_id=vid, n_needles=n_needles,
                      seed=seed, max_size=20000)
    base = vol.file_name()
    vol.close()
    write_ec_files(base, codec_name="cpu")
    write_sorted_file_from_idx(base)
    os.remove(base + ".dat")
    os.remove(base + ".idx")
    store, scrubber = _make_store(tmp_path)
    store.mount_ec_shards(vid, "", list(range(ecc.TOTAL_SHARDS)))
    return store, scrubber, base


def test_scrub_detects_flipped_byte_in_ec_shard(tmp_path):
    store, scrubber, base = _make_ec_store(tmp_path)
    r = scrubber.scrub_once()
    assert r["corrupt_shards"] == 0
    # flip a byte in a DATA shard holding live needle bytes
    _flip_byte(base + ecc.to_ext(0), 5000, 0x5A)
    r = scrubber.scrub_once()
    assert r["corrupt_shards"] >= 1
    findings = scrubber.outstanding_findings()
    assert any(f["kind"] == "ec_shard" and f["shard_id"] == 0
               for f in findings), findings
    # a repair remounts the shard -> forget stops the re-delivery
    scrubber.forget_shards(2, [0])
    assert scrubber.outstanding_findings() == []
    store.close()


def test_scrub_localizes_corrupt_parity_shard(tmp_path):
    store, scrubber, base = _make_ec_store(tmp_path, seed=8)
    _flip_byte(base + ecc.to_ext(11), 600, 0x3C)  # parity shard
    scrubber.scrub_once()
    findings = scrubber.outstanding_findings()
    assert any(f["kind"] == "ec_shard" and f["shard_id"] == 11
               for f in findings), findings
    store.close()


def test_ec_read_serves_through_corruption_byte_identical(tmp_path):
    """A flipped shard byte under a live needle: the EC read path must
    reconstruct and serve the ORIGINAL bytes (zero client errors) and
    flag the corrupt shard for the scrubber."""
    vid = 2
    vol = make_volume(str(tmp_path), volume_id=vid, n_needles=40,
                      seed=9, max_size=20000)
    base = vol.file_name()
    expected = {}
    for nid in range(1, 41):
        expected[nid] = bytes(vol.read_needle(nid).data)
    vol.close()
    write_ec_files(base, codec_name="cpu")
    write_sorted_file_from_idx(base)
    os.remove(base + ".dat")
    os.remove(base + ".idx")
    store, scrubber = _make_store(tmp_path)
    store.mount_ec_shards(vid, "", list(range(ecc.TOTAL_SHARDS)))
    _flip_byte(base + ecc.to_ext(0), 5000, 0x77)
    marks = []
    ev = store.find_ec_volume(vid)
    ev.corruption_hook = lambda v, s: marks.append((v, s))
    for nid in range(1, 41):
        n = store.read_needle(vid, nid)
        assert bytes(n.data) == expected[nid], f"needle {nid} diverged"
    assert (vid, 0) in marks, "corrupt shard never flagged"
    store.close()


# ---------------------------------------------------------------------------
# index verification + offline fix_index (the scrubber's last resort)
# ---------------------------------------------------------------------------


def test_fix_index_rebuilds_from_dat(tmp_path):
    from seaweedfs_tpu.storage.idx import walk_index_file
    from seaweedfs_tpu.tools.offline import fix_index

    vol = make_volume(str(tmp_path), volume_id=3, n_needles=25, seed=10)
    vol.delete_needle(5)
    vol.delete_needle(6)
    vol.sync()
    base = vol.file_name()
    before = {nv.key: (nv.offset, nv.size)
              for nv in vol.needle_map.items_ascending()
              if nv.size > 0}
    vol.close()
    os.remove(base + ".idx")
    n = fix_index(str(tmp_path), 3)
    assert n == len(before) == 23
    rebuilt = {}
    for key, offset, size in walk_index_file(base + ".idx"):
        rebuilt[key] = (offset, size)
    assert rebuilt == before


def test_fix_index_missing_dat_raises(tmp_path):
    from seaweedfs_tpu.tools.offline import fix_index

    with pytest.raises(FileNotFoundError):
        fix_index(str(tmp_path), 99)


def test_scrub_repairs_corrupt_index(tmp_path):
    """Scribble over the on-disk .idx while the volume is live: the
    scrubber's index verification catches the divergence and the
    fix_index last resort rebuilds it from the .dat."""
    make_volume(str(tmp_path), volume_id=4, n_needles=20, seed=11).close()
    store, scrubber = _make_store(tmp_path)
    v = store.find_volume(4)
    idx_path = v.file_name() + ".idx"
    # corrupt one entry's offset field on disk (in-memory map unaffected)
    _flip_byte(idx_path, 16 * 3 + 9)
    r = scrubber.scrub_once()
    assert r["index_repairs"] == 1
    # the rebuilt on-disk index now matches the map, and reads still work
    v = store.find_volume(4)
    assert scrubber._verify_index(v)
    assert store.read_needle(4, 7).id == 7
    r2 = scrubber.scrub_once()
    assert r2["index_repairs"] == 0
    store.close()


# ---------------------------------------------------------------------------
# vacuum verifies while copying
# ---------------------------------------------------------------------------


def test_vacuum_reports_corrupt_needle_for_repair(tmp_path):
    """Vacuum through the STORE queues a repair finding for the needle it
    had to drop — replicas must not silently diverge."""
    make_volume(str(tmp_path), volume_id=5, n_needles=12, seed=20).close()
    store, scrubber = _make_store(tmp_path)
    _corrupt_needle(store.find_volume(5), 4)
    store.compact_volume(5)
    store.commit_compact_volume(5)
    findings = scrubber.outstanding_findings()
    assert [(f["kind"], f["needle_id"]) for f in findings] == [("replica", 4)]
    store.close()


def test_vacuum_skips_corrupt_needle(tmp_path):
    from seaweedfs_tpu.stats.metrics import SCRUB_ERRORS
    from seaweedfs_tpu.storage.vacuum import vacuum_volume

    vol = make_volume(str(tmp_path), volume_id=5, n_needles=20, seed=12)
    expected = {nid: bytes(vol.read_needle(nid).data)
                for nid in range(1, 21)}
    vol.delete_needle(3)
    _corrupt_needle(vol, 8)
    before = SCRUB_ERRORS.labels("vacuum").value
    vacuum_volume(vol)
    assert SCRUB_ERRORS.labels("vacuum").value == before + 1
    # the rot was NOT propagated into the compacted copy
    assert vol.needle_map.get(8) is None
    for nid in expected:
        if nid in (3, 8):
            continue
        assert bytes(vol.read_needle(nid).data) == expected[nid]
    vol.close()


# ---------------------------------------------------------------------------
# chaos: end-to-end detect -> quarantine -> repair -> byte-identical
# ---------------------------------------------------------------------------


def _http(method, url, data=None, timeout=30.0):
    req = urllib.request.Request(url, data=data, method=method)
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, r.read()
    except urllib.error.HTTPError as e:
        return e.code, e.read()


@pytest.fixture()
def scrub_cluster(tmp_path_factory):
    """master + 2 volume servers + filer with replication 001; scrub
    daemons idle (huge interval) so tests drive scans deterministically."""
    import os as _os

    from seaweedfs_tpu.filer.server import FilerServer
    from seaweedfs_tpu.master.server import MasterServer
    from seaweedfs_tpu.volume.server import VolumeServer

    _os.environ["SEAWEEDFS_TPU_SCRUB_INTERVAL_S"] = "3600"
    master = MasterServer(ip="127.0.0.1", port=free_port(),
                          volume_size_limit_mb=64)
    master.start()
    vols = []
    for i in range(2):
        vs = VolumeServer(
            directories=[str(tmp_path_factory.mktemp(f"scrubvol{i}"))],
            master_addresses=[f"127.0.0.1:{master.grpc_port}"],
            ip="127.0.0.1", port=free_port(), pulse_seconds=0.5,
            max_volume_count=30,
        )
        vs.start()
        vols.append(vs)
    deadline = time.time() + 15
    while time.time() < deadline and len(master.topo.nodes) < 2:
        time.sleep(0.1)
    assert len(master.topo.nodes) == 2
    filer = FilerServer(
        masters=[f"127.0.0.1:{master.grpc_port}"],
        ip="127.0.0.1", port=free_port(), store="memory",
        default_replication="001", chunk_cache_mem_mb=0,
    )
    filer.start()
    yield master, vols, filer
    filer.stop()
    for v in vols:
        v.stop()
    master.stop()
    _os.environ.pop("SEAWEEDFS_TPU_SCRUB_INTERVAL_S", None)


@pytest.mark.chaos
def test_chaos_replica_detect_repair_no_client_errors(scrub_cluster):
    """Flip a byte in one replica's .dat: concurrent client GETs never
    see a 5xx (rotation covers the window), scrub detects, the finding
    rides the heartbeat, the master re-copies from the healthy peer, and
    the repaired replica is byte-identical."""
    master, vols, filer = scrub_cluster
    base = f"http://127.0.0.1:{filer.port}"
    payload = os.urandom(150_000)
    code, _ = _http("PUT", base + "/scrub/blob.bin", payload)
    assert code == 201

    target = None
    for vs in vols:
        for loc in vs.store.locations:
            for vid, v in loc.volumes.items():
                if v.file_count() > 0:
                    target = (vs, v)
                    break
    assert target is not None
    vs0, v0 = target
    nv = next(iter(v0.needle_map.items_ascending()))
    _flip_byte(v0.file_name() + ".dat", nv.offset + 30)

    # concurrent reader: no 5xx allowed across the whole window
    stop = threading.Event()
    errors: list[int] = []
    reads = [0]

    def reader():
        while not stop.is_set():
            code, body = _http("GET", base + "/scrub/blob.bin", timeout=10)
            if code >= 500:
                errors.append(code)
            elif code == 200 and body != payload:
                errors.append(-1)  # wrong bytes is as bad as a 5xx
            reads[0] += 1

    t = threading.Thread(target=reader, daemon=True)
    t.start()
    try:
        r = vs0.scrubber.scrub_once()
        assert r["corrupt_needles"] >= 1, r
        deadline = time.time() + 10
        while time.time() < deadline and not master.scrub_findings_snapshot():
            time.sleep(0.2)
        assert master.scrub_findings_snapshot(), "finding never reached master"
        summary = master.repair_pass()
        assert summary["repaired"], summary
    finally:
        time.sleep(0.5)  # a little post-repair read traffic
        stop.set()
        t.join(timeout=10)
    assert not errors, f"client saw errors: {errors} over {reads[0]} reads"
    assert reads[0] > 0

    # repaired replica byte-identical: a fresh scrub pass is clean and
    # the needle parses with a valid CRC on the repaired node
    v0b = vs0.store.find_volume(v0.volume_id)
    assert v0b is not None
    n = v0b.read_needle(nv.key)
    r2 = vs0.scrubber.scrub_once()
    assert r2["corrupt_needles"] == 0, r2
    assert master.scrub_findings_snapshot() == []
    code, body = _http("GET", base + "/scrub/blob.bin")
    assert code == 200 and body == payload
    assert len(n.data) == len(payload)


@pytest.mark.chaos
def test_chaos_ec_shard_detect_repair_byte_identical(scrub_cluster):
    """Flip a byte in an .ec shard: scrub at a 4 MB/s throttle detects it
    (measured read rate within ~2x of the throttle), degraded reads stay
    byte-identical during the window, and the master's repair pass
    rebuilds the shard byte-identically and remounts it."""
    from seaweedfs_tpu.pb import rpc as rpclib
    from seaweedfs_tpu.pb import volume_server_pb2 as vspb

    master, vols, filer = scrub_cluster
    vs0 = vols[0]
    d = vs0.store.locations[0].directory
    vid = 42
    vol = make_volume(d, volume_id=vid, n_needles=50, seed=13,
                      max_size=20000)
    base = vol.file_name()
    expected = {nid: bytes(vol.read_needle(nid).data)
                for nid in range(1, 51)}
    vol.close()
    assert vs0.store.mount_volume(vid)
    vs0.store.generate_ec_shards(vid, "")
    vs0.store.unmount_volume(vid)
    os.remove(base + ".dat")
    os.remove(base + ".idx")
    vs0.store.mount_ec_shards(vid, "", list(range(ecc.TOTAL_SHARDS)))
    deadline = time.time() + 15
    while (time.time() < deadline
           and len(master.topo.lookup_ec_shards(vid)) < ecc.TOTAL_SHARDS):
        time.sleep(0.2)

    shard_path = base + ecc.to_ext(1)
    with open(shard_path, "rb") as f:
        orig_shard = f.read()
    _flip_byte(shard_path, 9000, 0x42)

    # degraded reads through the corruption stay byte-identical
    for nid in (1, 5, 9):
        assert bytes(vs0.store.read_needle(vid, nid).data) == expected[nid]

    # on-demand scrub over gRPC at the 4 MB/s acceptance throttle;
    # measured rate must stay within ~2x of configured (+1s burst grace)
    stub = rpclib.volume_server_stub(f"127.0.0.1:{vs0.grpc_port}",
                                     timeout=600)
    t0 = time.monotonic()
    resp = stub.VolumeScrub(vspb.VolumeScrubRequest(
        volume_id=vid, rate_mbps=4.0))
    elapsed = time.monotonic() - t0
    assert resp.corrupt_shards >= 1, resp
    measured = resp.scanned_bytes / max(elapsed, 1e-6)
    budget = 2.0 * 4.0 * (1 << 20)
    burst_grace = 4.0 * (1 << 20)  # one bucket of startup burst
    assert measured <= budget + burst_grace / max(elapsed, 1e-6), (
        f"scrub read {measured / (1 << 20):.1f} MB/s against a 4 MB/s "
        f"throttle ({resp.scanned_bytes} B in {elapsed:.2f}s)")

    deadline = time.time() + 10
    while time.time() < deadline and not any(
            f["kind"] == "ec_shard"
            for f in master.scrub_findings_snapshot()):
        time.sleep(0.2)
    findings = master.scrub_findings_snapshot()
    assert any(f["kind"] == "ec_shard" and f["shard_id"] == 1
               for f in findings), findings

    summary = master.repair_pass()
    assert summary["repaired"], summary
    with open(shard_path, "rb") as f:
        rebuilt = f.read()
    assert rebuilt == orig_shard, "rebuilt shard not byte-identical"
    ev = vs0.store.find_ec_volume(vid)
    assert 1 in ev.shards
    for nid in range(1, 51):
        assert bytes(vs0.store.read_needle(vid, nid).data) == expected[nid]
    r2 = vs0.scrubber.scrub_volume(vid)
    assert r2["corrupt_shards"] == 0, r2


@pytest.mark.chaos
def test_chaos_scrub_faultpoints_no_false_findings(tmp_path):
    """Armed scrub.read / scrub.verify faults hit the scrubber's unlocked
    fast path; the locked recheck must absorb them WITHOUT reporting a
    healthy volume as corrupt (transient I/O noise != rot)."""
    from seaweedfs_tpu.util import faultpoint

    make_volume(str(tmp_path), volume_id=6, n_needles=15, seed=14).close()
    store, scrubber = _make_store(tmp_path)
    try:
        faultpoint.set_fault("scrub.verify", "partial", count=5)
        r = scrubber.scrub_once()
        assert r["corrupt_needles"] == 0, r
        assert scrubber.outstanding_findings() == []
        faultpoint.set_fault("scrub.read", "error", count=5)
        r = scrubber.scrub_once()
        assert r["corrupt_needles"] == 0, r
        assert scrubber.outstanding_findings() == []
    finally:
        faultpoint.clear_fault("all")
        store.close()


@pytest.mark.chaos
def test_chaos_scrub_shell_command(scrub_cluster):
    """`volume.scrub` sweeps every node and prints findings."""
    from seaweedfs_tpu.shell.commands import CommandEnv, run_command

    master, vols, filer = scrub_cluster
    base = f"http://127.0.0.1:{filer.port}"
    code, _ = _http("PUT", base + "/shell/obj.bin", os.urandom(50_000))
    assert code == 201
    vs0 = None
    for vs in vols:
        for loc in vs.store.locations:
            for vid, v in loc.volumes.items():
                if v.file_count() > 0:
                    vs0, v0 = vs, v
    nv = next(iter(v0.needle_map.items_ascending()))
    _flip_byte(v0.file_name() + ".dat", nv.offset + 30)
    env = CommandEnv(f"127.0.0.1:{master.grpc_port}")
    out = run_command(env, "volume.scrub -rate=100")
    assert "corruptNeedles=1" in out, out
    assert "finding:" in out, out
    # /debug/scrub surfaces the same state over HTTP
    code, body = _http("GET", f"http://127.0.0.1:{vs0.port}/debug/scrub")
    assert code == 200
    doc = json.loads(body)
    assert doc["counts"]["corrupt_needles"] >= 1
    assert doc["quarantine"]["needles"]
