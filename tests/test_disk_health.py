"""Disk-fault survival plane units (ISSUE 14): the health state
machine, typed error classification, ENOSPC append/delete rollback via
the `disk.write` faultpoint family, the tombstone size cap, and the
heartbeat's per-disk payload."""

from __future__ import annotations

import errno
import os

import pytest

from helpers import make_volume

from seaweedfs_tpu.storage import types as t
from seaweedfs_tpu.storage.disk_health import (
    DiskFailingError,
    DiskFullError,
    DiskHealth,
    classify_write_error,
    disk_stats,
)
from seaweedfs_tpu.storage.needle import Needle
from seaweedfs_tpu.storage.store import Store
from seaweedfs_tpu.storage.volume import Volume
from seaweedfs_tpu.util import faultpoint

GB = 1 << 30
MB = 1 << 20


@pytest.fixture(autouse=True)
def _clear_faults():
    yield
    faultpoint.clear_fault("all")


def _health(free_seq, total=100 * GB, min_free_mb=64,
            min_free_percent=1.0, eio_threshold=3):
    """DiskHealth over a scripted statvfs: free_seq values are consumed
    per poll (last value repeats)."""
    seq = list(free_seq)

    def fake(_dir):
        return total, seq.pop(0) if len(seq) > 1 else seq[0]

    return DiskHealth("/fake", min_free_mb=min_free_mb,
                      min_free_percent=min_free_percent,
                      eio_threshold=eio_threshold, statvfs=fake)


# ---------------------------------------------------------------------------
# state machine
# ---------------------------------------------------------------------------


def test_watermark_transitions():
    # floor = max(64MB, 1% of 100GB) = 1GB; low-space = 4GB
    h = _health([50 * GB, 3 * GB, 512 * MB, 2 * GB, 50 * GB])
    assert h.poll() == "healthy"
    assert h.poll() == "low_space"
    assert h.poll() == "full"
    assert not h.writable
    assert h.poll() == "low_space"  # space freed above the floor
    assert h.poll() == "healthy"
    assert h.writable


def test_enospc_forces_full_until_space_returns():
    h = _health([50 * GB, 50 * GB])
    assert h.poll() == "healthy"
    h.record_write_error(OSError(errno.ENOSPC, "no space"))
    assert h.state == "full"  # trusted over a stale statvfs
    assert h.poll() == "healthy"  # poll shows room again: cleared


def test_eio_threshold_failing_and_sticky():
    h = _health([50 * GB], eio_threshold=3)
    h.poll()
    for _ in range(2):
        h.record_write_error(OSError(errno.EIO, "io error"))
        assert h.state != "failing"
    h.record_write_error(OSError(errno.EIO, "io error"))
    assert h.state == "failing"
    # sticky: one good write (or a clean poll) does not un-fail a disk
    h.record_write_ok()
    assert h.poll() == "failing"
    h.mark_repaired()
    assert h.state == "healthy"


def test_classify_write_error():
    full = classify_write_error(OSError(errno.ENOSPC, "x"), "/d/1.dat")
    assert isinstance(full, DiskFullError)
    eio = classify_write_error(OSError(errno.EIO, "x"), "/d/1.dat")
    assert isinstance(eio, DiskFailingError)


def test_disk_stats_real_directory(tmp_path):
    total, free = disk_stats(str(tmp_path))
    assert total > 0 and 0 <= free <= total


# ---------------------------------------------------------------------------
# append/delete hardening via the disk.write faultpoint family
# ---------------------------------------------------------------------------


def _payload(i: int, size: int = 900) -> bytes:
    return bytes((i * 31 + j) % 256 for j in range(size))


def test_append_enospc_rolls_back_cleanly(tmp_path):
    vol = make_volume(str(tmp_path), n_needles=3)
    base = vol.file_name()
    pre_dat = os.path.getsize(base + ".dat")
    pre_idx = os.path.getsize(base + ".idx")
    faultpoint.set_fault("disk.write.enospc", "error", count=1,
                         match=base + ".dat")
    with pytest.raises(DiskFullError):
        vol.append_needle(Needle(cookie=1, id=50, data=_payload(50)))
    # rollback: no torn tail on disk, no index entry (memory or .idx)
    assert os.path.getsize(base + ".dat") == pre_dat
    assert os.path.getsize(base + ".idx") == pre_idx
    assert vol.needle_map.get(50) is None
    # the volume flipped read-only-full with the typed error
    assert vol.read_only and vol.read_only_reason == "full"
    with pytest.raises(DiskFullError):
        vol.append_needle(Needle(cookie=1, id=51, data=b"x"))
    # remount: durability invariant holds, prior needles byte-identical
    vol.close()
    vol2 = Volume(str(tmp_path), "", 1)
    assert vol2.read_needle(1).id == 1
    with pytest.raises(KeyError):
        vol2.read_needle(50)
    # a fresh volume is writable again (the flip was in-memory state)
    off, _size = vol2.append_needle(
        Needle(cookie=1, id=52, data=_payload(52)))
    assert vol2.read_needle(52).data == _payload(52)
    assert off % t.NEEDLE_PADDING_SIZE == 0
    vol2.close()


def test_append_eio_rolls_back_and_counts(tmp_path):
    vol = make_volume(str(tmp_path), n_needles=2)
    base = vol.file_name()
    pre = os.path.getsize(base + ".dat")
    faultpoint.set_fault("disk.write.partial", "error", count=1,
                         match=base + ".dat")
    with pytest.raises(DiskFailingError):
        vol.append_needle(Needle(cookie=1, id=9, data=_payload(9)))
    assert os.path.getsize(base + ".dat") == pre
    assert not vol.read_only  # EIO does not flip read-only-full
    assert vol.health is None  # bare Volume: no location health attached
    # next write goes through fine
    vol.append_needle(Needle(cookie=1, id=9, data=_payload(9)))
    assert vol.read_needle(9).data == _payload(9)
    vol.close()


def test_short_write_detected_and_rolled_back(tmp_path):
    vol = make_volume(str(tmp_path), n_needles=2)
    base = vol.file_name()
    pre = os.path.getsize(base + ".dat")
    # `short` models a lying device: write_at silently lands half
    faultpoint.set_fault("disk.write.short", "partial", count=1,
                         match=base + ".dat")
    with pytest.raises(DiskFailingError):
        vol.append_needle(Needle(cookie=1, id=9, data=_payload(9)))
    assert os.path.getsize(base + ".dat") == pre
    vol.close()


def test_delete_enforces_volume_size_cap(tmp_path, monkeypatch):
    vol = make_volume(str(tmp_path), n_needles=3)
    # shrink the cap under the current file size: tombstones must be
    # refused exactly like appends (offset addressability, not policy)
    monkeypatch.setattr(t, "MAX_POSSIBLE_VOLUME_SIZE", 64)
    with pytest.raises(IOError, match="size limit"):
        vol.delete_needle(2)
    assert vol.read_needle(2).id == 2  # nothing was tombstoned
    vol.close()


def test_delete_enospc_rolls_back(tmp_path):
    vol = make_volume(str(tmp_path), n_needles=3)
    base = vol.file_name()
    pre = os.path.getsize(base + ".dat")
    faultpoint.set_fault("disk.write.enospc", "error", count=1,
                         match=base + ".dat")
    with pytest.raises(DiskFullError):
        vol.delete_needle(2)
    assert os.path.getsize(base + ".dat") == pre
    assert vol.read_needle(2).id == 2  # still live: the delete failed
    # deletes are allowed on a read-only-FULL volume (they free space)
    assert vol.read_only and vol.read_only_reason == "full"
    assert vol.delete_needle(2) > 0
    with pytest.raises(KeyError):
        vol.read_needle(2)
    # appends stay refused
    with pytest.raises(DiskFullError):
        vol.append_needle(Needle(cookie=1, id=77, data=b"x"))
    vol.close()


# ---------------------------------------------------------------------------
# store-level reconciliation + heartbeat payload
# ---------------------------------------------------------------------------


def test_store_heartbeat_carries_disk_health(tmp_path):
    store = Store([str(tmp_path)], needle_cache_mb=0)
    store.add_volume(1, "")
    hb = store.collect_heartbeat()
    assert len(hb.disk_health) == 1
    d = hb.disk_health[0]
    assert d.dir == str(tmp_path)
    assert d.state == "healthy"
    assert 0 < d.free_bytes <= d.total_bytes
    store.close()


def test_store_watermark_flips_and_recovers_volumes(tmp_path):
    store = Store([str(tmp_path)], needle_cache_mb=0)
    store.add_volume(1, "")
    loc = store.locations[0]
    free = [50 * GB]
    loc.health._statvfs = lambda _d: (100 * GB, free[0])
    events = []
    store.on_disk_event = lambda: events.append(1)
    assert store.apply_disk_health()[0]["state"] == "healthy"
    v = store.find_volume(1)
    assert not v.read_only
    # disk fills: the full beat flips every volume read-only-full
    free[0] = 100 * MB
    snaps = store.apply_disk_health()
    assert snaps[0]["state"] == "full"
    assert v.read_only and v.read_only_reason == "full"
    with pytest.raises(DiskFullError):
        store.write_needle(1, Needle(cookie=1, id=5, data=b"x"))
    assert events  # the write fault woke the heartbeat
    # space returns: exactly the fault-plane flip is undone
    free[0] = 50 * GB
    store.apply_disk_health()
    assert not v.read_only
    store.write_needle(1, Needle(cookie=1, id=5, data=b"x"))
    # an operator read-only volume is NOT touched by recovery
    v.read_only, v.read_only_reason = True, ""
    store.apply_disk_health()
    assert v.read_only
    store.close()
