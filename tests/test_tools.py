"""Operator tools: offline fix/export + benchmark smoke test.

Reference analogue: weed/command/fix.go, export.go, benchmark.go.
"""

import os
import tarfile

from seaweedfs_tpu.storage.needle import FLAG_HAS_NAME, Needle
from seaweedfs_tpu.storage.volume import Volume
from seaweedfs_tpu.tools.offline import export_volume, fix_index, scan_dat_file


def _make_volume(tmp_path, vid=7, n=20):
    v = Volume(str(tmp_path), "", vid)
    for i in range(n):
        needle = Needle(cookie=0x1234, id=i + 1,
                        data=f"payload-{i}".encode() * 10)
        needle.set(FLAG_HAS_NAME)
        needle.name = f"file{i}.txt".encode()
        v.append_needle(needle)
    return v


def test_scan_dat_file(tmp_path):
    v = _make_volume(tmp_path)
    v.close()
    records = list(scan_dat_file(str(tmp_path / "7.dat")))
    assert len(records) == 20
    assert records[0][1].data == b"payload-0" * 10
    assert records[19][1].name == b"file19.txt"
    # offsets ascending and 8-aligned
    offs = [o for o, _ in records]
    assert offs == sorted(offs) and all(o % 8 == 0 for o in offs)


def test_fix_index_rebuilds_idx(tmp_path):
    v = _make_volume(tmp_path)
    v.delete_needle(3)
    v.close()
    idx = tmp_path / "7.idx"
    original = idx.read_bytes()
    idx.write_bytes(b"garbage!" * 3)  # corrupt it
    live = fix_index(str(tmp_path), 7)
    assert live == 19  # 20 written, 1 deleted
    # the rebuilt index loads and reads correctly
    v2 = Volume(str(tmp_path), "", 7)
    assert v2.read_needle(5).data == b"payload-4" * 10
    try:
        v2.read_needle(3)
        assert False, "deleted needle must stay deleted after fix"
    except KeyError:
        pass
    v2.close()
    assert len(original) % 16 == 0  # sanity on fixture


def test_export_volume(tmp_path):
    v = _make_volume(tmp_path, n=5)
    v.delete_needle(2)
    v.close()
    out = str(tmp_path / "out.tar")
    count = export_volume(str(tmp_path), 7, output=out)
    assert count == 4
    with tarfile.open(out) as tar:
        names = tar.getnames()
        assert "file0.txt" in names and "file1.txt" not in names
        data = tar.extractfile("file4.txt").read()
        assert data == b"payload-4" * 10


def test_benchmark_smoke(tmp_path):
    """Tiny write+read benchmark against an in-process cluster."""
    import socket
    import time

    from seaweedfs_tpu.master.server import MasterServer
    from seaweedfs_tpu.tools.benchmark import run_benchmark
    from seaweedfs_tpu.volume.server import VolumeServer

    def free_port():
        while True:
            with socket.socket() as s:
                s.bind(("127.0.0.1", 0))
                p = s.getsockname()[1]
            if p < 50000:
                return p

    m = MasterServer(ip="127.0.0.1", port=free_port())
    m.start()
    vs = VolumeServer(
        directories=[str(tmp_path / "bvol")],
        master_addresses=[f"127.0.0.1:{m.grpc_port}"],
        ip="127.0.0.1", port=free_port(), pulse_seconds=0.5,
    )
    os.makedirs(tmp_path / "bvol", exist_ok=True)
    vs.start()
    deadline = time.time() + 10
    while time.time() < deadline and not m.topo.nodes:
        time.sleep(0.1)
    stats = run_benchmark(
        master=f"127.0.0.1:{m.port}", num_files=24, file_size=512,
        concurrency=4,
    )
    assert len(stats["write"].latencies_ms) == 24
    assert stats["write"].failed == 0
    assert len(stats["read"].latencies_ms) == 24
    assert stats["read"].failed == 0
    vs.stop()
    m.stop()
