"""Telemetry unit tests: span recorder, traceparent, middleware, glog.

The cross-process trace assertion lives in test_trace_cluster.py; this
module covers the in-process invariants.
"""

import io
import json
import os
import threading

import pytest

from seaweedfs_tpu.telemetry import middleware, trace
from seaweedfs_tpu.util import glog


# -- traceparent -------------------------------------------------------------


def test_traceparent_format_and_parse_roundtrip():
    with trace.start_span("root") as span:
        hdr = trace.traceparent_header()
        assert hdr == f"00-{span.trace_id}-{span.span_id}-01"
        parsed = trace.parse_traceparent(hdr)
        assert parsed == (span.trace_id, span.span_id)
    assert trace.traceparent_header() is None


@pytest.mark.parametrize("bad", [
    None, "", "garbage", "00-short-span-01",
    "00-" + "g" * 32 + "-" + "1" * 16 + "-01",      # non-hex
    "00-+" + "a" * 31 + "-" + "1" * 16 + "-01",     # int() quirk: sign
    "00-" + "a_a".ljust(32, "b") + "-" + "1" * 16 + "-01",  # underscore
    "zz-" + "1" * 32 + "-" + "1" * 16 + "-01",      # non-hex version
    "ff-" + "1" * 32 + "-" + "1" * 16 + "-01",      # forbidden version
    "00-" + "0" * 32 + "-" + "1" * 16 + "-01",      # all-zero trace id
    "00-" + "1" * 32 + "-" + "0" * 16 + "-01",      # all-zero span id
])
def test_parse_traceparent_rejects_malformed(bad):
    assert trace.parse_traceparent(bad) is None


def test_remote_context_adopts_caller_trace():
    hdr = "00-" + "ab" * 16 + "-" + "cd" * 8 + "-01"
    with trace.remote_context(hdr):
        with trace.start_span("child") as span:
            assert span.trace_id == "ab" * 16
            assert span.parent_id == "cd" * 8
    # malformed header -> fresh root trace, not a crash
    with trace.remote_context("nope"):
        with trace.start_span("orphan") as span:
            assert span.parent_id == ""


# -- span recorder -----------------------------------------------------------


def test_span_nesting_links_parents():
    t = trace.Tracer(max_spans=16)
    with trace.start_span("outer", tracer=t) as outer:
        with trace.start_span("inner", tracer=t) as inner:
            pass
    assert inner.trace_id == outer.trace_id
    assert inner.parent_id == outer.span_id
    traces = t.recent_traces()
    assert len(traces) == 1
    assert [s["name"] for s in traces[0]["spans"]] == ["outer", "inner"]


def test_span_records_error_status():
    t = trace.Tracer(max_spans=4)
    with pytest.raises(ValueError):
        with trace.start_span("boom", tracer=t):
            raise ValueError("x")
    (span,) = t.spans()
    assert span.status.startswith("error")
    assert span.duration >= 0


def test_ring_buffer_is_bounded():
    t = trace.Tracer(max_spans=8)
    for i in range(50):
        with trace.start_span(f"s{i}", tracer=t):
            pass
    spans = t.spans()
    assert len(spans) == 8
    assert spans[-1].name == "s49"  # newest kept, oldest evicted


def test_wrap_context_carries_trace_into_worker_thread():
    t = trace.Tracer(max_spans=8)
    seen = {}

    def worker():
        with trace.start_span("pool-task", tracer=t) as s:
            seen["trace"] = s.trace_id
            seen["parent"] = s.parent_id

    with trace.start_span("request", tracer=t) as root:
        th = threading.Thread(target=trace.wrap_context(worker))
        th.start()
        th.join()
    assert seen["trace"] == root.trace_id
    assert seen["parent"] == root.span_id
    # without wrap_context the same worker starts an orphan trace
    th = threading.Thread(target=worker)
    th.start()
    th.join()
    assert seen["parent"] == ""


def test_traces_json_shape():
    t = trace.Tracer(max_spans=8)
    with trace.start_span("a", tracer=t, path="/x"):
        pass
    doc = json.loads(t.traces_json())
    (tr,) = doc["traces"]
    (span,) = tr["spans"]
    assert span["name"] == "a"
    assert span["attrs"] == {"path": "/x"}
    assert span["durationMs"] >= 0
    assert tr["traceId"] == span["traceId"]


# -- middleware --------------------------------------------------------------


class _FakeHandler:
    command = "GET"
    path = "/dir/assign?count=1"

    def __init__(self, headers=None):
        self.headers = headers or {}


def test_http_request_emits_counter_histogram_span():
    from seaweedfs_tpu.stats.metrics import REQUEST_COUNTER, REQUEST_HISTOGRAM

    hdr = "00-" + "77" * 16 + "-" + "88" * 8 + "-01"
    before = REQUEST_COUNTER.labels("testsrv", "op1").value
    h_child = REQUEST_HISTOGRAM.labels("testsrv", "op1")
    count_before = h_child.count
    with middleware.http_request(_FakeHandler({"traceparent": hdr}),
                                 "testsrv", "op1") as span:
        pass
    assert REQUEST_COUNTER.labels("testsrv", "op1").value == before + 1
    assert h_child.count == count_before + 1
    assert span.trace_id == "77" * 16  # joined the caller's trace
    assert span.attrs["path"] == "/dir/assign"


def test_record_op_observes_histogram_on_exception():
    from seaweedfs_tpu.stats.metrics import REQUEST_HISTOGRAM

    h_child = REQUEST_HISTOGRAM.labels("testsrv", "op2")
    before = h_child.count
    with pytest.raises(RuntimeError):
        with middleware.record_op("testsrv", "op2"):
            raise RuntimeError("x")
    assert h_child.count == before + 1


# -- glog --------------------------------------------------------------------


def test_glog_line_carries_trace_id():
    buf = io.StringIO()
    glog.set_output(buf)
    try:
        with trace.start_span("logged") as span:
            glog.info("inside span")
        glog.info("outside span")
    finally:
        import sys

        glog.set_output(sys.stderr)
    inside, outside = buf.getvalue().strip().splitlines()
    assert f"trace={span.trace_id}" in inside
    assert "trace=" not in outside


def test_glog_survives_rotation_failure(tmp_path, monkeypatch):
    """A failed os.replace must not leave the sink closed (the seed bug:
    every log line after a failed rotation was silently dropped)."""
    path = str(tmp_path / "app.log")
    glog.set_output(path, max_bytes=64)
    try:
        real_replace = os.replace

        def broken_replace(src, dst):
            raise OSError("EBUSY")

        monkeypatch.setattr(os, "replace", broken_replace)
        for i in range(5):  # every line overflows max_bytes -> rotation try
            glog.info("line %d with enough padding to cross the limit", i)
        monkeypatch.setattr(os, "replace", real_replace)
        glog.info("after recovery")
    finally:
        import sys

        glog.set_output(sys.stderr)
    content = open(path).read() + (
        open(path + ".1").read() if os.path.exists(path + ".1") else "")
    for i in range(5):
        assert f"line {i}" in content, "log line dropped after failed rotation"
    assert "after recovery" in content


def test_glog_fatal_flushes_before_exit(tmp_path):
    path = str(tmp_path / "fatal.log")
    glog.set_output(path, max_bytes=1 << 20)
    try:
        with pytest.raises(SystemExit):
            glog.fatal("dying: %s", "reason")
        assert "dying: reason" in open(path).read()
    finally:
        import sys

        glog.set_output(sys.stderr)
