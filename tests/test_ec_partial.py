"""Partial-sum repair protocol (ISSUE 10): rack-aware source planning,
serve/fetch byte identity against full-fetch rebuilds across loss
patterns and codecs, clean fallback on source death (faultpoint
`ec.partial.apply`), degraded reads through the partial path, and the
locality-labeled wire-reduction counters.

The in-process source fleet (storage.ec.partial.local_source_network)
drives the REAL serve/fetch code without sockets; the chaos test at the
bottom runs the whole thing through a live master + 4 volume servers
across two racks and kills one source mid-protocol.
"""

import json
import os
import threading
import time
import urllib.request

import numpy as np
import pytest

from seaweedfs_tpu.stats.metrics import (
    EC_PARTIAL_BYTES,
    EC_PARTIAL_FALLBACK,
    EC_PARTIAL_JOBS,
    EC_REBUILD_BYTES,
)
from seaweedfs_tpu.storage.ec import constants as ecc
from seaweedfs_tpu.storage.ec import partial as P
from seaweedfs_tpu.storage.ec.encoder import (
    generate_ec_files,
    rebuild_ec_files,
    write_sorted_file_from_idx,
)
from seaweedfs_tpu.storage.ec.volume import EcVolume
from seaweedfs_tpu.topology.placement import (
    ec_source_locality,
    group_partial_sources,
    order_ec_sources,
)
from seaweedfs_tpu.util import faultpoint

from helpers import make_volume

LARGE = 10000
SMALL = 100


# -- pure planning --------------------------------------------------------


def test_ec_source_locality():
    assert ec_source_locality("r1", "d1", "r1", "d1") == "rack"
    assert ec_source_locality("r2", "d1", "r1", "d1") == "dc"
    assert ec_source_locality("r1", "d2", "r1", "d1") == "dc"
    # unknown rack can never claim rack-locality
    assert ec_source_locality("", "d1", "r1", "d1") == "dc"


def test_order_ec_sources_prefers_rack_then_dc():
    holders = {
        0: ("n0", "r2", "d1"),   # same dc, other rack
        1: ("n1", "r1", "d1"),   # same rack
        2: ("n2", "r9", "d9"),   # other dc
        3: ("n3", "r1", "d1"),   # same rack
    }
    assert order_ec_sources(holders, "r1", "d1") == [1, 3, 0, 2]


def test_group_partial_sources_one_group_per_rack():
    holders = {
        0: ("a", "r1", "d1"),
        1: ("a", "r1", "d1"),
        2: ("b", "r1", "d1"),
        3: ("c", "r2", "d1"),
    }
    groups = group_partial_sources(holders)
    assert len(groups) == 2
    g1 = next(g for g in groups if g["rack"] == "r1")
    # aggregator holds the most shards; the single-shard member delegates
    assert g1["aggregator"] == "a"
    assert g1["members"] == {"a": [0, 1], "b": [2]}
    g2 = next(g for g in groups if g["rack"] == "r2")
    assert g2["members"] == {"c": [3]}


def test_pack_coefficients_layout():
    coef = {3: np.array([1, 2], dtype=np.uint8),
            7: np.array([5, 6], dtype=np.uint8)}
    # rows x shards, column j == shard_ids[j]
    assert P.pack_coefficients(coef, [3, 7]) == bytes([1, 5, 2, 6])


# -- fixtures -------------------------------------------------------------


@pytest.fixture()
def encoded_base(tmp_path):
    vol = make_volume(str(tmp_path), n_needles=60, seed=33, max_size=3000)
    base = vol.file_name()
    vol.close()
    generate_ec_files(base, large_block_size=LARGE, small_block_size=SMALL,
                      codec_name="cpu", slice_size=1 << 20)
    write_sorted_file_from_idx(base)
    return base


def _shard_bytes(base):
    return {i: open(base + ecc.to_ext(i), "rb").read()
            for i in range(ecc.TOTAL_SHARDS)}


def _fleet(base, lost, rack_of=lambda sid: f"rack{sid % 2}"):
    """One fake node per surviving shard; returns (client kwargs)."""
    nodes, holders = {}, {}
    for sid in range(ecc.TOTAL_SHARDS):
        if sid in lost:
            continue
        addr = f"src-{sid}:0"
        nodes[addr] = (base, [sid])
        holders[sid] = [(addr, rack_of(sid), "dc1")]
    stub_for = P.local_source_network(nodes)
    return P.PartialRepairClient(
        1, "", lambda: holders, stub_for, my_rack="rack0", my_dc="dc1")


def _full_fetch(base, lost):
    def fetch(sid, off, length):
        if sid in lost:
            return None
        with open(base + ecc.to_ext(sid), "rb") as f:
            f.seek(off)
            return f.read(length)

    return fetch


# -- rebuild byte identity ------------------------------------------------

LOSS_PATTERNS = [
    (0,),
    (13,),
    (0, 1, 2, 3),          # worst case: 4 data shards
    (10, 11, 12, 13),      # all parity
    (2, 7, 11, 13),        # mixed
]


@pytest.mark.parametrize("lost", LOSS_PATTERNS)
@pytest.mark.parametrize("codec_name", ["cpu", "tpu"])
def test_partial_rebuild_byte_identity(encoded_base, tmp_path, lost,
                                       codec_name):
    """All 10 sources remote: the aggregated partials must reproduce the
    full-fetch rebuild bytes exactly for data/parity/mixed losses on the
    host AND device codec paths."""
    originals = _shard_bytes(encoded_base)
    rdir = tmp_path / "rebuilder"
    rdir.mkdir()
    rbase = str(rdir / "1")
    client = _fleet(encoded_base, set(lost))
    rebuilt = rebuild_ec_files(
        rbase, codec_name=codec_name, slice_size=1000,
        remote_fetch=_full_fetch(encoded_base, set(lost)), partial=client)
    assert sorted(rebuilt) == sorted(lost)
    for sid in lost:
        got = open(rbase + ecc.to_ext(sid), "rb").read()
        assert got == originals[sid], f"shard {sid} differs via partial"


def test_partial_rebuild_with_local_sources(encoded_base, tmp_path):
    """Mixed sourcing: local shards' plan columns applied at the
    rebuilder, remote columns via partials — XOR must close the GF sum."""
    originals = _shard_bytes(encoded_base)
    rdir = tmp_path / "mixed"
    rdir.mkdir()
    rbase = str(rdir / "1")
    # rebuilder already holds shards 0-5; 2 is lost cluster-wide
    for sid in (0, 1, 3, 4, 5):
        os.link(encoded_base + ecc.to_ext(sid), rbase + ecc.to_ext(sid))
    lost = {2}
    client = _fleet(encoded_base, lost | {0, 1, 3, 4, 5})
    before = EC_PARTIAL_JOBS.labels("fetch", "ok").value
    rebuilt = rebuild_ec_files(
        rbase, codec_name="cpu", slice_size=1000,
        remote_fetch=_full_fetch(encoded_base, lost), partial=client)
    assert rebuilt == [2]
    assert open(rbase + ecc.to_ext(2), "rb").read() == originals[2]
    assert EC_PARTIAL_JOBS.labels("fetch", "ok").value > before


def test_partial_rebuild_wire_reduction_counters(encoded_base, tmp_path):
    """The acceptance headline: one lost shard, all 10 sources remote on
    2 racks -> partial ingress is 5x below full-fetch ingress, visible in
    the locality-labeled rebuild counters."""
    lost = {0}
    shard_size = os.path.getsize(encoded_base + ecc.to_ext(1))

    def leg(name, **kw):
        rdir = tmp_path / name
        rdir.mkdir()
        before = {lab: EC_REBUILD_BYTES.labels(lab).value
                  for lab in ("local", "rack", "dc")}
        rebuilt = rebuild_ec_files(
            str(rdir / "1"), codec_name="cpu", slice_size=1000,
            shard_size=shard_size, **kw)
        assert rebuilt == [0]
        return {lab: EC_REBUILD_BYTES.labels(lab).value - before[lab]
                for lab in ("local", "rack", "dc")}

    fetch = _full_fetch(encoded_base, lost)
    fetch.locality_of = lambda sid: "rack" if sid % 2 == 0 else "dc"
    full = leg("full", remote_fetch=fetch)
    part = leg("partial", remote_fetch=_full_fetch(encoded_base, lost),
               partial=_fleet(encoded_base, lost))
    assert full["rack"] + full["dc"] == 10 * shard_size
    # 2 racks -> 2 aggregated partials of (1 x shard_size) each
    assert part["rack"] + part["dc"] == 2 * shard_size
    assert (full["rack"] + full["dc"]) / (part["rack"] + part["dc"]) >= 5.0
    assert part["rack"] == shard_size and part["dc"] == shard_size


def test_partial_source_death_falls_back_clean(encoded_base, tmp_path):
    """faultpoint ec.partial.apply kills one source mid-protocol: the
    rebuild degrades to full fetches in place (fallback counter), output
    stays byte-identical, and no partial .ecNN survives a TOTAL failure."""
    originals = _shard_bytes(encoded_base)
    lost = {0, 13}
    rdir = tmp_path / "fb"
    rdir.mkdir()
    rbase = str(rdir / "1")
    faultpoint.set_fault("ec.partial.apply", "error", match="src-1:0")
    try:
        before = EC_PARTIAL_FALLBACK.labels("rebuild").value
        rebuilt = rebuild_ec_files(
            rbase, codec_name="cpu", slice_size=1000,
            remote_fetch=_full_fetch(encoded_base, lost),
            partial=_fleet(encoded_base, lost, rack_of=lambda sid: "rack0"))
        assert sorted(rebuilt) == sorted(lost)
        assert EC_PARTIAL_FALLBACK.labels("rebuild").value == before + 1
        for sid in lost:
            got = open(rbase + ecc.to_ext(sid), "rb").read()
            assert got == originals[sid]
    finally:
        faultpoint.clear_fault("ec.partial.apply")

    # total failure (no fallback transport): clean error, outputs removed
    rdir2 = tmp_path / "fb2"
    rdir2.mkdir()
    rbase2 = str(rdir2 / "1")
    faultpoint.set_fault("ec.partial.apply", "error")
    try:
        with pytest.raises((IOError, ValueError)):
            rebuild_ec_files(
                rbase2, codec_name="cpu", slice_size=1000,
                partial=_fleet(encoded_base, lost,
                               rack_of=lambda sid: "rack0"))
        for sid in lost:
            assert not os.path.exists(rbase2 + ecc.to_ext(sid)), \
                "partial output must not survive a failed rebuild"
    finally:
        faultpoint.clear_fault("ec.partial.apply")


def test_partial_skipped_when_full_fetch_is_cheaper(encoded_base,
                                                    tmp_path):
    """4 lost shards with only 3 remote sources: partial would pull
    racks x 4 x width > 3 x width, so the rebuilder must choose the
    full-fetch path outright (no partial jobs, no fallback counted as
    an error path)."""
    originals = _shard_bytes(encoded_base)
    lost = (0, 1, 2, 3)
    rdir = tmp_path / "cheaper"
    rdir.mkdir()
    rbase = str(rdir / "1")
    # rebuilder holds 7 shards locally; only 3 sources are remote
    for sid in (4, 5, 6, 7, 8, 9, 13):
        os.link(encoded_base + ecc.to_ext(sid), rbase + ecc.to_ext(sid))
    client = _fleet(encoded_base, set(lost) | {4, 5, 6, 7, 8, 9, 13})
    assert client.ingress_advantage([10, 11, 12], 4) < 1.0
    fetched_ok = EC_PARTIAL_JOBS.labels("fetch", "ok").value
    rebuilt = rebuild_ec_files(
        rbase, codec_name="cpu", slice_size=1000,
        remote_fetch=_full_fetch(encoded_base, set(lost)), partial=client)
    assert sorted(rebuilt) == sorted(lost)
    assert EC_PARTIAL_JOBS.labels("fetch", "ok").value == fetched_ok
    for sid in lost:
        assert open(rbase + ecc.to_ext(sid), "rb").read() == originals[sid]


def test_partial_probe_answers_shard_size(encoded_base):
    client = _fleet(encoded_base, {0})
    assert client.shard_size() == os.path.getsize(
        encoded_base + ecc.to_ext(1))


def test_serve_partial_rejects_bad_geometry(encoded_base):
    from types import SimpleNamespace

    req = SimpleNamespace(row_count=2, shard_ids=[1], coefficients=b"\x01",
                          size=10, offset=0, delegates=[], volume_id=1,
                          collection="")
    with pytest.raises(ValueError):
        P.serve_partial(req, lambda sid, off, ln: b"\0" * ln)
    # a missing local shard must fail the serve, not zero-fill it
    req2 = SimpleNamespace(row_count=1, shard_ids=[1],
                           coefficients=b"\x01", size=10, offset=0,
                           delegates=[], volume_id=1, collection="")
    with pytest.raises(IOError):
        P.serve_partial(req2, lambda sid, off, ln: None)


# -- degraded reads -------------------------------------------------------


def test_degraded_read_partial_byte_identity(tmp_path):
    """Needles whose intervals live on LOST shards reconstruct through
    one 1 x W partial per rack, byte-identical to the gathered path."""
    vol = make_volume(str(tmp_path), n_needles=50, seed=5)
    vol.sync()
    base = vol.file_name()
    generate_ec_files(base, large_block_size=LARGE, small_block_size=SMALL)
    write_sorted_file_from_idx(base)
    wants = {i: bytes(vol.read_needle(i).data) for i in range(1, 51)}
    vol.close()
    full = tmp_path / "fullcopy"
    full.mkdir()
    fbase = str(full / "1")
    for sid in range(ecc.TOTAL_SHARDS):
        os.link(base + ecc.to_ext(sid), fbase + ecc.to_ext(sid))
    # shard 0 lost cluster-wide, 1-7 remote, 8-13 local
    for sid in range(0, 8):
        os.remove(base + ecc.to_ext(sid))
    nodes, holders = {}, {}
    for sid in range(1, 8):
        addr = f"deg-{sid}:0"
        nodes[addr] = (fbase, [sid])
        holders[sid] = [(addr, f"rack{sid % 2}", "dc1")]
    ev = EcVolume(base, 1, large_block_size=LARGE, small_block_size=SMALL)
    ev.partial_client = P.PartialRepairClient(
        1, "", lambda: holders, P.local_source_network(nodes),
        my_rack="rack0", my_dc="dc1")
    before = EC_PARTIAL_JOBS.labels("fetch", "ok").value
    for i in range(1, 51):
        assert bytes(ev.read_needle(i).data) == wants[i], f"needle {i}"
    assert EC_PARTIAL_JOBS.labels("fetch", "ok").value > before
    ev.close()


def test_degraded_read_partial_falls_back(tmp_path):
    """A dead partial client must not fail the read — the gather path
    serves it and the degraded fallback counter moves."""
    vol = make_volume(str(tmp_path), n_needles=20, seed=6)
    vol.sync()
    base = vol.file_name()
    generate_ec_files(base, large_block_size=LARGE, small_block_size=SMALL)
    write_sorted_file_from_idx(base)
    wants = {i: bytes(vol.read_needle(i).data) for i in range(1, 21)}
    vol.close()
    full = tmp_path / "fullcopy"
    full.mkdir()
    fbase = str(full / "1")
    for sid in range(ecc.TOTAL_SHARDS):
        os.link(base + ecc.to_ext(sid), fbase + ecc.to_ext(sid))
    for sid in range(0, 8):
        os.remove(base + ecc.to_ext(sid))

    class Dead:
        def remote_shards(self):
            raise IOError("master unreachable")

    ev = EcVolume(base, 1, large_block_size=LARGE, small_block_size=SMALL)
    ev.partial_client = Dead()
    # shard 0 is lost cluster-wide, so reads MUST reconstruct
    ev.remote_fetch = _full_fetch(fbase, {0})
    before = EC_PARTIAL_FALLBACK.labels("degraded").value
    for i in range(1, 21):
        assert bytes(ev.read_needle(i).data) == wants[i]
    assert EC_PARTIAL_FALLBACK.labels("degraded").value > before
    ev.close()


# -- shell plan (pure) ----------------------------------------------------


def test_rebuild_plan_prefers_same_rack_sources():
    from seaweedfs_tpu.shell.ec_commands import _rebuild_plan
    from seaweedfs_tpu.storage.ec.shard_bits import ShardBits

    def bits(*sids):
        b = ShardBits(0)
        for s in sids:
            b = b.add(s)
        return b

    by_node = {
        "reb:80": bits(0, 1, 2, 3),
        "a:80": bits(4, 5, 6),
        "b:80": bits(7, 8, 9),
        "c:80": bits(10, 11, 12),   # shard 13 lost
    }
    have = bits(*range(13))
    locality = {
        "reb:80": ("rack0", "dc1"),
        "a:80": ("rack0", "dc1"),
        "b:80": ("rack1", "dc1"),
        "c:80": ("rack2", "dc2"),
    }
    plan = _rebuild_plan(13, by_node, have, locality)
    assert plan["rebuilder"] == "reb:80"
    assert plan["lost"] == [13]
    assert plan["local_sources"] == [0, 1, 2, 3]
    # 6 remote sources topped up same-rack first: all of a's, then b/c
    assert set(plan["remote_sources"]) == {4, 5, 6, 7, 8, 9}
    racks = {g["rack"] for g in plan["groups"]}
    assert racks == {"rack0", "rack1"}


# -- chaos: live cluster, source killed mid-partial-stream ----------------


def _http(method, url, data=None):
    req = urllib.request.Request(url, data=data, method=method)
    try:
        with urllib.request.urlopen(req, timeout=10) as r:
            return r.status, r.read()
    except urllib.error.HTTPError as e:
        return e.code, e.read()


@pytest.mark.chaos
def test_partial_rebuild_cluster_chaos(tmp_path):
    """Master + 4 volume servers across 2 racks: encode, lose one shard
    cluster-wide, kill one SOURCE mid-partial-protocol (faultpoint
    ec.partial.apply scoped to that node), and assert the shell rebuild
    still completes with byte-identical reads and ZERO client 5xx while
    concurrent GETs hammer the EC volume.  A second loss then rebuilds
    with no fault and must ride the partial path end to end."""
    from seaweedfs_tpu.master.server import MasterServer
    from seaweedfs_tpu.shell.commands import CommandEnv, run_command
    from seaweedfs_tpu.volume.server import VolumeServer

    from helpers import free_port

    master = MasterServer(ip="127.0.0.1", port=free_port(),
                          volume_size_limit_mb=64)
    master.start()
    servers = []
    try:
        for i in range(4):
            d = tmp_path / f"vol{i}"
            d.mkdir()
            s = VolumeServer(
                directories=[str(d)],
                master_addresses=[f"127.0.0.1:{master.grpc_port}"],
                ip="127.0.0.1", port=free_port(), pulse_seconds=0.5,
                rack=f"rack{i % 2}", data_center="dc1",
                max_volume_count=50,
            )
            s.start()
            servers.append(s)
        deadline = time.time() + 15
        while time.time() < deadline and len(master.topo.nodes) < 4:
            time.sleep(0.1)
        assert len(master.topo.nodes) == 4

        # write one collection and EC-encode it
        payloads = {}
        for i in range(24):
            code, body = _http(
                "GET",
                f"http://127.0.0.1:{master.port}/dir/assign?collection=pc")
            a = json.loads(body)
            payload = (f"pc-needle-{i}-".encode() * 331)[:4000]
            code, _ = _http("POST", f"http://{a['url']}/{a['fid']}", payload)
            assert code == 201
            payloads[a["fid"]] = payload
        vid = int(next(iter(payloads)).split(",")[0])
        env = CommandEnv(f"127.0.0.1:{master.grpc_port}")
        out = run_command(env, f"ec.encode -volumeId={vid} -collection=pc")
        assert f"ec.encode {vid}" in out
        deadline = time.time() + 20
        while time.time() < deadline and len(
                master.topo.lookup_ec_shards(vid)) < 14:
            time.sleep(0.2)
        assert len(master.topo.lookup_ec_shards(vid)) == 14

        def lose_one_shard():
            holders = [s for s in servers if s.store.find_ec_volume(vid)]
            victim = min(holders, key=lambda s: len(
                s.store.find_ec_volume(vid).shard_ids()))
            sid = victim.store.find_ec_volume(vid).shard_ids()[0]
            victim.store.delete_ec_shards(vid, "pc", [sid])
            deadline = time.time() + 15
            while time.time() < deadline and len(
                    master.topo.lookup_ec_shards(vid)) == 14:
                time.sleep(0.2)
            return sid

        def all_mounted():
            # the MASTER's view gates progress: the next loss/rebuild
            # round plans from it, so a server-only check would race the
            # mount registration delta and plan against a stale map
            if len(master.topo.lookup_ec_shards(vid)) != 14:
                return False
            total = set()
            for s in servers:
                ev = s.store.find_ec_volume(vid)
                if ev:
                    total.update(ev.shard_ids())
            return len(total) == 14

        def check_reads() -> int:
            bad = 0
            holder = next(s for s in servers
                          if s.store.find_ec_volume(vid) is not None)
            for fid, want in list(payloads.items())[:6]:
                code, got = _http(
                    "GET", f"http://127.0.0.1:{holder.port}/{fid}")
                if code >= 500:
                    bad += 1
                elif code == 200:
                    assert got == want, f"corrupt read for {fid}"
            return bad

        lose_one_shard()

        # the plan dry-run names sources with racks and touches nothing
        plan_out = run_command(env, "ec.rebuild -plan")
        assert "(plan)" in plan_out and "rack" in plan_out
        assert len(master.topo.lookup_ec_shards(vid)) < 14

        # kill ONE source mid-partial-protocol; concurrent reads must
        # see zero 5xx and the rebuild must complete via fallback
        victim_src = next(
            s for s in servers if s.store.find_ec_volume(vid) is not None)
        faultpoint.set_fault(
            "ec.partial.apply", "error",
            match=f"127.0.0.1:{victim_src.port}")
        errs_5xx = []
        stop = threading.Event()

        def hammer():
            while not stop.is_set():
                errs_5xx.append(check_reads())
                time.sleep(0.05)

        t = threading.Thread(target=hammer, daemon=True)
        t.start()
        try:
            out = run_command(env, "ec.rebuild")
            assert "rebuilt" in out
        finally:
            stop.set()
            t.join(timeout=10)
            faultpoint.clear_fault("ec.partial.apply")
        deadline = time.time() + 15
        while time.time() < deadline and not all_mounted():
            time.sleep(0.2)
        assert all_mounted(), "rebuild did not restore all 14 shards"
        assert sum(errs_5xx) == 0, "client 5xx during chaos rebuild"
        assert check_reads() == 0

        # clean second loss: the partial path itself must carry it
        before_ok = EC_PARTIAL_JOBS.labels("fetch", "ok").value
        before_bytes = EC_PARTIAL_BYTES.labels("recv").value
        lose_one_shard()
        out = run_command(env, "ec.rebuild")
        assert "rebuilt" in out
        deadline = time.time() + 15
        while time.time() < deadline and not all_mounted():
            time.sleep(0.2)
        assert all_mounted()
        assert EC_PARTIAL_JOBS.labels("fetch", "ok").value > before_ok
        assert EC_PARTIAL_BYTES.labels("recv").value > before_bytes
        assert check_reads() == 0
    finally:
        for s in servers:
            s.stop()
        master.stop()
