"""SQL-on-blob Query rpc + VolumeNeedleStatus.

Reference: weed/server/volume_grpc_query.go:12, weed/query/json/,
volume_server.proto QueryRequest/QueriedStripe/VolumeNeedleStatus.
"""

from __future__ import annotations

import json
import time
import urllib.request

import pytest

from helpers import free_port
from seaweedfs_tpu.pb import rpc as rpclib
from seaweedfs_tpu.pb import volume_server_pb2 as vs
from seaweedfs_tpu.query.engine import query_csv_lines, query_json_lines


# -- pure engine -------------------------------------------------------------


def test_json_filter_and_projection():
    data = (b'{"name":"a","age":30,"addr":{"city":"sf"}}\n'
            b'{"name":"b","age":5,"addr":{"city":"nyc"}}\n'
            b'{"name":"c","age":40,"addr":{"city":"sf"}}\n')
    out = query_json_lines(data, ["name"], field="age", op=">=", value="30")
    rows = [json.loads(line) for line in out.splitlines()]
    assert rows == [{"name": "a"}, {"name": "c"}]
    # nested dotted path filter, full-record projection
    out = query_json_lines(data, [], field="addr.city", op="=", value="nyc")
    rows = [json.loads(line) for line in out.splitlines()]
    assert len(rows) == 1 and rows[0]["name"] == "b"
    # string comparison
    out = query_json_lines(data, ["age"], field="name", op="!=", value="b")
    assert [json.loads(r)["age"] for r in out.splitlines()] == [30, 40]


def test_csv_filter_and_projection():
    data = b"name,age,city\na,30,sf\nb,5,nyc\nc,40,sf\n"
    out = query_csv_lines(data, ["name", "city"],
                          field="age", op=">", value="10")
    assert out == b"a,sf\nc,sf\n"
    # positional columns without a header row
    data2 = b"a,30\nb,5\n"
    out = query_csv_lines(data2, ["_1"], field="_2", op="<", value="10",
                          header="NONE")
    assert out == b"b\n"


# -- over the wire -----------------------------------------------------------


@pytest.fixture(scope="module")
def volume_cluster(tmp_path_factory):
    from seaweedfs_tpu.master.server import MasterServer
    from seaweedfs_tpu.volume.server import VolumeServer

    master = MasterServer(ip="127.0.0.1", port=free_port(),
                          volume_size_limit_mb=64)
    master.start()
    vsrv = VolumeServer(
        directories=[str(tmp_path_factory.mktemp("queryvol"))],
        master_addresses=[f"127.0.0.1:{master.grpc_port}"],
        ip="127.0.0.1", port=free_port(), pulse_seconds=0.5,
    )
    vsrv.start()
    deadline = time.time() + 15
    while time.time() < deadline and len(master.topo.nodes) < 1:
        time.sleep(0.1)
    yield master, vsrv
    vsrv.stop()
    master.stop()


def _upload(master, vsrv, payload: bytes) -> str:
    with urllib.request.urlopen(
        f"http://127.0.0.1:{master.port}/dir/assign", timeout=10
    ) as r:
        a = json.loads(r.read())
    fid = a["fid"]
    boundary = "qb"
    body = (f"--{boundary}\r\nContent-Disposition: form-data; "
            f'name="file"; filename="q.json"\r\n'
            f"Content-Type: application/json\r\n\r\n").encode() + \
        payload + f"\r\n--{boundary}--\r\n".encode()
    req = urllib.request.Request(
        f"http://{a['url']}/{fid}", data=body, method="POST",
        headers={"Content-Type":
                 f"multipart/form-data; boundary={boundary}"})
    with urllib.request.urlopen(req, timeout=10) as r:
        r.read()
    return fid


def test_query_rpc_json_where(volume_cluster):
    master, vsrv = volume_cluster
    lines = b"\n".join(
        json.dumps({"user": f"u{i}", "score": i * 10}).encode()
        for i in range(8))
    fid = _upload(master, vsrv, lines)
    stub = rpclib.volume_server_stub(
        f"127.0.0.1:{vsrv.grpc_port}", timeout=20)
    req = vs.QueryRequest(
        selections=["user"], from_file_ids=[fid],
        filter=vs.QueryRequest.Filter(field="score", operand=">=",
                                      value="50"),
        input_serialization=vs.QueryRequest.InputSerialization(
            json_input=vs.QueryRequest.InputSerialization.JSONInput(
                type="LINES")),
    )
    records = b"".join(s.records for s in stub.Query(req))
    users = [json.loads(r)["user"] for r in records.splitlines()]
    assert users == ["u5", "u6", "u7"]


def test_query_rpc_csv(volume_cluster):
    master, vsrv = volume_cluster
    fid = _upload(master, vsrv, b"city,pop\nsf,800\nnyc,8000\nla,4000\n")
    stub = rpclib.volume_server_stub(
        f"127.0.0.1:{vsrv.grpc_port}", timeout=20)
    req = vs.QueryRequest(
        selections=["city"], from_file_ids=[fid],
        filter=vs.QueryRequest.Filter(field="pop", operand=">",
                                      value="1000"),
        input_serialization=vs.QueryRequest.InputSerialization(
            csv_input=vs.QueryRequest.InputSerialization.CSVInput(
                file_header_info="USE")),
    )
    records = b"".join(s.records for s in stub.Query(req))
    assert records == b"nyc\nla\n"


def test_volume_needle_status(volume_cluster):
    master, vsrv = volume_cluster
    fid = _upload(master, vsrv, b"status-check-payload")
    vid, _, ncookie = fid.partition(",")
    from seaweedfs_tpu.storage.file_id import FileId

    parsed = FileId.parse(fid)
    stub = rpclib.volume_server_stub(
        f"127.0.0.1:{vsrv.grpc_port}", timeout=20)
    resp = stub.VolumeNeedleStatus(vs.VolumeNeedleStatusRequest(
        volume_id=parsed.volume_id, needle_id=parsed.key))
    assert resp.needle_id == parsed.key
    assert resp.cookie == parsed.cookie
    assert resp.size == len(b"status-check-payload")
