"""IAM API: user/key/policy CRUD persisted through the filer, picked up
live by the s3 gateway.

Reference: weed/iamapi/iamapi_management_handlers.go (action switch),
iamapi_server.go (config at /etc/iam/identity.json inside the filer).
"""

from __future__ import annotations

import hashlib
import socket
import time
import urllib.error
import urllib.parse
import urllib.request
import xml.etree.ElementTree as ET

import pytest

import seaweedfs_tpu.s3api.auth as s3auth
from seaweedfs_tpu.iamapi.server import (
    actions_to_policy,
    policy_to_actions,
)


def _free_port() -> int:
    from helpers import free_port

    return free_port()


def _strip_ns(root: ET.Element) -> ET.Element:
    for el in root.iter():
        el.tag = el.tag.rpartition("}")[2]
    return root


def _iam_post(port: int, params: dict, headers: dict | None = None):
    body = urllib.parse.urlencode(params).encode()
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/", data=body, method="POST",
        headers={"Content-Type": "application/x-www-form-urlencoded",
                 **(headers or {})},
    )
    try:
        with urllib.request.urlopen(req, timeout=10) as r:
            return r.status, _strip_ns(ET.fromstring(r.read()))
    except urllib.error.HTTPError as e:
        return e.code, _strip_ns(ET.fromstring(e.read()))


def _sign_v4(method, host, port, path, access_key, secret, body=b""):
    amz_date = time.strftime("%Y%m%dT%H%M%SZ", time.gmtime())
    date = amz_date[:8]
    payload_hash = hashlib.sha256(body).hexdigest()
    headers = {
        "host": f"{host}:{port}",
        "x-amz-content-sha256": payload_hash,
        "x-amz-date": amz_date,
    }
    signed = sorted(headers)
    canon = s3auth.canonical_request(
        method, path, "", headers, signed, payload_hash)
    sig = s3auth.sign_v4(secret, date, "us-east-1", "s3", amz_date, canon)
    headers["Authorization"] = (
        f"AWS4-HMAC-SHA256 Credential={access_key}/{date}/us-east-1/s3/"
        f"aws4_request, SignedHeaders={';'.join(signed)}, Signature={sig}"
    )
    return headers


def _s3_req(port, method, path, headers, body=None):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}", data=body, method=method,
        headers=headers)
    try:
        with urllib.request.urlopen(req, timeout=10) as r:
            return r.status, r.read()
    except urllib.error.HTTPError as e:
        return e.code, e.read()


@pytest.fixture(scope="module")
def iam_stack(tmp_path_factory):
    from seaweedfs_tpu.filer.server import FilerServer
    from seaweedfs_tpu.iamapi.server import IamApiServer
    from seaweedfs_tpu.master.server import MasterServer
    from seaweedfs_tpu.s3api.server import S3ApiServer
    from seaweedfs_tpu.volume.server import VolumeServer

    master = MasterServer(ip="127.0.0.1", port=_free_port(),
                          volume_size_limit_mb=64)
    master.start()
    vs = VolumeServer(
        directories=[str(tmp_path_factory.mktemp("iamvol"))],
        master_addresses=[f"127.0.0.1:{master.grpc_port}"],
        ip="127.0.0.1", port=_free_port(), pulse_seconds=0.5,
        max_volume_count=100,
    )
    vs.start()
    deadline = time.time() + 15
    while time.time() < deadline and len(master.topo.nodes) < 1:
        time.sleep(0.1)
    filer = FilerServer(
        masters=[f"127.0.0.1:{master.grpc_port}"],
        ip="127.0.0.1", port=_free_port(), store="memory", max_mb=1,
    )
    filer.start()
    iam = IamApiServer(filer=f"127.0.0.1:{filer.port}", port=_free_port())
    iam.start()
    s3 = S3ApiServer(
        filer=f"127.0.0.1:{filer.port}", port=_free_port(),
        iam_config_filer_path="/etc/iam/identity.json",
        iam_refresh_seconds=0.2,
    )
    s3.start()
    yield iam, s3
    s3.stop()
    iam.stop()
    filer.stop()
    vs.stop()
    master.stop()


def test_policy_action_mapping_roundtrip():
    doc = {
        "Version": "2012-10-17",
        "Statement": [
            {"Effect": "Allow", "Action": ["s3:Get*", "s3:List*"],
             "Resource": ["arn:aws:s3:::mybucket/*"]},
            {"Effect": "Allow", "Action": ["s3:Put*"],
             "Resource": ["arn:aws:s3:::*"]},
            {"Effect": "Deny", "Action": ["s3:Get*"],
             "Resource": ["arn:aws:s3:::secret/*"]},
        ],
    }
    actions = policy_to_actions(doc)
    assert actions == ["Read:mybucket", "List:mybucket", "Write"]
    back = actions_to_policy(actions)
    flat = {(s["Resource"][0], a)
            for s in back["Statement"] for a in s["Action"]}
    assert ("arn:aws:s3:::mybucket/*", "s3:Get*") in flat
    assert ("*", "s3:Put*") in flat


def test_iam_user_key_policy_lifecycle(iam_stack):
    iam, s3 = iam_stack
    ip, sp = iam.port, s3.port

    # create a user, then an access key for it
    code, root = _iam_post(ip, {"Action": "CreateUser",
                                "UserName": "alice"})
    assert code == 200 and root.find(".//UserName").text == "alice"

    code, root = _iam_post(ip, {"Action": "CreateAccessKey",
                                "UserName": "alice"})
    assert code == 200
    access_key = root.find(".//AccessKeyId").text
    secret_key = root.find(".//SecretAccessKey").text
    assert len(access_key) == 21 and len(secret_key) == 42

    # grant admin via a policy document
    code, _ = _iam_post(ip, {
        "Action": "PutUserPolicy", "UserName": "alice",
        "PolicyName": "admin",
        "PolicyDocument":
            '{"Version":"2012-10-17","Statement":[{"Effect":"Allow",'
            '"Action":["s3:*"],"Resource":["arn:aws:s3:::*"]}]}',
    })
    assert code == 200

    # the s3 gateway picks the identity up and accepts signed requests
    deadline = time.time() + 10
    while time.time() < deadline:
        h = _sign_v4("PUT", "127.0.0.1", sp, "/iambucket",
                     access_key, secret_key)
        code, body = _s3_req(sp, "PUT", "/iambucket", h)
        if code == 200:
            break
        time.sleep(0.3)
    assert code == 200, body

    h = _sign_v4("PUT", "127.0.0.1", sp, "/iambucket/hello.txt",
                 access_key, secret_key, b"hi from iam")
    code, body = _s3_req(sp, "PUT", "/iambucket/hello.txt", h,
                         b"hi from iam")
    assert code == 200, body
    h = _sign_v4("GET", "127.0.0.1", sp, "/iambucket/hello.txt",
                 access_key, secret_key)
    code, body = _s3_req(sp, "GET", "/iambucket/hello.txt", h)
    assert code == 200 and body == b"hi from iam"

    # listing surfaces the user and the key
    code, root = _iam_post(ip, {"Action": "ListUsers"},
                           _sign_v4("POST", "127.0.0.1", ip, "/",
                                    access_key, secret_key,
                                    urllib.parse.urlencode(
                                        {"Action": "ListUsers"}).encode()))
    assert code == 200
    assert "alice" in [u.text for u in root.findall(".//UserName")]

    # GetUserPolicy reconstructs a policy document
    body = urllib.parse.urlencode({"Action": "GetUserPolicy",
                                   "UserName": "alice",
                                   "PolicyName": "admin"}).encode()
    code, root = _iam_post(
        ip, {"Action": "GetUserPolicy", "UserName": "alice",
             "PolicyName": "admin"},
        _sign_v4("POST", "127.0.0.1", ip, "/", access_key, secret_key,
                 body))
    assert code == 200
    assert "s3:*" in root.find(".//PolicyDocument").text

    # unsigned IAM calls are rejected once identities exist
    code, root = _iam_post(ip, {"Action": "ListUsers"})
    assert code == 403

    # delete the key: signed s3 requests must stop working
    body = urllib.parse.urlencode({
        "Action": "DeleteAccessKey", "UserName": "alice",
        "AccessKeyId": access_key}).encode()
    code, _ = _iam_post(
        ip, {"Action": "DeleteAccessKey", "UserName": "alice",
             "AccessKeyId": access_key},
        _sign_v4("POST", "127.0.0.1", ip, "/", access_key, secret_key,
                 body))
    assert code == 200
    deadline = time.time() + 10
    while time.time() < deadline:
        h = _sign_v4("GET", "127.0.0.1", sp, "/iambucket/hello.txt",
                     access_key, secret_key)
        code, body = _s3_req(sp, "GET", "/iambucket/hello.txt", h)
        if code == 403:
            break
        time.sleep(0.3)
    assert code == 403, body
