"""Cluster integration: in-process master + volume servers over real gRPC/HTTP.

The tier-4 harness (SURVEY.md §4): write/read/delete through the public
HTTP surface after a master Assign, replication fan-out, then the full
ec.encode -> spread -> degraded-read -> ec.rebuild admin flow via the shell.
"""

import json
import socket
import time
import urllib.request

import pytest

from seaweedfs_tpu.master.server import MasterServer
from seaweedfs_tpu.shell.commands import CommandEnv, run_command
from seaweedfs_tpu.volume.server import VolumeServer


def _free_port() -> int:
    from helpers import free_port

    return free_port()


def _http(method: str, url: str, data: bytes | None = None) -> tuple[int, bytes]:
    req = urllib.request.Request(url, data=data, method=method)
    try:
        with urllib.request.urlopen(req, timeout=10) as r:
            return r.status, r.read()
    except urllib.error.HTTPError as e:
        return e.code, e.read()


@pytest.fixture(scope="module")
def cluster(tmp_path_factory):
    mport = _free_port()
    master = MasterServer(ip="127.0.0.1", port=mport, volume_size_limit_mb=64)
    master.start()
    servers = []
    for i in range(3):
        vport = _free_port()
        d = tmp_path_factory.mktemp(f"vol{i}")
        vs_ = VolumeServer(
            directories=[str(d)],
            master_addresses=[f"127.0.0.1:{master.grpc_port}"],
            ip="127.0.0.1",
            port=vport,
            pulse_seconds=0.5,
            rack=f"rack{i % 2}",
            max_volume_count=50,  # keep free EC slots on every node so
            # shard spread never degenerates to a single holder
        )
        vs_.start()
        servers.append(vs_)
    # wait for all three nodes to register
    deadline = time.time() + 15
    while time.time() < deadline:
        if len(master.topo.nodes) == 3:
            break
        time.sleep(0.1)
    assert len(master.topo.nodes) == 3, "volume servers did not register"
    yield master, servers
    for s in servers:
        s.stop()
    master.stop()


def _assign(master, **params) -> dict:
    qs = "&".join(f"{k}={v}" for k, v in params.items())
    code, body = _http(
        "GET", f"http://127.0.0.1:{master.port}/dir/assign?{qs}"
    )
    assert code == 200, body
    return json.loads(body)


def test_write_read_delete(cluster):
    master, _ = cluster
    a = _assign(master)
    payload = b"hello tpu blob store" * 50
    code, body = _http("POST", f"http://{a['url']}/{a['fid']}", payload)
    assert code == 201, body
    code, got = _http("GET", f"http://{a['publicUrl']}/{a['fid']}")
    assert code == 200 and got == payload
    # lookup via HTTP API
    vid = a["fid"].split(",")[0]
    code, body = _http(
        "GET", f"http://127.0.0.1:{master.port}/dir/lookup?volumeId={vid}"
    )
    assert code == 200 and json.loads(body)["locations"]
    # delete, then read 404s
    code, _ = _http("DELETE", f"http://{a['url']}/{a['fid']}")
    assert code == 202
    code, _ = _http("GET", f"http://{a['url']}/{a['fid']}")
    assert code == 404


def test_range_reads(cluster):
    master, _ = cluster
    a = _assign(master)
    payload = bytes(range(256)) * 4
    code, _ = _http("POST", f"http://{a['url']}/{a['fid']}", payload)
    assert code == 201
    base = f"http://{a['url']}/{a['fid']}"

    def get_range(spec):
        req = urllib.request.Request(base, headers={"Range": spec})
        with urllib.request.urlopen(req, timeout=10) as r:
            return r.status, r.read(), r.headers.get("Content-Range")

    code, got, cr = get_range("bytes=0-9")
    assert code == 206 and got == payload[:10]
    assert cr == f"bytes 0-9/{len(payload)}"
    code, got, cr = get_range("bytes=100-")
    assert code == 206 and got == payload[100:]
    # suffix range: last N bytes (RFC 7233)
    code, got, cr = get_range("bytes=-10")
    assert code == 206 and got == payload[-10:]
    assert cr == f"bytes {len(payload) - 10}-{len(payload) - 1}/{len(payload)}"


def test_replicated_write(cluster):
    master, servers = cluster
    a = _assign(master, replication="001")
    payload = b"replicated payload"
    code, _ = _http("POST", f"http://{a['url']}/{a['fid']}", payload)
    assert code == 201
    vid = int(a["fid"].split(",")[0])
    holders = [s for s in servers if s.store.find_volume(vid) is not None]
    assert len(holders) == 2, "replication should place the volume twice"
    # both copies readable directly
    for s in holders:
        code, got = _http(
            "GET", f"http://127.0.0.1:{s.port}/{a['fid']}"
        )
        assert code == 200 and got == payload


def test_ec_encode_flow(cluster):
    master, servers = cluster
    # write a bunch of blobs into one collection
    fids = []
    payloads = {}
    for i in range(20):
        a = _assign(master, collection="ectest")
        payload = (f"needle-{i}-".encode() * 199)[:4000]
        code, _ = _http("POST", f"http://{a['url']}/{a['fid']}", payload)
        assert code == 201
        fids.append(a["fid"])
        payloads[a["fid"]] = payload
    vid = int(fids[0].split(",")[0])

    env = CommandEnv(f"127.0.0.1:{master.grpc_port}")
    out = run_command(env, f"ec.encode -volumeId={vid} -collection=ectest")
    assert f"ec.encode {vid}" in out

    # wait until all 14 shard registrations reach the master (delta channels
    # deliver incrementally, so a partial map is expected transiently)
    deadline = time.time() + 15
    shard_map = {}
    while time.time() < deadline:
        shard_map = master.topo.lookup_ec_shards(vid)
        if len(shard_map) == 14:
            break
        time.sleep(0.2)
    assert len(shard_map) == 14, f"expected 14 shards, got {len(shard_map)}"
    # original volume is gone from every server
    assert all(s.store.find_volume(vid) is None for s in servers)

    # every blob still readable through the EC path on any shard holder
    for fid in fids[:5]:
        holder = next(
            s for s in servers if s.store.find_ec_volume(vid) is not None
        )
        code, got = _http("GET", f"http://127.0.0.1:{holder.port}/{fid}")
        assert code == 200, got
        assert got == payloads[fid]


def test_ec_rebuild_flow(cluster):
    master, servers = cluster
    # reuse the ec volume from the encode test
    vids = {
        vid for s in servers for vid in s.store.status()["ec_volumes"]
    }
    assert vids, "ec volume should exist from previous test"
    vid = sorted(vids)[0]
    # destroy the shards on the holder with the fewest (so >=10 remain —
    # losing 5 of 14 would be genuinely unrepairable)
    holders = [s for s in servers if s.store.find_ec_volume(vid)]
    victim = min(
        holders, key=lambda s: len(s.store.find_ec_volume(vid).shard_ids())
    )
    # lose at most 4 shards (the RS(10,4) repairability bound)
    lost = victim.store.find_ec_volume(vid).shard_ids()[:4]
    assert lost
    victim.store.delete_ec_shards(vid, "ectest", lost)
    # wait until the master's view reflects the shard loss
    deadline = time.time() + 15
    while time.time() < deadline:
        if len(master.topo.lookup_ec_shards(vid)) < 14:
            break
        time.sleep(0.2)
    assert len(master.topo.lookup_ec_shards(vid)) < 14

    env = CommandEnv(f"127.0.0.1:{master.grpc_port}")
    out = run_command(env, "ec.rebuild -force")
    assert "rebuilt" in out or "nothing to do" in out
    deadline = time.time() + 15
    while time.time() < deadline:
        total = set()
        for s in servers:
            ev = s.store.find_ec_volume(vid)
            if ev:
                total.update(ev.shard_ids())
        if len(total) == 14:
            break
        time.sleep(0.2)
    assert len(total) == 14, f"shards after rebuild: {sorted(total)}"


def test_shell_volume_list(cluster):
    master, _ = cluster
    env = CommandEnv(f"127.0.0.1:{master.grpc_port}")
    out = run_command(env, "volume.list")
    assert "rack" in out


def test_ec_delete_fanout(cluster):
    """Encode → HTTP DELETE on one holder → 404 from EVERY shard holder.

    Reference behavior: store_ec_delete.go:15-33 fans VolumeEcBlobDelete to
    all shard-holding servers so deleted EC blobs cannot resurrect from a
    degraded read on another holder."""
    master, servers = cluster
    fids = []
    for i in range(8):
        a = _assign(master, collection="ecdel")
        payload = (f"ecdel-{i}-".encode() * 100)[:900]
        code, _ = _http("POST", f"http://{a['url']}/{a['fid']}", payload)
        assert code == 201
        fids.append(a["fid"])
    vid = int(fids[0].split(",")[0])
    env = CommandEnv(f"127.0.0.1:{master.grpc_port}")
    encode_out = run_command(env, f"ec.encode -volumeId={vid} -collection=ecdel")
    deadline = time.time() + 150  # 1-vCPU host under load: spread is slow
    holders = []
    balance_log = []
    rebalance_at = time.time() + 5
    while time.time() < deadline:
        holders = [s for s in servers if s.store.find_ec_volume(vid)]
        if len(master.topo.lookup_ec_shards(vid)) == 14 and len(holders) >= 2:
            break
        if len(holders) < 2 and time.time() >= rebalance_at:
            # under 1-vCPU starvation the master's capacity view can
            # degrade (stalled heartbeats -> peers show no free slots), so
            # both spread and balance legitimately refuse.  This test is
            # about DELETE FAN-OUT, not placement: arrange >=2 holders
            # directly with the same shard-copy rpcs the balancer uses.
            rebalance_at = time.time() + 5
            try:
                balance_log.append(
                    run_command(env, "ec.balance -force -collection=ecdel"))
                holders = [s for s in servers
                           if s.store.find_ec_volume(vid)]
                if len(holders) == 1:
                    from seaweedfs_tpu.pb import rpc as rpclib
                    from seaweedfs_tpu.pb import volume_server_pb2 as vspb

                    src = holders[0]
                    dst = next(s for s in servers if s is not src)
                    sids = src.store.find_ec_volume(vid).shard_ids()[:7]
                    dst_stub = rpclib.volume_server_stub(
                        f"127.0.0.1:{dst.grpc_port}", timeout=60)
                    dst_stub.VolumeEcShardsCopy(vspb.VolumeEcShardsCopyRequest(
                        volume_id=vid, collection="ecdel", shard_ids=sids,
                        copy_ecx_file=True, copy_ecj_file=True,
                        copy_vif_file=True,
                        copy_from_data_node=f"127.0.0.1:{src.grpc_port}"))
                    dst_stub.VolumeEcShardsMount(
                        vspb.VolumeEcShardsMountRequest(
                            volume_id=vid, collection="ecdel",
                            shard_ids=sids))
                    balance_log.append(f"manually spread {sids} to dst")
            except Exception as e:
                balance_log.append(f"balance error: {e!r}")
        time.sleep(0.2)
    assert len(holders) >= 2, (
        "shards should be spread across servers; "
        f"encode_out={encode_out!r} "
        f"nodes={list(master.topo.nodes)} "
        f"shard_map={master.topo.lookup_ec_shards(vid)} "
        f"balance_log={balance_log[-3:]}"
    )
    victim_fid = fids[0]
    # delete through ONE holder's public HTTP surface
    code, body = _http("DELETE", f"http://127.0.0.1:{holders[0].port}/{victim_fid}")
    assert code == 202, body
    # every holder answers 404 now (tombstone fanned out, no resurrection)
    for s in holders:
        code, _ = _http("GET", f"http://127.0.0.1:{s.port}/{victim_fid}")
        assert code == 404, f"holder {s.port} still serves deleted EC needle"
    # other needles still readable
    code, _ = _http("GET", f"http://127.0.0.1:{holders[0].port}/{fids[1]}")
    assert code == 200


def test_tail_receiver_replica_catchup(cluster):
    """VolumeTailReceiver pulls appends (and tombstones) from a peer into
    a local replica (volume_grpc_tail.go + volume_grpc_copy_incremental.go)."""
    from seaweedfs_tpu.pb import rpc as rpclib
    from seaweedfs_tpu.pb import volume_server_pb2 as vspb

    master, servers = cluster
    src, dst = servers[0], servers[1]
    vid = 7001
    for s in (src, dst):
        rpclib.volume_server_stub(f"127.0.0.1:{s.grpc_port}").AllocateVolume(
            vspb.AllocateVolumeRequest(volume_id=vid, collection="",
                                       replication="000")
        )
    # write three needles + delete one, directly against the source
    fids = []
    for i in range(3):
        fid = f"{vid},{i + 1:x}00000001"
        code, _ = _http("POST", f"http://127.0.0.1:{src.port}/{fid}",
                        f"tail-{i}".encode() * 50)
        assert code == 201
        fids.append(fid)
    code, _ = _http("DELETE", f"http://127.0.0.1:{src.port}/{fids[2]}")
    assert code == 202
    # destination pulls the tail from the source
    rpclib.volume_server_stub(f"127.0.0.1:{dst.grpc_port}").VolumeTailReceiver(
        vspb.VolumeTailReceiverRequest(
            volume_id=vid, since_ns=0, idle_timeout_seconds=1,
            source_volume_server=f"127.0.0.1:{src.port}",
        )
    )
    for fid in fids[:2]:
        code, body = _http("GET", f"http://127.0.0.1:{dst.port}/{fid}")
        assert code == 200, f"replica missing {fid}"
    code, _ = _http("GET", f"http://127.0.0.1:{dst.port}/{fids[2]}")
    assert code == 404, "tombstone did not propagate"
    # incremental copy streams the raw .dat tail
    stream = rpclib.volume_server_stub(
        f"127.0.0.1:{src.grpc_port}"
    ).VolumeIncrementalCopy(
        vspb.VolumeIncrementalCopyRequest(volume_id=vid, since_ns=0)
    )
    data = b"".join(r.file_content for r in stream)
    assert b"tail-0" in data and b"tail-1" in data


def test_find_replica_divergence_pure():
    from types import SimpleNamespace

    from seaweedfs_tpu.shell.volume_commands import find_replica_divergence

    st = lambda fc, sz: SimpleNamespace(file_count=fc, dat_file_size=sz)  # noqa
    statuses = {
        1: [("a", st(5, 100)), ("b", st(5, 100))],
        2: [("a", st(5, 100)), ("b", st(3, 60))],
        3: [("a", st(9, 10))],
    }
    out = find_replica_divergence(statuses)
    assert 2 in out and 1 not in out and 3 not in out
    assert {n for n, _fc, _sz in out[2]} == {"a", "b"}


def test_ghost_node_reregisters_after_liveness_drop(cluster):
    """If the liveness sweep unregisters a starved node while its
    heartbeat stream is still alive, the next beat must re-register it —
    a dropped node whose stream survives must not ghost forever (the
    root cause of ec spread degenerating to a single holder under CPU
    starvation)."""
    master, servers = cluster
    victim_id = f"127.0.0.1:{servers[0].port}"
    assert victim_id in master.topo.nodes
    # simulate the liveness sweep's decision without actual starvation
    master.topo.unregister_node(victim_id)
    assert victim_id not in master.topo.nodes
    deadline = time.time() + 10
    while time.time() < deadline:
        if victim_id in master.topo.nodes:
            break
        time.sleep(0.1)
    assert victim_id in master.topo.nodes, "node did not re-register"


def test_volume_copy_mark_configure_commands(cluster):
    """volume.copy / volume.mark / volume.configure.replication against
    the live cluster (command_volume_copy.go, command_volume_mark.go,
    command_volume_configure_replication.go)."""
    master, servers = cluster
    a = _assign(master, collection="shellops")
    payload = b"shell ops payload"
    code, _ = _http("POST", f"http://{a['url']}/{a['fid']}", payload)
    assert code == 201
    vid = int(a["fid"].split(",")[0])
    env = CommandEnv(f"127.0.0.1:{master.grpc_port}")
    # wait for the heartbeat delta to land the new volume in the topology
    deadline = time.time() + 15
    while time.time() < deadline:
        if any(v.volume_id == vid
               for n in master.topo.nodes.values()
               for v in n.volumes.values()):
            break
        time.sleep(0.1)

    source = next(s for s in servers if s.store.find_volume(vid) is not None)
    target = next(s for s in servers if s.store.find_volume(vid) is None)
    out = run_command(
        env,
        f"volume.copy -volumeId={vid} "
        f"-source=127.0.0.1:{source.port} -target=127.0.0.1:{target.port}")
    assert "copied" in out
    assert target.store.find_volume(vid) is not None  # source kept too
    assert source.store.find_volume(vid) is not None
    code, got = _http("GET", f"http://127.0.0.1:{target.port}/{a['fid']}")
    assert code == 200 and got == payload

    out = run_command(
        env, f"volume.mark -volumeId={vid} -node=127.0.0.1:{source.port}")
    assert "readonly" in out
    assert source.store.find_volume(vid).read_only
    out = run_command(
        env,
        f"volume.mark -volumeId={vid} -node=127.0.0.1:{source.port} "
        "-writable=true")
    assert "writable" in out
    assert not source.store.find_volume(vid).read_only

    out = run_command(
        env, f"volume.configure.replication -volumeId={vid} -replication=001")
    assert "replication=001" in out
    assert str(source.store.find_volume(vid)
               .super_block.replica_placement) == "001"




def test_master_admin_http_endpoints(cluster):
    """/submit, /vol/grow, /vol/status, /col/delete, /cluster/healthz
    (master_server_handlers_admin.go surface)."""
    master, servers = cluster
    base = f"http://127.0.0.1:{master.port}"

    code, body = _http("GET", f"{base}/cluster/healthz")
    assert code == 200 and json.loads(body)["ok"]

    # one-shot submit: assign + upload in a single POST
    boundary = "testbound123"
    payload = b"submitted-in-one-shot"
    mp = (f"--{boundary}\r\n"
          'Content-Disposition: form-data; name="file"; '
          'filename="one.txt"\r\n'
          "Content-Type: text/plain\r\n\r\n").encode() + payload + \
        f"\r\n--{boundary}--\r\n".encode()
    req = urllib.request.Request(
        f"{base}/submit?collection=subm", data=mp, method="POST",
        headers={"Content-Type":
                 f"multipart/form-data; boundary={boundary}"})
    with urllib.request.urlopen(req, timeout=15) as r:
        out = json.loads(r.read())
    assert out["fid"] and out["size"] > 0
    code, got = _http("GET", f"http://{out['fileUrl']}")
    assert code == 200 and got == payload

    # oversized /submit bodies bounce with 413 before being read into
    # memory (the master never handles object payloads elsewhere)
    req = urllib.request.Request(
        f"{base}/submit", data=b"x", method="POST",
        headers={"Content-Length":
                 str(master.topo.volume_size_limit + 1)})
    try:
        urllib.request.urlopen(req, timeout=15)
        raise AssertionError("oversized submit accepted")
    except urllib.error.HTTPError as e:
        assert e.code == 413
    except urllib.error.URLError:
        pass  # connection closed mid-send is also acceptable

    # status + grow + col delete (wait out the heartbeat delta lag)
    deadline = time.time() + 15
    vols = {}
    while time.time() < deadline:
        code, body = _http("GET", f"{base}/vol/status")
        vols = json.loads(body)["Volumes"]
        if any(v["collection"] == "subm" for v in vols.values()):
            break
        time.sleep(0.2)
    assert any(v["collection"] == "subm" for v in vols.values())
    code, body = _http("GET", f"{base}/vol/grow?collection=grown&count=1")
    assert code == 200 and json.loads(body)["count"] == 1
    # state-changing: GET must refuse (crawler safety), POST/DELETE work
    code, body = _http("GET", f"{base}/col/delete?collection=subm")
    assert code == 405
    code, body = _http("POST", f"{base}/col/delete?collection=subm")
    assert code == 200 and json.loads(body)["deleted"]
    deadline = time.time() + 10
    while time.time() < deadline:
        code, body = _http("GET", f"{base}/vol/status")
        if not any(v["collection"] == "subm"
                   for v in json.loads(body)["Volumes"].values()):
            break
        time.sleep(0.2)
    assert not any(v["collection"] == "subm"
                   for v in json.loads(body)["Volumes"].values())


def test_deleted_volume_leaves_writable_set(cluster):
    """A volume deleted on its server must leave the master's layouts at
    the next full heartbeat — otherwise assigns keep picking the dead vid
    until master restart (regression: rebuild_layouts only registers)."""
    master, servers = cluster
    a = _assign(master, collection="delreg")
    vid = int(a["fid"].split(",")[0])
    holder = next(s for s in servers if s.store.find_volume(vid) is not None)
    # the layout knows the vid once the heartbeat lands
    deadline = time.time() + 15
    while time.time() < deadline:
        layouts = [l for (c, _r, _t), l in master.layouts.items()
                   if c == "delreg"]
        if layouts and any(vid in l.locations for l in layouts):
            break
        time.sleep(0.1)
    holder.store.delete_volume(vid)
    deadline = time.time() + 15
    gone = False
    while time.time() < deadline:
        layouts = [l for (c, _r, _t), l in master.layouts.items()
                   if c == "delreg"]
        if layouts and not any(
                holder_id == f"127.0.0.1:{holder.port}"
                for l in layouts
                for holder_id in l.locations.get(vid, [])):
            gone = True
            break
        time.sleep(0.2)
    assert gone, "deleted volume still registered to its old holder"


def test_master_vacuum_orchestration(cluster):
    """Leader-driven Check -> Compact -> Commit over gRPC reclaims
    tombstoned bytes and keeps survivors readable
    (topology_vacuum.go:147-167)."""
    master, servers = cluster
    fids = []
    for i in range(10):
        a = _assign(master, collection="vac")
        payload = (f"vacuum-{i}-".encode() * 300)[:2500]
        code, _ = _http("POST", f"http://{a['url']}/{a['fid']}", payload)
        assert code == 201
        fids.append((a, payload))
    vid = int(fids[0][0]["fid"].split(",")[0])
    holder = next(s for s in servers if s.store.find_volume(vid) is not None)
    size_before = holder.store.find_volume(vid).content_size
    # the periodic sweep only sees volumes the heartbeat has registered;
    # wait for the fresh volume to land in the topology first
    deadline = time.time() + 15
    while time.time() < deadline:
        if any(vid in n.volumes for n in master.topo.nodes.values()):
            break
        time.sleep(0.1)
    # delete 8 of 10 -> ~80% garbage
    for a, _p in fids[:8]:
        code, _ = _http("DELETE", f"http://{a['url']}/{a['fid']}")
        assert code == 202
    code, body = _http(
        "GET",
        f"http://127.0.0.1:{master.port}/vol/vacuum?garbageThreshold=0.3")
    assert code == 200
    vacuumed = json.loads(body)["vacuumed"]
    assert vid in vacuumed, (vacuumed, vid)
    v = holder.store.find_volume(vid)
    assert v.content_size < size_before, "vacuum did not shrink the volume"
    # survivors still readable, deleted still 404
    for a, payload in fids[8:]:
        code, got = _http("GET", f"http://{a['url']}/{a['fid']}")
        assert code == 200 and got == payload
    code, _ = _http("GET", f"http://{fids[0][0]['url']}/{fids[0][0]['fid']}")
    assert code == 404


def test_fix_replication_restores_lost_replica(cluster):
    """volume.fix.replication copies an under-replicated volume to a new
    node and the data survives (command_volume_fix_replication.go)."""
    master, servers = cluster
    a = _assign(master, replication="001", collection="fixrep")
    payload = b"replica payload " * 64
    code, _ = _http("POST", f"http://{a['url']}/{a['fid']}", payload)
    assert code == 201
    vid = int(a["fid"].split(",")[0])
    holders = [s for s in servers if s.store.find_volume(vid) is not None]
    assert len(holders) == 2
    # lose one replica and wait for the topology to notice
    victim = holders[1]
    victim.store.delete_volume(vid)
    deadline = time.time() + 15
    while time.time() < deadline:
        topo_holders = [n.id for n in master.topo.nodes.values()
                        if vid in n.volumes]
        if len(topo_holders) == 1:
            break
        time.sleep(0.2)
    assert len(topo_holders) == 1, topo_holders

    env = CommandEnv(f"127.0.0.1:{master.grpc_port}")
    out = run_command(env, "volume.fix.replication")
    assert f"{vid}: copied to" in out, out
    holders_after = [s for s in servers
                     if s.store.find_volume(vid) is not None]
    assert len(holders_after) == 2
    for s_ in holders_after:
        code, got = _http("GET", f"http://127.0.0.1:{s_.port}/{a['fid']}")
        assert code == 200 and got == payload


def test_fsck_check_disk_and_collection_delete(cluster):
    """volume.fsck reports a diverged replica, volume.check.disk -force
    tail-syncs it back, and collection.delete removes a collection's
    volumes cluster-wide including the master's layouts."""
    master, servers = cluster
    env = CommandEnv(f"127.0.0.1:{master.grpc_port}")

    # --- fsck + check.disk over a manufactured divergence
    a = _assign(master, replication="001", collection="fsck")
    vid = int(a["fid"].split(",")[0])
    code, _ = _http("POST", f"http://{a['url']}/{a['fid']}",
                    b"first write")
    assert code == 201
    holders = [s for s in servers if s.store.find_volume(vid) is not None]
    assert len(holders) == 2
    # the fsck sweep walks the TOPOLOGY; wait until both replicas'
    # heartbeats have registered the volume
    deadline = time.time() + 15
    while time.time() < deadline:
        if sum(vid in n.volumes
               for n in master.topo.nodes.values()) == 2:
            break
        time.sleep(0.2)
    # write a SECOND needle to only one replica (?type=replicate marks
    # it as an already-fanned-out replica write, so no fan-out happens)
    fid2 = f"{vid},1f00000002"
    code, _ = _http(
        "POST",
        f"http://127.0.0.1:{holders[0].port}/{fid2}?type=replicate",
        b"diverged write")
    assert code == 201
    out = run_command(env, "volume.fsck")
    assert f"volume {vid} diverged" in out, out
    # a transient tail-connect failure surfaces as "sync failed" in the
    # command output; retry the repair a couple of times before judging
    deadline = time.time() + 20
    synced = False
    while time.time() < deadline:
        out = run_command(env, "volume.check.disk -force")
        if f"volume {vid}: synced" in out:
            synced = True
        if (holders[1].store.find_volume(vid) is not None
                and holders[1].store.find_volume(vid).file_count()
                == holders[0].store.find_volume(vid).file_count()):
            break
        time.sleep(0.5)
    assert synced, out
    assert (holders[1].store.find_volume(vid).file_count()
            == holders[0].store.find_volume(vid).file_count())
    out = run_command(env, "volume.fsck")
    assert f"volume {vid} diverged" not in out, out

    # --- collection.delete sweeps servers and layouts
    out = run_command(env, "collection.delete fsck")
    assert "deleted" in out
    deadline = time.time() + 10
    while time.time() < deadline:
        if all(s.store.find_volume(vid) is None for s in servers):
            break
        time.sleep(0.2)
    assert all(s.store.find_volume(vid) is None for s in servers)
    assert not [l for (c, _r, _t), l in master.layouts.items()
                if c == "fsck" and l.locations]


def test_volume_evacuate(cluster):
    """Moves all volumes off a node and tells it to leave
    (command_volume_server_evacuate.go).  Runs LAST: the evacuated node
    stops heartbeating."""
    master, servers = cluster
    victim = servers[2]
    node_id = f"127.0.0.1:{victim.port}"
    # ensure the victim owns at least one volume
    from seaweedfs_tpu.pb import rpc as rpclib
    from seaweedfs_tpu.pb import volume_server_pb2 as vspb

    vid = 7100
    rpclib.volume_server_stub(f"127.0.0.1:{victim.grpc_port}").AllocateVolume(
        vspb.AllocateVolumeRequest(volume_id=vid, collection="",
                                   replication="000")
    )
    fid = f"{vid},1200000001"
    code, _ = _http("POST", f"http://127.0.0.1:{victim.port}/{fid}", b"evac!")
    assert code == 201
    deadline = time.time() + 15
    while time.time() < deadline:
        node = master.topo.nodes.get(node_id)
        if node is not None and vid in node.volumes:
            break
        time.sleep(0.2)
    env = CommandEnv(f"127.0.0.1:{master.grpc_port}")
    out = run_command(env, f"volume.evacuate -node={node_id}")
    assert f"v{vid}->" in out, out
    # the volume now lives (readable) on another server
    others = [s for s in servers if s is not victim]
    assert any(s.store.find_volume(vid) for s in others)
    target = next(s for s in others if s.store.find_volume(vid))
    code, body = _http("GET", f"http://127.0.0.1:{target.port}/{fid}")
    assert code == 200 and body == b"evac!"


def test_maintenance_loop_encodes_automatically(tmp_path_factory):
    """The master's periodic [master.maintenance] script runs ec.encode
    over full volumes without any operator action — the reference's
    production EC entry point (master_server.go:187-242, SURVEY §5.3)."""
    master = MasterServer(
        ip="127.0.0.1", port=_free_port(), volume_size_limit_mb=1,
        maintenance_interval=1.0,
        maintenance_script=["ec.encode -fullPercent=50 -quietFor=0"],
    )
    master.start()
    vs_ = VolumeServer(
        directories=[str(tmp_path_factory.mktemp("mvol"))],
        master_addresses=[f"127.0.0.1:{master.grpc_port}"],
        ip="127.0.0.1", port=_free_port(), pulse_seconds=0.5,
        max_volume_count=40,
    )
    vs_.start()
    try:
        deadline = time.time() + 15
        while time.time() < deadline and len(master.topo.nodes) < 1:
            time.sleep(0.1)
        # fill one volume past 50% of the 1MB size limit
        a = _assign(master, collection="auto")
        vid = int(a["fid"].split(",")[0])
        payload = b"m" * (700 << 10)
        code, _ = _http("POST", f"http://{a['url']}/{a['fid']}", payload)
        assert code == 201
        # the loop must freeze + encode it without any shell interaction
        deadline = time.time() + 60
        while time.time() < deadline:
            if len(master.topo.lookup_ec_shards(vid)) == 14:
                break
            time.sleep(0.5)
        assert len(master.topo.lookup_ec_shards(vid)) == 14, (
            "maintenance loop did not EC-encode the full volume")
        # the blob survives through the EC read path
        code, got = _http("GET", f"http://{a['url']}/{a['fid']}")
        assert code == 200 and got == payload
    finally:
        vs_.stop()
        master.stop()
