"""Pinned known-answer vectors for the RS(10,4) codec.

The byte-identity claim (parity equal to klauspost/reedsolomon v1.9.2, the
codec SeaweedFS calls from weed/storage/erasure_coding/ec_encoder.go:198)
rests on the generator-matrix construction in ops/gf256.py.  Every other
test compares codecs against each other or against identity data rows, so a
drift in the matrix construction would pass silently.  This file pins:

1. the RS(10,4) parity-matrix bytes as literal constants,
2. parity outputs for deterministic input stripes (KATs),
3. SHA-256 of all 14 shard files produced from the reference's checked-in
   ``1.dat`` binary fixture (weed/storage/erasure_coding/1.dat) with the
   scaled block sizes of the reference's own harness (ec_test.go:16-19),
4. an INDEPENDENT re-derivation of the matrix using bitwise carry-less
   multiplication and pure-Python Gauss-Jordan — sharing no tables or numpy
   code with ops/gf256.py — so a bug in the exp/log tables cannot hide.

PROVENANCE — what anchors the cross-implementation identity claim, stated
plainly (this environment has no Go toolchain and zero egress, so klauspost
itself has never run here; nothing in this file is a klauspost-produced
artifact):

* The ten DATA shards (.ec00-.ec09) involve no GF math at all — they are
  the row-major striping of the volume defined by ec_encoder.go:194-231,
  so their pinned SHAs anchor the striping/padding geometry directly
  against the reference's spec.
* The four PARITY shards depend only on the generator matrix.  The anchor
  for matrix identity is an algorithmic port of klauspost v1.9.2
  ``matrix.go`` ``buildMatrix`` (``vandermonde(rows, cols)`` with
  ``vm[r][c] = galExp(r, c)`` over poly 0x11D, invert the top k-square by
  Gauss-Jordan, right-multiply) — re-implemented below (_indep_rs_matrix)
  from the published construction with primitives (carry-less mul,
  brute-force inverse) that share nothing with ops/gf256.py, and asserted
  equal to the PARITY_MATRIX_10_4 literals.  The same construction is
  used by the Backblaze/klauspost lineage and is fully determined by
  (poly=0x11D, vandermonde-normalised); there is no free parameter left
  for the two implementations to disagree on.
* Given matrix identity + striping identity, the shard SHAs pin the whole
  pipeline against REGRESSION.  They were first produced by this repo's
  own encoder, so on their own they are self-referential — the
  cross-implementation claim rests on the two bullets above, not on them.
"""

import hashlib
import os
import shutil

import numpy as np
import pytest

from seaweedfs_tpu.ops import gf256
from seaweedfs_tpu.storage.ec.encoder import generate_ec_files

REF_EC_DIR = "/root/reference/weed/storage/erasure_coding"

# RS(10,4) parity rows of the Vandermonde-normalised generator matrix used
# by the klauspost/Backblaze lineage (data rows are the identity).
PARITY_MATRIX_10_4 = [
    [129, 150, 175, 184, 210, 196, 254, 232, 3, 2],
    [150, 129, 184, 175, 196, 210, 232, 254, 2, 3],
    [191, 214, 98, 10, 6, 111, 223, 183, 5, 4],
    [214, 191, 10, 98, 111, 6, 183, 223, 4, 5],
]

# Parity of the stripe d[i, j] = (i*31 + j*7 + 1) % 256, shape (10, 16).
KAT_AFFINE_PARITY = [
    [11, 23, 69, 36, 227, 42, 14, 188, 160, 242, 125, 202, 70, 17, 10, 59],
    [140, 180, 100, 206, 194, 113, 239, 142, 65, 191, 28, 93, 103, 130, 100, 228],
    [140, 59, 131, 42, 246, 142, 87, 112, 34, 134, 166, 221, 96, 38, 165, 136],
    [140, 75, 162, 160, 215, 199, 54, 186, 67, 166, 199, 153, 65, 110, 122, 12],
]

# Parity of d = 255 * I_10: column j is 255 * (parity-matrix column j).
KAT_IMPULSE_PARITY = [
    [157, 17, 152, 20, 251, 136, 29, 110, 28, 227],
    [17, 157, 20, 152, 136, 251, 110, 29, 227, 28],
    [211, 32, 68, 72, 56, 203, 116, 120, 36, 219],
    [32, 211, 72, 68, 203, 56, 120, 116, 219, 36],
]

# SHA-256 of the 14 shard files from encoding the reference 1.dat fixture
# with large=10000 / small=100 (the reference harness's scaled sizes).
FIXTURE_SHARD_SHA256 = [
    "ecc8f0c25381bc0da9c7cd97ddbcf3fae7f6d710058f06be8a68161f2d4850f9",
    "52ef93ba0347e7b3a7d0190ac6bf233419e8bbca7f5a1b1bd1076b3a4852f0a2",
    "087844ad5ecc0d6b626dcc5d243f99e56fd41ba78c2363fc4768297f5e602762",
    "ca24349f4755768ccedde6250de6b77d6790523f3960ea7d7a05b2e8155a9904",
    "f3bb8b2032b60cb21d31b5af3fe10a3d99e477cea1d6ebf2a0a5edac3838ec92",
    "d0d9b0d0275b84f492aac6ca623f67868a2ed8e56fa32a6c7f027fae1e920a2e",
    "159aab42af549aca65d90e901d9f2978111c967c093068f35aa007e5ed7e4b52",
    "2968a8d78373397bee481cbe61672cc87629c25789aa65a9b5cc6a5526fe58dc",
    "b766df3234513e06863d81ea508500fd3f218a73548908583920b5f280f90636",
    "45384c46490df10e5178903a229f0f7ff5775087f8caeca5c144e1fb122651e8",
    "d2f5515bd185fd2a6b068842ab6a8e06f20a20150b78fef3b406d94536e86f12",
    "7fe79457341eeacd74c5cadd9c6380407ffc9480066255862183b239f4178e28",
    "6a845184fc105d418513279ce8c0a99923bb1e32954a49227fc53a9fc1d503d0",
    "bc63a3d7b954864cb6a023f1a34b705a37cdc69f84bbe025a59b4d6cd7400995",
]


def test_parity_matrix_pinned_bytes():
    p = gf256.rs_parity_matrix(10, 4)
    assert p.tolist() == PARITY_MATRIX_10_4
    # full matrix: identity on top
    m = gf256.rs_matrix(10, 14)
    assert m[:10].tolist() == gf256.mat_identity(10).tolist()
    assert m[10:].tolist() == PARITY_MATRIX_10_4


def test_parity_known_answer_vectors():
    p = np.asarray(PARITY_MATRIX_10_4, dtype=np.uint8)
    d = np.fromfunction(lambda i, j: (i * 31 + j * 7 + 1) % 256, (10, 16))
    d = d.astype(np.uint8)
    assert gf256.mat_mul(p, d).tolist() == KAT_AFFINE_PARITY

    d2 = np.zeros((10, 10), dtype=np.uint8)
    np.fill_diagonal(d2, 255)
    assert gf256.mat_mul(p, d2).tolist() == KAT_IMPULSE_PARITY


def test_every_codec_matches_kat():
    """All registered codecs must reproduce the pinned parity bytes."""
    from seaweedfs_tpu.ops.codec import available_codecs, get_codec

    d = np.fromfunction(lambda i, j: (i * 31 + j * 7 + 1) % 256, (10, 16))
    d = d.astype(np.uint8)
    for name in available_codecs():
        codec = get_codec(name)
        par = np.asarray(codec.parity_of(d))
        assert par.tolist() == KAT_AFFINE_PARITY, f"codec {name} drifted"


@pytest.mark.skipif(not os.path.isdir(REF_EC_DIR), reason="fixture absent")
def test_fixture_shard_checksums(tmp_path):
    base = str(tmp_path / "1")
    shutil.copy(os.path.join(REF_EC_DIR, "1.dat"), base + ".dat")
    shutil.copy(os.path.join(REF_EC_DIR, "1.idx"), base + ".idx")
    generate_ec_files(base, large_block_size=10000, small_block_size=100)
    for i, want in enumerate(FIXTURE_SHARD_SHA256):
        with open(f"{base}.ec{i:02d}", "rb") as f:
            got = hashlib.sha256(f.read()).hexdigest()
        assert got == want, f"shard .ec{i:02d} drifted"


# ---------------------------------------------------------------------------
# Independent re-derivation: no shared tables, no numpy GF code.
# ---------------------------------------------------------------------------


def _clmul_mod(a: int, b: int, poly: int = 0x11D) -> int:
    """Carry-less multiply then reduce mod the field polynomial — bitwise,
    sharing nothing with the exp/log-table implementation."""
    prod = 0
    for bit in range(8):
        if (b >> bit) & 1:
            prod ^= a << bit
    for bit in range(15, 7, -1):
        if (prod >> bit) & 1:
            prod ^= poly << (bit - 8)
    return prod


def _inv_bruteforce(a: int) -> int:
    for x in range(1, 256):
        if _clmul_mod(a, x) == 1:
            return x
    raise ZeroDivisionError(a)


def _indep_rs_matrix(k: int, n: int):
    """Port of klauspost v1.9.2 matrix.go buildMatrix:
    ``vm := vandermonde(totalShards, dataShards)`` (vm[r][c] = galExp(r,c)),
    ``top := vm.SubMatrix(0,0,k,k); return vm.Multiply(top.Invert())``.
    Inversion follows matrix.go's augmented Gauss-Jordan
    (gaussianElimination over [A|I])."""
    def gexp(r, c):
        out = 1
        for _ in range(c):
            out = _clmul_mod(out, r)
        return out

    vm = [[gexp(r, c) for c in range(k)] for r in range(n)]
    # Gauss-Jordan inversion of the top square, pure ints
    top = [row[:] + [1 if i == j else 0 for j in range(k)]
           for i, row in enumerate(vm[:k])]
    for col in range(k):
        if top[col][col] == 0:
            for r in range(col + 1, k):
                if top[r][col]:
                    top[col], top[r] = top[r], top[col]
                    break
        inv_p = _inv_bruteforce(top[col][col])
        top[col] = [_clmul_mod(inv_p, x) for x in top[col]]
        for r in range(k):
            if r != col and top[r][col]:
                f = top[r][col]
                top[r] = [x ^ _clmul_mod(f, y)
                          for x, y in zip(top[r], top[col])]
    top_inv = [row[k:] for row in top]
    out = []
    for r in range(n):
        row = []
        for c in range(k):
            acc = 0
            for i in range(k):
                acc ^= _clmul_mod(vm[r][i], top_inv[i][c])
            row.append(acc)
        out.append(row)
    return out


def test_matrix_against_independent_derivation():
    indep = _indep_rs_matrix(10, 14)
    assert indep[10:] == PARITY_MATRIX_10_4
    assert gf256.rs_matrix(10, 14).tolist() == indep
