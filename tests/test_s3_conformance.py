"""S3 conformance corpus: ported assertions from the ceph/s3-tests suite.

The reference runs ceph/s3-tests in a container
(/root/reference/docker/Dockerfile.s3tests:20,
docker/compose/local-s3tests-compose.yml); that suite cannot run here
(zero egress, no boto), so this file ports a representative subset of its
functional assertions (~30 cases) against the gateway, named after the
s3-tests cases they mirror (s3tests/functional/test_s3.py).  Unlike
tests/test_s3.py — written alongside the implementation, sharing its
blind spots — these assertions encode EXTERNAL expectations: list
continuation/delimiter behavior, ranged and conditional reads, multipart
edge cases, and error-code XML bodies.
"""

import hashlib
import time
import urllib.parse
import xml.etree.ElementTree as ET

import pytest

from helpers import free_port

_NS = "{http://s3.amazonaws.com/doc/2006-03-01/}"


def _free_port():
    return free_port()


def _req(method, url, data=None, headers=None):
    import urllib.error
    import urllib.request

    req = urllib.request.Request(url, data=data, method=method,
                                 headers=headers or {})
    try:
        with urllib.request.urlopen(req, timeout=20) as r:
            return r.status, dict(r.headers), r.read()
    except urllib.error.HTTPError as e:
        return e.code, dict(e.headers), e.read()


_STACK: dict = {}


@pytest.fixture(scope="module")
def s3(tmp_path_factory):
    from seaweedfs_tpu.filer.server import FilerServer
    from seaweedfs_tpu.master.server import MasterServer
    from seaweedfs_tpu.s3api.server import S3ApiServer
    from seaweedfs_tpu.volume.server import VolumeServer

    master = MasterServer(ip="127.0.0.1", port=_free_port(),
                          volume_size_limit_mb=64)
    master.start()
    vs = VolumeServer(
        directories=[str(tmp_path_factory.mktemp("confvol"))],
        master_addresses=[f"127.0.0.1:{master.grpc_port}"],
        ip="127.0.0.1", port=_free_port(), pulse_seconds=0.5,
        max_volume_count=200,
    )
    vs.start()
    deadline = time.time() + 15
    while time.time() < deadline and len(master.topo.nodes) < 1:
        time.sleep(0.1)
    filer = FilerServer(
        masters=[f"127.0.0.1:{master.grpc_port}"],
        ip="127.0.0.1", port=_free_port(),
        store="leveldb3",
        store_path=str(tmp_path_factory.mktemp("confdb") / "ldb3"),
        max_mb=1,
    )
    filer.start()
    gw = S3ApiServer(filer=f"127.0.0.1:{filer.port}", port=_free_port())
    gw.start()
    # the admission/quota rejection tests reach into the (in-process)
    # filer's tenant plane to arm deterministic rejections
    _STACK["filer"] = filer
    yield f"http://127.0.0.1:{gw.port}"
    gw.stop()
    filer.stop()
    vs.stop()
    master.stop()


def _mk_bucket(base, name):
    code, _, _ = _req("PUT", f"{base}/{name}")
    assert code in (200, 409)


def _put(base, bucket, key, body=b"x", headers=None):
    code, hdrs, _ = _req("PUT", f"{base}/{bucket}/{key}", body, headers)
    assert code == 200, (bucket, key, code)
    return hdrs


def _xml(body):
    return ET.fromstring(body)


def _findall(root, tag):
    return root.findall(f"{_NS}{tag}") + root.findall(tag)


def _find(root, tag):
    el = root.find(f"{_NS}{tag}")
    return el if el is not None else root.find(tag)


def _text(root, tag, default=None):
    el = _find(root, tag)
    return el.text if el is not None and el.text is not None else default


def _keys(root):
    out = []
    for c in _findall(root, "Contents"):
        out.append(_text(c, "Key"))
    return out


# ---------------------------------------------------------------------------
# Listing (s3tests: test_bucket_list_*)
# ---------------------------------------------------------------------------


def test_bucket_list_empty(s3):
    _mk_bucket(s3, "empty-b")
    code, _, body = _req("GET", f"{s3}/empty-b")
    assert code == 200
    root = _xml(body)
    assert _keys(root) == []
    assert _text(root, "IsTruncated") == "false"


def test_bucket_list_delimiter_basic(s3):
    # s3tests test_bucket_list_delimiter_basic: keys foo/bar, foo/baz/xyzzy,
    # quux/thud, asdf with delimiter '/' -> one key + two common prefixes
    _mk_bucket(s3, "delim-b")
    for k in ("foo/bar", "foo/baz/xyzzy", "quux/thud", "asdf"):
        _put(s3, "delim-b", k)
    code, _, body = _req("GET", f"{s3}/delim-b?delimiter=/")
    assert code == 200
    root = _xml(body)
    assert _keys(root) == ["asdf"]
    prefixes = sorted(
        _text(p, "Prefix") for p in _findall(root, "CommonPrefixes"))
    assert prefixes == ["foo/", "quux/"]
    assert _text(root, "Delimiter") == "/"


def test_bucket_list_delimiter_prefix(s3):
    # s3tests test_bucket_list_delimiter_prefix: prefix+delimiter paging
    _mk_bucket(s3, "dp-b")
    for k in ("asdf", "boo/bar", "boo/baz/xyzzy", "cquux/thud"):
        _put(s3, "dp-b", k)
    code, _, body = _req("GET", f"{s3}/dp-b?prefix=boo/&delimiter=/")
    root = _xml(body)
    assert _keys(root) == ["boo/bar"]
    assert [_text(p, "Prefix") for p in _findall(root, "CommonPrefixes")] \
        == ["boo/baz/"]


def test_bucket_list_maxkeys_one(s3):
    # s3tests test_bucket_list_maxkeys_one: truncation + marker resume
    _mk_bucket(s3, "mk1-b")
    keys = ["bar", "baz", "foo", "quxx"]
    for k in keys:
        _put(s3, "mk1-b", k)
    code, _, body = _req("GET", f"{s3}/mk1-b?max-keys=1")
    root = _xml(body)
    assert _keys(root) == keys[:1]
    assert _text(root, "IsTruncated") == "true"
    code, _, body = _req("GET", f"{s3}/mk1-b?marker={keys[0]}")
    root = _xml(body)
    assert _keys(root) == keys[1:]
    assert _text(root, "IsTruncated") == "false"


def test_bucket_list_maxkeys_invalid(s3):
    # s3tests test_bucket_list_maxkeys_invalid: non-numeric -> 400
    _mk_bucket(s3, "mki-b")
    code, _, body = _req("GET", f"{s3}/mki-b?max-keys=blah")
    assert code == 400
    assert b"InvalidArgument" in body


def test_bucket_list_marker_after_list(s3):
    # s3tests test_bucket_list_marker_after_list: marker past the end
    _mk_bucket(s3, "mal-b")
    for k in ("aaa", "bbb"):
        _put(s3, "mal-b", k)
    code, _, body = _req("GET", f"{s3}/mal-b?marker=zzz")
    root = _xml(body)
    assert _keys(root) == []
    assert _text(root, "IsTruncated") == "false"


def test_bucket_listv2_continuationtoken(s3):
    # s3tests test_bucket_listv2_continuationtoken
    _mk_bucket(s3, "v2ct-b")
    keys = ["bar", "baz", "foo", "quxx"]
    for k in keys:
        _put(s3, "v2ct-b", k)
    code, _, body = _req("GET", f"{s3}/v2ct-b?list-type=2&max-keys=1")
    root = _xml(body)
    assert _keys(root) == ["bar"]
    assert _text(root, "IsTruncated") == "true"
    token = _text(root, "NextContinuationToken")
    assert token
    code, _, body = _req(
        "GET",
        f"{s3}/v2ct-b?list-type=2&continuation-token="
        f"{urllib.parse.quote(token)}")
    root = _xml(body)
    assert _keys(root) == keys[1:]
    assert _text(root, "IsTruncated") == "false"


def test_bucket_listv2_startafter(s3):
    # s3tests test_bucket_listv2_startafter_after_list
    _mk_bucket(s3, "v2sa-b")
    for k in ("bar", "baz", "foo"):
        _put(s3, "v2sa-b", k)
    code, _, body = _req("GET", f"{s3}/v2sa-b?list-type=2&start-after=baz")
    root = _xml(body)
    assert _keys(root) == ["foo"]
    code, _, body = _req("GET", f"{s3}/v2sa-b?list-type=2&start-after=zzz")
    assert _keys(_xml(body)) == []


def test_bucket_listv2_keycount(s3):
    # v2 responses carry KeyCount
    _mk_bucket(s3, "v2kc-b")
    for k in ("a", "b", "c"):
        _put(s3, "v2kc-b", k)
    code, _, body = _req("GET", f"{s3}/v2kc-b?list-type=2")
    assert _text(_xml(body), "KeyCount") == "3"


def test_bucket_list_encoding_url(s3):
    # s3tests encoding-type=url: keys come back percent-encoded.
    # PUT "sp%20ace+plus" stores key "sp ace+plus" (the path decodes),
    # so the url-encoded listing must round-trip back to that.
    _mk_bucket(s3, "enc-b")
    _put(s3, "enc-b", "sp%20ace+plus")
    code, _, body = _req("GET", f"{s3}/enc-b?encoding-type=url")
    root = _xml(body)
    assert _text(root, "EncodingType") == "url"
    keys = _keys(root)
    assert len(keys) == 1
    assert keys[0] != "sp ace+plus"  # actually encoded on the wire
    assert urllib.parse.unquote(keys[0]) == "sp ace+plus"


# ---------------------------------------------------------------------------
# Objects (s3tests: test_object_*)
# ---------------------------------------------------------------------------


def test_object_read_notexist(s3):
    _mk_bucket(s3, "or-b")
    code, _, body = _req("GET", f"{s3}/or-b/missing-key")
    assert code == 404
    assert b"NoSuchKey" in body


def test_object_in_nonexistent_bucket(s3):
    code, _, body = _req("PUT", f"{s3}/no-such-bkt-xyz/k", b"x")
    assert code == 404
    assert b"NoSuchBucket" in body


def test_object_head_zero_bytes(s3):
    _mk_bucket(s3, "zero-b")
    _put(s3, "zero-b", "empty", b"")
    code, headers, _ = _req("HEAD", f"{s3}/zero-b/empty")
    assert code == 200
    assert headers.get("Content-Length") == "0"


def test_object_write_read_update_read_delete(s3):
    _mk_bucket(s3, "wrud-b")
    _put(s3, "wrud-b", "k", b"zzz")
    code, _, body = _req("GET", f"{s3}/wrud-b/k")
    assert (code, body) == (200, b"zzz")
    _put(s3, "wrud-b", "k", b"new-content")
    code, _, body = _req("GET", f"{s3}/wrud-b/k")
    assert (code, body) == (200, b"new-content")
    code, _, _ = _req("DELETE", f"{s3}/wrud-b/k")
    assert code == 204
    code, _, _ = _req("GET", f"{s3}/wrud-b/k")
    assert code == 404


def test_object_set_get_metadata_overwrite(s3):
    # s3tests test_object_set_get_metadata_overwrite_to_empty
    _mk_bucket(s3, "meta-b")
    _put(s3, "meta-b", "m", b"1", {"x-amz-meta-meta1": "bar"})
    code, headers, _ = _req("GET", f"{s3}/meta-b/m")
    assert headers.get("x-amz-meta-meta1") == "bar"
    _put(s3, "meta-b", "m", b"2")  # rewrite without metadata clears it
    code, headers, _ = _req("GET", f"{s3}/meta-b/m")
    assert headers.get("x-amz-meta-meta1") is None


def test_object_copy_same_bucket(s3):
    # s3tests test_object_copy_same_bucket
    _mk_bucket(s3, "copy-b")
    _put(s3, "copy-b", "src", b"copy-me")
    code, _, body = _req(
        "PUT", f"{s3}/copy-b/dst", b"",
        {"x-amz-copy-source": "/copy-b/src"})
    assert code == 200
    assert b"CopyObjectResult" in body
    code, _, body = _req("GET", f"{s3}/copy-b/dst")
    assert body == b"copy-me"


def test_object_copy_notexist(s3):
    _mk_bucket(s3, "copy404-b")
    code, _, body = _req(
        "PUT", f"{s3}/copy404-b/dst", b"",
        {"x-amz-copy-source": "/copy404-b/ghost"})
    assert code == 404


def test_ranged_request_response_code(s3):
    # s3tests test_ranged_request_response_code: bytes=4-7 of 11 bytes
    _mk_bucket(s3, "range-b")
    _put(s3, "range-b", "r", b"testcontent")
    code, headers, body = _req(
        "GET", f"{s3}/range-b/r", headers={"Range": "bytes=4-7"})
    assert code == 206
    assert body == b"cont"
    assert headers.get("Content-Range") == "bytes 4-7/11"


def test_ranged_request_skip_leading_and_suffix(s3):
    # s3tests test_ranged_request_skip_leading_bytes_response_code and
    # test_ranged_request_return_trailing_bytes_response_code
    _mk_bucket(s3, "range2-b")
    _put(s3, "range2-b", "r", b"testcontent")
    code, _, body = _req(
        "GET", f"{s3}/range2-b/r", headers={"Range": "bytes=4-"})
    assert (code, body) == (206, b"content")
    code, _, body = _req(
        "GET", f"{s3}/range2-b/r", headers={"Range": "bytes=-7"})
    assert (code, body) == (206, b"content")


def test_ranged_request_invalid_range(s3):
    # s3tests test_ranged_request_invalid_range: out of bounds -> 416
    _mk_bucket(s3, "range3-b")
    _put(s3, "range3-b", "r", b"short")
    code, _, body = _req(
        "GET", f"{s3}/range3-b/r", headers={"Range": "bytes=40-50"})
    assert code == 416


def test_ranged_request_empty_object(s3):
    # s3tests test_ranged_request_empty_object: any range on empty -> 416
    _mk_bucket(s3, "range4-b")
    _put(s3, "range4-b", "r", b"")
    code, _, _ = _req(
        "GET", f"{s3}/range4-b/r", headers={"Range": "bytes=0-10"})
    assert code == 416


def test_get_object_ifmatch_failed(s3):
    # s3tests test_get_object_ifmatch_failed: wrong etag -> 412
    _mk_bucket(s3, "cond-b")
    _put(s3, "cond-b", "c", b"conditional")
    code, _, _ = _req(
        "GET", f"{s3}/cond-b/c",
        headers={"If-Match": '"bogus-etag"'})
    assert code == 412
    good = _req("HEAD", f"{s3}/cond-b/c")[1].get("ETag")
    code, _, body = _req(
        "GET", f"{s3}/cond-b/c", headers={"If-Match": good})
    assert (code, body) == (200, b"conditional")


def test_get_object_ifnonematch_good(s3):
    # s3tests test_get_object_ifnonematch_good: matching etag -> 304
    _mk_bucket(s3, "cond2-b")
    _put(s3, "cond2-b", "c", b"abc")
    etag = _req("HEAD", f"{s3}/cond2-b/c")[1].get("ETag")
    code, _, _ = _req(
        "GET", f"{s3}/cond2-b/c", headers={"If-None-Match": etag})
    assert code == 304


# ---------------------------------------------------------------------------
# Buckets (s3tests: test_bucket_*)
# ---------------------------------------------------------------------------


def test_bucket_delete_notexist(s3):
    code, _, body = _req("DELETE", f"{s3}/ghost-bucket-zz")
    assert code == 404
    assert b"NoSuchBucket" in body


def test_bucket_delete_nonempty(s3):
    _mk_bucket(s3, "full-b")
    _put(s3, "full-b", "k")
    code, _, body = _req("DELETE", f"{s3}/full-b")
    assert code == 409
    assert b"BucketNotEmpty" in body


def test_bucket_create_naming_bad_short_one(s3):
    # s3tests test_bucket_create_naming_bad_short_one: "a" -> 400
    code, _, body = _req("PUT", f"{s3}/a")
    assert code == 400
    assert b"InvalidBucketName" in body


def test_bucket_create_naming_bad_uppercase(s3):
    code, _, body = _req("PUT", f"{s3}/BadUpper")
    assert code == 400
    assert b"InvalidBucketName" in body


def test_bucket_head_extended(s3):
    # HEAD on missing bucket: bare 404, no body parsing required
    code, _, _ = _req("HEAD", f"{s3}/head-ghost-b")
    assert code == 404


# ---------------------------------------------------------------------------
# Multipart (s3tests: test_multipart_*, test_abort_multipart_*)
# ---------------------------------------------------------------------------


def _initiate(s3, bucket, key):
    code, _, body = _req("POST", f"{s3}/{bucket}/{key}?uploads", b"")
    assert code == 200
    return _text(_xml(body), "UploadId")


def _upload_part(s3, bucket, key, upload_id, num, data):
    code, headers, _ = _req(
        "PUT",
        f"{s3}/{bucket}/{key}?partNumber={num}&uploadId={upload_id}",
        data)
    assert code == 200
    return headers.get("ETag")


def _complete_xml(parts):
    inner = "".join(
        f"<Part><PartNumber>{n}</PartNumber><ETag>{e}</ETag></Part>"
        for n, e in parts)
    return f"<CompleteMultipartUpload>{inner}</CompleteMultipartUpload>" \
        .encode()


def test_multipart_upload(s3):
    # s3tests test_multipart_upload: 3 parts, ETag gets "-<n>" suffix
    _mk_bucket(s3, "mp-b")
    uid = _initiate(s3, "mp-b", "big")
    part = b"p" * (5 << 20)
    etags = [_upload_part(s3, "mp-b", "big", uid, n, part)
             for n in (1, 2)]
    etags.append(_upload_part(s3, "mp-b", "big", uid, 3, b"tail"))
    code, _, body = _req(
        "POST", f"{s3}/mp-b/big?uploadId={uid}",
        _complete_xml(list(zip((1, 2, 3), etags))))
    assert code == 200
    assert b"CompleteMultipartUploadResult" in body
    etag = _text(_xml(body), "ETag")
    assert etag and etag.strip('"').endswith("-3")
    code, headers, got = _req("GET", f"{s3}/mp-b/big")
    assert code == 200
    assert got == part * 2 + b"tail"


def test_multipart_upload_incorrect_etag(s3):
    # s3tests test_multipart_upload_incorrect_etag -> 400 InvalidPart
    _mk_bucket(s3, "mpe-b")
    uid = _initiate(s3, "mpe-b", "bad")
    _upload_part(s3, "mpe-b", "bad", uid, 1, b"data")
    code, _, body = _req(
        "POST", f"{s3}/mpe-b/bad?uploadId={uid}",
        _complete_xml([(1, '"ffffffffffffffffffffffffffffffff"')]))
    assert code == 400
    assert b"InvalidPart" in body


def test_multipart_upload_missing_part(s3):
    # complete references a part never uploaded -> 400 InvalidPart
    _mk_bucket(s3, "mpm-b")
    uid = _initiate(s3, "mpm-b", "miss")
    _upload_part(s3, "mpm-b", "miss", uid, 1, b"data")
    code, _, body = _req(
        "POST", f"{s3}/mpm-b/miss?uploadId={uid}",
        _complete_xml([(1, '"%s"' % hashlib.md5(b"data").hexdigest()),
                       (2, '"%s"' % hashlib.md5(b"x").hexdigest())]))
    assert code == 400
    assert b"InvalidPart" in body


def test_abort_multipart_upload(s3):
    # s3tests test_abort_multipart_upload + abort_multipart_upload_not_found
    _mk_bucket(s3, "mpa-b")
    uid = _initiate(s3, "mpa-b", "gone")
    _upload_part(s3, "mpa-b", "gone", uid, 1, b"data")
    code, _, _ = _req("DELETE", f"{s3}/mpa-b/gone?uploadId={uid}")
    assert code == 204
    # the aborted upload is no longer listable / completable
    code, _, body = _req(
        "POST", f"{s3}/mpa-b/gone?uploadId={uid}",
        _complete_xml([(1, '"x"')]))
    assert code == 404
    assert b"NoSuchUpload" in body
    code, _, body = _req(
        "DELETE", f"{s3}/mpa-b/gone?uploadId=bogus-upload-id")
    assert code == 404


def test_multipart_upload_list_parts(s3):
    _mk_bucket(s3, "mpl-b")
    uid = _initiate(s3, "mpl-b", "lp")
    for n in (1, 2):
        _upload_part(s3, "mpl-b", "lp", uid, n, b"block-%d" % n)
    code, _, body = _req("GET", f"{s3}/mpl-b/lp?uploadId={uid}")
    assert code == 200
    root = _xml(body)
    nums = sorted(_text(p, "PartNumber") for p in _findall(root, "Part"))
    assert nums == ["1", "2"]


def test_multipart_invalid_part_order(s3):
    # s3tests test_multipart_upload_contents wrong order -> InvalidPartOrder
    _mk_bucket(s3, "mpo-b")
    uid = _initiate(s3, "mpo-b", "ord")
    e1 = _upload_part(s3, "mpo-b", "ord", uid, 1, b"one")
    e2 = _upload_part(s3, "mpo-b", "ord", uid, 2, b"two")
    code, _, body = _req(
        "POST", f"{s3}/mpo-b/ord?uploadId={uid}",
        _complete_xml([(2, e2), (1, e1)]))
    assert code == 400
    assert b"InvalidPartOrder" in body


def test_list_multipart_uploads(s3):
    _mk_bucket(s3, "mpu-b")
    uid = _initiate(s3, "mpu-b", "u1")
    code, _, body = _req("GET", f"{s3}/mpu-b?uploads")
    assert code == 200
    root = _xml(body)
    uploads = _findall(root, "Upload")
    assert any(_text(u, "UploadId") == uid for u in uploads)
    _req("DELETE", f"{s3}/mpu-b/u1?uploadId={uid}")


# ---------------------------------------------------------------------------
# Multi-object delete (s3tests: test_multi_object_delete)
# ---------------------------------------------------------------------------


def test_multi_object_delete(s3):
    _mk_bucket(s3, "mdel-b")
    for k in ("key0", "key1", "key2"):
        _put(s3, "mdel-b", k)
    payload = (
        b"<Delete>"
        b"<Object><Key>key0</Key></Object>"
        b"<Object><Key>key1</Key></Object>"
        b"<Object><Key>ghost</Key></Object>"
        b"</Delete>")
    code, _, body = _req("POST", f"{s3}/mdel-b?delete", payload)
    assert code == 200
    root = _xml(body)
    deleted = sorted(_text(d, "Key") for d in _findall(root, "Deleted"))
    # AWS semantics: deleting a nonexistent key still reports Deleted
    assert deleted == ["ghost", "key0", "key1"]
    code, _, body = _req("GET", f"{s3}/mdel-b")
    assert _keys(_xml(body)) == ["key2"]


# ---------------------------------------------------------------------------
# ACL surface (s3tests: test_bucket_acl_default)
# ---------------------------------------------------------------------------


def test_bucket_acl_default(s3):
    _mk_bucket(s3, "acl-b")
    code, _, body = _req("GET", f"{s3}/acl-b?acl")
    assert code == 200
    root = _xml(body)
    grants = _findall(_find(root, "AccessControlList"), "Grant")
    assert len(grants) == 1
    assert _text(grants[0], "Permission") == "FULL_CONTROL"


def test_object_acl_default(s3):
    _mk_bucket(s3, "acl2-b")
    _put(s3, "acl2-b", "o")
    code, _, body = _req("GET", f"{s3}/acl2-b/o?acl")
    assert code == 200
    assert b"FULL_CONTROL" in body


# ---------------------------------------------------------------------------
# Second tranche (r05): delimiter variants, unicode keys, copy directives
# ---------------------------------------------------------------------------


def test_bucket_list_delimiter_alt(s3):
    # s3tests test_bucket_list_delimiter_alt: delimiter 'a' groups on a
    # non-slash character
    _mk_bucket(s3, "dalt-b")
    for k in ("bar", "baza", "cab", "foo"):
        _put(s3, "dalt-b", k)
    code, _, body = _req("GET", f"{s3}/dalt-b?delimiter=a")
    assert code == 200
    root = _xml(body)
    assert _keys(root) == ["foo"]
    prefixes = sorted(
        _text(p, "Prefix") for p in _findall(root, "CommonPrefixes"))
    assert prefixes == ["ba", "ca"]


def test_bucket_list_delimiter_prefix_ends_with_delimiter(s3):
    # s3tests test_bucket_list_delimiter_prefix_ends_with_delimiter
    # adapted to weed semantics: a trailing-slash PUT creates a
    # directory marker (filer_server_handlers_write.go mkdir branch),
    # which surfaces as a CommonPrefix when listing its parent
    _mk_bucket(s3, "dpe-b")
    code, _, _ = _req("PUT", f"{s3}/dpe-b/asdf/", b"")
    assert code == 200
    code, _, body = _req("GET", f"{s3}/dpe-b?delimiter=/")
    root = _xml(body)
    assert [_text(p, "Prefix")
            for p in _findall(root, "CommonPrefixes")] == ["asdf/"]
    # objects under the marker list normally
    _put(s3, "dpe-b", "asdf/child.txt", b"c")
    code, _, body = _req(
        "GET", f"{s3}/dpe-b?prefix=asdf/&delimiter=/")
    assert _keys(_xml(body)) == ["asdf/child.txt"]


def test_bucket_list_unicode_keys(s3):
    # s3tests test_bucket_list_distinct + unicode coverage
    _mk_bucket(s3, "uni-b")
    keys = ["éclair.txt", "日本語/doc.md", "plain.txt"]
    for k in keys:
        code, _, _ = _req(
            "PUT", f"{s3}/uni-b/{urllib.parse.quote(k)}", b"u")
        assert code == 200
    code, _, body = _req("GET", f"{s3}/uni-b")
    got = _keys(_xml(body))
    assert sorted(got) == sorted(
        ["éclair.txt", "日本語/doc.md", "plain.txt"])
    code, _, b = _req(
        "GET", f"{s3}/uni-b/{urllib.parse.quote(keys[0])}")
    assert (code, b) == (200, b"u")


def test_object_copy_replace_metadata(s3):
    # s3tests test_object_copy_canned_acl/metadata: REPLACE directive
    # swaps user metadata; default COPY carries it over
    _mk_bucket(s3, "cmd-b")
    _put(s3, "cmd-b", "src", b"copy-meta",
         {"x-amz-meta-orig": "one"})
    code, _, _ = _req(
        "PUT", f"{s3}/cmd-b/kept", b"",
        {"x-amz-copy-source": "/cmd-b/src"})
    assert code == 200
    assert _req("HEAD", f"{s3}/cmd-b/kept")[1].get(
        "x-amz-meta-orig") == "one"
    code, _, _ = _req(
        "PUT", f"{s3}/cmd-b/swapped", b"",
        {"x-amz-copy-source": "/cmd-b/src",
         "x-amz-metadata-directive": "REPLACE",
         "x-amz-meta-fresh": "two"})
    assert code == 200
    h = _req("HEAD", f"{s3}/cmd-b/swapped")[1]
    assert h.get("x-amz-meta-fresh") == "two"
    assert h.get("x-amz-meta-orig") is None


def test_object_copy_to_itself_without_replace(s3):
    # s3tests test_object_copy_to_itself: same source+dest without
    # REPLACE is invalid
    _mk_bucket(s3, "self-b")
    _put(s3, "self-b", "me", b"x")
    code, _, body = _req(
        "PUT", f"{s3}/self-b/me", b"",
        {"x-amz-copy-source": "/self-b/me"})
    assert code == 400
    assert b"InvalidRequest" in body
    # with REPLACE it is the canonical way to rewrite metadata in place
    code, _, _ = _req(
        "PUT", f"{s3}/self-b/me", b"",
        {"x-amz-copy-source": "/self-b/me",
         "x-amz-metadata-directive": "REPLACE",
         "x-amz-meta-new": "v"})
    assert code == 200
    assert _req("HEAD", f"{s3}/self-b/me")[1].get("x-amz-meta-new") == "v"


def test_directory_marker_lifecycle(s3):
    # weed-adapted: marker PUT/HEAD/GET/DELETE round trip, with a
    # non-empty marker body served back and a real md5 ETag
    _mk_bucket(s3, "mk-b")
    code, headers, _ = _req("PUT", f"{s3}/mk-b/folder/", b"")
    assert code == 200
    assert headers.get("ETag").strip('"') == hashlib.md5(b"").hexdigest()
    code, headers, _ = _req("HEAD", f"{s3}/mk-b/folder/")
    assert code == 200 and headers.get("Content-Length") == "0"
    code, _, body = _req("GET", f"{s3}/mk-b/folder/")
    assert (code, body) == (200, b"")
    # non-empty marker body rides along and reads back
    code, headers, _ = _req("PUT", f"{s3}/mk-b/notes/", b"marker-bytes")
    assert headers.get("ETag").strip('"') == \
        hashlib.md5(b"marker-bytes").hexdigest()
    code, _, body = _req("GET", f"{s3}/mk-b/notes/")
    assert (code, body) == (200, b"marker-bytes")
    # DELETE removes an empty marker; children keep a prefix alive
    code, _, _ = _req("DELETE", f"{s3}/mk-b/notes/")
    assert code == 204
    code, _, _ = _req("HEAD", f"{s3}/mk-b/notes/")
    assert code == 404
    _put(s3, "mk-b", "folder/kid.txt", b"k")
    code, _, _ = _req("DELETE", f"{s3}/mk-b/folder/")
    assert code == 204
    code, _, body = _req("GET", f"{s3}/mk-b/folder/kid.txt")
    assert (code, body) == (200, b"k")


# ---------------------------------------------------------------------------
# Tenant admission + quota rejections (ISSUE 7: fleet error semantics)
# ---------------------------------------------------------------------------


def _reject_count(reason):
    from seaweedfs_tpu.stats.metrics import S3_REJECT

    return S3_REJECT.labels(reason).value


def test_quota_exceeded_returns_403_error_xml(s3):
    """An over-quota tenant gets well-formed 403 QuotaExceeded XML and
    the reject counter moves; a second tenant proceeds unthrottled."""
    _mk_bucket(s3, "quota-b")
    _mk_bucket(s3, "quota-free")
    _put(s3, "quota-b", "one", b"fits")
    _STACK["filer"].tenants.set_config("quota-b", quota_objects=1)
    before = _reject_count("quota")
    code, _, body = _req("PUT", f"{s3}/quota-b/two", b"over")
    assert code == 403, (code, body)
    root = _xml(body)
    assert root.tag == "Error"
    assert _text(root, "Code") == "QuotaExceeded"
    assert _text(root, "Resource") == "/quota-b/two"
    assert _reject_count("quota") == before + 1
    # the other tenant's writes proceed
    _put(s3, "quota-free", "anything", b"ok")
    # overwrite of the existing object is NOT a new object -> allowed
    _put(s3, "quota-b", "one", b"rewritten")
    # freeing the slot re-admits writes
    assert _req("DELETE", f"{s3}/quota-b/one")[0] == 204
    _put(s3, "quota-b", "two", b"now fits")
    _STACK["filer"].tenants.set_config("quota-b", quota_objects=0)


def test_admission_slowdown_returns_503_with_retry_after(s3):
    """WFQ admission rejections surface as 503 SlowDown XML with a
    Retry-After header (the S3 throttle contract SDKs back off on)."""
    _mk_bucket(s3, "slow-b")
    _put(s3, "slow-b", "obj", b"seed")
    filer = _STACK["filer"]
    old_capacity = filer.admission.capacity
    filer.admission.capacity = 1
    slot = filer.admission.admit("slow-b")
    slot.__enter__()  # the tenant now holds the whole capacity
    try:
        before = _reject_count("slowdown")
        code, headers, body = _req("GET", f"{s3}/slow-b/obj")
        assert code == 503, (code, body)
        root = _xml(body)
        assert _text(root, "Code") == "SlowDown"
        assert int(headers.get("Retry-After", "0")) >= 1
        assert _reject_count("slowdown") == before + 1
        # an untouched tenant keeps its reserved share
        _mk_bucket(s3, "slow-free")
        _put(s3, "slow-free", "k", b"independent tenant")
        assert _req("GET", f"{s3}/slow-free/k")[0] == 200
    finally:
        slot.__exit__(None, None, None)
        filer.admission.capacity = old_capacity
    # capacity released: the throttled tenant is served again
    assert _req("GET", f"{s3}/slow-b/obj")[0] == 200
