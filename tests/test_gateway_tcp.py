"""Generic REST gateway + experimental raw-TCP volume data path.

Reference: weed/command/gateway.go + server/gateway_server.go;
weed/server/volume_server_tcp_handlers_write.go.
"""

from __future__ import annotations

import json
import socket
import struct
import time
import urllib.request

import pytest

from helpers import free_port


@pytest.fixture(scope="module")
def stack(tmp_path_factory):
    from seaweedfs_tpu.filer.server import FilerServer
    from seaweedfs_tpu.gateway import GatewayServer
    from seaweedfs_tpu.master.server import MasterServer
    from seaweedfs_tpu.volume.server import VolumeServer

    master = MasterServer(ip="127.0.0.1", port=free_port(),
                          volume_size_limit_mb=64)
    master.start()
    tcp_port = free_port()
    vs = VolumeServer(
        directories=[str(tmp_path_factory.mktemp("gwvol"))],
        master_addresses=[f"127.0.0.1:{master.grpc_port}"],
        ip="127.0.0.1", port=free_port(), pulse_seconds=0.5,
        max_volume_count=100, tcp_port=tcp_port,
    )
    vs.start()
    deadline = time.time() + 15
    while time.time() < deadline and len(master.topo.nodes) < 1:
        time.sleep(0.1)
    filer = FilerServer(
        masters=[f"127.0.0.1:{master.grpc_port}"],
        ip="127.0.0.1", port=free_port(), store="memory", max_mb=1,
    )
    filer.start()
    gw = GatewayServer(masters=[f"127.0.0.1:{master.port}"],
                       filers=[f"127.0.0.1:{filer.port}"],
                       port=free_port())
    gw.start()
    yield master, vs, filer, gw, tcp_port
    gw.stop()
    filer.stop()
    vs.stop()
    master.stop()


def _req(url, method="GET", data=None, headers=None):
    req = urllib.request.Request(url, data=data, method=method,
                                 headers=headers or {})
    try:
        with urllib.request.urlopen(req, timeout=10) as r:
            return r.status, r.read()
    except urllib.error.HTTPError as e:
        return e.code, e.read()


def test_gateway_blobs(stack):
    _, _, _, gw, _ = stack
    code, body = _req(f"http://127.0.0.1:{gw.port}/blobs/", "POST",
                      b"gateway blob payload")
    assert code == 201, body
    fid = json.loads(body)["fid"]
    # blob readable directly from the volume server
    url = json.loads(body)["url"]
    code, data = _req(f"http://{url}")
    assert code == 200 and data == b"gateway blob payload"
    code, _ = _req(f"http://127.0.0.1:{gw.port}/blobs/{fid}", "DELETE")
    assert code in (200, 202)
    code, _ = _req(f"http://{url}")
    assert code == 404


def test_gateway_files(stack):
    _, _, _, gw, _ = stack
    base = f"http://127.0.0.1:{gw.port}"
    code, body = _req(f"{base}/files/docs/readme.txt", "POST",
                      b"via gateway")
    assert code == 201, body
    code, body = _req(f"{base}/files/docs/readme.txt")
    assert code == 200 and body == b"via gateway"
    code, _ = _req(f"{base}/files/docs/readme.txt", "DELETE")
    assert code in (200, 204)
    code, _ = _req(f"{base}/files/docs/readme.txt")
    assert code == 404


def test_gateway_topics(stack):
    _, _, filer, gw, _ = stack
    base = f"http://127.0.0.1:{gw.port}"
    for i in range(3):
        code, body = _req(f"{base}/topics/chat/room1", "POST",
                          f"msg-{i}\n".encode())
        assert code == 201, body
    # messages accumulate in the filer-backed topic log
    code, body = _req(
        f"http://127.0.0.1:{filer.port}/topics/chat/room1/messages.log")
    assert code == 200
    assert body == b"msg-0\nmsg-1\nmsg-2\n"


def _tcp_cmd(sock_file, wfile, line: bytes, payload: bytes = b""):
    wfile.write(line + b"\n" + payload)
    wfile.flush()
    return sock_file.readline()


def test_tcp_put_get_delete(stack):
    master, vs, _, _, tcp_port = stack
    with urllib.request.urlopen(
            f"http://127.0.0.1:{master.port}/dir/assign", timeout=10) as r:
        fid = json.loads(r.read())["fid"]
    payload = b"tcp-needle-payload" * 10
    s = socket.create_connection(("127.0.0.1", tcp_port), timeout=10)
    rf, wf = s.makefile("rb"), s.makefile("wb")
    # put
    resp = _tcp_cmd(rf, wf, f"+{fid}".encode(),
                    struct.pack(">I", len(payload)) + payload)
    assert resp == b"+OK\n"
    # get
    wf.write(f"?{fid}\n".encode())
    wf.flush()
    head = rf.readline()
    assert head.startswith(b"+OK ")
    size = int(head.split()[1])
    assert rf.read(size) == payload
    # the same needle is readable over HTTP too
    with urllib.request.urlopen(
            f"http://127.0.0.1:{vs.port}/{fid}", timeout=10) as r:
        assert r.read() == payload
    # delete + get -> error
    assert _tcp_cmd(rf, wf, f"-{fid}".encode()) == b"+OK\n"
    wf.write(f"?{fid}\n".encode())
    wf.flush()
    assert rf.readline().startswith(b"-ERR")
    # unknown command
    assert _tcp_cmd(rf, wf, b"zwhat").startswith(b"-ERR")
    # a bad fid on '+' still consumes its frame: the NEXT command parses
    # (no protocol desync)
    bad_payload = b"xyz"
    resp = _tcp_cmd(rf, wf, b"+notafid",
                    struct.pack(">I", len(bad_payload)) + bad_payload)
    assert resp.startswith(b"-ERR")
    wf.write(b"?" + fid.encode() + b"\n")
    wf.flush()
    assert rf.readline().startswith(b"-ERR")  # deleted above, but PARSED
    s.close()
