"""Compact needle map: two-tier correctness + the 10M-entry scale test
(reference: weed/storage/needle_map/compact_map.go:28-50 and its
compact_map_perf_test.go, which loads 10M entries)."""

import time

import numpy as np
import pytest

from seaweedfs_tpu.storage.needle_map import NeedleMap


def test_put_get_delete_with_merges():
    nm = NeedleMap(merge_threshold=8)  # force frequent tier merges
    for k in range(100):
        nm.put(k + 1, (k + 1) * 8, 100 + k)
    assert len(nm) == 100
    assert nm.file_count == 100
    assert nm.get(50).offset == 50 * 8
    assert nm.get(50).size == 149
    assert 101 not in nm
    # overwrite: accounting moves old bytes to deleted
    nm.put(50, 8000, 500)
    assert nm.get(50).offset == 8000
    assert nm.get(50).size == 500
    assert nm.deleted_count == 1
    assert nm.deleted_bytes == 149
    # delete across tiers
    freed = nm.delete(51)
    assert freed == 150
    assert nm.get(51) is None
    assert len(nm) == 99
    assert nm.delete(51) == 0  # idempotent
    # re-insert after delete
    nm.put(51, 400, 7)
    assert nm.get(51).size == 7
    assert len(nm) == 100


def test_iteration_sorted_and_next_key():
    nm = NeedleMap(merge_threshold=4)
    keys = [9, 2, 77, 31, 5, 64, 100, 1]
    for k in keys:
        nm.put(k, k * 8, k)
    assert nm.sorted_keys() == sorted(keys)
    got = [v.key for v in nm.items_ascending()]
    assert got == sorted(keys)
    assert nm.next_key_after(5) == 9
    assert nm.next_key_after(100) is None
    assert nm.maximum_key == 100


def test_content_size_accounting():
    nm = NeedleMap()
    nm.put(1, 8, 10)
    nm.put(2, 16, 20)
    assert nm.content_size == 30
    nm.delete(1)
    assert nm.content_size == 20
    nm.put(2, 24, 5)  # overwrite shrinks
    assert nm.content_size == 5


def test_write_sorted_index_matches_scalar_pack(tmp_path):
    nm = NeedleMap()
    vals = [(5, 40, 11), (1, 8, 22), (9, 1024, 33)]
    for k, o, s in vals:
        nm.put(k, o, s)
    p = tmp_path / "x.ecx"
    nm.write_sorted_index(p)
    from seaweedfs_tpu.storage import types as t

    blob = p.read_bytes()
    want = b"".join(
        t.pack_index_entry(k, o, s) for k, o, s in sorted(vals)
    )
    assert blob == want


def test_load_from_idx_with_deletes(tmp_path):
    from seaweedfs_tpu.storage import types as t

    p = tmp_path / "v.idx"
    with open(p, "wb") as f:
        f.write(t.pack_index_entry(1, 8, 100))
        f.write(t.pack_index_entry(2, 112, 200))
        f.write(t.pack_index_entry(1, 320, 150))  # overwrite
        f.write(t.pack_index_entry(2, 480, t.TOMBSTONE_FILE_SIZE))  # delete
    nm = NeedleMap.load_from_idx(p)
    assert len(nm) == 1
    assert nm.get(1).offset == 320
    assert nm.get(2) is None
    assert nm.deleted_count == 2  # one overwrite + one tombstone


def test_scale_10m_entries(tmp_path):
    """10M entries load in seconds and cost ~20 bytes each (the reference's
    compact-map perf envelope), with correct random lookups."""
    n = 10_000_000
    keys = np.arange(1, n + 1, dtype=np.uint64)
    out = np.empty((n, 16), dtype=np.uint8)
    out[:, 0:8] = keys[:, None].view(np.uint8).reshape(n, 8)[:, ::-1]
    stored = np.arange(1, n + 1, dtype=">u4")  # offset/8
    out[:, 8:12] = stored[:, None].view(np.uint8).reshape(n, 4)
    sizes = np.full(n, 1000, dtype=">u4")
    out[:, 12:16] = sizes[:, None].view(np.uint8).reshape(n, 4)
    p = tmp_path / "big.idx"
    with open(p, "wb") as f:
        f.write(out.tobytes())

    t0 = time.monotonic()
    nm = NeedleMap.load_from_idx(p)
    load_s = time.monotonic() - t0
    assert len(nm) == n
    assert nm.maximum_key == n
    # ~20 B/entry in the base tier (plus numpy overhead, nowhere near a dict)
    base_bytes = nm._keys.nbytes + nm._offsets.nbytes + nm._sizes.nbytes
    assert base_bytes <= 24 * n
    rng = np.random.default_rng(0)
    for k in rng.integers(1, n + 1, 1000):
        v = nm.get(int(k))
        assert v is not None and v.offset == int(k) * 8
    assert nm.get(n + 5) is None
    assert load_s < 30, f"10M-entry load took {load_s:.1f}s"


def test_disk_needle_map_bounded_ram(tmp_path):
    """needle_map.go:13-19 low-memory kinds: the disk map serves lookups
    by on-disk binary search; resident state stays bounded by the
    overflow limit no matter how many needles exist."""
    from seaweedfs_tpu.storage.disk_needle_map import DiskNeedleMap

    m = DiskNeedleMap(str(tmp_path / "1.sdx"), overflow_limit=500)
    n = 5000
    for k in range(1, n + 1):
        m.put(k, k * 8, 100 + (k % 7))
    # RAM budget: overflow never exceeds its bound (+merge hysteresis)
    assert len(m._overflow) + len(m._deleted) <= 501
    assert m._base_count >= n - 501
    assert len(m) == n
    for k in (1, 250, 2500, n):
        nv = m.get(k)
        assert nv is not None and nv.offset == k * 8
    assert m.get(n + 1) is None
    # deletes fold through merges
    for k in range(1, 1001):
        m.delete(k)
    assert len(m) == n - 1000
    assert m.get(500) is None and m.get(1001) is not None
    # ascending iteration is the merged view
    keys = m.sorted_keys()
    assert keys[0] == 1001 and keys[-1] == n and len(keys) == n - 1000
    m.close()


def test_disk_needle_map_volume_roundtrip(tmp_path):
    """A volume loads and serves with the disk-backed map."""
    from seaweedfs_tpu.storage import volume as volmod
    from seaweedfs_tpu.storage.disk_needle_map import DiskNeedleMap

    from helpers import make_volume

    volmod.set_needle_map_kind("disk")
    try:
        vol = make_volume(str(tmp_path), n_needles=40)
        assert isinstance(vol.needle_map, DiskNeedleMap)
        data = bytes(vol.read_needle(7).data)
        assert data
        vol.delete_needle(7)
        import pytest as _pytest

        with _pytest.raises(KeyError):
            vol.read_needle(7)
        vol.close()
        # reload from .idx: still disk-backed, still serves
        vol2 = volmod.Volume(str(tmp_path), "", 1)
        assert isinstance(vol2.needle_map, DiskNeedleMap)
        assert vol2.read_needle(8).data
        with _pytest.raises(KeyError):
            vol2.read_needle(7)
        vol2.close()
    finally:
        volmod.set_needle_map_kind("memory")
