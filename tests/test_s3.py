"""S3 gateway tests: SigV4 primitives against the pinned AWS documentation
vector, identity/action scoping, the aws-chunked decoder, and a live
bucket/object/multipart/tagging sweep over a mini-cluster.

Reference analogues: weed/s3api/auto_signature_v4_test.go and the
ceph/s3-tests compose tier (SURVEY.md §4 tier 4).
"""

import hashlib
import json
import socket
import time
import urllib.error
import urllib.parse
import urllib.request
import xml.etree.ElementTree as ET

import pytest

from seaweedfs_tpu.s3api import auth as s3auth


def _free_port() -> int:
    from helpers import free_port

    return free_port()


# -- signature primitives ----------------------------------------------------


def test_sigv4_aws_documented_vector():
    """The AWS General Reference worked example (get-vanilla, iam service):
    pins the canonical-request / string-to-sign / signing-key chain."""
    headers = {
        "content-type": "application/x-www-form-urlencoded; charset=utf-8",
        "host": "iam.amazonaws.com",
        "x-amz-date": "20150830T123600Z",
    }
    canon = s3auth.canonical_request(
        "GET",
        "/",
        "Action=ListUsers&Version=2010-05-08",
        headers,
        ["content-type", "host", "x-amz-date"],
        hashlib.sha256(b"").hexdigest(),
    )
    assert hashlib.sha256(canon.encode()).hexdigest() == (
        "f536975d06c0309214f805bb90ccff089219ecd68b2577efef23edd43b7e1a59"
    )
    sig = s3auth.sign_v4(
        "wJalrXUtnFEMI/K7MDENG+bPxRfiCYEXAMPLEKEY",
        "20150830",
        "us-east-1",
        "iam",
        "20150830T123600Z",
        canon,
    )
    assert sig == (
        "5d672d79c15b13162d9279b0855cfba6789a8edb4c82c400e06b5924a6f2b5d7"
    )


def test_identity_action_scoping():
    iam = s3auth.IdentityAccessManagement()
    iam.load_config(
        {
            "identities": [
                {
                    "name": "admin",
                    "credentials": [{"accessKey": "AK1", "secretKey": "SK1"}],
                    "actions": ["Admin"],
                },
                {
                    "name": "readonly-b1",
                    "credentials": [{"accessKey": "AK2", "secretKey": "SK2"}],
                    "actions": ["Read:b1", "List:b1"],
                },
            ]
        }
    )
    admin, _ = iam.lookup("AK1")
    limited, _ = iam.lookup("AK2")
    assert admin.can_do(s3auth.ACTION_WRITE, "anything")
    assert limited.can_do(s3auth.ACTION_READ, "b1")
    assert not limited.can_do(s3auth.ACTION_READ, "b2")
    assert not limited.can_do(s3auth.ACTION_WRITE, "b1")
    with pytest.raises(s3auth.AuthError):
        iam.authorize(limited, s3auth.ACTION_WRITE, "b1")


def test_streaming_chunk_decode_and_verify():
    """Build an aws-chunked body with a correctly chained signature and
    check the decoder both reassembles and verifies it."""
    secret, date, region = "sekrit", "20260729", "us-east-1"
    amz_date = "20260729T000000Z"
    seed = "a" * 64
    req = s3auth.S3HttpRequest(
        method="PUT", raw_path="/b/k", raw_query="", headers={},
        seed_signature=seed, sig_date=date, sig_region=region,
        sig_secret=secret, sig_amz_date=amz_date,
    )
    key = s3auth.signing_key(secret, date, region, "s3")
    empty = hashlib.sha256(b"").hexdigest()

    def chunk_sig(prev, data):
        sts = "\n".join([
            "AWS4-HMAC-SHA256-PAYLOAD", amz_date,
            f"{date}/{region}/s3/aws4_request", prev, empty,
            hashlib.sha256(data).hexdigest(),
        ])
        import hmac as _hmac

        return _hmac.new(key, sts.encode(), hashlib.sha256).hexdigest()

    parts = [b"hello ", b"chunked world", b""]
    body = b""
    prev = seed
    for data in parts:
        sig = chunk_sig(prev, data)
        body += f"{len(data):x};chunk-signature={sig}\r\n".encode()
        body += data + b"\r\n"
        prev = sig
    assert s3auth.decode_streaming_body(body, req) == b"hello chunked world"
    # corrupt one chunk signature -> rejected
    bad = body.replace(b"chunk-signature=" + chunk_sig(seed, parts[0]).encode(),
                       b"chunk-signature=" + b"0" * 64)
    with pytest.raises(s3auth.AuthError):
        s3auth.decode_streaming_body(bad, req)


def test_streaming_truncation_rejected():
    """A signed stream cut at a chunk boundary (no terminal 0-size chunk)
    must fail, and a decoded length differing from the signed
    x-amz-decoded-content-length must fail (chunked_reader_v4.go behavior)."""
    body = b"5;chunk-signature=" + b"0" * 64 + b"\r\nhello\r\n"
    with pytest.raises(s3auth.AuthError) as ei:
        s3auth.decode_streaming_body(body)  # unverified decode, still gated
    assert ei.value.code == "IncompleteBody"
    whole = body + b"0;chunk-signature=" + b"0" * 64 + b"\r\n\r\n"
    assert s3auth.decode_streaming_body(whole) == b"hello"
    req = s3auth.S3HttpRequest(
        method="PUT", raw_path="/b/k", raw_query="",
        headers={"x-amz-decoded-content-length": "9"},
    )
    with pytest.raises(s3auth.AuthError) as ei:
        s3auth.decode_streaming_body(whole, req)
    assert ei.value.code == "IncompleteBody"


def test_v2_replay_window():
    """V2 header auth must reject requests whose Date is outside the
    15-minute skew window (same bound V4 enforces)."""
    import base64
    import email.utils
    import hmac as _hmac

    iam = s3auth.IdentityAccessManagement()
    iam.load_config({"identities": [{
        "name": "u", "credentials": [{"accessKey": "AK", "secretKey": "SK"}],
        "actions": ["Admin"],
    }]})

    def v2_req(date_header):
        req = s3auth.S3HttpRequest(
            method="GET", raw_path="/b/k", raw_query="",
            headers={"date": date_header},
        )
        sts = iam._v2_string_to_sign(req)
        sig = base64.b64encode(
            _hmac.new(b"SK", sts.encode(), hashlib.sha1).digest()
        ).decode()
        req.headers["authorization"] = f"AWS AK:{sig}"
        return req

    fresh = v2_req(email.utils.formatdate(usegmt=True))
    assert iam.authenticate(fresh).name == "u"
    stale = v2_req("Tue, 27 Mar 2007 19:36:42 +0000")
    with pytest.raises(s3auth.AuthError) as ei:
        iam.authenticate(stale)
    assert ei.value.code == "RequestTimeTooSkewed"


# -- live gateway ------------------------------------------------------------


def _req(method, url, data=None, headers=None):
    req = urllib.request.Request(url, data=data, method=method,
                                 headers=headers or {})
    try:
        with urllib.request.urlopen(req, timeout=20) as r:
            return r.status, dict(r.headers), r.read()
    except urllib.error.HTTPError as e:
        return e.code, dict(e.headers), e.read()


@pytest.fixture(scope="module")
def s3_cluster(tmp_path_factory):
    from seaweedfs_tpu.filer.server import FilerServer
    from seaweedfs_tpu.master.server import MasterServer
    from seaweedfs_tpu.s3api.server import S3ApiServer
    from seaweedfs_tpu.volume.server import VolumeServer

    master = MasterServer(ip="127.0.0.1", port=_free_port(),
                          volume_size_limit_mb=64)
    master.start()
    vs = VolumeServer(
        directories=[str(tmp_path_factory.mktemp("s3vol"))],
        master_addresses=[f"127.0.0.1:{master.grpc_port}"],
        ip="127.0.0.1", port=_free_port(), pulse_seconds=0.5,
        max_volume_count=100,  # every bucket grows a 3-volume collection
    )
    vs.start()
    deadline = time.time() + 15
    while time.time() < deadline and len(master.topo.nodes) < 1:
        time.sleep(0.1)
    filer = FilerServer(
        masters=[f"127.0.0.1:{master.grpc_port}"],
        ip="127.0.0.1", port=_free_port(),
        store="sqlite",
        store_path=str(tmp_path_factory.mktemp("s3db") / "filer.db"),
        max_mb=1,
    )
    filer.start()
    s3 = S3ApiServer(filer=f"127.0.0.1:{filer.port}", port=_free_port())
    s3.start()
    yield master, vs, filer, s3
    s3.stop()
    filer.stop()
    vs.stop()
    master.stop()


def _base(s3_cluster):
    return f"http://127.0.0.1:{s3_cluster[3].port}"


def test_s3_bucket_lifecycle(s3_cluster):
    base = _base(s3_cluster)
    code, _, _ = _req("PUT", f"{base}/bk1")
    assert code == 200
    code, _, _ = _req("PUT", f"{base}/bk1")
    assert code == 409  # duplicate
    code, _, body = _req("GET", f"{base}/")
    assert code == 200 and b"<Name>bk1</Name>" in body
    code, _, _ = _req("HEAD", f"{base}/bk1")
    assert code == 200
    code, _, _ = _req("HEAD", f"{base}/nope")
    assert code == 404


def test_s3_object_roundtrip(s3_cluster):
    base = _base(s3_cluster)
    _req("PUT", f"{base}/objs")
    payload = b"the quick brown fox" * 1000
    etag = hashlib.md5(payload).hexdigest()
    code, headers, _ = _req(
        "PUT", f"{base}/objs/dir/hello.txt", payload,
        {"Content-Type": "text/plain", "x-amz-meta-color": "blue"},
    )
    assert code == 200 and headers["ETag"] == f'"{etag}"'
    code, headers, body = _req("GET", f"{base}/objs/dir/hello.txt")
    assert code == 200 and body == payload
    assert headers["ETag"] == f'"{etag}"'
    assert headers["x-amz-meta-color"] == "blue"
    assert headers["Content-Type"] == "text/plain"
    # range
    code, headers, body = _req("GET", f"{base}/objs/dir/hello.txt", None,
                               {"Range": "bytes=4-8"})
    assert code == 206 and body == payload[4:9]
    # head
    code, headers, _ = _req("HEAD", f"{base}/objs/dir/hello.txt")
    assert code == 200 and int(headers["Content-Length"]) == len(payload)
    # missing
    code, _, _ = _req("GET", f"{base}/objs/none.txt")
    assert code == 404
    # delete
    code, _, _ = _req("DELETE", f"{base}/objs/dir/hello.txt")
    assert code == 204
    code, _, _ = _req("GET", f"{base}/objs/dir/hello.txt")
    assert code == 404


def test_s3_copy_object(s3_cluster):
    base = _base(s3_cluster)
    _req("PUT", f"{base}/cpy")
    _req("PUT", f"{base}/cpy/src.bin", b"copy me",
         {"x-amz-meta-origin": "here"})
    code, _, body = _req(
        "PUT", f"{base}/cpy/dst.bin", None,
        {"x-amz-copy-source": "/cpy/src.bin"},
    )
    assert code == 200 and b"CopyObjectResult" in body
    code, headers, got = _req("GET", f"{base}/cpy/dst.bin")
    assert code == 200 and got == b"copy me"
    assert headers["x-amz-meta-origin"] == "here"


def test_s3_listing(s3_cluster):
    base = _base(s3_cluster)
    _req("PUT", f"{base}/lst")
    for k in ["a.txt", "b/one.txt", "b/two.txt", "c.txt", "b/deep/x.txt"]:
        _req("PUT", f"{base}/lst/{k}", b"d")
    # V1, no delimiter: recursive key order
    code, _, body = _req("GET", f"{base}/lst")
    keys = [e.text for e in ET.fromstring(body).iter()
            if e.tag.endswith("Key")]
    assert keys == ["a.txt", "b/deep/x.txt", "b/one.txt", "b/two.txt", "c.txt"]
    # delimiter
    code, _, body = _req("GET", f"{base}/lst?delimiter=/")
    tree = ET.fromstring(body)
    keys = [e.text for e in tree.iter() if e.tag.endswith("Key")]
    prefixes = [e.text for e in tree.iter() if e.tag.endswith("Prefix") and e.text]
    assert keys == ["a.txt", "c.txt"]
    assert "b/" in prefixes
    # prefix + delimiter
    code, _, body = _req("GET", f"{base}/lst?delimiter=/&prefix=b/")
    tree = ET.fromstring(body)
    keys = [e.text for e in tree.iter() if e.tag.endswith("Key")]
    assert keys == ["b/one.txt", "b/two.txt"]
    # V2 with max-keys paging
    ns = "{http://s3.amazonaws.com/doc/2006-03-01/}"
    code, _, body = _req("GET", f"{base}/lst?list-type=2&max-keys=2")
    tree = ET.fromstring(body)
    assert tree.findtext(f"{ns}IsTruncated") == "true"
    token = tree.findtext(f"{ns}NextContinuationToken")
    code, _, body = _req(
        f"GET", f"{base}/lst?list-type=2&continuation-token="
        + urllib.parse.quote(token)
    )
    keys2 = [e.text for e in ET.fromstring(body).iter()
             if e.tag.endswith("Key")]
    assert keys2 == ["b/one.txt", "b/two.txt", "c.txt"]


def test_s3_multipart(s3_cluster):
    base = _base(s3_cluster)
    _req("PUT", f"{base}/mpb")
    code, _, body = _req("POST", f"{base}/mpb/big.bin?uploads", b"")
    assert code == 200
    upload_id = ET.fromstring(body).findtext(
        "{http://s3.amazonaws.com/doc/2006-03-01/}UploadId"
    )
    assert upload_id
    # two parts, each > filer chunk size (1MB) to force multi-chunk splice
    p1 = b"A" * (1 << 20) + b"B" * 512
    p2 = b"C" * 2048
    etags = []
    for i, p in ((1, p1), (2, p2)):
        code, headers, _ = _req(
            "PUT", f"{base}/mpb/big.bin?partNumber={i}&uploadId={upload_id}", p
        )
        assert code == 200
        etags.append(headers["ETag"])
    # list parts
    code, _, body = _req("GET", f"{base}/mpb/big.bin?uploadId={upload_id}")
    assert code == 200 and b"<PartNumber>1</PartNumber>" in body
    complete = (
        "<CompleteMultipartUpload>"
        + "".join(
            f"<Part><PartNumber>{i}</PartNumber><ETag>{e}</ETag></Part>"
            for i, e in ((1, etags[0]), (2, etags[1]))
        )
        + "</CompleteMultipartUpload>"
    ).encode()
    code, _, body = _req(
        "POST", f"{base}/mpb/big.bin?uploadId={upload_id}", complete
    )
    assert code == 200 and b"CompleteMultipartUploadResult" in body
    code, headers, got = _req("GET", f"{base}/mpb/big.bin")
    assert code == 200 and got == p1 + p2
    assert headers["ETag"].endswith('-2"')
    # upload dir is gone
    code, _, body = _req("GET", f"{base}/mpb?uploads")
    assert upload_id.encode() not in body


def test_s3_multipart_abort(s3_cluster):
    base = _base(s3_cluster)
    _req("PUT", f"{base}/mpa")
    code, _, body = _req("POST", f"{base}/mpa/x.bin?uploads", b"")
    upload_id = ET.fromstring(body).findtext(
        "{http://s3.amazonaws.com/doc/2006-03-01/}UploadId"
    )
    _req("PUT", f"{base}/mpa/x.bin?partNumber=1&uploadId={upload_id}", b"zz")
    code, _, _ = _req("DELETE", f"{base}/mpa/x.bin?uploadId={upload_id}")
    assert code == 204
    code, _, _ = _req(
        "POST", f"{base}/mpa/x.bin?uploadId={upload_id}",
        b"<CompleteMultipartUpload></CompleteMultipartUpload>",
    )
    assert code == 404  # NoSuchUpload


def test_s3_delete_multiple(s3_cluster):
    base = _base(s3_cluster)
    _req("PUT", f"{base}/dmb")
    for k in ["x1", "x2", "x3"]:
        _req("PUT", f"{base}/dmb/{k}", b"v")
    payload = (
        "<Delete>"
        "<Object><Key>x1</Key></Object>"
        "<Object><Key>x3</Key></Object>"
        "</Delete>"
    ).encode()
    code, _, body = _req("POST", f"{base}/dmb?delete", payload)
    assert code == 200
    assert body.count(b"<Deleted>") == 2
    code, _, _ = _req("GET", f"{base}/dmb/x1")
    assert code == 404
    code, _, _ = _req("GET", f"{base}/dmb/x2")
    assert code == 200


def test_s3_tagging(s3_cluster):
    base = _base(s3_cluster)
    _req("PUT", f"{base}/tgb")
    _req("PUT", f"{base}/tgb/obj", b"v")
    tags = (
        "<Tagging><TagSet>"
        "<Tag><Key>env</Key><Value>prod</Value></Tag>"
        "<Tag><Key>team</Key><Value>tpu</Value></Tag>"
        "</TagSet></Tagging>"
    ).encode()
    code, _, _ = _req("PUT", f"{base}/tgb/obj?tagging", tags)
    assert code == 200
    code, _, body = _req("GET", f"{base}/tgb/obj?tagging")
    assert code == 200 and b"<Key>env</Key>" in body and b"prod" in body
    code, _, _ = _req("DELETE", f"{base}/tgb/obj?tagging")
    assert code == 204
    code, _, body = _req("GET", f"{base}/tgb/obj?tagging")
    assert b"<Tag>" not in body


def test_s3_delete_bucket_rules(s3_cluster):
    base = _base(s3_cluster)
    _req("PUT", f"{base}/db1")
    _req("PUT", f"{base}/db1/f", b"v")
    code, _, _ = _req("DELETE", f"{base}/db1")
    assert code == 409  # not empty
    _req("DELETE", f"{base}/db1/f")
    code, _, _ = _req("DELETE", f"{base}/db1")
    assert code == 204
    code, _, _ = _req("HEAD", f"{base}/db1")
    assert code == 404


# -- authenticated gateway ---------------------------------------------------


def _sign_v4(method, host, port, path, query, access_key, secret,
             body=b"", region="us-east-1"):
    amz_date = time.strftime("%Y%m%dT%H%M%SZ", time.gmtime())
    date = amz_date[:8]
    payload_hash = hashlib.sha256(body).hexdigest()
    headers = {
        "host": f"{host}:{port}",
        "x-amz-content-sha256": payload_hash,
        "x-amz-date": amz_date,
    }
    signed = sorted(headers)
    canon = s3auth.canonical_request(
        method, path, query, headers, signed, payload_hash
    )
    sig = s3auth.sign_v4(secret, date, region, "s3", amz_date, canon)
    headers["Authorization"] = (
        f"AWS4-HMAC-SHA256 Credential={access_key}/{date}/{region}/s3/"
        f"aws4_request, SignedHeaders={';'.join(signed)}, Signature={sig}"
    )
    return headers


@pytest.fixture(scope="module")
def s3_auth_gateway(s3_cluster, tmp_path_factory):
    from seaweedfs_tpu.s3api.server import S3ApiServer

    conf = tmp_path_factory.mktemp("s3conf") / "s3.json"
    conf.write_text(json.dumps({
        "identities": [
            {"name": "admin",
             "credentials": [{"accessKey": "AKADMIN", "secretKey": "SKADMIN"}],
             "actions": ["Admin"]},
            {"name": "reader",
             "credentials": [{"accessKey": "AKREAD", "secretKey": "SKREAD"}],
             "actions": ["Read", "List"]},
        ]
    }))
    filer = s3_cluster[2]
    gw = S3ApiServer(filer=f"127.0.0.1:{filer.port}", port=_free_port(),
                     config_path=str(conf))
    gw.start()
    yield gw
    gw.stop()


def test_s3_auth_required(s3_auth_gateway):
    port = s3_auth_gateway.port
    code, _, body = _req("PUT", f"http://127.0.0.1:{port}/authb")
    assert code == 403 and b"AccessDenied" in body


def test_s3_auth_signed_requests(s3_auth_gateway):
    port = s3_auth_gateway.port
    # admin creates a bucket + writes
    h = _sign_v4("PUT", "127.0.0.1", port, "/authb", "", "AKADMIN", "SKADMIN")
    code, _, body = _req("PUT", f"http://127.0.0.1:{port}/authb", None, h)
    assert code == 200, body
    payload = b"signed payload"
    h = _sign_v4("PUT", "127.0.0.1", port, "/authb/k.txt", "",
                 "AKADMIN", "SKADMIN", payload)
    code, _, body = _req("PUT", f"http://127.0.0.1:{port}/authb/k.txt",
                         payload, h)
    assert code == 200, body
    # reader reads but cannot write
    h = _sign_v4("GET", "127.0.0.1", port, "/authb/k.txt", "",
                 "AKREAD", "SKREAD")
    code, _, body = _req("GET", f"http://127.0.0.1:{port}/authb/k.txt",
                         None, h)
    assert code == 200 and body == payload
    h = _sign_v4("PUT", "127.0.0.1", port, "/authb/w.txt", "",
                 "AKREAD", "SKREAD", b"nope")
    code, _, body = _req("PUT", f"http://127.0.0.1:{port}/authb/w.txt",
                         b"nope", h)
    assert code == 403
    # bad secret -> signature mismatch
    h = _sign_v4("GET", "127.0.0.1", port, "/authb/k.txt", "",
                 "AKREAD", "WRONG")
    code, _, body = _req("GET", f"http://127.0.0.1:{port}/authb/k.txt",
                         None, h)
    assert code == 403 and b"SignatureDoesNotMatch" in body


def test_s3_auth_tampered_body_rejected(s3_auth_gateway):
    """A captured signed PUT replayed with a different body must be
    rejected (the signed x-amz-content-sha256 is verified against the
    actual payload) and must NOT leave the forged object behind."""
    port = s3_auth_gateway.port
    h = _sign_v4("PUT", "127.0.0.1", port, "/authb/t.txt", "",
                 "AKADMIN", "SKADMIN", b"the signed body")
    code, _, body = _req("PUT", f"http://127.0.0.1:{port}/authb/t.txt",
                         b"EVIL REPLACEMENT", h)
    assert code == 400 and b"XAmzContentSHA256Mismatch" in body
    h = _sign_v4("GET", "127.0.0.1", port, "/authb/t.txt", "",
                 "AKADMIN", "SKADMIN")
    code, _, _ = _req("GET", f"http://127.0.0.1:{port}/authb/t.txt", None, h)
    assert code == 404


def _presign_v4(method, host, port, path, access_key, secret,
                expires=300, region="us-east-1", extra_query=""):
    """Build a SigV4 presigned URL per the AWS query-parameter spec:
    UNSIGNED-PAYLOAD, host-only signed headers, X-Amz-* in the query."""
    amz_date = time.strftime("%Y%m%dT%H%M%SZ", time.gmtime())
    date = amz_date[:8]
    cred = f"{access_key}/{date}/{region}/s3/aws4_request"
    q = {
        "X-Amz-Algorithm": "AWS4-HMAC-SHA256",
        "X-Amz-Credential": cred,
        "X-Amz-Date": amz_date,
        "X-Amz-Expires": str(expires),
        "X-Amz-SignedHeaders": "host",
    }
    query = "&".join(
        f"{urllib.parse.quote(k, safe='')}={urllib.parse.quote(v, safe='')}"
        for k, v in q.items())
    if extra_query:
        query = f"{extra_query}&{query}"
    headers = {"host": f"{host}:{port}"}
    canon = s3auth.canonical_request(
        method, path, query, headers, ["host"], "UNSIGNED-PAYLOAD")
    sig = s3auth.sign_v4(secret, date, region, "s3", amz_date, canon)
    return f"http://{host}:{port}{path}?{query}&X-Amz-Signature={sig}"


def test_s3_presigned_get_and_expiry(s3_auth_gateway):
    """Presigned V4 GET serves without headers; a tampered signature and
    an expired window are rejected (auth_signature_v4.go presigned path)."""
    port = s3_auth_gateway.port
    payload = b"presigned content"
    h = _sign_v4("PUT", "127.0.0.1", port, "/authb/pre.txt", "",
                 "AKADMIN", "SKADMIN", payload)
    code, _, _ = _req("PUT", f"http://127.0.0.1:{port}/authb/pre.txt",
                      payload, h)
    assert code == 200

    url = _presign_v4("GET", "127.0.0.1", port, "/authb/pre.txt",
                      "AKREAD", "SKREAD")
    code, _, body = _req("GET", url)
    assert code == 200 and body == payload

    # tampered signature
    bad = url[:-4] + ("0000" if not url.endswith("0000") else "1111")
    code, _, body = _req("GET", bad)
    assert code == 403 and b"SignatureDoesNotMatch" in body

    # expired window: X-Amz-Date in the past with tiny X-Amz-Expires
    past = time.strftime("%Y%m%dT%H%M%SZ", time.gmtime(time.time() - 3600))
    date = past[:8]
    cred = f"AKREAD/{date}/us-east-1/s3/aws4_request"
    q = {
        "X-Amz-Algorithm": "AWS4-HMAC-SHA256",
        "X-Amz-Credential": cred,
        "X-Amz-Date": past,
        "X-Amz-Expires": "1",
        "X-Amz-SignedHeaders": "host",
    }
    query = "&".join(
        f"{urllib.parse.quote(k, safe='')}={urllib.parse.quote(v, safe='')}"
        for k, v in q.items())
    headers = {"host": f"127.0.0.1:{port}"}
    canon = s3auth.canonical_request(
        "GET", "/authb/pre.txt", query, headers, ["host"],
        "UNSIGNED-PAYLOAD")
    sig = s3auth.sign_v4("SKREAD", date, "us-east-1", "s3", past, canon)
    code, _, body = _req(
        "GET",
        f"http://127.0.0.1:{port}/authb/pre.txt?{query}&X-Amz-Signature={sig}")
    assert code == 403 and b"expired" in body.lower()

    # presigned identity still respects action scoping: reader cannot PUT
    url = _presign_v4("PUT", "127.0.0.1", port, "/authb/pw.txt",
                      "AKREAD", "SKREAD")
    code, _, body = _req("PUT", url, b"denied")
    assert code == 403
