"""Field algebra and generator-matrix construction tests.

The matrix checks pin the klauspost/reedsolomon-compatible construction
(Vandermonde normalised to systematic form) that byte-identical parity
depends on (reference: weed/storage/erasure_coding/ec_encoder.go:198).
"""

import numpy as np
import pytest

from seaweedfs_tpu.ops import gf256


def test_field_axioms():
    rng = np.random.default_rng(0)
    for _ in range(200):
        a, b, c = (int(x) for x in rng.integers(0, 256, 3))
        assert gf256.gf_mul(a, b) == gf256.gf_mul(b, a)
        assert gf256.gf_mul(a, gf256.gf_mul(b, c)) == gf256.gf_mul(
            gf256.gf_mul(a, b), c
        )
        # distributive over XOR
        assert gf256.gf_mul(a, b ^ c) == gf256.gf_mul(a, b) ^ gf256.gf_mul(a, c)
    for a in range(1, 256):
        assert gf256.gf_mul(a, gf256.gf_inv(a)) == 1
        assert gf256.gf_div(gf256.gf_mul(a, 7), 7) == a


def test_known_products():
    # 2*0x80 wraps through the polynomial 0x11D -> 0x1D
    assert gf256.gf_mul(2, 0x80) == 0x1D
    assert gf256.gf_mul(3, 4) == 12
    assert gf256.gf_mul(7, 7) == 21
    assert gf256.gf_mul(23, 45) == 41  # klauspost galois test vector


def test_exp_table_is_standard():
    # First powers of the generator 2 with poly 0x11D
    assert list(gf256.EXP_TABLE[:10]) == [1, 2, 4, 8, 16, 32, 64, 128, 0x1D, 0x3A]
    assert gf256.gf_exp(2, 254) == gf256.gf_inv(2)
    assert gf256.gf_exp(0, 0) == 1
    assert gf256.gf_exp(0, 5) == 0


def test_matrix_inverse_roundtrip():
    rng = np.random.default_rng(1)
    for n in (1, 2, 5, 10):
        while True:
            m = rng.integers(0, 256, (n, n)).astype(np.uint8)
            try:
                inv = gf256.mat_inv(m)
                break
            except np.linalg.LinAlgError:
                continue
        assert np.array_equal(gf256.mat_mul(m, inv), gf256.mat_identity(n))


def test_rs_matrix_systematic():
    m = gf256.rs_matrix(10, 14)
    assert m.shape == (14, 10)
    assert np.array_equal(m[:10], gf256.mat_identity(10))
    # Any 10 rows must be invertible (MDS property of the construction)
    rng = np.random.default_rng(2)
    for _ in range(20):
        rows = sorted(rng.choice(14, 10, replace=False).tolist())
        gf256.mat_inv(m[np.array(rows)])  # raises if singular


def test_rs_matrix_known_values():
    # For RS(2,2): vandermonde(4,2) = [[1,0],[1,1],[1,2],[1,3]]; the top
    # square [[1,0],[1,1]] is its own inverse, so the parity rows come out as
    # [1,2]*inv = [3,2] and [1,3]*inv = [2,3].
    m = gf256.rs_matrix(2, 4)
    assert np.array_equal(
        m, np.array([[1, 0], [0, 1], [3, 2], [2, 3]], dtype=np.uint8)
    )


def test_decode_matrix():
    m = gf256.rs_matrix(10, 14)
    present = [0, 2, 3, 4, 5, 6, 7, 8, 9, 10, 12]  # shard 1 missing
    dec = gf256.decode_matrix_for(m, 10, present)
    # dec * rows(present[:10]) == I, so dec recovers data from those shards
    assert np.array_equal(
        gf256.mat_mul(dec, m[np.array(present[:10])]), gf256.mat_identity(10)
    )
    with pytest.raises(ValueError):
        gf256.decode_matrix_for(m, 10, list(range(9)))


def test_bit_matrix_linearization():
    m = gf256.rs_parity_matrix(10, 4)
    a = gf256.bit_matrix(m)
    assert a.shape == (32, 80)
    rng = np.random.default_rng(3)
    data = rng.integers(0, 256, (10, 64)).astype(np.uint8)
    # reference: table-lookup GF matmul
    t = gf256.mul_table()
    expect = np.zeros((4, 64), dtype=np.uint8)
    for i in range(4):
        acc = np.zeros(64, dtype=np.uint8)
        for j in range(10):
            acc ^= t[m[i, j]][data[j]]
        expect[i] = acc
    # bit-plane integer matmul + parity
    bits = ((data[:, None, :] >> np.arange(8)[None, :, None]) & 1).reshape(80, 64)
    pbits = (a.astype(np.int32) @ bits.astype(np.int32)) & 1
    got = np.zeros((4, 64), dtype=np.uint8)
    for k in range(8):
        got |= (pbits.reshape(4, 8, 64)[:, k, :] << k).astype(np.uint8)
    assert np.array_equal(got, expect)
