"""Unit coverage for the PR-5 observability plane pieces: the sampling
profiler, /debug/traces query validation, trace stitching with clock
skew, metered executors, and the registry's heartbeat snapshot."""

from __future__ import annotations

import threading
import time

import pytest

# -- sampling profiler -------------------------------------------------------


def test_profiler_collects_thread_stacks():
    from seaweedfs_tpu.util import profiler

    stop = threading.Event()

    def busy_loop_marker():
        while not stop.is_set():
            sum(range(500))

    t = threading.Thread(target=busy_loop_marker, daemon=True)
    t.start()
    try:
        counts = profiler.sample_stacks(duration_s=0.4, hz=150)
    finally:
        stop.set()
        t.join()
    assert counts, "no stacks sampled"
    joined = "\n".join(counts)
    assert "busy_loop_marker" in joined
    text = profiler.collapsed(counts)
    # collapsed format: `frames count` lines, hottest first
    first = text.splitlines()[0]
    stack, _, n = first.rpartition(" ")
    assert int(n) >= 1 and ";" in stack or stack


def test_profiler_validates_and_serializes():
    from seaweedfs_tpu.util import profiler

    for bad in ((0, 99), (-1, 99), (999, 99), (1, 0), (1, 100000)):
        with pytest.raises(ValueError):
            profiler.sample_stacks(*bad)
    # exclusive: a second run while one is in flight is refused
    results = []

    def run():
        try:
            results.append(profiler.sample_stacks(0.5, 50))
        except profiler.ProfilerBusy as e:
            results.append(e)

    t1 = threading.Thread(target=run)
    t1.start()
    time.sleep(0.1)
    with pytest.raises(profiler.ProfilerBusy):
        profiler.sample_stacks(0.2, 50)
    t1.join()
    assert len(results) == 1 and isinstance(results[0], dict)


# -- /debug/traces query validation ------------------------------------------


def test_parse_trace_query():
    from seaweedfs_tpu.telemetry import parse_trace_query

    assert parse_trace_query({}) == (None, 50)
    tid = "ab" * 16
    assert parse_trace_query({"trace": [tid]}) == (tid, 50)
    assert parse_trace_query({"trace": [tid.upper()]}) == (tid, 50)
    assert parse_trace_query({"limit": ["7"]}) == (None, 7)
    for bad in ({"trace": ["xyz"]}, {"trace": ["ab" * 15]},
                {"limit": ["0"]}, {"limit": ["1001"]},
                {"limit": ["seven"]}, {"trace": ["g" * 32]}):
        with pytest.raises(ValueError):
            parse_trace_query(bad)


def test_tracer_trace_filter_and_now():
    import json

    from seaweedfs_tpu.telemetry.trace import Tracer, Span

    tr = Tracer(max_spans=16)
    for i, tid in enumerate(("aa" * 16, "bb" * 16, "aa" * 16)):
        tr.record(Span(trace_id=tid, span_id=f"{i:016x}", parent_id="",
                       name=f"s{i}", start=time.time(), duration=0.001))
    doc = json.loads(tr.traces_json(50, trace_id="aa" * 16))
    assert isinstance(doc["now"], float)
    assert len(doc["traces"]) == 1
    assert {s["name"] for s in doc["traces"][0]["spans"]} == {"s0", "s2"}
    assert len(json.loads(tr.traces_json(50))["traces"]) == 2


# -- trace stitching ---------------------------------------------------------


def test_stitch_trace_merges_skews_and_marks_orphans():
    from seaweedfs_tpu.telemetry.stitch import estimate_skew, stitch_trace

    t0 = 1_722_729_600.0
    tid = "cd" * 16
    span = lambda sid, parent, start, dur_ms, name: {  # noqa: E731
        "traceId": tid, "spanId": sid, "parentId": parent, "name": name,
        "start": start, "durationMs": dur_ms, "attrs": {}, "status": "ok",
    }
    filer = {
        "instance": "f:8888", "type": "filer", "skew_s": 0.0, "rtt_s": 0.001,
        "spans": [span("f" * 16, "", t0, 30.0, "filer.post")],
    }
    # the volume node's clock runs 10s fast; unadjusted, its span would
    # sort before the filer's
    volume = {
        "instance": "v:8080", "type": "volume", "skew_s": 10.0,
        "rtt_s": 0.002,
        "spans": [span("e" * 16, "f" * 16, t0 + 10.005, 5.0,
                       "volumeServer.post"),
                  span("d" * 16, "0" * 16, t0 + 10.010, 1.0, "orphaned")],
    }
    out = stitch_trace(tid, [filer, volume])
    assert out["traceId"] == tid
    assert [s["name"] for s in out["spans"]] == [
        "filer.post", "volumeServer.post", "orphaned"]
    by_name = {s["name"]: s for s in out["spans"]}
    assert by_name["volumeServer.post"]["instance"] == "v:8080"
    assert abs(by_name["volumeServer.post"]["startAdjusted"]
               - (t0 + 0.005)) < 1e-6
    assert not by_name["volumeServer.post"]["orphan"]  # parent on filer
    assert by_name["orphaned"]["orphan"]
    assert out["nodes"]["v:8080"]["clockSkewMs"] == 10000.0
    assert out["nodes"]["f:8888"]["spanCount"] == 1
    assert out["durationMs"] > 0
    # NTP-style estimate: node replied 0.2s after send with rtt 0.1
    assert abs(estimate_skew(100.2, 100.0, 0.1) - 0.15) < 1e-9


# -- metered executors -------------------------------------------------------


def test_metered_executor_gauges_track_saturation():
    from seaweedfs_tpu.stats.metrics import (
        EXECUTOR_ACTIVE,
        EXECUTOR_MAX,
        EXECUTOR_QUEUE_DEPTH,
    )
    from seaweedfs_tpu.util.executors import MeteredThreadPoolExecutor

    name = "t_metered"
    pool = MeteredThreadPoolExecutor(max_workers=2, name=name)
    assert EXECUTOR_MAX.labels(name).value == 2
    gate = threading.Event()
    running = threading.Semaphore(0)

    def task():
        running.release()
        gate.wait(timeout=5)

    futs = [pool.submit(task) for _ in range(4)]
    assert running.acquire(timeout=5) and running.acquire(timeout=5)
    time.sleep(0.05)
    assert EXECUTOR_ACTIVE.labels(name).value == 2
    assert EXECUTOR_QUEUE_DEPTH.labels(name).value == 2
    gate.set()
    for f in futs:
        f.result(timeout=5)
    time.sleep(0.05)
    assert EXECUTOR_ACTIVE.labels(name).value == 0
    assert EXECUTOR_QUEUE_DEPTH.labels(name).value == 0
    pool.shutdown()
    with pytest.raises(RuntimeError):
        pool.submit(task)
    assert EXECUTOR_QUEUE_DEPTH.labels(name).value == 0  # unwound


def test_metered_executor_unwinds_queue_on_cancelled_map():
    """Executor.map cancels pending futures when the consumer raises
    mid-iteration; cancelled futures never run, so the queue gauge must
    unwind via the done-callback, not the (never-called) wrapper."""
    from seaweedfs_tpu.stats.metrics import EXECUTOR_QUEUE_DEPTH
    from seaweedfs_tpu.util.executors import MeteredThreadPoolExecutor

    name = "t_cancelled"
    pool = MeteredThreadPoolExecutor(max_workers=1, name=name)

    def work(i):
        if i == 0:
            time.sleep(0.05)
            raise RuntimeError("boom")
        return i

    with pytest.raises(RuntimeError):
        list(pool.map(work, range(10)))
    pool.shutdown(wait=True)
    time.sleep(0.05)  # done-callbacks fire on cancellation, allow a beat
    assert EXECUTOR_QUEUE_DEPTH.labels(name).value == 0


def test_profiler_disable_gate(monkeypatch):
    from seaweedfs_tpu.util import profiler

    monkeypatch.setenv(profiler.DISABLE_VAR, "1")
    assert not profiler.enabled()
    monkeypatch.delenv(profiler.DISABLE_VAR)
    assert profiler.enabled()


# -- shell cluster.status ----------------------------------------------------


def test_shell_cluster_status_renders():
    from helpers import free_port

    from seaweedfs_tpu.master.server import MasterServer
    from seaweedfs_tpu.shell.commands import CommandEnv, run_command

    m = MasterServer(ip="127.0.0.1", port=free_port())
    m.start()
    try:
        env = CommandEnv(f"127.0.0.1:{m.grpc_port}")
        out = run_command(env, "cluster.status")
        assert f"master 127.0.0.1:{m.port}" in out
        assert "volume servers (0):" in out
        assert "/cluster/metrics" in out
        as_json = run_command(env, "cluster.status -json")
        import json

        assert json.loads(as_json)["IsLeader"] is True
    finally:
        m.stop()


# -- registry snapshot -------------------------------------------------------


def test_snapshot_samples_counters_and_gauges_only():
    from seaweedfs_tpu.stats.metrics import Registry

    r = Registry()
    r.counter("t_c_total", "c", labels=("op",)).labels("x").inc(3)
    r.gauge("t_g", "g").set(1.5)
    r.histogram("t_h_seconds", "h").observe(0.2)
    samples = dict(r.snapshot_samples())
    assert samples['t_c_total{op="x"}'] == 3.0
    assert samples["t_g"] == 1.5
    assert not any(k.startswith("t_h_seconds") for k in samples)
    # bounded
    big = Registry()
    c = big.counter("t_many_total", "c", labels=("i",))
    for i in range(600):
        c.labels(str(i)).inc()
    assert len(big.snapshot_samples(max_samples=512)) == 512


def test_snapshot_samples_deny_list_cannot_evict_slo_families():
    """High-cardinality families (hot-key gauges, per-peer connpool,
    per-dir disk) sort last under the 512-sample heartbeat cap so they
    can never crowd out the families the SLO engine reads from stale
    snapshots."""
    from seaweedfs_tpu.stats.metrics import Registry

    r = Registry()
    # a family the alerting plane depends on (plain tier)
    reads = r.counter("seaweedfs_read_requests_total", "r", labels=("op",))
    for i in range(8):
        reads.labels(f"op{i}").inc()
    # tier-0: must survive even the tightest cap
    r.gauge("seaweedfs_geo_lag_seconds", "g").set(2.0)
    # deny-listed flood: 600 hot-key children and 600 per-peer gauges
    hot = r.gauge("seaweedfs_hotkey_top_count", "h", labels=("dim", "key"))
    pool = r.gauge("seaweedfs_connpool_in_use", "p", labels=("peer",))
    for i in range(600):
        hot.labels("needle", f"k{i}").set(float(i))
        pool.labels(f"10.0.0.{i}").set(1.0)

    samples = dict(r.snapshot_samples(max_samples=64))
    assert len(samples) == 64
    # every non-deny-listed sample made it in...
    assert sum(1 for k in samples
               if k.startswith("seaweedfs_read_requests_total")) == 8
    assert "seaweedfs_geo_lag_seconds" in samples
    # ...and the flood only got the leftover slots
    flood = [k for k in samples
             if k.startswith(("seaweedfs_hotkey_", "seaweedfs_connpool_"))]
    assert len(flood) == 64 - 8 - 1

    # with no cap pressure the deny-listed families still appear
    full = dict(r.snapshot_samples(max_samples=1 << 20))
    assert sum(1 for k in full if k.startswith("seaweedfs_hotkey_")) == 600
