"""Geo replication plane units (ISSUE 12).

Covers the durable metadata event log (fsynced segments, monotonic
gap-detectable sequence numbers, torn-tail truncation, bounded
retention), the hybrid logical clock + LWW stamps, the GeoApplier's
conflict resolution (reject-older, tombstone fencing, watermark
exactly-once), the GeoReplicator's ship/checkpoint/resync loop against a
stub remote, the classified sink apply path, listener eviction, and the
fleet client's fail-over-to-remote mode.  The live two-cluster
SIGKILL/rejoin proof is tests/test_geo_cluster.py (chaos).
"""

from __future__ import annotations

import json
import os
import threading
import time
import urllib.parse
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from seaweedfs_tpu.filer.filer import Filer, split_path
from seaweedfs_tpu.filer.filerstore import make_store
from seaweedfs_tpu.filer.meta_log import (
    GEO_HLC_KEY,
    MetaLogBuffer,
    MetaLogGap,
    decode_hlc,
    encode_hlc,
    entry_hlc,
    tombstone_key,
)
from seaweedfs_tpu.pb import filer_pb2
from seaweedfs_tpu.stats.metrics import REGISTRY


def _entry(name: str, content: bytes = b"") -> filer_pb2.Entry:
    e = filer_pb2.Entry(name=name, content=content)
    e.attributes.mtime = int(time.time())
    e.attributes.file_mode = 0o644
    return e


def _counter(family: str, *labels) -> float:
    m = REGISTRY.family(family)
    if m is None:
        return 0.0
    child = m._children.get(tuple(str(v) for v in labels))
    return float(child.value) if child else 0.0


# ---------------------------------------------------------------------------
# durable meta log
# ---------------------------------------------------------------------------


def test_durable_log_append_recover(tmp_path):
    d = str(tmp_path / "log")
    log = MetaLogBuffer(capacity=4, dir=d)
    for i in range(10):
        log.append("/d", None, _entry(f"f{i}"))
    assert log.last_seq() == 10
    log.close()
    log2 = MetaLogBuffer(capacity=4, dir=d)
    assert log2.last_seq() == 10
    # appends continue the sequence, never reuse it
    log2.append("/d", None, _entry("f10"))
    assert log2.last_seq() == 11


def test_tail_serves_evicted_history_from_disk(tmp_path):
    log = MetaLogBuffer(capacity=4, dir=str(tmp_path / "log"))
    for i in range(12):
        log.append("/d", None, _entry(f"f{i}"))
    stop = threading.Event()
    seqs, names = [], []
    for seq, ev in log.tail(0, stop_event=stop, poll_interval=0.02):
        seqs.append(seq)
        names.append(ev.event_notification.new_entry.name)
        if seq == 12:
            stop.set()
    # contiguous — the gap-free contract the geo replicator resumes on
    assert seqs == list(range(1, 13))
    assert names[0] == "f0" and names[-1] == "f11"


def test_tail_resumes_mid_stream(tmp_path):
    log = MetaLogBuffer(capacity=64, dir=str(tmp_path / "log"))
    for i in range(8):
        log.append("/d", None, _entry(f"f{i}"))
    stop = threading.Event()
    got = []
    for seq, _ev in log.tail(5, stop_event=stop, poll_interval=0.02):
        got.append(seq)
        if seq == 8:
            stop.set()
    assert got == [6, 7, 8]


def test_torn_tail_truncated(tmp_path):
    d = str(tmp_path / "log")
    log = MetaLogBuffer(dir=d)
    for i in range(5):
        log.append("/d", None, _entry(f"f{i}"))
    log.close()
    seg = sorted(p for p in os.listdir(d) if p.startswith("seg-"))[-1]
    with open(os.path.join(d, seg), "ab") as f:
        f.write(b"\x13\x37torn-half-record")
    log2 = MetaLogBuffer(dir=d)
    assert log2.last_seq() == 5  # garbage dropped, good prefix kept
    log2.append("/d", None, _entry("f5"))
    assert log2.last_seq() == 6


def test_retention_drops_segments_and_gap_is_loud(tmp_path):
    log = MetaLogBuffer(capacity=4, dir=str(tmp_path / "log"),
                        segment_bytes=256, retain_bytes=512)
    for i in range(60):
        log.append("/d", None, _entry(f"g{i}"))
    assert log.first_retained_seq > 1
    with pytest.raises(MetaLogGap):
        next(iter(log.tail(0, stop_event=threading.Event())))
    # resuming at/after the retention floor works
    stop = threading.Event()
    first = next(iter(log.tail(log.first_retained_seq - 1,
                               stop_event=stop)))
    assert first[0] == log.first_retained_seq


def test_memory_log_eviction_raises_gap():
    log = MetaLogBuffer(capacity=4)
    for i in range(10):
        log.append("/d", None, _entry(f"f{i}"))
    with pytest.raises(MetaLogGap):
        next(iter(log.tail(0, stop_event=threading.Event())))


def test_subscribe_serves_persisted_history(tmp_path):
    d = str(tmp_path / "log")
    log = MetaLogBuffer(capacity=4, dir=d)
    for i in range(10):
        log.append("/d", None, _entry(f"f{i}"))
    stop = threading.Event()
    names = []
    for ev in log.subscribe(0, stop_event=stop, poll_interval=0.02):
        names.append(ev.event_notification.new_entry.name)
        if len(names) == 10:
            stop.set()
    assert names == [f"f{i}" for i in range(10)]


def test_hlc_next_ts_monotonic_and_observe():
    log = MetaLogBuffer()
    a = log.next_ts()
    b = log.next_ts()
    assert b > a
    future = time.time_ns() + 60_000_000_000
    log.observe(future)  # remote event from a fast clock
    assert log.next_ts() > future  # local writes stamp past it


def test_hlc_stamp_helpers():
    raw = encode_hlc(123456789, 7)
    assert decode_hlc(raw) == (123456789, 7)
    assert decode_hlc(None) is None
    assert decode_hlc(b"short") is None
    e = _entry("x")
    e.extended[GEO_HLC_KEY] = raw
    assert entry_hlc(e) == (123456789, 7)
    e2 = _entry("y")  # falls back to mtime seconds, cluster 0
    ts, cid = entry_hlc(e2)
    assert cid == 0 and ts == e2.attributes.mtime * 1_000_000_000


# ---------------------------------------------------------------------------
# listener eviction (satellite)
# ---------------------------------------------------------------------------


def test_listener_evicted_after_consecutive_failures():
    log = MetaLogBuffer()
    calls = []

    def bad(_resp):
        calls.append(1)
        raise RuntimeError("sink is dead")

    log.add_listener(bad)
    before_err = _counter("seaweedfs_meta_listener_errors_total", "error")
    before_evict = _counter("seaweedfs_meta_listener_errors_total",
                            "evicted")
    from seaweedfs_tpu.filer.meta_log import LISTENER_MAX_FAILURES

    for i in range(LISTENER_MAX_FAILURES + 5):
        log.append("/d", None, _entry(f"f{i}"))
    # invoked exactly MAX times, then unsubscribed — not forever
    assert len(calls) == LISTENER_MAX_FAILURES
    assert log.listener_count() == 0
    assert _counter("seaweedfs_meta_listener_errors_total",
                    "error") - before_err == LISTENER_MAX_FAILURES
    assert _counter("seaweedfs_meta_listener_errors_total",
                    "evicted") - before_evict == 1


def test_listener_failure_count_resets_on_success():
    log = MetaLogBuffer()
    state = {"fail": True, "calls": 0}

    def flaky(_resp):
        state["calls"] += 1
        if state["fail"]:
            raise RuntimeError("transient")

    log.add_listener(flaky)
    from seaweedfs_tpu.filer.meta_log import LISTENER_MAX_FAILURES

    for i in range(LISTENER_MAX_FAILURES - 1):
        log.append("/d", None, _entry(f"a{i}"))
    state["fail"] = False  # one success wipes the strike count
    log.append("/d", None, _entry("ok"))
    state["fail"] = True
    for i in range(LISTENER_MAX_FAILURES - 1):
        log.append("/d", None, _entry(f"b{i}"))
    assert log.listener_count() == 1  # never hit MAX in a row


# ---------------------------------------------------------------------------
# filer HLC stamping + tombstones
# ---------------------------------------------------------------------------


def _geo_filer(cluster_id: int = 1) -> Filer:
    f = Filer(make_store("memory"))
    f.cluster_id = cluster_id
    f.geo_stamp = True
    return f


def test_filer_stamps_mutations_and_tombstones_deletes():
    f = _geo_filer(cluster_id=3)
    e = _entry("a.txt", b"hello")
    f.create_entry("/buckets/b", e)
    stored = f.find_entry("/buckets/b/a.txt")
    stamp = decode_hlc(bytes(stored.extended[GEO_HLC_KEY]))
    assert stamp is not None and stamp[1] == 3
    f.delete_entry("/buckets/b", "a.txt")
    tomb = decode_hlc(f.store.kv_get(tombstone_key("/buckets/b/a.txt")))
    assert tomb is not None and tomb[1] == 3
    assert tomb[0] > stamp[0]  # the delete happened after the create


def test_filer_preserves_relayed_origin_stamp():
    f = _geo_filer(cluster_id=3)
    e = _entry("a.txt", b"hello")
    e.extended[GEO_HLC_KEY] = encode_hlc(424242, 9)  # origin cluster 9
    # a RELAY carries replication signatures: the origin stamp sticks
    f.create_entry("/buckets/b", e, signatures=[9])
    stored = f.find_entry("/buckets/b/a.txt")
    assert decode_hlc(bytes(stored.extended[GEO_HLC_KEY])) == (424242, 9)
    # and the origin ts folded into the local clock
    assert f.meta_log.next_ts() > 424242


def test_filer_restamps_client_echoed_stamp():
    """A direct client mutation (no signatures) that echoes a stored
    stamp back — a read-modify-write UpdateEntry like chmod/touch — is
    a NEW write and must be re-stamped: honoring the echo would make
    the update compare "dup" against the version it overwrote and
    never replicate."""
    f = _geo_filer(cluster_id=3)
    e = _entry("a.txt", b"v1")
    f.create_entry("/buckets/b", e)
    stored = f.find_entry("/buckets/b/a.txt")
    old_stamp = decode_hlc(bytes(stored.extended[GEO_HLC_KEY]))
    # client round-trips the entry verbatim (stale stamp included)
    stored.attributes.file_mode = 0o600
    f.update_entry("/buckets/b", stored)
    restamped = decode_hlc(bytes(
        f.find_entry("/buckets/b/a.txt").extended[GEO_HLC_KEY]))
    assert restamped[1] == 3  # stamped by THIS cluster
    assert restamped[0] > old_stamp[0]  # strictly newer: it replicates


# ---------------------------------------------------------------------------
# GeoApplier: LWW conflict resolution + exactly-once watermarks
# ---------------------------------------------------------------------------


class _StubFs:
    """The slice of FilerServer the geo plane needs, volume-plane-free:
    content-carrying entries only."""

    def __init__(self, cluster_id: int = 2, signature: int = 777):
        self.filer = Filer(make_store("memory"))
        self.filer.cluster_id = cluster_id
        self.filer.geo_stamp = True
        self.signature = signature

    def write_file(self, path, data, mime="", signatures=None,
                   extended=None, **_kw):
        d, n = split_path(path)
        e = _entry(n, data)
        e.attributes.mime = mime or ""
        for k, v in (extended or {}).items():
            e.extended[k] = v
        self.filer.create_entry(d, e, signatures=signatures)
        return e

    def read_entry_range(self, entry, offset, size):
        return bytes(entry.content)[offset:offset + size]


def _applier(fs=None):
    from seaweedfs_tpu.replication.geo import GeoApplier

    fs = fs or _StubFs()
    return GeoApplier(fs), fs


def _read(fs, path) -> bytes | None:
    e = fs.filer.find_entry(path)
    return bytes(e.content) if e is not None and e.name else None


def test_applier_lww_applies_newer_rejects_older():
    ap, fs = _applier()
    base = fs.filer.meta_log.next_ts()
    out = ap.apply(origin=1, source=11, seq=1, hlc=base + 10, op="put",
                   path="/buckets/b/k", data=b"newer", mime="")
    assert out["result"] == "ok"
    assert _read(fs, "/buckets/b/k") == b"newer"
    before = _counter("seaweedfs_geo_conflicts_total", "1", "local")
    out = ap.apply(origin=1, source=11, seq=2, hlc=base + 5, op="put",
                   path="/buckets/b/k", data=b"older-concurrent")
    assert out["result"] == "conflict"
    assert _read(fs, "/buckets/b/k") == b"newer"  # LWW held
    assert _counter("seaweedfs_geo_conflicts_total",
                    "1", "local") == before + 1


def test_applier_local_write_beats_older_remote():
    ap, fs = _applier()
    fs.write_file("/buckets/b/k", b"local-now")  # stamped with local HLC
    local_stamp = entry_hlc(fs.filer.find_entry("/buckets/b/k"))
    out = ap.apply(origin=1, source=11, seq=1, hlc=local_stamp[0] - 10,
                   op="put", path="/buckets/b/k", data=b"remote-older")
    assert out["result"] == "conflict"
    assert _read(fs, "/buckets/b/k") == b"local-now"


def test_applier_tombstone_blocks_resurrection():
    ap, fs = _applier()
    fs.write_file("/buckets/b/dead", b"v1")
    d, n = split_path("/buckets/b/dead")
    fs.filer.delete_entry(d, n)  # local delete -> tombstone
    tomb = decode_hlc(
        fs.filer.store.kv_get(tombstone_key("/buckets/b/dead")))
    out = ap.apply(origin=1, source=11, seq=1, hlc=tomb[0] - 100,
                   op="put", path="/buckets/b/dead", data=b"zombie")
    assert out["result"] == "conflict"
    assert _read(fs, "/buckets/b/dead") is None  # stayed dead
    # but a STRICTLY NEWER remote write resurrects legitimately
    out = ap.apply(origin=1, source=11, seq=2, hlc=tomb[0] + 100,
                   op="put", path="/buckets/b/dead", data=b"reborn")
    assert out["result"] == "ok"
    assert _read(fs, "/buckets/b/dead") == b"reborn"


def test_applier_delete_lww_and_tombstone_stamp():
    ap, fs = _applier()
    ts = fs.filer.meta_log.next_ts()
    ap.apply(origin=1, source=11, seq=1, hlc=ts + 10, op="put",
             path="/buckets/b/x", data=b"v1")
    # older remote delete loses to the newer create
    out = ap.apply(origin=1, source=11, seq=2, hlc=ts + 5, op="delete",
                   path="/buckets/b/x")
    assert out["result"] == "conflict"
    assert _read(fs, "/buckets/b/x") == b"v1"
    # newer delete wins and fences with the ORIGIN stamp
    out = ap.apply(origin=1, source=11, seq=3, hlc=ts + 20, op="delete",
                   path="/buckets/b/x")
    assert out["result"] == "ok"
    assert _read(fs, "/buckets/b/x") is None
    assert decode_hlc(fs.filer.store.kv_get(
        tombstone_key("/buckets/b/x"))) == (ts + 20, 1)


def test_applier_recursive_delete_keeps_newer_children():
    """A recursive directory delete is LWW per CHILD, not per root: a
    child stamped newer than the delete is a concurrent write the
    delete must lose to — on the origin it beats the ancestor tombstone
    and gets re-created, so destroying it here would diverge the
    clusters forever."""
    ap, fs = _applier()
    ts = fs.filer.meta_log.next_ts()
    ap.apply(origin=1, source=11, seq=1, hlc=ts + 10, op="put",
             path="/buckets/b/d/old", data=b"old")
    ap.apply(origin=1, source=11, seq=2, hlc=ts + 30, op="put",
             path="/buckets/b/d/new", data=b"new")
    before = _counter("seaweedfs_geo_conflicts_total", "1", "local")
    out = ap.apply(origin=1, source=11, seq=3, hlc=ts + 20, op="delete",
                   path="/buckets/b/d")
    assert out["result"] == "conflict"
    assert _read(fs, "/buckets/b/d/old") is None    # older: deleted
    assert _read(fs, "/buckets/b/d/new") == b"new"  # newer: survives
    assert _counter("seaweedfs_geo_conflicts_total",
                    "1", "local") == before + 1
    # the fence still blocks older resurrections under /d
    out = ap.apply(origin=1, source=11, seq=4, hlc=ts + 5, op="put",
                   path="/buckets/b/d/zombie", data=b"z")
    assert out["result"] == "conflict"
    assert decode_hlc(fs.filer.store.kv_get(
        tombstone_key("/buckets/b/d"))) == (ts + 20, 1)
    # an all-older subtree still deletes wholesale
    out = ap.apply(origin=1, source=11, seq=5, hlc=ts + 40, op="delete",
                   path="/buckets/b/d")
    assert out["result"] == "ok"
    assert _read(fs, "/buckets/b/d/new") is None


def test_applied_delete_tombstone_lands_before_event_notify():
    """A tailing replicator (woken by the meta-log notify inside the
    applied delete's append) must already see the ORIGIN's tombstone
    stamp: writing it after delete_entry logged the event leaves a
    window where the relay ships a fresh inflated local stamp around a
    3+-cluster mesh."""
    ap, fs = _applier()
    ts = fs.filer.meta_log.next_ts()
    ap.apply(origin=1, source=11, seq=1, hlc=ts + 10, op="put",
             path="/buckets/b/r", data=b"v1")
    seen = []

    def on_event(resp):
        n = resp.event_notification
        if n.old_entry.name and not n.new_entry.name:
            seen.append(decode_hlc(fs.filer.store.kv_get(
                tombstone_key("/buckets/b/r"))))

    fs.filer.meta_log.add_listener(on_event)
    out = ap.apply(origin=1, source=11, seq=2, hlc=ts + 20, op="delete",
                   path="/buckets/b/r")
    assert out["result"] == "ok"
    assert seen == [(ts + 20, 1)]  # origin stamp visible AT notify time


def test_applier_watermark_exactly_once_and_persisted():
    ap, fs = _applier()
    ts = fs.filer.meta_log.next_ts()
    assert ap.apply(origin=1, source=11, seq=5, hlc=ts + 1, op="put",
                    path="/buckets/b/w", data=b"v1")["result"] == "ok"
    # re-shipped after a sender crash: dropped by the watermark
    assert ap.apply(origin=1, source=11, seq=5, hlc=ts + 1, op="put",
                    path="/buckets/b/w",
                    data=b"v1")["result"] == "dup"
    # a DIFFERENT source link is tracked independently
    assert ap.apply(origin=1, source=22, seq=5, hlc=ts + 1, op="put",
                    path="/buckets/b/w2", data=b"v2")["result"] == "ok"
    ap.flush()
    ap2, _ = _applier(fs)  # restart: watermark read back from store KV
    assert ap2.watermark(11) == (5, "")
    assert ap2.apply(origin=1, source=11, seq=4, hlc=ts + 9, op="put",
                     path="/buckets/b/w3",
                     data=b"late")["result"] == "dup"


def test_applier_seq0_resync_events_rely_on_lww_only():
    ap, fs = _applier()
    ts = fs.filer.meta_log.next_ts()
    for _ in range(2):  # idempotent, no watermark involvement
        out = ap.apply(origin=1, source=11, seq=0, hlc=ts + 1, op="put",
                       path="/buckets/b/r", data=b"resync")
    assert out["result"] in ("ok", "dup")
    assert _read(fs, "/buckets/b/r") == b"resync"
    assert ap.watermark(11) == (0, "")


# ---------------------------------------------------------------------------
# GeoReplicator against a stub remote
# ---------------------------------------------------------------------------


class _GeoStub(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    applies: list = []
    cluster_id = 9
    fail_next = 0

    def log_message(self, fmt, *args):
        pass

    def _json(self, code, obj):
        body = json.dumps(obj).encode()
        self.send_response(code)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):
        if self.path.startswith("/.geo/status"):
            return self._json(200, {"clusterId": self.cluster_id,
                                    "signature": 999})
        self._json(404, {})

    def do_POST(self):
        q = urllib.parse.parse_qs(urllib.parse.urlparse(self.path).query)
        body = self.rfile.read(
            int(self.headers.get("Content-Length") or 0))
        if type(self).fail_next > 0:
            type(self).fail_next -= 1
            return self._json(503, {"error": "injected"})
        if type(self).quota_next > 0:
            type(self).quota_next -= 1
            return self._json(403, {"error": "quota exceeded"})
        if type(self).disabled_next > 0:
            type(self).disabled_next -= 1
            return self._json(404, {"error": "geo replication not "
                                             "enabled"})
        if type(self).skew_next > 0:
            type(self).skew_next -= 1
            body = json.dumps({"error": "hlc ahead of clock"}).encode()
            self.send_response(400)
            self.send_header("Content-Length", str(len(body)))
            self.send_header("X-Seaweed-Reject", "skew")
            self.end_headers()
            self.wfile.write(body)
            return
        self.applies.append({
            "op": q.get("op", [""])[0],
            "path": q.get("path", [""])[0],
            "seq": int(q.get("seq", ["0"])[0]),
            "hlc": int(q.get("hlc", ["0"])[0]),
            "origin": int(q.get("origin", ["0"])[0]),
            "data": body,
        })
        self._json(200, {"result": "ok"})


def _start_stub():
    handler = type("BoundGeoStub", (_GeoStub,),
                   {"applies": [], "cluster_id": 9, "fail_next": 0,
                    "quota_next": 0, "disabled_next": 0,
                    "skew_next": 0})
    httpd = ThreadingHTTPServer(("127.0.0.1", 0), handler)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    return httpd, handler, f"127.0.0.1:{httpd.server_address[1]}"


def _wait(cond, timeout=10.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(0.02)
    return False


def test_replicator_ships_checkpoints_and_resumes_exactly_once(tmp_path):
    from seaweedfs_tpu.replication.geo import GeoReplicator

    httpd, handler, addr = _start_stub()
    try:
        fs = _StubFs(cluster_id=1)
        for i in range(5):
            fs.write_file(f"/buckets/b/f{i}", f"payload-{i}".encode())
        rep = GeoReplicator(fs, addr, journal_dir=str(tmp_path),
                            rate_mbps=0)
        rep.start()
        assert _wait(lambda: len(
            [a for a in handler.applies if a["op"] == "put"]) >= 5)
        rep.stop()
        puts = [a for a in handler.applies if a["op"] == "put"]
        assert [a["path"] for a in puts] == [
            f"/buckets/b/f{i}" for i in range(5)]
        assert puts[0]["data"] == b"payload-0"
        assert rep.checkpoint() == fs.filer.meta_log.last_seq()
        # restart on the same journal: nothing re-ships
        n = len(handler.applies)
        rep2 = GeoReplicator(fs, addr, journal_dir=str(tmp_path),
                             rate_mbps=0)
        rep2.start()
        fs.write_file("/buckets/b/after", b"only-this")
        assert _wait(lambda: any(a["path"] == "/buckets/b/after"
                                 for a in handler.applies))
        rep2.stop()
        new = handler.applies[n:]
        assert [a["path"] for a in new if a["op"] == "put"] == [
            "/buckets/b/after"]
    finally:
        httpd.shutdown()


def test_replicator_retries_transient_503(tmp_path):
    from seaweedfs_tpu.replication.geo import GeoReplicator

    httpd, handler, addr = _start_stub()
    try:
        fs = _StubFs(cluster_id=1)
        fs.write_file("/buckets/b/x", b"v")
        handler.fail_next = 2  # two 503s, then accept
        rep = GeoReplicator(fs, addr, journal_dir=str(tmp_path),
                            rate_mbps=0)
        rep.start()
        assert _wait(lambda: any(a["path"] == "/buckets/b/x"
                                 for a in handler.applies), timeout=15)
        rep.stop()
    finally:
        httpd.shutdown()


def test_replicator_stop_mid_ship_does_not_advance_checkpoint(tmp_path):
    """stop() while an event is un-acknowledged (remote rejecting with
    retryable 503s) must NOT advance the checkpoint: a restart
    re-delivers the event instead of silently losing it forever."""
    from seaweedfs_tpu.replication.geo import GeoReplicator

    httpd, handler, addr = _start_stub()
    try:
        fs = _StubFs(cluster_id=1)
        fs.write_file("/buckets/b/lost", b"v")
        handler.fail_next = 1 << 30  # remote never accepts
        rep = GeoReplicator(fs, addr, journal_dir=str(tmp_path),
                            rate_mbps=0)
        rep.start()
        # wait until the ship loop has burned at least two attempts
        assert _wait(lambda: handler.fail_next < (1 << 30) - 1,
                     timeout=15)
        rep.stop()
        assert rep.checkpoint() == 0  # event stays owed
        assert not handler.applies
    finally:
        httpd.shutdown()


def test_replicator_holds_link_on_remote_quota_403(tmp_path):
    """A remote 403 (tenant quota full) is transient over OPERATOR
    time, not poison: skipping it would advance the checkpoint past
    the event and silently break byte-identity with no resync trigger.
    The link must hold and deliver once the quota clears."""
    from seaweedfs_tpu.replication.geo import GeoReplicator

    httpd, handler, addr = _start_stub()
    try:
        fs = _StubFs(cluster_id=1)
        fs.write_file("/buckets/b/q", b"v")
        handler.quota_next = 2  # two 403s, then the quota is raised
        rep = GeoReplicator(fs, addr, journal_dir=str(tmp_path),
                            rate_mbps=0)
        rep.start()
        assert _wait(lambda: any(a["path"] == "/buckets/b/q"
                                 for a in handler.applies), timeout=15)
        rep.stop()
    finally:
        httpd.shutdown()


def test_replicator_holds_link_on_remote_geo_disabled_404(tmp_path):
    """A 404 from /.geo/apply means the remote runs with geo DISABLED
    (config rollback) — remote state, not a poison event: the link must
    hold and deliver once geo is re-enabled, never advance the
    checkpoint past the window."""
    from seaweedfs_tpu.replication.geo import GeoReplicator

    httpd, handler, addr = _start_stub()
    try:
        fs = _StubFs(cluster_id=1)
        fs.write_file("/buckets/b/g", b"v")
        handler.disabled_next = 2  # two 404s, then geo is re-enabled
        rep = GeoReplicator(fs, addr, journal_dir=str(tmp_path),
                            rate_mbps=0)
        rep.start()
        assert _wait(lambda: any(a["path"] == "/buckets/b/g"
                                 for a in handler.applies), timeout=15)
        rep.stop()
    finally:
        httpd.shutdown()


def test_replicator_holds_link_on_skew_rejection(tmp_path):
    """A 400 carrying the X-Seaweed-Reject: skew marker means OUR
    clock looks broken to the remote — remote-state, clears over
    operator time: hold the link, never skip past the checkpoint (a
    plain 400 stays poison and is skipped)."""
    from seaweedfs_tpu.replication.geo import GeoReplicator

    httpd, handler, addr = _start_stub()
    try:
        fs = _StubFs(cluster_id=1)
        fs.write_file("/buckets/b/s", b"v")
        handler.skew_next = 2  # two skew rejections, then accepted
        rep = GeoReplicator(fs, addr, journal_dir=str(tmp_path),
                            rate_mbps=0)
        rep.start()
        assert _wait(lambda: any(a["path"] == "/buckets/b/s"
                                 for a in handler.applies), timeout=15)
        rep.stop()
    finally:
        httpd.shutdown()


def test_replicator_skips_events_signed_by_remote(tmp_path):
    from seaweedfs_tpu.replication.geo import GeoReplicator

    httpd, handler, addr = _start_stub()  # stub reports clusterId 9
    try:
        fs = _StubFs(cluster_id=1)
        # an apply FROM cluster 9 (the remote): must not ship back
        fs.write_file("/buckets/b/from-remote", b"looped?",
                      signatures=[9])
        fs.write_file("/buckets/b/local", b"ship me")
        rep = GeoReplicator(fs, addr, journal_dir=str(tmp_path),
                            rate_mbps=0)
        rep.start()
        assert _wait(lambda: any(a["path"] == "/buckets/b/local"
                                 for a in handler.applies))
        rep.stop()
        assert not any(a["path"] == "/buckets/b/from-remote"
                       for a in handler.applies)
    finally:
        httpd.shutdown()


def test_replicator_resyncs_on_meta_log_gap(tmp_path):
    from seaweedfs_tpu.replication.geo import GeoReplicator

    httpd, handler, addr = _start_stub()
    try:
        fs = _StubFs(cluster_id=1)
        # memory-only ring with tiny capacity: early events evict -> a
        # from-zero tail hits MetaLogGap -> full namespace resync
        fs.filer.meta_log = MetaLogBuffer(capacity=4)
        fs.filer.meta_log.observe(1)
        for i in range(10):
            fs.write_file(f"/buckets/b/f{i}", f"p{i}".encode())
        rep = GeoReplicator(fs, addr, journal_dir=str(tmp_path),
                            rate_mbps=0)
        rep.start()
        assert _wait(lambda: len(
            {a["path"] for a in handler.applies
             if a["op"] == "put"}) >= 10, timeout=15)
        rep.stop()
        assert rep.resyncs >= 1
        shipped = {a["path"] for a in handler.applies
                   if a["op"] == "put"}
        assert shipped == {f"/buckets/b/f{i}" for i in range(10)}
        # resync events carry seq=0 (LWW-only, no watermark)
        assert all(a["seq"] == 0 for a in handler.applies
                   if a["op"] == "put")
    finally:
        httpd.shutdown()


def test_replicator_skips_config_namespaces(tmp_path):
    from seaweedfs_tpu.replication.geo import GeoReplicator

    httpd, handler, addr = _start_stub()
    try:
        fs = _StubFs(cluster_id=1)
        fs.write_file("/etc/seaweedfs/filer.conf", b"local config")
        fs.write_file("/buckets/b/real", b"object")
        rep = GeoReplicator(fs, addr, journal_dir=str(tmp_path),
                            rate_mbps=0)
        rep.start()
        assert _wait(lambda: any(a["path"] == "/buckets/b/real"
                                 for a in handler.applies))
        rep.stop()
        assert not any(a["path"].startswith("/etc/")
                       for a in handler.applies)
    finally:
        httpd.shutdown()


def test_replicator_file_rename_put_survives_watermark(tmp_path):
    """A move ships delete+put halves from ONE source event: the delete
    must ride seq=0 (LWW/tombstone-fenced) so advancing the remote
    watermark on it cannot drop the sibling put as a duplicate."""
    from seaweedfs_tpu.replication.geo import GeoReplicator

    httpd, handler, addr = _start_stub()
    try:
        fs = _StubFs(cluster_id=1)
        fs.write_file("/buckets/b/old.bin", b"payload")
        fs.filer.rename_entry("/buckets/b", "old.bin",
                              "/buckets/b", "new.bin")
        rep = GeoReplicator(fs, addr, journal_dir=str(tmp_path),
                            rate_mbps=0)
        rep.start()
        assert _wait(lambda: any(a["path"] == "/buckets/b/new.bin"
                                 for a in handler.applies))
        rep.stop()
        deletes = [a for a in handler.applies if a["op"] == "delete"]
        assert [a["path"] for a in deletes] == ["/buckets/b/old.bin"]
        assert deletes[0]["seq"] == 0
        put = [a for a in handler.applies
               if a["path"] == "/buckets/b/new.bin"]
        assert put and put[-1]["seq"] > 0
        # replay the exact shipped stream into a REAL applier: the
        # renamed object must exist at the new path, not vanish
        ap, target = _applier(_StubFs(cluster_id=2, signature=888))
        for a in handler.applies:
            ap.apply(origin=a["origin"], source=11, seq=a["seq"],
                     hlc=a["hlc"], op=a["op"], path=a["path"],
                     data=a["data"])
        assert _read(target, "/buckets/b/new.bin") == b"payload"
        assert _read(target, "/buckets/b/old.bin") is None
    finally:
        httpd.shutdown()


def test_replicator_dir_rename_reships_children(tmp_path):
    """A renamed directory moved its children with raw store ops (no
    per-child events): the replicator must re-ship the subtree under the
    new path, or the remote's recursive delete destroys it forever."""
    from seaweedfs_tpu.replication.geo import GeoReplicator

    httpd, handler, addr = _start_stub()
    try:
        fs = _StubFs(cluster_id=1)
        fs.write_file("/buckets/b/dir/x.bin", b"xx")
        fs.write_file("/buckets/b/dir/sub/y.bin", b"yy")
        fs.filer.rename_entry("/buckets/b", "dir", "/buckets/b", "dir2")
        rep = GeoReplicator(fs, addr, journal_dir=str(tmp_path),
                            rate_mbps=0)
        rep.start()
        assert _wait(lambda: {a["path"] for a in handler.applies
                              if a["op"] == "put"} >= {
            "/buckets/b/dir2/x.bin", "/buckets/b/dir2/sub/y.bin"})
        rep.stop()
        deletes = [a for a in handler.applies if a["op"] == "delete"]
        assert [a["path"] for a in deletes] == ["/buckets/b/dir"]
        assert deletes[0]["seq"] == 0
        by_path = {a["path"]: a for a in handler.applies
                   if a["op"] == "put"}
        assert by_path["/buckets/b/dir2/x.bin"]["data"] == b"xx"
        assert by_path["/buckets/b/dir2/sub/y.bin"]["data"] == b"yy"
        # end-to-end replay: the remote ends with the subtree at the new
        # path only
        ap, target = _applier(_StubFs(cluster_id=2, signature=888))
        for a in handler.applies:
            ap.apply(origin=a["origin"], source=11, seq=a["seq"],
                     hlc=a["hlc"], op=a["op"], path=a["path"],
                     data=a["data"])
        assert _read(target, "/buckets/b/dir2/x.bin") == b"xx"
        assert _read(target, "/buckets/b/dir2/sub/y.bin") == b"yy"
        assert _read(target, "/buckets/b/dir/x.bin") is None
    finally:
        httpd.shutdown()


def test_resync_preserves_remote_origin_no_phantom_conflict(tmp_path):
    """_resync re-ships pre-existing state with each entry's TRUE origin
    stamp: an entry the remote itself originated must compare equal
    there (dup) instead of inflating the conflict counter with
    same-timestamp cluster-id mismatches."""
    from seaweedfs_tpu.replication.geo import GeoReplicator

    httpd, handler, addr = _start_stub()
    try:
        fs = _StubFs(cluster_id=1)
        ts = fs.filer.meta_log.next_ts()
        # an entry the REMOTE cluster (id 9) originated, relayed here
        # (relays carry replication signatures; the origin stamp sticks)
        fs.write_file("/buckets/b/theirs", b"their-bytes",
                      signatures=[9],
                      extended={GEO_HLC_KEY: encode_hlc(ts, 9)})
        fs.write_file("/buckets/b/ours", b"our-bytes")
        rep = GeoReplicator(fs, addr, journal_dir=str(tmp_path),
                            rate_mbps=0)
        rep._remote_cid = 9
        rep._resync()
        by_path = {a["path"]: a for a in handler.applies
                   if a["op"] == "put"}
        assert by_path["/buckets/b/theirs"]["origin"] == 9
        assert by_path["/buckets/b/ours"]["origin"] == 1
        # replayed into a remote that already holds ITS copy: equal
        # stamps land as dup, never a phantom LWW conflict
        ap, target = _applier(_StubFs(cluster_id=9, signature=999))
        target.write_file("/buckets/b/theirs", b"their-bytes",
                          signatures=[1],
                          extended={GEO_HLC_KEY: encode_hlc(ts, 9)})
        before = _counter("seaweedfs_geo_conflicts_total", "9", "local")
        a = by_path["/buckets/b/theirs"]
        out = ap.apply(origin=a["origin"], source=11, seq=0,
                       hlc=a["hlc"], op="put", path=a["path"],
                       data=a["data"])
        assert out["result"] == "dup"
        assert _counter("seaweedfs_geo_conflicts_total",
                        "9", "local") == before
    finally:
        httpd.shutdown()


def test_subscribe_no_duplicates_when_ring_overlaps_disk(tmp_path):
    """The cold (disk) scan ts-filters at the frame header; the hand-off
    to the live ring must not re-deliver records the filter skipped."""
    log = MetaLogBuffer(capacity=8, dir=str(tmp_path / "log"))
    tss = [log.append("/d", None, _entry(f"f{i}")) for i in range(6)]
    stop = threading.Event()
    got: list = []

    def consume():
        for ev in log.subscribe(tss[2], stop_event=stop,
                                poll_interval=0.02):
            got.append(ev.event_notification.new_entry.name)

    t = threading.Thread(target=consume, daemon=True)
    t.start()
    assert _wait(lambda: len(got) >= 3)
    log.append("/d", None, _entry("live"))
    assert _wait(lambda: "live" in got)
    stop.set()
    t.join(timeout=5)
    assert got == ["f3", "f4", "f5", "live"]


def test_applier_refuses_far_future_hlc():
    """A corrupt/forged far-future stamp must be rejected (400 to the
    sender) BEFORE it poisons the local clock or fences the path."""
    ap, fs = _applier()
    bad = time.time_ns() + int(48 * 3600 * 1e9)  # 48h ahead
    with pytest.raises(ValueError):
        ap.apply(origin=1, source=11, seq=1, hlc=bad, op="delete",
                 path="/buckets/b/poison")
    # the clock did not fold the stamp in, and no tombstone landed
    assert fs.filer.meta_log.next_ts() < bad
    assert fs.filer.store.kv_get(
        tombstone_key("/buckets/b/poison")) is None
    # a stamp within the allowed skew still applies
    ok = time.time_ns() + int(60 * 1e9)
    out = ap.apply(origin=1, source=11, seq=1, hlc=ok, op="put",
                   path="/buckets/b/skewed", data=b"v")
    assert out["result"] == "ok"


def test_recover_clock_survives_empty_newest_segment(tmp_path):
    """A crash right after a segment roll leaves the newest segment
    empty; recovery must restore the HLC from the previous segment so
    new stamps never regress below already-issued ones."""
    d = str(tmp_path / "log")
    log = MetaLogBuffer(dir=d, segment_bytes=1 << 20)
    future = time.time_ns() + int(120 * 1e9)
    log.observe(future)  # a remote stamp ahead of the wall clock
    log.append("/d", None, _entry("f0"))  # persisted with ts > future
    log.close()
    # simulate the roll-then-crash: an empty newest segment
    open(os.path.join(d, "seg-0000000000000002.log"), "wb").close()
    log2 = MetaLogBuffer(dir=d)
    assert log2.last_seq() == 1
    assert log2.next_ts() > future


def test_recover_clock_scans_past_older_ingested_segments(tmp_path):
    """The max issued ts is not necessarily in the NEWEST segment:
    aggregator-ingested peer events keep their original (older) stamps
    and can fill whole segments after a local append with a newer one.
    Recovery must scan all retained segments or the clock regresses and
    later stamps lose LWW remotely."""
    d = str(tmp_path / "log")
    log = MetaLogBuffer(dir=d, segment_bytes=256)
    future = time.time_ns() + int(120 * 1e9)
    log.observe(future)
    log.append("/d", None, _entry("fresh"))  # ts > future, segment 1
    old = time.time_ns() - int(3600 * 1e9)
    for i in range(12):  # several segments of older-stamped peer events
        resp = filer_pb2.SubscribeMetadataResponse(
            directory="/d", ts_ns=old + i)
        resp.event_notification.new_entry.CopyFrom(_entry(f"peer{i}"))
        log.ingest(resp)
    segs = [p for p in os.listdir(d) if p.startswith("seg-")]
    assert len(segs) >= 2  # the newest segment holds only old stamps
    log.close()
    log2 = MetaLogBuffer(dir=d)
    assert log2.next_ts() > future


def test_read_persisted_retention_race_raises_gap(tmp_path):
    """Retention deleting a segment mid-read must surface MetaLogGap
    (the documented loud-gap protocol), not FileNotFoundError."""
    d = str(tmp_path / "log")
    log = MetaLogBuffer(dir=d, segment_bytes=256)
    for i in range(12):  # several segments
        log.append("/d", None, _entry(f"f{i}"))
    segs = sorted(p for p in os.listdir(d) if p.startswith("seg-"))
    assert len(segs) >= 3
    gen = log._read_persisted(0, 1 << 60)
    next(gen)  # first segment is open
    for name in segs[1:]:  # retention removes the rest under us
        os.remove(os.path.join(d, name))
    with pytest.raises(MetaLogGap):
        for _ in gen:
            pass


def test_applied_event_ts_stays_monotonic_for_ts_subscribers():
    """A geo apply keeps the ORIGIN stamp on the entry but must log a
    fresh monotonic event ts: a ts-resumed subscriber (within-cluster
    replicator) would otherwise silently skip the applied mutation."""
    ap, fs = _applier()
    fs.write_file("/buckets/b/recent", b"local")  # advances the clock
    high = fs.filer.meta_log.last_seq()
    with fs.filer.meta_log._cond:
        last_ts = fs.filer.meta_log._last_ts
    old_hlc = last_ts - 10_000_000  # origin stamp BEHIND the local clock
    out = ap.apply(origin=1, source=11, seq=1, hlc=old_hlc, op="put",
                   path="/buckets/b/applied", data=b"remote")
    assert out["result"] == "ok"
    stop = threading.Event()
    events = []
    for seq, ev in fs.filer.meta_log.tail(high, stop_event=stop,
                                          poll_interval=0.02):
        events.append(ev)
        if ev.event_notification.new_entry.name == "applied":
            stop.set()
    assert all(ev.ts_ns > last_ts for ev in events), \
        "applied event logged with a regressed ts"
    # while the ENTRY keeps the origin stamp for LWW
    stored = fs.filer.find_entry("/buckets/b/applied")
    assert decode_hlc(bytes(stored.extended[GEO_HLC_KEY])) == (old_hlc, 1)


def test_relayed_event_ships_origin_stamp(tmp_path):
    """In a 3+ cluster mesh, relaying an applied event must ship the
    entry's ORIGIN (hlc, cluster), not the relay's — otherwise every hop
    re-wins LWW over the original and stamps diverge around the mesh."""
    from seaweedfs_tpu.replication.geo import GeoReplicator

    httpd, handler, addr = _start_stub()
    try:
        fs = _StubFs(cluster_id=2)
        origin_hlc = fs.filer.meta_log.next_ts() - 5_000_000
        # an apply from cluster 1 relayed through this cluster (2) —
        # signed by 1, stamped (origin_hlc, 1)
        fs.write_file("/buckets/b/relay", b"v", signatures=[1],
                      extended={GEO_HLC_KEY: encode_hlc(origin_hlc, 1)})
        rep = GeoReplicator(fs, addr, journal_dir=str(tmp_path),
                            rate_mbps=0)  # stub reports cluster_id 9
        rep.start()
        assert _wait(lambda: any(a["path"] == "/buckets/b/relay"
                                 for a in handler.applies))
        rep.stop()
        a = [x for x in handler.applies
             if x["path"] == "/buckets/b/relay"][-1]
        assert a["origin"] == 1, "relay must not claim the event"
        assert a["hlc"] == origin_hlc
    finally:
        httpd.shutdown()


def test_applier_lww_window_serialized_with_local_writes():
    """The stripe lock closes the check-then-act window: a newer local
    write that lands while the applier is mid-apply must not be
    overwritten by the older remote event."""
    ap, fs = _applier()
    path = "/buckets/b/raced"
    old_hlc = fs.filer.meta_log.next_ts()
    started, done = threading.Event(), threading.Event()

    def apply_older():
        started.set()
        out = ap.apply(origin=1, source=11, seq=1, hlc=old_hlc,
                       op="put", path=path, data=b"stale-remote")
        done.set()
        results.append(out["result"])

    results: list = []
    lock = fs.filer.path_mutation_lock(path)
    with lock:  # hold the stripe: the applier must block…
        t = threading.Thread(target=apply_older, daemon=True)
        t.start()
        started.wait(5)
        time.sleep(0.1)
        assert not done.is_set(), "applier ignored the mutation stripe"
        # …while a newer local write lands (reentrant for this thread)
        fs.write_file(path, b"newer-local")
    t.join(timeout=10)
    assert results == ["conflict"]
    assert _read(fs, path) == b"newer-local"


def test_meta_log_fsync_param_passthrough(tmp_path):
    f = Filer(make_store("memory"), meta_log_dir=str(tmp_path / "l"),
              meta_log_fsync=False)
    assert f.meta_log._fsync is False
    f2 = Filer(make_store("memory"), meta_log_dir=str(tmp_path / "l2"),
               meta_log_fsync=True)
    assert f2.meta_log._fsync is True


# ---------------------------------------------------------------------------
# classified sink applies (satellite)
# ---------------------------------------------------------------------------


class _FlakySink(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    codes: list = []
    hits = 0

    def log_message(self, fmt, *args):
        pass

    def _reply(self, code):
        type(self).hits += 1
        self.rfile.read(int(self.headers.get("Content-Length") or 0))
        self.send_response(code)
        self.send_header("Content-Length", "0")
        self.end_headers()

    def do_PUT(self):
        self._reply(self.codes.pop(0) if self.codes else 200)

    def do_DELETE(self):
        self._reply(self.codes.pop(0) if self.codes else 204)


def _start_sink(codes):
    handler = type("BoundFlaky", (_FlakySink,),
                   {"codes": list(codes), "hits": 0})
    httpd = ThreadingHTTPServer(("127.0.0.1", 0), handler)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    return httpd, handler, f"127.0.0.1:{httpd.server_address[1]}"


def test_sink_apply_retries_5xx_then_succeeds():
    from seaweedfs_tpu.replication.sink import FilerSink

    httpd, handler, addr = _start_sink([503])
    try:
        FilerSink(addr).create_entry("/d", _entry("a", b"x"), b"x")
        assert handler.hits == 2  # one 503, one success
    finally:
        httpd.shutdown()


def test_sink_apply_4xx_is_permanent_no_retry():
    from seaweedfs_tpu.replication.sink import (
        FilerSink,
        SinkPermanentError,
    )

    httpd, handler, addr = _start_sink([403, 200, 200])
    try:
        with pytest.raises(SinkPermanentError):
            FilerSink(addr).create_entry("/d", _entry("a", b"x"), b"x")
        assert handler.hits == 1  # no second attempt
    finally:
        httpd.shutdown()


def test_sink_delete_404_is_success():
    from seaweedfs_tpu.replication.sink import FilerSink

    httpd, handler, addr = _start_sink([404])
    try:
        FilerSink(addr).delete_entry("/d", "gone", False)
        assert handler.hits == 1
    finally:
        httpd.shutdown()


def test_replicator_skips_permanent_rejects_and_continues():
    """A poison event (permanent 4xx) must not dam the stream: the
    replicator counts it and applies the NEXT event."""
    from seaweedfs_tpu.replication.replicator import Replicator
    from seaweedfs_tpu.replication.sink import SinkPermanentError

    class _Sink:
        def __init__(self):
            self.created = []

        def create_entry(self, directory, entry, data):
            if entry.name == "poison":
                raise SinkPermanentError("403 forbidden")
            self.created.append(entry.name)

        def delete_entry(self, *a):
            pass

    class _Src:
        def read_entry_data(self, directory, entry):
            return b"d"

    sink = _Sink()
    rep = Replicator(_Src(), sink)
    ev = filer_pb2.EventNotification()
    ev.new_entry.name = "poison"
    with pytest.raises(SinkPermanentError):
        rep.process_event("/d", ev)
    ok = filer_pb2.EventNotification()
    ok.new_entry.name = "fine"
    rep.process_event("/d", ok)
    assert sink.created == ["fine"]


# ---------------------------------------------------------------------------
# fleet client geo failover
# ---------------------------------------------------------------------------


def test_fleet_client_fails_over_to_remote_cluster():
    from seaweedfs_tpu.filer.fleet.fleet_client import FleetFilerClient
    from seaweedfs_tpu.filer.fleet.router import FleetRouter

    router = FleetRouter(filers=["127.0.0.1:1", "127.0.0.1:2"],
                         remote_filers=["127.0.0.1:3"])
    client = FleetFilerClient(router)
    served = []

    def fn(c):
        if c.http_address in ("127.0.0.1:1", "127.0.0.1:2"):
            raise ConnectionRefusedError("local cluster is dead")
        served.append(c.http_address)
        return "remote-answer"

    before = _counter("seaweedfs_filer_ring_route_total", "remote")
    assert client._run("/buckets/b/k", fn) == "remote-answer"
    assert served == ["127.0.0.1:3"]
    assert _counter("seaweedfs_filer_ring_route_total",
                    "remote") == before + 1


def test_fleet_client_prefers_local_when_alive():
    from seaweedfs_tpu.filer.fleet.fleet_client import FleetFilerClient
    from seaweedfs_tpu.filer.fleet.router import FleetRouter

    router = FleetRouter(filers=["127.0.0.1:1"],
                         remote_filers=["127.0.0.1:3"])
    client = FleetFilerClient(router)
    assert client._run("/buckets/b/k",
                       lambda c: c.http_address) == "127.0.0.1:1"


def test_router_without_remote_has_no_remote_candidates():
    from seaweedfs_tpu.filer.fleet.router import FleetRouter

    router = FleetRouter(filers=["127.0.0.1:1"])
    assert router.remote_candidates("/buckets/b/k") == []


def test_fleet_client_total_loss_beyond_try_cap_goes_remote():
    """Geo failover must engage on TOTAL local loss even when the fleet
    is larger than the bounded try cap: the sweep proves every local
    shard dark before dodging to the remote cluster (a capped sweep
    would misclassify the all-dark cluster as a partial outage and 503
    forever)."""
    from seaweedfs_tpu.filer.fleet.fleet_client import (
        FleetFilerClient,
        MAX_TRIES,
    )
    from seaweedfs_tpu.filer.fleet.router import FleetRouter

    local = [f"127.0.0.1:{p}" for p in range(1, MAX_TRIES + 3)]
    router = FleetRouter(filers=local, remote_filers=["127.0.0.1:99"])
    client = FleetFilerClient(router)
    touched = []

    def fn(c):
        touched.append(c.http_address)
        if c.http_address != "127.0.0.1:99":
            raise ConnectionRefusedError("down")
        return "remote-answer"

    assert client._run("/buckets/b/k", fn) == "remote-answer"
    assert set(local) <= set(touched)  # every local shard proven dark
    assert touched[-1] == "127.0.0.1:99"


def test_fleet_client_partial_outage_serves_from_surviving_shard():
    """A PARTIAL outage must never route to the remote cluster
    (avoidable LWW conflicts + local stale reads): the full local sweep
    reaches the surviving shard and serves from it."""
    from seaweedfs_tpu.filer.fleet.fleet_client import (
        FleetFilerClient,
        MAX_TRIES,
    )
    from seaweedfs_tpu.filer.fleet.router import FleetRouter

    local = [f"127.0.0.1:{p}" for p in range(1, MAX_TRIES + 3)]
    alive = local[-1]
    router = FleetRouter(filers=local, remote_filers=["127.0.0.1:99"])
    client = FleetFilerClient(router)
    touched = []

    def fn(c):
        touched.append(c.http_address)
        if c.http_address != alive:
            raise ConnectionRefusedError("down")
        return "local-answer"

    assert client._run("/buckets/b/k", fn) == "local-answer"
    assert "127.0.0.1:99" not in touched  # remote never consulted


def test_fleet_client_try_cap_bounds_sweep_without_geo():
    """Without a geo fallback the bounded try cap still applies — a
    flapping fleet must not turn one request into an unbounded sweep."""
    from seaweedfs_tpu.filer.fleet.fleet_client import (
        FleetFilerClient,
        FilerUnavailable,
        MAX_TRIES,
    )
    from seaweedfs_tpu.filer.fleet.router import FleetRouter

    local = [f"127.0.0.1:{p}" for p in range(1, MAX_TRIES + 3)]
    router = FleetRouter(filers=local)
    client = FleetFilerClient(router)
    touched = []

    def fn(c):
        touched.append(c.http_address)
        raise ConnectionRefusedError("down")

    with pytest.raises(FilerUnavailable):
        client._run("/buckets/b/k", fn)
    assert len(touched) == MAX_TRIES


def test_fleet_client_discovery_failure_goes_remote():
    """A fresh gateway (no cached ring) whose local masters are all
    unreachable must still reach the geo fallback: discovery failures
    are an outage, not an unclassified error."""
    from seaweedfs_tpu.filer.fleet.fleet_client import FleetFilerClient
    from seaweedfs_tpu.filer.fleet.router import FleetRouter

    router = FleetRouter(masters=["127.0.0.1:1"],
                         remote_filers=["127.0.0.1:99"])
    client = FleetFilerClient(router)
    served = []

    def fn(c):
        served.append(c.http_address)
        return "remote-answer"

    assert client._run("/buckets/b/k", fn) == "remote-answer"
    assert served == ["127.0.0.1:99"]


# ---------------------------------------------------------------------------
# master geo registry
# ---------------------------------------------------------------------------


def test_master_geo_status_probes_peers_and_collects_link_samples():
    from seaweedfs_tpu.master.server import MasterServer
    from seaweedfs_tpu.pb import master_pb2

    m = MasterServer(port=1, peer_clusters=["127.0.0.1:9"])  # not started
    try:
        snap = master_pb2.StatsSnapshot(captured_at_ms=1)
        snap.samples.add(
            name='seaweedfs_geo_lag_seconds{link="c1->x"}', value=0.25)
        snap.samples.add(name="seaweedfs_request_total", value=99)
        m.record_stats_snapshot("127.0.0.1:8888", "filer", snap)
        doc = m.geo_status()
        assert doc["peerClusters"]["127.0.0.1:9"]["reachable"] is False
        links = doc["links"]["127.0.0.1:8888"]
        assert links['seaweedfs_geo_lag_seconds{link="c1->x"}'] == 0.25
        assert "seaweedfs_request_total" not in links
    finally:
        m.federation_pool.shutdown(wait=False)


# ---------------------------------------------------------------------------
# checkpoint / log-incarnation binding, body cap, lag semantics
# ---------------------------------------------------------------------------


def test_replicator_resyncs_on_log_incarnation_change(tmp_path):
    """A checkpoint is bound to ONE meta-log identity: after the log dir
    is wiped/repointed (seq restarts at 1), resuming by bare seq would
    silently skip the new log's first N events once last_seq catches up
    past the stale checkpoint — the link must resync instead."""
    from seaweedfs_tpu.replication.geo import GeoReplicator

    httpd, handler, addr = _start_stub()
    try:
        fs = _StubFs(cluster_id=1)
        fs.filer.meta_log = MetaLogBuffer(dir=str(tmp_path / "log-a"))
        for i in range(3):
            fs.write_file(f"/buckets/b/f{i}", f"p{i}".encode())
        rep = GeoReplicator(fs, addr,
                            journal_dir=str(tmp_path / "j"), rate_mbps=0)
        rep.start()
        assert _wait(lambda: len(
            [a for a in handler.applies if a["op"] == "put"]) >= 3)
        rep.stop()
        stale_ckpt = rep.checkpoint()
        assert stale_ckpt == fs.filer.meta_log.last_seq()
        # NEW incarnation: fresh dir, seq restarts at 1 — the stale
        # checkpoint is at-or-past the new head, so without the log_id
        # check tail() would serve few or none of the new events
        fs.filer.meta_log = MetaLogBuffer(dir=str(tmp_path / "log-b"))
        for i in range(5):
            fs.write_file(f"/buckets/b/g{i}", f"q{i}".encode())
        n = len(handler.applies)
        rep2 = GeoReplicator(fs, addr,
                             journal_dir=str(tmp_path / "j"), rate_mbps=0)
        rep2.start()
        assert _wait(lambda: {f"/buckets/b/g{i}" for i in range(5)} <= {
            a["path"] for a in handler.applies[n:] if a["op"] == "put"},
            timeout=15)
        rep2.stop()
        # without the log_id check, tail(3) on the new log serves only
        # seqs 4..5 — g0..g2 would be missing above; the resync path is
        # what shipped them
        assert rep2.resyncs >= 1
        # drained after resync: lag reads 0, not age-of-last-event
        assert rep2.status()["lagSeconds"] == 0.0
        # the healed checkpoint is bound to the NEW incarnation
        rec = rep2.journal.get(rep2._key)
        assert rec["log_id"] == fs.filer.meta_log.log_id
    finally:
        httpd.shutdown()


def test_replicator_skips_oversized_entries(tmp_path, monkeypatch):
    """An entry above the geo body cap is skipped (counted), not
    shipped: one multi-GB object must not OOM both filers or dam the
    stream behind a guaranteed 413."""
    from seaweedfs_tpu.replication import geo as geo_mod

    monkeypatch.setattr(geo_mod, "MAX_BODY_BYTES", 16)
    httpd, handler, addr = _start_stub()
    try:
        fs = _StubFs(cluster_id=1)
        fs.write_file("/buckets/b/big", b"x" * 64)
        fs.write_file("/buckets/b/ok", b"small")
        rep = geo_mod.GeoReplicator(fs, addr,
                                    journal_dir=str(tmp_path),
                                    rate_mbps=0)
        rep.start()
        assert _wait(lambda: any(a["path"] == "/buckets/b/ok"
                                 for a in handler.applies))
        rep.stop()
        assert not any(a["path"] == "/buckets/b/big"
                       for a in handler.applies)
        # the stream advanced past the oversized event (checkpointed)
        assert rep.checkpoint() == fs.filer.meta_log.last_seq()
    finally:
        httpd.shutdown()


def test_geo_apply_rejects_oversized_content_length():
    """POST /.geo/apply with a huge Content-Length is refused up front
    (413, connection closed) — never buffered."""
    import socket

    from seaweedfs_tpu.filer.http_handlers import FilerHttpHandler
    from seaweedfs_tpu.replication.geo import GeoApplier, MAX_BODY_BYTES

    fs = _StubFs(cluster_id=1)
    fs.geo_applier = GeoApplier(fs)
    handler = type("BoundFilerHandler", (FilerHttpHandler,),
                   {"filer_server": fs})
    httpd = ThreadingHTTPServer(("127.0.0.1", 0), handler)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    try:
        with socket.create_connection(httpd.server_address,
                                      timeout=10) as s:
            s.sendall(
                b"POST /.geo/apply?op=put&path=/buckets/b/x HTTP/1.1\r\n"
                b"Host: t\r\n"
                b"Content-Length: " + str(MAX_BODY_BYTES + 1).encode()
                + b"\r\n\r\n")
            status = s.recv(4096).split(b"\r\n", 1)[0]
        assert b"413" in status
        # a small body on the same surface still applies fine
        ts = fs.filer.meta_log.next_ts()
        q = (f"origin=7&src=77&seq=1&hlc={ts + 5}"
             f"&op=put&path=/buckets/b/x")
        req = urllib.request.Request(
            f"http://127.0.0.1:{httpd.server_address[1]}"
            f"/.geo/apply?{q}", data=b"v1", method="POST")
        with urllib.request.urlopen(req, timeout=10) as r:
            assert json.loads(r.read())["result"] == "ok"
        assert _read(fs, "/buckets/b/x") == b"v1"
    finally:
        httpd.shutdown()


def test_walk_ship_dirs_carry_true_origin(tmp_path):
    """Resync re-ships a directory with the cluster id that CREATED it
    (its stored stamp), not the local id — otherwise a backlog delete
    carrying the true origin stamp mis-compares against the resynced
    mkdir and the 'same mutation' dup/LWW tiebreak inverts."""
    from seaweedfs_tpu.replication.geo import GeoApplier, GeoReplicator

    fs = _StubFs(cluster_id=1)
    ap = GeoApplier(fs)
    ts = fs.filer.meta_log.next_ts()
    ap.apply(origin=7, source=77, seq=1, hlc=ts + 5, op="mkdir",
             path="/buckets/b/dir7")
    httpd, handler, addr = _start_stub()
    try:
        rep = GeoReplicator(fs, addr, rate_mbps=0)
        rep._walk_ship("/")
        mk = [a for a in handler.applies
              if a["op"] == "mkdir" and a["path"] == "/buckets/b/dir7"]
        assert mk and mk[0]["origin"] == 7
        assert mk[0]["hlc"] == ts + 5
    finally:
        httpd.shutdown()


def test_applier_watermark_scoped_to_log_incarnation():
    """The (source, seq) dup check only means "already applied" within
    ONE sender log incarnation: after the sender's log dir is wiped and
    seq restarts at 1, the new log's low seqs must APPLY — not be
    swallowed as duplicates of the old log's higher watermark."""
    ap, fs = _applier()
    ts = fs.filer.meta_log.next_ts()
    assert ap.apply(origin=1, source=11, seq=7, hlc=ts + 1, op="put",
                    path="/buckets/b/a", data=b"v1",
                    log="log-A")["result"] == "ok"
    # same incarnation, re-delivered: dup
    assert ap.apply(origin=1, source=11, seq=7, hlc=ts + 1, op="put",
                    path="/buckets/b/a", data=b"v1",
                    log="log-A")["result"] == "dup"
    # NEW incarnation restarts at seq 2 < 7: must apply, not dup
    assert ap.apply(origin=1, source=11, seq=2, hlc=ts + 5, op="put",
                    path="/buckets/b/b", data=b"v2",
                    log="log-B")["result"] == "ok"
    assert _read(fs, "/buckets/b/b") == b"v2"
    # the watermark rebound to the new incarnation...
    assert ap.watermark(11) == (2, "log-B")
    # ...and re-delivery within it dedupes again
    assert ap.apply(origin=1, source=11, seq=2, hlc=ts + 5, op="put",
                    path="/buckets/b/b", data=b"v2",
                    log="log-B")["result"] == "dup"
    # persistence round-trips the (seq, log) pair
    ap.flush()
    ap2, _ = _applier(fs)
    assert ap2.watermark(11) == (2, "log-B")


def test_applier_ancestor_tombstone_fences_subtree():
    """A recursive directory delete leaves ONE tombstone at the
    directory; a backlogged OLDER remote write inside the subtree must
    compare against that ancestor fence — else it resurrects the
    deleted tree on this cluster only (permanent divergence)."""
    ap, fs = _applier()
    fs.write_file("/buckets/b/d/f", b"v1")
    d, n = split_path("/buckets/b/d")
    fs.filer.delete_entry(d, n, is_recursive=True,
                          ignore_recursive_error=True)
    tomb = decode_hlc(fs.filer.store.kv_get(tombstone_key("/buckets/b/d")))
    assert tomb is not None
    out = ap.apply(origin=1, source=11, seq=1, hlc=tomb[0] - 100,
                   op="put", path="/buckets/b/d/f2", data=b"zombie")
    assert out["result"] == "conflict"
    assert _read(fs, "/buckets/b/d/f2") is None
    e = fs.filer.find_entry("/buckets/b/d")
    assert e is None or not e.name  # the dir stayed dead too
    # the same fence applies to a resurrecting mkdir of a SUBdirectory
    out = ap.apply(origin=1, source=11, seq=2, hlc=tomb[0] - 50,
                   op="mkdir", path="/buckets/b/d/sub")
    assert out["result"] == "conflict"
    # a STRICTLY NEWER write inside the subtree resurrects legitimately
    out = ap.apply(origin=1, source=11, seq=3, hlc=tomb[0] + 100,
                   op="put", path="/buckets/b/d/f3", data=b"reborn")
    assert out["result"] == "ok"
    assert _read(fs, "/buckets/b/d/f3") == b"reborn"


def test_relayed_delete_ships_tombstone_origin_stamp(tmp_path):
    """Relaying an applied DELETE (3+-cluster mesh) must ship the
    tombstone's ORIGIN (hlc, cluster), not the relay's fresh event
    stamp — an inflated fence at every hop would wrongly beat
    concurrent writes the origin delete properly lost to."""
    from seaweedfs_tpu.replication.geo import GeoApplier, GeoReplicator

    httpd, handler, addr = _start_stub()
    try:
        fs = _StubFs(cluster_id=2)
        ap = GeoApplier(fs)
        fs.write_file("/buckets/b/x", b"v1")
        h = fs.filer.meta_log.next_ts() + 1000
        ap.apply(origin=1, source=11, seq=1, hlc=h, op="delete",
                 path="/buckets/b/x")
        rep = GeoReplicator(fs, addr, journal_dir=str(tmp_path),
                            rate_mbps=0)
        rep.start()
        assert _wait(lambda: any(
            a["op"] == "delete" and a["path"] == "/buckets/b/x"
            for a in handler.applies))
        rep.stop()
        d = [a for a in handler.applies if a["op"] == "delete"][-1]
        assert d["hlc"] == h
        assert d["origin"] == 1, "relay must not claim the delete"
    finally:
        httpd.shutdown()


def test_append_with_stale_reserved_ts_stays_monotonic():
    """A stamp reserved via next_ts() before append's lock can lose the
    append race to a later reservation; the LOGGED event ts must still
    be arrival-monotonic or ts-resumed subscribers silently skip the
    late-appended event on resubscribe."""
    log = MetaLogBuffer()
    t1 = log.next_ts()
    t2 = log.next_ts()
    logged_b = log.append("/d", None, _entry("b"), ts=t2)
    logged_a = log.append("/d", None, _entry("a"), ts=t1)  # late append
    assert logged_b == t2
    assert logged_a > logged_b  # bumped, never regressing
