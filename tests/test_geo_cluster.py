"""Geo chaos acceptance (ISSUE 12): two live clusters, active-active.

Real subprocesses through the CLI — each cluster is its own master +
volume server + filer (cluster ids 1 and 2), cross-linked with
``-geoPeers``.  The scenario pins the acceptance criteria:

* steady-state: writes on either cluster appear byte-identical on the
  other;
* SIGKILL cluster A mid-stream: writes CONTINUE on B with zero 5xx
  (B's replicator link to A just retries in the background);
* a write that landed on A but was never shipped (A died first)
  CONFLICTS with a newer B-side write to the same key after A rejoins:
  LWW picks B's version on BOTH clusters and the conflict is counted in
  ``seaweedfs_geo_conflicts_total`` — never silent;
* A rejoins (same data dirs): both replicators resume from their
  journaled checkpoints/watermarks and a FULL KEY SCAN proves
  byte-identity for every non-conflicting object;
* the filer restart also doubles as the replicator-SIGKILL-resume
  proof: the resumed link must not duplicate applies (watermark
  exactly-once) nor leave gaps (sequence-contiguous tail).
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import pytest

from helpers import free_port

pytestmark = pytest.mark.chaos

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _env():
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env.setdefault("JAX_PLATFORMS", "cpu")
    return env


def _spawn(args, cwd):
    return subprocess.Popen(
        [sys.executable, "-m", "seaweedfs_tpu", *args],
        cwd=cwd, env=_env(),
        stdout=subprocess.DEVNULL, stderr=subprocess.STDOUT,
    )


def _req(method, url, data=None, timeout=15):
    req = urllib.request.Request(url, data=data, method=method)
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, r.read()
    except urllib.error.HTTPError as e:
        return e.code, e.read()


def _wait_http(url, deadline_s=30):
    deadline = time.time() + deadline_s
    while time.time() < deadline:
        try:
            with urllib.request.urlopen(url, timeout=2) as r:
                return r.status
        except (urllib.error.URLError, ConnectionError, OSError):
            time.sleep(0.3)
    raise TimeoutError(url)


class Cluster:
    """One cluster's process set + addresses."""

    def __init__(self, tag: str, cid: int, root: str):
        self.tag, self.cid, self.root = tag, cid, root
        self.mport = free_port()
        self.vport = free_port()
        self.fport = free_port()
        self.procs: dict[str, subprocess.Popen] = {}
        os.makedirs(os.path.join(root, "vol"), exist_ok=True)

    def start(self, geo_peer: str | None = None):
        self.procs["master"] = _spawn(
            ["master", "-port", str(self.mport)], self.root)
        _wait_http(f"http://127.0.0.1:{self.mport}/cluster/healthz")
        self.procs["volume"] = _spawn(
            ["volume", "-dir", os.path.join(self.root, "vol"),
             "-port", str(self.vport),
             "-mserver", f"127.0.0.1:{self.mport}",
             "-ec.codec", "cpu", "-max", "100"], self.root)
        filer_args = [
            "filer", "-master", f"127.0.0.1:{self.mport}",
            "-port", str(self.fport),
            "-store", os.path.join(self.root, "filer.db"),
            "-clusterId", str(self.cid),
        ]
        if geo_peer:
            filer_args += ["-geoPeers", geo_peer]
        self.procs["filer"] = _spawn(filer_args, self.root)
        _wait_http(f"http://127.0.0.1:{self.fport}/")
        # volume server registered
        deadline = time.time() + 30
        while time.time() < deadline:
            try:
                doc = json.loads(urllib.request.urlopen(
                    f"http://127.0.0.1:{self.mport}/cluster/status",
                    timeout=5).read())
                if len(doc.get("DataNodes", {})) >= 1:
                    return
            except Exception:
                pass
            time.sleep(0.3)
        raise TimeoutError(f"cluster {self.tag}: volume never registered")

    def kill(self):
        for p in self.procs.values():
            if p.poll() is None:
                os.kill(p.pid, signal.SIGKILL)
        for p in self.procs.values():
            p.wait(timeout=10)
        self.procs.clear()

    def put(self, path, data):
        return _req("PUT", f"http://127.0.0.1:{self.fport}{path}",
                    data=data)

    def get(self, path):
        return _req("GET", f"http://127.0.0.1:{self.fport}{path}")

    def metrics(self) -> str:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{self.fport}/metrics", timeout=5) as r:
            return r.read().decode()

    def geo_status(self) -> dict:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{self.fport}/.geo/status",
                timeout=5) as r:
            return json.loads(r.read())


def _wait_visible(cluster, path, want, timeout_s=45):
    deadline = time.time() + timeout_s
    while time.time() < deadline:
        st, body = cluster.get(path)
        if st == 200 and body == want:
            return True
        time.sleep(0.3)
    return False


def _counter_value(metrics_text: str, prefix: str) -> float:
    total = 0.0
    for line in metrics_text.splitlines():
        if line.startswith(prefix):
            try:
                total += float(line.rsplit(" ", 1)[1])
            except ValueError:
                pass
    return total


def test_geo_active_active_kill_primary_rejoin_reconcile(tmp_path):
    a = Cluster("a", 1, str(tmp_path / "a"))
    b = Cluster("b", 2, str(tmp_path / "b"))
    objects: dict[str, bytes] = {}
    try:
        # phase 0 — A alone (no link yet): seed objects + the conflict
        # key.  These events sit in A's DURABLE log, unshipped, so A's
        # death makes them exactly the rejoin backlog.
        a.start()
        for i in range(8):
            key = f"/buckets/geo/seed-{i}.bin"
            blob = (f"seed-{i}:".encode() + os.urandom(64).hex().encode())
            st, _ = a.put(key, blob)
            assert st == 201, f"seed write {st}"
            objects[key] = blob
        st, _ = a.put("/buckets/geo/conflict.txt", b"old from A")
        assert st == 201

        # phase 1 — SIGKILL the whole primary mid-stream (nothing has
        # replicated; its log + journal survive on disk)
        a.kill()

        # phase 2 — B comes up linked to (dead) A; writes must keep
        # working with ZERO 5xx while its geo link retries in vain
        b.start(geo_peer=f"127.0.0.1:{a.fport}")
        codes: list[int] = []
        stop_writes = threading.Event()

        def survivor_writer():
            i = 0
            while not stop_writes.is_set():
                key = f"/buckets/geo/b-live-{i}.bin"
                blob = f"b-live-{i}".encode() * 8
                st, _ = b.put(key, blob)
                codes.append(st)
                if st == 201:
                    objects[key] = blob
                i += 1
                time.sleep(0.05)

        w = threading.Thread(target=survivor_writer, daemon=True)
        w.start()
        st, _ = b.put("/buckets/geo/conflict.txt", b"NEW from B")
        assert st == 201
        objects["/buckets/geo/conflict.txt"] = b"NEW from B"
        time.sleep(3)  # a real window of survivor-only traffic

        # phase 3 — A rejoins with the SAME dirs, now geo-linked to B.
        # Its replicator reads the durable log from seq 1 and ships the
        # pre-death backlog; B's link starts delivering its backlog too.
        a.start(geo_peer=f"127.0.0.1:{b.fport}")
        stop_writes.set()
        w.join(timeout=10)
        assert codes and all(c == 201 for c in codes), (
            f"survivor writes saw non-201s: "
            f"{sorted(set(c for c in codes if c != 201))}")

        # phase 4 — convergence: full key scan, byte-identical both ways
        for key, blob in objects.items():
            assert _wait_visible(a, key, blob), f"{key} wrong/missing on A"
            assert _wait_visible(b, key, blob), f"{key} wrong/missing on B"

        # the conflict resolved LWW (B's newer write) on BOTH clusters…
        for c in (a, b):
            st, body = c.get("/buckets/geo/conflict.txt")
            assert (st, body) == (200, b"NEW from B"), (c.tag, st, body)
        # …and was COUNTED, not silent: A shipped its stale version, B
        # rejected it on the hybrid-logical-clock compare
        assert _counter_value(
            b.metrics(), "seaweedfs_geo_conflicts_total") >= 1

        # phase 5 — replicator SIGKILL + restart resumes exactly-once:
        # kill ONLY A's filer (checkpoint + watermark live on disk),
        # write on A while it is down is impossible — so write on B,
        # restart A's filer, and verify the resumed links neither skip
        # nor duplicate.
        b_applied_before = _counter_value(
            b.metrics(), 'seaweedfs_geo_applied_total{origin="1",result="ok"')
        fp = a.procs.pop("filer")
        os.kill(fp.pid, signal.SIGKILL)
        fp.wait(timeout=10)
        st, _ = b.put("/buckets/geo/while-a-down.bin", b"survivor again")
        assert st == 201
        objects["/buckets/geo/while-a-down.bin"] = b"survivor again"
        a.procs["filer"] = _spawn(
            ["filer", "-master", f"127.0.0.1:{a.mport}",
             "-port", str(a.fport),
             "-store", os.path.join(a.root, "filer.db"),
             "-clusterId", "1",
             "-geoPeers", f"127.0.0.1:{b.fport}"], a.root)
        _wait_http(f"http://127.0.0.1:{a.fport}/")
        assert _wait_visible(a, "/buckets/geo/while-a-down.bin",
                             b"survivor again")
        # full scan again after the restart — no object lost or doubled
        for key, blob in objects.items():
            assert _wait_visible(a, key, blob), f"{key} broken on A"
        # exactly-once: the resumed A-link re-shipped nothing B already
        # applied as new "ok"s beyond the genuinely new events; gaps are
        # impossible by construction (sequence-contiguous tail), dups are
        # dropped by the watermark — assert the dup path did the work if
        # anything was re-sent
        b_applied_after = _counter_value(
            b.metrics(), 'seaweedfs_geo_applied_total{origin="1",result="ok"')
        assert b_applied_after >= b_applied_before
        status = a.geo_status()
        assert status["clusterId"] == 1
        assert status["links"], "A's geo link did not come back"
    finally:
        b.kill()
        a.kill()
