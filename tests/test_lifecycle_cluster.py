"""Lifecycle plane acceptance (ISSUE 9): the full policy-driven
seal -> EC-encode -> tier -> vacuum pipeline under concurrent client
reads, the master-SIGKILL-mid-EC-encode journal resume, and the shared
token-bucket throughput bound.
"""

from __future__ import annotations

import json
import os
import shutil
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from helpers import (
    free_port,
    make_volume,
    start_master_cluster,
    start_s3_stub,
)

from seaweedfs_tpu.storage.backend import BackendStorage, register_backend


class _DirBackend(BackendStorage):
    """Local-directory tier backend for the throughput test."""

    def __init__(self, backend_id, directory):
        super().__init__("dir", backend_id)
        self.directory = directory
        os.makedirs(directory, exist_ok=True)

    def _p(self, key):
        return os.path.join(self.directory, key.replace("/", "_"))

    def upload_file(self, local_path, key, progress=None):
        shutil.copyfile(local_path, self._p(key))
        size = os.path.getsize(local_path)
        if progress:
            progress(size)
        return size

    def download_file(self, key, local_path, progress=None):
        shutil.copyfile(self._p(key), local_path)
        return os.path.getsize(local_path)

    def delete_file(self, key):
        if os.path.exists(self._p(key)):
            os.remove(self._p(key))

    def read_range(self, key, offset, size):
        with open(self._p(key), "rb") as f:
            f.seek(offset)
            return f.read(size)


# ---------------------------------------------------------------------------
# helpers
# ---------------------------------------------------------------------------


def _http(method, url, data=None, headers=None, timeout=30.0):
    req = urllib.request.Request(url, data=data, method=method,
                                 headers=headers or {})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, r.read()
    except urllib.error.HTTPError as e:
        return e.code, e.read()


def _put_needle(url: str, fid: str, payload: bytes) -> bool:
    body = (b"--bb\r\nContent-Disposition: form-data; "
            b'name="file"; filename="b.bin"\r\n\r\n'
            + payload + b"\r\n--bb--\r\n")
    code, _ = _http("POST", f"http://{url}/{fid}", data=body, headers={
        "Content-Type": "multipart/form-data; boundary=bb"})
    return code < 300


def _derived_fids(base_fid: str, n: int) -> list[str]:
    vid_s, _, rest = base_fid.partition(",")
    base_key = int(rest[:-8], 16)
    cookie = rest[-8:]
    return [f"{vid_s},{base_key + i:x}{cookie}" for i in range(n)]


def _assign(master_port: int) -> tuple[str, str]:
    code, body = _http(
        "GET", f"http://127.0.0.1:{master_port}/dir/assign")
    assert code == 200, body
    a = json.loads(body)
    return a["fid"], a["url"]


# ---------------------------------------------------------------------------
# chaos: full pipeline under concurrent reads
# ---------------------------------------------------------------------------


@pytest.mark.chaos
def test_chaos_pipeline_seal_ec_tier_vacuum_under_reads(tmp_path_factory):
    """Fill a volume hot -> the controller seals it, EC-encodes it
    (shards spread + mounted), tiers the .dat into the S3 stub, and
    vacuums a garbage-heavy sibling — while concurrent client GETs stay
    byte-identical with zero 5xx at every stage."""
    from seaweedfs_tpu.master.server import MasterServer
    from seaweedfs_tpu.storage.backend_s3 import make_s3_backend
    from seaweedfs_tpu.volume.server import VolumeServer

    stub, stub_handler = start_s3_stub()
    stub_objects = stub_handler.objects
    endpoint = f"http://127.0.0.1:{stub.server_address[1]}"
    make_s3_backend("lifestub", {"endpoint": endpoint,
                                 "bucket": "cold"})

    jd = str(tmp_path_factory.mktemp("lifecycle-journal"))
    master, cluster = start_master_cluster(
        jd, volume_size_limit_mb=4,
        lifecycle_dir=jd,
        lifecycle_policy={"*": {
            "seal_full_percent": 10.0,
            "ec_cooldown_seconds": 0.5,
            "tier_backend": "s3.lifestub",
            "tier_idle_seconds": 0.0,
            "vacuum_garbage_ratio": 0.25,
        }})
    vols = []
    for i in range(2):
        v = VolumeServer(
            directories=[str(tmp_path_factory.mktemp(f"lcvol{i}"))],
            master_addresses=[f"127.0.0.1:{m.grpc_port}"
                              for m in cluster],
            ip="127.0.0.1", port=free_port(), pulse_seconds=0.5,
            max_volume_count=16)
        v.start()
        vols.append(v)
    try:
        deadline = time.time() + 15
        while time.time() < deadline and len(master.topo.nodes) < 2:
            time.sleep(0.1)

        # seed the target volume past the seal threshold (~420KB)
        rng = np.random.default_rng(3)
        first_fid, url = _assign(master.port)
        target_vid = int(first_fid.split(",")[0])
        known: dict[str, bytes] = {}
        for fid in _derived_fids(first_fid, 10):
            payload = rng.integers(0, 256, 64 << 10).astype(
                np.uint8).tobytes()
            assert _put_needle(url, fid, payload)
            known[fid] = payload

        # garbage-heavy sibling: write 10, delete 8
        g_base = None
        for _ in range(30):
            fid2, url2 = _assign(master.port)
            if int(fid2.split(",")[0]) != target_vid:
                g_base = (fid2, url2)
                break
        assert g_base is not None
        g_vid = int(g_base[0].split(",")[0])
        g_fids = _derived_fids(g_base[0], 10)
        g_keep: dict[str, bytes] = {}
        for i, fid2 in enumerate(g_fids):
            payload = os.urandom(32 << 10)
            assert _put_needle(g_base[1], fid2, payload)
            if i >= 8:
                g_keep[fid2] = payload
        for fid2 in g_fids[:8]:
            code, _ = _http("DELETE", f"http://{g_base[1]}/{fid2}")
            assert code < 300

        # concurrent readers: byte-identity + zero 5xx across ALL stages
        stop = threading.Event()
        errors: list[str] = []
        reads = [0]

        def reader():
            items = list(known.items())
            i = 0
            while not stop.is_set():
                fid, want = items[i % len(items)]
                i += 1
                code, body = _http("GET", f"http://{url}/{fid}",
                                   timeout=15)
                if code >= 500:
                    errors.append(f"{fid}: {code}")
                elif code == 200 and body != want:
                    errors.append(f"{fid}: wrong bytes")
                reads[0] += 1

        threads = [threading.Thread(target=reader, daemon=True)
                   for _ in range(3)]
        for t in threads:
            t.start()

        # drive the controller until the full pipeline lands
        done: dict[str, str] = {}
        deadline = time.time() + 60
        while time.time() < deadline:
            master.lifecycle.run_once()
            done = {j["key"]: j["state"]
                    for j in master.lifecycle.journal.jobs(("done",))}
            if (f"{target_vid}:tier" in done
                    and f"{g_vid}:vacuum" in done):
                break
            time.sleep(0.5)
        time.sleep(1.0)  # post-transition read traffic
        stop.set()
        for t in threads:
            t.join(timeout=10)

        assert f"{target_vid}:seal" in done, done
        assert f"{target_vid}:ec_encode" in done, done
        assert f"{target_vid}:tier" in done, done
        assert f"{g_vid}:vacuum" in done, done
        assert not errors, (
            f"clients saw {len(errors)} errors over {reads[0]} reads: "
            f"{errors[:5]}")
        assert reads[0] > 0

        # the .dat landed in the S3 stub and the holder serves remote
        assert any(k.endswith(f"{target_vid}.dat") for k in stub_objects)
        holder = next(v for v in vols
                      if v.store.find_volume(target_vid) is not None)
        assert holder.store.find_volume(target_vid).is_remote
        # EC shards exist cluster-wide (the encode kept the source)
        shard_map = master.topo.lookup_ec_shards(target_vid)
        assert len(shard_map) == 14, sorted(shard_map)

        # reads still byte-identical from the REMOTE tier + EC state
        for fid, want in known.items():
            code, body = _http("GET", f"http://{url}/{fid}")
            assert code == 200 and body == want
        # vacuumed sibling: survivors intact, deleted stay gone
        for fid2, want in g_keep.items():
            code, body = _http("GET", f"http://{g_base[1]}/{fid2}")
            assert code == 200 and body == want
        code, _ = _http("GET", f"http://{g_base[1]}/{g_fids[0]}")
        assert code == 404
        g_vol = None
        for v in vols:
            g_vol = g_vol or v.store.find_volume(g_vid)
        assert g_vol is not None and g_vol.garbage_level() < 0.05

        # operator surface: shell command + /cluster/lifecycle agree
        from seaweedfs_tpu.shell.commands import CommandEnv, run_command

        env = CommandEnv(f"127.0.0.1:{master.grpc_port}")
        out = run_command(env, "volume.lifecycle")
        assert f"{target_vid}:tier: done" in out, out
        code, body = _http(
            "GET", f"http://127.0.0.1:{master.port}/cluster/lifecycle")
        assert code == 200
        doc = json.loads(body)
        assert doc["jobStates"].get("done", 0) >= 4
        code, body = _http(
            "GET", f"http://127.0.0.1:{master.port}/cluster/status")
        assert json.loads(body)["Lifecycle"]["jobStates"]
    finally:
        stop = locals().get("stop")
        if stop is not None:
            stop.set()
        for v in vols:
            v.stop()
        for m in cluster:
            m.stop()
        stub.shutdown()
        stub.server_close()


@pytest.mark.chaos
def test_chaos_ttl_expired_volume_deleted(tmp_path_factory):
    """A TTL volume whose last write is older than its TTL is deleted
    wholesale by the ttl_expire transition (storage/ttl.py enforced by
    the controller, not just stored on the write path)."""
    from seaweedfs_tpu.master.server import MasterServer
    from seaweedfs_tpu.storage.super_block import SuperBlock
    from seaweedfs_tpu.storage.ttl import TTL
    from seaweedfs_tpu.volume.server import VolumeServer

    vol_dir = str(tmp_path_factory.mktemp("ttlvol"))
    v = make_volume(vol_dir, volume_id=21, n_needles=5)
    v.close()
    # stamp a 1-minute TTL in the super block and age the .dat 2 hours
    sb = SuperBlock(ttl=TTL.parse("1m"))
    with open(os.path.join(vol_dir, "21.dat"), "r+b") as f:
        f.write(sb.to_bytes())
    old = time.time() - 7200
    os.utime(os.path.join(vol_dir, "21.dat"), (old, old))

    jd = str(tmp_path_factory.mktemp("ttl-journal"))
    master, cluster = start_master_cluster(
        jd, volume_size_limit_mb=64, lifecycle_dir=jd)
    vs_ = VolumeServer(
        directories=[vol_dir],
        master_addresses=[f"127.0.0.1:{m.grpc_port}" for m in cluster],
        ip="127.0.0.1", port=free_port(), pulse_seconds=0.5,
        max_volume_count=16)
    vs_.start()
    try:
        deadline = time.time() + 15
        while time.time() < deadline and len(master.topo.nodes) < 1:
            time.sleep(0.1)
        deadline = time.time() + 30
        while time.time() < deadline:
            master.lifecycle.run_once()
            if "21:ttl_expire" in {
                    j["key"] for j in
                    master.lifecycle.journal.jobs(("done",))}:
                break
            time.sleep(0.3)
        assert "21:ttl_expire" in {
            j["key"] for j in master.lifecycle.journal.jobs(("done",))}
        assert vs_.store.find_volume(21) is None
        assert not os.path.exists(os.path.join(vol_dir, "21.dat"))
    finally:
        vs_.stop()
        for m in cluster:
            m.stop()


# ---------------------------------------------------------------------------
# chaos: SIGKILL the master mid-EC-encode, journal resumes
# ---------------------------------------------------------------------------


def _spawn_master(mport, jd, policy_path, extra_env=None):
    env = {**os.environ,
           "JAX_PLATFORMS": "cpu",
           "PYTHONPATH": os.path.dirname(os.path.dirname(
               os.path.abspath(__file__)))}
    env.update(extra_env or {})
    return subprocess.Popen(
        [sys.executable, "-m", "seaweedfs_tpu", "master",
         "-port", str(mport),
         "-volumeSizeLimitMB", "4",
         "-lifecycleInterval", "0.3",
         "-lifecycleDir", jd,
         "-lifecyclePolicy", policy_path],
        stdout=subprocess.DEVNULL, stderr=subprocess.STDOUT, env=env)


def _journal_jobs(jd) -> dict[str, dict]:
    jobs: dict[str, dict] = {}
    try:
        with open(os.path.join(jd, "lifecycle.journal.jsonl")) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                if "key" in rec:
                    jobs[rec["key"]] = rec
    except FileNotFoundError:
        pass
    return jobs


@pytest.mark.chaos
def test_chaos_master_sigkill_mid_ec_encode_resumes(tmp_path_factory):
    """SIGKILL the master while an ec_encode job is RUNNING (held open
    by a delay fault): the restarted master replays the journal and
    finishes the transition exactly once — no duplicate, no loss, and
    every needle byte-identical through the EC-served volume."""
    jd = str(tmp_path_factory.mktemp("kill-journal"))
    policy_path = os.path.join(jd, "policy.json")
    with open(policy_path, "w") as f:
        json.dump({"*": {"seal_full_percent": 10.0,
                         "ec_cooldown_seconds": 0.5,
                         "vacuum_garbage_ratio": 0.0}}, f)
    mport = free_port()
    # every lifecycle job pauses 5s at lifecycle.job.run: the window in
    # which the kill lands while ec_encode is journaled as RUNNING
    master_proc = _spawn_master(
        mport, jd, policy_path,
        extra_env={"SEAWEEDFS_TPU_FAULTS": "lifecycle.job.run=delay:5"})

    from seaweedfs_tpu.volume.server import VolumeServer

    vols = []
    second = None
    try:
        for i in range(2):
            v = VolumeServer(
                directories=[str(tmp_path_factory.mktemp(f"kvol{i}"))],
                master_addresses=[f"127.0.0.1:{mport + 10000}"],
                ip="127.0.0.1", port=free_port(), pulse_seconds=0.5,
                max_volume_count=16)
            v.start()
            vols.append(v)
        # generous: the subprocess master pays the full interpreter +
        # jax import tax, which stretches under a loaded CI host
        deadline = time.time() + 90
        first_fid = url = None
        while time.time() < deadline and first_fid is None:
            try:
                first_fid, url = _assign(mport)
            except (OSError, AssertionError):
                time.sleep(0.3)
        assert first_fid is not None, "master never came up"
        target_vid = int(first_fid.split(",")[0])
        rng = np.random.default_rng(11)
        known: dict[str, bytes] = {}
        for fid in _derived_fids(first_fid, 10):
            payload = rng.integers(0, 256, 64 << 10).astype(
                np.uint8).tobytes()
            assert _put_needle(url, fid, payload)
            known[fid] = payload

        # wait for the ec_encode job to be journaled as RUNNING (the
        # delay fault holds it there), then SIGKILL the master
        deadline = time.time() + 60
        while time.time() < deadline:
            rec = _journal_jobs(jd).get(f"{target_vid}:ec_encode")
            if rec is not None and rec["state"] == "running":
                break
            time.sleep(0.05)
        else:
            pytest.fail(f"ec_encode never reached running: "
                        f"{_journal_jobs(jd)}")
        master_proc.kill()
        master_proc.wait(timeout=10)

        # restart WITHOUT the fault: the journal replays the running
        # job as pending and the controller finishes it
        second = _spawn_master(mport, jd, policy_path)
        deadline = time.time() + 90
        while time.time() < deadline:
            rec = _journal_jobs(jd).get(f"{target_vid}:ec_encode")
            if rec is not None and rec["state"] == "done":
                break
            time.sleep(0.3)
        else:
            pytest.fail(f"ec_encode never finished after restart: "
                        f"{_journal_jobs(jd)}")
        rec = _journal_jobs(jd)[f"{target_vid}:ec_encode"]
        assert rec.get("resumed", 0) >= 1, rec

        # exactly one transition: one ec_encode key, all 14 shards
        # mounted exactly once across the cluster, source volume gone
        deadline = time.time() + 30
        while time.time() < deadline:
            bits = [v.store.status()["ec_volumes"].get(target_vid, [])
                    for v in vols]
            flat = [s for b in bits for s in b]
            if (sorted(flat) == list(range(14))
                    and all(v.store.find_volume(target_vid) is None
                            for v in vols)):
                break
            time.sleep(0.3)
        flat = [s for v in vols
                for s in v.store.status()["ec_volumes"].get(
                    target_vid, [])]
        assert sorted(flat) == list(range(14)), (
            f"shards duplicated or lost: {flat}")

        # byte-identity through the EC-served reads
        for fid, want in known.items():
            code, body = _http("GET", f"http://{url}/{fid}", timeout=20)
            assert code == 200 and body == want, (fid, code, len(body))
    finally:
        for v in vols:
            v.stop()
        for p in (master_proc, second):
            if p is not None and p.poll() is None:
                p.kill()
                p.wait(timeout=10)


# ---------------------------------------------------------------------------
# chaos: shared token bucket bounds lifecycle throughput
# ---------------------------------------------------------------------------


@pytest.mark.chaos
def test_chaos_lifecycle_throughput_respects_token_bucket(
        tmp_path_factory):
    """Three ~1.5MB tier moves at a 2 MB/s budget: measured lifecycle
    throughput stays within ~2x of the configured rate (the PR 8 scrub
    bound) while a foreground read load keeps being served."""
    from seaweedfs_tpu.master.server import MasterServer
    from seaweedfs_tpu.volume.server import VolumeServer

    tier_dir = str(tmp_path_factory.mktemp("tier-objects"))
    register_backend(_DirBackend("lifethrottle", tier_dir))

    vol_dir = str(tmp_path_factory.mktemp("tvol"))
    sizes = {}
    known = {}
    for vid in (11, 12, 13):
        v = make_volume(vol_dir, volume_id=vid, n_needles=76, seed=vid,
                        max_size=20000, collection="cold")
        known[vid] = {i: bytes(v.read_needle(i).data)
                      for i in (1, 40, 76)}
        sizes[vid] = v.content_size
        v.close()
    fg = make_volume(vol_dir, volume_id=14, n_needles=20, seed=99)
    fg_want = {}
    for i in range(1, 21):
        n = fg.read_needle(i)
        fg_want[f"14,{i:x}{n.cookie:08x}"] = bytes(n.data)
    fg.close()

    rate_mbps = 2.0
    jd = str(tmp_path_factory.mktemp("throttle-journal"))
    master, cluster = start_master_cluster(
        jd, volume_size_limit_mb=64,
        lifecycle_dir=jd, lifecycle_rate_mbps=rate_mbps,
        lifecycle_policy={
            "*": {"seal_full_percent": 0.0, "vacuum_garbage_ratio": 0.0,
                  "ttl_expire": False},
            "cold": {"seal_full_percent": 0.0,
                     "seal_age_seconds": 0.1,
                     "tier_backend": "dir.lifethrottle",
                     "tier_idle_seconds": 0.0,
                     "vacuum_garbage_ratio": 0.0,
                     "ttl_expire": False},
        })
    vs_ = VolumeServer(
        directories=[vol_dir],
        master_addresses=[f"127.0.0.1:{m.grpc_port}" for m in cluster],
        ip="127.0.0.1", port=free_port(), pulse_seconds=0.5,
        max_volume_count=16)
    vs_.start()
    try:
        deadline = time.time() + 15
        while time.time() < deadline and len(master.topo.nodes) < 1:
            time.sleep(0.1)
        # wait until the node adopted the pushed shared budget
        deadline = time.time() + 10
        while (time.time() < deadline
               and vs_.scrubber.bucket.rate != rate_mbps * (1 << 20)):
            time.sleep(0.1)
        assert vs_.scrubber.bucket.rate == rate_mbps * (1 << 20), (
            "heartbeat ack never delivered the shared budget")

        stop = threading.Event()
        fg_errors: list[str] = []
        fg_reads = [0]

        fg_items = list(fg_want.items())

        def fg_reader():
            i = 0
            while not stop.is_set():
                fid, want = fg_items[i % len(fg_items)]
                i += 1
                code, body = _http(
                    "GET", f"http://127.0.0.1:{vs_.port}/{fid}",
                    timeout=15)
                if code >= 500:
                    fg_errors.append(f"{fid}: {code}")
                elif code == 200 and body != want:
                    fg_errors.append(f"{fid}: wrong bytes")
                elif code != 200:
                    fg_errors.append(f"{fid}: {code}")
                fg_reads[0] += 1

        t = threading.Thread(target=fg_reader, daemon=True)
        t.start()

        total = sum(sizes.values())
        t0 = time.monotonic()
        deadline = time.time() + 120
        done: dict[str, str] = {}
        while time.time() < deadline:
            master.lifecycle.run_once()
            done = {j["key"]: j["state"]
                    for j in master.lifecycle.journal.jobs(("done",))}
            if all(f"{vid}:tier" in done for vid in (11, 12, 13)):
                break
            time.sleep(0.2)
        elapsed = time.monotonic() - t0
        stop.set()
        t.join(timeout=10)

        assert all(f"{vid}:tier" in done for vid in (11, 12, 13)), done
        measured = total / elapsed
        budget = 2.0 * rate_mbps * (1 << 20)
        burst_grace = 2 * rate_mbps * (1 << 20)  # master+node cold buckets
        assert measured <= budget + burst_grace / elapsed, (
            f"lifecycle moved {measured / (1 << 20):.2f} MB/s against a "
            f"{rate_mbps} MB/s budget ({total} B in {elapsed:.2f}s)")
        assert not fg_errors, fg_errors[:5]
        assert fg_reads[0] > 0

        # tiered volumes serve byte-identical from the remote backend
        for vid, wants in known.items():
            assert vs_.store.find_volume(vid).is_remote
            for nid, want in wants.items():
                assert bytes(
                    vs_.store.read_needle(vid, nid).data) == want
    finally:
        vs_.stop()
        for m in cluster:
            m.stop()
